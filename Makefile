.PHONY: test doctest soak clean env multichip bench

# Test suite on the 8-virtual-device CPU mesh (tests/conftest.py pins the platform),
# then a small fixed-seed slice of the executed-reference fuzz soak — the single
# highest-yield bug-finder in this project's history (11+ real convention
# divergences across rounds); fresh seed ranges each round via `make soak`.
test:
	python -m pytest tests/ -q
	python tools/fuzz_soak.py --surfaces all --seeds 500:502

# Wider randomized sweep (pass SEEDS=a:b to pick a fresh range).
SEEDS ?= 1000:1020
soak:
	python tools/fuzz_soak.py --surfaces all --seeds $(SEEDS)

# Docstring examples across the package (reference runs --doctest-modules over src/,
# /root/reference/Makefile:23-31 + pyproject.toml:28-33). One walker — the same one
# the normal test suite runs — so examples can't pass one config and fail another.
doctest:
	python -m pytest tests/test_doctests.py -q

# Driver-facing artifacts.
multichip:
	XLA_FLAGS=--xla_force_host_platform_device_count=8 python -c "import __graft_entry__; __graft_entry__.dryrun_multichip(8); print('multichip OK')"

bench:
	python bench.py

# Fraction-of-ceiling verdicts from the latest durable roofline captures
# (suite.py --only roofline appends them to benchmarks/suite_runs.jsonl).
roofline-report:
	python tools/roofline_report.py --backend tpu --write

env:
	pip install -e ".[test]"

clean:
	rm -rf build dist *.egg-info .pytest_cache
	find . -name __pycache__ -type d -exec rm -rf {} +
