"""Cross-host trace context: the identity a request carries across every plane.

A :class:`TraceContext` is the compact causal identity minted once per
submitted request — ``trace_id`` (the whole causal tree), ``span_id`` (this
hop), ``sampled`` (the recording bit) — and threaded through guard admission,
backlog residency, fused dispatch, the WAL, repl frames, and the ckpt journal,
so a follower's apply span and a crash-recovered engine's replay span link
back to the primary submit that caused them, across process and host
boundaries.

Propagation has three carriers:

- **in-process**: a thread-local ambient context (:func:`current` /
  :func:`activate`) — ``ShardedEngine.submit`` activates the minted context
  around its delegation so the per-shard ``StreamingEngine.submit`` adopts it
  instead of minting a second one;
- **in-span**: span attributes (``trace=<hex>``, ``span=<hex>``) on the
  process tracer — the ring/Chrome-trace shape is unchanged, the ids ride the
  existing ``attrs`` dict;
- **on-the-wire**: a fixed 17-byte encoding (:meth:`TraceContext.to_bytes`)
  appended to WAL chunk/request records and therefore carried verbatim inside
  shipped repl frames — decoders treat the block as optional, so journals and
  spool files written before this existed (or with obs off) replay unchanged.

Disabled, nothing is minted: hot paths test ``OBS.enabled`` once and carry
``None``. Stdlib only.
"""

from __future__ import annotations

import os
import random
import struct
import threading
from typing import Any, Iterator, Optional

from metrics_tpu.obs.registry import OBS

# u64 trace_id + u64 span_id + u8 flags (bit 0 = sampled)
_WIRE = struct.Struct("<QQB")
WIRE_SIZE = _WIRE.size  # 17

# Process-private id source. `random.Random` seeded from os.urandom gives
# 64-bit ids that never collide across the processes of one fleet test in
# practice, without burning an os.urandom read per request. A lock keeps the
# generator state sane under concurrent submits (getrandbits is not atomic).
_rng = random.Random(int.from_bytes(os.urandom(8), "little"))
_rng_lock = threading.Lock()

_local = threading.local()


def _fresh_id() -> int:
    with _rng_lock:
        # avoid 0: an all-zero id doubles as "absent" in the wire block
        return _rng.getrandbits(64) or 1


class TraceContext:
    """One hop of a cross-host trace: (trace_id, span_id, sampled)."""

    __slots__ = ("trace_id", "span_id", "sampled")

    def __init__(self, trace_id: int, span_id: int, sampled: bool = True) -> None:
        self.trace_id = trace_id
        self.span_id = span_id
        self.sampled = sampled

    # ------------------------------------------------------------------ lineage

    def child(self) -> "TraceContext":
        """A new hop in the same trace (fresh span_id, inherited trace_id)."""
        return TraceContext(self.trace_id, _fresh_id(), self.sampled)

    # ------------------------------------------------------------------ wire

    def to_bytes(self) -> bytes:
        return _WIRE.pack(self.trace_id, self.span_id, 1 if self.sampled else 0)

    @staticmethod
    def from_bytes(data: bytes, off: int = 0) -> "TraceContext":
        trace_id, span_id, flags = _WIRE.unpack_from(data, off)
        return TraceContext(trace_id, span_id, bool(flags & 1))

    # ------------------------------------------------------------------ display

    @property
    def trace_hex(self) -> str:
        return f"{self.trace_id:016x}"

    @property
    def span_hex(self) -> str:
        return f"{self.span_id:016x}"

    def __repr__(self) -> str:
        return f"TraceContext(trace={self.trace_hex}, span={self.span_hex}, sampled={self.sampled})"

    def __eq__(self, other: Any) -> bool:
        if not isinstance(other, TraceContext):
            return NotImplemented
        return (
            self.trace_id == other.trace_id
            and self.span_id == other.span_id
            and self.sampled == other.sampled
        )

    def __hash__(self) -> int:
        return hash((self.trace_id, self.span_id, self.sampled))


def mint() -> TraceContext:
    """A brand-new root context (new trace_id). Callers gate on ``OBS.enabled``."""
    return TraceContext(_fresh_id(), _fresh_id(), True)


def current() -> Optional[TraceContext]:
    """The ambient context on THIS thread (None when nothing is active)."""
    stack = getattr(_local, "stack", None)
    return stack[-1] if stack else None


class _Activation:
    """Context manager installing one TraceContext as the thread's ambient context."""

    __slots__ = ("_ctx",)

    def __init__(self, ctx: Optional[TraceContext]) -> None:
        self._ctx = ctx

    def __enter__(self) -> Optional[TraceContext]:
        stack = getattr(_local, "stack", None)
        if stack is None:
            stack = _local.stack = []
        stack.append(self._ctx)
        return self._ctx

    def __exit__(self, *exc_info: Any) -> bool:
        stack = getattr(_local, "stack", None)
        if stack:
            stack.pop()
        return False


def activate(ctx: Optional[TraceContext]) -> _Activation:
    """Install ``ctx`` as the ambient context for a ``with`` block.

    ``activate(None)`` is a valid (and cheap) no-op shadowing — engines use it
    unconditionally so the disabled path stays branch-free at the call site.
    """
    return _Activation(ctx)


def mint_or_current() -> Optional[TraceContext]:
    """The propagation rule engines apply at submit: adopt the ambient context
    if a caller (ShardedEngine, a user span, a test) activated one, else mint a
    fresh root — and only when obs is on."""
    if not OBS.enabled:
        return None
    ctx = current()
    return ctx if ctx is not None else mint()


def trace_attrs(ctx: Optional[TraceContext]) -> dict:
    """Span-attribute dict carrying the ids (empty when no context)."""
    if ctx is None:
        return {}
    return {"trace": ctx.trace_hex, "span": ctx.span_hex}


def iter_wire_blocks(payload: bytes, off: int) -> Iterator[TraceContext]:
    """Decode consecutive wire blocks from ``payload[off:]`` until exhausted.

    The optional-trailer convention: WAL decoders call this with the offset
    where positional decoding finished — zero remaining bytes (an old record,
    or obs-off writer) yields nothing.
    """
    while off + WIRE_SIZE <= len(payload):
        yield TraceContext.from_bytes(payload, off)
        off += WIRE_SIZE
