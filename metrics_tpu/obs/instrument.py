"""Instrumentation hooks the core library calls into — the obs side of the wiring.

``metric.py`` / ``collections.py`` / ``engine/runtime.py`` / ``parallel/sync.py``
call these entry points; everything here funnels into the process-global
:data:`~metrics_tpu.obs.registry.REGISTRY` and
:data:`~metrics_tpu.obs.trace.TRACER`. Three concerns:

- **op timing** (:func:`metric_op`): per-instance wall time of
  ``update``/``compute``/``sync`` as a histogram + a trace span;
- **retrace attribution** (:func:`record_retrace`, :func:`wrap_jitted_updater`):
  which abstract-shape signature caused each new compile, counted at
  jit-cache-miss time — the number that explains "why is serving slow after
  that deploy" when the answer is an unstable input shape;
- **sync payload accounting** (:func:`record_sync_bytes`, :func:`tree_nbytes`):
  state-tree byte size per host gather / in-trace all-gather.

Every hook is behind the master gate: callers on hot paths test
``OBS.enabled`` themselves (one attribute load), and each hook re-checks so
cold-path callers can call unconditionally.

Stdlib only — array leaves are duck-typed on ``shape``/``dtype``/``nbytes``,
never imported.
"""

from __future__ import annotations

import itertools
import math
import threading
import time
from typing import Any, Callable, Dict, List, Optional

from metrics_tpu.obs.flight import FLIGHT
from metrics_tpu.obs.registry import OBS, REGISTRY
from metrics_tpu.obs.trace import _NULL_SPAN, TRACER

# byte-sized buckets for payload histograms: 64B → 64MB, ×16 per step
_BYTE_BUCKETS = (64.0, 1024.0, 16384.0, 262144.0, 4194304.0, 67108864.0)

OP_SECONDS = REGISTRY.histogram(
    "metrics_tpu_op_seconds",
    "Wall time of metric operations (op=update|compute|sync|jitted_update), per metric class and instance.",
)
RETRACES = REGISTRY.counter(
    "metrics_tpu_retraces_total",
    "New compiles attributed to the abstract-shape signature that caused them, counted at jit-cache-miss time.",
)
SYNC_BYTES = REGISTRY.counter(
    "metrics_tpu_sync_bytes_total",
    "Cumulative state-tree payload bytes moved through HOST-level distributed sync (counted per call).",
)
SYNC_TRACED_BYTES = REGISTRY.counter(
    "metrics_tpu_sync_traced_bytes_total",
    "Per-compile payload accounting for in-trace collectives: bytes each EXECUTION of the "
    "traced collective moves per participant, recorded ONCE at trace time — multiply by the "
    "step rate yourself; do not compare against the per-call host counter.",
)
SYNC_PAYLOAD = REGISTRY.histogram(
    "metrics_tpu_sync_payload_bytes",
    "State-tree byte size per host-level sync/all-gather.",
    buckets=_BYTE_BUCKETS,
)

# Bounded per-instance labeling: the registry never evicts, so unbounded distinct
# instance ids (per-request metrics, clones) would grow series forever in a
# long-lived serving process. The label is stored ON the object (monotone issue
# number — id() reuse after GC can never alias a new metric onto a dead one's
# series); past the cap, new instances share one overflow label — per-class
# series stay exact, per-instance attribution degrades last.
_INSTANCE_CAP = 256
_INSTANCE_ATTR = "_obs_instance_label"
_instance_ids = itertools.count()


def instance_label(obj: Any) -> str:
    """Stable-for-the-lifetime-of-the-object instance id label (bounded set)."""
    label = getattr(obj, _INSTANCE_ATTR, None)
    if label is not None:
        return label
    n = next(_instance_ids)
    label = str(n) if n < _INSTANCE_CAP else "overflow"
    try:
        object.__setattr__(obj, _INSTANCE_ATTR, label)
    except Exception:  # noqa: BLE001 — slotted/immutable hosts: don't burn cap slots on them
        return "untracked"
    return label


# ---------------------------------------------------------------------- op timing


class _OpTimer:
    """Span + wall-time histogram around one metric operation."""

    __slots__ = ("_op", "_metric", "_instance", "_span", "_t0")

    def __init__(self, op: str, metric: str, instance: str) -> None:
        self._op = op
        self._metric = metric
        self._instance = instance

    def __enter__(self) -> "_OpTimer":
        self._span = TRACER.span(f"metric.{self._op}", metric=self._metric)
        self._span.__enter__()
        self._t0 = time.perf_counter()
        return self

    def __exit__(self, exc_type: Any, exc: Any, tb: Any) -> bool:
        dur = time.perf_counter() - self._t0
        self._span.__exit__(exc_type, exc, tb)
        OP_SECONDS.observe(dur, op=self._op, metric=self._metric, instance=self._instance)
        return False

    def set_attr(self, **attrs: Any) -> None:
        self._span.set_attr(**attrs)


def metric_op(op: str, owner: Any) -> Any:
    """Context manager timing one ``update``/``compute``/``sync`` on ``owner``.

    Returns a shared no-op when the master switch is off, so cold-path callers
    can use it unconditionally; hot paths should branch on ``OBS.enabled``
    themselves to skip even this call.
    """
    if not OBS.enabled:
        return _NULL_SPAN
    return _OpTimer(op, type(owner).__name__, instance_label(owner))


# ---------------------------------------------------------------------- retrace attribution


def record_retrace(site: str, signature: str) -> None:
    """Count one fresh compile at ``site`` against the signature that caused it."""
    if not OBS.enabled:
        return
    RETRACES.inc(1, site=site, signature=signature)


def abstract_signature(tree: Any) -> str:
    """Compact, deterministic abstract-shape signature of a pytree-ish value.

    Array-like leaves (anything with ``shape`` + ``dtype``) render as
    ``dtype[d0xd1]``; containers recurse (dicts in key order); other leaves
    render as their type name — exactly the identity jax's jit cache keys on
    at our level of abstraction, so one signature ↔ one compile.
    """
    parts: List[str] = []
    _walk_signature(tree, parts)
    return ",".join(parts)


def _walk_signature(x: Any, parts: List[str]) -> None:
    if isinstance(x, dict):
        parts.append("{")
        for k in sorted(x, key=str):
            parts.append(f"{k}:")
            _walk_signature(x[k], parts)
        parts.append("}")
        return
    if isinstance(x, (list, tuple)):
        parts.append("(")
        for item in x:
            _walk_signature(item, parts)
        parts.append(")")
        return
    shape = getattr(x, "shape", None)
    dtype = getattr(x, "dtype", None)
    if shape is not None and dtype is not None:
        parts.append(f"{dtype}[{'x'.join(map(str, shape))}]")
        return
    parts.append(type(x).__name__)


class _InstrumentedUpdater:
    """Retrace attribution + timing around a compiled updater.

    This callable is what ``_cached_jitted_updater`` caches, so identity-caching
    semantics (``updater is metric.jitted_update_state()``) are preserved, and
    unknown attributes (``.lower``, ``.clear_cache``, ...) forward to the
    underlying ``jax.jit`` callable — the pre-obs return surface keeps working.
    Disabled, the only per-call cost is one attribute test.

    Each call (with obs on) derives the operands' abstract signature
    (positional AND keyword — both key the jit cache) and records a retrace
    against it when the call actually compiled. Freshness prefers the runtime's
    own jit-cache size (immune to the warm-process pitfall where enabling obs
    late would count already-compiled signatures). Observed cache growth is
    CLAIMED under a lock (a high-water mark): one compile can never be recorded
    twice, and a signature already marked seen is never re-recorded. Under
    truly concurrent first-calls the attribution of a single observed compile
    to *which* signature is best-effort — we bias toward undercounting rather
    than phantom retraces on innocent warm signatures.
    """

    __slots__ = ("_fn", "_owner", "_metric_name", "_site", "_seen", "_seen_lock", "_claimed")

    def __init__(self, fn: Callable, owner: Any) -> None:
        self._fn = fn
        self._owner = owner
        self._metric_name = type(owner).__name__
        self._site = f"{self._metric_name}.jitted_update_state"
        self._seen: set = set()
        self._seen_lock = threading.Lock()
        self._claimed: Any = None  # cache-size high-water mark already attributed

    @property
    def __wrapped__(self) -> Callable:
        return self._fn

    def __getattr__(self, name: str) -> Any:
        return getattr(self._fn, name)

    def _cache_size(self) -> Any:
        probe = getattr(self._fn, "_cache_size", None)
        if probe is None:
            return None
        try:
            return probe()
        except Exception:  # noqa: BLE001 — private API: degrade to the seen-set
            return None

    def __call__(self, state: Any, *args: Any, **kwargs: Any) -> Any:
        if not OBS.enabled:
            return self._fn(state, *args, **kwargs)
        signature = abstract_signature((state, args, kwargs))
        size_before = self._cache_size()
        t0 = time.perf_counter()
        with TRACER.span("metric.jitted_update", metric=self._metric_name) as span:
            out = self._fn(state, *args, **kwargs)
        dur = time.perf_counter() - t0
        size_after = self._cache_size() if size_before is not None else None
        with self._seen_lock:
            if size_after is not None:
                if self._claimed is None:
                    # first probed call: everything compiled before obs was
                    # watching is pre-claimed, never attributed to anyone
                    self._claimed = size_before
                # only UNCLAIMED growth past the high-water mark counts, so a
                # concurrent compile straddling our probes is claimed at most
                # once across all callers
                compiled = size_after > self._claimed
                if compiled:
                    self._claimed = size_after
            else:
                compiled = True  # probe unavailable: let the seen-set decide alone
            fresh = compiled and signature not in self._seen
            # a warm signature is known-compiled even when `compiled` is False —
            # remember it so a later straddling probe can't misattribute it
            self._seen.add(signature)
        if fresh:
            RETRACES.inc(1, site=self._site, signature=signature)
            span.set_attr(retrace=True)
        OP_SECONDS.observe(
            dur, op="jitted_update", metric=self._metric_name, instance=instance_label(self._owner)
        )
        return out


def wrap_jitted_updater(fn: Callable, owner: Any) -> Callable:
    """Wrap a compiled updater for retrace attribution + timing (see
    :class:`_InstrumentedUpdater`)."""
    return _InstrumentedUpdater(fn, owner)


# ---------------------------------------------------------------------- sync payload


def tree_nbytes(tree: Any) -> int:
    """Total byte size of every array-like leaf in a state pytree.

    Duck-typed: concrete arrays report ``nbytes``; abstract values inside a
    trace (shape + dtype, no buffer) fall back to ``prod(shape) * itemsize`` —
    so recording at trace time prices the payload the collective will move.
    """
    total = 0
    stack = [tree]
    while stack:
        x = stack.pop()
        if isinstance(x, dict):
            stack.extend(x.values())
        elif isinstance(x, (list, tuple)):
            stack.extend(x)
        else:
            nbytes = getattr(x, "nbytes", None)
            if nbytes is not None:
                try:
                    total += int(nbytes)
                    continue
                except Exception:  # noqa: BLE001 — aval nbytes may be symbolic
                    pass
            shape = getattr(x, "shape", None)
            dtype = getattr(x, "dtype", None)
            if shape is not None and dtype is not None:
                try:
                    total += int(math.prod(shape)) * int(getattr(dtype, "itemsize", 0))
                except Exception:  # noqa: BLE001 — dynamic dims: skip the leaf
                    pass
    return total


def record_sync_bytes(site: str, metric: str, nbytes: int) -> None:
    """Account one HOST-level sync's state-tree payload (per-call counter + distribution)."""
    if not OBS.enabled:
        return
    SYNC_BYTES.inc(nbytes, site=site, metric=metric)
    SYNC_PAYLOAD.observe(nbytes, site=site)


def record_traced_sync_bytes(site: str, metric: str, nbytes: int) -> None:
    """Account an IN-TRACE collective's payload, once per compile.

    Kept in a separate counter from :func:`record_sync_bytes`: this body runs at
    trace time only, so the number means 'bytes per execution of the compiled
    collective', not 'cumulative bytes moved' — summing the two sites into one
    series would make the traced path look ~free next to per-call host syncs.
    """
    if not OBS.enabled:
        return
    SYNC_TRACED_BYTES.inc(nbytes, site=site, metric=metric)


# ---------------------------------------------------------------------- comm plane

COMM_RAW_BYTES = REGISTRY.counter(
    "metrics_tpu_comm_raw_bytes_total",
    "Cumulative pre-codec state bytes handed to the comm plane per sync site.",
)
COMM_WIRE_BYTES = REGISTRY.counter(
    "metrics_tpu_comm_wire_bytes_total",
    "Cumulative post-codec bytes this process actually put on the wire per sync site.",
)
COMM_RATIO = REGISTRY.gauge(
    "metrics_tpu_comm_compression_ratio",
    "raw/wire byte ratio of the most recent comm sync per site (1.0 = lossless passthrough).",
)
COMM_RETRIES = REGISTRY.counter(
    "metrics_tpu_comm_retries_total",
    "Comm-plane sync attempts re-issued after a transient transport failure, per site.",
)
COMM_TIMEOUTS = REGISTRY.counter(
    "metrics_tpu_comm_timeouts_total",
    "Comm-plane collectives that blew the configured deadline, per site.",
)
COMM_DEGRADATIONS = REGISTRY.counter(
    "metrics_tpu_comm_degradations_total",
    "Degradation-ladder rungs taken (step=lossless_only|live_subset|local_state), per site.",
)
COMM_STALE = REGISTRY.gauge(
    "metrics_tpu_comm_stale_state",
    "1 while the most recent sync at this site served LOCAL state (ladder bottom), else 0.",
)
COMM_PEER_LIVE = REGISTRY.gauge(
    "metrics_tpu_comm_peer_live",
    "1 while this process's WorldView believes the labeled peer rank is live, else 0.",
)
COMM_PARTIAL_SYNCS = REGISTRY.counter(
    "metrics_tpu_comm_partial_syncs_total",
    "Syncs completed over an agreed live subset of the world (the live_subset rung), per site.",
)


def record_comm_payload(site: str, raw_bytes: int, wire_bytes: int) -> None:
    """Account one comm sync's pre-codec vs on-the-wire bytes (+ ratio gauge)."""
    if not OBS.enabled:
        return
    COMM_RAW_BYTES.inc(raw_bytes, site=site)
    COMM_WIRE_BYTES.inc(wire_bytes, site=site)
    COMM_RATIO.set(raw_bytes / wire_bytes if wire_bytes else 1.0, site=site)


def record_comm_retry(site: str) -> None:
    if not OBS.enabled:
        return
    COMM_RETRIES.inc(1, site=site)


def record_comm_timeout(site: str) -> None:
    if not OBS.enabled:
        return
    COMM_TIMEOUTS.inc(1, site=site)


def record_comm_degradation(site: str, step: str) -> None:
    if not OBS.enabled:
        return
    COMM_DEGRADATIONS.inc(1, site=site, step=step)


def set_comm_stale(site: str, stale: bool) -> None:
    if not OBS.enabled:
        return
    COMM_STALE.set(1.0 if stale else 0.0, site=site)


def record_comm_peer_live(peer: int, live: bool) -> None:
    if not OBS.enabled:
        return
    COMM_PEER_LIVE.set(1.0 if live else 0.0, peer=str(peer))


def record_comm_partial_sync(site: str) -> None:
    if not OBS.enabled:
        return
    COMM_PARTIAL_SYNCS.inc(1, site=site)


def record_comm_live_set(site: str, previous: Any, agreed: Any) -> None:
    """One committed ``agree_live_set`` outcome: the membership edge lands in
    the flight ring, and an agreed set that LOST ranks relative to the
    previous commit (a real partition/death, not a rejoin) dumps a bundle."""
    if not OBS.enabled:
        return
    prev = set(previous) if previous is not None else None
    now_set = set(agreed)
    FLIGHT.record(
        "comm_live_set",
        site=site,
        previous=sorted(prev) if prev is not None else None,
        agreed=sorted(now_set),
    )
    if prev is not None and (prev - now_set):
        FLIGHT.dump(
            "live_set_shrink", site=site, lost=sorted(prev - now_set),
            agreed=sorted(now_set),
        )


def comm_span(name: str, **attrs: Any) -> Any:
    """Trace span for comm-plane internals (sync, gather, encode/decode)."""
    if not OBS.enabled:
        return _NULL_SPAN
    return TRACER.span(name, **attrs)


# ---------------------------------------------------------------------- ckpt plane

CKPT_BYTES = REGISTRY.counter(
    "metrics_tpu_ckpt_bytes_total",
    "Cumulative snapshot bytes moved through the durable state plane, per site and op (write|restore).",
)
CKPT_SECONDS = REGISTRY.histogram(
    "metrics_tpu_ckpt_seconds",
    "Wall time of checkpoint writes and restores (serialize + commit / read + validate + apply).",
)
CKPT_FAILURES = REGISTRY.counter(
    "metrics_tpu_ckpt_failures_total",
    "Checkpoint operations that failed (and were absorbed, not raised), per site and op.",
)
CKPT_GENERATION = REGISTRY.gauge(
    "metrics_tpu_ckpt_generation",
    "Most recently committed (op=write) or recovered (op=restore) snapshot generation, per site.",
)
CKPT_SKIPPED = REGISTRY.counter(
    "metrics_tpu_ckpt_skipped_generations_total",
    "Snapshot generations skipped as corrupt/torn/invalid during a latest_valid recovery scan, "
    "by failure reason — each skip silently cost one generation of recovery staleness.",
)


def record_ckpt_io(
    site: str, op: str, nbytes: int, seconds: float, generation: Optional[int] = None
) -> None:
    """Account one checkpoint write/restore: bytes, latency, generation gauge."""
    if not OBS.enabled:
        return
    CKPT_BYTES.inc(nbytes, site=site, op=op)
    CKPT_SECONDS.observe(seconds, site=site, op=op)
    if generation is not None:
        CKPT_GENERATION.set(generation, site=site, op=op)


def record_ckpt_failure(site: str, op: str) -> None:
    if not OBS.enabled:
        return
    CKPT_FAILURES.inc(1, site=site, op=op)


def record_ckpt_skipped(reason: str, n: int = 1) -> None:
    """Count one generation skipped by a recovery scan (reason = exception type)."""
    if not OBS.enabled:
        return
    CKPT_SKIPPED.inc(n, reason=reason)


def ckpt_span(name: str, **attrs: Any) -> Any:
    """Trace span for durable-state-plane internals (serialize, commit, restore)."""
    if not OBS.enabled:
        return _NULL_SPAN
    return TRACER.span(name, **attrs)


# ---------------------------------------------------------------------- guard plane

GUARD_SHED = REGISTRY.counter(
    "metrics_tpu_guard_shed_total",
    "Requests dropped by the overload controller (queue sojourn above target for a full interval), per engine.",
)
GUARD_QUOTA_REJECTIONS = REGISTRY.counter(
    "metrics_tpu_guard_quota_rejections_total",
    "Submits refused at admission because the tenant's token bucket was empty, per engine.",
)
GUARD_DEADLINE_EXPIRED = REGISTRY.counter(
    "metrics_tpu_guard_deadline_expired_total",
    "Requests whose deadline expired before dispatch (failed fast, no batch slot), per engine.",
)
GUARD_WATCHDOG_RESTARTS = REGISTRY.counter(
    "metrics_tpu_guard_watchdog_restarts_total",
    "Dispatcher workers superseded and restarted after the watchdog declared them hung, per engine.",
)
GUARD_QUARANTINES = REGISTRY.counter(
    "metrics_tpu_guard_quarantines_total",
    "Tenants placed under quarantine probation after repeated request failures, per engine.",
)
GUARD_BREAKER_STATE = REGISTRY.gauge(
    "metrics_tpu_guard_breaker_state",
    "Circuit breaker state per engine and dependency (0=closed, 1=half-open, 2=open).",
)
GUARD_HEALTH_STATE = REGISTRY.gauge(
    "metrics_tpu_guard_health_state",
    "Engine health state machine (0=SERVING, 1=DEGRADED, 2=QUARANTINED).",
)

_GUARD_EVENT_COUNTERS = {
    "shed": GUARD_SHED,
    "quota_rejections": GUARD_QUOTA_REJECTIONS,
    "deadline_expired": GUARD_DEADLINE_EXPIRED,
    "watchdog_restarts": GUARD_WATCHDOG_RESTARTS,
    "quarantines": GUARD_QUARANTINES,
}

_HEALTH_CODES = {"SERVING": 0, "DEGRADED": 1, "QUARANTINED": 2}


def record_guard_event(engine: str, kind: str, n: int = 1) -> None:
    """Count one guard decision (kind in shed|quota_rejections|deadline_expired|
    watchdog_restarts|quarantines) against its engine label.

    Tenant quarantines and watchdog restarts are flight-recorder triggering
    edges (the guard fires this exactly once per edge): each dumps one
    post-mortem bundle on top of the counter."""
    if not OBS.enabled:
        return
    _GUARD_EVENT_COUNTERS[kind].inc(n, engine=engine)
    if kind == "quarantines":
        FLIGHT.record("guard_quarantine", engine=engine)
        FLIGHT.dump("guard_quarantine", engine=engine)
    elif kind == "watchdog_restarts":
        FLIGHT.record("watchdog_restart", engine=engine)
        FLIGHT.dump("watchdog_restart", engine=engine)


def set_guard_breaker_state(engine: str, breaker: str, state_code: int) -> None:
    if not OBS.enabled:
        return
    GUARD_BREAKER_STATE.set(state_code, engine=engine, breaker=breaker)
    # the flight recorder dedups gauge refreshes into edges and dumps one
    # bundle on the transition INTO open (2)
    FLIGHT.record_breaker_state(engine, breaker, state_code)


def set_guard_health(engine: str, state: str) -> None:
    if not OBS.enabled:
        return
    GUARD_HEALTH_STATE.set(_HEALTH_CODES[state], engine=engine)


def record_health_transition(engine: str, old: str, new: str) -> None:
    """One engine health-state edge (fired beside the user's
    ``on_health_transition`` observer — exactly once per transition, outside
    the engine's locks). Entering QUARANTINED dumps a flight bundle: the
    engine just declared itself unable to serve safely, which is precisely
    when the run-up evidence matters."""
    if not OBS.enabled:
        return
    FLIGHT.record("health_transition", engine=engine, old=old, new=new)
    if new == "QUARANTINED":
        FLIGHT.dump("engine_quarantine", engine=engine, old=old)


def guard_span(name: str, **attrs: Any) -> Any:
    """Trace span for guard-plane internals (drain forming, hang handling)."""
    if not OBS.enabled:
        return _NULL_SPAN
    return TRACER.span(name, **attrs)


# ---------------------------------------------------------------------- repl plane

REPL_SHIPPED = REGISTRY.counter(
    "metrics_tpu_repl_shipped_records_total",
    "WAL records the primary's shipper published over the replication transport, per engine.",
)
REPL_APPLIED = REGISTRY.counter(
    "metrics_tpu_repl_applied_records_total",
    "Shipped WAL records a follower replayed into its local state, per engine.",
)
REPL_LAG_SEQS = REGISTRY.gauge(
    "metrics_tpu_repl_lag_seqs",
    "Follower staleness in WAL records: known primary position minus applied position, per engine.",
)
REPL_LAG_SECONDS = REGISTRY.gauge(
    "metrics_tpu_repl_lag_seconds",
    "Follower staleness in wall-clock seconds (now minus the primary instant the replica is "
    "known current through); -1 before bootstrap (unbounded).",
)
REPL_PROMOTIONS = REGISTRY.counter(
    "metrics_tpu_repl_promotions_total",
    "Follower→primary promotions (explicit promote() or guard-quarantine failover), per engine.",
)


def record_repl_shipped(engine: str, n: int = 1) -> None:
    if not OBS.enabled:
        return
    REPL_SHIPPED.inc(n, engine=engine)


def record_repl_applied(engine: str, n: int = 1) -> None:
    if not OBS.enabled:
        return
    REPL_APPLIED.inc(n, engine=engine)


def set_repl_lag(engine: str, seqs_behind: int, seconds_behind: float) -> None:
    if not OBS.enabled:
        return
    REPL_LAG_SEQS.set(seqs_behind, engine=engine)
    REPL_LAG_SECONDS.set(
        -1.0 if seconds_behind == float("inf") else seconds_behind, engine=engine
    )


def record_repl_promotion(engine: str) -> None:
    if not OBS.enabled:
        return
    REPL_PROMOTIONS.inc(1, engine=engine)
    FLIGHT.record("repl_promotion", engine=engine)


def repl_span(name: str, **attrs: Any) -> Any:
    """Trace span for replication internals (ship tick, bootstrap, promotion)."""
    if not OBS.enabled:
        return _NULL_SPAN
    return TRACER.span(name, **attrs)


# ---------------------------------------------------------------------- cluster plane

CLUSTER_ROLE = REGISTRY.gauge(
    "metrics_tpu_cluster_role",
    "This node's role in the cluster control plane: 1 leader (holds the lease), "
    "0 follower, per node.",
)
CLUSTER_FAILOVERS = REGISTRY.counter(
    "metrics_tpu_cluster_failovers_total",
    "Self-driving failovers completed by this node: lease won + promote() "
    "succeeded at the lease epoch, per node.",
)
CLUSTER_LEASE_RENEWALS = REGISTRY.counter(
    "metrics_tpu_cluster_lease_renewals_total",
    "Leadership lease renewals (same epoch, deadline extended), per node.",
)
CLUSTER_SUSPICIONS = REGISTRY.counter(
    "metrics_tpu_cluster_suspicions_total",
    "Failure-detector suspicion edges: a peer's heartbeat went silent past the "
    "suspect threshold (counted once per silence episode), per node.",
)

_ROLE_CODES = {"follower": 0, "leader": 1}


def set_cluster_role(node: str, role: str) -> None:
    if not OBS.enabled:
        return
    CLUSTER_ROLE.set(_ROLE_CODES.get(role, 0), node=node)


def record_cluster_failover(node: str) -> None:
    if not OBS.enabled:
        return
    CLUSTER_FAILOVERS.inc(1, node=node)
    FLIGHT.record("cluster_failover", node=node)


def record_cluster_lease_renewal(node: str) -> None:
    if not OBS.enabled:
        return
    CLUSTER_LEASE_RENEWALS.inc(1, node=node)


def record_cluster_suspicion(node: str, peer: str) -> None:
    if not OBS.enabled:
        return
    CLUSTER_SUSPICIONS.inc(1, node=node, peer=peer)
    FLIGHT.record("cluster_suspicion", node=node, peer=peer)


def record_cluster_election_failed(node: str) -> None:
    """One lost election: this node was eligible, past its backoff, raced the
    lease CAS during an actual leader vacancy — and lost. Routine contention
    against a LIVE leader never reaches this hook, so each firing is a real
    failover-stalled edge worth a bundle."""
    if not OBS.enabled:
        return
    FLIGHT.record("election_failed", node=node)
    FLIGHT.dump("election_failed", node=node)


# ------------------------------------------------------------------- partition plane

PART_ROLE = REGISTRY.gauge(
    "metrics_tpu_part_role",
    "This node's role for one keyspace partition: 1 leader (holds the named "
    "lease), 0 follower, per node and partition.",
)
PART_FAILOVERS = REGISTRY.counter(
    "metrics_tpu_part_failovers_total",
    "Per-partition failovers completed by this node: named lease won + "
    "promote() succeeded at the lease epoch, per node and partition.",
)
PART_MIGRATIONS = REGISTRY.counter(
    "metrics_tpu_part_migrations_total",
    "Live tenant migrations completed between partitions (quarantine + "
    "snapshot handoff + destination-first commit), per node.",
)


def set_part_role(node: str, partition: str, role: str) -> None:
    if not OBS.enabled:
        return
    PART_ROLE.set(_ROLE_CODES.get(role, 0), node=node, partition=partition)


def record_part_failover(node: str, partition: str) -> None:
    if not OBS.enabled:
        return
    PART_FAILOVERS.inc(1, node=node, partition=partition)
    FLIGHT.record("part_failover", node=node, partition=partition)


def record_part_lease_lost(node: str, partition: str) -> None:
    """A held partition lease was lost (expired or conceded) and the partition
    stepped down — the per-partition analogue of the cluster plane's failover
    edge, always worth a flight-recorder mark."""
    if not OBS.enabled:
        return
    FLIGHT.record("part_lease_lost", node=node, partition=partition)


def record_part_migration(node: str) -> None:
    if not OBS.enabled:
        return
    PART_MIGRATIONS.inc(1, node=node)
    FLIGHT.record("part_migration", node=node)


PART_WAL_SEQ = REGISTRY.gauge(
    "metrics_tpu_part_wal_seq",
    "Newest WAL position of one partition's engine — journaled seq on a "
    "leader, applied seq on a follower (-1 before the first record), per "
    "engine and partition. The query plane's watermark cache keys on "
    "(epoch, seq) pairs of exactly this number.",
)


def set_part_wal_seq(engine: str, partition: str, seq: int) -> None:
    if not OBS.enabled:
        return
    PART_WAL_SEQ.set(float(seq), engine=engine, partition=partition)


# ------------------------------------------------------------------- query plane

QUERY_GLOBAL = REGISTRY.counter(
    "metrics_tpu_query_global_total",
    "Global (fleet-wide) queries answered by the query plane, per op "
    "(quantile|cardinality|top_k|compute) and result source (cached|merged).",
)
QUERY_CACHE_HITS = REGISTRY.counter(
    "metrics_tpu_query_cache_hits_total",
    "Global query results served from the watermark-keyed cache: every "
    "contributing partition's (epoch, seq) watermark compared equal, no "
    "re-merge ran.",
)
QUERY_CACHE_MISSES = REGISTRY.counter(
    "metrics_tpu_query_cache_misses_total",
    "Global queries that had to re-merge: no cached result, a watermark "
    "advanced, an epoch changed, or the live subset differed.",
)
QUERY_LEADER_READS = REGISTRY.counter(
    "metrics_tpu_query_leader_reads_total",
    "Query-plane reads (rollups or watermark probes) served by a partition's "
    "WRITE LEADER instead of a follower — the number the follower-served "
    "read contract drives to zero under healthy replication, per op.",
)
QUERY_PARTITIONS_MISSING = REGISTRY.counter(
    "metrics_tpu_query_partitions_missing_total",
    "Partitions a global query could not reach (headless past the retry "
    "budget, or every replica refused the staleness bound): the answer "
    "degraded to a NAMED live subset, one count per missing partition per "
    "query, per partition.",
)
QUERY_ROLLUP_SECONDS = REGISTRY.histogram(
    "metrics_tpu_query_rollup_seconds",
    "Wall time of one partition rollup fold (every local tenant's mergeable "
    "state folded into one partition-level state), per engine.",
)


def record_query(op: str, *, cached: bool) -> None:
    if not OBS.enabled:
        return
    QUERY_GLOBAL.inc(1, op=op, source="cached" if cached else "merged")
    if cached:
        QUERY_CACHE_HITS.inc(1)
    else:
        QUERY_CACHE_MISSES.inc(1)


def record_query_leader_read(op: str) -> None:
    if not OBS.enabled:
        return
    QUERY_LEADER_READS.inc(1, op=op)


def record_query_partition_missing(partition: str) -> None:
    if not OBS.enabled:
        return
    QUERY_PARTITIONS_MISSING.inc(1, partition=partition)
    FLIGHT.record("query_partition_missing", partition=partition)


def record_query_rollup_seconds(engine: str, seconds: float) -> None:
    if not OBS.enabled:
        return
    QUERY_ROLLUP_SECONDS.observe(float(seconds), engine=engine)


# ---------------------------------------------------------------------- shard plane

SHARD_TENANTS = REGISTRY.gauge(
    "metrics_tpu_shard_tenants",
    "Registered tenants currently owned by one shard of a ShardedEngine "
    "(consistent-hash placement), per engine and shard.",
)
SHARD_REBALANCES = REGISTRY.counter(
    "metrics_tpu_shard_rebalances_total",
    "Completed shard-count resizes (hash-ring growth + tenant migration), "
    "per sharded engine.",
)


def set_shard_tenants(engine: str, shard: int, tenants: int) -> None:
    if not OBS.enabled:
        return
    SHARD_TENANTS.set(tenants, engine=engine, shard=str(shard))


def record_shard_rebalance(engine: str) -> None:
    if not OBS.enabled:
        return
    SHARD_REBALANCES.inc(1, engine=engine)


# ---------------------------------------------------------------------- tier plane

TIER_RESIDENCY = REGISTRY.gauge(
    "metrics_tpu_tier_residency",
    "Tenants resident in each tier of a tiered StreamingEngine (hot = stacked "
    "device slab, warm = host-RAM mirror, cold = disk spill manifest), per "
    "engine and tier.",
)
TIER_PROMOTIONS = REGISTRY.counter(
    "metrics_tpu_tier_promotions_total",
    "Tenant readmissions into the device slab, per engine and source tier "
    "(warm = host mirror restore, cold = MTCKPT1 spill-file restore).",
)
TIER_DEMOTIONS = REGISTRY.counter(
    "metrics_tpu_tier_demotions_total",
    "Tenant demotions out of the device slab into the host-RAM mirror, "
    "per engine.",
)
TIER_SPILL_BYTES = REGISTRY.counter(
    "metrics_tpu_tier_spill_bytes_total",
    "Bytes written to cold-tier spill files (MTCKPT1 containers), per engine.",
)
ENGINE_SLAB_BYTES = REGISTRY.gauge(
    "metrics_tpu_engine_slab_bytes",
    "Device bytes held by the stacked tenant slab (live segment + window "
    "ring), per engine, dtype group and shard (empty shard label = unsharded).",
)


def set_tier_residency(engine: str, hot: int, warm: int, cold: int) -> None:
    if not OBS.enabled:
        return
    TIER_RESIDENCY.set(hot, engine=engine, tier="hot")
    TIER_RESIDENCY.set(warm, engine=engine, tier="warm")
    TIER_RESIDENCY.set(cold, engine=engine, tier="cold")


def record_tier_promotion(engine: str, source: str) -> None:
    if not OBS.enabled:
        return
    TIER_PROMOTIONS.inc(1, engine=engine, source=source)


def record_tier_demotion(engine: str) -> None:
    if not OBS.enabled:
        return
    TIER_DEMOTIONS.inc(1, engine=engine)


def record_tier_spill(engine: str, nbytes: int) -> None:
    if not OBS.enabled:
        return
    TIER_SPILL_BYTES.inc(nbytes, engine=engine)


def set_engine_slab_bytes(engine: str, dtype: str, nbytes: int, shard: str = "") -> None:
    if not OBS.enabled:
        return
    ENGINE_SLAB_BYTES.set(nbytes, engine=engine, dtype=dtype, shard=shard)


# ---------------------------------------------------------------------- pilot plane

PILOT_DECISIONS = REGISTRY.counter(
    "metrics_tpu_pilot_decisions_total",
    "Autopilot reconcile decisions journaled, per node and decision kind "
    "(partition_hot, rebalance_planned, tier_retune, ...) — flag edges and "
    "refusals-to-act count too, so a silent controller is visibly deciding "
    "nothing rather than dead.",
)
PILOT_MIGRATIONS = REGISTRY.counter(
    "metrics_tpu_pilot_migrations_total",
    "Tenant migrations the autopilot EXECUTED (a subset of "
    "metrics_tpu_part_migrations_total, which also counts operator-driven "
    "moves), per node.",
)
PILOT_PAUSED = REGISTRY.gauge(
    "metrics_tpu_pilot_paused",
    "1 while this node's autopilot actuation is frozen (pause() or "
    "enabled=False) — the kill switch, scrapeable.",
)


def record_pilot_decision(node: str, kind: str) -> None:
    if not OBS.enabled:
        return
    PILOT_DECISIONS.inc(1, node=node, kind=kind)


def record_pilot_migration(node: str) -> None:
    if not OBS.enabled:
        return
    PILOT_MIGRATIONS.inc(1, node=node)
    FLIGHT.record("pilot_migration", node=node)


def set_pilot_paused(node: str, paused: bool) -> None:
    if not OBS.enabled:
        return
    PILOT_PAUSED.set(1 if paused else 0, node=node)


def record_pilot_lease_won(node: str, epoch: int) -> None:
    """This node became the fleet's controller (won the pilot named lease)."""
    if not OBS.enabled:
        return
    FLIGHT.record("pilot_lease_won", node=node, epoch=epoch)


def record_pilot_lease_lost(node: str) -> None:
    if not OBS.enabled:
        return
    FLIGHT.record("pilot_lease_lost", node=node)


def record_pilot_action_failed(node: str, kind: str) -> None:
    """An actuator action raised — always a bundle-worthy edge: the journal
    says what was attempted, the bundle preserves the fleet state it was
    attempted against."""
    if not OBS.enabled:
        return
    FLIGHT.record("pilot_action_failed", node=node, action=kind)
    FLIGHT.dump("pilot_action_failed", node=node, action=kind)


# ---------------------------------------------------------------------- kernel plane

KERNEL_DISPATCHES = REGISTRY.counter(
    "metrics_tpu_kernel_dispatch_total",
    "Kernel-plane registry dispatch decisions per entry and impl "
    "(optimized|reference|fallback). Callers are jitted, so this counts "
    "COMPILED LOWERINGS (one per trace), not per-call executions.",
)
KERNEL_OCCUPANCY = REGISTRY.gauge(
    "metrics_tpu_kernel_occupancy_fraction",
    "Most recently measured fraction-of-ceiling for a kernel-plane entry "
    "(HBM or MXU roofline fraction, per the row's accounting), per entry and "
    "backend — published by benchmarks/suite.py's chained roofline captures "
    "for kernel-mapped rows (obs-gated; CPU fractions are proxy values). "
    "Feeding it back into the bucket-ladder autotuner is ROADMAP headroom.",
)


def record_kernel_dispatch(name: str, impl: str, interpret: bool = False) -> None:
    """Count one registry dispatch decision (at trace time — see the counter help)."""
    if not OBS.enabled:
        return
    KERNEL_DISPATCHES.inc(1, kernel=name, impl=impl, interpret=str(bool(interpret)).lower())


def record_kernel_compile(name: str, signature: str) -> None:
    """Retrace attribution for a kernel-plane entry: one fresh Pallas/XLA
    compile at ``kernels.<name>`` against the operand signature that caused it."""
    if not OBS.enabled:
        return
    RETRACES.inc(1, site=f"kernels.{name}", signature=signature)


def record_kernel_occupancy(name: str, fraction: float, backend: str) -> None:
    """Publish a measured fraction-of-ceiling for one kernel entry (benchmark-side)."""
    if not OBS.enabled:
        return
    KERNEL_OCCUPANCY.set(fraction, kernel=name, backend=backend)


# ---------------------------------------------------------------------- engine hooks


def record_engine_compile(signature: Any, bucket: int, capacity: int) -> None:
    """Retrace attribution for the engine's bucket kernels, at kernel-cache-miss
    time: one recorded compile per new (request signature, bucket, capacity)."""
    if not OBS.enabled:
        return
    RETRACES.inc(
        1,
        site="engine.bucket_kernel",
        signature=f"{abstract_signature(signature)}|bucket={bucket}|capacity={capacity}",
    )


def engine_span(name: str, **attrs: Any) -> Any:
    """Trace span for engine internals (dispatch, drain, inline apply)."""
    if not OBS.enabled:
        return _NULL_SPAN
    return TRACER.span(name, **attrs)
