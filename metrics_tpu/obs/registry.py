"""Process-global metrics registry: named counters/gauges/histograms with labels.

The registry is the shared substrate for every telemetry producer in the
library — the engine's :class:`~metrics_tpu.engine.telemetry.EngineTelemetry`,
the instrumentation hooks in :mod:`metrics_tpu.obs.instrument`, and any user
code that wants a process-wide number. One ``Registry`` instance
(:data:`REGISTRY`) serves the whole process; instruments are get-or-create by
name so independent subsystems share series instead of colliding.

Reads produce plain dicts (:meth:`Registry.snapshot`), a Prometheus v0.0.4
text exposition (:meth:`Registry.render_prometheus`) for scraping, and JSONL
lines through the one shared writer (:mod:`metrics_tpu.obs.jsonl`).

This module also hosts the library-wide master switch :data:`OBS`: every
instrumentation hook tests ``OBS.enabled`` — a single attribute load, no lock
— before doing any work, so the disabled library is indistinguishable from an
uninstrumented one (gated by ``benchmarks/obs_overhead.py``). Direct registry
use (``counter(...).inc()``) is NOT gated: a subsystem that records
explicitly, like the engine's telemetry, always records.

Stdlib only — no jax/numpy import, so ``metrics_tpu.obs`` stays importable in
any stripped environment.
"""

from __future__ import annotations

import re
import threading
from bisect import bisect_left
from typing import Any, Dict, Iterable, List, Optional, Tuple

from metrics_tpu.obs.jsonl import append_jsonl


class ObsGate:
    """The one master switch. A bare attribute (``OBS.enabled``) so the hot-path
    check in ``Metric._wrap_update`` et al. is a single LOAD_ATTR, not a call."""

    __slots__ = ("enabled",)

    def __init__(self) -> None:
        self.enabled = False


OBS = ObsGate()

# Prometheus text-format identifier grammars (exposition format v0.0.4).
_METRIC_NAME_RE = re.compile(r"^[a-zA-Z_:][a-zA-Z0-9_:]*$")
_LABEL_NAME_RE = re.compile(r"^[a-zA-Z_][a-zA-Z0-9_]*$")

LabelKey = Tuple[Tuple[str, str], ...]

# Default histogram edges: latency-shaped (seconds), 1µs → 10s decades.
DEFAULT_BUCKETS: Tuple[float, ...] = (
    1e-6, 1e-5, 1e-4, 1e-3, 1e-2, 1e-1, 1.0, 10.0,
)


def _label_key(labels: Dict[str, Any]) -> LabelKey:
    """Canonical hashable identity of a label set (sorted, stringified values)."""
    if not labels:
        return ()
    for name in labels:
        if not _LABEL_NAME_RE.match(name):
            raise ValueError(f"invalid Prometheus label name {name!r}")
    return tuple(sorted((k, str(v)) for k, v in labels.items()))


def _escape_label_value(value: str) -> str:
    return value.replace("\\", "\\\\").replace('"', '\\"').replace("\n", "\\n")


def _escape_help(text: str) -> str:
    return text.replace("\\", "\\\\").replace("\n", "\\n")


def _fmt_value(v: float) -> str:
    """Prometheus sample value: integral counts render without a fraction."""
    f = float(v)
    if f != f:  # NaN
        return "NaN"
    if f in (float("inf"), float("-inf")):
        return "+Inf" if f > 0 else "-Inf"
    if f == int(f) and abs(f) < 1e15:
        return str(int(f))
    return repr(f)


def _render_labels(key: LabelKey, extra: Tuple[Tuple[str, str], ...] = ()) -> str:
    pairs = key + extra
    if not pairs:
        return ""
    body = ",".join(f'{k}="{_escape_label_value(v)}"' for k, v in pairs)
    return "{" + body + "}"


def _key_str(key: LabelKey) -> str:
    """Human-readable label identity for ``snapshot()`` dict keys."""
    return ",".join(f"{k}={v}" for k, v in key)


class _Instrument:
    """Base: a named family of samples, one value slot per label set."""

    kind = "untyped"

    def __init__(self, name: str, help: str) -> None:
        self.name = name
        self.help = help
        self._lock = threading.Lock()

    def label_key(self, **labels: Any) -> LabelKey:
        """Precompute (and validate) a label identity once; hot paths can then
        use the ``*_key`` fast variants and skip per-call validation/sorting."""
        return _label_key(labels)

    def _value_maps(self) -> Tuple[Dict[LabelKey, Any], ...]:
        raise NotImplementedError

    def drop_labels(self, **labels: Any) -> None:
        """Evict every series whose label set CONTAINS ``labels`` (e.g. one
        engine's ``engine=<id>`` family) — the anti-leak hook for subsystems
        that materialise per-instance series in the process-global registry."""
        match = set(_label_key(labels))
        with self._lock:
            for values in self._value_maps():
                for key in [k for k in values if match <= set(k)]:
                    del values[key]

    def clear(self) -> None:
        raise NotImplementedError


class Counter(_Instrument):
    """Monotone counter family. ``inc`` is the only mutator; negative increments
    raise (a counter that goes down is a gauge)."""

    kind = "counter"

    def __init__(self, name: str, help: str) -> None:
        super().__init__(name, help)
        self._values: Dict[LabelKey, float] = {}

    def inc(self, n: float = 1, **labels: Any) -> None:
        if n < 0:
            raise ValueError(f"counter {self.name!r} cannot decrease (inc({n}))")
        key = _label_key(labels)
        with self._lock:
            self._values[key] = self._values.get(key, 0) + n

    def inc_key(self, key: LabelKey, n: float = 1) -> None:
        """Hot-path inc with a :meth:`label_key`-precomputed identity (no
        per-call validation/sorting/stringification)."""
        if n < 0:
            raise ValueError(f"counter {self.name!r} cannot decrease (inc({n}))")
        with self._lock:
            self._values[key] = self._values.get(key, 0) + n

    def inc_many_keys(self, updates: Iterable[Tuple[float, LabelKey]]) -> None:
        """``inc_many`` over precomputed keys: one lock, zero per-call label work."""
        updates = list(updates)
        if any(n < 0 for n, _ in updates):
            raise ValueError(f"counter {self.name!r} cannot decrease (inc_many_keys)")
        with self._lock:
            for n, key in updates:
                self._values[key] = self._values.get(key, 0) + n

    def inc_many(self, updates: Iterable[Tuple[float, Dict[str, Any]]]) -> None:
        """Apply several ``(n, labels)`` increments under ONE lock acquisition.

        For multi-series invariants (e.g. the engine's rows/padded_rows/batches
        per dispatched micro-batch): a concurrent ``collect()`` sees either all
        of the group's increments or none, never a partial batch.
        """
        keyed = [(float(n), _label_key(labels)) for n, labels in updates]
        if any(n < 0 for n, _ in keyed):
            raise ValueError(f"counter {self.name!r} cannot decrease (inc_many)")
        with self._lock:
            for n, key in keyed:
                self._values[key] = self._values.get(key, 0) + n

    def touch(self, **labels: Any) -> None:
        """Materialise a zero-valued series so exports show it before first inc."""
        key = _label_key(labels)
        with self._lock:
            self._values.setdefault(key, 0)

    def value(self, **labels: Any) -> float:
        key = _label_key(labels)
        with self._lock:
            return self._values.get(key, 0)

    def collect(self) -> Dict[LabelKey, float]:
        with self._lock:
            return dict(self._values)

    def _value_maps(self) -> Tuple[Dict[LabelKey, Any], ...]:
        return (self._values,)

    def clear(self) -> None:
        with self._lock:
            self._values.clear()


class Gauge(_Instrument):
    """Point-in-time value family (queue depths, capacities, flags)."""

    kind = "gauge"

    def __init__(self, name: str, help: str) -> None:
        super().__init__(name, help)
        self._values: Dict[LabelKey, float] = {}

    def set(self, value: float, **labels: Any) -> None:
        key = _label_key(labels)
        with self._lock:
            self._values[key] = float(value)

    def set_key(self, key: LabelKey, value: float) -> None:
        """Hot-path set with a precomputed label identity."""
        with self._lock:
            self._values[key] = float(value)

    def inc(self, n: float = 1, **labels: Any) -> None:
        key = _label_key(labels)
        with self._lock:
            self._values[key] = self._values.get(key, 0) + n

    def value(self, **labels: Any) -> float:
        key = _label_key(labels)
        with self._lock:
            return self._values.get(key, 0)

    def collect(self) -> Dict[LabelKey, float]:
        with self._lock:
            return dict(self._values)

    def _value_maps(self) -> Tuple[Dict[LabelKey, Any], ...]:
        return (self._values,)

    def clear(self) -> None:
        with self._lock:
            self._values.clear()


class Histogram(_Instrument):
    """Bucketed distribution family with per-label-set (buckets, sum, count).

    Buckets are upper-inclusive edges (Prometheus ``le`` semantics); an implicit
    ``+Inf`` overflow bucket always exists. Stored counts are per-bucket
    (non-cumulative); the Prometheus renderer emits the cumulative form the
    text format requires.
    """

    kind = "histogram"

    def __init__(self, name: str, help: str, buckets: Iterable[float] = DEFAULT_BUCKETS) -> None:
        super().__init__(name, help)
        edges = sorted(float(b) for b in buckets)
        if not edges:
            raise ValueError(f"histogram {self.name!r} needs at least one bucket edge")
        if any(e != e or e in (float("inf"), float("-inf")) for e in edges):
            raise ValueError(f"histogram {self.name!r} edges must be finite (``+Inf`` is implicit)")
        if len(set(edges)) != len(edges):
            raise ValueError(f"histogram {self.name!r} has duplicate bucket edges")
        self.edges: Tuple[float, ...] = tuple(edges)
        # labelkey -> [per-bucket counts... , overflow]; plus running sum/count
        self._buckets: Dict[LabelKey, List[int]] = {}
        self._sums: Dict[LabelKey, float] = {}
        self._counts: Dict[LabelKey, int] = {}

    def observe(self, value: float, **labels: Any) -> None:
        self.observe_key(_label_key(labels), value)

    def observe_key(self, key: LabelKey, value: float) -> None:
        """Hot-path observe with a precomputed label identity."""
        v = float(value)
        idx = bisect_left(self.edges, v)  # first edge >= v, i.e. smallest le-bucket
        with self._lock:
            row = self._buckets.get(key)
            if row is None:
                row = self._buckets[key] = [0] * (len(self.edges) + 1)
            row[idx] += 1
            self._sums[key] = self._sums.get(key, 0.0) + v
            self._counts[key] = self._counts.get(key, 0) + 1

    def touch(self, **labels: Any) -> None:
        key = _label_key(labels)
        with self._lock:
            self._buckets.setdefault(key, [0] * (len(self.edges) + 1))
            self._sums.setdefault(key, 0.0)
            self._counts.setdefault(key, 0)

    def bucket_counts(self, **labels: Any) -> Dict[float, int]:
        """Per-edge (non-cumulative) counts; the overflow bucket under ``inf``."""
        key = _label_key(labels)
        with self._lock:
            row = self._buckets.get(key, [0] * (len(self.edges) + 1))
            out = {edge: row[i] for i, edge in enumerate(self.edges)}
            out[float("inf")] = row[-1]
            return out

    def count(self, **labels: Any) -> int:
        key = _label_key(labels)
        with self._lock:
            return self._counts.get(key, 0)

    def sum(self, **labels: Any) -> float:
        key = _label_key(labels)
        with self._lock:
            return self._sums.get(key, 0.0)

    def collect(self) -> Dict[LabelKey, Tuple[List[int], float, int]]:
        with self._lock:
            return {
                key: (list(row), self._sums.get(key, 0.0), self._counts.get(key, 0))
                for key, row in self._buckets.items()
            }

    def _value_maps(self) -> Tuple[Dict[LabelKey, Any], ...]:
        return (self._buckets, self._sums, self._counts)

    def clear(self) -> None:
        with self._lock:
            self._buckets.clear()
            self._sums.clear()
            self._counts.clear()


class Registry:
    """Thread-safe, ordered, get-or-create home for instrument families.

    Re-requesting a name returns the existing instrument; a kind (or, for
    histograms, bucket-edge) mismatch raises instead of silently forking the
    series — two subsystems disagreeing about what a name means is a bug.
    """

    def __init__(self) -> None:
        self._lock = threading.Lock()
        self._instruments: Dict[str, _Instrument] = {}

    def _get_or_create(self, cls: type, name: str, help: str, **kwargs: Any) -> Any:
        if not _METRIC_NAME_RE.match(name):
            raise ValueError(f"invalid Prometheus metric name {name!r}")
        with self._lock:
            inst = self._instruments.get(name)
            if inst is None:
                create_kwargs = dict(kwargs)
                if cls is Histogram:
                    create_kwargs.setdefault("buckets", DEFAULT_BUCKETS)
                inst = cls(name, help, **create_kwargs)
                self._instruments[name] = inst
                return inst
        if not isinstance(inst, cls):
            raise TypeError(
                f"registry name {name!r} is already a {inst.kind}, requested {cls.kind}"  # type: ignore[attr-defined]
            )
        if cls is Histogram and "buckets" in kwargs:
            requested = tuple(sorted(float(b) for b in kwargs["buckets"]))
            if requested != inst.edges:  # type: ignore[union-attr]
                raise ValueError(
                    f"histogram {name!r} already registered with edges {inst.edges}, requested {requested}"  # type: ignore[union-attr]
                )
        return inst

    def counter(self, name: str, help: str = "") -> Counter:
        return self._get_or_create(Counter, name, help)

    def gauge(self, name: str, help: str = "") -> Gauge:
        return self._get_or_create(Gauge, name, help)

    def histogram(self, name: str, help: str = "", buckets: Optional[Iterable[float]] = None) -> Histogram:
        """Get or create a histogram. ``buckets=None`` means "whatever edges the
        family has" (DEFAULT_BUCKETS when creating) — only an EXPLICIT edge set
        is checked against an existing family, so a plain get of a custom-edge
        histogram never trips the conflict check."""
        if buckets is None:
            return self._get_or_create(Histogram, name, help)
        return self._get_or_create(Histogram, name, help, buckets=buckets)

    def get(self, name: str) -> Optional[_Instrument]:
        with self._lock:
            return self._instruments.get(name)

    def names(self) -> List[str]:
        with self._lock:
            return list(self._instruments)

    # ------------------------------------------------------------------ reading

    def snapshot(self) -> Dict[str, Any]:
        """Everything as one plain dict (logs, dashboards, jsonl).

        Shape per family: ``{"type", "help", "values"}`` where ``values`` maps a
        ``"k=v,k2=v2"`` label string (``""`` for the unlabeled series) to the
        sample — a number for counters/gauges, ``{"buckets", "sum", "count"}``
        for histograms (bucket keys are the stringified upper edges, ``"inf"``
        for overflow).
        """
        with self._lock:
            instruments = list(self._instruments.items())
        out: Dict[str, Any] = {}
        for name, inst in instruments:
            if isinstance(inst, Histogram):
                values: Dict[str, Any] = {}
                for key, (row, total, count) in inst.collect().items():
                    buckets = {str(edge): row[i] for i, edge in enumerate(inst.edges)}
                    buckets["inf"] = row[-1]
                    values[_key_str(key)] = {"buckets": buckets, "sum": total, "count": count}
            else:
                values = {_key_str(key): v for key, v in inst.collect().items()}
            out[name] = {"type": inst.kind, "help": inst.help, "values": values}
        return out

    def render_prometheus(self) -> str:
        """Prometheus text exposition format v0.0.4 (``text/plain; version=0.0.4``)."""
        with self._lock:
            instruments = list(self._instruments.items())
        lines: List[str] = []
        for name, inst in instruments:
            if inst.help:
                lines.append(f"# HELP {name} {_escape_help(inst.help)}")
            lines.append(f"# TYPE {name} {inst.kind}")
            if isinstance(inst, Histogram):
                for key, (row, total, count) in sorted(inst.collect().items()):
                    cumulative = 0
                    for i, edge in enumerate(inst.edges):
                        cumulative += row[i]
                        labels = _render_labels(key, (("le", _fmt_value(edge)),))
                        lines.append(f"{name}_bucket{labels} {cumulative}")
                    labels = _render_labels(key, (("le", "+Inf"),))
                    lines.append(f"{name}_bucket{labels} {count}")
                    lines.append(f"{name}_sum{_render_labels(key)} {_fmt_value(total)}")
                    lines.append(f"{name}_count{_render_labels(key)} {count}")
            else:
                for key, value in sorted(inst.collect().items()):
                    lines.append(f"{name}{_render_labels(key)} {_fmt_value(value)}")
        return "\n".join(lines) + "\n" if lines else ""

    def emit(self, path: str, **extra: Any) -> Dict[str, Any]:
        """Append one full snapshot as a JSONL record through the shared writer."""
        record: Dict[str, Any] = {"what": "obs_registry", **extra, "registry": self.snapshot()}
        append_jsonl(path, record)
        return record

    # ------------------------------------------------------------------ lifecycle

    def clear_values(self) -> None:
        """Zero every recorded sample, keeping registered instruments (and any
        references subsystems hold to them) valid — the test-isolation hook."""
        with self._lock:
            instruments = list(self._instruments.values())
        for inst in instruments:
            inst.clear()


REGISTRY = Registry()
