"""Fleet telemetry aggregation: one merged Prometheus view across hosts.

Every process's :data:`~metrics_tpu.obs.registry.REGISTRY` is process-local.
This module makes the fleet scrapeable from one place without growing a new
transport: nodes serialise a compact, lossless registry snapshot
(:func:`node_snapshot`) and piggyback it on channels they already own —
repl heartbeat frames (primary → follower) and ``CoordStore`` membership
records (every node → whoever reads the member table, i.e. the leader) — and a
:class:`FleetAggregator` merges whatever arrives into one
``render_prometheus()`` page with a ``node=<id>`` label on every series.

Staleness is first-class: each node's latest snapshot carries an ingest stamp;
past ``stale_after_s`` its series render with
``metrics_tpu_fleet_node_stale{node=...} 1`` (still visible — a silent node is
an alert, not a gap), and past ``retire_after_s`` the node's series are
retired from the page entirely (dead-node label-set hygiene: a fleet that
churns hosts must not accrete series forever).

The snapshot format carries label sets as explicit pairs (never the
``"k=v,k2=v2"`` display string — label values legally contain ``,`` and
``=``), so merging is lossless. Stdlib only.
"""

from __future__ import annotations

import threading
import time
from typing import Any, Callable, Dict, Iterable, List, Optional, Tuple

from metrics_tpu.obs.registry import (
    REGISTRY,
    Histogram,
    Registry,
    _escape_help,
    _fmt_value,
    _render_labels,
)

SNAPSHOT_KIND = "metrics_tpu-fleet-node"
SNAPSHOT_VERSION = 1


def node_snapshot(node_id: str, registry: Optional[Registry] = None) -> Dict[str, Any]:
    """This process's registry as one compact, JSON-able, lossless document.

    Shape: ``{"kind", "version", "node", "t_wall", "families"}`` where each
    family is ``{"type", "help", "samples"}`` and each sample is
    ``[[[label, value], ...], sample_value]`` — histogram sample values are
    ``{"edges", "buckets", "sum", "count"}`` with non-cumulative rows.
    """
    reg = REGISTRY if registry is None else registry
    families: Dict[str, Any] = {}
    for name in reg.names():
        inst = reg.get(name)
        if inst is None:
            continue
        samples: List[Any] = []
        if isinstance(inst, Histogram):
            for key, (row, total, count) in inst.collect().items():
                samples.append(
                    [
                        [list(pair) for pair in key],
                        {
                            "edges": list(inst.edges),
                            "buckets": list(row),
                            "sum": total,
                            "count": count,
                        },
                    ]
                )
        else:
            for key, value in inst.collect().items():
                samples.append([[list(pair) for pair in key], value])
        families[name] = {"type": inst.kind, "help": inst.help, "samples": samples}
    return {
        "kind": SNAPSHOT_KIND,
        "version": SNAPSHOT_VERSION,
        "node": str(node_id),
        "t_wall": time.time(),
        "families": families,
    }


class FleetAggregator:
    """Merge per-node snapshots into one fleet-wide Prometheus/jsonl view."""

    def __init__(
        self,
        stale_after_s: float = 10.0,
        retire_after_s: float = 60.0,
        clock: Callable[[], float] = time.monotonic,
    ) -> None:
        if retire_after_s < stale_after_s:
            raise ValueError("retire_after_s must be >= stale_after_s")
        self.stale_after_s = float(stale_after_s)
        self.retire_after_s = float(retire_after_s)
        self._clock = clock
        self._lock = threading.Lock()
        # node -> (snapshot, ingest stamp on self._clock)
        self._nodes: Dict[str, Tuple[Dict[str, Any], float]] = {}
        self._retired: List[str] = []

    # ------------------------------------------------------------------ ingest

    def ingest(self, snap: Dict[str, Any], node_id: Optional[str] = None) -> None:
        """Accept one node snapshot (latest-wins per node)."""
        if not isinstance(snap, dict) or snap.get("kind") != SNAPSHOT_KIND:
            return  # wrong/garbled payload on a shared channel: ignore, don't raise
        node = str(node_id if node_id is not None else snap.get("node", ""))
        if not node:
            return
        with self._lock:
            self._nodes[node] = (snap, self._clock())

    def ingest_members(self, members: Iterable[Any]) -> int:
        """Pull piggybacked snapshots off a ``CoordStore`` member table.

        Any member object with a non-None ``fleet`` attribute contributes;
        returns how many were ingested (the leader's merge-loop heartbeat).
        """
        n = 0
        for member in members:
            snap = getattr(member, "fleet", None)
            if snap is not None:
                self.ingest(snap, node_id=getattr(member, "node_id", None))
                n += 1
        return n

    # ------------------------------------------------------------------ reading

    def _sweep(self, now: float) -> List[Tuple[str, Dict[str, Any], float, bool]]:
        """Retire dead nodes; return live (node, snap, age, stale) rows sorted."""
        with self._lock:
            for node in [
                n for n, (_, t) in self._nodes.items() if now - t > self.retire_after_s
            ]:
                del self._nodes[node]
                self._retired.append(node)
            rows = [
                (node, snap, now - t, now - t > self.stale_after_s)
                for node, (snap, t) in self._nodes.items()
            ]
        rows.sort(key=lambda r: r[0])
        return rows

    def nodes(self) -> Dict[str, Dict[str, Any]]:
        """Per-node liveness view: ``{node: {"age_s", "stale"}}`` (post-sweep)."""
        return {
            node: {"age_s": age, "stale": stale}
            for node, _, age, stale in self._sweep(self._clock())
        }

    def rows(self) -> List[Tuple[str, Dict[str, Any], float, bool]]:
        """Live ``(node, snapshot, age_s, stale)`` rows, post-sweep — the
        consumer-side view (the autopilot's signal source): retired nodes are
        gone, stale ones are flagged so a reader can exclude rather than
        extrapolate."""
        return self._sweep(self._clock())

    def retired(self) -> List[str]:
        """Nodes whose series were retired for silence, in retirement order."""
        with self._lock:
            return list(self._retired)

    def render_prometheus(self) -> str:
        """One merged Prometheus v0.0.4 page: every live node's series with a
        ``node=<id>`` label, plus the fleet meta-series (staleness, ages,
        node count)."""
        rows = self._sweep(self._clock())
        # merged family table: name -> (type, help, [(node, label_pairs, sample)])
        merged: Dict[str, Tuple[str, str, List[Tuple[str, Any, Any]]]] = {}
        for node, snap, _, _ in rows:
            for name, family in sorted(snap.get("families", {}).items()):
                entry = merged.get(name)
                if entry is None:
                    entry = merged[name] = (family["type"], family["help"], [])
                for pairs, sample in family["samples"]:
                    entry[2].append((node, pairs, sample))
        lines: List[str] = [
            "# HELP metrics_tpu_fleet_nodes Live nodes currently contributing "
            "series to this fleet view.",
            "# TYPE metrics_tpu_fleet_nodes gauge",
            f"metrics_tpu_fleet_nodes {len(rows)}",
            "# HELP metrics_tpu_fleet_node_stale 1 while the labeled node's "
            "snapshot is older than stale_after_s (silent node), else 0.",
            "# TYPE metrics_tpu_fleet_node_stale gauge",
        ]
        for node, _, _, stale in rows:
            lines.append(
                f"metrics_tpu_fleet_node_stale{_render_labels((('node', node),))} "
                f"{1 if stale else 0}"
            )
        lines.append(
            "# HELP metrics_tpu_fleet_node_age_seconds Seconds since the labeled "
            "node's snapshot was last ingested."
        )
        lines.append("# TYPE metrics_tpu_fleet_node_age_seconds gauge")
        for node, _, age, _ in rows:
            lines.append(
                f"metrics_tpu_fleet_node_age_seconds"
                f"{_render_labels((('node', node),))} {_fmt_value(age)}"
            )
        for name in sorted(merged):
            kind, help_text, samples = merged[name]
            if help_text:
                lines.append(f"# HELP {name} {_escape_help(help_text)}")
            lines.append(f"# TYPE {name} {kind}")
            # node label leads; a node's own `node=` label (cluster series) is
            # overridden by the fleet's authoritative attribution
            keyed = []
            for node, pairs, sample in samples:
                label_key = tuple(
                    [("node", node)]
                    + [(str(k), str(v)) for k, v in pairs if str(k) != "node"]
                )
                keyed.append((label_key, sample))
            keyed.sort(key=lambda kv: kv[0])
            for label_key, sample in keyed:
                if kind == "histogram":
                    edges = sample["edges"]
                    row = sample["buckets"]
                    cumulative = 0
                    for i, edge in enumerate(edges):
                        cumulative += row[i]
                        labels = _render_labels(label_key + (("le", _fmt_value(edge)),))
                        lines.append(f"{name}_bucket{labels} {cumulative}")
                    labels = _render_labels(label_key + (("le", "+Inf"),))
                    lines.append(f"{name}_bucket{labels} {sample['count']}")
                    lines.append(
                        f"{name}_sum{_render_labels(label_key)} {_fmt_value(sample['sum'])}"
                    )
                    lines.append(f"{name}_count{_render_labels(label_key)} {sample['count']}")
                else:
                    lines.append(
                        f"{name}{_render_labels(label_key)} {_fmt_value(sample)}"
                    )
        return "\n".join(lines) + "\n"

    def snapshot(self) -> Dict[str, Any]:
        """The fleet view as one plain dict (jsonl / dashboards / tests)."""
        rows = self._sweep(self._clock())
        return {
            "what": "obs_fleet",
            "nodes": {
                node: {"age_s": age, "stale": stale, "t_wall": snap.get("t_wall")}
                for node, snap, age, stale in rows
            },
            "retired": self.retired(),
            "families": sorted(
                {name for _, snap, _, _ in rows for name in snap.get("families", {})}
            ),
        }

    # ------------------------------------------------------------------ lifecycle

    def clear(self) -> None:
        with self._lock:
            self._nodes.clear()
            self._retired.clear()


# The process-global aggregator: repl appliers and cluster leaders ingest here
# by default, so `fleet.AGGREGATOR.render_prometheus()` is the one-endpoint
# scrape a ClusterClient host serves. Tests may build private instances.
AGGREGATOR = FleetAggregator()
