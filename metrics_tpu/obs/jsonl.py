"""Shared append-only JSONL recording — ONE writer for the whole stack.

Both the repo's hardware-evidence tooling (``tools/jsonl_log.py``) and the
library's own emitters (``EngineTelemetry.emit``, ``obs.Registry.emit``)
delegate here, so there is exactly one record format and one atomicity
contract: a single short ``O_APPEND`` write per record is atomic on POSIX, so
overlapping watcher + manual runs interleave whole lines instead of racing a
read-modify-write of one document. Recording must never break the run being
recorded: failures are noted on the record itself instead of raised.

Stdlib only — ``metrics_tpu.obs`` is importable with no third-party deps.
"""

from __future__ import annotations

import json
import time
from typing import Any, Dict


def append_jsonl(path: str, record: Dict[str, Any]) -> None:
    """Append ``record`` as one JSON line to ``path`` (UTC-stamped, never raises)."""
    try:
        record.setdefault("utc", time.strftime("%Y-%m-%dT%H:%M:%SZ", time.gmtime()))
        with open(path, "a") as fh:
            fh.write(json.dumps(record, default=_coerce) + "\n")
    except Exception as exc:  # noqa: BLE001 — recording must never break the caller
        record["log_error"] = repr(exc)


def _coerce(obj: Any) -> Any:
    """Last-resort JSON coercion for array scalars and other numerics.

    Registry snapshots can carry numpy/jax scalars when callers attach derived
    stats; a hard ``TypeError`` here would defeat the never-raise contract, so
    anything float()-able serializes as a number and the rest as ``repr``.
    """
    try:
        return float(obj)
    except Exception:  # noqa: BLE001
        return repr(obj)
