"""Black-box flight recorder: always-on bounded event ring + post-mortem bundles.

An aircraft-style recorder for the serving stack: while obs is enabled it
keeps a bounded ring of recent *edges* — health transitions, breaker state
changes, membership/lease/live-set movement, guard decisions — alongside the
span ring the tracer already holds. On any **triggering edge** (guard
quarantine, breaker open, watchdog restart, failed election, ``agree_live_set``
shrink) it dumps one self-contained post-mortem bundle:

- the trigger + wall-clock stamp,
- the recent-event ring (the causal run-up),
- the tracer's retained spans as a Chrome trace document,
- a full registry snapshot,
- every registered context provider's view (engines register ``health()`` +
  last WAL seq; cluster nodes register their member table) — provider
  failures are captured in-bundle, never raised,
- the live-set history (the membership edges retained in the ring).

Triggers are *edges*, not states: the instrument hooks feed state changes in
(:func:`~metrics_tpu.obs.instrument.record_health_transition`,
breaker-state transitions deduped here), so one incident dumps one bundle per
distinct edge however many times the underlying gauge is refreshed.

Bundles are kept in memory (bounded) and, when :meth:`FlightRecorder.configure`
set a directory, written as self-describing JSON files that
``tools/obs_dump.py`` renders into a causal timeline. Everything is gated on
``OBS.enabled``: disabled, every entry point is one attribute test.

Stdlib only.
"""

from __future__ import annotations

import json
import os
import threading
import time
from collections import deque
from typing import Any, Callable, Dict, List, Optional

from metrics_tpu.obs.registry import OBS, REGISTRY

BUNDLE_KIND = "metrics_tpu-flight"
BUNDLE_VERSION = 1

# the edges that dump a bundle (the trigger matrix in docs/source/observability.md)
TRIGGERS = (
    "guard_quarantine",
    "engine_quarantine",
    "breaker_open",
    "watchdog_restart",
    "election_failed",
    "live_set_shrink",
    "pilot_action_failed",
)


def _json_safe(x: Any) -> Any:
    """Best-effort conversion of provider output into JSON-serializable data."""
    if x is None or isinstance(x, (bool, int, float, str)):
        return x
    if isinstance(x, dict):
        return {str(k): _json_safe(v) for k, v in x.items()}
    if isinstance(x, (list, tuple, set, frozenset)):
        return [_json_safe(v) for v in x]
    return repr(x)


class FlightRecorder:
    """Process-global bounded edge ring + triggered post-mortem bundle dumps."""

    def __init__(self, capacity: int = 1024, max_bundles: int = 8) -> None:
        self._lock = threading.Lock()
        self._events: deque = deque(maxlen=int(capacity))
        self._seq = 0
        self._directory: Optional[str] = None
        self._max_bundles = int(max_bundles)
        self._bundles: List[Dict[str, Any]] = []
        self._dump_counts: Dict[str, int] = {}
        self._dumps_total = 0
        # context providers: name -> zero-arg callable returning a JSON-able view
        self._providers: Dict[str, Callable[[], Any]] = {}
        # breaker-edge dedup: (engine, breaker) -> last seen state code
        self._breaker_states: Dict[Any, int] = {}

    # ------------------------------------------------------------------ wiring

    def configure(
        self,
        directory: Optional[str] = None,
        max_bundles: Optional[int] = None,
    ) -> None:
        """Set (or clear) the on-disk bundle directory and the in-memory bound."""
        with self._lock:
            self._directory = directory
            if max_bundles is not None:
                self._max_bundles = int(max_bundles)

    def register_provider(self, name: str, fn: Callable[[], Any]) -> None:
        """Attach a named context provider snapshotted into every bundle.

        Engines register their ``health()`` + WAL position here at
        construction; re-registering a name replaces it (an engine restarted
        under the same id supersedes the dead incarnation's closure).
        """
        with self._lock:
            self._providers[name] = fn

    def unregister_provider(self, name: str) -> None:
        with self._lock:
            self._providers.pop(name, None)

    # ------------------------------------------------------------------ recording

    def record(self, kind: str, **attrs: Any) -> None:
        """Append one edge to the ring (gated; cheap enough for cold paths)."""
        if not OBS.enabled:
            return
        with self._lock:
            self._seq += 1
            self._events.append(
                {"seq": self._seq, "t_wall": time.time(), "kind": kind, **attrs}
            )

    def record_breaker_state(self, engine: str, breaker: str, state_code: int) -> None:
        """Dedup breaker gauge refreshes into edges; dump on the open edge.

        The gauge hook calls this on every publish — only an actual state
        CHANGE lands in the ring, and only the transition *into* open (2)
        triggers a bundle.
        """
        if not OBS.enabled:
            return
        key = (engine, breaker)
        with self._lock:
            prev = self._breaker_states.get(key)
            if prev == state_code:
                return
            self._breaker_states[key] = state_code
        self.record(
            "breaker_state", engine=engine, breaker=breaker,
            state=state_code, prev_state=prev,
        )
        if state_code == 2:
            self.dump("breaker_open", engine=engine, breaker=breaker)

    # ------------------------------------------------------------------ dumping

    def dump(self, trigger: str, **attrs: Any) -> Optional[Dict[str, Any]]:
        """Assemble one self-contained post-mortem bundle for ``trigger``.

        Returns the bundle (also retained in memory and written to the
        configured directory). Never raises: a broken provider or an
        unwritable directory is captured in the bundle itself.
        """
        if not OBS.enabled:
            return None
        from metrics_tpu.obs.trace import TRACER

        with self._lock:
            providers = dict(self._providers)
            events = list(self._events)
            directory = self._directory
            self._dumps_total += 1
            self._dump_counts[trigger] = self._dump_counts.get(trigger, 0) + 1
            serial = self._dumps_total
        contexts: Dict[str, Any] = {}
        for name, fn in providers.items():
            try:
                contexts[name] = _json_safe(fn())
            except Exception as exc:  # noqa: BLE001 — a dead provider is evidence, not an error
                contexts[name] = {"provider_error": repr(exc)}
        bundle: Dict[str, Any] = {
            "bundle": BUNDLE_KIND,
            "version": BUNDLE_VERSION,
            "serial": serial,
            "trigger": trigger,
            "trigger_attrs": _json_safe(attrs),
            "t_wall": time.time(),
            "pid": os.getpid(),
            "events": events,
            "live_set_history": [e for e in events if e["kind"] == "comm_live_set"],
            "trace": TRACER.export_chrome_trace(),
            "registry": REGISTRY.snapshot(),
            "contexts": contexts,
        }
        path = None
        if directory is not None:
            try:
                os.makedirs(directory, exist_ok=True)
                path = os.path.join(directory, f"flight-{serial:04d}-{trigger}.json")
                tmp = path + ".tmp"
                with open(tmp, "w") as fh:
                    json.dump(bundle, fh)
                os.replace(tmp, path)
            except Exception as exc:  # noqa: BLE001 — IO failure must not poison the trigger site
                bundle["write_error"] = repr(exc)
                path = None
        bundle["path"] = path
        with self._lock:
            self._bundles.append(bundle)
            del self._bundles[: -self._max_bundles]
        return bundle

    # ------------------------------------------------------------------ reading

    def events(self) -> List[Dict[str, Any]]:
        with self._lock:
            return list(self._events)

    def bundles(self) -> List[Dict[str, Any]]:
        """Retained in-memory bundles, oldest first."""
        with self._lock:
            return list(self._bundles)

    def dump_counts(self) -> Dict[str, int]:
        """Bundles dumped per trigger since the last clear (exactly-once checks)."""
        with self._lock:
            return dict(self._dump_counts)

    # ------------------------------------------------------------------ lifecycle

    def clear(self) -> None:
        """Drop events, bundles, dedup state and counters; keep wiring
        (directory + providers survive — test isolation mirrors obs.reset())."""
        with self._lock:
            self._events.clear()
            self._seq = 0
            self._bundles.clear()
            self._dump_counts.clear()
            self._dumps_total = 0
            self._breaker_states.clear()


FLIGHT = FlightRecorder()


def load_bundle(path: str) -> Dict[str, Any]:
    """Read one on-disk bundle back, validating the self-describing header."""
    with open(path) as fh:
        bundle = json.load(fh)
    if bundle.get("bundle") != BUNDLE_KIND:
        raise ValueError(f"{path!r} is not a {BUNDLE_KIND} bundle")
    return bundle
