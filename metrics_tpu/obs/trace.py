"""Span tracing: thread-local context propagation + ring buffer + Chrome trace export.

Usage::

    from metrics_tpu import obs

    obs.enable()
    with obs.span("metric.update", metric="BinaryF1Score"):
        metric.update(preds, target)
    obs.export_chrome_trace("/tmp/trace.json")   # load in Perfetto / chrome://tracing

Spans nest: each thread carries its own context stack (``threading.local``), so
a span opened inside another records its parent — and concurrent threads (the
engine's client threads + dispatcher) interleave without sharing state. Closed
spans land in a fixed-size ring buffer: sustained tracing overwrites
oldest-first instead of growing without bound, so ``enable()`` is safe to leave
on in a serving process.

The exported JSON is the Chrome trace-event format (one ``"X"`` — complete —
event per span, microsecond timestamps, ``pid``/``tid`` attribution plus
thread-name metadata events), directly loadable in Perfetto or
``chrome://tracing``.

When the master switch is off, :meth:`Tracer.span` returns a shared no-op
context manager after a single attribute test — no allocation, no lock.

Stdlib only.
"""

from __future__ import annotations

import json
import os
import threading
import time
from typing import Any, Dict, List, Optional, Tuple

from metrics_tpu.obs.registry import OBS

# one closed span: (name, start_ns, dur_ns, tid, thread_name, parent_name, attrs)
_SpanRecord = Tuple[str, int, int, int, str, Optional[str], Dict[str, Any]]


class _NullSpan:
    """Shared do-nothing context manager for the disabled path."""

    __slots__ = ()

    def __enter__(self) -> "_NullSpan":
        return self

    def __exit__(self, *exc_info: Any) -> bool:
        return False

    def set_attr(self, **attrs: Any) -> None:
        pass


_NULL_SPAN = _NullSpan()


class _Span:
    """A live span; records itself into the tracer's ring on exit."""

    __slots__ = ("_tracer", "name", "attrs", "_start_ns", "_parent")

    def __init__(self, tracer: "Tracer", name: str, attrs: Dict[str, Any]) -> None:
        self._tracer = tracer
        self.name = name
        self.attrs = attrs
        self._start_ns = 0
        self._parent: Optional[str] = None

    def set_attr(self, **attrs: Any) -> None:
        """Attach attributes discovered mid-span (payload sizes, cache hits...)."""
        self.attrs.update(attrs)

    def __enter__(self) -> "_Span":
        stack = self._tracer._stack()
        self._parent = stack[-1] if stack else None
        stack.append(self.name)
        self._start_ns = time.perf_counter_ns()
        return self

    def __exit__(self, exc_type: Any, exc: Any, tb: Any) -> bool:
        end_ns = time.perf_counter_ns()
        stack = self._tracer._stack()
        if stack and stack[-1] == self.name:
            stack.pop()
        if exc_type is not None:
            self.attrs.setdefault("error", exc_type.__name__)
        thread = threading.current_thread()
        self._tracer._record(
            (self.name, self._start_ns, end_ns - self._start_ns, thread.ident or 0,
             thread.name, self._parent, self.attrs)
        )
        return False


class Tracer:
    """Ring-buffered span storage with per-thread context propagation."""

    def __init__(self, capacity: int = 4096) -> None:
        if capacity < 1:
            raise ValueError(f"tracer capacity must be >= 1, got {capacity}")
        self._lock = threading.Lock()
        self._capacity = int(capacity)
        self._ring: List[Optional[_SpanRecord]] = [None] * self._capacity
        self._total = 0  # spans ever recorded; ring index = _total % capacity
        self._local = threading.local()
        # perf_counter epoch for this tracer: exported ts are relative µs
        self._epoch_ns = time.perf_counter_ns()

    # ------------------------------------------------------------------ recording

    def span(self, name: str, **attrs: Any) -> Any:
        """Context manager timing one named region. No-op when obs is disabled."""
        if not OBS.enabled:
            return _NULL_SPAN
        return _Span(self, name, attrs)

    def _stack(self) -> List[str]:
        stack = getattr(self._local, "stack", None)
        if stack is None:
            stack = self._local.stack = []
        return stack

    def _record(self, record: _SpanRecord) -> None:
        with self._lock:
            self._ring[self._total % self._capacity] = record
            self._total += 1

    def current_span_name(self) -> Optional[str]:
        """The innermost open span on THIS thread (context propagation probe)."""
        stack = self._stack()
        return stack[-1] if stack else None

    def record_span(
        self,
        name: str,
        start_ns: int,
        dur_ns: int,
        parent: Optional[str] = None,
        **attrs: Any,
    ) -> None:
        """Record one ALREADY-MEASURED span directly into the ring.

        For retrospective spans whose boundaries were stamped elsewhere — the
        engine's per-request lifetime span is assembled at future-resolution
        time from timestamps collected across submit/drain/kernel/journal.
        ``start_ns`` is on the ``time.perf_counter_ns`` clock (same epoch the
        live spans use, so exported traces interleave correctly).
        """
        if not OBS.enabled:
            return
        thread = threading.current_thread()
        self._record(
            (name, int(start_ns), max(0, int(dur_ns)), thread.ident or 0,
             thread.name, parent, attrs)
        )

    # ------------------------------------------------------------------ reading

    @property
    def capacity(self) -> int:
        return self._capacity

    @property
    def total_recorded(self) -> int:
        """Spans ever closed (recorded), including ones the ring overwrote."""
        with self._lock:
            return self._total

    def spans(self) -> List[Dict[str, Any]]:
        """Retained spans, oldest first, as plain dicts (ns timestamps)."""
        with self._lock:
            n = min(self._total, self._capacity)
            start = self._total % self._capacity if self._total > self._capacity else 0
            ordered = [self._ring[(start + i) % self._capacity] for i in range(n)]
        out = []
        for rec in ordered:
            if rec is None:
                continue
            name, start_ns, dur_ns, tid, tname, parent, attrs = rec
            out.append(
                {"name": name, "start_ns": start_ns, "dur_ns": dur_ns, "tid": tid,
                 "thread_name": tname, "parent": parent, "attrs": dict(attrs)}
            )
        return out

    def export_chrome_trace(self, path: Optional[str] = None) -> Dict[str, Any]:
        """Retained spans as a Chrome trace-event document.

        One complete (``"ph": "X"``) event per span with microsecond ``ts``
        (monotone, relative to the tracer's start) and ``dur``, plus one
        ``thread_name`` metadata event per thread seen. Written to ``path``
        as JSON when given; the document is returned either way.
        """
        spans = self.spans()
        pid = os.getpid()
        events: List[Dict[str, Any]] = []
        threads_seen: Dict[int, str] = {}
        for s in spans:
            threads_seen.setdefault(s["tid"], s["thread_name"])
            args = dict(s["attrs"])
            if s["parent"]:
                args["parent"] = s["parent"]
            events.append(
                {
                    "name": s["name"],
                    "cat": "metrics_tpu",
                    "ph": "X",
                    "ts": (s["start_ns"] - self._epoch_ns) / 1e3,
                    "dur": s["dur_ns"] / 1e3,
                    "pid": pid,
                    "tid": s["tid"],
                    "args": args,
                }
            )
        events.sort(key=lambda e: e["ts"])
        meta = [
            {"name": "thread_name", "ph": "M", "pid": pid, "tid": tid,
             "args": {"name": tname}}
            for tid, tname in sorted(threads_seen.items())
        ]
        doc = {"traceEvents": meta + events, "displayTimeUnit": "ms"}
        if path is not None:
            try:
                with open(path, "w") as fh:
                    json.dump(doc, fh)
            except Exception as exc:  # noqa: BLE001 — exporting must never break the host
                doc["export_error"] = repr(exc)
        return doc

    # ------------------------------------------------------------------ lifecycle

    def clear(self) -> None:
        with self._lock:
            self._ring = [None] * self._capacity
            self._total = 0
            self._epoch_ns = time.perf_counter_ns()


TRACER = Tracer()
