"""metrics_tpu.obs — library-wide observability: metrics registry, span tracing,
retrace/sync attribution.

One process-global, zero-third-party-dependency subsystem spanning the whole
stack::

    from metrics_tpu import obs

    obs.enable()                                  # master switch (default: off)
    with obs.span("eval.epoch", split="val"):     # your spans nest with the library's
        metric.update(preds, target)              # -> metric.update span + wall-time histogram
        metric.compute()                          #    retraces + sync payloads attributed too

    obs.snapshot()                                # everything as one plain dict
    print(obs.render_prometheus())                # Prometheus v0.0.4 text exposition
    obs.export_chrome_trace("trace.json")         # load in Perfetto / chrome://tracing
    obs.disable()

Layout: :mod:`~metrics_tpu.obs.registry` (thread-safe labeled
counters/gauges/histograms + Prometheus exposition + the :data:`OBS` master
gate), :mod:`~metrics_tpu.obs.trace` (thread-local span propagation,
ring-buffered storage, Chrome trace export), :mod:`~metrics_tpu.obs.instrument`
(the hooks ``metric.py`` / ``collections.py`` / ``engine/`` / ``parallel/``
call into), :mod:`~metrics_tpu.obs.jsonl` (the one shared JSONL writer).

Cost contract (gated by ``benchmarks/obs_overhead.py``): with the switch off,
every hook exits after a single attribute test — no lock, no allocation —
adding <5% to a hot eager ``update()`` loop; enabled, <15%.
"""

from __future__ import annotations

from typing import Any, Dict, Optional

from metrics_tpu.obs.jsonl import append_jsonl
from metrics_tpu.obs.registry import (
    OBS,
    REGISTRY,
    Counter,
    Gauge,
    Histogram,
    ObsGate,
    Registry,
)
from metrics_tpu.obs.trace import TRACER, Tracer
from metrics_tpu.obs.context import TraceContext, activate, current, mint
from metrics_tpu.obs.fleet import AGGREGATOR, FleetAggregator, node_snapshot
from metrics_tpu.obs.flight import FLIGHT, FlightRecorder, load_bundle
from metrics_tpu.obs import instrument  # noqa: F401  (registers the hook instruments)


def enable() -> None:
    """Turn on library-wide instrumentation (spans, op timing, retrace/sync attribution)."""
    OBS.enabled = True


def disable() -> None:
    """Turn instrumentation off. Recorded data is kept; recording stops."""
    OBS.enabled = False


def enabled() -> bool:
    return OBS.enabled


def span(name: str, **attrs: Any) -> Any:
    """Open a trace span on the process tracer (no-op context manager when disabled)."""
    return TRACER.span(name, **attrs)


def counter(name: str, help: str = "") -> Counter:
    return REGISTRY.counter(name, help)


def gauge(name: str, help: str = "") -> Gauge:
    return REGISTRY.gauge(name, help)


def histogram(name: str, help: str = "", buckets: Any = None) -> Histogram:
    return REGISTRY.histogram(name, help, buckets=buckets)


def snapshot() -> Dict[str, Any]:
    """The whole registry as one plain dict."""
    return REGISTRY.snapshot()


def render_prometheus() -> str:
    """Prometheus text exposition (serve with ``Content-Type: text/plain; version=0.0.4``)."""
    return REGISTRY.render_prometheus()


def export_chrome_trace(path: Optional[str] = None) -> Dict[str, Any]:
    """Retained spans as Chrome trace-event JSON (optionally written to ``path``)."""
    return TRACER.export_chrome_trace(path)


def emit(path: str, **extra: Any) -> Dict[str, Any]:
    """Append one registry snapshot as a JSONL record through the shared writer."""
    return REGISTRY.emit(path, **extra)


def reset() -> None:
    """Disable and clear all recorded values/spans/flight evidence/fleet state,
    keeping registered instruments (and references held to them) valid.
    Test-isolation hook."""
    disable()
    REGISTRY.clear_values()
    TRACER.clear()
    FLIGHT.clear()
    AGGREGATOR.clear()


__all__ = [
    "AGGREGATOR",
    "FLIGHT",
    "FleetAggregator",
    "FlightRecorder",
    "OBS",
    "REGISTRY",
    "TRACER",
    "Counter",
    "Gauge",
    "Histogram",
    "ObsGate",
    "Registry",
    "TraceContext",
    "Tracer",
    "activate",
    "append_jsonl",
    "counter",
    "current",
    "disable",
    "emit",
    "enable",
    "enabled",
    "export_chrome_trace",
    "gauge",
    "histogram",
    "instrument",
    "load_bundle",
    "mint",
    "node_snapshot",
    "render_prometheus",
    "reset",
    "snapshot",
    "span",
]
