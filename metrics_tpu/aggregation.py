"""Streaming scalar aggregators.

Reference parity: src/torchmetrics/aggregation.py — BaseAggregator :24, MaxMetric :95,
MinMetric :156, SumMetric :217, CatMetric :276, MeanMetric :336. ``nan_strategy``
(error/warn/ignore/float-impute) preserved; the masking is implemented with
``jnp.where`` (trace-safe) instead of boolean filtering, per SURVEY §7.1's static-shape
constraint — except for 'error'/'warn', which need a host-side value check and therefore
no-op inside jit (same escape as ``validate_args=False``).
"""

from __future__ import annotations

from typing import Any, Callable, List, Optional, Union

import jax
import jax.numpy as jnp
from jax import Array

from metrics_tpu.metric import Metric, zero_state
from metrics_tpu.utils.checks import _value_check_possible
from metrics_tpu.utils.data import dim_zero_cat
from metrics_tpu.utils.prints import rank_zero_warn


class BaseAggregator(Metric):
    """Base class for aggregators (reference aggregation.py:24-92)."""

    is_differentiable = None
    higher_is_better = None
    full_state_update = False
    _neutral: float = 0.0  # value NaNs map to under nan_strategy='ignore'

    def __init__(
        self,
        fn: Union[Callable, str],
        default_value: Union[Array, List],
        nan_strategy: Union[str, float] = "error",
        state_name: str = "value",
        **kwargs: Any,
    ) -> None:
        super().__init__(**kwargs)
        allowed_nan_strategy = ("error", "warn", "ignore")
        if nan_strategy not in allowed_nan_strategy and not isinstance(nan_strategy, float):
            raise ValueError(
                f"Arg `nan_strategy` should either be a float or one of {allowed_nan_strategy} but got {nan_strategy}."
            )
        self.nan_strategy = nan_strategy
        self.add_state(state_name, default=default_value, dist_reduce_fx=fn)
        self.state_name = state_name

    def _cast_and_nan_check_input(self, x: Union[float, Array], weight: Optional[Union[float, Array]] = None) -> tuple:
        """Cast to float and handle NaNs per ``nan_strategy``.

        Returns (x, weight) with NaNs replaced (ignore → neutral handled by caller via
        the returned nan mask inside x==nan_to_num semantics).
        """
        x = jnp.asarray(x, dtype=jnp.float32)
        if weight is not None:
            weight = jnp.asarray(weight, dtype=jnp.float32)
            weight = jnp.broadcast_to(weight, x.shape)

        nans = jnp.isnan(x)
        anynan = jnp.any(nans)
        if self.nan_strategy in ("error", "warn"):
            if _value_check_possible(x) and bool(anynan):
                if self.nan_strategy == "error":
                    raise RuntimeError("Encountered `nan` values in tensor")
                rank_zero_warn("Encountered `nan` values in tensor. Will be removed.", UserWarning)
                x = x[~nans]
                if weight is not None:
                    weight = weight[~nans]
        elif self.nan_strategy == "ignore":
            # trace-safe: replace NaNs with the op's neutral element and zero their
            # weight instead of boolean filtering (static shapes — SURVEY §7.1)
            if weight is None:
                weight = jnp.ones_like(x)
            weight = jnp.where(nans, 0.0, weight)
            x = jnp.where(nans, jnp.asarray(self._neutral, dtype=x.dtype), x)
        else:  # float imputation
            x = jnp.where(nans, jnp.asarray(self.nan_strategy, dtype=x.dtype), x)

        if weight is None:
            weight = jnp.ones_like(x)
        return x.reshape(-1), weight.reshape(-1)

    def update(self, value: Union[float, Array]) -> None:
        pass

    def compute(self) -> Array:
        return getattr(self, self.state_name)


class MaxMetric(BaseAggregator):
    """Running max (reference aggregation.py:95).

    Example:
        >>> import jax.numpy as jnp
        >>> from metrics_tpu import MaxMetric
        >>> metric = MaxMetric()
        >>> metric.update(jnp.array([1.0, 3.0, 2.0]))
        >>> metric.compute()
        Array(3., dtype=float32)
    """

    full_state_update = True
    _neutral = -float("inf")

    def __init__(self, nan_strategy: Union[str, float] = "warn", **kwargs: Any) -> None:
        super().__init__("max", jnp.asarray(-jnp.inf, dtype=jnp.float32), nan_strategy, state_name="max_value", **kwargs)

    def update(self, value: Union[float, Array]) -> None:
        value, _ = self._cast_and_nan_check_input(value)
        if value.size:  # a fully-NaN-filtered batch contributes nothing
            self.max_value = jnp.maximum(self.max_value, jnp.max(value))


class MinMetric(BaseAggregator):
    """Running min (reference aggregation.py:156).

    Example:
        >>> import jax.numpy as jnp
        >>> from metrics_tpu import MinMetric
        >>> metric = MinMetric()
        >>> metric.update(jnp.array([1.0, 3.0, 2.0]))
        >>> metric.compute()
        Array(1., dtype=float32)
    """

    full_state_update = True
    _neutral = float("inf")

    def __init__(self, nan_strategy: Union[str, float] = "warn", **kwargs: Any) -> None:
        super().__init__("min", jnp.asarray(jnp.inf, dtype=jnp.float32), nan_strategy, state_name="min_value", **kwargs)

    def update(self, value: Union[float, Array]) -> None:
        value, _ = self._cast_and_nan_check_input(value)
        if value.size:
            self.min_value = jnp.minimum(self.min_value, jnp.min(value))


class SumMetric(BaseAggregator):
    """Running sum (reference aggregation.py:217).

    Example:
        >>> import jax.numpy as jnp
        >>> from metrics_tpu import SumMetric
        >>> metric = SumMetric()
        >>> metric.update(jnp.array([1.0, 3.0, 2.0]))
        >>> metric.update(4.0)
        >>> metric.compute()
        Array(10., dtype=float32)
    """

    def __init__(self, nan_strategy: Union[str, float] = "warn", **kwargs: Any) -> None:
        super().__init__("sum", zero_state((), jnp.float32), nan_strategy, state_name="sum_value", **kwargs)

    def update(self, value: Union[float, Array]) -> None:
        value, _ = self._cast_and_nan_check_input(value)
        if value.size:
            self.sum_value = self.sum_value + jnp.sum(value)


class CatMetric(BaseAggregator):
    """Concatenate all seen values (reference aggregation.py:276).

    Example:
        >>> import jax.numpy as jnp
        >>> from metrics_tpu import CatMetric
        >>> metric = CatMetric()
        >>> metric.update(jnp.array([1.0, 2.0]))
        >>> metric.update(jnp.array([3.0]))
        >>> metric.compute()
        Array([1., 2., 3.], dtype=float32)
    """

    def __init__(self, nan_strategy: Union[str, float] = "warn", **kwargs: Any) -> None:
        super().__init__("cat", [], nan_strategy, **kwargs)

    def update(self, value: Union[float, Array]) -> None:
        value, weight = self._cast_and_nan_check_input(value)
        if self.nan_strategy == "ignore" and _value_check_possible(value):
            value = value[weight != 0]
        if value.size:
            self.value.append(value)

    def compute(self) -> Array:
        if isinstance(self.value, list) and self.value:
            return dim_zero_cat(self.value)
        return self.value


class MeanMetric(BaseAggregator):
    """Weighted running mean: ``value``+``weight`` sum states (reference aggregation.py:336).

    Example:
        >>> import jax.numpy as jnp
        >>> from metrics_tpu import MeanMetric
        >>> metric = MeanMetric()
        >>> metric.update(jnp.array([1.0, 2.0, 3.0]))
        >>> metric.update(5.0, weight=3.0)
        >>> metric.compute()
        Array(3.5, dtype=float32)
    """

    def __init__(self, nan_strategy: Union[str, float] = "warn", **kwargs: Any) -> None:
        super().__init__("sum", zero_state((), jnp.float32), nan_strategy, state_name="mean_value", **kwargs)
        self.add_state("weight", default=zero_state((), dtype=jnp.float32), dist_reduce_fx="sum")

    def update(self, value: Union[float, Array], weight: Union[float, Array] = 1.0) -> None:
        value, weight = self._cast_and_nan_check_input(value, weight)
        if value.size == 0:
            return
        self.mean_value = self.mean_value + jnp.sum(value * weight)
        self.weight = self.weight + jnp.sum(weight)

    def compute(self) -> Array:
        return self.mean_value / self.weight


__all__ = ["BaseAggregator", "MaxMetric", "MinMetric", "SumMetric", "CatMetric", "MeanMetric"]
