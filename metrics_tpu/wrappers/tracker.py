"""MetricTracker — track a metric (or collection) over epochs/steps.

Reference parity: src/torchmetrics/wrappers/tracker.py (:26 class, increment :117,
compute_all :137, best_metric :165).
"""

from __future__ import annotations

from copy import deepcopy
from typing import Any, Dict, List, Optional, Tuple, Union

import jax.numpy as jnp
import numpy as np

from metrics_tpu.collections import MetricCollection
from metrics_tpu.metric import Metric, _raise_on_unconsumed
from metrics_tpu.utils.prints import rank_zero_warn


class MetricTracker:
    """List of deep-copied snapshots, one per ``increment()`` (reference tracker.py:26).

    Example:
        >>> import jax.numpy as jnp
        >>> from metrics_tpu import MetricTracker, MeanMetric
        >>> tracker = MetricTracker(MeanMetric())
        >>> tracker.increment()
        >>> tracker.update(jnp.array(1.0))
        >>> tracker.increment()
        >>> tracker.update(jnp.array(3.0))
        >>> float(tracker.best_metric())
        3.0
    """

    def __init__(self, metric: Union[Metric, MetricCollection], maximize: Union[bool, List[bool]] = True) -> None:
        if not isinstance(metric, (Metric, MetricCollection)):
            raise TypeError(
                "Metric arg need to be an instance of a metrics_tpu"
                f" `Metric` or `MetricCollection` but got {metric}"
            )
        self._base_metric = metric
        if not isinstance(maximize, (bool, list)):
            raise ValueError("Argument `maximize` should either be a single bool or list of bool")
        if isinstance(maximize, list) and isinstance(metric, MetricCollection) and len(maximize) != len(metric):
            raise ValueError("The len of argument `maximize` should match the length of the metric collection")
        if isinstance(metric, Metric) and not isinstance(maximize, bool):
            raise ValueError("Argument `maximize` should be a single bool when `metric` is a single Metric")
        self.maximize = maximize
        self._increment_called = False
        self._metrics: List[Union[Metric, MetricCollection]] = []

    @property
    def n_steps(self) -> int:
        """Number of tracked metrics (reference: len - 1 for the base)."""
        return len(self._metrics)

    def increment(self) -> None:
        """Create a new metric snapshot for the next epoch (reference :117-120)."""
        self._increment_called = True
        self._metrics.append(deepcopy(self._base_metric))
        self._metrics[-1].reset()

    def __len__(self) -> int:
        return len(self._metrics)

    def __getitem__(self, val: int) -> Union[Metric, MetricCollection]:
        return self._metrics[val]

    # ------------------------------------------------------------------ persistence
    # The tracked history is DYNAMIC structure (one snapshot per increment), so
    # serialization records the step count and load rebuilds the snapshots
    # before restoring their states — matching by the live instance's children
    # alone would silently drop the whole history on a fresh instance (found by
    # the checkpoint_resume fuzz surface's review).

    def persistent(self, mode: bool = False) -> None:
        self._base_metric.persistent(mode)
        for m in self._metrics:
            m.persistent(mode)

    def state_dict(self, destination: Optional[Dict[str, Any]] = None, prefix: str = "") -> Dict[str, Any]:
        destination = {} if destination is None else destination
        destination[prefix + "_n_steps"] = np.asarray(len(self._metrics))
        for i, m in enumerate(self._metrics):
            m.state_dict(destination, prefix=f"{prefix}_metrics.{i}.")
        return destination

    def load_state_dict(
        self, state_dict: Dict[str, Any], prefix: str = "", strict: bool = True, _consumed: Optional[set] = None
    ) -> None:
        owns_check = _consumed is None
        consumed: set = set() if owns_check else _consumed
        key = prefix + "_n_steps"
        if key not in state_dict:
            if strict:
                raise KeyError(f"Missing key {key} in state_dict")
            return
        consumed.add(key)
        n = int(state_dict[key])
        while len(self._metrics) < n:
            self.increment()
        # truncate as well as grow: loading a checkpoint into a tracker that
        # already advanced past it must not keep post-checkpoint history
        del self._metrics[n:]
        self._increment_called = n > 0
        for i in range(n):
            self._metrics[i].load_state_dict(state_dict, prefix=f"{prefix}_metrics.{i}.", strict=strict, _consumed=consumed)
        if owns_check and strict:
            _raise_on_unconsumed(state_dict, prefix, consumed)

    def _check_for_increment(self, method: str) -> None:
        if not self._increment_called:
            raise ValueError(f"`{method}` cannot be called before `.increment()` has been called")

    def update(self, *args: Any, **kwargs: Any) -> None:
        self._check_for_increment("update")
        self._metrics[-1].update(*args, **kwargs)

    def forward(self, *args: Any, **kwargs: Any) -> Any:
        self._check_for_increment("forward")
        return self._metrics[-1](*args, **kwargs)

    def __call__(self, *args: Any, **kwargs: Any) -> Any:
        return self.forward(*args, **kwargs)

    def compute(self) -> Any:
        self._check_for_increment("compute")
        return self._metrics[-1].compute()

    def compute_all(self) -> Any:
        """Compute all tracked steps (reference :137-154)."""
        self._check_for_increment("compute_all")
        res = [metric.compute() for metric in self._metrics]
        if isinstance(self._base_metric, MetricCollection):
            keys = res[0].keys()
            return {k: jnp.stack([jnp.asarray(r[k]) for r in res], axis=0) for k in keys}
        return jnp.stack([jnp.asarray(r) for r in res], axis=0)

    def reset(self) -> None:
        """Reset the current metric."""
        self._metrics[-1].reset()

    def reset_all(self) -> None:
        for metric in self._metrics:
            metric.reset()

    def best_metric(
        self, return_step: bool = False
    ) -> Union[Any, Tuple[Any, Any]]:
        """Best value (and optionally its step) over all tracked steps (reference :165-235)."""
        res = self.compute_all()
        if isinstance(self._base_metric, Metric):
            fn = np.argmax if self.maximize else np.argmin
            try:
                value = np.asarray(res)
                idx = int(fn(value))
                if return_step:
                    return float(value[idx]), idx
                return float(value[idx])
            except (ValueError, TypeError) as error:
                rank_zero_warn(
                    f"Encountered the following error when trying to get the best metric: {error}"
                    "this is probably due to the 'best' not being defined for this metric."
                    "Returning `None` instead.", UserWarning,
                )
                if return_step:
                    return None, None
                return None
        else:
            maximize = self.maximize if isinstance(self.maximize, list) else len(res) * [self.maximize]
            value, idx = {}, {}
            for i, (k, v) in enumerate(res.items()):
                try:
                    fn = np.argmax if maximize[i] else np.argmin
                    out = np.asarray(v)
                    idx[k] = int(fn(out))
                    value[k] = float(out[idx[k]])
                except (ValueError, TypeError) as error:
                    rank_zero_warn(
                        f"Encountered the following error when trying to get the best metric for metric {k}:"
                        f"{error} this is probably due to the 'best' not being defined for this metric."
                        "Returning `None` instead.", UserWarning,
                    )
                    value[k], idx[k] = None, None
            if return_step:
                return value, idx
            return value
