"""MultioutputWrapper — apply a metric per output column.

Reference parity: src/torchmetrics/wrappers/multioutput.py (:~46): N clones, one per
column of ``output_dim``; optional NaN-row removal (host-side, value-dependent).
"""

from __future__ import annotations

from copy import deepcopy
from typing import Any, List, Optional

import jax
import jax.numpy as jnp
from jax import Array

from metrics_tpu.metric import Metric
from metrics_tpu.utils.checks import _value_check_possible


def _get_nan_indices(*tensors: Array) -> Array:
    """Rows where any tensor has a NaN (reference multioutput.py)."""
    if len(tensors) == 0:
        raise ValueError("Must pass at least one tensor as argument")
    sentinel = tensors[0]
    nan_idxs = jnp.zeros(len(sentinel), dtype=jnp.bool_)
    for tensor in tensors:
        permuted = tensor.reshape(len(sentinel), -1)
        nan_idxs = nan_idxs | jnp.any(jnp.isnan(permuted), axis=1)
    return nan_idxs


class MultioutputWrapper(Metric):
    """Multioutput Wrapper.

    Example:
        >>> import jax.numpy as jnp
        >>> from metrics_tpu import MultioutputWrapper, MeanSquaredError
        >>> metric = MultioutputWrapper(MeanSquaredError(), num_outputs=2)
        >>> metric.update(jnp.array([[1.0, 10.0], [2.0, 20.0]]), jnp.array([[1.0, 11.0], [2.0, 22.0]]))
        >>> metric.compute()
        Array([0. , 2.5], dtype=float32)
    """

    is_differentiable = False

    def __init__(
        self,
        base_metric: Metric,
        num_outputs: int,
        output_dim: int = -1,
        remove_nans: bool = True,
        squeeze_outputs: bool = True,
        **kwargs: Any,
    ) -> None:
        super().__init__(**kwargs)
        self.metrics = [deepcopy(base_metric) for _ in range(num_outputs)]
        self.output_dim = output_dim
        self.remove_nans = remove_nans
        self.squeeze_outputs = squeeze_outputs

    def _get_args_kwargs_by_output(self, *args: Array, **kwargs: Array):
        """Slice inputs per output column (reference multioutput.py)."""
        args_kwargs_by_output = []
        for i in range(len(self.metrics)):
            selected_args = [jnp.take(arg, jnp.asarray([i]), axis=self.output_dim) for arg in args]
            selected_kwargs = {k: jnp.take(v, jnp.asarray([i]), axis=self.output_dim) for k, v in kwargs.items()}
            if self.remove_nans:
                tensors = selected_args + list(selected_kwargs.values())
                if tensors and _value_check_possible(*tensors):
                    nan_idxs = _get_nan_indices(*tensors)
                    selected_args = [arg[~nan_idxs] for arg in selected_args]
                    selected_kwargs = {k: v[~nan_idxs] for k, v in selected_kwargs.items()}
            if self.squeeze_outputs:
                selected_args = [arg.squeeze(self.output_dim) for arg in selected_args]
                selected_kwargs = {k: v.squeeze(self.output_dim) for k, v in selected_kwargs.items()}
            args_kwargs_by_output.append((selected_args, selected_kwargs))
        return args_kwargs_by_output

    def update(self, *args: Any, **kwargs: Any) -> None:
        reshaped_args_kwargs = self._get_args_kwargs_by_output(*args, **kwargs)
        for metric, (selected_args, selected_kwargs) in zip(self.metrics, reshaped_args_kwargs):
            metric.update(*selected_args, **selected_kwargs)

    def compute(self) -> Array:
        return jnp.stack([jnp.asarray(m.compute()) for m in self.metrics], axis=0)

    def forward(self, *args: Any, **kwargs: Any) -> Any:
        reshaped_args_kwargs = self._get_args_kwargs_by_output(*args, **kwargs)
        results = [
            metric(*selected_args, **selected_kwargs)
            for metric, (selected_args, selected_kwargs) in zip(self.metrics, reshaped_args_kwargs)
        ]
        if any(r is None for r in results):
            return None
        return jnp.stack([jnp.asarray(r) for r in results], axis=0)

    def reset(self) -> None:
        for metric in self.metrics:
            metric.reset()
        super().reset()
