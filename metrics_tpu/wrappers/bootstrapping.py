"""BootStrapper — bootstrapped confidence estimates for any metric.

Reference parity: src/torchmetrics/wrappers/bootstrapping.py (:25 class, :48 init,
resampling per update :117-134). Each update resamples the batch (poisson weights or
multinomial indices) once per bootstrap copy.

TPU-native redesign (SURVEY §7.2-4): with ``sampling_strategy="multinomial"`` the
resample is fixed-shape — an ``(num_bootstraps, batch)`` index matrix — so instead of
the reference's N deep-copied metrics each dispatching their own update, ONE state
pytree stacked along a leading bootstrap axis is updated by a single ``jax.vmap`` of
the pure ``update_state``: one XLA dispatch for all copies, and the whole thing can sit
inside a jitted train step. Poisson resampling (ragged multiplicities), host-compute
metrics and ragged "cat" states keep the reference's per-copy loop; if the vmapped
update turns out untraceable for a given base metric (e.g. ``validate_args=True``
doing data-dependent Python checks) the instance permanently falls back to the loop.
"""

from __future__ import annotations

from copy import deepcopy
from typing import Any, Dict, Optional, Union

import jax
import jax.numpy as jnp
import numpy as np
from jax import Array

from metrics_tpu.metric import Metric, _raise_on_unconsumed
from metrics_tpu.utils.data import apply_to_collection


# CDF of Poisson(lam=1) at k=0..35: P(X<=k) = e^-1 * sum_{i<=k} 1/i!
_POISSON1_CDF = np.cumsum(np.exp(-1.0) / np.cumprod(np.concatenate([[1.0], np.arange(1.0, 36.0)])))


def _chunk_spans(n: int, chunkable: bool):
    """Split ``[0, n)`` into a 4096-aligned head span + power-of-two tail spans.

    Poisson resampling draws a fresh ragged length every update; feeding those
    shapes straight to the jitted update kernels means a compile-cache miss
    per copy per update (measured ~250 ms each — a 20-copy update took 5 s).
    Chunking bounds the set of shapes ever seen: the head is one span of
    ``(n // 4096) * 4096`` elements (a Poisson(size) total concentrates on a
    couple of distinct multiples), the < 4096 remainder decomposes into at
    most 12 power-of-two spans shared by every update. update() accumulates
    across calls, so chunked updates equal the single-batch update for
    streaming metrics.
    """
    if not chunkable or n <= 0:
        return [(0, n)]
    spans = []
    head = (n >> 12) << 12
    if head:
        spans.append((0, head))
    off = head
    while off < n:
        chunk = 1 << ((n - off).bit_length() - 1)
        spans.append((off, off + chunk))
        off += chunk
    return spans


def _bootstrap_sampler(
    size: int,
    sampling_strategy: str = "poisson",
    rng: Optional[np.random.Generator] = None,
) -> np.ndarray:
    """Resampling indices (reference bootstrapping.py ``_bootstrap_sampler``).

    Returned as a host numpy array: the per-copy loop slices it into
    shape-stable chunks (free in numpy) before the single device gather per
    chunk — see ``_chunk_spans``.
    """
    rng = rng or np.random.default_rng()
    if sampling_strategy == "poisson":
        # Poisson(1) via inverse-CDF on a uniform draw: one vectorized
        # rng.random + a searchsorted over a 36-entry table is ~3x numpy's
        # per-value transformed-rejection sampler, and exact — the table
        # covers k<=35 where the residual tail probability underflows f64
        p = np.searchsorted(_POISSON1_CDF, rng.random(size), side="left")
        return np.arange(size).repeat(p)
    if sampling_strategy == "multinomial":
        return rng.integers(0, size, size)
    raise ValueError("Unknown sampling strategy")


class BootStrapper(Metric):
    """Bootstrap confidence intervals via one vmapped update over resampled copies.

    Example:
        >>> import jax.numpy as jnp
        >>> from metrics_tpu import BootStrapper, MeanSquaredError
        >>> preds = jnp.array([2.5, 0.0, 2.0, 8.0, 4.5, 1.0, 3.0, 6.0])
        >>> target = jnp.array([3.0, -0.5, 2.0, 7.0, 4.0, 1.5, 2.5, 6.5])
        >>> metric = BootStrapper(MeanSquaredError(), num_bootstraps=20, seed=123)
        >>> metric.update(preds, target)
        >>> sorted(metric.compute().keys())
        ['mean', 'std']
        >>> bool(abs(float(metric.compute()["mean"]) - 0.3) < 0.2)  # MSE is 0.3125 exactly
        True
    """

    full_state_update: Optional[bool] = True

    def __init__(
        self,
        base_metric: Metric,
        num_bootstraps: int = 10,
        mean: bool = True,
        std: bool = True,
        quantile: Optional[Union[float, Array]] = None,
        raw: bool = False,
        sampling_strategy: str = "poisson",
        seed: Optional[int] = None,
        **kwargs: Any,
    ) -> None:
        super().__init__(**kwargs)
        if not isinstance(base_metric, Metric):
            raise ValueError(
                f"Expected base metric to be an instance of metrics_tpu.Metric but received {base_metric}"
            )
        self.num_bootstraps = num_bootstraps

        self.mean = mean
        self.std = std
        self.quantile = quantile
        self.raw = raw

        allowed_sampling = ("poisson", "multinomial")
        if sampling_strategy not in allowed_sampling:
            raise ValueError(
                f"Expected argument ``sampling_strategy`` to be one of {allowed_sampling} but received"
                f" {sampling_strategy}"
            )
        self.sampling_strategy = sampling_strategy
        self._rng = np.random.default_rng(seed)

        self.base_metric = base_metric
        has_list_state = any(isinstance(d, list) for d in base_metric._defaults.values())
        self._use_vmap = (
            sampling_strategy == "multinomial"
            and not getattr(base_metric, "_host_compute", False)
            and not has_list_state
        )
        if self._use_vmap:
            self.metrics = []  # no copies needed — state carries the bootstrap axis
            self._stacked_state = self._init_stacked_state()
        else:
            self.metrics = [deepcopy(base_metric) for _ in range(num_bootstraps)]

    def _init_stacked_state(self) -> Dict[str, Any]:
        base = self.base_metric.init_state()
        return jax.tree.map(lambda x: jnp.broadcast_to(x, (self.num_bootstraps,) + x.shape), base)

    def _vmap_update(self, *args: Any, **kwargs: Any) -> bool:
        """Single vmapped update over the stacked state. Returns False if untraceable."""
        size = self._batch_size(args, kwargs)
        # One (N, size) draw fills row-major, so row i equals the i-th sequential draw
        # the reference loop would have made — bit-identical resampling streams.
        indices = jnp.asarray(self._rng.integers(0, size, (self.num_bootstraps, size)))

        def one_copy(state: Dict[str, Any], idx: Array) -> Dict[str, Any]:
            new_args = apply_to_collection(args, jax.Array, jnp.take, idx, axis=0)
            new_kwargs = apply_to_collection(kwargs, jax.Array, jnp.take, idx, axis=0)
            return self.base_metric.update_state(state, *new_args, **new_kwargs)

        try:
            self._stacked_state = jax.vmap(one_copy)(self._stacked_state, indices)
        except (TypeError, IndexError):
            # TypeError covers TracerBoolConversionError/ConcretizationTypeError;
            # IndexError covers NonConcreteBooleanIndexError (data-dependent boolean
            # masking). A genuine bug in the base metric's update is NOT masked: the
            # fallback loop re-runs the same update eagerly and re-raises it there.
            return False
        return True

    def _batch_size(self, args: Any, kwargs: Any) -> int:
        # only jax-array leaves define the resample axis (they are the only
        # leaves the gather touches); anything else cannot be bootstrapped —
        # same contract as the reference, which fails on tensor-free inputs
        # (ref bootstrapping.py:122-129)
        for leaf in jax.tree.leaves((args, kwargs)):
            if isinstance(leaf, jax.Array) and leaf.ndim > 0:
                return int(leaf.shape[0])
        raise ValueError("None of the input contained tensors, so could not determine the sampling size")

    def update(self, *args: Any, **kwargs: Any) -> None:
        """Resample the batch once per bootstrap copy (reference :117-134)."""
        if self._use_vmap:
            if self._vmap_update(*args, **kwargs):
                return
            # permanent fallback: materialise the per-copy metrics from the stacked
            # state accumulated so far, then continue with the reference loop
            self._use_vmap = False
            self.metrics = [deepcopy(self.base_metric) for _ in range(self.num_bootstraps)]
            for i, m in enumerate(self.metrics):
                m._swap_in(jax.tree.map(lambda x: x[i], self._stacked_state))
            del self._stacked_state

        size = self._batch_size(args, kwargs)
        chunkable = self._chunkable(args, kwargs)
        for idx in range(self.num_bootstraps):
            sample_idx = _bootstrap_sampler(size, self.sampling_strategy, self._rng)
            if sample_idx.size == 0:
                continue
            for lo, hi in _chunk_spans(int(sample_idx.size), chunkable):
                # numpy slice (free) then ONE gather per chunk: jnp.take is
                # compile-cached by SHAPE, and power-of-two chunk shapes bound
                # the cache; eager `a[lo:hi]` would recompile per (lo, hi) pair
                chunk = jnp.asarray(sample_idx[lo:hi])
                chunk_args = apply_to_collection(args, jax.Array, jnp.take, chunk, axis=0)
                chunk_kwargs = apply_to_collection(kwargs, jax.Array, jnp.take, chunk, axis=0)
                self.metrics[idx].update(*chunk_args, **chunk_kwargs)

    @staticmethod
    def _chunkable(args: Any, kwargs: Any) -> bool:
        """Chunking applies when every leaf is either a jax array (gathered
        and sliced along axis 0) or a passthrough scalar/flag (e.g. FID's
        ``real=True``, identical in every chunk). Host batch content such as
        lists of strings (flattened to str leaves) disables chunking — the
        full resample must reach the base metric in one call."""
        leaves = jax.tree.leaves((args, kwargs))
        return any(isinstance(l, jax.Array) for l in leaves) and all(
            isinstance(l, (jax.Array, bool, int, float, complex, type(None))) for l in leaves
        )


    def forward(self, *args: Any, **kwargs: Any) -> Dict[str, Array]:
        """Accumulate globally AND return the batch-only bootstrap statistics.

        Overrides ``Metric.forward``: the generic full-state path caches only
        registered states (``_defaults``), which would silently drop the wrapper-held
        ``_stacked_state`` / child-metric states across its reset — so the
        cache/reset/restore dance is done here over the wrapper's real state.
        """
        self.update(*args, **kwargs)

        if self._use_vmap:
            cache = self._stacked_state
            self._stacked_state = self._init_stacked_state()
        else:
            cache = [m._swap_in(m.init_state()) for m in self.metrics]  # reset, keep snapshot

        try:
            self.update(*args, **kwargs)
            self._computed = None
            batch_value = self.compute()
        finally:
            if self._use_vmap:
                self._stacked_state = cache
            else:
                for m, snapshot in zip(self.metrics, cache):
                    m._swap_in(snapshot)
                    m._computed = None  # drop the batch-value cache along with the state
            self._computed = None
        return batch_value

    def compute(self) -> Dict[str, Array]:
        """mean/std/quantile/raw over bootstrap computes (reference :136-…)."""
        if self._use_vmap:
            computed_vals = jax.vmap(lambda s: jnp.asarray(self.base_metric.compute_from(s)))(
                self._stacked_state
            )
        else:
            computed_vals = jnp.stack([jnp.asarray(m.compute()) for m in self.metrics], axis=0)
        output_dict = {}
        if self.mean:
            output_dict["mean"] = jnp.mean(computed_vals, axis=0)
        if self.std:
            output_dict["std"] = jnp.std(computed_vals, axis=0, ddof=1)
        if self.quantile is not None:
            output_dict["quantile"] = jnp.quantile(computed_vals, self.quantile, axis=0)
        if self.raw:
            output_dict["raw"] = computed_vals
        return output_dict

    def reset(self) -> None:
        if self._use_vmap:
            self._stacked_state = self._init_stacked_state()
        for m in self.metrics:
            m.reset()
        super().reset()

    # ------------------------------------------------------------------ persistence
    # The vmap fast path keeps ALL accumulation in the stacked pytree (a plain
    # dict, not registered states), and both paths draw resampling indices from
    # self._rng — so checkpointing must carry the stacked state and the RNG
    # stream or a resume silently restarts the bootstrap from scratch and
    # diverges from an uninterrupted run (found by the checkpoint_resume fuzz
    # surface's review). The copies path is covered by the base class's
    # child-metric recursion over ``self.metrics``.

    # persistence gating uses Metric._any_persistent (recursive): a one-level
    # check would read False for a wrapper-typed base metric, which registers
    # no states of its own, and silently drop the rng/stacked payload

    @staticmethod
    def _encode_rng_state(rng: np.random.Generator) -> Optional[np.ndarray]:
        """PCG64 state as a (6,) uint64 array — keeps state_dict a pure
        numpy-array tree (orbax-friendly). Non-PCG64 generators (only
        reachable by monkeypatching _rng) are not encodable."""
        st = rng.bit_generator.state
        if st.get("bit_generator") != "PCG64":
            return None
        m64 = (1 << 64) - 1
        s, inc = st["state"]["state"], st["state"]["inc"]
        return np.array([s & m64, (s >> 64) & m64, inc & m64, (inc >> 64) & m64,
                         st["has_uint32"], st["uinteger"]], dtype=np.uint64)

    @staticmethod
    def _decode_rng_state(arr: np.ndarray) -> Dict[str, Any]:
        a = [int(x) for x in np.asarray(arr)]
        return {"bit_generator": "PCG64",
                "state": {"state": a[0] | (a[1] << 64), "inc": a[2] | (a[3] << 64)},
                "has_uint32": a[4], "uinteger": a[5]}

    def state_dict(self, destination: Optional[Dict] = None, prefix: str = "") -> Dict[str, Any]:
        destination = super().state_dict(destination, prefix)
        if self._any_persistent():
            # mode marker: the vmap->copies runtime fallback is permanent, so
            # a fresh instance may reconstruct in the other mode and must be
            # re-shaped before restoring (see load_state_dict)
            destination[prefix + "_use_vmap"] = np.asarray(self._use_vmap)
            # resampling config: a checkpoint restored into an instance with a
            # different bootstrap count or sampling strategy is a silently
            # different estimator (wrong copy count / wrong resampling law), so
            # both are recorded and verified at load (advisor round-5 finding)
            destination[prefix + "_num_bootstraps"] = np.asarray(self.num_bootstraps)
            destination[prefix + "_sampling_strategy"] = np.asarray(self.sampling_strategy)
            if self._use_vmap:
                for k, v in self._stacked_state.items():
                    destination[f"{prefix}_stacked_state.{k}"] = np.asarray(v)
            encoded = self._encode_rng_state(self._rng)
            if encoded is not None:
                destination[prefix + "_rng_state"] = encoded
        return destination

    def load_state_dict(
        self, state_dict: Dict[str, Any], prefix: str = "", strict: bool = True, _consumed: Optional[set] = None
    ) -> None:
        owns_check = _consumed is None
        consumed: set = set() if owns_check else _consumed
        # config guard FIRST: re-shaping the stacked state or restoring copies
        # against a mismatched bootstrap configuration would corrupt silently
        nb_key = prefix + "_num_bootstraps"
        if nb_key in state_dict:
            consumed.add(nb_key)
            ckpt_nb = int(np.asarray(state_dict[nb_key]))
            if ckpt_nb != self.num_bootstraps:
                raise ValueError(
                    f"BootStrapper checkpoint was written with num_bootstraps={ckpt_nb} but this"
                    f" instance has num_bootstraps={self.num_bootstraps}; construct the instance to"
                    " match the checkpoint"
                )
        ss_key = prefix + "_sampling_strategy"
        if ss_key in state_dict:
            consumed.add(ss_key)
            ckpt_ss = str(np.asarray(state_dict[ss_key]))
            if ckpt_ss != self.sampling_strategy:
                raise ValueError(
                    f"BootStrapper checkpoint was written with sampling_strategy={ckpt_ss!r} but this"
                    f" instance has sampling_strategy={self.sampling_strategy!r}; construct the"
                    " instance to match the checkpoint"
                )
        mode_key = prefix + "_use_vmap"
        if mode_key in state_dict:
            consumed.add(mode_key)
        if mode_key in state_dict and bool(np.asarray(state_dict[mode_key])) != self._use_vmap:
            # re-shape to the checkpoint's mode, mirroring __init__'s branches —
            # otherwise a copies-mode checkpoint loaded into a fresh vmap-mode
            # instance raises on missing _stacked_state keys (or silently drops
            # the copies' accumulation with strict=False)
            self._use_vmap = bool(np.asarray(state_dict[mode_key]))
            if self._use_vmap:
                self.metrics = []
                self._stacked_state = self._init_stacked_state()
            else:
                self.metrics = [deepcopy(self.base_metric) for _ in range(self.num_bootstraps)]
        super().load_state_dict(state_dict, prefix, strict, _consumed=consumed)
        if self._use_vmap:
            for k in list(self._stacked_state):
                name = f"{prefix}_stacked_state.{k}"
                if name in state_dict:
                    consumed.add(name)
                    self._stacked_state[k] = jnp.asarray(state_dict[name])
                elif strict and self.base_metric._persistent.get(k, False):
                    raise KeyError(f"Missing key {name} in state_dict")
        rng_key = prefix + "_rng_state"
        if rng_key in state_dict:
            consumed.add(rng_key)
            self._rng.bit_generator.state = self._decode_rng_state(state_dict[rng_key])
        elif strict and self._any_persistent():
            # a resume without the rng stream would silently diverge from the
            # uninterrupted run in its post-resume resampling draws
            raise KeyError(f"Missing key {rng_key} in state_dict")
        if owns_check and strict:
            _raise_on_unconsumed(state_dict, prefix, consumed)
