"""MinMaxMetric — track the min and max of a base metric's computed value.

Reference parity: src/torchmetrics/wrappers/minmax.py (:23).
"""

from __future__ import annotations

from typing import Any, Dict, Optional, Union

import jax
import jax.numpy as jnp
from jax import Array

from metrics_tpu.metric import Metric


class MinMaxMetric(Metric):
    """Min Max Metric.

    Example:
        >>> import jax.numpy as jnp
        >>> from metrics_tpu import MinMaxMetric, MeanMetric
        >>> metric = MinMaxMetric(MeanMetric())
        >>> metric.update(jnp.array(2.0))
        >>> {k: float(v) for k, v in metric.compute().items()}
        {'raw': 2.0, 'max': 2.0, 'min': 2.0}
        >>> metric.update(jnp.array(4.0))
        >>> {k: float(v) for k, v in metric.compute().items()}
        {'raw': 3.0, 'max': 3.0, 'min': 2.0}
    """

    full_state_update: Optional[bool] = True

    min_val: Array
    max_val: Array

    def __init__(self, base_metric: Metric, **kwargs: Any) -> None:
        super().__init__(**kwargs)
        if not isinstance(base_metric, Metric):
            raise ValueError(
                f"Expected base metric to be an instance of `metrics_tpu.Metric` but received {base_metric}"
            )
        self._base_metric = base_metric
        self.min_val = jnp.asarray(float("inf"))
        self.max_val = jnp.asarray(float("-inf"))

    def update(self, *args: Any, **kwargs: Any) -> None:
        self._base_metric.update(*args, **kwargs)

    def compute(self) -> Dict[str, Array]:
        """Current value + running min/max (reference minmax.py compute)."""
        val = self._base_metric.compute()
        if not self._is_suitable_val(val):
            raise RuntimeError(f"Returned value from base metric should be a float or scalar tensor, but got {val}")
        self.max_val = jnp.where(self.max_val > val, self.max_val, jnp.asarray(val, dtype=jnp.float32))
        self.min_val = jnp.where(self.min_val < val, self.min_val, jnp.asarray(val, dtype=jnp.float32))
        return {"raw": jnp.asarray(val), "max": self.max_val, "min": self.min_val}

    def reset(self) -> None:
        super().reset()
        self._base_metric.reset()
        self.min_val = jnp.asarray(float("inf"))
        self.max_val = jnp.asarray(float("-inf"))

    @staticmethod
    def _is_suitable_val(val: Union[float, Array]) -> bool:
        if isinstance(val, (int, float)):
            return True
        if isinstance(val, jax.Array):
            return val.size == 1
        return False
