"""MinMaxMetric — track the min and max of a base metric's computed value.

Reference parity: src/torchmetrics/wrappers/minmax.py (:23).
"""

from __future__ import annotations

from typing import Any, Dict, Optional, Union

import jax
import jax.numpy as jnp
import numpy as np
from jax import Array

from metrics_tpu.metric import Metric, _raise_on_unconsumed


class MinMaxMetric(Metric):
    """Min Max Metric.

    Example:
        >>> import jax.numpy as jnp
        >>> from metrics_tpu import MinMaxMetric, MeanMetric
        >>> metric = MinMaxMetric(MeanMetric())
        >>> metric.update(jnp.array(2.0))
        >>> {k: float(v) for k, v in metric.compute().items()}
        {'raw': 2.0, 'max': 2.0, 'min': 2.0}
        >>> metric.update(jnp.array(4.0))
        >>> {k: float(v) for k, v in metric.compute().items()}
        {'raw': 3.0, 'max': 3.0, 'min': 2.0}
    """

    full_state_update: Optional[bool] = True

    min_val: Array
    max_val: Array

    def __init__(self, base_metric: Metric, **kwargs: Any) -> None:
        super().__init__(**kwargs)
        if not isinstance(base_metric, Metric):
            raise ValueError(
                f"Expected base metric to be an instance of `metrics_tpu.Metric` but received {base_metric}"
            )
        self._base_metric = base_metric
        # deliberately PLAIN attributes, not registered states: they mutate
        # inside compute(), and forward()'s full-state snapshot/restore (and
        # the distributed sync/unsync context) would revert a registered
        # state's compute-time mutation, freezing the running extremes — the
        # reference keeps them unregistered for the same reason. Checkpointing
        # is handled by the explicit state_dict/load_state_dict overrides
        # below (the reference loses them through state_dict; found by the
        # checkpoint_resume fuzz surface).
        self.min_val = jnp.asarray(float("inf"))
        self.max_val = jnp.asarray(float("-inf"))

    def update(self, *args: Any, **kwargs: Any) -> None:
        self._base_metric.update(*args, **kwargs)

    def compute(self) -> Dict[str, Array]:
        """Current value + running min/max (reference minmax.py compute)."""
        val = self._base_metric.compute()
        if not self._is_suitable_val(val):
            raise RuntimeError(f"Returned value from base metric should be a float or scalar tensor, but got {val}")
        self.max_val = jnp.where(self.max_val > val, self.max_val, jnp.asarray(val, dtype=jnp.float32))
        self.min_val = jnp.where(self.min_val < val, self.min_val, jnp.asarray(val, dtype=jnp.float32))
        return {"raw": jnp.asarray(val), "max": self.max_val, "min": self.min_val}

    def reset(self) -> None:
        """Reset the base metric. The running extremes are deliberately KEPT:
        the reference behaves this way (minmax.py:92-95 — its docstring claims
        the bounds reset, but the body never touches the plain attributes),
        and `forward` relies on it — the full-state forward path calls
        `reset()` internally, so clearing here would wipe the extremes every
        batch (observed: min==max==last batch value, vs the reference's
        running min/max across forwards)."""
        super().reset()
        self._base_metric.reset()

    def state_dict(self, destination: Optional[Dict] = None, prefix: str = "") -> Dict[str, Any]:
        destination = super().state_dict(destination, prefix)  # recurses into _base_metric
        if self._any_persistent():  # recursive — the base may itself be a wrapper
            destination[prefix + "min_val"] = np.asarray(self.min_val)
            destination[prefix + "max_val"] = np.asarray(self.max_val)
        return destination

    def load_state_dict(
        self, state_dict: Dict[str, Any], prefix: str = "", strict: bool = True, _consumed: Optional[set] = None
    ) -> None:
        owns_check = _consumed is None
        consumed: set = set() if owns_check else _consumed
        super().load_state_dict(state_dict, prefix, strict, _consumed=consumed)
        for key in ("min_val", "max_val"):
            name = prefix + key
            if name in state_dict:
                consumed.add(name)
                setattr(self, key, jnp.asarray(state_dict[name]))
            elif strict and self._any_persistent():
                raise KeyError(f"Missing key {name} in state_dict")
        if owns_check and strict:
            _raise_on_unconsumed(state_dict, prefix, consumed)

    @staticmethod
    def _is_suitable_val(val: Union[float, Array]) -> bool:
        if isinstance(val, (int, float)):
            return True
        if isinstance(val, jax.Array):
            return val.size == 1
        return False
