"""ClasswiseWrapper — explode a per-class result tensor into a labelled dict.

Reference parity: src/torchmetrics/wrappers/classwise.py (:21 class, :86-90 compute).
"""

from __future__ import annotations

from typing import Any, Dict, List, Optional

from jax import Array

from metrics_tpu.metric import Metric


class ClasswiseWrapper(Metric):
    """Classwise Wrapper.

    Example:
        >>> import jax.numpy as jnp
        >>> from metrics_tpu import ClasswiseWrapper
        >>> from metrics_tpu.classification import MulticlassAccuracy
        >>> metric = ClasswiseWrapper(MulticlassAccuracy(num_classes=3, average=None))
        >>> metric.update(jnp.array([0, 1, 2, 1]), jnp.array([0, 1, 2, 2]))
        >>> {k: float(v) for k, v in metric.compute().items()}
        {'multiclassaccuracy_0': 1.0, 'multiclassaccuracy_1': 1.0, 'multiclassaccuracy_2': 0.5}
    """

    full_state_update: Optional[bool] = True

    def __init__(self, metric: Metric, labels: Optional[List[str]] = None, **kwargs: Any) -> None:
        super().__init__(**kwargs)
        if not isinstance(metric, Metric):
            raise ValueError(f"Expected argument `metric` to be an instance of `metrics_tpu.Metric` but got {metric}")
        if labels is not None and not (isinstance(labels, list) and all(isinstance(lab, str) for lab in labels)):
            raise ValueError(f"Expected argument `labels` to either be `None` or a list of strings but got {labels}")
        self.metric = metric
        self.labels = labels

    def _convert(self, x: Array) -> Dict[str, Array]:
        name = self.metric.__class__.__name__.lower()
        if self.labels is None:
            return {f"{name}_{i}": val for i, val in enumerate(x)}
        return {f"{name}_{lab}": val for lab, val in zip(self.labels, x)}

    def update(self, *args: Any, **kwargs: Any) -> None:
        self.metric.update(*args, **kwargs)

    def compute(self) -> Dict[str, Array]:
        return self._convert(self.metric.compute())

    def forward(self, *args: Any, **kwargs: Any) -> Any:
        return self._convert(self.metric(*args, **kwargs))

    def reset(self) -> None:
        self.metric.reset()

    def _wrap_update(self, update):  # keep bookkeeping out of the inner metric's way
        return update

    def _wrap_compute(self, compute):
        return compute
