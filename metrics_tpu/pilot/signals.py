"""Signal extraction: fleet telemetry snapshots → EWMA'd per-target readings.

The pilot never talks to engines to observe — it reads the same piggybacked
:func:`~metrics_tpu.obs.fleet.node_snapshot` documents the leader already
merges into its :class:`~metrics_tpu.obs.fleet.FleetAggregator` (PR 14), so
observing costs zero extra fleet traffic. Staleness is respected, not
patched over: a node past ``stale_after_s`` contributes NOTHING to any
reading this cycle (its last-known values are excluded, never extrapolated),
and the excluded node list is part of every journaled cycle.

Per partition (the ``partition=`` label the part plane stamps on engine
series) the book derives:

- **write rate** (events/s): per-node deltas of the cumulative
  ``metrics_tpu_engine_events_total{event="submitted"}`` counter over
  snapshot wall-time, summed across nodes, then EWMA'd. Deltas clamp at
  zero — a counter reset (engine restart, telemetry relabel) reads as a
  quiet interval, never as negative traffic.
- **backlog** (requests): sum of ``metrics_tpu_engine_queue_depth`` gauges.
- **p99 latency** (s): worst ``metrics_tpu_engine_latency_quantile_seconds``
  ``{quantile="0.99"}`` across nodes.

Per engine id the book tracks the hot-tier residency gauge
(``metrics_tpu_tier_residency{tier="hot"}``) for capacity retuning, and the
fleet-wide backlog total for shard growth.
"""

from __future__ import annotations

from dataclasses import dataclass
from typing import Any, Dict, List, Optional, Tuple

__all__ = ["Reading", "SignalBook"]

_EVENTS = "metrics_tpu_engine_events_total"
_DEPTH = "metrics_tpu_engine_queue_depth"
_QUANTILE = "metrics_tpu_engine_latency_quantile_seconds"
_RESIDENCY = "metrics_tpu_tier_residency"


@dataclass
class Reading:
    """One target's smoothed signals + how often it has been observed."""

    rate: float = 0.0  # EWMA events/s
    backlog: float = 0.0  # EWMA queued requests
    p99_s: float = 0.0  # EWMA p99 submit->commit latency
    observations: int = 0

    def as_doc(self) -> Dict[str, float]:
        return {
            "rate": round(self.rate, 3),
            "backlog": round(self.backlog, 2),
            "p99_s": round(self.p99_s, 6),
            "observations": self.observations,
        }


def _labels(pairs: Any) -> Dict[str, str]:
    return {str(k): str(v) for k, v in pairs}


class SignalBook:
    """EWMA state over successive fleet observations."""

    def __init__(self, alpha: float) -> None:
        if not 0.0 < alpha <= 1.0:
            raise ValueError(f"alpha must be in (0, 1], got {alpha}")
        self.alpha = float(alpha)
        self._parts: Dict[str, Reading] = {}
        # (node, partition) -> (last cumulative submitted, last t_wall)
        self._submitted: Dict[Tuple[str, str], Tuple[float, float]] = {}
        self._tier_hot: Dict[str, float] = {}  # engine id -> EWMA hot residents
        self._backlog_total = 0.0
        self._observations = 0
        self.excluded_stale: List[str] = []  # last ingest's excluded nodes

    # ------------------------------------------------------------------ ingest

    def ingest(self, aggregator: Any) -> Dict[str, Reading]:
        """Fold the aggregator's current live rows into the book.

        Returns the per-partition readings after this observation. Stale
        nodes are recorded in :attr:`excluded_stale` and contribute nothing.
        """
        rows = aggregator.rows()
        self.excluded_stale = [node for node, _, _, stale in rows if stale]
        live = [(node, snap) for node, snap, _, stale in rows if not stale]

        # raw accumulators for this observation
        rate_by_part: Dict[str, float] = {}
        backlog_by_part: Dict[str, float] = {}
        p99_by_part: Dict[str, float] = {}
        tier_hot: Dict[str, float] = {}
        backlog_total = 0.0

        for node, snap in live:
            t_wall = float(snap.get("t_wall", 0.0))
            families = snap.get("families", {})
            for pairs, value in families.get(_EVENTS, {}).get("samples", ()):
                lab = _labels(pairs)
                part = lab.get("partition")
                if part is None or lab.get("event") != "submitted":
                    continue
                key = (node, part)
                prev = self._submitted.get(key)
                self._submitted[key] = (float(value), t_wall)
                if prev is None:
                    continue  # first sighting: no interval to rate over
                prev_v, prev_t = prev
                dt = t_wall - prev_t
                if dt <= 0:
                    # same snapshot re-ingested: restore the older stamp so the
                    # next genuinely-new snapshot rates over the full interval
                    self._submitted[key] = prev
                    continue
                delta = max(0.0, float(value) - prev_v)  # counter reset -> quiet
                rate_by_part[part] = rate_by_part.get(part, 0.0) + delta / dt
            for pairs, value in families.get(_DEPTH, {}).get("samples", ()):
                lab = _labels(pairs)
                backlog_total += float(value)
                part = lab.get("partition")
                if part is not None:
                    backlog_by_part[part] = backlog_by_part.get(part, 0.0) + float(value)
            for pairs, value in families.get(_QUANTILE, {}).get("samples", ()):
                lab = _labels(pairs)
                part = lab.get("partition")
                if part is None or lab.get("quantile") != "0.99":
                    continue
                p99_by_part[part] = max(p99_by_part.get(part, 0.0), float(value))
            for pairs, value in families.get(_RESIDENCY, {}).get("samples", ()):
                lab = _labels(pairs)
                if lab.get("tier") != "hot":
                    continue
                eid = lab.get("engine", "")
                tier_hot[eid] = tier_hot.get(eid, 0.0) + float(value)

        a = self.alpha
        seen = set(rate_by_part) | set(backlog_by_part) | set(p99_by_part)
        for part in seen:
            r = self._parts.get(part)
            if r is None:
                r = self._parts[part] = Reading()
            r.rate += a * (rate_by_part.get(part, 0.0) - r.rate)
            r.backlog += a * (backlog_by_part.get(part, 0.0) - r.backlog)
            r.p99_s += a * (p99_by_part.get(part, 0.0) - r.p99_s)
            r.observations += 1
        for eid, hot in tier_hot.items():
            prev_hot = self._tier_hot.get(eid, hot)
            self._tier_hot[eid] = prev_hot + a * (hot - prev_hot)
        self._backlog_total += a * (backlog_total - self._backlog_total)
        self._observations += 1
        return dict(self._parts)

    # ------------------------------------------------------------------ reading

    def readings(self) -> Dict[str, Reading]:
        return dict(self._parts)

    def tier_hot(self, engine_id: str) -> Optional[float]:
        """EWMA hot-tier residents for one engine id (None = never observed)."""
        return self._tier_hot.get(engine_id)

    @property
    def backlog_total(self) -> float:
        return self._backlog_total

    @property
    def observations(self) -> int:
        return self._observations

    def as_doc(self) -> Dict[str, Any]:
        """The book's current state, journal-shaped."""
        return {
            "partitions": {p: r.as_doc() for p, r in sorted(self._parts.items())},
            "backlog_total": round(self._backlog_total, 2),
            "excluded_stale": sorted(self.excluded_stale),
            "observations": self._observations,
        }
