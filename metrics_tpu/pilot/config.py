"""PilotConfig — wiring and policy knobs for one autopilot controller."""

from __future__ import annotations

from dataclasses import dataclass
from typing import Optional

from metrics_tpu.cluster.errors import ClusterConfigError
from metrics_tpu.cluster.store import CoordStore

__all__ = ["PilotConfig", "PILOT_LEASE"]

# the controller's dedicated named lease: same CAS-with-TTL machinery as the
# per-partition "p<N>" leases, so at most one live controller fleet-wide and
# failover needs no new mechanism
PILOT_LEASE = "pilot"


@dataclass(frozen=True)
class PilotConfig:
    """One :class:`~metrics_tpu.pilot.loop.AutoPilot`'s configuration.

    Leadership / cadence (store-clock seconds, like every cluster knob):

    - ``lease_ttl_s``: TTL on the ``pilot`` named lease; renewed at half TTL.
    - ``tick_interval_s``: supervisor-thread cadence (lease upkeep).
    - ``evaluate_interval_s``: minimum store time between reconcile cycles —
      the lease renews every tick, decisions happen at most this often.

    Signal shaping:

    - ``ewma_alpha``: smoothing weight for every ingested signal (1.0 = raw).
    - ``min_observations``: a partition is not actionable until its signals
      were observed this many times — one noisy sample never moves tenants.
    - ``min_rate``: fleet below this aggregate write rate (events/s) is idle;
      an idle fleet has no hot spots, only noise.

    Hysteresis bands (flag at ``high``, unflag at ``low`` — the gap is what
    prevents flap; every band validates ``high > low``):

    - ``hot_ratio_high`` / ``hot_ratio_low``: a partition is HOT when its
      EWMA write rate exceeds ``high`` x the fleet mean, and stays flagged
      until it drops under ``low`` x the mean.
    - ``backlog_high`` / ``backlog_low``: queue-depth band (absolute
      requests) arming shard growth.
    - ``tier_occupancy_high`` / ``tier_occupancy_low``: hot-set fill
      fraction band arming a ``hot_capacity`` retune.

    Actuation bounds:

    - ``migration_budget`` per ``budget_window_s``: the actuator never starts
      more migrations than this inside one sliding window.
    - ``tenant_cooldown_s``: a tenant the pilot touched is untouchable for
      this long — the other half of anti-thrash.
    - ``max_actions_per_cycle``: hard per-cycle cap across all action kinds.
    - ``tier_retune_factor`` / ``tier_capacity_max``: hot-capacity growth
      step and ceiling (retunes only grow, like ``resize()``).
    - ``max_shards``: ceiling for planned shard growth.

    Kill switch: ``enabled=False`` builds an inert pilot (never acquires the
    lease, ticks are no-ops); runtime :meth:`~AutoPilot.pause` /
    :meth:`~AutoPilot.resume` keep the lease but stop actuation.
    ``dry_run=True`` plans and journals every cycle but executes nothing —
    migrations go through ``migrate_tenant(dry_run=True)`` so the journaled
    plan is the validated one.

    ``journal_directory`` pins the append-only CRC-framed decision log;
    ``None`` keeps decisions in memory only (tests).
    """

    node_id: str
    store: CoordStore
    enabled: bool = True
    dry_run: bool = False
    lease_ttl_s: float = 3.0
    tick_interval_s: float = 0.25
    evaluate_interval_s: float = 1.0
    ewma_alpha: float = 0.4
    min_observations: int = 2
    min_rate: float = 1.0
    hot_ratio_high: float = 2.0
    hot_ratio_low: float = 1.25
    backlog_high: float = 64.0
    backlog_low: float = 8.0
    tier_occupancy_high: float = 0.9
    tier_occupancy_low: float = 0.5
    tier_retune_factor: float = 2.0
    tier_capacity_max: int = 1 << 20
    max_shards: int = 64
    migration_budget: int = 4
    budget_window_s: float = 10.0
    tenant_cooldown_s: float = 30.0
    max_actions_per_cycle: int = 8
    journal_directory: Optional[str] = None

    def __post_init__(self) -> None:
        if not self.node_id:
            raise ClusterConfigError("PilotConfig.node_id must be non-empty")
        if self.store is None:
            raise ClusterConfigError("PilotConfig.store is required")
        for knob in ("lease_ttl_s", "tick_interval_s", "evaluate_interval_s",
                     "budget_window_s", "tenant_cooldown_s"):
            if getattr(self, knob) <= 0:
                raise ClusterConfigError(f"PilotConfig.{knob} must be > 0")
        if not 0.0 < self.ewma_alpha <= 1.0:
            raise ClusterConfigError("PilotConfig.ewma_alpha must be in (0, 1]")
        if self.min_observations < 1:
            raise ClusterConfigError("PilotConfig.min_observations must be >= 1")
        if self.min_rate < 0:
            raise ClusterConfigError("PilotConfig.min_rate must be >= 0")
        for high, low in (("hot_ratio_high", "hot_ratio_low"),
                          ("backlog_high", "backlog_low"),
                          ("tier_occupancy_high", "tier_occupancy_low")):
            if getattr(self, high) <= getattr(self, low):
                raise ClusterConfigError(
                    f"PilotConfig.{high} must exceed {low} — the hysteresis gap "
                    "is what prevents flag/unflag flap"
                )
        if self.hot_ratio_low < 1.0:
            raise ClusterConfigError(
                "PilotConfig.hot_ratio_low must be >= 1.0 — a partition at or "
                "under the fleet mean is balanced by definition"
            )
        if self.tier_retune_factor <= 1.0:
            raise ClusterConfigError("PilotConfig.tier_retune_factor must be > 1.0")
        if self.tier_capacity_max < 1:
            raise ClusterConfigError("PilotConfig.tier_capacity_max must be >= 1")
        if self.max_shards < 1:
            raise ClusterConfigError("PilotConfig.max_shards must be >= 1")
        if self.migration_budget < 1:
            raise ClusterConfigError("PilotConfig.migration_budget must be >= 1")
        if self.max_actions_per_cycle < 1:
            raise ClusterConfigError("PilotConfig.max_actions_per_cycle must be >= 1")
