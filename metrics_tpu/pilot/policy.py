"""Reconcile policy: hysteresis-banded detection → a bounded action plan.

Detection runs on ratios, not absolutes: a partition is HOT when its EWMA
write rate exceeds ``hot_ratio_high`` x the fleet mean, and stays flagged
until it drops under ``hot_ratio_low`` x the mean — the band gap is the
anti-flap guarantee (a partition oscillating around one threshold would
otherwise bounce tenants back and forth forever). The same banding arms tier
retunes (hot-set fill fraction) and shard growth (fleet backlog depth); both
of those actuations only ever GROW, mirroring ``ShardedEngine.resize()``'s
monotonicity, so a mis-tuned band costs capacity, never correctness.

Rebalancing is deliberately signal-light at the tenant grain: engine
telemetry attributes load to *partitions* (the ``partition=`` label), not to
individual tenants, so the planner spreads a hot partition's tenants
round-robin across the coldest partitions down to its fair share and lets
the next cycles re-observe — a few bounded moves per window plus hysteresis
converges without per-tenant rate accounting, and never overshoots by more
than one window's budget.

Every plan entry is a frozen dataclass with a ``describe()`` journal form;
the policy also returns *decision* docs for flag/unflag edges so the journal
explains inaction (a hot flag with no local leadership, a band not yet
crossed) as well as action.
"""

from __future__ import annotations

from dataclasses import dataclass
from typing import Any, Dict, Hashable, List, Optional, Sequence, Set, Tuple

from metrics_tpu.pilot.config import PilotConfig
from metrics_tpu.pilot.signals import Reading

__all__ = ["Action", "MigrateTenant", "RetuneTier", "ResizeShards", "Policy"]


@dataclass(frozen=True)
class Action:
    kind = "action"

    def describe(self) -> Dict[str, Any]:
        return {"kind": self.kind}


@dataclass(frozen=True)
class MigrateTenant(Action):
    key: Hashable
    src_pid: int
    dst_pid: int
    kind = "migrate_tenant"

    def describe(self) -> Dict[str, Any]:
        return {
            "kind": self.kind,
            "tenant": repr(self.key),
            "src_pid": self.src_pid,
            "dst_pid": self.dst_pid,
        }


@dataclass(frozen=True)
class RetuneTier(Action):
    pid: int
    hot_capacity: int
    kind = "retune_tier"

    def describe(self) -> Dict[str, Any]:
        return {"kind": self.kind, "pid": self.pid, "hot_capacity": self.hot_capacity}


@dataclass(frozen=True)
class ResizeShards(Action):
    new_shards: int
    kind = "resize_shards"

    def describe(self) -> Dict[str, Any]:
        return {"kind": self.kind, "new_shards": self.new_shards}


class Policy:
    """Hysteresis state + planner. One instance per pilot; not thread-safe
    (the loop serializes cycles under its tick lock)."""

    def __init__(self, cfg: PilotConfig) -> None:
        self.cfg = cfg
        self._hot: Set[str] = set()  # flagged partitions (hysteresis memory)
        self._tier_armed: Set[str] = set()  # engine ids past the occupancy band
        self._backlog_armed = False

    @property
    def hot(self) -> Tuple[str, ...]:
        return tuple(sorted(self._hot))

    # ------------------------------------------------------------------ planning

    def plan(
        self,
        readings: Dict[str, Reading],
        *,
        partition_of: Dict[str, int],
        owned: Sequence[int],
        tenants_of: Dict[int, List[Hashable]],
        tier_view: Dict[int, Tuple[str, int, Optional[float]]],
        shard_view: Optional[Tuple[int, float]] = None,
    ) -> Tuple[List[Dict[str, Any]], List[Action]]:
        """One reconcile pass: update flags, emit a bounded action list.

        - ``partition_of``: partition label -> pid (only labeled partitions
          are actionable).
        - ``owned``: pids this host currently leads — the pilot only moves
          tenants it can quarantine locally (source leadership is the
          migration precondition; a hot partition led elsewhere is journaled
          as out of reach, not guessed at).
        - ``tenants_of``: pid -> resident tenant keys for owned partitions.
        - ``tier_view``: pid -> (engine telemetry id, current hot_capacity,
          EWMA hot residents or None) for owned tiered partitions — residency
          comes from the signal book, capacity from the local engine.
        - ``shard_view``: (current shard count, backlog EWMA) when the pilot
          supervises a ShardedEngine, else None.
        """
        cfg = self.cfg
        decisions: List[Dict[str, Any]] = []
        actions: List[Action] = []

        mature = {
            p: r for p, r in readings.items()
            if r.observations >= cfg.min_observations and p in partition_of
        }
        total_rate = sum(r.rate for r in mature.values())
        mean_rate = total_rate / len(mature) if mature else 0.0

        # ---- hot-partition detection (ratio band over the fleet mean)
        if total_rate >= cfg.min_rate and mean_rate > 0:
            for part, r in sorted(mature.items()):
                ratio = r.rate / mean_rate
                if part in self._hot:
                    if ratio <= cfg.hot_ratio_low:
                        self._hot.discard(part)
                        decisions.append({
                            "what": "partition_cooled", "partition": part,
                            "ratio": round(ratio, 3), "band_low": cfg.hot_ratio_low,
                        })
                elif ratio >= cfg.hot_ratio_high:
                    self._hot.add(part)
                    decisions.append({
                        "what": "partition_hot", "partition": part,
                        "ratio": round(ratio, 3), "band_high": cfg.hot_ratio_high,
                        "rate": round(r.rate, 3), "fleet_mean": round(mean_rate, 3),
                    })
        elif self._hot and total_rate < cfg.min_rate:
            # idle fleet: nothing is hot relative to silence
            for part in sorted(self._hot):
                decisions.append({"what": "partition_cooled", "partition": part,
                                  "ratio": 0.0, "band_low": cfg.hot_ratio_low})
            self._hot.clear()

        # ---- rebalance plan: spread each owned hot partition to fair share
        owned_set = set(owned)
        cold_order = [
            partition_of[p]
            for p, _ in sorted(mature.items(), key=lambda kv: kv[1].rate)
            if p not in self._hot
        ]
        for part in sorted(self._hot):
            pid = partition_of[part]
            if pid not in owned_set:
                decisions.append({
                    "what": "hot_but_not_local", "partition": part,
                    "why": "this pilot does not lead the source partition; "
                           "its leader's pilot standby will act if it wins the lease",
                })
                continue
            if not cold_order:
                decisions.append({"what": "no_cold_destination", "partition": part})
                continue
            tenants = list(tenants_of.get(pid, ()))
            fair = max(1, len(tenants) // max(1, len(mature)))
            movable = tenants[fair:]
            if not movable:
                decisions.append({"what": "nothing_to_move", "partition": part,
                                  "tenants": len(tenants), "fair_share": fair})
                continue
            planned = 0
            for i, key in enumerate(movable):
                if len(actions) >= cfg.max_actions_per_cycle:
                    break
                actions.append(MigrateTenant(key, pid, cold_order[i % len(cold_order)]))
                planned += 1
            decisions.append({
                "what": "rebalance_planned", "partition": part,
                "tenants": len(tenants), "fair_share": fair,
                "planned_moves": planned,
            })

        # ---- tier retune: grow hot_capacity when the hot set runs full
        for pid, (eid, capacity, hot) in sorted(tier_view.items()):
            if hot is None or capacity <= 0:
                continue
            frac = hot / capacity
            if eid in self._tier_armed:
                if frac <= cfg.tier_occupancy_low:
                    self._tier_armed.discard(eid)
            elif frac >= cfg.tier_occupancy_high and capacity < cfg.tier_capacity_max:
                self._tier_armed.add(eid)
                new_cap = min(int(capacity * cfg.tier_retune_factor), cfg.tier_capacity_max)
                if new_cap > capacity and len(actions) < cfg.max_actions_per_cycle:
                    actions.append(RetuneTier(pid, new_cap))
                    decisions.append({
                        "what": "tier_retune", "pid": pid, "engine": eid,
                        "occupancy": round(frac, 3), "band_high": cfg.tier_occupancy_high,
                        "hot_capacity": capacity, "new_capacity": new_cap,
                    })

        # ---- shard growth: fleet backlog sustained past the band
        if shard_view is not None:
            current, backlog = shard_view
            if self._backlog_armed:
                if backlog <= cfg.backlog_low:
                    self._backlog_armed = False
            elif backlog >= cfg.backlog_high and current < cfg.max_shards:
                self._backlog_armed = True
                new_shards = min(current * 2, cfg.max_shards)
                if new_shards > current and len(actions) < cfg.max_actions_per_cycle:
                    actions.append(ResizeShards(new_shards))
                    decisions.append({
                        "what": "shard_growth", "backlog": round(backlog, 2),
                        "band_high": cfg.backlog_high,
                        "shards": current, "new_shards": new_shards,
                    })

        return decisions, actions[: cfg.max_actions_per_cycle]
