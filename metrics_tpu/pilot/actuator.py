"""Rate-limited actuator: a bounded plan → executed (or refused) actions.

Every action runs through three gates before it touches anything:

1. **Budget.** At most ``migration_budget`` migrations start inside any
   sliding ``budget_window_s`` window — a mis-detecting policy can degrade
   the fleet by at most one window's worth of quarantine holds before the
   budget refuses it.
2. **Cooldown.** A tenant the pilot touched (even unsuccessfully) is
   untouchable for ``tenant_cooldown_s`` — the pair of a hysteresis band on
   detection and a cooldown on actuation is what makes the loop convergent
   instead of oscillatory.
3. **Locality.** Migrations need both partition leaders' engines writable on
   THIS host (``migrate_tenant``'s contract); an action whose engines are led
   elsewhere is refused as ``not_local``, journaled, and left for the host
   that can actually quarantine the source.

``dry_run`` routes migrations through ``migrate_tenant(dry_run=True)`` so
the journaled outcome carries the *validated* plan (leases, quarantine,
epoch floor) rather than a guess. An action that raises is an actuator
failure edge: counted, flight-dumped (``pilot_action_failed`` bundle), and
reported in the outcome — the cycle continues, the loop survives.
"""

from __future__ import annotations

from collections import deque
from dataclasses import replace as _dc_replace
from typing import Any, Dict, Hashable, List, Optional, Sequence

from metrics_tpu.obs import instrument as _obs
from metrics_tpu.part.migrate import migrate_tenant
from metrics_tpu.pilot.config import PilotConfig
from metrics_tpu.pilot.policy import Action, MigrateTenant, ResizeShards, RetuneTier
from metrics_tpu.shard.ring import stable_key_bytes

__all__ = ["Actuator"]


class Actuator:
    """Execute a policy plan against one host's engines, within bounds."""

    def __init__(self, cfg: PilotConfig, node: Any, sharded: Optional[Any] = None) -> None:
        self.cfg = cfg
        self._node = node  # PartitionedNode: pmap + engines + leadership truth
        self._sharded = sharded
        self._window: deque = deque()  # migration start stamps (store time)
        self._cooldown: Dict[str, float] = {}  # stable tenant key hex -> stamp
        self.executed = 0
        self.refused = 0
        self.failures = 0

    # ------------------------------------------------------------------ gates

    def budget_left(self, now: float) -> int:
        while self._window and now - self._window[0] > self.cfg.budget_window_s:
            self._window.popleft()
        return max(0, self.cfg.migration_budget - len(self._window))

    def _cooling(self, key: Hashable, now: float) -> bool:
        stamp = self._cooldown.get(stable_key_bytes(key).hex())
        return stamp is not None and now - stamp < self.cfg.tenant_cooldown_s

    def _writable(self, pid: int) -> Optional[Any]:
        eng = self._node.engine_for(pid)
        return None if getattr(eng, "_repl_follower", False) else eng

    # ------------------------------------------------------------------ execute

    def execute(self, actions: Sequence[Action], now: float) -> List[Dict[str, Any]]:
        """Run each action through the gates; one outcome doc per action."""
        outcomes: List[Dict[str, Any]] = []
        for action in actions[: self.cfg.max_actions_per_cycle]:
            doc = action.describe()
            try:
                if isinstance(action, MigrateTenant):
                    doc.update(self._migrate(action, now))
                elif isinstance(action, RetuneTier):
                    doc.update(self._retune(action))
                elif isinstance(action, ResizeShards):
                    doc.update(self._resize(action))
                else:
                    doc["outcome"] = "unknown_action"
            except Exception as exc:  # noqa: BLE001 — one bad action must not kill the loop
                self.failures += 1
                doc["outcome"] = "error"
                doc["error"] = f"{type(exc).__name__}: {exc}"
                _obs.record_pilot_action_failed(self.cfg.node_id, action.kind)
            if doc["outcome"] in ("refused_budget", "refused_cooldown", "not_local",
                                  "no_tier", "no_sharded"):
                self.refused += 1
            outcomes.append(doc)
        return outcomes

    def _migrate(self, action: MigrateTenant, now: float) -> Dict[str, Any]:
        if self._cooling(action.key, now):
            return {"outcome": "refused_cooldown",
                    "cooldown_s": self.cfg.tenant_cooldown_s}
        if self.budget_left(now) <= 0:
            return {"outcome": "refused_budget",
                    "budget": self.cfg.migration_budget,
                    "window_s": self.cfg.budget_window_s}
        src = self._writable(action.src_pid)
        dst = self._writable(action.dst_pid)
        if src is None or dst is None:
            return {"outcome": "not_local",
                    "src_writable": src is not None, "dst_writable": dst is not None}
        # the budget charges attempts, not successes: an error storm must be
        # rate-limited exactly like a success storm
        self._window.append(now)
        self._cooldown[stable_key_bytes(action.key).hex()] = now
        if self.cfg.dry_run:
            plan = migrate_tenant(
                action.key, action.dst_pid, pmap=self._node.pmap,
                src_engine=src, dst_engine=dst, node_id=self.cfg.node_id,
                dry_run=True,
            )
            return {"outcome": "dry_run", "plan": plan}
        moved = migrate_tenant(
            action.key, action.dst_pid, pmap=self._node.pmap,
            src_engine=src, dst_engine=dst, node_id=self.cfg.node_id,
        )
        if moved:
            self.executed += 1
            _obs.record_pilot_migration(self.cfg.node_id)
        return {"outcome": "ok" if moved else "noop"}

    def _retune(self, action: RetuneTier) -> Dict[str, Any]:
        eng = self._node.engine_for(action.pid)
        tier = getattr(eng, "_tier", None)
        if tier is None:
            return {"outcome": "no_tier"}
        old = tier.cfg.hot_capacity
        if self.cfg.dry_run:
            return {"outcome": "dry_run", "plan": {"hot_capacity": old,
                                                   "new_capacity": action.hot_capacity}}
        # TierConfig is frozen; the manager reads .cfg on every pass, so a
        # replace-and-assign takes effect at the next tier sweep
        tier.cfg = _dc_replace(tier.cfg, hot_capacity=int(action.hot_capacity))
        self.executed += 1
        return {"outcome": "ok", "was": old}

    def _resize(self, action: ResizeShards) -> Dict[str, Any]:
        if self._sharded is None:
            return {"outcome": "no_sharded"}
        if self.cfg.dry_run:
            return {"outcome": "dry_run", "plan": {"new_shards": action.new_shards}}
        moved = self._sharded.resize(action.new_shards)
        self.executed += 1
        return {"outcome": "ok", "tenants_moved": len(moved)}
