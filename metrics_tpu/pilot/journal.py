"""Append-only CRC-framed decision journal — the pilot's flight log.

Every reconcile cycle appends ONE record: what was observed (including which
nodes were excluded as stale), what the policy decided and why, what the
actuator did, and how each action ended. The framing is the coordination
store's record discipline (``<II`` length+crc32 header per payload) applied
to a single append-only file, so a torn tail from a crash mid-append is
detected and dropped at read time — never half-parsed.

The journal is the post-mortem contract: :func:`read_journal` over the
directory reconstructs every action the pilot ever took, with the signal
values that justified it, without any other data source.
"""

from __future__ import annotations

import json
import os
import struct
import threading
import zlib
from typing import Any, Dict, List, Optional

__all__ = ["DecisionJournal", "read_journal", "JOURNAL_FILE"]

JOURNAL_FILE = "pilot_decisions.log"

# per-record header: payload length + crc32(payload) — the same framing the
# coordination store and WAL use, so torn/corrupt records are detectable
_CRC = struct.Struct("<II")


def _frame(doc: Dict[str, Any]) -> bytes:
    payload = json.dumps(doc, sort_keys=True, default=repr).encode("utf-8")
    return _CRC.pack(len(payload), zlib.crc32(payload)) + payload


def _scan(data: bytes) -> tuple:
    """(intact records, byte offset of the first torn/corrupt frame)."""
    out: List[Dict[str, Any]] = []
    off = 0
    while off + _CRC.size <= len(data):
        length, crc = _CRC.unpack_from(data, off)
        start = off + _CRC.size
        payload = data[start : start + length]
        if len(payload) != length or zlib.crc32(payload) != crc:
            break  # torn tail: the crash frame and anything after it is noise
        try:
            out.append(json.loads(payload.decode("utf-8")))
        except ValueError:
            break
        off = start + length
    return out, off


class DecisionJournal:
    """Append-only journal of observation→decision→action→outcome cycles."""

    def __init__(self, directory: str, filename: str = JOURNAL_FILE) -> None:
        os.makedirs(directory, exist_ok=True)
        self.path = os.path.join(directory, filename)
        self._lock = threading.Lock()
        # resume the sequence from the existing log (the pilot lease moves
        # between hosts sharing a journal directory; seqs must keep climbing)
        # — and truncate a crash-torn tail first, or every frame appended
        # after it would sit forever behind unreadable bytes
        existing: List[Dict[str, Any]] = []
        if os.path.exists(self.path):
            with open(self.path, "rb") as fh:
                data = fh.read()
            existing, intact = _scan(data)
            if intact < len(data):
                with open(self.path, "r+b") as fh:
                    fh.truncate(intact)
                    fh.flush()
                    os.fsync(fh.fileno())
        self._seq = max((int(d.get("seq", -1)) for d in existing), default=-1) + 1

    def append(self, doc: Dict[str, Any]) -> int:
        """Frame + append one cycle record; returns its sequence number.

        fsync per append: a decision record that evaporates in a crash defeats
        the journal's whole purpose, and the pilot appends at most once per
        ``evaluate_interval_s`` — durability here is off the serving hot path.
        """
        with self._lock:
            seq = self._seq
            self._seq += 1
            framed = _frame({**doc, "seq": seq})
            with open(self.path, "ab") as fh:
                fh.write(framed)
                fh.flush()
                os.fsync(fh.fileno())
            return seq


def read_journal(
    directory: str, filename: str = JOURNAL_FILE, limit: Optional[int] = None
) -> List[Dict[str, Any]]:
    """Every intact record in order; a torn/corrupt tail ends the read.

    Append-only means corruption can only be a crash-truncated tail, so
    stopping at the first bad frame loses at most the record being written
    when the process died — everything the pilot *finished* deciding is here.
    """
    path = os.path.join(directory, filename)
    if not os.path.exists(path):
        return []
    with open(path, "rb") as fh:
        data = fh.read()
    out, _ = _scan(data)
    return out if limit is None else out[:limit]
