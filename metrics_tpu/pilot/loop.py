"""AutoPilot — the leader-run reconcile loop over fleet telemetry.

One controller fleet-wide, by construction: the loop acts only while holding
the dedicated ``pilot`` named lease (the same CAS-with-TTL machinery that
fences partition leaders — see :mod:`metrics_tpu.cluster.store`), renewed at
half TTL. Every candidate host runs an AutoPilot; all but the lease holder
are warm standbys whose ticks cost one lease read. Kill the holder and a
standby wins the lease within one TTL — controller failover needs no new
mechanism and loses nothing but the in-memory EWMA warmup (the decision
journal and the fleet's telemetry both survive the hop).

A reconcile cycle is observe → decide → act → journal, in that order:

1. **Observe.** Pull the member table (one read the leader already pays),
   fold the piggybacked node snapshots into the fleet aggregator, fold the
   aggregator's live rows into the EWMA signal book. Stale nodes are
   excluded and named in the journal — never guessed at.
2. **Decide.** The hysteresis policy (:mod:`metrics_tpu.pilot.policy`) turns
   readings into a bounded action plan plus decision docs explaining every
   flag edge and every refusal-to-act.
3. **Act.** The rate-limited actuator (:mod:`metrics_tpu.pilot.actuator`)
   executes within migration budgets and tenant cooldowns; ``pause()`` (or
   ``dry_run``) stops actuation without giving up the lease, so an operator
   can freeze the fleet's controller without electing a new one.
4. **Journal.** The whole cycle — observations, decisions, actions, outcomes
   — lands as one CRC-framed record; actuator failures additionally dump a
   flight-recorder bundle. Post-mortem needs the journal alone.
"""

from __future__ import annotations

import threading
from typing import Any, Dict, Hashable, List, Optional, Tuple

from metrics_tpu.cluster.errors import CoordStoreError
from metrics_tpu.cluster.store import Lease
from metrics_tpu.obs import fleet as _fleet
from metrics_tpu.obs import instrument as _obs
from metrics_tpu.part.pmap import partition_name
from metrics_tpu.pilot.actuator import Actuator
from metrics_tpu.pilot.config import PILOT_LEASE, PilotConfig
from metrics_tpu.pilot.journal import DecisionJournal
from metrics_tpu.pilot.policy import Policy
from metrics_tpu.pilot.signals import SignalBook

__all__ = ["AutoPilot"]


class AutoPilot:
    """Supervise the fleet: hold the ``pilot`` lease, reconcile, journal.

    ``node`` is this host's :class:`~metrics_tpu.part.PartitionedNode` — the
    pilot's window onto local leadership (which partitions' engines it may
    quarantine) and the executor surface for migrations/retunes. ``sharded``
    optionally names a :class:`~metrics_tpu.shard.ShardedEngine` this host
    serves, enabling planned ``resize()`` growth. ``aggregator`` defaults to
    the process-global fleet aggregator; tests inject their own (with a
    manual clock) for deterministic staleness.
    """

    def __init__(
        self,
        node: Any,
        cfg: PilotConfig,
        *,
        aggregator: Optional[Any] = None,
        sharded: Optional[Any] = None,
        start: bool = True,
    ) -> None:
        self.cfg = cfg
        self._node = node
        self._store = cfg.store
        self._aggregator = aggregator if aggregator is not None else _fleet.AGGREGATOR
        self.signals = SignalBook(cfg.ewma_alpha)
        self.policy = Policy(cfg)
        self.actuator = Actuator(cfg, node, sharded=sharded)
        self.journal: Optional[DecisionJournal] = (
            DecisionJournal(cfg.journal_directory)
            if cfg.journal_directory is not None else None
        )
        self._sharded = sharded
        self._tick_lock = threading.Lock()
        self._lease: Optional[Lease] = None
        self._paused = False
        self._last_cycle = float("-inf")
        self.cycles = 0
        self.decisions = 0
        self.last_error: Optional[BaseException] = None
        # name -> pid for every partition this fleet serves (the part plane
        # stamps exactly these names on the engine series)
        self._partition_of: Dict[str, int] = {
            partition_name(pid): pid for pid in range(node.cfg.partitions)
        }
        _obs.set_pilot_paused(cfg.node_id, (not cfg.enabled) or self._paused)

        self._stop = threading.Event()
        self._thread: Optional[threading.Thread] = None
        if start and cfg.enabled:
            self._thread = threading.Thread(
                target=self._run, name=f"metrics-tpu-pilot-{cfg.node_id}", daemon=True
            )
            self._thread.start()

    # ------------------------------------------------------------------ lifecycle

    def _run(self) -> None:
        while not self._stop.is_set():
            try:
                self.tick()
            except Exception as exc:  # noqa: BLE001 — the controller outlives any one bad cycle
                self.last_error = exc
            self._stop.wait(self.cfg.tick_interval_s)

    def close(self, *, release: bool = True) -> None:
        """Stop the controller; ``release=True`` concedes the pilot lease so a
        standby takes over immediately instead of waiting out the TTL."""
        self._stop.set()
        if self._thread is not None and self._thread is not threading.current_thread():
            self._thread.join(timeout=5.0)
        if release and self._lease is not None:
            try:
                self._store.release_lease(self.cfg.node_id, name=PILOT_LEASE)
            except CoordStoreError:
                pass  # unreachable store: the TTL is the fallback
        self._lease = None

    # ------------------------------------------------------------------ kill switch

    def pause(self) -> None:
        """Freeze actuation without conceding the lease: cycles keep observing
        and journaling (with ``paused: true``) but no action executes."""
        self._paused = True
        _obs.set_pilot_paused(self.cfg.node_id, True)

    def resume(self) -> None:
        self._paused = False
        _obs.set_pilot_paused(self.cfg.node_id, (not self.cfg.enabled))

    @property
    def paused(self) -> bool:
        return self._paused

    @property
    def role(self) -> str:
        """"pilot" while holding the lease, else "standby"."""
        now = self._store.now()
        held = self._lease is not None and not self._lease.expired(now)
        return "pilot" if held else "standby"

    def health(self) -> Dict[str, Any]:
        """Controller state, one plain dict — the kill-switch surface."""
        now = self._store.now()
        lease = self._lease
        return {
            "node_id": self.cfg.node_id,
            "role": self.role,
            "enabled": self.cfg.enabled,
            "paused": self._paused,
            "dry_run": self.cfg.dry_run,
            "lease_epoch": lease.epoch if lease is not None else None,
            "lease_ttl_remaining_s": (
                max(0.0, lease.remaining(now)) if lease is not None else None
            ),
            "cycles": self.cycles,
            "decisions": self.decisions,
            "actions_executed": self.actuator.executed,
            "actions_refused": self.actuator.refused,
            "actions_failed": self.actuator.failures,
            "migration_budget_left": self.actuator.budget_left(now),
            "hot_partitions": list(self.policy.hot),
            "excluded_stale": sorted(self.signals.excluded_stale),
            "last_error": repr(self.last_error) if self.last_error else None,
        }

    # ------------------------------------------------------------------ the tick

    def tick(self) -> None:
        """One supervisor pass: lease upkeep, then (holder only, at most once
        per ``evaluate_interval_s``) a full reconcile cycle."""
        if not self.cfg.enabled:
            return
        with self._tick_lock:
            now = self._store.now()
            if not self._hold_lease(now):
                return
            if now - self._last_cycle < self.cfg.evaluate_interval_s:
                return
            self._last_cycle = now
            self._cycle(now)

    def _hold_lease(self, now: float) -> bool:
        lease = self._lease
        if lease is not None and not lease.expired(now) \
                and lease.remaining(now) > self.cfg.lease_ttl_s / 2.0:
            return True
        was_holder = lease is not None and not lease.expired(now)
        try:
            granted = self._store.acquire_lease(
                self.cfg.node_id, self.cfg.lease_ttl_s, name=PILOT_LEASE
            )
        except CoordStoreError as exc:
            self.last_error = exc
            granted = None
        if granted is not None:
            if not was_holder:
                _obs.record_pilot_lease_won(self.cfg.node_id, granted.epoch)
            self._lease = granted
            return True
        # renewal refused: still covered until OUR deadline passes — past it,
        # assume a standby already won a newer epoch
        if lease is not None and not lease.expired(now):
            return True
        if was_holder or lease is not None:
            _obs.record_pilot_lease_lost(self.cfg.node_id)
        self._lease = None
        return False

    # ------------------------------------------------------------------ the cycle

    def _observe(self) -> None:
        """Fold whatever telemetry has arrived into the signal book."""
        try:
            members = self._store.members()
        except CoordStoreError as exc:
            self.last_error = exc
            members = {}
        self._aggregator.ingest_members(members.values())
        try:
            # the holder's own registry, always fresh — its heartbeat snapshot
            # otherwise round-trips through the store it itself reads
            self._aggregator.ingest(_fleet.node_snapshot(self.cfg.node_id))
        except Exception:  # noqa: BLE001 — self-telemetry must not break the cycle
            pass
        self.signals.ingest(self._aggregator)

    def _tier_view(self) -> Dict[int, Tuple[str, int, Optional[float]]]:
        view: Dict[int, Tuple[str, int, Optional[float]]] = {}
        for pid in self._node.owned():
            eng = self._node.engine_for(pid)
            tier = getattr(eng, "_tier", None)
            if tier is None:
                continue
            eid = eng.telemetry.engine_id
            view[pid] = (eid, int(tier.cfg.hot_capacity), self.signals.tier_hot(eid))
        return view

    def _cycle(self, now: float) -> None:
        self._observe()
        self.cycles += 1
        readings = self.signals.readings()
        owned = self._node.owned()
        if self._paused:
            decisions: List[Dict[str, Any]] = [{"what": "paused"}]
            actions, outcomes = [], []
        else:
            tenants_of: Dict[int, List[Hashable]] = {
                pid: self._node.tenant_keys(pid) for pid in owned
            }
            shard_view = None
            if self._sharded is not None:
                shard_view = (len(self._sharded._engines), self.signals.backlog_total)
            decisions, actions = self.policy.plan(
                readings,
                partition_of=self._partition_of,
                owned=owned,
                tenants_of=tenants_of,
                tier_view=self._tier_view(),
                shard_view=shard_view,
            )
            outcomes = self.actuator.execute(actions, now)
        self.decisions += len(decisions)
        for d in decisions:
            _obs.record_pilot_decision(self.cfg.node_id, str(d.get("what", "unknown")))
        if self.journal is not None:
            self.journal.append({
                "t": now,
                "node": self.cfg.node_id,
                "lease_epoch": self._lease.epoch if self._lease is not None else None,
                "paused": self._paused,
                "dry_run": self.cfg.dry_run,
                "observations": self.signals.as_doc(),
                "decisions": decisions,
                "outcomes": outcomes,
            })
