"""Autopilot plane — the controller that makes the other planes self-driving.

Twelve planes of mechanism exist below this one: fleet telemetry with
staleness stamps (obs), live bit-identical tenant migration between
partition leaders (part), live shard growth (shard), and tier residency
series + retunable hot capacity (tier). This plane closes the loop: a
reconcile controller that runs only on the holder of the dedicated ``pilot``
named lease, reads the telemetry the leader already receives, detects hot
partitions via hysteresis bands over EWMA'd signals, and executes a bounded,
budgeted, cooled-down action plan — every cycle journaled to an append-only
CRC-framed decision log::

    from metrics_tpu.pilot import AutoPilot, PilotConfig

    pilot = AutoPilot(part_node, PilotConfig(
        node_id="a", store=store, journal_directory="/shared/pilot"))
    pilot.health()          # role, lease, budget, hot set, kill-switch state
    pilot.pause()           # freeze actuation; keep the lease; keep observing
    pilot.resume()

Safety is layered: ``PilotConfig.enabled=False`` builds an inert pilot;
``pause()``/``resume()`` gate actuation at runtime; ``dry_run=True`` plans
and journals validated migrations (``migrate_tenant(dry_run=True)``) without
moving anything; and the actuator's per-window migration budget + per-tenant
cooldown bound the blast radius of any mis-detection. See
``docs/source/autopilot.md`` for the signal model and the post-mortem
walkthrough.
"""

from metrics_tpu.pilot.actuator import Actuator
from metrics_tpu.pilot.config import PILOT_LEASE, PilotConfig
from metrics_tpu.pilot.journal import DecisionJournal, read_journal
from metrics_tpu.pilot.loop import AutoPilot
from metrics_tpu.pilot.policy import (
    Action,
    MigrateTenant,
    Policy,
    ResizeShards,
    RetuneTier,
)
from metrics_tpu.pilot.signals import Reading, SignalBook

__all__ = [
    "Action",
    "Actuator",
    "AutoPilot",
    "DecisionJournal",
    "MigrateTenant",
    "PILOT_LEASE",
    "PilotConfig",
    "Policy",
    "Reading",
    "ResizeShards",
    "RetuneTier",
    "SignalBook",
    "read_journal",
]
