"""Moment-streaming regression module metrics (reference src/torchmetrics/regression/
{pearson,concordance,explained_variance,r2}.py)."""

from __future__ import annotations

from typing import Any, Callable, List

import jax.numpy as jnp
import numpy as np
from jax import Array

from metrics_tpu.functional.regression.moments import (
    _concordance_corrcoef_compute,
    _explained_variance_compute,
    _explained_variance_update,
    _pearson_corrcoef_compute,
    _pearson_corrcoef_update,
    _r2_score_compute,
    _r2_score_update,
)
from metrics_tpu.metric import Metric, zero_state


def _final_aggregation(
    means_x: Array, means_y: Array, vars_x: Array, vars_y: Array, corrs_xy: Array, nbs: Array
):
    """Merge per-device Welford states (reference pearson.py ``_final_aggregation``).

    Used when states arrive stacked over devices (dist_reduce_fx=None-style gather);
    pairwise parallel-variance merge, associative and jittable via a fori-style fold.
    """
    if means_x.ndim == 0 or means_x.shape[0] == 1:
        return means_x[0] if means_x.ndim else means_x, means_y[0] if means_y.ndim else means_y, \
            vars_x[0] if vars_x.ndim else vars_x, vars_y[0] if vars_y.ndim else vars_y, \
            corrs_xy[0] if corrs_xy.ndim else corrs_xy, nbs[0] if nbs.ndim else nbs
    mx1, my1, vx1, vy1, cxy1, n1 = means_x[0], means_y[0], vars_x[0], vars_y[0], corrs_xy[0], nbs[0]
    for i in range(1, means_x.shape[0]):
        mx2, my2, vx2, vy2, cxy2, n2 = means_x[i], means_y[i], vars_x[i], vars_y[i], corrs_xy[i], nbs[i]
        nb = n1 + n2
        mean_x = (n1 * mx1 + n2 * mx2) / nb
        mean_y = (n1 * my1 + n2 * my2) / nb
        # var_x
        element_x1 = (n1 + 1) * mean_x - n1 * mx1
        vx1 = vx1 + (element_x1 - mx1) * (element_x1 - mean_x) - (element_x1 - mean_x) ** 2
        element_x2 = (n2 + 1) * mean_x - n2 * mx2
        vx2 = vx2 + (element_x2 - mx2) * (element_x2 - mean_x) - (element_x2 - mean_x) ** 2
        var_x = vx1 + vx2
        # var_y
        element_y1 = (n1 + 1) * mean_y - n1 * my1
        vy1 = vy1 + (element_y1 - my1) * (element_y1 - mean_y) - (element_y1 - mean_y) ** 2
        element_y2 = (n2 + 1) * mean_y - n2 * my2
        vy2 = vy2 + (element_y2 - my2) * (element_y2 - mean_y) - (element_y2 - mean_y) ** 2
        var_y = vy1 + vy2
        # corr_xy
        cxy1 = cxy1 + (element_x1 - mx1) * (element_y1 - mean_y) - (element_x1 - mean_x) * (element_y1 - mean_y)
        cxy2 = cxy2 + (element_x2 - mx2) * (element_y2 - mean_y) - (element_x2 - mean_x) * (element_y2 - mean_y)
        corr_xy = cxy1 + cxy2

        mx1, my1, vx1, vy1, cxy1, n1 = mean_x, mean_y, var_x, var_y, corr_xy, nb
    return mx1, my1, vx1, vy1, cxy1, n1


class _PearsonBase(Metric):
    """Shared Welford state plumbing for Pearson/Concordance."""

    is_differentiable = True
    full_state_update = True

    def __init__(self, num_outputs: int = 1, **kwargs: Any) -> None:
        super().__init__(**kwargs)
        if not (isinstance(num_outputs, int) and num_outputs > 0):
            raise ValueError("Expected argument `num_outputs` to be an int larger than 0")
        self.num_outputs = num_outputs
        shape = (num_outputs,) if num_outputs > 1 else ()
        # dist_reduce_fx=None → states gathered (stacked) across replicas, merged in
        # compute via the parallel-Welford _final_aggregation (reference pearson.py)
        self.add_state("mean_x", zero_state(shape, jnp.float32), dist_reduce_fx=None)
        self.add_state("mean_y", zero_state(shape, jnp.float32), dist_reduce_fx=None)
        self.add_state("var_x", zero_state(shape, jnp.float32), dist_reduce_fx=None)
        self.add_state("var_y", zero_state(shape, jnp.float32), dist_reduce_fx=None)
        self.add_state("corr_xy", zero_state(shape, jnp.float32), dist_reduce_fx=None)
        self.add_state("n_total", zero_state((), jnp.float32), dist_reduce_fx=None)

    def update(self, preds: Array, target: Array) -> None:
        self.mean_x, self.mean_y, self.var_x, self.var_y, self.corr_xy, self.n_total = _pearson_corrcoef_update(
            preds, target, self.mean_x, self.mean_y, self.var_x, self.var_y, self.corr_xy, self.n_total,
            self.num_outputs,
        )

    def _aggregate(self):
        if self.mean_x.ndim > (1 if self.num_outputs > 1 else 0):
            # synced: stacked over replicas → parallel merge
            return _final_aggregation(self.mean_x, self.mean_y, self.var_x, self.var_y, self.corr_xy, self.n_total)
        return self.mean_x, self.mean_y, self.var_x, self.var_y, self.corr_xy, self.n_total


class PearsonCorrCoef(_PearsonBase):
    """Pearson Corr Coef.

    Example:
        >>> import jax.numpy as jnp
        >>> from metrics_tpu import PearsonCorrCoef
        >>> target = jnp.array([3.0, -0.5, 2.0, 7.0])
        >>> preds = jnp.array([2.5, 0.0, 2.0, 8.0])
        >>> metric = PearsonCorrCoef()
        >>> metric.update(preds, target)
        >>> round(float(metric.compute()), 4)
        0.9849
    """

    higher_is_better = None

    def compute(self) -> Array:
        _, _, var_x, var_y, corr_xy, n_total = self._aggregate()
        return _pearson_corrcoef_compute(var_x, var_y, corr_xy, n_total)


class ConcordanceCorrCoef(_PearsonBase):
    """Concordance Corr Coef.

    Example:
        >>> import jax.numpy as jnp
        >>> from metrics_tpu import ConcordanceCorrCoef
        >>> target = jnp.array([3.0, -0.5, 2.0, 7.0])
        >>> preds = jnp.array([2.5, 0.0, 2.0, 8.0])
        >>> metric = ConcordanceCorrCoef()
        >>> metric.update(preds, target)
        >>> metric.compute()
        Array(0.9777347, dtype=float32)
    """

    higher_is_better = None

    def compute(self) -> Array:
        mean_x, mean_y, var_x, var_y, corr_xy, n_total = self._aggregate()
        return _concordance_corrcoef_compute(mean_x, mean_y, var_x, var_y, corr_xy, n_total)


class ExplainedVariance(Metric):
    """Explained Variance.

    Example:
        >>> import jax.numpy as jnp
        >>> from metrics_tpu import ExplainedVariance
        >>> target = jnp.array([3.0, -0.5, 2.0, 7.0])
        >>> preds = jnp.array([2.5, 0.0, 2.0, 8.0])
        >>> metric = ExplainedVariance()
        >>> metric.update(preds, target)
        >>> metric.compute()
        Array(0.95717347, dtype=float32)
    """

    is_differentiable = True
    higher_is_better = True
    full_state_update = False

    def __init__(self, multioutput: str = "uniform_average", **kwargs: Any) -> None:
        super().__init__(**kwargs)
        allowed_multioutput = ("raw_values", "uniform_average", "variance_weighted")
        if multioutput not in allowed_multioutput:
            raise ValueError(f"Invalid input to argument `multioutput`. Choose one of the following: {allowed_multioutput}")
        self.multioutput = multioutput
        self.add_state("sum_error", zero_state((), jnp.float32), dist_reduce_fx="sum")
        self.add_state("sum_squared_error", zero_state((), jnp.float32), dist_reduce_fx="sum")
        self.add_state("sum_target", zero_state((), jnp.float32), dist_reduce_fx="sum")
        self.add_state("sum_squared_target", zero_state((), jnp.float32), dist_reduce_fx="sum")
        self.add_state("num_obs", zero_state((), jnp.float32), dist_reduce_fx="sum")

    def update(self, preds: Array, target: Array) -> None:
        num_obs, sum_error, sum_squared_error, sum_target, sum_squared_target = _explained_variance_update(preds, target)
        self._accumulate(
            num_obs=np.float32(num_obs),
            sum_error=sum_error,
            sum_squared_error=sum_squared_error,
            sum_target=sum_target,
            sum_squared_target=sum_squared_target,
        )

    def compute(self) -> Array:
        return _explained_variance_compute(
            self.num_obs, self.sum_error, self.sum_squared_error, self.sum_target, self.sum_squared_target,
            self.multioutput,
        )


class R2Score(Metric):
    """R2 Score.

    Example:
        >>> import jax.numpy as jnp
        >>> from metrics_tpu import R2Score
        >>> target = jnp.array([3.0, -0.5, 2.0, 7.0])
        >>> preds = jnp.array([2.5, 0.0, 2.0, 8.0])
        >>> metric = R2Score()
        >>> metric.update(preds, target)
        >>> metric.compute()
        Array(0.94860816, dtype=float32)
    """

    is_differentiable = True
    higher_is_better = True
    full_state_update = False

    def __init__(self, num_outputs: int = 1, adjusted: int = 0, multioutput: str = "uniform_average", **kwargs: Any) -> None:
        super().__init__(**kwargs)
        self.num_outputs = num_outputs
        if adjusted < 0 or not isinstance(adjusted, int):
            raise ValueError("`adjusted` parameter should be an integer larger or equal to 0.")
        self.adjusted = adjusted
        allowed_multioutput = ("raw_values", "uniform_average", "variance_weighted")
        if multioutput not in allowed_multioutput:
            raise ValueError(f"Invalid input to argument `multioutput`. Choose one of the following: {allowed_multioutput}")
        self.multioutput = multioutput
        shape = (num_outputs,) if num_outputs > 1 else ()
        self.add_state("sum_squared_error", zero_state(shape, jnp.float32), dist_reduce_fx="sum")
        self.add_state("sum_error", zero_state(shape, jnp.float32), dist_reduce_fx="sum")
        self.add_state("residual", zero_state(shape, jnp.float32), dist_reduce_fx="sum")
        self.add_state("total", zero_state((), jnp.float32), dist_reduce_fx="sum")

    def update(self, preds: Array, target: Array) -> None:
        sum_squared_obs, sum_obs, residual, num_obs = _r2_score_update(preds, target)
        self._accumulate(
            sum_squared_error=sum_squared_obs,
            sum_error=sum_obs,
            residual=residual,
            total=np.float32(num_obs),
        )

    def compute(self) -> Array:
        return _r2_score_compute(
            self.sum_squared_error, self.sum_error, self.residual, self.total, self.adjusted, self.multioutput
        )
