"""Regression module metrics (SURVEY §2.5, reference src/torchmetrics/regression/)."""

from metrics_tpu.regression.basic import (
    LogCoshError,
    MeanAbsoluteError,
    MeanAbsolutePercentageError,
    MeanSquaredError,
    MeanSquaredLogError,
    SymmetricMeanAbsolutePercentageError,
    WeightedMeanAbsolutePercentageError,
)
from metrics_tpu.regression.misc import (
    CosineSimilarity,
    KendallRankCorrCoef,
    KLDivergence,
    SpearmanCorrCoef,
    TweedieDevianceScore,
)
from metrics_tpu.regression.moments import (
    ConcordanceCorrCoef,
    ExplainedVariance,
    PearsonCorrCoef,
    R2Score,
)

__all__ = [
    "ConcordanceCorrCoef",
    "CosineSimilarity",
    "ExplainedVariance",
    "KendallRankCorrCoef",
    "KLDivergence",
    "LogCoshError",
    "MeanAbsoluteError",
    "MeanAbsolutePercentageError",
    "MeanSquaredError",
    "MeanSquaredLogError",
    "PearsonCorrCoef",
    "R2Score",
    "SpearmanCorrCoef",
    "SymmetricMeanAbsolutePercentageError",
    "TweedieDevianceScore",
    "WeightedMeanAbsolutePercentageError",
]
