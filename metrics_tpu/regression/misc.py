"""Remaining regression module metrics (reference src/torchmetrics/regression/
{cosine_similarity,kl_divergence,tweedie_deviance,kendall,spearman}.py)."""

from __future__ import annotations

from typing import Any, Optional

import jax.numpy as jnp
from jax import Array

from metrics_tpu.functional.regression.misc import (
    _cosine_similarity_compute,
    _cosine_similarity_update,
    _kendall_tau_compute,
    _kld_compute,
    _kld_update,
    _spearman_corrcoef_compute,
    _tweedie_deviance_score_compute,
    _tweedie_deviance_score_update,
)
from metrics_tpu.metric import Metric, zero_state
from metrics_tpu.utils.checks import _check_same_shape
from metrics_tpu.utils.data import dim_zero_cat


class CosineSimilarity(Metric):
    """Cosine Similarity.

    Example:
        >>> import jax.numpy as jnp
        >>> from metrics_tpu import CosineSimilarity
        >>> target = jnp.array([[3.0, 4.0], [0.0, 1.0]])
        >>> preds = jnp.array([[3.0, 4.0], [1.0, 0.0]])
        >>> metric = CosineSimilarity()
        >>> metric.update(preds, target)
        >>> metric.compute()
        Array(1., dtype=float32)
    """

    is_differentiable = True
    higher_is_better = True
    full_state_update = False

    def __init__(self, reduction: str = "sum", **kwargs: Any) -> None:
        super().__init__(**kwargs)
        allowed_reduction = ("sum", "mean", "none", None)
        if reduction not in allowed_reduction:
            raise ValueError(f"Expected argument `reduction` to be one of {allowed_reduction} but got {reduction}")
        self.reduction = reduction
        self.add_state("preds", [], dist_reduce_fx="cat")
        self.add_state("target", [], dist_reduce_fx="cat")

    def update(self, preds: Array, target: Array) -> None:
        preds, target = _cosine_similarity_update(preds, target)
        self.preds.append(preds)
        self.target.append(target)

    def compute(self) -> Array:
        preds = dim_zero_cat(self.preds)
        target = dim_zero_cat(self.target)
        return _cosine_similarity_compute(preds, target, self.reduction)


class KLDivergence(Metric):
    """KL Divergence.

    Example:
        >>> import jax.numpy as jnp
        >>> from metrics_tpu import KLDivergence
        >>> p = jnp.array([[0.36, 0.48, 0.16]])
        >>> q = jnp.array([[1/3, 1/3, 1/3]])
        >>> metric = KLDivergence()
        >>> metric.update(p, q)
        >>> round(float(metric.compute()), 4)
        0.0853
    """

    is_differentiable = True
    higher_is_better = False
    full_state_update = False

    def __init__(self, log_prob: bool = False, reduction: Optional[str] = "mean", **kwargs: Any) -> None:
        super().__init__(**kwargs)
        if not isinstance(log_prob, bool):
            raise TypeError(f"Expected argument `log_prob` to be bool but got {log_prob}")
        self.log_prob = log_prob
        allowed_reduction = ("mean", "sum", "none", None)
        if reduction not in allowed_reduction:
            raise ValueError(f"Expected argument `reduction` to be one of {allowed_reduction} but got {reduction}")
        self.reduction = reduction

        if self.reduction in ("mean", "sum"):
            self.add_state("measures", zero_state((), jnp.float32), dist_reduce_fx="sum")
        else:
            self.add_state("measures", [], dist_reduce_fx="cat")
        self.add_state("total", zero_state((), jnp.float32), dist_reduce_fx="sum")

    def update(self, p: Array, q: Array) -> None:
        measures, total = _kld_update(p, q, self.log_prob)
        if self.reduction is None or self.reduction == "none":
            self.measures.append(measures)
        else:
            self.measures = self.measures + jnp.sum(measures)
        self.total = self.total + total

    def compute(self) -> Array:
        measures = dim_zero_cat(self.measures) if isinstance(self.measures, list) else self.measures
        if self.reduction in ("mean",):
            return measures / self.total
        if self.reduction == "sum":
            return measures
        return measures


class TweedieDevianceScore(Metric):
    """Tweedie Deviance Score.

    Example:
        >>> import jax.numpy as jnp
        >>> from metrics_tpu import TweedieDevianceScore
        >>> target = jnp.array([3.0, -0.5, 2.0, 7.0])
        >>> preds = jnp.array([2.5, 0.0, 2.0, 8.0])
        >>> metric = TweedieDevianceScore()
        >>> metric.update(preds, target)
        >>> metric.compute()
        Array(0.375, dtype=float32)
    """

    is_differentiable = True
    higher_is_better = False
    full_state_update = False

    def __init__(self, power: float = 0.0, **kwargs: Any) -> None:
        super().__init__(**kwargs)
        if 0 < power < 1:
            raise ValueError(f"Deviance Score is not defined for power={power}.")
        self.power = power
        self.add_state("sum_deviance_score", zero_state((), jnp.float32), dist_reduce_fx="sum")
        self.add_state("num_observations", zero_state((), jnp.float32), dist_reduce_fx="sum")

    def update(self, preds: Array, target: Array) -> None:
        sum_deviance_score, num_observations = _tweedie_deviance_score_update(preds, target, self.power)
        self.sum_deviance_score = self.sum_deviance_score + sum_deviance_score
        self.num_observations = self.num_observations + num_observations

    def compute(self) -> Array:
        return _tweedie_deviance_score_compute(self.sum_deviance_score, self.num_observations)


class SpearmanCorrCoef(Metric):
    """Spearman Corr Coef.

    Example:
        >>> import jax.numpy as jnp
        >>> from metrics_tpu import SpearmanCorrCoef
        >>> target = jnp.array([3.0, -0.5, 2.0, 7.0])
        >>> preds = jnp.array([2.5, 0.0, 2.0, 8.0])
        >>> metric = SpearmanCorrCoef()
        >>> metric.update(preds, target)
        >>> float(metric.compute())  # doctest: +ELLIPSIS
        0.999...
    """

    is_differentiable = False
    higher_is_better = True
    full_state_update = False
    _host_compute = True  # rank transform is sort-based over the full sample

    def __init__(self, num_outputs: int = 1, **kwargs: Any) -> None:
        super().__init__(**kwargs)
        if not (isinstance(num_outputs, int) and num_outputs > 0):
            raise ValueError("Expected argument `num_outputs` to be an int larger than 0")
        self.num_outputs = num_outputs
        self.add_state("preds", [], dist_reduce_fx="cat")
        self.add_state("target", [], dist_reduce_fx="cat")

    def update(self, preds: Array, target: Array) -> None:
        _check_same_shape(preds, target)
        if not jnp.issubdtype(preds.dtype, jnp.floating) or not jnp.issubdtype(target.dtype, jnp.floating):
            raise TypeError("Expected `preds` and `target` both to be floating point tensors")
        self.preds.append(preds.astype(jnp.float32))
        self.target.append(target.astype(jnp.float32))

    def compute(self) -> Array:
        preds = dim_zero_cat(self.preds)
        target = dim_zero_cat(self.target)
        return _spearman_corrcoef_compute(preds, target)


class KendallRankCorrCoef(Metric):
    """Kendall Rank Corr Coef.

    Example:
        >>> import jax.numpy as jnp
        >>> from metrics_tpu import KendallRankCorrCoef
        >>> target = jnp.array([3.0, -0.5, 2.0, 7.0])
        >>> preds = jnp.array([2.5, 0.0, 2.0, 8.0])
        >>> metric = KendallRankCorrCoef()
        >>> metric.update(preds, target)
        >>> float(metric.compute())
        1.0
    """

    is_differentiable = False
    higher_is_better = None
    full_state_update = True
    _host_compute = True

    def __init__(
        self,
        variant: str = "b",
        t_test: bool = False,
        alternative: Optional[str] = "two-sided",
        num_outputs: int = 1,
        **kwargs: Any,
    ) -> None:
        super().__init__(**kwargs)
        if variant not in ("a", "b", "c"):
            raise ValueError(f"Argument `variant` is expected to be one of `['a', 'b', 'c']`, but got {variant!r}")
        if not isinstance(t_test, bool):
            raise ValueError(f"Argument `t_test` is expected to be of a type `bool`, but got {t_test!r}")
        self.variant = variant
        self.t_test = t_test
        self.alternative = alternative
        self.num_outputs = num_outputs
        self.add_state("preds", [], dist_reduce_fx="cat")
        self.add_state("target", [], dist_reduce_fx="cat")

    def update(self, preds: Array, target: Array) -> None:
        _check_same_shape(preds, target)
        self.preds.append(jnp.asarray(preds, dtype=jnp.float32))
        self.target.append(jnp.asarray(target, dtype=jnp.float32))

    def compute(self) -> Array:
        from metrics_tpu.functional.regression.misc import kendall_rank_corrcoef

        preds = dim_zero_cat(self.preds)
        target = dim_zero_cat(self.target)
        return kendall_rank_corrcoef(preds, target, self.variant, self.t_test, self.alternative)
