"""Error-sum regression module metrics (reference src/torchmetrics/regression/{mae,mse,
mape,symmetric_mape,wmape,log_mse,log_cosh}.py): two sum states, psum-mergeable."""

from __future__ import annotations

from typing import Any

import jax.numpy as jnp
import numpy as np
from jax import Array

from metrics_tpu.functional.regression.basic import (
    _log_cosh_error_compute,
    _log_cosh_error_update,
    _mean_absolute_error_compute,
    _mean_absolute_error_update,
    _mean_absolute_percentage_error_compute,
    _mean_absolute_percentage_error_update,
    _mean_squared_error_compute,
    _mean_squared_error_update,
    _mean_squared_log_error_update,
    _symmetric_mean_absolute_percentage_error_update,
    _weighted_mean_absolute_percentage_error_compute,
    _weighted_mean_absolute_percentage_error_update,
)
from metrics_tpu.metric import Metric, zero_state


class MeanAbsoluteError(Metric):
    """Mean Absolute Error.

    Example:
        >>> import jax.numpy as jnp
        >>> from metrics_tpu import MeanAbsoluteError
        >>> target = jnp.array([3.0, -0.5, 2.0, 7.0])
        >>> preds = jnp.array([2.5, 0.0, 2.0, 8.0])
        >>> metric = MeanAbsoluteError()
        >>> metric.update(preds, target)
        >>> metric.compute()
        Array(0.5, dtype=float32)
    """

    is_differentiable = True
    higher_is_better = False
    full_state_update = False

    def __init__(self, **kwargs: Any) -> None:
        super().__init__(**kwargs)
        self.add_state("sum_abs_error", zero_state((), jnp.float32), dist_reduce_fx="sum")
        self.add_state("total", zero_state((), jnp.float32), dist_reduce_fx="sum")

    def update(self, preds: Array, target: Array) -> None:
        sum_abs_error, num_obs = _mean_absolute_error_update(preds, target)
        self._accumulate(sum_abs_error=sum_abs_error, total=np.float32(num_obs))

    def compute(self) -> Array:
        return _mean_absolute_error_compute(self.sum_abs_error, self.total)


class MeanSquaredError(Metric):
    """Mean Squared Error.

    Example:
        >>> import jax.numpy as jnp
        >>> from metrics_tpu import MeanSquaredError
        >>> target = jnp.array([3.0, -0.5, 2.0, 7.0])
        >>> preds = jnp.array([2.5, 0.0, 2.0, 8.0])
        >>> metric = MeanSquaredError()
        >>> metric.update(preds, target)
        >>> metric.compute()
        Array(0.375, dtype=float32)
    """

    is_differentiable = True
    higher_is_better = False
    full_state_update = False

    def __init__(self, squared: bool = True, num_outputs: int = 1, **kwargs: Any) -> None:
        super().__init__(**kwargs)
        if not isinstance(squared, bool):
            raise ValueError(f"Expected argument `squared` to be a boolean but got {squared}")
        self.squared = squared
        if not (isinstance(num_outputs, int) and num_outputs > 0):
            raise ValueError(f"Expected num_outputs to be a positive integer but got {num_outputs}")
        self.num_outputs = num_outputs
        shape = () if num_outputs == 1 else (num_outputs,)
        self.add_state("sum_squared_error", zero_state(shape, jnp.float32), dist_reduce_fx="sum")
        self.add_state("total", zero_state((), jnp.float32), dist_reduce_fx="sum")

    def update(self, preds: Array, target: Array) -> None:
        sum_squared_error, num_obs = _mean_squared_error_update(preds, target, self.num_outputs)
        self._accumulate(sum_squared_error=sum_squared_error, total=np.float32(num_obs))

    def compute(self) -> Array:
        return _mean_squared_error_compute(self.sum_squared_error, self.total, self.squared)


class MeanAbsolutePercentageError(Metric):
    """Mean Absolute Percentage Error.

    Example:
        >>> import jax.numpy as jnp
        >>> from metrics_tpu import MeanAbsolutePercentageError
        >>> target = jnp.array([3.0, -0.5, 2.0, 7.0])
        >>> preds = jnp.array([2.5, 0.0, 2.0, 8.0])
        >>> metric = MeanAbsolutePercentageError()
        >>> metric.update(preds, target)
        >>> metric.compute()
        Array(0.32738096, dtype=float32)
    """

    is_differentiable = True
    higher_is_better = False
    full_state_update = False

    def __init__(self, **kwargs: Any) -> None:
        super().__init__(**kwargs)
        self.add_state("sum_abs_per_error", zero_state((), jnp.float32), dist_reduce_fx="sum")
        self.add_state("total", zero_state((), jnp.float32), dist_reduce_fx="sum")

    def update(self, preds: Array, target: Array) -> None:
        sum_abs_per_error, num_obs = _mean_absolute_percentage_error_update(preds, target)
        self._accumulate(sum_abs_per_error=sum_abs_per_error, total=np.float32(num_obs))

    def compute(self) -> Array:
        return _mean_absolute_percentage_error_compute(self.sum_abs_per_error, self.total)


class SymmetricMeanAbsolutePercentageError(Metric):
    """Symmetric Mean Absolute Percentage Error.

    Example:
        >>> import jax.numpy as jnp
        >>> from metrics_tpu import SymmetricMeanAbsolutePercentageError
        >>> target = jnp.array([3.0, -0.5, 2.0, 7.0])
        >>> preds = jnp.array([2.5, 0.0, 2.0, 8.0])
        >>> metric = SymmetricMeanAbsolutePercentageError()
        >>> metric.update(preds, target)
        >>> metric.compute()
        Array(0.5787879, dtype=float32)
    """

    is_differentiable = True
    higher_is_better = False
    full_state_update = False

    def __init__(self, **kwargs: Any) -> None:
        super().__init__(**kwargs)
        self.add_state("sum_abs_per_error", zero_state((), jnp.float32), dist_reduce_fx="sum")
        self.add_state("total", zero_state((), jnp.float32), dist_reduce_fx="sum")

    def update(self, preds: Array, target: Array) -> None:
        sum_abs_per_error, num_obs = _symmetric_mean_absolute_percentage_error_update(preds, target)
        self._accumulate(sum_abs_per_error=sum_abs_per_error, total=np.float32(num_obs))

    def compute(self) -> Array:
        return self.sum_abs_per_error / self.total


class WeightedMeanAbsolutePercentageError(Metric):
    """Weighted Mean Absolute Percentage Error.

    Example:
        >>> import jax.numpy as jnp
        >>> from metrics_tpu import WeightedMeanAbsolutePercentageError
        >>> target = jnp.array([3.0, -0.5, 2.0, 7.0])
        >>> preds = jnp.array([2.5, 0.0, 2.0, 8.0])
        >>> metric = WeightedMeanAbsolutePercentageError()
        >>> metric.update(preds, target)
        >>> metric.compute()
        Array(0.16, dtype=float32)
    """

    is_differentiable = True
    higher_is_better = False
    full_state_update = False

    def __init__(self, **kwargs: Any) -> None:
        super().__init__(**kwargs)
        self.add_state("sum_abs_error", zero_state((), jnp.float32), dist_reduce_fx="sum")
        self.add_state("sum_scale", zero_state((), jnp.float32), dist_reduce_fx="sum")

    def update(self, preds: Array, target: Array) -> None:
        sum_abs_error, sum_scale = _weighted_mean_absolute_percentage_error_update(preds, target)
        self._accumulate(sum_abs_error=sum_abs_error, sum_scale=sum_scale)

    def compute(self) -> Array:
        return _weighted_mean_absolute_percentage_error_compute(self.sum_abs_error, self.sum_scale)


class MeanSquaredLogError(Metric):
    """Mean Squared Log Error.

    Example:
        >>> import jax.numpy as jnp
        >>> from metrics_tpu import MeanSquaredLogError
        >>> target = jnp.array([3.0, 5.0, 2.5, 7.0])
        >>> preds = jnp.array([2.5, 5.0, 4.0, 8.0])
        >>> metric = MeanSquaredLogError()
        >>> metric.update(preds, target)
        >>> round(float(metric.compute()), 4)
        0.0397
    """

    is_differentiable = True
    higher_is_better = False
    full_state_update = False

    def __init__(self, **kwargs: Any) -> None:
        super().__init__(**kwargs)
        self.add_state("sum_squared_log_error", zero_state((), jnp.float32), dist_reduce_fx="sum")
        self.add_state("total", zero_state((), jnp.float32), dist_reduce_fx="sum")

    def update(self, preds: Array, target: Array) -> None:
        sum_squared_log_error, num_obs = _mean_squared_log_error_update(preds, target)
        self._accumulate(sum_squared_log_error=sum_squared_log_error, total=np.float32(num_obs))

    def compute(self) -> Array:
        return self.sum_squared_log_error / self.total


class LogCoshError(Metric):
    """Log Cosh Error.

    Example:
        >>> import jax.numpy as jnp
        >>> from metrics_tpu import LogCoshError
        >>> target = jnp.array([3.0, -0.5, 2.0, 7.0])
        >>> preds = jnp.array([2.5, 0.0, 2.0, 8.0])
        >>> metric = LogCoshError()
        >>> metric.update(preds, target)
        >>> round(float(metric.compute()), 4)
        0.1685
    """

    is_differentiable = True
    higher_is_better = False
    full_state_update = False

    def __init__(self, num_outputs: int = 1, **kwargs: Any) -> None:
        super().__init__(**kwargs)
        if not (isinstance(num_outputs, int) and num_outputs > 0):
            raise ValueError(f"Expected num_outputs to be a positive integer but got {num_outputs}")
        self.num_outputs = num_outputs
        self.add_state("sum_log_cosh_error", zero_state((num_outputs,), jnp.float32), dist_reduce_fx="sum")
        self.add_state("total", zero_state((), jnp.float32), dist_reduce_fx="sum")

    def update(self, preds: Array, target: Array) -> None:
        sum_log_cosh_error, num_obs = _log_cosh_error_update(preds, target, self.num_outputs)
        self._accumulate(sum_log_cosh_error=sum_log_cosh_error, total=np.float32(num_obs))

    def compute(self) -> Array:
        return _log_cosh_error_compute(self.sum_log_cosh_error, self.total)
