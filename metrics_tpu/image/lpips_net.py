"""TPU-native LPIPS network (perceptual distance) in flax.

Replaces the reference's dependency on the torch ``lpips`` pip package
(src/torchmetrics/image/lpip.py:34) with a JAX implementation that runs inside the
metric's XLA graph. Architecture follows the published LPIPS v0.1 design (Zhang et
al. 2018): a frozen backbone feature stack (``alex`` / ``vgg`` / ``squeeze``),
channel-unit-normalised features per tap, squared differences, learned non-negative
1x1 linear heads per tap, spatial mean, sum over taps.

Weights: offline-friendly, same protocol as :mod:`metrics_tpu.image.inception_net` —
``load_params(path)`` reads a flat ``.npz`` written by ``save_params`` (keys are
``/``-joined pytree paths). When no weight file is given and none is found at
``$METRICS_TPU_LPIPS_WEIGHTS``, construction raises unless the caller explicitly
opts into seeded random initialisation (``allow_random_weights=True``) —
self-consistent for tests and relative comparisons, NOT comparable to published
LPIPS numbers. ``tools/convert_lpips_weights.py`` produces the weight file from
the torch-ecosystem checkpoints.

Layout note: inputs follow the reference convention (N, C, H, W) in [-1, 1]
(``normalize=True`` on the metric maps [0,1] inputs); internally NHWC, the
TPU-native convolution layout.
"""

from __future__ import annotations

import os
from typing import Any, Dict, Sequence, Tuple

import flax.linen as nn
import jax
import jax.numpy as jnp
import numpy as np
from jax import Array

from metrics_tpu.utils.prints import rank_zero_warn

_WEIGHTS_ENV = "METRICS_TPU_LPIPS_WEIGHTS"

# ImageNet scaling layer constants (lpips ScalingLayer)
_SHIFT = np.array([-0.030, -0.088, -0.188], dtype=np.float32)
_SCALE = np.array([0.458, 0.448, 0.450], dtype=np.float32)

# tap channel widths per backbone (lpips v0.1)
NET_CHANNELS = {
    "alex": (64, 192, 384, 256, 256),
    "vgg": (64, 128, 256, 512, 512),
    "squeeze": (64, 128, 256, 384, 384, 512, 512),
}


def _max_pool(x: Array, window: int = 3, stride: int = 2) -> Array:
    return nn.max_pool(x, (window, window), strides=(stride, stride))


def _max_pool_ceil(x: Array, window: int = 3, stride: int = 2) -> Array:
    """Max pool with torch ``ceil_mode=True`` semantics (squeezenet1_1 pools).

    Torch's ceil mode keeps a final window that hangs off the right/bottom edge;
    emulate by -inf padding up to the ceil output size before a VALID pool.
    """
    h, w = x.shape[1], x.shape[2]
    out_h = -(-(h - window) // stride) + 1  # ceil division
    out_w = -(-(w - window) // stride) + 1
    pad_h = max((out_h - 1) * stride + window - h, 0)
    pad_w = max((out_w - 1) * stride + window - w, 0)
    if pad_h or pad_w:
        x = jnp.pad(x, ((0, 0), (0, pad_h), (0, pad_w), (0, 0)), constant_values=-jnp.inf)
    return nn.max_pool(x, (window, window), strides=(stride, stride))


class AlexFeatures(nn.Module):
    """AlexNet feature stack, taps after each of the 5 ReLUs."""

    @nn.compact
    def __call__(self, x: Array) -> Tuple[Array, ...]:
        taps = []
        x = nn.relu(nn.Conv(64, (11, 11), (4, 4), padding=((2, 2), (2, 2)), name="conv1")(x))
        taps.append(x)
        x = _max_pool(x)
        x = nn.relu(nn.Conv(192, (5, 5), padding=((2, 2), (2, 2)), name="conv2")(x))
        taps.append(x)
        x = _max_pool(x)
        x = nn.relu(nn.Conv(384, (3, 3), padding=((1, 1), (1, 1)), name="conv3")(x))
        taps.append(x)
        x = nn.relu(nn.Conv(256, (3, 3), padding=((1, 1), (1, 1)), name="conv4")(x))
        taps.append(x)
        x = nn.relu(nn.Conv(256, (3, 3), padding=((1, 1), (1, 1)), name="conv5")(x))
        taps.append(x)
        return tuple(taps)


class VGG16Features(nn.Module):
    """VGG16 stack, taps after relu1_2, relu2_2, relu3_3, relu4_3, relu5_3."""

    @nn.compact
    def __call__(self, x: Array) -> Tuple[Array, ...]:
        taps = []
        cfg = [(64, 2), (128, 2), (256, 3), (512, 3), (512, 3)]
        for stage, (width, n_convs) in enumerate(cfg, start=1):
            for i in range(1, n_convs + 1):
                x = nn.relu(
                    nn.Conv(width, (3, 3), padding=((1, 1), (1, 1)), name=f"conv{stage}_{i}")(x)
                )
            taps.append(x)
            if stage < 5:
                x = nn.max_pool(x, (2, 2), strides=(2, 2))
        return tuple(taps)


class Fire(nn.Module):
    """SqueezeNet fire module: squeeze 1x1 → expand 1x1 + 3x3, concat."""

    squeeze: int
    expand: int

    @nn.compact
    def __call__(self, x: Array) -> Array:
        s = nn.relu(nn.Conv(self.squeeze, (1, 1), name="squeeze")(x))
        e1 = nn.relu(nn.Conv(self.expand, (1, 1), name="expand1x1")(s))
        e3 = nn.relu(nn.Conv(self.expand, (3, 3), padding=((1, 1), (1, 1)), name="expand3x3")(s))
        return jnp.concatenate([e1, e3], axis=-1)


class SqueezeFeatures(nn.Module):
    """SqueezeNet 1.1 stack with the 7 LPIPS taps."""

    @nn.compact
    def __call__(self, x: Array) -> Tuple[Array, ...]:
        taps = []
        x = nn.relu(nn.Conv(64, (3, 3), (2, 2), padding="VALID", name="conv1")(x))
        taps.append(x)  # 64
        x = _max_pool_ceil(x)  # torchvision squeezenet1_1 pools use ceil_mode=True
        x = Fire(16, 64, name="fire2")(x)
        x = Fire(16, 64, name="fire3")(x)
        taps.append(x)  # 128
        x = _max_pool_ceil(x)
        x = Fire(32, 128, name="fire4")(x)
        x = Fire(32, 128, name="fire5")(x)
        taps.append(x)  # 256
        x = _max_pool_ceil(x)
        x = Fire(48, 192, name="fire6")(x)
        taps.append(x)  # 384
        x = Fire(48, 192, name="fire7")(x)
        taps.append(x)  # 384
        x = Fire(64, 256, name="fire8")(x)
        taps.append(x)  # 512
        x = Fire(64, 256, name="fire9")(x)
        taps.append(x)  # 512
        return tuple(taps)


_BACKBONES = {"alex": AlexFeatures, "vgg": VGG16Features, "squeeze": SqueezeFeatures}


class LPIPSNet(nn.Module):
    """Backbone + unit-normalise + squared diff + learned 1x1 heads + spatial mean."""

    net_type: str = "alex"

    @nn.compact
    def __call__(self, img0: Array, img1: Array) -> Array:
        # (N, C, H, W) in [-1, 1] → scaled NHWC
        def prep(x):
            x = jnp.transpose(x, (0, 2, 3, 1)).astype(jnp.float32)
            return (x - _SHIFT) / _SCALE

        backbone = _BACKBONES[self.net_type](name="features")
        taps0 = backbone(prep(img0))
        # flax reuses the same params for the second call inside one module scope
        taps1 = backbone(prep(img1))

        total = jnp.zeros((img0.shape[0],), jnp.float32)
        for i, (f0, f1) in enumerate(zip(taps0, taps1)):
            f0 = f0 / jnp.maximum(jnp.linalg.norm(f0, axis=-1, keepdims=True), 1e-10)
            f1 = f1 / jnp.maximum(jnp.linalg.norm(f1, axis=-1, keepdims=True), 1e-10)
            diff = (f0 - f1) ** 2
            # learned non-negative linear head (lpips NetLinLayer): 1x1 conv, no bias
            w = self.param(f"lin{i}", nn.initializers.uniform(scale=0.1), (diff.shape[-1], 1), jnp.float32)
            contrib = diff @ jnp.abs(w)  # (N, H, W, 1); abs keeps the head a distance
            total = total + jnp.mean(contrib, axis=(1, 2, 3))
        return total


# ------------------------------------------------------------------ params io


from metrics_tpu.utils.params_io import load_params, save_params  # noqa: E402,F401  (shared npz protocol)


def init_params(net_type: str = "alex", seed: int = 0, image_size: int = 64) -> Dict:
    model = LPIPSNet(net_type=net_type)
    dummy = jnp.zeros((1, 3, image_size, image_size), jnp.float32)
    return model.init(jax.random.PRNGKey(seed), dummy, dummy)


def make_distance_fn(
    net_type: str = "alex",
    weights_path: str | None = None,
    seed: int = 0,
    allow_random_weights: bool = False,
):
    """Build ``(img0, img1) -> (N,)`` perceptual distances on the JAX net.

    Weight resolution: explicit ``weights_path`` → ``$METRICS_TPU_LPIPS_WEIGHTS`` →
    error, unless ``allow_random_weights=True`` opts into seeded random
    initialisation (self-consistent for tests/relative comparisons, NOT comparable
    to published LPIPS numbers — random weights must never reach an eval dashboard
    silently).
    """
    if net_type not in _BACKBONES:
        raise ValueError(f"Argument `net_type` must be one of {tuple(_BACKBONES)}, but got {net_type}.")
    path = weights_path or os.environ.get(_WEIGHTS_ENV)
    model = LPIPSNet(net_type=net_type)
    if path:
        variables = load_params(path)
        # fail fast with a clear message when the file is for a different net_type —
        # otherwise flax raises an opaque kernel-shape error deep in apply().
        # eval_shape gives the expected tree/shapes without running any init FLOPs.
        dummy = jnp.zeros((1, 3, 16, 16), jnp.float32)
        expected = jax.eval_shape(model.init, jax.random.PRNGKey(0), dummy, dummy)
        if jax.tree_util.tree_structure(variables) != jax.tree_util.tree_structure(expected) or any(
            np.asarray(a).shape != b.shape
            for a, b in zip(jax.tree_util.tree_leaves(variables), jax.tree_util.tree_leaves(expected))
        ):
            raise ValueError(
                f"LPIPS weights at {path!r} do not match net_type={net_type!r}"
                " (wrong backbone or corrupted file)."
            )
    elif allow_random_weights:
        rank_zero_warn(
            "LPIPS is using seeded RANDOM weights (allow_random_weights=True, no weights file)."
            " Distances are self-consistent but NOT comparable to published LPIPS numbers."
        )
        variables = init_params(net_type, seed=seed)
    else:
        raise FileNotFoundError(
            "No LPIPS weights available: pass `weights_path=`, set $METRICS_TPU_LPIPS_WEIGHTS,"
            " or opt into random initialisation with `allow_random_weights=True`"
            " (tests/relative comparisons only)."
        )

    def distance(img0: Array, img1: Array) -> Array:
        return model.apply(variables, jnp.asarray(img0), jnp.asarray(img1))

    return distance
