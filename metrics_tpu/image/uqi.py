"""UQI module metric.

Reference parity: src/torchmetrics/image/uqi.py. TPU-native divergence: the reference
keeps O(N) ``preds``/``target`` cat-lists and recomputes at the end; per-image UQI maps
are independent, so for mean/sum reductions this accumulates (score-sum, pixel-count)
scalars instead — constant memory, psum-sync, identical value.
"""

from __future__ import annotations

from typing import Any, Optional, Sequence

import jax.numpy as jnp
from jax import Array

from metrics_tpu.functional.image.uqi import _uqi_compute, _uqi_update
from metrics_tpu.metric import Metric, zero_state
from metrics_tpu.utils.data import dim_zero_cat
from metrics_tpu.utils.distributed import reduce


class UniversalImageQualityIndex(Metric):
    """Universal Image Quality Index.

    Example:
        >>> import jax, jax.numpy as jnp
        >>> key1, key2 = jax.random.split(jax.random.PRNGKey(0))
        >>> preds = jax.random.uniform(key1, (2, 3, 16, 16))
        >>> target = preds * 0.75 + jax.random.uniform(key2, (2, 3, 16, 16)) * 0.25
        >>> from metrics_tpu.image import UniversalImageQualityIndex
        >>> metric = UniversalImageQualityIndex()
        >>> metric.update(preds, target)
        >>> metric.compute()
        Array(0.9225343, dtype=float32)
    """
    is_differentiable = True
    higher_is_better = True
    full_state_update = False

    def __init__(
        self,
        kernel_size: Sequence[int] = (11, 11),
        sigma: Sequence[float] = (1.5, 1.5),
        reduction: Optional[str] = "elementwise_mean",
        **kwargs: Any,
    ) -> None:
        super().__init__(**kwargs)
        self.kernel_size = kernel_size
        self.sigma = sigma
        self.reduction = reduction
        if reduction in ("elementwise_mean", "sum"):
            self.add_state("score_sum", zero_state(()), dist_reduce_fx="sum")
            self.add_state("total", zero_state(()), dist_reduce_fx="sum")
        else:
            self.add_state("scores", [], dist_reduce_fx="cat")

    def update(self, preds: Array, target: Array) -> None:
        preds, target = _uqi_update(preds, target)
        idx = _uqi_compute(preds, target, self.kernel_size, self.sigma, reduction="none")
        if self.reduction in ("elementwise_mean", "sum"):
            self.score_sum = self.score_sum + jnp.sum(idx)
            self.total = self.total + idx.size
        else:
            self.scores.append(idx)

    def compute(self) -> Array:
        if self.reduction == "elementwise_mean":
            return self.score_sum / self.total
        if self.reduction == "sum":
            return self.score_sum
        return reduce(dim_zero_cat(self.scores), self.reduction)
