"""ERGAS module metric.

Reference parity: src/torchmetrics/image/ergas.py. TPU-native divergence: per-image
scores are independent, so (score-sum, image-count) scalars replace the reference's
O(N) cat-list states for mean/sum reductions — identical value, constant memory.
"""

from __future__ import annotations

from typing import Any, Optional, Union

import jax.numpy as jnp
from jax import Array

from metrics_tpu.functional.image.ergas import _ergas_compute, _ergas_update
from metrics_tpu.metric import Metric, zero_state
from metrics_tpu.utils.data import dim_zero_cat
from metrics_tpu.utils.distributed import reduce


class ErrorRelativeGlobalDimensionlessSynthesis(Metric):
    """Error Relative Global Dimensionless Synthesis.

    Example:
        >>> import jax, jax.numpy as jnp
        >>> key1, key2 = jax.random.split(jax.random.PRNGKey(0))
        >>> preds = jax.random.uniform(key1, (2, 3, 16, 16))
        >>> target = preds * 0.75 + jax.random.uniform(key2, (2, 3, 16, 16)) * 0.25
        >>> from metrics_tpu.image import ErrorRelativeGlobalDimensionlessSynthesis
        >>> metric = ErrorRelativeGlobalDimensionlessSynthesis(ratio=4)
        >>> metric.update(preds, target)
        >>> metric.compute()
        Array(84.62497, dtype=float32)
    """
    is_differentiable = True
    higher_is_better = False
    full_state_update = False

    def __init__(
        self,
        ratio: Union[int, float] = 4,
        reduction: Optional[str] = "elementwise_mean",
        **kwargs: Any,
    ) -> None:
        super().__init__(**kwargs)
        self.ratio = ratio
        self.reduction = reduction
        if reduction in ("elementwise_mean", "sum"):
            self.add_state("score_sum", zero_state(()), dist_reduce_fx="sum")
            self.add_state("total", zero_state(()), dist_reduce_fx="sum")
        else:
            self.add_state("scores", [], dist_reduce_fx="cat")

    def update(self, preds: Array, target: Array) -> None:
        preds, target = _ergas_update(preds, target)
        score = _ergas_compute(preds, target, self.ratio, reduction="none")
        if self.reduction in ("elementwise_mean", "sum"):
            self.score_sum = self.score_sum + jnp.sum(score)
            self.total = self.total + score.size
        else:
            self.scores.append(score)

    def compute(self) -> Array:
        if self.reduction == "elementwise_mean":
            return self.score_sum / self.total
        if self.reduction == "sum":
            return self.score_sum
        return reduce(dim_zero_cat(self.scores), self.reduction)
