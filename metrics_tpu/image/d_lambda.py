"""Spectral Distortion Index (D_lambda) module metric.

Reference parity: src/torchmetrics/image/d_lambda.py (cat-list preds/target states
:84-85 — the cross-band UQI matrices must be computed over the union of all batches,
so state stays O(N) exactly like the reference).
"""

from __future__ import annotations

from typing import Any, Optional

from jax import Array

from metrics_tpu.functional.image.d_lambda import (
    _spectral_distortion_index_compute,
    _spectral_distortion_index_update,
)
from metrics_tpu.metric import Metric
from metrics_tpu.utils.data import dim_zero_cat


class SpectralDistortionIndex(Metric):
    """Spectral Distortion Index.

    Example:
        >>> import jax, jax.numpy as jnp
        >>> key1, key2 = jax.random.split(jax.random.PRNGKey(0))
        >>> preds = jax.random.uniform(key1, (2, 3, 16, 16))
        >>> target = preds * 0.75 + jax.random.uniform(key2, (2, 3, 16, 16)) * 0.25
        >>> from metrics_tpu.image import SpectralDistortionIndex
        >>> metric = SpectralDistortionIndex()
        >>> metric.update(preds, target)
        >>> metric.compute()
        Array(0.04102586, dtype=float32)
    """
    is_differentiable = True
    higher_is_better = False
    full_state_update = False
    _host_compute = False

    def __init__(self, p: int = 1, reduction: Optional[str] = "elementwise_mean", **kwargs: Any) -> None:
        super().__init__(**kwargs)
        if not isinstance(p, int) or p <= 0:
            raise ValueError(f"Expected `p` to be a positive integer. Got p: {p}.")
        self.p = p
        allowed_reduction = ("elementwise_mean", "sum", "none")
        if reduction not in allowed_reduction:
            raise ValueError(f"Expected argument `reduction` be one of {allowed_reduction} but got {reduction}")
        self.reduction = reduction
        self.add_state("preds", [], dist_reduce_fx="cat")
        self.add_state("target", [], dist_reduce_fx="cat")

    def update(self, preds: Array, target: Array) -> None:
        preds, target = _spectral_distortion_index_update(preds, target)
        self.preds.append(preds)
        self.target.append(target)

    def compute(self) -> Array:
        preds = dim_zero_cat(self.preds)
        target = dim_zero_cat(self.target)
        return _spectral_distortion_index_compute(preds, target, self.p, self.reduction)
