"""Total variation module metric.

Reference parity: src/torchmetrics/image/tv.py (sum states for mean/sum :71-74,
cat list for 'none').
"""

from __future__ import annotations

from typing import Any, Optional

import jax.numpy as jnp
from jax import Array

from metrics_tpu.functional.image.tv import _total_variation_compute, _total_variation_update
from metrics_tpu.metric import Metric, zero_state
from metrics_tpu.utils.data import dim_zero_cat


class TotalVariation(Metric):
    """Total Variation.

    Example:
        >>> import jax.numpy as jnp
        >>> from metrics_tpu import TotalVariation
        >>> img = jnp.array([[[[0.1, 0.2], [0.3, 0.4]]]])
        >>> metric = TotalVariation()
        >>> metric.update(img)
        >>> metric.compute()
        Array(0.6, dtype=float32)
    """

    is_differentiable = True
    higher_is_better = False
    full_state_update = False

    def __init__(self, reduction: Optional[str] = "sum", **kwargs: Any) -> None:
        super().__init__(**kwargs)
        if reduction is not None and reduction not in ("sum", "mean", "none"):
            raise ValueError("Expected argument `reduction` to either be 'sum', 'mean', 'none' or None")
        self.reduction = reduction

        if self.reduction is None or self.reduction == "none":
            self.add_state("score", [], dist_reduce_fx="cat")
        else:
            self.add_state("score", zero_state(()), dist_reduce_fx="sum")
        self.add_state("num_elements", zero_state((), dtype=jnp.int32), dist_reduce_fx="sum")

    def update(self, img: Array) -> None:
        score, num_elements = _total_variation_update(jnp.asarray(img))
        if self.reduction is None or self.reduction == "none":
            self.score.append(score)
        else:
            self.score = self.score + jnp.sum(score)
        self.num_elements = self.num_elements + num_elements

    def compute(self) -> Array:
        if self.reduction is None or self.reduction == "none":
            return dim_zero_cat(self.score)
        if self.reduction == "mean":
            return self.score / self.num_elements
        return self.score
