"""Learned Perceptual Image Patch Similarity (LPIPS).

Reference parity: src/torchmetrics/image/lpip.py (class
``LearnedPerceptualImagePatchSimilarity`` :34 wrapping the ``lpips`` pip package with
scalar sum states :136-137). The package dependency is import-gated identically; a
user-supplied callable ``(img1, img2) -> (N,)`` distance function is the TPU-native
alternative (e.g. a flax VGG/AlexNet port).
"""

from __future__ import annotations

from typing import Any, Callable, Optional

import jax.numpy as jnp
from jax import Array

from metrics_tpu.metric import Metric
from metrics_tpu.utils.imports import _LPIPS_AVAILABLE


class LearnedPerceptualImagePatchSimilarity(Metric):
    is_differentiable = True
    higher_is_better = False
    full_state_update = False

    sum_scores: Array
    total: Array

    def __init__(
        self,
        net_type: str = "alex",
        reduction: str = "mean",
        normalize: bool = False,
        distance_fn: Optional[Callable] = None,
        **kwargs: Any,
    ) -> None:
        super().__init__(**kwargs)
        if distance_fn is None:
            if not _LPIPS_AVAILABLE:
                raise ModuleNotFoundError(
                    "LPIPS metric requires that lpips is installed."
                    " Either install as `pip install torchmetrics[image]` or `pip install lpips`,"
                    " or pass a `distance_fn` callable computing per-image perceptual distances."
                )
            valid_net_type = ("vgg", "alex", "squeeze")
            if net_type not in valid_net_type:
                raise ValueError(f"Argument `net_type` must be one of {valid_net_type}, but got {net_type}.")
            import lpips  # pragma: no cover

            net = lpips.LPIPS(net=net_type)  # pragma: no cover
            distance_fn = lambda a, b: net(a, b).reshape(-1)  # noqa: E731  # pragma: no cover
        valid_reduction = ("mean", "sum")
        if reduction not in valid_reduction:
            raise ValueError(f"Argument `reduction` must be one of {valid_reduction}, but got {reduction}")
        if not isinstance(normalize, bool):
            raise ValueError(f"Argument `normalize` should be a bool but got {normalize}")
        self.distance_fn = distance_fn
        self.reduction = reduction
        self.normalize = normalize

        self.add_state("sum_scores", jnp.zeros((), dtype=jnp.float32), dist_reduce_fx="sum")
        self.add_state("total", jnp.zeros((), dtype=jnp.float32), dist_reduce_fx="sum")

    def update(self, img1: Array, img2: Array) -> None:
        img1 = jnp.asarray(img1)
        img2 = jnp.asarray(img2)
        if self.normalize:
            # [0,1] → [-1,1] expected by LPIPS nets
            img1 = 2 * img1 - 1
            img2 = 2 * img2 - 1
        loss = jnp.asarray(self.distance_fn(img1, img2)).reshape(-1).astype(jnp.float32)
        self.sum_scores = self.sum_scores + jnp.sum(loss)
        self.total = self.total + loss.shape[0]

    def compute(self) -> Array:
        if self.reduction == "mean":
            return self.sum_scores / self.total
        return self.sum_scores
