"""Learned Perceptual Image Patch Similarity (LPIPS).

Reference parity: src/torchmetrics/image/lpip.py (class
``LearnedPerceptualImagePatchSimilarity`` :34 wrapping the ``lpips`` pip package with
scalar sum states :136-137). TPU-native redesign: the default backbone is the JAX
LPIPS network in :mod:`metrics_tpu.image.lpips_net` (alex/vgg/squeeze feature stacks
+ learned linear heads, offline weight loading), which runs inside the metric's XLA
graph — no torch in the loop. A user-supplied callable ``(img1, img2) -> (N,)`` is
still accepted, and the torch ``lpips`` package remains available as an explicit
opt-in backend for bit-parity with the reference.
"""

from __future__ import annotations

from typing import Any, Callable, Optional

import jax.numpy as jnp
from jax import Array

from metrics_tpu.metric import Metric, zero_state
from metrics_tpu.utils.imports import _LPIPS_AVAILABLE


class LearnedPerceptualImagePatchSimilarity(Metric):
    """Learned Perceptual Image Patch Similarity over a JAX feature net.

    Example (requires converted LPIPS weights on disk; not executed offline):
        >>> import jax
        >>> from metrics_tpu.image import LearnedPerceptualImagePatchSimilarity
        >>> metric = LearnedPerceptualImagePatchSimilarity(net_type="alex")  # doctest: +SKIP
        >>> img1 = jax.random.uniform(jax.random.PRNGKey(0), (2, 3, 64, 64)) * 2 - 1  # doctest: +SKIP
        >>> img2 = jax.random.uniform(jax.random.PRNGKey(1), (2, 3, 64, 64)) * 2 - 1  # doctest: +SKIP
        >>> metric.update(img1, img2)  # doctest: +SKIP
        >>> metric.compute()  # doctest: +SKIP
        Array(0.3..., dtype=float32)
    """

    is_differentiable = True
    higher_is_better = False
    full_state_update = False

    sum_scores: Array
    total: Array

    def __init__(
        self,
        net_type: str = "alex",
        reduction: str = "mean",
        normalize: bool = False,
        distance_fn: Optional[Callable] = None,
        weights_path: Optional[str] = None,
        backend: str = "jax",
        allow_random_weights: bool = False,
        **kwargs: Any,
    ) -> None:
        super().__init__(**kwargs)
        valid_net_type = ("vgg", "alex", "squeeze")
        if net_type not in valid_net_type:
            raise ValueError(f"Argument `net_type` must be one of {valid_net_type}, but got {net_type}.")
        if backend not in ("jax", "lpips"):
            raise ValueError(f"Argument `backend` must be 'jax' or 'lpips', but got {backend}.")
        valid_reduction = ("mean", "sum")
        if reduction not in valid_reduction:
            raise ValueError(f"Argument `reduction` must be one of {valid_reduction}, but got {reduction}")
        if not isinstance(normalize, bool):
            raise ValueError(f"Argument `normalize` should be a bool but got {normalize}")
        if distance_fn is None:
            if backend == "lpips":
                if not _LPIPS_AVAILABLE:
                    raise ModuleNotFoundError(
                        "backend='lpips' requires the lpips package (`pip install lpips`);"
                        " the default backend='jax' needs no torch dependency."
                    )
                import lpips  # pragma: no cover
                import numpy as _np  # pragma: no cover
                import torch  # pragma: no cover

                net = lpips.LPIPS(net=net_type)  # pragma: no cover

                def distance_fn(a, b):  # pragma: no cover
                    # torch-side bridge: jax arrays → torch tensors → numpy distances.
                    # f32 cast: torch.from_numpy can't take ml_dtypes (bf16) arrays and
                    # the lpips net weights are float32.
                    ta = torch.from_numpy(_np.asarray(a, dtype=_np.float32))
                    tb = torch.from_numpy(_np.asarray(b, dtype=_np.float32))
                    with torch.no_grad():
                        return _np.asarray(net(ta, tb).reshape(-1))
            else:
                from metrics_tpu.image.lpips_net import make_distance_fn

                distance_fn = make_distance_fn(
                    net_type, weights_path=weights_path, allow_random_weights=allow_random_weights
                )
        self.distance_fn = distance_fn
        self.reduction = reduction
        self.normalize = normalize

        self.add_state("sum_scores", zero_state((), dtype=jnp.float32), dist_reduce_fx="sum")
        self.add_state("total", zero_state((), dtype=jnp.float32), dist_reduce_fx="sum")

    def update(self, img1: Array, img2: Array) -> None:
        img1 = jnp.asarray(img1)
        img2 = jnp.asarray(img2)
        if self.normalize:
            # [0,1] → [-1,1] expected by LPIPS nets
            img1 = 2 * img1 - 1
            img2 = 2 * img2 - 1
        loss = jnp.asarray(self.distance_fn(img1, img2)).reshape(-1).astype(jnp.float32)
        self.sum_scores = self.sum_scores + jnp.sum(loss)
        self.total = self.total + loss.shape[0]

    def compute(self) -> Array:
        if self.reduction == "mean":
            return self.sum_scores / self.total
        return self.sum_scores
