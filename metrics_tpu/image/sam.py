"""Spectral Angle Mapper module metric.

Reference parity: src/torchmetrics/image/sam.py. TPU-native divergence: per-pixel
angles are independent, so (score-sum, pixel-count) scalars replace the reference's
O(N) cat-list states for mean/sum reductions — identical value, constant memory.
"""

from __future__ import annotations

from typing import Any, Optional

import jax.numpy as jnp
from jax import Array

from metrics_tpu.functional.image.sam import _sam_compute, _sam_update
from metrics_tpu.metric import Metric, zero_state
from metrics_tpu.utils.data import dim_zero_cat
from metrics_tpu.utils.distributed import reduce


class SpectralAngleMapper(Metric):
    """Spectral Angle Mapper.

    Example:
        >>> import jax, jax.numpy as jnp
        >>> key1, key2 = jax.random.split(jax.random.PRNGKey(0))
        >>> preds = jax.random.uniform(key1, (2, 3, 16, 16))
        >>> target = preds * 0.75 + jax.random.uniform(key2, (2, 3, 16, 16)) * 0.25
        >>> from metrics_tpu.image import SpectralAngleMapper
        >>> metric = SpectralAngleMapper()
        >>> metric.update(preds, target)
        >>> metric.compute()
        Array(0.15643196, dtype=float32)
    """
    is_differentiable = True
    higher_is_better = False
    full_state_update = False

    def __init__(self, reduction: Optional[str] = "elementwise_mean", **kwargs: Any) -> None:
        super().__init__(**kwargs)
        self.reduction = reduction
        if reduction in ("elementwise_mean", "sum"):
            self.add_state("score_sum", zero_state(()), dist_reduce_fx="sum")
            self.add_state("total", zero_state(()), dist_reduce_fx="sum")
        else:
            self.add_state("scores", [], dist_reduce_fx="cat")

    def update(self, preds: Array, target: Array) -> None:
        preds, target = _sam_update(preds, target)
        score = _sam_compute(preds, target, reduction="none")
        if self.reduction in ("elementwise_mean", "sum"):
            self.score_sum = self.score_sum + jnp.sum(score)
            self.total = self.total + score.size
        else:
            self.scores.append(score)

    def compute(self) -> Array:
        if self.reduction == "elementwise_mean":
            return self.score_sum / self.total
        if self.reduction == "sum":
            return self.score_sum
        return reduce(dim_zero_cat(self.scores), self.reduction)
