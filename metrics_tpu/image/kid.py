"""Kernel Inception Distance.

Reference parity: src/torchmetrics/image/kid.py (``maximum_mean_discrepancy`` :29,
``poly_kernel`` :49, ``poly_mmd`` :57, class ``KernelInceptionDistance`` :67 with
cat-list feature states and subset-resampled polynomial MMD at compute).
"""

from __future__ import annotations

from typing import Any, Callable, Optional, Tuple, Union

import jax.numpy as jnp
import numpy as np
from jax import Array

from metrics_tpu.image.fid import _resolve_feature_extractor
from metrics_tpu.metric import Metric
from metrics_tpu.utils.data import dim_zero_cat


def maximum_mean_discrepancy(k_xx: Array, k_xy: Array, k_yy: Array) -> Array:
    """Unbiased MMD² estimate from kernel matrices (reference :29-46)."""
    m = k_xx.shape[0]
    diag_x = jnp.diag(k_xx)
    diag_y = jnp.diag(k_yy)
    kt_xx_sum = (jnp.sum(k_xx) - jnp.sum(diag_x)) / (m * (m - 1))
    kt_yy_sum = (jnp.sum(k_yy) - jnp.sum(diag_y)) / (m * (m - 1))
    k_xy_sum = jnp.sum(k_xy) / (m * m)
    return kt_xx_sum + kt_yy_sum - 2 * k_xy_sum


def poly_kernel(f1: Array, f2: Array, degree: int = 3, gamma: Optional[float] = None, coef: float = 1.0) -> Array:
    if gamma is None:
        gamma = 1.0 / f1.shape[1]
    return (f1 @ f2.T * gamma + coef) ** degree


def poly_mmd(f_real: Array, f_fake: Array, degree: int = 3, gamma: Optional[float] = None, coef: float = 1.0) -> Array:
    k_11 = poly_kernel(f_real, f_real, degree, gamma, coef)
    k_22 = poly_kernel(f_fake, f_fake, degree, gamma, coef)
    k_12 = poly_kernel(f_real, f_fake, degree, gamma, coef)
    return maximum_mean_discrepancy(k_11, k_12, k_22)


class KernelInceptionDistance(Metric):
    """Kernel Inception Distance.

    Example:
        >>> import jax, jax.numpy as jnp
        >>> from metrics_tpu.image import KernelInceptionDistance
        >>> flatten8 = lambda imgs: imgs.reshape(imgs.shape[0], -1)[:, :8].astype(jnp.float32)
        >>> kid = KernelInceptionDistance(feature=flatten8, subsets=2, subset_size=4)
        >>> key1, key2 = jax.random.split(jax.random.PRNGKey(0))
        >>> kid.update(jax.random.uniform(key1, (8, 3, 8, 8)), real=True)
        >>> kid.update(jax.random.uniform(key2, (8, 3, 8, 8)), real=False)
        >>> kid_mean, kid_std = kid.compute()
        >>> bool(jnp.isfinite(kid_mean))
        True
    """
    is_differentiable = False
    higher_is_better = False
    full_state_update = False
    _host_compute = True  # random subset resampling at compute

    def __init__(
        self,
        feature: Union[int, Callable] = 2048,
        subsets: int = 100,
        subset_size: int = 1000,
        degree: int = 3,
        gamma: Optional[float] = None,
        coef: float = 1.0,
        reset_real_features: bool = True,
        normalize: bool = False,
        allow_random_weights: bool = False,
        **kwargs: Any,
    ) -> None:
        super().__init__(**kwargs)
        self.extractor, _ = _resolve_feature_extractor(feature, allow_random_weights)
        if not (isinstance(subsets, int) and subsets > 0):
            raise ValueError("Argument `subsets` expected to be integer larger than 0")
        self.subsets = subsets
        if not (isinstance(subset_size, int) and subset_size > 0):
            raise ValueError("Argument `subset_size` expected to be integer larger than 0")
        self.subset_size = subset_size
        if not (isinstance(degree, int) and degree > 0):
            raise ValueError("Argument `degree` expected to be integer larger than 0")
        self.degree = degree
        if gamma is not None and not (isinstance(gamma, float) and gamma > 0):
            raise ValueError("Argument `gamma` expected to be `None` or float larger than 0")
        self.gamma = gamma
        if not (isinstance(coef, float) and coef > 0):
            raise ValueError("Argument `coef` expected to be float larger than 0")
        self.coef = coef
        if not isinstance(reset_real_features, bool):
            raise ValueError("Argument `reset_real_features` expected to be a bool")
        self.reset_real_features = reset_real_features
        if not isinstance(normalize, bool):
            raise ValueError("Argument `normalize` expected to be a bool")
        self.normalize = normalize

        self.add_state("real_features", [], dist_reduce_fx=None)
        self.add_state("fake_features", [], dist_reduce_fx=None)

    def update(self, imgs: Array, real: bool) -> None:
        imgs = (jnp.asarray(imgs) * 255).astype(jnp.uint8) if self.normalize else jnp.asarray(imgs)
        features = jnp.asarray(self.extractor(imgs))
        if real:
            self.real_features.append(features)
        else:
            self.fake_features.append(features)

    def compute(self) -> Tuple[Array, Array]:
        real_features = dim_zero_cat(self.real_features)
        fake_features = dim_zero_cat(self.fake_features)

        n_samples_real = real_features.shape[0]
        if n_samples_real < self.subset_size:
            raise ValueError("Argument `subset_size` should be smaller than the number of samples")
        n_samples_fake = fake_features.shape[0]
        if n_samples_fake < self.subset_size:
            raise ValueError("Argument `subset_size` should be smaller than the number of samples")

        kid_scores_ = []
        for _ in range(self.subsets):
            perm = np.random.permutation(n_samples_real)
            f_real = real_features[perm[: self.subset_size]]
            perm = np.random.permutation(n_samples_fake)
            f_fake = fake_features[perm[: self.subset_size]]
            kid_scores_.append(poly_mmd(f_real, f_fake, self.degree, self.gamma, self.coef))
        kid_scores = jnp.stack(kid_scores_)
        return jnp.mean(kid_scores), jnp.std(kid_scores)

    def reset(self) -> None:
        if not self.reset_real_features:
            value = self.real_features
            super().reset()
            self.real_features = value
        else:
            super().reset()
