"""SSIM / MS-SSIM module metrics.

Reference parity: src/torchmetrics/image/ssim.py (similarity sum state for
mean/sum reductions :99-103, cat lists for 'none' :101, MS-SSIM :246-250).
"""

from __future__ import annotations

from typing import Any, Optional, Sequence, Tuple, Union

import jax.numpy as jnp
from jax import Array

from metrics_tpu.functional.image.ssim import _multiscale_ssim_update, _ssim_check_inputs, _ssim_update
from metrics_tpu.metric import Metric, zero_state
from metrics_tpu.utils.data import dim_zero_cat


class StructuralSimilarityIndexMeasure(Metric):
    """Structural Similarity Index Measure.

    Example:
        >>> import jax, jax.numpy as jnp
        >>> key1, key2 = jax.random.split(jax.random.PRNGKey(0))
        >>> preds = jax.random.uniform(key1, (2, 3, 16, 16))
        >>> target = preds * 0.75 + jax.random.uniform(key2, (2, 3, 16, 16)) * 0.25
        >>> from metrics_tpu.image import StructuralSimilarityIndexMeasure
        >>> metric = StructuralSimilarityIndexMeasure(data_range=1.0)
        >>> metric.update(preds, target)
        >>> metric.compute()
        Array(0.9230765, dtype=float32)
    """
    is_differentiable = True
    higher_is_better = True
    full_state_update = False

    def __init__(
        self,
        gaussian_kernel: bool = True,
        sigma: Union[float, Sequence[float]] = 1.5,
        kernel_size: Union[int, Sequence[int]] = 11,
        reduction: Optional[str] = "elementwise_mean",
        data_range: Optional[float] = None,
        k1: float = 0.01,
        k2: float = 0.03,
        return_full_image: bool = False,
        return_contrast_sensitivity: bool = False,
        **kwargs: Any,
    ) -> None:
        super().__init__(**kwargs)
        valid_reduction = ("elementwise_mean", "sum", "none", None)
        if reduction not in valid_reduction:
            raise ValueError(f"Argument `reduction` must be one of {valid_reduction}, but got {reduction}")

        if reduction in ("elementwise_mean", "sum"):
            self.add_state("similarity", zero_state(()), dist_reduce_fx="sum")
        else:
            self.add_state("similarity", [], dist_reduce_fx="cat")
        self.add_state("total", zero_state(()), dist_reduce_fx="sum")

        if return_contrast_sensitivity or return_full_image:
            self.add_state("image_return", [], dist_reduce_fx="cat")

        self.gaussian_kernel = gaussian_kernel
        self.sigma = sigma
        self.kernel_size = kernel_size
        self.reduction = reduction
        self.data_range = data_range
        self.k1 = k1
        self.k2 = k2
        self.return_full_image = return_full_image
        self.return_contrast_sensitivity = return_contrast_sensitivity

    def update(self, preds: Array, target: Array) -> None:
        preds, target = _ssim_check_inputs(preds, target)
        similarity_pack = _ssim_update(
            preds, target, self.gaussian_kernel, self.sigma, self.kernel_size, self.data_range,
            self.k1, self.k2, self.return_full_image, self.return_contrast_sensitivity,
        )
        if isinstance(similarity_pack, tuple):
            similarity, image = similarity_pack
            self.image_return.append(image)
        else:
            similarity = similarity_pack

        if self.reduction in ("elementwise_mean", "sum"):
            self.similarity = self.similarity + jnp.sum(similarity)
        else:
            self.similarity.append(similarity)
        self.total = self.total + preds.shape[0]

    def compute(self) -> Union[Array, Tuple[Array, Array]]:
        if self.reduction == "elementwise_mean":
            similarity = self.similarity / self.total
        elif self.reduction == "sum":
            similarity = self.similarity
        else:
            similarity = dim_zero_cat(self.similarity)

        if self.return_contrast_sensitivity or self.return_full_image:
            return similarity, dim_zero_cat(self.image_return)
        return similarity


class MultiScaleStructuralSimilarityIndexMeasure(Metric):
    """Multi-scale SSIM over a pyramid of 2x-downsampled scales.

    Example:
        >>> import jax.numpy as jnp
        >>> import numpy as np
        >>> from metrics_tpu import MultiScaleStructuralSimilarityIndexMeasure
        >>> img = jnp.asarray(np.random.RandomState(0).rand(2, 3, 48, 48).astype(np.float32))
        >>> metric = MultiScaleStructuralSimilarityIndexMeasure(data_range=1.0, betas=(0.2, 0.3, 0.5))
        >>> metric.update(img, img)
        >>> metric.compute()
        Array(1., dtype=float32)
    """

    is_differentiable = True
    higher_is_better = True
    full_state_update = False

    def __init__(
        self,
        gaussian_kernel: bool = True,
        kernel_size: Union[int, Sequence[int]] = 11,
        sigma: Union[float, Sequence[float]] = 1.5,
        reduction: Optional[str] = "elementwise_mean",
        data_range: Optional[float] = None,
        k1: float = 0.01,
        k2: float = 0.03,
        betas: Tuple[float, ...] = (0.0448, 0.2856, 0.3001, 0.2363, 0.1333),
        normalize: Optional[str] = "relu",
        **kwargs: Any,
    ) -> None:
        super().__init__(**kwargs)
        valid_reduction = ("elementwise_mean", "sum", "none", None)
        if reduction not in valid_reduction:
            raise ValueError(f"Argument `reduction` must be one of {valid_reduction}, but got {reduction}")

        if reduction in ("elementwise_mean", "sum"):
            self.add_state("similarity", zero_state(()), dist_reduce_fx="sum")
        else:
            self.add_state("similarity", [], dist_reduce_fx="cat")
        self.add_state("total", zero_state(()), dist_reduce_fx="sum")

        if not (isinstance(kernel_size, (Sequence, int))):
            raise ValueError("Argument `kernel_size` expected to be an sequence or an int")
        if not isinstance(betas, tuple) or not all(isinstance(beta, float) for beta in betas):
            raise ValueError("Argument `betas` is expected to be of a type tuple of floats.")
        if normalize and normalize not in ("relu", "simple"):
            raise ValueError("Argument `normalize` to be expected either `None` or one of 'relu' or 'simple'")

        self.gaussian_kernel = gaussian_kernel
        self.sigma = sigma
        self.kernel_size = kernel_size
        self.reduction = reduction
        self.data_range = data_range
        self.k1 = k1
        self.k2 = k2
        self.betas = betas
        self.normalize = normalize

    def update(self, preds: Array, target: Array) -> None:
        preds, target = _ssim_check_inputs(preds, target)
        similarity = _multiscale_ssim_update(
            preds, target, self.gaussian_kernel, self.sigma, self.kernel_size, self.data_range,
            self.k1, self.k2, self.betas, self.normalize,
        )
        if self.reduction in ("none", None):
            self.similarity.append(similarity)
        else:
            self.similarity = self.similarity + jnp.sum(similarity)
        self.total = self.total + preds.shape[0]

    def compute(self) -> Array:
        if self.reduction == "elementwise_mean":
            return self.similarity / self.total
        if self.reduction == "sum":
            return self.similarity
        return dim_zero_cat(self.similarity)
