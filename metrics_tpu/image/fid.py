"""Fréchet Inception Distance.

Reference parity: src/torchmetrics/image/fid.py (``NoTrainInceptionV3`` :41,
``MatrixSquareRoot`` via scipy sqrtm :61-96, ``_compute_fid`` :98, class
``FrechetInceptionDistance`` :127, running mean+cov states :253-259 — so FID syncs
O(d²) covariance, not O(N·d) features).

TPU-native design:
- ``feature`` accepts an **int tap** (64/192/768/2048 — builds the in-repo flax
  InceptionV3, ``image/inception_net.py``, replacing the reference's torch-fidelity
  dependency) or a **callable** ``imgs -> (N, d)`` (a jitted JAX model, a host
  function, or any torch module).
- the matrix square root offers two backends: ``"scipy"`` (host, exact — what the
  reference uses) and ``"newton"`` (Newton–Schulz iterations, jittable, runs on TPU
  inside the compute graph; SURVEY §7.2.7).
"""

from __future__ import annotations

from copy import deepcopy
from typing import Any, Callable, Optional, Union

import jax.numpy as jnp
import numpy as np
from jax import Array

import jax

from metrics_tpu.metric import Metric, zero_state
from metrics_tpu.utils.imports import _SCIPY_AVAILABLE
from metrics_tpu.utils.prints import rank_zero_info


def sqrtm_newton_schulz(mat: Array, num_iters: int = 100) -> Array:
    """Matrix square root by Newton–Schulz iteration — jittable, MXU-bound matmuls.

    Converges for matrices with ||A/||A||_F - I|| < 1 (PSD covariance products in
    practice). f32 on TPU; accuracy ~1e-4 relative, sufficient for FID's trace.
    """
    dim = mat.shape[0]
    norm = jnp.linalg.norm(mat)
    y = mat / norm
    eye = jnp.eye(dim, dtype=mat.dtype)
    z = eye
    for _ in range(num_iters):
        t = 0.5 * (3.0 * eye - z @ y)
        y = y @ t
        z = t @ z
    return y * jnp.sqrt(norm)


def _sqrtm_scipy(mat: Array) -> Array:
    import scipy.linalg

    res = scipy.linalg.sqrtm(np.asarray(mat, dtype=np.float64))
    return jnp.asarray(res.real)


def _compute_fid(mu1: Array, sigma1: Array, mu2: Array, sigma2: Array, eps: float = 1e-6, sqrtm_backend: str = "scipy") -> Array:
    """d² = |μ1-μ2|² + Tr(Σ1 + Σ2 - 2·sqrt(Σ1·Σ2)) (reference :98-125)."""
    sqrtm = _sqrtm_scipy if sqrtm_backend == "scipy" else sqrtm_newton_schulz
    diff = mu1 - mu2
    if sqrtm_backend == "newton":
        # Newton–Schulz oscillates on singular products (rank-deficient covariances,
        # e.g. fewer samples than feature dims). Regularising unconditionally keeps the
        # path jittable — no data-dependent branch — and shifts the trace by O(d·√eps)
        # at most, well below FID's meaningful resolution.
        offset = jnp.eye(sigma1.shape[0], dtype=mu1.dtype) * eps
        sigma1 = sigma1 + offset
        sigma2 = sigma2 + offset
    covmean = sqrtm(sigma1 @ sigma2)
    if sqrtm_backend == "scipy" and not bool(jnp.all(jnp.isfinite(covmean))):
        rank_zero_info(f"FID calculation produces singular product; adding {eps} to diagonal of covariance estimates")
        offset = jnp.eye(sigma1.shape[0], dtype=mu1.dtype) * eps
        covmean = sqrtm((sigma1 + offset) @ (sigma2 + offset))
    tr_covmean = jnp.trace(covmean)
    return diff @ diff + jnp.trace(sigma1) + jnp.trace(sigma2) - 2 * tr_covmean


def _resolve_feature_extractor(feature: Union[int, str, Callable], allow_random_weights: bool = False) -> tuple:
    """Returns (extract_fn, num_features).

    Integer (64/192/768/2048) and string ("logits_unbiased") inputs build the in-repo
    flax InceptionV3 (``image/inception_net.py``) — the TPU-native replacement for the
    reference's torch-fidelity ``NoTrainInceptionV3`` (src/torchmetrics/image/fid.py:41).
    Weights come from ``$METRICS_TPU_INCEPTION_WEIGHTS`` (see
    ``tools/convert_inception_weights.py``); ``allow_random_weights=True`` opts into
    seeded random initialisation for tests/relative comparisons. A callable is used
    as-is and must return an ``(N, d)`` feature matrix.
    """
    if isinstance(feature, (int, str)) and not isinstance(feature, bool):
        from metrics_tpu.image.inception_net import FEATURE_DIMS, InceptionFeatureExtractor

        if feature not in FEATURE_DIMS:
            valid_int_input = tuple(k for k in FEATURE_DIMS if isinstance(k, int))
            valid_str_input = tuple(k for k in FEATURE_DIMS if isinstance(k, str))
            raise ValueError(
                f"Input to argument `feature` must be one of {valid_int_input} (feature taps)"
                f" or {valid_str_input} (logit heads), but got {feature!r}."
            )
        extractor = InceptionFeatureExtractor(feature, allow_random_weights=allow_random_weights)
        return extractor, extractor.num_features
    if callable(feature):
        return feature, None
    raise TypeError("Got unknown input to argument `feature`: expected an int, a str or a callable")


class FrechetInceptionDistance(Metric):
    """Frechet Inception Distance.

    Example:
        >>> import jax, jax.numpy as jnp
        >>> from metrics_tpu.image import FrechetInceptionDistance
        >>> flatten8 = lambda imgs: imgs.reshape(imgs.shape[0], -1)[:, :8].astype(jnp.float32)
        >>> fid = FrechetInceptionDistance(feature=flatten8, num_features=8)  # tiny extractor for the example
        >>> key1, key2 = jax.random.split(jax.random.PRNGKey(0))
        >>> fid.update(jax.random.uniform(key1, (8, 3, 8, 8)), real=True)
        >>> fid.update(jax.random.uniform(key2, (8, 3, 8, 8)), real=False)
        >>> fid.compute()
        Array(0.94201267, dtype=float32)
    """
    is_differentiable = False
    higher_is_better = False
    full_state_update = False

    real_features_sum: Array
    real_features_cov_sum: Array
    real_features_num_samples: Array
    fake_features_sum: Array
    fake_features_cov_sum: Array
    fake_features_num_samples: Array

    def __init__(
        self,
        feature: Union[int, Callable] = 2048,
        reset_real_features: bool = True,
        normalize: bool = False,
        num_features: Optional[int] = None,
        sqrtm_backend: str = "scipy",
        allow_random_weights: bool = False,
        **kwargs: Any,
    ) -> None:
        super().__init__(**kwargs)
        self.extractor, inferred = _resolve_feature_extractor(feature, allow_random_weights)
        num_features = num_features or inferred or (feature if isinstance(feature, int) else None)
        if num_features is None:
            raise ValueError(
                "When `feature` is a callable, pass `num_features=<d>` (its output feature dimension)."
            )
        if not isinstance(reset_real_features, bool):
            raise ValueError("Argument `reset_real_features` expected to be a bool")
        if not isinstance(normalize, bool):
            raise ValueError("Argument `normalize` expected to be a bool")
        if sqrtm_backend not in ("scipy", "newton"):
            raise ValueError(f"Argument `sqrtm_backend` must be 'scipy' or 'newton', got {sqrtm_backend}")
        if sqrtm_backend == "scipy" and not _SCIPY_AVAILABLE:
            sqrtm_backend = "newton"
        self.reset_real_features = reset_real_features
        self.normalize = normalize
        self.sqrtm_backend = sqrtm_backend
        self._host_compute = sqrtm_backend == "scipy"
        d = num_features
        self.num_features = d

        # f64 accumulators when x64 is enabled (host/CPU), else f32 (TPU-native)
        ftype = jax.dtypes.canonicalize_dtype(jnp.float64)
        itype = jax.dtypes.canonicalize_dtype(jnp.int64)
        self.add_state("real_features_sum", zero_state(d, dtype=ftype), dist_reduce_fx="sum")
        self.add_state("real_features_cov_sum", zero_state((d, d), dtype=ftype), dist_reduce_fx="sum")
        self.add_state("real_features_num_samples", zero_state((), dtype=itype), dist_reduce_fx="sum")
        self.add_state("fake_features_sum", zero_state(d, dtype=ftype), dist_reduce_fx="sum")
        self.add_state("fake_features_cov_sum", zero_state((d, d), dtype=ftype), dist_reduce_fx="sum")
        self.add_state("fake_features_num_samples", zero_state((), dtype=itype), dist_reduce_fx="sum")
        # first-batch centering shift: a constant feature shift leaves the covariance
        # (and the FID mean-difference) unchanged but removes the catastrophic
        # cancellation of accumulating raw second moments in f32 on TPU
        self.add_state("real_center", zero_state(d, dtype=ftype), dist_reduce_fx="mean")
        self.add_state("fake_center", zero_state(d, dtype=ftype), dist_reduce_fx="mean")

    def _extract(self, imgs: Array) -> Array:
        imgs = (jnp.asarray(imgs) * 255).astype(jnp.uint8) if self.normalize else jnp.asarray(imgs)
        features = jnp.asarray(self.extractor(imgs))
        if features.ndim == 1:
            features = features.reshape(1, -1)
        return features.astype(self.real_features_sum.dtype)

    def update(self, imgs: Array, real: bool) -> None:
        features = self._extract(imgs)
        n = features.shape[0]
        if real:
            self.real_center = jnp.where(self.real_features_num_samples == 0, jnp.mean(features, axis=0), self.real_center)
            centered = features - self.real_center
            self.real_features_sum = self.real_features_sum + centered.sum(axis=0)
            self.real_features_cov_sum = self.real_features_cov_sum + centered.T @ centered
            self.real_features_num_samples = self.real_features_num_samples + n
        else:
            self.fake_center = jnp.where(self.fake_features_num_samples == 0, jnp.mean(features, axis=0), self.fake_center)
            centered = features - self.fake_center
            self.fake_features_sum = self.fake_features_sum + centered.sum(axis=0)
            self.fake_features_cov_sum = self.fake_features_cov_sum + centered.T @ centered
            self.fake_features_num_samples = self.fake_features_num_samples + n

    def compute(self) -> Array:
        n_real = self.real_features_num_samples
        n_fake = self.fake_features_num_samples
        mean_real_c = self.real_features_sum / n_real
        mean_fake_c = self.fake_features_sum / n_fake
        cov_real = (self.real_features_cov_sum - n_real * jnp.outer(mean_real_c, mean_real_c)) / (n_real - 1)
        cov_fake = (self.fake_features_cov_sum - n_fake * jnp.outer(mean_fake_c, mean_fake_c)) / (n_fake - 1)
        mean_real = mean_real_c + self.real_center
        mean_fake = mean_fake_c + self.fake_center
        return _compute_fid(mean_real, cov_real, mean_fake, cov_fake, sqrtm_backend=self.sqrtm_backend)

    def reset(self) -> None:
        """Keep real-distribution stats across resets if requested (reference :290-300)."""
        if not self.reset_real_features:
            real_sum = self.real_features_sum
            real_cov = self.real_features_cov_sum
            real_n = self.real_features_num_samples
            real_center = self.real_center
            super().reset()
            self.real_features_sum = real_sum
            self.real_features_cov_sum = real_cov
            self.real_features_num_samples = real_n
            self.real_center = real_center
        else:
            super().reset()
