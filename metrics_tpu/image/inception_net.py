"""TPU-native InceptionV3 feature extractor for FID/KID/InceptionScore.

Replaces the reference's ``NoTrainInceptionV3`` (src/torchmetrics/image/fid.py:41, which
wraps torch-fidelity's port of the TF-slim InceptionV3 used by the original FID paper)
with a flax implementation that runs inside the metric's XLA graph. Architecture follows
the torch-fidelity FID variant: BN convs (eps=1e-3), Inception A/B/C/D/E towers,
``count_include_pad=False`` average pooling, max-pool branch in the final E block, and a
1008-way logits head; feature taps at 64 (pool1), 192 (pool2), 768 (Mixed_6e) and 2048
(final pool) are globally average-pooled to ``(N, C)``.

Weights: offline-friendly. ``load_params(path)`` reads a flat ``.npz`` written by
``save_params`` (keys are ``/``-joined pytree paths); produce it from the canonical
FID checkpoint with ``tools/convert_inception_weights.py``. When no weight file is
given and none is found at ``$METRICS_TPU_INCEPTION_WEIGHTS``, construction FAILS
unless ``allow_random_weights=True`` opts into seeded random initialisation —
self-consistent for tests and relative comparisons, but NOT comparable to published
FID numbers, so it must never reach an eval dashboard silently (same posture as the
LPIPS net).

Layout note: inputs follow the reference convention (N, C, H, W) uint8; internally
everything is NHWC, the TPU-native convolution layout.
"""

from __future__ import annotations

import functools
import os
from typing import Any, Dict, Optional, Sequence, Tuple

import flax.linen as nn
import jax
import jax.numpy as jnp
import numpy as np
from jax import Array

from metrics_tpu.utils.prints import rank_zero_warn

FEATURE_DIMS = {64: 64, 192: 192, 768: 768, 2048: 2048, "logits": 1008, "logits_unbiased": 1008}
_WEIGHTS_ENV = "METRICS_TPU_INCEPTION_WEIGHTS"


class BasicConv2d(nn.Module):
    """Conv(no bias) + frozen BatchNorm(eps=1e-3) + ReLU — the TF-slim conv unit."""

    features: int
    kernel: Tuple[int, int]
    strides: Tuple[int, int] = (1, 1)
    padding: Any = "VALID"

    @nn.compact
    def __call__(self, x: Array) -> Array:
        x = nn.Conv(self.features, self.kernel, self.strides, padding=self.padding, use_bias=False, name="conv")(x)
        x = nn.BatchNorm(use_running_average=True, epsilon=1e-3, momentum=0.9, name="bn")(x)
        return nn.relu(x)


def _avg_pool_3x3_no_pad_count(x: Array) -> Array:
    """3x3 stride-1 average pool, pad 1, ``count_include_pad=False`` semantics.

    The FID inception variant divides by the number of VALID elements under the window
    (TF behaviour), not the fixed window size — this is exactly the torch-fidelity
    patch over torchvision (FIDInceptionA/C/E_1).
    """
    ones = jnp.ones(x.shape[1:3], x.dtype)[None, :, :, None]
    summed = jax.lax.reduce_window(x, 0.0, jax.lax.add, (1, 3, 3, 1), (1, 1, 1, 1), [(0, 0), (1, 1), (1, 1), (0, 0)])
    count = jax.lax.reduce_window(ones, 0.0, jax.lax.add, (1, 3, 3, 1), (1, 1, 1, 1), [(0, 0), (1, 1), (1, 1), (0, 0)])
    return summed / count


def _max_pool(x: Array, window: int, stride: int, padding: str = "VALID") -> Array:
    return nn.max_pool(x, (window, window), (stride, stride), padding)


class InceptionA(nn.Module):
    pool_features: int

    @nn.compact
    def __call__(self, x: Array) -> Array:
        b1 = BasicConv2d(64, (1, 1), name="branch1x1")(x)
        b5 = BasicConv2d(48, (1, 1), name="branch5x5_1")(x)
        b5 = BasicConv2d(64, (5, 5), padding=[(2, 2), (2, 2)], name="branch5x5_2")(b5)
        b3 = BasicConv2d(64, (1, 1), name="branch3x3dbl_1")(x)
        b3 = BasicConv2d(96, (3, 3), padding=[(1, 1), (1, 1)], name="branch3x3dbl_2")(b3)
        b3 = BasicConv2d(96, (3, 3), padding=[(1, 1), (1, 1)], name="branch3x3dbl_3")(b3)
        bp = _avg_pool_3x3_no_pad_count(x)
        bp = BasicConv2d(self.pool_features, (1, 1), name="branch_pool")(bp)
        return jnp.concatenate([b1, b5, b3, bp], axis=-1)


class InceptionB(nn.Module):
    @nn.compact
    def __call__(self, x: Array) -> Array:
        b3 = BasicConv2d(384, (3, 3), strides=(2, 2), name="branch3x3")(x)
        bd = BasicConv2d(64, (1, 1), name="branch3x3dbl_1")(x)
        bd = BasicConv2d(96, (3, 3), padding=[(1, 1), (1, 1)], name="branch3x3dbl_2")(bd)
        bd = BasicConv2d(96, (3, 3), strides=(2, 2), name="branch3x3dbl_3")(bd)
        bp = _max_pool(x, 3, 2)
        return jnp.concatenate([b3, bd, bp], axis=-1)


class InceptionC(nn.Module):
    channels_7x7: int

    @nn.compact
    def __call__(self, x: Array) -> Array:
        c7 = self.channels_7x7
        b1 = BasicConv2d(192, (1, 1), name="branch1x1")(x)
        b7 = BasicConv2d(c7, (1, 1), name="branch7x7_1")(x)
        b7 = BasicConv2d(c7, (1, 7), padding=[(0, 0), (3, 3)], name="branch7x7_2")(b7)
        b7 = BasicConv2d(192, (7, 1), padding=[(3, 3), (0, 0)], name="branch7x7_3")(b7)
        bd = BasicConv2d(c7, (1, 1), name="branch7x7dbl_1")(x)
        bd = BasicConv2d(c7, (7, 1), padding=[(3, 3), (0, 0)], name="branch7x7dbl_2")(bd)
        bd = BasicConv2d(c7, (1, 7), padding=[(0, 0), (3, 3)], name="branch7x7dbl_3")(bd)
        bd = BasicConv2d(c7, (7, 1), padding=[(3, 3), (0, 0)], name="branch7x7dbl_4")(bd)
        bd = BasicConv2d(192, (1, 7), padding=[(0, 0), (3, 3)], name="branch7x7dbl_5")(bd)
        bp = _avg_pool_3x3_no_pad_count(x)
        bp = BasicConv2d(192, (1, 1), name="branch_pool")(bp)
        return jnp.concatenate([b1, b7, bd, bp], axis=-1)


class InceptionD(nn.Module):
    @nn.compact
    def __call__(self, x: Array) -> Array:
        b3 = BasicConv2d(192, (1, 1), name="branch3x3_1")(x)
        b3 = BasicConv2d(320, (3, 3), strides=(2, 2), name="branch3x3_2")(b3)
        b7 = BasicConv2d(192, (1, 1), name="branch7x7x3_1")(x)
        b7 = BasicConv2d(192, (1, 7), padding=[(0, 0), (3, 3)], name="branch7x7x3_2")(b7)
        b7 = BasicConv2d(192, (7, 1), padding=[(3, 3), (0, 0)], name="branch7x7x3_3")(b7)
        b7 = BasicConv2d(192, (3, 3), strides=(2, 2), name="branch7x7x3_4")(b7)
        bp = _max_pool(x, 3, 2)
        return jnp.concatenate([b3, b7, bp], axis=-1)


class InceptionE(nn.Module):
    pool_type: str  # "avg" (Mixed_7b) or "max" (Mixed_7c) — the FID-variant split

    @nn.compact
    def __call__(self, x: Array) -> Array:
        b1 = BasicConv2d(320, (1, 1), name="branch1x1")(x)
        b3 = BasicConv2d(384, (1, 1), name="branch3x3_1")(x)
        b3a = BasicConv2d(384, (1, 3), padding=[(0, 0), (1, 1)], name="branch3x3_2a")(b3)
        b3b = BasicConv2d(384, (3, 1), padding=[(1, 1), (0, 0)], name="branch3x3_2b")(b3)
        b3 = jnp.concatenate([b3a, b3b], axis=-1)
        bd = BasicConv2d(448, (1, 1), name="branch3x3dbl_1")(x)
        bd = BasicConv2d(384, (3, 3), padding=[(1, 1), (1, 1)], name="branch3x3dbl_2")(bd)
        bda = BasicConv2d(384, (1, 3), padding=[(0, 0), (1, 1)], name="branch3x3dbl_3a")(bd)
        bdb = BasicConv2d(384, (3, 1), padding=[(1, 1), (0, 0)], name="branch3x3dbl_3b")(bd)
        bd = jnp.concatenate([bda, bdb], axis=-1)
        if self.pool_type == "avg":
            bp = _avg_pool_3x3_no_pad_count(x)
        else:
            bp = _max_pool(x, 3, 1, padding=((1, 1), (1, 1)))
        bp = BasicConv2d(192, (1, 1), name="branch_pool")(bp)
        return jnp.concatenate([b1, b3, bd, bp], axis=-1)


class InceptionV3(nn.Module):
    """FID-variant InceptionV3 returning all feature taps in one forward."""

    @nn.compact
    def __call__(self, x: Array) -> Dict[Any, Array]:
        out: Dict[Any, Array] = {}
        x = BasicConv2d(32, (3, 3), strides=(2, 2), name="Conv2d_1a_3x3")(x)
        x = BasicConv2d(32, (3, 3), name="Conv2d_2a_3x3")(x)
        x = BasicConv2d(64, (3, 3), padding=[(1, 1), (1, 1)], name="Conv2d_2b_3x3")(x)
        x = _max_pool(x, 3, 2)
        out[64] = jnp.mean(x, axis=(1, 2))
        x = BasicConv2d(80, (1, 1), name="Conv2d_3b_1x1")(x)
        x = BasicConv2d(192, (3, 3), name="Conv2d_4a_3x3")(x)
        x = _max_pool(x, 3, 2)
        out[192] = jnp.mean(x, axis=(1, 2))
        x = InceptionA(32, name="Mixed_5b")(x)
        x = InceptionA(64, name="Mixed_5c")(x)
        x = InceptionA(64, name="Mixed_5d")(x)
        x = InceptionB(name="Mixed_6a")(x)
        x = InceptionC(128, name="Mixed_6b")(x)
        x = InceptionC(160, name="Mixed_6c")(x)
        x = InceptionC(160, name="Mixed_6d")(x)
        x = InceptionC(192, name="Mixed_6e")(x)
        out[768] = jnp.mean(x, axis=(1, 2))
        x = InceptionD(name="Mixed_7a")(x)
        x = InceptionE("avg", name="Mixed_7b")(x)
        x = InceptionE("max", name="Mixed_7c")(x)
        pooled = jnp.mean(x, axis=(1, 2))
        out[2048] = pooled
        fc = nn.Dense(1008, name="fc")
        out["logits"] = fc(pooled)
        # IS convention (torch-fidelity): logits through the weight matrix only — the
        # bias cancels in softmax ratios and omitting it matches the TF graph.
        out["logits_unbiased"] = pooled @ fc.variables["params"]["kernel"]  # type: ignore[index]
        return out


from metrics_tpu.utils.params_io import load_params, save_params  # noqa: E402,F401  (shared npz protocol)


def init_params(seed: int = 0) -> Dict:
    """Random-initialise the network variables (params + batch_stats)."""
    model = InceptionV3()
    return model.init(jax.random.PRNGKey(seed), jnp.zeros((1, 299, 299, 3), jnp.float32))


@functools.partial(jax.jit, static_argnums=(0,))
def _forward(tap: Any, variables: Dict, imgs: Array) -> Array:
    """One shared compiled executable per tap — variables are a traced argument, so
    FID + KID + IS instances reuse the same compilation instead of each baking the
    ~24M-param tree into a private closure."""
    x = jnp.asarray(imgs, jnp.float32)
    x = jnp.transpose(x, (0, 2, 3, 1))  # NCHW (reference convention) -> NHWC
    x = jax.image.resize(x, (x.shape[0], 299, 299, x.shape[3]), method="bilinear")
    x = x / 255.0 * 2.0 - 1.0
    return InceptionV3().apply(variables, x)[tap]


@functools.lru_cache(maxsize=4)
def _cached_variables(weights_path: Optional[str], seed: int) -> Any:
    if weights_path is not None:
        return load_params(weights_path)
    rank_zero_warn(
        "InceptionV3 is using seeded RANDOM weights (allow_random_weights=True, no"
        " weights file). FID/KID/IS values will be self-consistent but NOT comparable"
        " to published numbers."
    )
    return init_params(seed)


class InceptionFeatureExtractor:
    """Callable ``imgs (N,C,H,W) uint8/float -> (N, d)`` features, jit-compiled.

    Drop-in for the reference's ``NoTrainInceptionV3`` seam: resizes to 299x299
    (bilinear), maps to [-1, 1], runs the flax net, returns the requested tap.
    """

    def __init__(
        self,
        feature: Any = 2048,
        weights_path: Optional[str] = None,
        seed: int = 0,
        allow_random_weights: bool = False,
    ) -> None:
        if feature not in FEATURE_DIMS:
            raise ValueError(f"`feature` must be one of {sorted(FEATURE_DIMS, key=str)}, got {feature}")
        self.feature = feature
        self.num_features = FEATURE_DIMS[feature]
        weights_path = weights_path or os.environ.get(_WEIGHTS_ENV) or None
        if weights_path is not None and not os.path.exists(weights_path):
            raise FileNotFoundError(f"Inception weights file not found: {weights_path}")
        if weights_path is None and not allow_random_weights:
            raise FileNotFoundError(
                "No InceptionV3 weights available: pass `weights_path=`, set"
                " $METRICS_TPU_INCEPTION_WEIGHTS (produce the .npz with"
                " tools/convert_inception_weights.py), or opt into random"
                " initialisation with `allow_random_weights=True`"
                " (tests/relative comparisons only)."
            )
        self._variables = _cached_variables(weights_path, seed)

    def __call__(self, imgs: Array) -> Array:
        return _forward(self.feature, self._variables, imgs)
