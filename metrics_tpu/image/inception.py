"""Inception Score.

Reference parity: src/torchmetrics/image/inception.py (class ``InceptionScore`` :29,
cat-list logit state :135, split-KL compute :143-166).
"""

from __future__ import annotations

from typing import Any, Callable, Tuple, Union

import jax
import jax.numpy as jnp
import numpy as np
from jax import Array

from metrics_tpu.image.fid import _resolve_feature_extractor
from metrics_tpu.metric import Metric
from metrics_tpu.utils.data import dim_zero_cat


class InceptionScore(Metric):
    """Inception Score.

    Example:
        >>> import jax, jax.numpy as jnp
        >>> from metrics_tpu.image import InceptionScore
        >>> logits16 = lambda imgs: imgs.reshape(imgs.shape[0], -1)[:, :16].astype(jnp.float32)
        >>> metric = InceptionScore(feature=logits16, splits=2)
        >>> metric.update(jax.random.uniform(jax.random.PRNGKey(0), (8, 3, 8, 8)))
        >>> score_mean, score_std = metric.compute()
        >>> bool(score_mean > 0)
        True
    """
    is_differentiable = False
    higher_is_better = True
    full_state_update = False
    _host_compute = True  # random permutation + chunking at compute

    def __init__(
        self,
        feature: Union[int, Callable] = "logits_unbiased",
        splits: int = 10,
        normalize: bool = False,
        allow_random_weights: bool = False,
        **kwargs: Any,
    ) -> None:
        super().__init__(**kwargs)
        if isinstance(feature, str) and feature not in ("logits", "logits_unbiased"):
            raise ValueError(
                f"Input to argument `feature` must be 'logits'/'logits_unbiased', an int or a callable, got {feature}"
            )
        self.extractor, _ = _resolve_feature_extractor(feature, allow_random_weights)
        if not (isinstance(splits, int) and splits > 0):
            raise ValueError("Argument `splits` expected to be integer larger than 0")
        self.splits = splits
        if not isinstance(normalize, bool):
            raise ValueError("Argument `normalize` expected to be a bool")
        self.normalize = normalize
        self.add_state("features", [], dist_reduce_fx=None)

    def update(self, imgs: Array) -> None:
        imgs = (jnp.asarray(imgs) * 255).astype(jnp.uint8) if self.normalize else jnp.asarray(imgs)
        features = jnp.asarray(self.extractor(imgs))
        self.features.append(features)

    def compute(self) -> Tuple[Array, Array]:
        features = dim_zero_cat(self.features)
        idx = np.random.permutation(features.shape[0])
        features = features[idx]

        prob = jax.nn.softmax(features, axis=1)
        log_prob = jax.nn.log_softmax(features, axis=1)

        prob_chunks = jnp.array_split(prob, self.splits, axis=0)
        log_prob_chunks = jnp.array_split(log_prob, self.splits, axis=0)

        mean_prob = [jnp.mean(p, axis=0, keepdims=True) for p in prob_chunks]
        kl_ = [p * (log_p - jnp.log(m_p)) for p, log_p, m_p in zip(prob_chunks, log_prob_chunks, mean_prob)]
        kl = jnp.stack([jnp.exp(jnp.mean(jnp.sum(k, axis=1))) for k in kl_])
        return jnp.mean(kl), jnp.std(kl, ddof=1)
