"""PSNR module metric.

Reference parity: src/torchmetrics/image/psnr.py (sum states when ``dim=None``
:91-92, cat-list states otherwise :94-95, min/max data-range tracking :104-105).
"""

from __future__ import annotations

from typing import Any, Optional, Tuple, Union

import jax.numpy as jnp
from jax import Array

from metrics_tpu.functional.image.psnr import _psnr_compute, _psnr_update
from metrics_tpu.metric import Metric, zero_state
from metrics_tpu.utils.data import dim_zero_cat
from metrics_tpu.utils.prints import rank_zero_warn


class PeakSignalNoiseRatio(Metric):
    """Peak Signal Noise Ratio.

    Example:
        >>> import jax.numpy as jnp
        >>> from metrics_tpu import PeakSignalNoiseRatio
        >>> preds = jnp.array([[[[0.1, 0.2], [0.3, 0.4]]]])
        >>> target = jnp.array([[[[0.1, 0.25], [0.3, 0.45]]]])
        >>> metric = PeakSignalNoiseRatio(data_range=1.0)
        >>> metric.update(preds, target)
        >>> round(float(metric.compute()), 4)
        29.0309
    """

    is_differentiable = True
    higher_is_better = True
    full_state_update = False

    def __init__(
        self,
        data_range: Optional[float] = None,
        base: float = 10.0,
        reduction: Optional[str] = "elementwise_mean",
        dim: Optional[Union[int, Tuple[int, ...]]] = None,
        **kwargs: Any,
    ) -> None:
        super().__init__(**kwargs)
        if dim is None and reduction != "elementwise_mean":
            rank_zero_warn(f"The `reduction={reduction}` will not have any effect when `dim` is None.")

        if dim is None:
            self.add_state("sum_squared_error", zero_state(()), dist_reduce_fx="sum")
            self.add_state("total", zero_state(()), dist_reduce_fx="sum")
        else:
            self.add_state("sum_squared_error", [], dist_reduce_fx="cat")
            self.add_state("total", [], dist_reduce_fx="cat")

        if data_range is None:
            if dim is not None:
                raise ValueError("The `data_range` must be given when `dim` is not None.")
            self.data_range = None
            self.add_state("min_target", zero_state(()), dist_reduce_fx="min")
            self.add_state("max_target", zero_state(()), dist_reduce_fx="max")
        else:
            self.add_state("data_range", jnp.asarray(float(data_range), jnp.float32), dist_reduce_fx="mean")
        self.base = base
        self.reduction = reduction
        self.dim = tuple(dim) if isinstance(dim, (list, tuple)) else dim

    def update(self, preds: Array, target: Array) -> None:
        preds = jnp.asarray(preds)
        target = jnp.asarray(target)
        sum_squared_error, n_obs = _psnr_update(preds, target, dim=self.dim)
        if self.dim is None:
            if self.data_range is None:
                # data_range unset: infer it later from the running target extrema
                self.min_target = jnp.minimum(jnp.min(target), self.min_target)
                self.max_target = jnp.maximum(jnp.max(target), self.max_target)
            self.sum_squared_error = self.sum_squared_error + sum_squared_error
            self.total = self.total + n_obs
        else:
            self.sum_squared_error.append(sum_squared_error)
            self.total.append(n_obs)

    def compute(self) -> Array:
        data_range = self.data_range if self.data_range is not None else (self.max_target - self.min_target)
        if self.dim is None:
            sum_squared_error = self.sum_squared_error
            total = self.total
        else:
            sum_squared_error = dim_zero_cat(self.sum_squared_error)
            total = dim_zero_cat(self.total)
        return _psnr_compute(sum_squared_error, total, data_range, base=self.base, reduction=self.reduction)
