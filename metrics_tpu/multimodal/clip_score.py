"""CLIPScore module metric (reference src/torchmetrics/multimodal/clip_score.py)."""

from __future__ import annotations

from typing import Any, List, Optional, Union

import jax.numpy as jnp
from jax import Array

from metrics_tpu.functional.multimodal.clip_score import _clip_score_update, _get_model_and_processor
from metrics_tpu.metric import Metric, zero_state


class CLIPScore(Metric):
    """Streaming CLIPScore (reference multimodal/clip_score.py:29-116).

    Example (requires the `transformers` FlaxCLIPModel; not executed offline):
        >>> import jax
        >>> from metrics_tpu.multimodal import CLIPScore
        >>> metric = CLIPScore()  # doctest: +SKIP
        >>> images = jax.random.uniform(jax.random.PRNGKey(0), (2, 3, 224, 224))  # doctest: +SKIP
        >>> metric.update(images, ["a photo of a cat", "a photo of a dog"])  # doctest: +SKIP
        >>> metric.compute()  # doctest: +SKIP
        Array(19..., dtype=float32)

    Two psum-able scalar states (score sum + sample count); the CLIP model runs
    inside ``update``. Pass ``model``/``processor`` to use a local Flax model.
    """

    is_differentiable = False
    higher_is_better = True
    full_state_update = True

    def __init__(
        self,
        model_name_or_path: str = "openai/clip-vit-large-patch14",
        model: Optional[Any] = None,
        processor: Optional[Any] = None,
        **kwargs: Any,
    ) -> None:
        super().__init__(**kwargs)
        if (model is None) != (processor is None):
            raise ValueError("Arguments `model` and `processor` must be provided together (or both omitted).")
        if model is None:
            model, processor = _get_model_and_processor(model_name_or_path)
        self.model = model
        self.processor = processor
        self.add_state("score", zero_state((), jnp.float32), dist_reduce_fx="sum")
        self.add_state("n_samples", zero_state((), jnp.int32), dist_reduce_fx="sum")

    def update(self, images: Union[Array, List[Array]], text: Union[str, List[str]]) -> None:
        score, n_samples = _clip_score_update(images, text, self.model, self.processor)
        self.score = self.score + jnp.sum(score)
        self.n_samples = self.n_samples + n_samples

    def compute(self) -> Array:
        return jnp.maximum(self.score / self.n_samples, jnp.asarray(0.0))
