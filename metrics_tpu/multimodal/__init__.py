"""Multimodal-domain module metrics (reference src/torchmetrics/multimodal/)."""

from metrics_tpu.multimodal.clip_score import CLIPScore

__all__ = ["CLIPScore"]
