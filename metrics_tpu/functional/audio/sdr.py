"""SDR / SI-SDR (reference src/torchmetrics/functional/audio/sdr.py).

TPU-first notes: the BSS-eval distortion filter is solved with FFT-based
auto/cross-correlations and a batched dense solve of the symmetric Toeplitz system —
all jittable jnp ops (the reference builds the Toeplitz matrix with as_strided,
sdr.py:36-60; here it is a gather on |i-j| which XLA fuses). The reference upcasts to
float64 (sdr.py:155-158); on TPU we accumulate in float32 by default and honor x64
when enabled — pass ``load_diag`` (e.g. 1e-8) to stabilize ill-conditioned systems.
"""

from __future__ import annotations

import math
from typing import Optional, Tuple

import jax
import jax.numpy as jnp
from jax import Array

from metrics_tpu.utils.checks import _check_same_shape


def _symmetric_toeplitz(vector: Array) -> Array:
    """Symmetric Toeplitz matrix from the first row, shape [..., L] -> [..., L, L]."""
    v_len = vector.shape[-1]
    idx = jnp.abs(jnp.arange(v_len)[:, None] - jnp.arange(v_len)[None, :])
    return vector[..., idx]


def _compute_autocorr_crosscorr(target: Array, preds: Array, corr_len: int) -> Tuple[Array, Array]:
    """FFT-based autocorrelation of target and cross-correlation with preds
    (reference sdr.py:63-90)."""
    n_fft = 2 ** math.ceil(math.log2(preds.shape[-1] + target.shape[-1] - 1))

    t_fft = jnp.fft.rfft(target, n=n_fft, axis=-1)
    r_0 = jnp.fft.irfft(t_fft.real**2 + t_fft.imag**2, n=n_fft)[..., :corr_len]

    p_fft = jnp.fft.rfft(preds, n=n_fft, axis=-1)
    b = jnp.fft.irfft(jnp.conj(t_fft) * p_fft, n=n_fft, axis=-1)[..., :corr_len]

    return r_0, b


def signal_distortion_ratio(
    preds: Array,
    target: Array,
    use_cg_iter: Optional[int] = None,
    filter_length: int = 512,
    zero_mean: bool = False,
    load_diag: Optional[float] = None,
) -> Array:
    """Signal-to-distortion ratio in dB per sample (reference sdr.py:93-202).

    ``use_cg_iter`` is accepted for API parity; the Toeplitz system is always solved
    directly (XLA-batched dense solve — the CG path exists in the reference only as a
    fast-bss-eval speed optimization).

    Args:
        preds: estimated signal ``(..., time)``
        target: reference signal ``(..., time)``
        use_cg_iter: accepted for parity, ignored (direct solve is used)
        filter_length: length of the allowed distortion filter
        zero_mean: subtract signal means before computation
        load_diag: diagonal loading to stabilize near-singular systems

    Example:
        >>> import jax.numpy as jnp
        >>> from metrics_tpu.functional import signal_distortion_ratio
        >>> import jax
        >>> key1, key2 = jax.random.split(jax.random.PRNGKey(0))
        >>> target = jax.random.normal(key1, (400,))
        >>> preds = target + 0.1 * jax.random.normal(key2, (400,))
        >>> signal_distortion_ratio(preds, target, filter_length=64)
        Array(20.753, dtype=float32)
    """
    _check_same_shape(preds, target)
    del use_cg_iter  # parity-only: direct batched solve is the TPU path

    preds_dtype = preds.dtype
    # float64 when x64 is enabled (CPU parity runs); float32 otherwise (TPU path)
    work_dtype = jnp.float64 if jax.config.jax_enable_x64 else jnp.float32
    preds = preds.astype(work_dtype)
    target = target.astype(work_dtype)

    if zero_mean:
        preds = preds - preds.mean(axis=-1, keepdims=True)
        target = target - target.mean(axis=-1, keepdims=True)

    # normalize along time-axis to unit norm
    target = target / jnp.maximum(jnp.linalg.norm(target, axis=-1, keepdims=True), 1e-6)
    preds = preds / jnp.maximum(jnp.linalg.norm(preds, axis=-1, keepdims=True), 1e-6)

    r_0, b = _compute_autocorr_crosscorr(target, preds, corr_len=filter_length)

    if load_diag is not None:
        r_0 = r_0.at[..., 0].add(load_diag)

    r = _symmetric_toeplitz(r_0)
    sol = jnp.linalg.solve(r, b[..., None])[..., 0]

    coh = jnp.einsum("...l,...l->...", b, sol)

    # Keep the result finite for degenerate inputs: a perfect reconstruction rounds
    # coh to exactly 1 in f32 (ratio -> inf), and an all-zero (silent) target makes
    # the Toeplitz system singular so solve() returns NaN. Clamp into (eps, 1-eps)
    # — caps SDR at ~±69 dB f32 instead of poisoning any running mean.
    eps = jnp.finfo(work_dtype).eps
    coh = jnp.clip(jnp.nan_to_num(coh, nan=0.0), eps, 1 - eps)
    ratio = coh / (1 - coh)
    val = 10.0 * jnp.log10(ratio)

    if preds_dtype == jnp.float64:
        return val
    return val.astype(jnp.float32)


def scale_invariant_signal_distortion_ratio(preds: Array, target: Array, zero_mean: bool = False) -> Array:
    """SI-SDR in dB per sample (reference sdr.py:205-245); fully jittable.

    Example:
        >>> import jax.numpy as jnp
        >>> target = jnp.asarray([3.0, -0.5, 2.0, 7.0])
        >>> preds = jnp.asarray([2.5, 0.0, 2.0, 8.0])
        >>> float(scale_invariant_signal_distortion_ratio(preds, target))  # doctest: +ELLIPSIS
        18.40...
    """
    _check_same_shape(preds, target)
    eps = jnp.finfo(preds.dtype).eps

    if zero_mean:
        target = target - jnp.mean(target, axis=-1, keepdims=True)
        preds = preds - jnp.mean(preds, axis=-1, keepdims=True)

    alpha = (jnp.sum(preds * target, axis=-1, keepdims=True) + eps) / (
        jnp.sum(target**2, axis=-1, keepdims=True) + eps
    )
    target_scaled = alpha * target
    noise = target_scaled - preds

    val = (jnp.sum(target_scaled**2, axis=-1) + eps) / (jnp.sum(noise**2, axis=-1) + eps)
    return 10 * jnp.log10(val)
