"""STOI / ESTOI (reference src/torchmetrics/functional/audio/stoi.py).

The reference is a thin wrapper over the C-backed ``pystoi`` pip package and
raises without it (ref stoi.py:24, 75-79). Here the DEFAULT backend is the
native jittable JAX implementation (:mod:`._stoi_native` — resample, STFT,
third-octave bands, silent-frame removal and segment correlation all in-trace,
TPU-compatible, zero optional deps); ``backend="pystoi"`` selects the wrapped
package for bit-level cross-checks and fails exactly like the reference when
it is not installed. The native path reproduces the reference's published
doctest value on seeded inputs (tests/audio/test_stoi_native.py).
"""

from __future__ import annotations

import numpy as np
import jax.numpy as jnp
from jax import Array

from metrics_tpu.functional.audio._stoi_native import native_stoi
from metrics_tpu.utils.checks import _check_same_shape
from metrics_tpu.utils.imports import _PYSTOI_AVAILABLE


def short_time_objective_intelligibility(
    preds: Array,
    target: Array,
    fs: int,
    extended: bool = False,
    keep_same_device: bool = False,
    backend: str = "native",
) -> Array:
    """STOI score per sample (reference stoi.py:29-94).

    Args:
        preds: estimated signal ``(..., time)``
        target: reference signal ``(..., time)``
        fs: sampling frequency in Hz
        extended: use the extended STOI (ESTOI) variant
        keep_same_device: return the score on the input device (the native
            backend always computes and returns on-device; this flag only
            affects the ``pystoi`` backend, mirroring the reference)
        backend: ``"native"`` (default — jittable JAX, runs anywhere) or
            ``"pystoi"`` (wraps the optional package, host-side; raises
            ``ModuleNotFoundError`` when not installed, like the reference)

    Example:
        >>> import jax.numpy as jnp
        >>> import numpy as np
        >>> from metrics_tpu.functional.audio import short_time_objective_intelligibility
        >>> rng = np.random.default_rng(0)
        >>> target = jnp.asarray(rng.normal(size=8000), jnp.float32)
        >>> preds = target + 0.1 * jnp.asarray(rng.normal(size=8000), jnp.float32)
        >>> bool(short_time_objective_intelligibility(preds, target, 8000) > 0.9)
        True
    """
    if backend == "native":
        _check_same_shape(preds, target)
        return native_stoi(preds, target, fs, extended)
    if backend != "pystoi":
        raise ValueError(f"backend must be 'native' or 'pystoi', got {backend!r}")

    # dependency gate fires BEFORE argument validation, mirroring the
    # reference's ordering (pinned by test_pesq_gate_precedes_arg_validation
    # for the sibling PESQ metric)
    if not _PYSTOI_AVAILABLE:
        raise ModuleNotFoundError(
            "STOI with backend='pystoi' requires that `pystoi` is installed. Either install as"
            " `pip install torchmetrics[audio]` or `pip install pystoi`, or use backend='native'."
        )

    import pystoi

    _check_same_shape(preds, target)
    if preds.ndim == 1:
        stoi_val_np = pystoi.stoi(np.asarray(target), np.asarray(preds), fs, extended)
        stoi_val = jnp.asarray(stoi_val_np, jnp.float32)
    else:
        preds_np = np.asarray(preds).reshape(-1, preds.shape[-1])
        target_np = np.asarray(target).reshape(-1, preds.shape[-1])
        stoi_val_np = np.empty(preds_np.shape[0])
        for b in range(preds_np.shape[0]):
            stoi_val_np[b] = pystoi.stoi(target_np[b, :], preds_np[b, :], fs, extended)
        stoi_val = jnp.asarray(stoi_val_np, jnp.float32).reshape(preds.shape[:-1])

    if keep_same_device:
        import jax

        stoi_val = jax.device_put(stoi_val, next(iter(preds.devices())))
    return stoi_val
