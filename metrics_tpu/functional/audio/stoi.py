"""STOI wrapper (reference src/torchmetrics/functional/audio/stoi.py).

Wraps the external ``pystoi`` package (host callback). Gated on package
availability exactly like the reference (stoi.py:22-26).
"""

from __future__ import annotations

import numpy as np
import jax.numpy as jnp
from jax import Array

from metrics_tpu.utils.checks import _check_same_shape
from metrics_tpu.utils.imports import _PYSTOI_AVAILABLE


def short_time_objective_intelligibility(
    preds: Array,
    target: Array,
    fs: int,
    extended: bool = False,
    keep_same_device: bool = False,
) -> Array:
    """STOI score per sample (reference stoi.py:29-94); host-side computation.

    Args:
        preds: estimated signal ``(..., time)``
        target: reference signal ``(..., time)``
        fs: sampling frequency in Hz
        extended: use the extended STOI variant
        keep_same_device: return the score on the input device

    Raises:
        ModuleNotFoundError: if the ``pystoi`` package is not installed.
    """
    if not _PYSTOI_AVAILABLE:
        raise ModuleNotFoundError(
            "ShortTimeObjectiveIntelligibility metric requires that `pystoi` is installed. Either install as"
            " `pip install torchmetrics[audio]` or `pip install pystoi`."
        )
    _check_same_shape(preds, target)

    import pystoi

    if preds.ndim == 1:
        stoi_val_np = pystoi.stoi(np.asarray(target), np.asarray(preds), fs, extended)
        stoi_val = jnp.asarray(stoi_val_np, jnp.float32)
    else:
        preds_np = np.asarray(preds).reshape(-1, preds.shape[-1])
        target_np = np.asarray(target).reshape(-1, preds.shape[-1])
        stoi_val_np = np.empty(preds_np.shape[0])
        for b in range(preds_np.shape[0]):
            stoi_val_np[b] = pystoi.stoi(target_np[b, :], preds_np[b, :], fs, extended)
        stoi_val = jnp.asarray(stoi_val_np, jnp.float32).reshape(preds.shape[:-1])

    if keep_same_device:
        import jax

        stoi_val = jax.device_put(stoi_val, next(iter(preds.devices())))
    return stoi_val
