"""Native jittable STOI/ESTOI (Taal et al. 2011; Jensen & Taal 2016).

The reference wraps the C-backed ``pystoi`` pip package and refuses to run
without it (ref src/torchmetrics/functional/audio/stoi.py:24, 75-79). STOI is
~150 lines of pure DSP, so this framework implements it end-to-end in JAX —
polyphase resample → hann STFT → 1/3-octave filterbank → silent-frame removal
→ 30-frame segment correlation — every stage fixed-shape and mask-based, so
the whole metric runs inside ``jax.jit`` on TPU (the same
exceed-the-reference move as the native ``iou_type='segm'`` mAP vs
pycocotools).

Algorithm constants and step order follow the published algorithm and the
pystoi reference implementation's conventions (MIT-licensed pystoi, Pariente;
not installed in this image — conventions reproduced from the published
algorithm description):

- internal rate 10 kHz; frames of 256 with hop 128 under ``hanning(258)[1:-1]``
- 512-point rfft; 15 one-third-octave bands from 150 Hz with edges
  ``150·2^((2k∓1)/6)`` snapped to the nearest rfft bin
- frames whose clean-signal energy is >40 dB below the loudest are removed and
  the survivors overlap-added back together before the STFT
- segments of N=30 frames; degraded segments are scaled to the clean segment's
  band norm and clipped at ``(1+10^(15/20))·clean`` (BETA = −15 dB); the score
  is the mean over bands and segments of the centred, normalised correlation
- extended mode (ESTOI) replaces scale+clip with row- then column-mean/variance
  normalisation of each segment block and averages ``Σ x̂·ŷ / N`` per segment

Documented deviations from pystoi (each invisible at ≥1e-4 on the reference
anchor, tests/audio/test_stoi_native.py):

- float32 throughout (TPU-native) with ``EPS = finfo(float32).eps`` in guarded
  divisions, where pystoi is float64 with f64 eps
- ESTOI's normalisation does not add pystoi's ``EPS·randn`` dither (that jitter
  is below f32 resolution and would make a jitted metric nondeterministic)
- a signal with fewer than 30 post-removal frames returns 1e-5 like pystoi,
  but the warning is only raisable on the eager path (inside jit the value is
  selected by ``jnp.where``)

The silent-frame machinery is the interesting TPU bit: pystoi drops frames by
boolean indexing (data-dependent shapes). Here frames are stably permuted so
survivors lead (``argsort`` of the drop mask), zeroed past the survivor count,
overlap-added into a fixed-length buffer, and every downstream stage carries a
segment-validity mask — identical numerics, static shapes.
"""

from __future__ import annotations

import fractions
import functools
import warnings
from typing import Tuple

import numpy as np

import jax
import jax.numpy as jnp
from jax import Array

FS = 10_000  # internal sample rate (Hz)
N_FRAME = 256
HOP = N_FRAME // 2
NFFT = 512
NUMBAND = 15
MINFREQ = 150.0
N_SEG = 30  # frames per intermediate-intelligibility segment
BETA = -15.0  # lower SDR bound (dB)
DYN_RANGE = 40.0  # silent-frame dynamic range (dB)
EPS = float(np.finfo(np.float32).eps)
TOO_SHORT_VALUE = 1e-5  # pystoi's sentinel when <N_SEG frames survive


@functools.lru_cache(maxsize=None)
def _third_octave_matrix() -> np.ndarray:
    """(NUMBAND, NFFT//2+1) 0/1 band matrix with edges snapped to rfft bins."""
    f = np.linspace(0, FS, NFFT + 1)[: NFFT // 2 + 1]
    k = np.arange(NUMBAND, dtype=np.float64)
    freq_low = MINFREQ * 2.0 ** ((2 * k - 1) / 6)
    freq_high = MINFREQ * 2.0 ** ((2 * k + 1) / 6)
    obm = np.zeros((NUMBAND, len(f)), np.float32)
    for i in range(NUMBAND):
        lo = int(np.argmin(np.square(f - freq_low[i])))
        hi = int(np.argmin(np.square(f - freq_high[i])))
        obm[i, lo:hi] = 1.0
    return obm


@functools.lru_cache(maxsize=None)
def _hann() -> np.ndarray:
    return np.hanning(N_FRAME + 2)[1:-1].astype(np.float32)


def _octave_resample_window(up: int, down: int) -> np.ndarray:
    """Octave-compatible anti-aliasing FIR (the resampler STOI was defined with).

    Standard Kaiser-design-by-formula lowpass (Oppenheim/Schafer): 60 dB
    stopband rejection, cutoff ``1/(2·max(up, down))``, roll-off width a tenth
    of the cutoff, ideal-sinc prototype apodised by the β-formula Kaiser
    window. This is deliberately NOT scipy's default resample_poly filter —
    STOI's published reference values assume the Octave/MATLAB ``resample``
    filter, and the two differ enough to move scores by ~2e-4.
    """
    rejection_db = 60.0
    cutoff = 1.0 / (2.0 * max(up, down))
    roll_off_width = cutoff / 10.0
    half_len = int(np.ceil((rejection_db - 8.0) / (28.714 * roll_off_width)))
    t = np.arange(-half_len, half_len + 1)
    ideal = 2 * up * cutoff * np.sinc(2 * cutoff * t)
    beta = 0.1102 * (rejection_db - 8.7)
    return np.kaiser(2 * half_len + 1, beta) * ideal


@functools.lru_cache(maxsize=None)
def _resample_plan(fs: int) -> Tuple[np.ndarray, int, int, int, int]:
    """(flipped padded FIR, up, down, n_pre_remove, len_h) for fs -> 10 kHz.

    Filter: the Octave-compatible window above, unit-sum normalised then
    scaled by ``up`` — numerically what ``scipy.signal.resample_poly(x, up,
    down, window=octave_window/sum)`` applies. Centring mirrors scipy's
    zero-pre-pad so the polyphase phase matches; parity vs scipy is asserted
    in tests/audio/test_stoi_native.py.
    """
    frac = fractions.Fraction(FS, int(fs))
    up, down = frac.numerator, frac.denominator
    h = _octave_resample_window(up, down).astype(np.float64)
    h = h / np.sum(h)
    half_len = (len(h) - 1) // 2
    h = h * up
    n_pre_pad = down - half_len % down
    n_pre_remove = (half_len + n_pre_pad) // down
    h = np.concatenate([np.zeros(n_pre_pad), h])
    # conv_general_dilated correlates; flip to convolve
    return h[::-1].astype(np.float32).copy(), up, down, n_pre_remove, len(h)


@functools.lru_cache(maxsize=None)
def _phase_kernel(fs: int):
    """(phase kernel (up, 1, K), up, down, n_pre_remove, K) — fs-keyed only.

    True polyphase decomposition of ``upfirdn(h, x, up, down)``: with
    ``y[j] = Σ_i x[i]·h[j·down − i·up]`` (the strided full convolution of the
    zero-stuffed input), put ``r = (j·down) mod up`` and ``s = (j·down) // up``;
    then ``y[j] = (x ⊛ h_r)[s]`` where ``h_r = h[r::up]`` is the r-th phase of
    the filter. All ``up`` phase convolutions run as ONE conv with ``up``
    output channels (the dilated-conv formulation made XLA-CPU grind through
    the zero-stuffed domain — measured ~30x slower), and the (phase, position)
    pair per output sample is a static numpy gather.
    """
    h, up, down, n_pre_remove, len_h = _resample_plan(fs)
    h = h[::-1]  # _resample_plan stores the flipped filter; unflip for indexing
    k = -(-len_h // up)
    phases = np.zeros((up, 1, k), np.float32)
    for r in range(up):
        taps = h[r::up]
        phases[r, 0, : len(taps)] = taps
    phases = phases[:, :, ::-1].copy()  # conv_general_dilated correlates; flip back
    return phases, up, down, n_pre_remove, k


def _resample_to_10k(x: Array, fs: int) -> Array:
    """Polyphase resample (..., T) -> (..., ceil(T*up/down)), scipy-equivalent."""
    if fs == FS:
        return x
    n_in = x.shape[-1]
    phases, up, down, n_pre_remove, k = _phase_kernel(fs)
    # the per-length (phase, position) gather indices are trivial arithmetic —
    # recomputed per trace rather than cached per (fs, n_in) pair
    n_out = -(-n_in * up // down)
    j = np.arange(n_pre_remove, n_pre_remove + n_out)
    phase_idx = (j * down % up).astype(np.int32)
    pos_idx = (j * down // up).astype(np.int32)
    lead = x.shape[:-1]
    lhs = x.reshape((-1, 1, n_in)).astype(jnp.float32)
    out = jax.lax.conv_general_dilated(
        lhs,
        jnp.asarray(phases),
        window_strides=(1,),
        padding=[(k - 1, k - 1)],
    )  # (B, up, n_in + k - 1): full conv of x with every phase filter
    # positions past the conv output are exact zeros (trailing virtual samples)
    needed = int(pos_idx.max()) + 1
    if needed > out.shape[-1]:
        out = jnp.pad(out, ((0, 0), (0, 0), (0, needed - out.shape[-1])))
    res = out[:, jnp.asarray(phase_idx), jnp.asarray(pos_idx)]
    return res.reshape(lead + (res.shape[-1],))


def _frame(x: Array) -> Array:
    """(..., T) -> (..., M, N_FRAME) hop-128 frames.

    Frame starts are ``range(0, T - N_FRAME, HOP)`` — an EXCLUSIVE stop, as in
    the reference pystoi implementation, so a frame ending exactly at T is
    dropped. The reference STOI values embody this convention (the post-OLA
    spectrogram always ends on an exact boundary, so it always loses its final
    frame); matching it is worth ~1.5e-4 on the published anchor.
    """
    n_frames = max((x.shape[-1] - N_FRAME + HOP - 1) // HOP, 0)
    idx = np.arange(n_frames)[:, None] * HOP + np.arange(N_FRAME)[None, :]
    return x[..., idx]


def _overlap_add(frames: Array) -> Array:
    """(M, N_FRAME) hop-128 frames -> ((M+1)*HOP,) signal via scatter-free OLA."""
    m = frames.shape[0]
    halves = frames.reshape(m, 2, HOP)
    slots = jnp.zeros((m + 1, HOP), frames.dtype)
    slots = slots.at[:m].add(halves[:, 0, :])
    slots = slots.at[1 : m + 1].add(halves[:, 1, :])
    return slots.reshape(-1)


def _stoi_pair(x: Array, y: Array, extended: bool) -> Array:
    """STOI of one (clean x, degraded y) pair, both already at 10 kHz, 1-D."""
    w = jnp.asarray(_hann())
    x_frames = _frame(x) * w
    y_frames = _frame(y) * w
    m = x_frames.shape[0]
    # the re-framed post-OLA signal statically yields m-1 spectral frames
    # (exact-alignment frame drop, see _frame); segments need N_SEG of those
    if m - 1 < N_SEG:
        # statically too short for even one segment: pystoi warns and returns
        # the sentinel at runtime; here the shape already proves it
        warnings.warn(
            "Not enough STFT segments to compute intermediate intelligibility measure; returning 1e-5",
            RuntimeWarning,
            stacklevel=4,
        )
        return jnp.float32(TOO_SHORT_VALUE)

    # ---- silent-frame removal (mask/permute form of pystoi's boolean indexing)
    energies = 20.0 * jnp.log10(jnp.linalg.norm(x_frames, axis=1) + EPS)
    keep = energies > (jnp.max(energies) - DYN_RANGE)
    n_kept = jnp.sum(keep)
    order = jnp.argsort(~keep, stable=True)  # survivors first, original order
    valid_frame = jnp.arange(m) < n_kept
    x_kept = x_frames[order] * valid_frame[:, None]
    y_kept = y_frames[order] * valid_frame[:, None]
    x_sil = _overlap_add(x_kept)
    y_sil = _overlap_add(y_kept)

    # ---- 1/3-octave band spectrogram (frames k >= n_kept are zero/garbage and
    # masked out at the segment stage)
    x_spec = jnp.fft.rfft(_frame(x_sil) * w, n=NFFT)
    y_spec = jnp.fft.rfft(_frame(y_sil) * w, n=NFFT)
    obm = jnp.asarray(_third_octave_matrix())
    x_tob = jnp.sqrt((jnp.abs(x_spec) ** 2) @ obm.T).T  # (NUMBAND, M)
    y_tob = jnp.sqrt((jnp.abs(y_spec) ** 2) @ obm.T).T

    # ---- N_SEG-frame segments with validity mask. The OLA signal really ends
    # after n_kept+1 half-frames, so its spectrogram has n_kept-1 valid frames
    # (the last aligned frame is dropped, as in the reference implementation)
    # and n_kept-N_SEG valid segments.
    n_segments = x_tob.shape[1] - N_SEG + 1  # static upper bound (= m-1-N_SEG+1)
    seg_idx = np.arange(n_segments)[:, None] + np.arange(N_SEG)[None, :]
    x_seg = x_tob[:, seg_idx]  # (NUMBAND, S, N_SEG)
    y_seg = y_tob[:, seg_idx]
    n_valid = jnp.maximum(n_kept - N_SEG, 0)
    valid_seg = (jnp.arange(n_segments) < n_valid)[None, :, None]

    if extended:

        def row_col_normalize(z):
            z = z - jnp.mean(z, axis=-1, keepdims=True)
            z = z / (jnp.linalg.norm(z, axis=-1, keepdims=True) + EPS)
            z = z - jnp.mean(z, axis=0, keepdims=True)
            return z / (jnp.linalg.norm(z, axis=0, keepdims=True) + EPS)

        x_n = row_col_normalize(x_seg)
        y_n = row_col_normalize(y_seg)
        d = jnp.sum(x_n * y_n * valid_seg) / (N_SEG * jnp.maximum(n_valid, 1))
    else:
        norm_x = jnp.linalg.norm(x_seg, axis=-1, keepdims=True)
        norm_y = jnp.linalg.norm(y_seg, axis=-1, keepdims=True)
        clip_value = 10.0 ** (-BETA / 20.0)
        y_prime = jnp.minimum(y_seg * norm_x / (norm_y + EPS), x_seg * (1.0 + clip_value))
        xc = x_seg - jnp.mean(x_seg, axis=-1, keepdims=True)
        yc = y_prime - jnp.mean(y_prime, axis=-1, keepdims=True)
        xc = xc / (jnp.linalg.norm(xc, axis=-1, keepdims=True) + EPS)
        yc = yc / (jnp.linalg.norm(yc, axis=-1, keepdims=True) + EPS)
        corr = jnp.sum(xc * yc * valid_seg, axis=-1)  # (NUMBAND, S)
        d = jnp.sum(corr) / (NUMBAND * jnp.maximum(n_valid, 1))

    return jnp.where(n_valid > 0, d, jnp.float32(TOO_SHORT_VALUE)).astype(jnp.float32)


@functools.partial(jax.jit, static_argnames=("fs", "extended"))
def _stoi_batch(preds: Array, target: Array, fs: int, extended: bool) -> Array:
    """(..., T) batched native STOI; clean reference is ``target``."""
    lead = preds.shape[:-1]
    p = _resample_to_10k(preds.reshape((-1, preds.shape[-1])).astype(jnp.float32), fs)
    t = _resample_to_10k(target.reshape((-1, target.shape[-1])).astype(jnp.float32), fs)
    vals = jax.vmap(lambda xt, yp: _stoi_pair(xt, yp, extended))(t, p)
    return vals.reshape(lead)


def native_stoi(preds: Array, target: Array, fs: int, extended: bool = False) -> Array:
    """Batched native STOI with the module-level constants above.

    ``preds``/``target``: (..., time). Returns shape ``preds.shape[:-1]``
    (0-d for 1-D inputs), float32, on the default device.
    """
    if fs <= 0 or not float(fs).is_integer():
        raise ValueError(f"fs must be a positive integer sample rate, got {fs}")
    return _stoi_batch(preds, target, int(fs), bool(extended))
