"""SNR / SI-SNR (reference src/torchmetrics/functional/audio/snr.py). Fully jittable."""

from __future__ import annotations

import jax.numpy as jnp
from jax import Array

from metrics_tpu.utils.checks import _check_same_shape
from metrics_tpu.functional.audio.sdr import scale_invariant_signal_distortion_ratio


def signal_noise_ratio(preds: Array, target: Array, zero_mean: bool = False) -> Array:
    """Signal-to-noise ratio in dB, per sample over the trailing time axis
    (reference snr.py:22-62).

    Example:
        >>> import jax.numpy as jnp
        >>> target = jnp.asarray([3.0, -0.5, 2.0, 7.0])
        >>> preds = jnp.asarray([2.5, 0.0, 2.0, 8.0])
        >>> float(signal_noise_ratio(preds, target))  # doctest: +ELLIPSIS
        16.180...
    """
    _check_same_shape(preds, target)
    eps = jnp.finfo(preds.dtype).eps

    if zero_mean:
        target = target - jnp.mean(target, axis=-1, keepdims=True)
        preds = preds - jnp.mean(preds, axis=-1, keepdims=True)

    noise = target - preds
    snr_value = (jnp.sum(target**2, axis=-1) + eps) / (jnp.sum(noise**2, axis=-1) + eps)
    return 10 * jnp.log10(snr_value)


def scale_invariant_signal_noise_ratio(preds: Array, target: Array) -> Array:
    """SI-SNR: SI-SDR with zero-mean normalization (reference snr.py:65-87).

    Example:
        >>> import jax.numpy as jnp
        >>> target = jnp.asarray([3.0, -0.5, 2.0, 7.0])
        >>> preds = jnp.asarray([2.5, 0.0, 2.0, 8.0])
        >>> float(scale_invariant_signal_noise_ratio(preds, target))  # doctest: +ELLIPSIS
        15.091...
    """
    return scale_invariant_signal_distortion_ratio(preds=preds, target=target, zero_mean=True)
