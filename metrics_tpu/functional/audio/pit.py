"""Permutation invariant training (reference src/torchmetrics/functional/audio/pit.py).

TPU-first redesign: the metric matrix is built with two vmaps over the speaker axes
(one traced ``metric_func`` call instead of the reference's spk² Python loop,
pit.py:140-152), and the best permutation is found by a fully-vectorized exhaustive
search over the spk! permutation table — jittable, static shapes, argmax on device.
The reference's scipy linear-sum-assignment path (pit.py:29-50) is kept as an
opt-in host fallback for large speaker counts where spk! explodes.
"""

from __future__ import annotations

from itertools import permutations
from typing import Any, Callable, Optional, Tuple

import jax
import jax.numpy as jnp
import numpy as np
from jax import Array

from metrics_tpu.utils.imports import _SCIPY_AVAILABLE
from metrics_tpu.utils.prints import rank_zero_warn

# cache of permutation tables keyed by speaker count (host-side constants)
_ps_dict: dict = {}


def _perm_table(spk_num: int) -> np.ndarray:
    """All permutations as an int array of shape [perm_num, spk_num]."""
    if spk_num not in _ps_dict:
        _ps_dict[spk_num] = np.asarray(list(permutations(range(spk_num))), dtype=np.int32)
    return _ps_dict[spk_num]


def _find_best_perm_by_exhaustive_method(metric_mtx: Array, eval_func: str) -> Tuple[Array, Array]:
    """Vectorized exhaustive assignment (reference pit.py:53-93), jittable.

    Args:
        metric_mtx: ``[batch, spk, spk]`` where entry [b, t, p] scores target t vs pred p
        eval_func: 'max' or 'min'
    """
    spk_num = metric_mtx.shape[-1]
    ps = jnp.asarray(_perm_table(spk_num))  # [perm_num, spk]
    # score of each permutation: mean over target index t of mtx[b, t, ps[k, t]]
    per_perm = jnp.mean(metric_mtx[:, jnp.arange(spk_num)[None, :], ps], axis=-1)  # [batch, perm_num]
    if eval_func == "max":
        best_idx = jnp.argmax(per_perm, axis=-1)
        best_metric = jnp.max(per_perm, axis=-1)
    else:
        best_idx = jnp.argmin(per_perm, axis=-1)
        best_metric = jnp.min(per_perm, axis=-1)
    best_perm = ps[best_idx]
    return best_metric, best_perm


def _find_best_perm_by_linear_sum_assignment(metric_mtx: Array, eval_func: str) -> Tuple[Array, Array]:
    """Host-side scipy Hungarian solver (reference pit.py:29-50); not jittable."""
    from scipy.optimize import linear_sum_assignment

    mmtx = np.asarray(metric_mtx)
    best_perm = jnp.asarray(
        np.stack([linear_sum_assignment(pwm, eval_func == "max")[1] for pwm in mmtx]), dtype=jnp.int32
    )
    best_metric = jnp.mean(jnp.take_along_axis(metric_mtx, best_perm[:, :, None], axis=2), axis=(-1, -2))
    return best_metric, best_perm


def permutation_invariant_training(
    preds: Array,
    target: Array,
    metric_func: Callable,
    eval_func: str = "max",
    use_linear_sum_assignment: Optional[bool] = None,
    **kwargs: Any,
) -> Tuple[Array, Array]:
    """PIT: best metric value over speaker permutations (reference pit.py:96-164).

    Args:
        preds: ``(batch, spk, ...)`` estimated signals
        target: ``(batch, spk, ...)`` reference signals
        metric_func: batched pairwise metric ``(preds, target, **kwargs) -> (batch,)``
        eval_func: 'max' (higher is better) or 'min'
        use_linear_sum_assignment: solver choice. ``None`` (default) follows the
            reference's auto rule (pit.py:156-162): the host-side scipy Hungarian
            solver for ``spk_num >= 3`` when available outside a trace, else the
            vectorized exhaustive search. ``True`` forces the Hungarian solver
            (errors if scipy is missing or inside jit); ``False`` forces the
            exhaustive ``spk!`` search.
        kwargs: forwarded to ``metric_func``

    Example:
        >>> import jax.numpy as jnp
        >>> from metrics_tpu.functional.audio import scale_invariant_signal_distortion_ratio
        >>> preds = jnp.asarray([[[-0.0579, 0.3560, -0.9604], [-0.1719, 0.3205, 0.2951]]])
        >>> target = jnp.asarray([[[1.0958, -0.1648, 0.5228], [-0.4100, 1.1942, -0.5103]]])
        >>> best_metric, best_perm = permutation_invariant_training(
        ...     preds, target, scale_invariant_signal_distortion_ratio, 'max')
        >>> best_perm.tolist()
        [[0, 1]]
    """
    if preds.shape[0:2] != target.shape[0:2]:
        raise RuntimeError(
            "Predictions and targets are expected to have the same shape at the batch and speaker dimensions"
        )
    if eval_func not in ["max", "min"]:
        raise ValueError(f'eval_func can only be "max" or "min" but got {eval_func}')
    if target.ndim < 2:
        raise ValueError(f"Inputs must be of shape [batch, spk, ...], got {target.shape} and {preds.shape} instead")

    spk_num = target.shape[1]
    batch_size = target.shape[0]
    idx = jnp.arange(spk_num)

    # metric matrix [batch, target_spk, pred_spk] via a double vmap over speaker axes —
    # ONE traced metric_func instead of the reference's spk² eager calls. Host-side
    # metric funcs (e.g. the PESQ/STOI wrappers) cannot run under vmap, so fall back
    # to the reference's eager pairwise loop for those.
    def pair_metric(t_idx: Array, p_idx: Array) -> Array:
        return metric_func(preds[:, p_idx, ...], target[:, t_idx, ...], **kwargs)

    try:
        metric_mtx = jax.vmap(lambda t: jax.vmap(lambda p: pair_metric(t, p))(idx))(idx)
        # [target_spk, pred_spk, batch] -> [batch, target_spk, pred_spk]
        metric_mtx = jnp.moveaxis(metric_mtx, -1, 0)
    except (jax.errors.TracerArrayConversionError, jax.errors.ConcretizationTypeError):
        rows = [
            jnp.stack([jnp.asarray(pair_metric(t, p)) for p in range(spk_num)], axis=-1)
            for t in range(spk_num)
        ]
        metric_mtx = jnp.stack(rows, axis=-2).reshape(batch_size, spk_num, spk_num)

    in_trace = isinstance(metric_mtx, jax.core.Tracer)
    if use_linear_sum_assignment is None:
        use_linear_sum_assignment = spk_num >= 3 and _SCIPY_AVAILABLE and not in_trace
        if spk_num >= 3 and not use_linear_sum_assignment:
            rank_zero_warn(
                f"For {spk_num} speakers the exhaustive search enumerates {spk_num}! permutations; the scipy"
                " Hungarian solver is faster but is unavailable"
                + (" inside jit/vmap traces." if in_trace else " (scipy not installed)."),
                UserWarning,
            )
    if use_linear_sum_assignment:
        if not _SCIPY_AVAILABLE:
            raise ModuleNotFoundError(
                "`use_linear_sum_assignment=True` requires that `scipy` is installed; the exhaustive"
                f" fallback would enumerate {spk_num}! permutations."
            )
        if in_trace:
            raise ValueError(
                "`use_linear_sum_assignment=True` runs a host-side scipy solver and cannot be used inside"
                " jit/shard_map traces; pass `use_linear_sum_assignment=False` there."
            )
        return _find_best_perm_by_linear_sum_assignment(metric_mtx, eval_func)
    return _find_best_perm_by_exhaustive_method(metric_mtx, eval_func)


def pit_permutate(preds: Array, perm: Array) -> Array:
    """Reorder ``preds`` speakers by ``perm`` (reference pit.py:167-178); jittable.

    Example:
        >>> import jax.numpy as jnp
        >>> from metrics_tpu.functional import pit_permutate
        >>> preds = jnp.array([[[1.0, 1.0], [2.0, 2.0]]])
        >>> perm = jnp.array([[1, 0]])
        >>> pit_permutate(preds, perm)
        Array([[[2., 2.],
                [1., 1.]]], dtype=float32)
    """
    return jnp.take_along_axis(preds, perm.reshape(perm.shape + (1,) * (preds.ndim - 2)), axis=1)
