"""PESQ wrapper (reference src/torchmetrics/functional/audio/pesq.py).

Wraps the external C-backed ``pesq`` package (host callback — the algorithm is a
standardized ITU-T P.862 implementation, not a tensor kernel). Gated on package
availability exactly like the reference (pesq.py:22-27).
"""

from __future__ import annotations

import numpy as np
import jax.numpy as jnp
from jax import Array

from metrics_tpu.utils.checks import _check_same_shape
from metrics_tpu.utils.imports import _PESQ_AVAILABLE


def perceptual_evaluation_speech_quality(
    preds: Array,
    target: Array,
    fs: int,
    mode: str,
    keep_same_device: bool = False,
) -> Array:
    """PESQ score per sample (reference pesq.py:30-115); host-side computation.

    Args:
        preds: estimated signal ``(..., time)``
        target: reference signal ``(..., time)``
        fs: sampling frequency (8000 or 16000)
        mode: ``'wb'`` (wide-band) or ``'nb'`` (narrow-band)
        keep_same_device: return the score on the input device

    Raises:
        ModuleNotFoundError: if the ``pesq`` package is not installed.
    """
    if not _PESQ_AVAILABLE:
        raise ModuleNotFoundError(
            "PESQ metric requires that pesq is installed. Either install as `pip install torchmetrics[audio]`"
            " or `pip install pesq`."
        )
    if fs not in (8000, 16000):
        raise ValueError(f"Expected argument `fs` to either be 8000 or 16000 but got {fs}")
    if mode not in ("wb", "nb"):
        raise ValueError(f"Expected argument `mode` to either be 'wb' or 'nb' but got {mode}")
    _check_same_shape(preds, target)

    import pesq as pesq_backend

    if preds.ndim == 1:
        pesq_val_np = pesq_backend.pesq(fs, np.asarray(target), np.asarray(preds), mode)
        pesq_val = jnp.asarray(pesq_val_np, jnp.float32)
    else:
        preds_np = np.asarray(preds).reshape(-1, preds.shape[-1])
        target_np = np.asarray(target).reshape(-1, preds.shape[-1])
        pesq_val_np = np.empty(preds_np.shape[0])
        for b in range(preds_np.shape[0]):
            pesq_val_np[b] = pesq_backend.pesq(fs, target_np[b, :], preds_np[b, :], mode)
        pesq_val = jnp.asarray(pesq_val_np, jnp.float32).reshape(preds.shape[:-1])

    if keep_same_device:
        import jax

        pesq_val = jax.device_put(pesq_val, next(iter(preds.devices())))
    return pesq_val
