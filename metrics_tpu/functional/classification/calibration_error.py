"""Top-label calibration error (ECE / MCE / RMSCE) functionals.

Reference parity: src/torchmetrics/functional/classification/calibration_error.py
(``_binning_bucketize`` :28, ``_ce_compute`` :60, binary :138, multiclass :245).

TPU-first notes: binning is a fixed-shape scatter (``segment_sum`` over ``n_bins``
buckets) — constant memory and jit-native. The module metric accumulates the per-bin
sums directly (conf/acc/count per bin), which is mathematically identical to the
reference's O(N) list states but syncs O(n_bins) scalars via psum instead of an
all_gather of every sample.
"""

from __future__ import annotations

from typing import Optional, Tuple

import jax
import jax.numpy as jnp
from jax import Array

from metrics_tpu.functional.classification.stat_scores import _ignore_mask, _sigmoid_if_logits, _softmax_if_logits
from metrics_tpu.utils.checks import _check_same_shape
from metrics_tpu.utils.compute import _safe_divide


def _ce_bucketize(
    confidences: Array, accuracies: Array, n_bins: int, weights: Optional[Array] = None
) -> Tuple[Array, Array, Array]:
    """Per-bin (accuracy-sum, confidence-sum, count) via one-hot segment sums.

    Bucketing matches the reference's ``torch.bucketize(conf, linspace(0,1,n+1)) - 1``
    (left-open bins ``(b_i, b_{i+1}]``, underflow clipped into bin 0).
    """
    bounds = jnp.linspace(0.0, 1.0, n_bins + 1, dtype=confidences.dtype)
    idx = jnp.clip(jnp.searchsorted(bounds, confidences, side="left") - 1, 0, n_bins - 1)
    w = weights if weights is not None else jnp.ones_like(confidences)
    onehot = jax.nn.one_hot(idx, n_bins, dtype=confidences.dtype) * w[:, None]  # (N, B)
    count_bin = jnp.sum(onehot, axis=0)
    conf_bin = confidences @ onehot
    acc_bin = accuracies.astype(confidences.dtype) @ onehot
    return acc_bin, conf_bin, count_bin


def _ce_compute_from_bins(acc_bin: Array, conf_bin: Array, count_bin: Array, norm: str = "l1") -> Array:
    """Calibration error from per-bin sums (reference ``_ce_compute`` :60-107)."""
    mean_acc = _safe_divide(acc_bin, count_bin)
    mean_conf = _safe_divide(conf_bin, count_bin)
    prop_bin = _safe_divide(count_bin, jnp.sum(count_bin))
    if norm == "l1":
        return jnp.sum(jnp.abs(mean_acc - mean_conf) * prop_bin)
    if norm == "max":
        return jnp.max(jnp.abs(mean_acc - mean_conf))
    if norm == "l2":
        ce = jnp.sum(jnp.square(mean_acc - mean_conf) * prop_bin)
        return jnp.where(ce > 0, jnp.sqrt(jnp.maximum(ce, 0.0)), 0.0)
    raise ValueError(f"Norm {norm} is not supported. Please select from l1, l2, or max. ")


def _ce_compute(confidences: Array, accuracies: Array, n_bins: int, norm: str = "l1", weights: Optional[Array] = None) -> Array:
    acc_bin, conf_bin, count_bin = _ce_bucketize(confidences, accuracies, n_bins, weights)
    return _ce_compute_from_bins(acc_bin, conf_bin, count_bin, norm)


def _binary_calibration_error_arg_validation(
    n_bins: int, norm: str = "l1", ignore_index: Optional[int] = None
) -> None:
    if not isinstance(n_bins, int) or n_bins < 1:
        raise ValueError(f"Expected argument `n_bins` to be an integer larger than 0, but got {n_bins}")
    if norm not in ("l1", "l2", "max"):
        raise ValueError(f"Expected argument `norm` to be one of ('l1', 'l2', 'max'), but got {norm}.")
    if ignore_index is not None and not isinstance(ignore_index, int):
        raise ValueError(f"Expected argument `ignore_index` to either be `None` or an integer, but got {ignore_index}")


def _binary_calibration_error_tensor_validation(preds: Array, target: Array, ignore_index: Optional[int] = None) -> None:
    _check_same_shape(preds, target)
    if not jnp.issubdtype(preds.dtype, jnp.floating):
        raise ValueError(
            "Expected argument `preds` to be floating tensor with probabilities/logits"
            f" but got tensor with dtype {preds.dtype}"
        )


def _binary_calibration_error_update(preds: Array, target: Array) -> Tuple[Array, Array]:
    """Binary case: confidence = positive-class probability, accuracy = target label.

    (Reference :133-135 — per-bin empirical positive rate vs mean predicted
    probability, verified against the reference doctest values.)
    """
    return preds, target.astype(preds.dtype)


def binary_calibration_error(
    preds: Array,
    target: Array,
    n_bins: int = 15,
    norm: str = "l1",
    ignore_index: Optional[int] = None,
    validate_args: bool = True,
) -> Array:
    """Top-label calibration error for binary tasks (reference :138-204)."""
    if validate_args:
        _binary_calibration_error_arg_validation(n_bins, norm, ignore_index)
        _binary_calibration_error_tensor_validation(preds, target, ignore_index)
    preds = jnp.asarray(preds).reshape(-1)
    target = jnp.asarray(target).reshape(-1)
    mask = _ignore_mask(target, ignore_index).reshape(-1).astype(preds.dtype)
    target = jnp.where(mask.astype(bool), target, 0)
    preds = _sigmoid_if_logits(preds)
    confidences, accuracies = _binary_calibration_error_update(preds, target)
    return _ce_compute(confidences, accuracies, n_bins, norm, weights=mask)


def _multiclass_calibration_error_arg_validation(
    num_classes: int, n_bins: int, norm: str = "l1", ignore_index: Optional[int] = None
) -> None:
    if not isinstance(num_classes, int) or num_classes < 2:
        raise ValueError(f"Expected argument `num_classes` to be an integer larger than 1, but got {num_classes}")
    _binary_calibration_error_arg_validation(n_bins, norm, ignore_index)


def _multiclass_calibration_error_tensor_validation(
    preds: Array, target: Array, num_classes: int, ignore_index: Optional[int] = None
) -> None:
    if preds.ndim != target.ndim + 1:
        raise ValueError("Expected `preds` to have one more dimension than `target`")
    if preds.shape[1] != num_classes:
        raise ValueError(f"Expected `preds.shape[1]={preds.shape[1]}` to equal `num_classes={num_classes}`")
    if not jnp.issubdtype(preds.dtype, jnp.floating):
        raise ValueError(
            "Expected argument `preds` to be floating tensor with probabilities/logits"
            f" but got tensor with dtype {preds.dtype}"
        )


def _multiclass_calibration_error_update(preds: Array, target: Array) -> Tuple[Array, Array]:
    """Top-1 confidence + correctness (reference :237-243)."""
    confidences = jnp.max(preds, axis=1)
    predictions = jnp.argmax(preds, axis=1)
    accuracies = (predictions == target).astype(preds.dtype)
    return confidences, accuracies


def multiclass_calibration_error(
    preds: Array,
    target: Array,
    num_classes: int,
    n_bins: int = 15,
    norm: str = "l1",
    ignore_index: Optional[int] = None,
    validate_args: bool = True,
) -> Array:
    """Top-label calibration error for multiclass tasks (reference :245-317)."""
    if validate_args:
        _multiclass_calibration_error_arg_validation(num_classes, n_bins, norm, ignore_index)
        _multiclass_calibration_error_tensor_validation(preds, target, num_classes, ignore_index)
    preds = jnp.moveaxis(jnp.asarray(preds), 1, -1).reshape(-1, num_classes)
    target = jnp.asarray(target).reshape(-1)
    mask = _ignore_mask(target, ignore_index).astype(preds.dtype)
    target = jnp.where(mask.astype(bool), target, 0)
    preds = _softmax_if_logits(preds, axis=-1)
    confidences, accuracies = _multiclass_calibration_error_update(preds, target)
    return _ce_compute(confidences, accuracies, n_bins, norm, weights=mask)


def calibration_error(
    preds: Array,
    target: Array,
    task: str,
    n_bins: int = 15,
    norm: str = "l1",
    num_classes: Optional[int] = None,
    ignore_index: Optional[int] = None,
    validate_args: bool = True,
) -> Array:
    """Task-dispatch façade (reference :320-…).

    Example:
        >>> import jax.numpy as jnp
        >>> from metrics_tpu.functional import calibration_error
        >>> preds = jnp.array([[0.7, 0.2, 0.1], [0.2, 0.6, 0.2], [0.1, 0.2, 0.7], [0.3, 0.4, 0.3]])
        >>> target = jnp.array([0, 1, 2, 1])
        >>> calibration_error(preds, target, task="multiclass", num_classes=3)
        Array(0.4, dtype=float32)
    """
    task = str(task).lower()
    if task == "binary":
        return binary_calibration_error(preds, target, n_bins, norm, ignore_index, validate_args)
    if task == "multiclass":
        assert isinstance(num_classes, int)
        return multiclass_calibration_error(preds, target, num_classes, n_bins, norm, ignore_index, validate_args)
    raise ValueError(f"Expected argument `task` to either be 'binary' or 'multiclass' but got {task}")
