"""Multilabel ranking functionals: coverage error, ranking average precision, ranking loss.

Reference parity: src/torchmetrics/functional/classification/ranking.py
(``_rank_data`` :26, coverage :47-105, rank-AP :108-176, rank-loss :179-246).

TPU-first notes: the reference ranks each sample in a Python loop with
``torch.unique``; here ranks are computed for the whole batch at once as boolean
comparison matrices reduced on the MXU (``O(N·L²)`` element ops, fully vectorized,
static shapes — no per-sample host loop).
"""

from __future__ import annotations

from typing import Optional, Tuple

import jax.numpy as jnp
from jax import Array

from metrics_tpu.functional.classification.stat_scores import _ignore_mask, _sigmoid_if_logits
from metrics_tpu.utils.checks import _check_same_shape
from metrics_tpu.utils.compute import _safe_divide


def _ranking_reduce(score: Array, n_elements: Array) -> Array:
    return _safe_divide(score, n_elements)


def _multilabel_ranking_arg_validation(num_labels: int, ignore_index: Optional[int] = None) -> None:
    if not isinstance(num_labels, int) or num_labels < 2:
        raise ValueError(f"Expected argument `num_labels` to be an integer larger than 1, but got {num_labels}")
    if ignore_index is not None and not isinstance(ignore_index, int):
        raise ValueError(f"Expected argument `ignore_index` to either be `None` or an integer, but got {ignore_index}")


def _multilabel_ranking_tensor_validation(
    preds: Array, target: Array, num_labels: int, ignore_index: Optional[int] = None
) -> None:
    _check_same_shape(preds, target)
    if preds.shape[1] != num_labels:
        raise ValueError(f"Expected `preds.shape[1]={preds.shape[1]}` to equal `num_labels={num_labels}`")
    if not jnp.issubdtype(preds.dtype, jnp.floating):
        raise ValueError(f"Expected preds tensor to be floating point, but received input with dtype {preds.dtype}")


def _multilabel_ranking_format(
    preds: Array, target: Array, num_labels: int, ignore_index: Optional[int] = None
) -> Tuple[Array, Array, Array]:
    """Flatten extra dims, sigmoid-if-logits; ignore_index → per-element 0/1 mask."""
    preds = jnp.moveaxis(jnp.asarray(preds), 1, -1).reshape(-1, num_labels)
    target = jnp.moveaxis(jnp.asarray(target), 1, -1).reshape(-1, num_labels)
    mask = _ignore_mask(target, ignore_index)
    target = jnp.where(mask, target, 0)
    preds = _sigmoid_if_logits(preds)
    return preds, target, mask


def _multilabel_coverage_error_update(preds: Array, target: Array) -> Tuple[Array, Array]:
    """Per sample: depth down the ranking needed to cover all true labels (reference :47-55)."""
    # lowest score among the relevant labels (offset pushes non-relevant above everything)
    offset = jnp.where(target == 0, jnp.abs(jnp.min(preds)) + 10.0, 0.0)
    preds_min = jnp.min(preds + offset, axis=1)
    coverage = jnp.sum(preds >= preds_min[:, None], axis=1).astype(jnp.float32)
    # samples with no relevant labels contribute 0 (the offset pushes preds_min above all)
    return jnp.sum(coverage), jnp.asarray(coverage.shape[0], dtype=jnp.float32)


def multilabel_coverage_error(
    preds: Array,
    target: Array,
    num_labels: int,
    ignore_index: Optional[int] = None,
    validate_args: bool = True,
) -> Array:
    """Multilabel coverage error (reference :57-105)."""
    if validate_args:
        _multilabel_ranking_arg_validation(num_labels, ignore_index)
        _multilabel_ranking_tensor_validation(preds, target, num_labels, ignore_index)
    preds, target, _ = _multilabel_ranking_format(preds, target, num_labels, ignore_index)
    coverage, total = _multilabel_coverage_error_update(preds, target)
    return _ranking_reduce(coverage, total)


def _multilabel_ranking_average_precision_update(preds: Array, target: Array) -> Tuple[Array, Array]:
    """Label-ranking AP, vectorized (reference :108-125 loops per sample).

    With max-rank tie handling (rank of x = #elements ≥ x, ties counted fully — the
    semantics of the reference's ``_rank_data`` on negated scores):
      rank_all[i,j]  = #labels k with preds[i,k] >= preds[i,j]
      rank_rel[i,j]  = #relevant labels k with preds[i,k] >= preds[i,j]
    and score_i = mean over relevant j of rank_rel/rank_all, with score_i = 1 when a
    sample has 0 or all-relevant labels.
    """
    n_labels = preds.shape[1]
    relevant = (target == 1).astype(preds.dtype)  # (N, L)
    ge = (preds[:, :, None] <= preds[:, None, :]).astype(preds.dtype)  # ge[i,j,k] = p[i,k] >= p[i,j]
    rank_all = jnp.sum(ge, axis=2)  # (N, L)
    rank_rel = jnp.einsum("ijk,ik->ij", ge, relevant)  # (N, L)
    n_rel = jnp.sum(relevant, axis=1)
    per_label = _safe_divide(rank_rel, rank_all) * relevant
    score = _safe_divide(jnp.sum(per_label, axis=1), n_rel)
    degenerate = (n_rel == 0) | (n_rel == n_labels)
    score = jnp.where(degenerate, 1.0, score)
    return jnp.sum(score), jnp.asarray(preds.shape[0], dtype=jnp.float32)


def multilabel_ranking_average_precision(
    preds: Array,
    target: Array,
    num_labels: int,
    ignore_index: Optional[int] = None,
    validate_args: bool = True,
) -> Array:
    """Label ranking average precision (reference :127-176)."""
    if validate_args:
        _multilabel_ranking_arg_validation(num_labels, ignore_index)
        _multilabel_ranking_tensor_validation(preds, target, num_labels, ignore_index)
    preds, target, _ = _multilabel_ranking_format(preds, target, num_labels, ignore_index)
    score, total = _multilabel_ranking_average_precision_update(preds, target)
    return _ranking_reduce(score, total)


def _multilabel_ranking_loss_update(preds: Array, target: Array) -> Tuple[Array, Array]:
    """Label-ranking loss, vectorized with a validity mask (reference :179-207).

    Samples with 0 or all-relevant labels are masked to 0 loss (the reference filters
    them out of the numerator but still divides by the full sample count).
    """
    n_labels = preds.shape[1]
    relevant = (target == 1).astype(preds.dtype)
    n_rel = jnp.sum(relevant, axis=1)
    valid = (n_rel > 0) & (n_rel < n_labels)
    # ascending positions (argsort of argsort), ties broken by position — same as reference
    inverse = jnp.argsort(jnp.argsort(preds, axis=1), axis=1).astype(preds.dtype)
    per_label_loss = (n_labels - inverse) * relevant
    correction = 0.5 * n_rel * (n_rel + 1)
    denom = n_rel * (n_labels - n_rel)
    loss = _safe_divide(jnp.sum(per_label_loss, axis=1) - correction, denom)
    loss = jnp.where(valid, loss, 0.0)
    return jnp.sum(loss), jnp.asarray(preds.shape[0], dtype=jnp.float32)


def multilabel_ranking_loss(
    preds: Array,
    target: Array,
    num_labels: int,
    ignore_index: Optional[int] = None,
    validate_args: bool = True,
) -> Array:
    """Label ranking loss (reference :209-246)."""
    if validate_args:
        _multilabel_ranking_arg_validation(num_labels, ignore_index)
        _multilabel_ranking_tensor_validation(preds, target, num_labels, ignore_index)
    preds, target, _ = _multilabel_ranking_format(preds, target, num_labels, ignore_index)
    loss, total = _multilabel_ranking_loss_update(preds, target)
    return _ranking_reduce(loss, total)
