"""Recall-at-fixed-precision functionals.

Reference parity: src/torchmetrics/functional/classification/recall_at_fixed_precision.py
(``_recall_at_precision`` :39-57, binary :83, multiclass :189, multilabel :…).

Computed from the precision-recall curve: the highest recall among curve points whose
precision ≥ ``min_precision``, plus the threshold achieving it (1e6 sentinel when no
point qualifies). The selection itself is a masked argmax — jit-friendly in binned mode.
"""

from __future__ import annotations

from typing import List, Optional, Tuple, Union

import jax.numpy as jnp
from jax import Array

from metrics_tpu.functional.classification.precision_recall_curve import (
    Thresholds,
    _binary_precision_recall_curve_arg_validation,
    _binary_precision_recall_curve_compute,
    _binary_precision_recall_curve_format,
    _binary_precision_recall_curve_tensor_validation,
    _binary_precision_recall_curve_update,
    _exact_mode_filter,
    _multiclass_precision_recall_curve_arg_validation,
    _multiclass_precision_recall_curve_compute,
    _multiclass_precision_recall_curve_format,
    _multiclass_precision_recall_curve_tensor_validation,
    _multiclass_precision_recall_curve_update,
    _multilabel_precision_recall_curve_arg_validation,
    _multilabel_precision_recall_curve_compute,
    _multilabel_precision_recall_curve_format,
    _multilabel_precision_recall_curve_tensor_validation,
    _multilabel_precision_recall_curve_update,
)


def _recall_at_precision(
    precision: Array, recall: Array, thresholds: Array, min_precision: float
) -> Tuple[Array, Array]:
    """Masked max over curve points with precision ≥ min_precision (reference :39-57).

    The curve's final sentinel point (precision=1, recall=0) has no threshold — it is
    excluded from the threshold lookup but its (1, 0) value cannot win the recall max
    anyway unless nothing qualifies, in which case recall=0/threshold=1e6 is returned.
    Exact-mode zero-positive curves (all-NaN recall) return (nan, thresholds[0]),
    matching the reference's tuple-max degeneration.
    """
    precision = jnp.asarray(precision)
    recall = jnp.asarray(recall)
    thresholds = jnp.asarray(thresholds, dtype=jnp.float32)
    n_t = thresholds.shape[0]
    precision, recall = precision[:n_t], recall[:n_t]
    qualify = precision >= min_precision
    # lexicographic max over (recall, precision, threshold) — parity with the
    # reference's ``max((r, p, t))`` tuple max, via three masked maxima
    masked_recall = jnp.where(qualify, recall, -jnp.inf)
    r_best = jnp.max(masked_recall)
    p_mask = qualify & (recall == r_best)
    p_best = jnp.max(jnp.where(p_mask, precision, -jnp.inf))
    t_mask = p_mask & (precision == p_best)
    t_best = jnp.max(jnp.where(t_mask, thresholds, -jnp.inf))
    max_recall = jnp.maximum(r_best, 0.0)
    max_recall = jnp.where(jnp.isfinite(max_recall), max_recall, 0.0)
    any_qualify = jnp.any(qualify) & (max_recall > 0.0)
    best_threshold = jnp.where(any_qualify, t_best, 1e6)
    # exact-mode zero-positive curve: recall is all-NaN (plain division in
    # _binary_precision_recall_curve_compute, reference semantics) and the
    # reference's python tuple-max then degenerates to the FIRST curve point,
    # returning (nan, thresholds[0]) — reproduce that instead of clamping to
    # the (0.0, 1e6) nothing-qualifies convention. NaN recall is all-or-none
    # (it only arises when tps[-1] == 0), so any() is equivalent to checking
    # the first point.
    if n_t:
        nan_curve = jnp.any(qualify) & jnp.any(jnp.isnan(recall))
        max_recall = jnp.where(nan_curve, jnp.asarray(jnp.nan, max_recall.dtype), max_recall)
        best_threshold = jnp.where(nan_curve, thresholds[0], best_threshold)
    return max_recall, best_threshold


def _binary_recall_at_fixed_precision_arg_validation(
    min_precision: float,
    thresholds: Thresholds = None,
    ignore_index: Optional[int] = None,
) -> None:
    _binary_precision_recall_curve_arg_validation(thresholds, ignore_index)
    if not isinstance(min_precision, float) or not (0 <= min_precision <= 1):
        raise ValueError(
            f"Expected argument `min_precision` to be an float in the [0,1] range, but got {min_precision}"
        )


def _binary_recall_at_fixed_precision_compute(
    state: Union[Array, Tuple[Array, Array]],
    thresholds: Optional[Array],
    min_precision: float,
    pos_label: int = 1,
) -> Tuple[Array, Array]:
    precision, recall, thresholds = _binary_precision_recall_curve_compute(state, thresholds, pos_label)
    return _recall_at_precision(precision, recall, thresholds, min_precision)


def binary_recall_at_fixed_precision(
    preds: Array,
    target: Array,
    min_precision: float,
    thresholds: Thresholds = None,
    ignore_index: Optional[int] = None,
    validate_args: bool = True,
) -> Tuple[Array, Array]:
    """Highest recall at the given minimum precision for binary tasks (reference :83-150)."""
    if validate_args:
        _binary_recall_at_fixed_precision_arg_validation(min_precision, thresholds, ignore_index)
        _binary_precision_recall_curve_tensor_validation(preds, target, ignore_index)
    preds, target, thresholds, mask = _binary_precision_recall_curve_format(preds, target, thresholds, ignore_index)
    if thresholds is None and ignore_index is not None:
        preds, target = _exact_mode_filter(preds, target, thresholds, ignore_index, mask)
        mask = None
    state = _binary_precision_recall_curve_update(preds, target, thresholds, mask)
    return _binary_recall_at_fixed_precision_compute(state, thresholds, min_precision)


def _multiclass_recall_at_fixed_precision_arg_validation(
    num_classes: int,
    min_precision: float,
    thresholds: Thresholds = None,
    ignore_index: Optional[int] = None,
) -> None:
    _multiclass_precision_recall_curve_arg_validation(num_classes, thresholds, ignore_index)
    if not isinstance(min_precision, float) or not (0 <= min_precision <= 1):
        raise ValueError(
            f"Expected argument `min_precision` to be an float in the [0,1] range, but got {min_precision}"
        )


def _multiclass_recall_at_fixed_precision_arg_compute(
    state: Union[Array, Tuple[Array, Array]],
    num_classes: int,
    thresholds: Optional[Array],
    min_precision: float,
) -> Tuple[Array, Array]:
    precision, recall, thresholds = _multiclass_precision_recall_curve_compute(state, num_classes, thresholds)
    if isinstance(precision, Array) and precision.ndim == 2:
        res = [_recall_at_precision(precision[i], recall[i], thresholds, min_precision) for i in range(num_classes)]
    else:
        res = [_recall_at_precision(p, r, t, min_precision) for p, r, t in zip(precision, recall, thresholds)]
    return jnp.stack([r[0] for r in res]), jnp.stack([r[1] for r in res])


def multiclass_recall_at_fixed_precision(
    preds: Array,
    target: Array,
    num_classes: int,
    min_precision: float,
    thresholds: Thresholds = None,
    ignore_index: Optional[int] = None,
    validate_args: bool = True,
) -> Tuple[Array, Array]:
    """Per-class highest recall at fixed precision (reference :189-…)."""
    if validate_args:
        _multiclass_recall_at_fixed_precision_arg_validation(num_classes, min_precision, thresholds, ignore_index)
        _multiclass_precision_recall_curve_tensor_validation(preds, target, num_classes, ignore_index)
    preds, target, thresholds, mask = _multiclass_precision_recall_curve_format(
        preds, target, num_classes, thresholds, ignore_index
    )
    if thresholds is None and ignore_index is not None:
        preds, target = _exact_mode_filter(preds, target, thresholds, ignore_index, mask)
        mask = None
    state = _multiclass_precision_recall_curve_update(preds, target, num_classes, thresholds, mask)
    return _multiclass_recall_at_fixed_precision_arg_compute(state, num_classes, thresholds, min_precision)


def _multilabel_recall_at_fixed_precision_arg_validation(
    num_labels: int,
    min_precision: float,
    thresholds: Thresholds = None,
    ignore_index: Optional[int] = None,
) -> None:
    _multilabel_precision_recall_curve_arg_validation(num_labels, thresholds, ignore_index)
    if not isinstance(min_precision, float) or not (0 <= min_precision <= 1):
        raise ValueError(
            f"Expected argument `min_precision` to be an float in the [0,1] range, but got {min_precision}"
        )


def _multilabel_recall_at_fixed_precision_arg_compute(
    state,
    num_labels: int,
    thresholds: Optional[Array],
    ignore_index: Optional[int],
    min_precision: float,
) -> Tuple[Array, Array]:
    precision, recall, thresholds = _multilabel_precision_recall_curve_compute(state, num_labels, thresholds, ignore_index)
    if isinstance(precision, Array) and precision.ndim == 2:
        res = [_recall_at_precision(precision[i], recall[i], thresholds, min_precision) for i in range(num_labels)]
    else:
        res = [_recall_at_precision(p, r, t, min_precision) for p, r, t in zip(precision, recall, thresholds)]
    return jnp.stack([r[0] for r in res]), jnp.stack([r[1] for r in res])


def multilabel_recall_at_fixed_precision(
    preds: Array,
    target: Array,
    num_labels: int,
    min_precision: float,
    thresholds: Thresholds = None,
    ignore_index: Optional[int] = None,
    validate_args: bool = True,
) -> Tuple[Array, Array]:
    """Per-label highest recall at fixed precision (reference :…)."""
    if validate_args:
        _multilabel_recall_at_fixed_precision_arg_validation(num_labels, min_precision, thresholds, ignore_index)
        _multilabel_precision_recall_curve_tensor_validation(preds, target, num_labels, ignore_index)
    preds, target, thresholds, mask = _multilabel_precision_recall_curve_format(
        preds, target, num_labels, thresholds, ignore_index
    )
    state = _multilabel_precision_recall_curve_update(preds, target, num_labels, thresholds, mask)
    return _multilabel_recall_at_fixed_precision_arg_compute(state, num_labels, thresholds, ignore_index, min_precision)
