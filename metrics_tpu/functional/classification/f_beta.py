"""F-beta / F1 functionals.

Reference parity: src/torchmetrics/functional/classification/f_beta.py
(``_fbeta_reduce`` + binary/multiclass/multilabel × fbeta/f1 + task façades).
"""

from __future__ import annotations

import functools
from typing import Optional

import jax
import jax.numpy as jnp
from jax import Array

from metrics_tpu.functional.classification._pipeline import binary_pipeline, multiclass_pipeline, multilabel_pipeline
from metrics_tpu.utils.compute import _adjust_weights_safe_divide, _safe_divide


@functools.partial(jax.jit, static_argnums=(4, 5, 6, 7))
def _fbeta_reduce(
    tp: Array,
    fp: Array,
    tn: Array,
    fn: Array,
    beta: float,
    average: Optional[str],
    multidim_average: str = "global",
    multilabel: bool = False,
) -> Array:
    """Jitted at definition: the reduce is ~10 small elementwise ops whose eager
    dispatch overhead otherwise dominates compute() on host (see
    ``_multiclass_stat_scores_update`` in stat_scores.py)."""
    beta2 = beta**2
    if average == "binary":
        return _safe_divide((1 + beta2) * tp, (1 + beta2) * tp + beta2 * fn + fp)
    if average == "micro":
        axis = 0 if multidim_average == "global" else 1
        tp = jnp.sum(tp, axis=axis)
        fn = jnp.sum(fn, axis=axis)
        fp = jnp.sum(fp, axis=axis)
        return _safe_divide((1 + beta2) * tp, (1 + beta2) * tp + beta2 * fn + fp)
    score = _safe_divide((1 + beta2) * tp, (1 + beta2) * tp + beta2 * fn + fp)
    return _adjust_weights_safe_divide(score, average, tp, fn)


def _validate_beta(beta: float) -> None:
    if not (isinstance(beta, float) and beta > 0):
        raise ValueError(f"Expected argument `beta` to be a float larger than 0, but got {beta}.")


def binary_fbeta_score(preds, target, beta, threshold=0.5, multidim_average="global", ignore_index=None, validate_args=True) -> Array:
    if validate_args:
        _validate_beta(beta)
    tp, fp, tn, fn = binary_pipeline(preds, target, threshold, multidim_average, ignore_index, validate_args)
    return _fbeta_reduce(tp, fp, tn, fn, beta, average="binary", multidim_average=multidim_average)


def multiclass_fbeta_score(preds, target, beta, num_classes, average="macro", top_k=1, multidim_average="global", ignore_index=None, validate_args=True) -> Array:
    if validate_args:
        _validate_beta(beta)
    tp, fp, tn, fn = multiclass_pipeline(preds, target, num_classes, average, top_k, multidim_average, ignore_index, validate_args)
    return _fbeta_reduce(tp, fp, tn, fn, beta, average=average, multidim_average=multidim_average)


def multilabel_fbeta_score(preds, target, beta, num_labels, threshold=0.5, average="macro", multidim_average="global", ignore_index=None, validate_args=True) -> Array:
    if validate_args:
        _validate_beta(beta)
    tp, fp, tn, fn = multilabel_pipeline(preds, target, num_labels, threshold, average, multidim_average, ignore_index, validate_args)
    return _fbeta_reduce(tp, fp, tn, fn, beta, average=average, multidim_average=multidim_average, multilabel=True)


def binary_f1_score(preds, target, threshold=0.5, multidim_average="global", ignore_index=None, validate_args=True) -> Array:
    return binary_fbeta_score(preds, target, 1.0, threshold, multidim_average, ignore_index, validate_args)


def multiclass_f1_score(preds, target, num_classes, average="macro", top_k=1, multidim_average="global", ignore_index=None, validate_args=True) -> Array:
    return multiclass_fbeta_score(preds, target, 1.0, num_classes, average, top_k, multidim_average, ignore_index, validate_args)


def multilabel_f1_score(preds, target, num_labels, threshold=0.5, average="macro", multidim_average="global", ignore_index=None, validate_args=True) -> Array:
    return multilabel_fbeta_score(preds, target, 1.0, num_labels, threshold, average, multidim_average, ignore_index, validate_args)


def fbeta_score(
    preds, target, task, beta=1.0, threshold=0.5, num_classes=None, num_labels=None, average="micro",
    multidim_average="global", top_k=1, ignore_index=None, validate_args=True,
) -> Array:
    """Task-dispatch façade over binary/multiclass/multilabel F-beta (reference functional/classification/f_beta.py).

    Example:
        >>> import jax.numpy as jnp
        >>> from metrics_tpu.functional import fbeta_score
        >>> fbeta_score(jnp.array([0, 2, 1, 2]), jnp.array([0, 1, 1, 2]), task="multiclass", num_classes=3, beta=0.5)
        Array(0.75, dtype=float32)
    """
    task = str(task).lower()
    if task == "binary":
        return binary_fbeta_score(preds, target, beta, threshold, multidim_average, ignore_index, validate_args)
    if task == "multiclass":
        return multiclass_fbeta_score(preds, target, beta, num_classes, average, top_k, multidim_average, ignore_index, validate_args)
    if task == "multilabel":
        return multilabel_fbeta_score(preds, target, beta, num_labels, threshold, average, multidim_average, ignore_index, validate_args)
    raise ValueError(f"Expected argument `task` to either be 'binary', 'multiclass' or 'multilabel' but got {task}")


def f1_score(
    preds, target, task, threshold=0.5, num_classes=None, num_labels=None, average="micro",
    multidim_average="global", top_k=1, ignore_index=None, validate_args=True,
) -> Array:
    """Task-dispatch façade over binary/multiclass/multilabel F1 (reference functional/classification/f_beta.py).

    Example:
        >>> import jax.numpy as jnp
        >>> from metrics_tpu.functional import f1_score
        >>> f1_score(jnp.array([0, 2, 1, 2]), jnp.array([0, 1, 1, 2]), task="multiclass", num_classes=3)
        Array(0.75, dtype=float32)
    """
    return fbeta_score(preds, target, task, 1.0, threshold, num_classes, num_labels, average, multidim_average, top_k, ignore_index, validate_args)
