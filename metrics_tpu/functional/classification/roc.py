"""ROC curve functionals.

Reference parity: src/torchmetrics/functional/classification/roc.py
(binary/multiclass/multilabel ``_*_roc_compute`` reusing the PRC state pipeline).
"""

from __future__ import annotations

from typing import List, Optional, Tuple, Union

import jax.numpy as jnp
from jax import Array

from metrics_tpu.functional.classification.precision_recall_curve import (
    Thresholds,
    _exact_mode_filter,
    _binary_clf_curve,
    _binary_precision_recall_curve_arg_validation,
    _binary_precision_recall_curve_format,
    _binary_precision_recall_curve_tensor_validation,
    _binary_precision_recall_curve_update,
    _multiclass_precision_recall_curve_arg_validation,
    _multiclass_precision_recall_curve_format,
    _multiclass_precision_recall_curve_tensor_validation,
    _multiclass_precision_recall_curve_update,
    _multilabel_precision_recall_curve_arg_validation,
    _multilabel_precision_recall_curve_format,
    _multilabel_precision_recall_curve_tensor_validation,
    _multilabel_precision_recall_curve_update,
)
from metrics_tpu.utils.checks import _value_check_possible
from metrics_tpu.utils.compute import _safe_divide


def _binary_roc_compute(
    state: Union[Array, Tuple[Array, Array]],
    thresholds: Optional[Array],
    pos_label: int = 1,
) -> Tuple[Array, Array, Array]:
    """Reference roc.py ``_binary_roc_compute``."""
    if isinstance(state, Array) and thresholds is not None:
        tps = state[:, 1, 1]
        fps = state[:, 0, 1]
        fns = state[:, 1, 0]
        tns = state[:, 0, 0]
        tpr = _safe_divide(tps, tps + fns)[::-1]
        fpr = _safe_divide(fps, fps + tns)[::-1]
        thres = thresholds[::-1]
        return fpr, tpr, thres

    preds, target = state
    fps, tps, thres = _binary_clf_curve(preds, target, pos_label=pos_label, drop_ignore_sentinel=True)
    # add an extra threshold so the curve starts at (0, 0); the sentinel is a
    # constant 1.0 (reference roc.py:57 — probability semantics), not sklearn's
    # max-score + 1
    tps = jnp.concatenate([jnp.zeros(1, dtype=tps.dtype), tps])
    fps = jnp.concatenate([jnp.zeros(1, dtype=fps.dtype), fps])
    thres = jnp.concatenate([jnp.ones(1, dtype=thres.dtype), thres])
    fpr = _safe_divide(fps, fps[-1])
    tpr = _safe_divide(tps, tps[-1])
    return fpr, tpr, thres


def binary_roc(
    preds: Array,
    target: Array,
    thresholds: Thresholds = None,
    ignore_index: Optional[int] = None,
    validate_args: bool = True,
) -> Tuple[Array, Array, Array]:
    if validate_args:
        _binary_precision_recall_curve_arg_validation(thresholds, ignore_index)
        _binary_precision_recall_curve_tensor_validation(preds, target, ignore_index)
    preds, target, thresholds, mask = _binary_precision_recall_curve_format(preds, target, thresholds, ignore_index)
    if thresholds is None and ignore_index is not None:
        preds, target = _exact_mode_filter(preds, target, thresholds, ignore_index, mask)
        mask = None
    state = _binary_precision_recall_curve_update(preds, target, thresholds, mask)
    return _binary_roc_compute(state, thresholds)


def _multiclass_roc_compute(
    state: Union[Array, Tuple[Array, Array]],
    num_classes: int,
    thresholds: Optional[Array],
):
    if isinstance(state, Array) and thresholds is not None:
        tps = state[:, :, 1, 1]
        fps = state[:, :, 0, 1]
        fns = state[:, :, 1, 0]
        tns = state[:, :, 0, 0]
        tpr = _safe_divide(tps, tps + fns)[::-1].T
        fpr = _safe_divide(fps, fps + tns)[::-1].T
        thres = thresholds[::-1]
        return fpr, tpr, thres

    preds, target = state
    fpr_list, tpr_list, thres_list = [], [], []
    for i in range(num_classes):
        res = _binary_roc_compute((preds[:, i], (target == i).astype(jnp.int32)), thresholds=None, pos_label=1)
        fpr_list.append(res[0])
        tpr_list.append(res[1])
        thres_list.append(res[2])
    return fpr_list, tpr_list, thres_list


def multiclass_roc(
    preds: Array,
    target: Array,
    num_classes: int,
    thresholds: Thresholds = None,
    ignore_index: Optional[int] = None,
    validate_args: bool = True,
):
    if validate_args:
        _multiclass_precision_recall_curve_arg_validation(num_classes, thresholds, ignore_index)
        _multiclass_precision_recall_curve_tensor_validation(preds, target, num_classes, ignore_index)
    preds, target, thresholds, mask = _multiclass_precision_recall_curve_format(
        preds, target, num_classes, thresholds, ignore_index
    )
    if thresholds is None and ignore_index is not None:
        preds, target = _exact_mode_filter(preds, target, thresholds, ignore_index, mask)
        mask = None
    state = _multiclass_precision_recall_curve_update(preds, target, num_classes, thresholds, mask)
    return _multiclass_roc_compute(state, num_classes, thresholds)


def _multilabel_roc_compute(
    state,
    num_labels: int,
    thresholds: Optional[Array],
    ignore_index: Optional[int] = None,
):
    if isinstance(state, Array) and thresholds is not None:
        return _multiclass_roc_compute(state, num_labels, thresholds)
    preds, target, mask = state
    fpr_list, tpr_list, thres_list = [], [], []
    for i in range(num_labels):
        p, t, m = preds[:, i], target[:, i], mask[:, i]
        if _value_check_possible(m):
            p, t = p[m], t[m]
        res = _binary_roc_compute((p, t), thresholds=None, pos_label=1)
        fpr_list.append(res[0])
        tpr_list.append(res[1])
        thres_list.append(res[2])
    return fpr_list, tpr_list, thres_list


def multilabel_roc(
    preds: Array,
    target: Array,
    num_labels: int,
    thresholds: Thresholds = None,
    ignore_index: Optional[int] = None,
    validate_args: bool = True,
):
    if validate_args:
        _multilabel_precision_recall_curve_arg_validation(num_labels, thresholds, ignore_index)
        _multilabel_precision_recall_curve_tensor_validation(preds, target, num_labels, ignore_index)
    preds, target, thresholds, mask = _multilabel_precision_recall_curve_format(
        preds, target, num_labels, thresholds, ignore_index
    )
    state = _multilabel_precision_recall_curve_update(preds, target, num_labels, thresholds, mask)
    return _multilabel_roc_compute(state, num_labels, thresholds, ignore_index)


def roc(
    preds: Array,
    target: Array,
    task: str,
    thresholds: Thresholds = None,
    num_classes: Optional[int] = None,
    num_labels: Optional[int] = None,
    ignore_index: Optional[int] = None,
    validate_args: bool = True,
):
    """Task-dispatch façade over binary/multiclass/multilabel ROC curves (reference functional/classification/roc.py).

    Example:
        >>> import jax.numpy as jnp
        >>> from metrics_tpu.functional import roc
        >>> preds = jnp.array([0.1, 0.6, 0.8, 0.4])
        >>> target = jnp.array([0, 1, 1, 0])
        >>> fpr, tpr, thresholds = roc(preds, target, task="binary", thresholds=4)
        >>> tpr
        Array([0. , 0.5, 1. , 1. ], dtype=float32)
    """
    task = str(task).lower()
    if task == "binary":
        return binary_roc(preds, target, thresholds, ignore_index, validate_args)
    if task == "multiclass":
        assert isinstance(num_classes, int)
        return multiclass_roc(preds, target, num_classes, thresholds, ignore_index, validate_args)
    if task == "multilabel":
        assert isinstance(num_labels, int)
        return multilabel_roc(preds, target, num_labels, thresholds, ignore_index, validate_args)
    raise ValueError(f"Expected argument `task` to either be 'binary', 'multiclass' or 'multilabel' but got {task}")
