"""Specificity-at-sensitivity functionals.

Reference parity: src/torchmetrics/functional/classification/specificity_at_sensitivity.py
(``_specificity_at_sensitivity`` :46-70, binary :96, multiclass :201, multilabel :316).

Computed from the ROC curve: among points with sensitivity (TPR) ≥ ``min_sensitivity``,
the maximum specificity (1 - FPR) and its threshold (1e6 sentinel when none qualify).
"""

from __future__ import annotations

from typing import List, Optional, Tuple, Union

import jax.numpy as jnp
from jax import Array

from metrics_tpu.functional.classification.precision_recall_curve import (
    Thresholds,
    _binary_precision_recall_curve_arg_validation,
    _binary_precision_recall_curve_format,
    _binary_precision_recall_curve_tensor_validation,
    _binary_precision_recall_curve_update,
    _exact_mode_filter,
    _multiclass_precision_recall_curve_arg_validation,
    _multiclass_precision_recall_curve_format,
    _multiclass_precision_recall_curve_tensor_validation,
    _multiclass_precision_recall_curve_update,
    _multilabel_precision_recall_curve_arg_validation,
    _multilabel_precision_recall_curve_format,
    _multilabel_precision_recall_curve_tensor_validation,
    _multilabel_precision_recall_curve_update,
)
from metrics_tpu.functional.classification.roc import (
    _binary_roc_compute,
    _multiclass_roc_compute,
    _multilabel_roc_compute,
)


def _convert_fpr_to_specificity(fpr: Array) -> Array:
    return 1 - fpr


def _specificity_at_sensitivity(
    specificity: Array, sensitivity: Array, thresholds: Array, min_sensitivity: float
) -> Tuple[Array, Array]:
    """Masked max over ROC points with sensitivity ≥ min_sensitivity (reference :46-70)."""
    specificity = jnp.asarray(specificity)
    sensitivity = jnp.asarray(sensitivity)
    thresholds = jnp.asarray(thresholds, dtype=jnp.float32)
    n = min(specificity.shape[0], sensitivity.shape[0], thresholds.shape[0])
    qualify = sensitivity[:n] >= min_sensitivity
    masked_spec = jnp.where(qualify, specificity[:n], -jnp.inf)
    best = jnp.argmax(masked_spec)
    any_qualify = jnp.any(qualify)
    max_spec = jnp.where(any_qualify, jnp.maximum(masked_spec[best], -jnp.inf), 0.0)
    max_spec = jnp.where(jnp.isfinite(max_spec), max_spec, 0.0)
    best_threshold = jnp.where(any_qualify, thresholds[best], 1e6)
    return max_spec, best_threshold


def _binary_specificity_at_sensitivity_arg_validation(
    min_sensitivity: float,
    thresholds: Thresholds = None,
    ignore_index: Optional[int] = None,
) -> None:
    _binary_precision_recall_curve_arg_validation(thresholds, ignore_index)
    if not isinstance(min_sensitivity, float) or not (0 <= min_sensitivity <= 1):
        raise ValueError(
            f"Expected argument `min_sensitivity` to be an float in the [0,1] range, but got {min_sensitivity}"
        )


def _binary_specificity_at_sensitivity_compute(
    state: Union[Array, Tuple[Array, Array]],
    thresholds: Optional[Array],
    min_sensitivity: float,
    pos_label: int = 1,
) -> Tuple[Array, Array]:
    fpr, sensitivity, thresholds = _binary_roc_compute(state, thresholds, pos_label)
    specificity = _convert_fpr_to_specificity(fpr)
    return _specificity_at_sensitivity(specificity, sensitivity, thresholds, min_sensitivity)


def binary_specificity_at_sensitivity(
    preds: Array,
    target: Array,
    min_sensitivity: float,
    thresholds: Thresholds = None,
    ignore_index: Optional[int] = None,
    validate_args: bool = True,
) -> Tuple[Array, Array]:
    """Highest specificity at the given minimum sensitivity for binary tasks (reference :96-163)."""
    if validate_args:
        _binary_specificity_at_sensitivity_arg_validation(min_sensitivity, thresholds, ignore_index)
        _binary_precision_recall_curve_tensor_validation(preds, target, ignore_index)
    preds, target, thresholds, mask = _binary_precision_recall_curve_format(preds, target, thresholds, ignore_index)
    if thresholds is None and ignore_index is not None:
        preds, target = _exact_mode_filter(preds, target, thresholds, ignore_index, mask)
        mask = None
    state = _binary_precision_recall_curve_update(preds, target, thresholds, mask)
    return _binary_specificity_at_sensitivity_compute(state, thresholds, min_sensitivity)


def _multiclass_specificity_at_sensitivity_arg_validation(
    num_classes: int,
    min_sensitivity: float,
    thresholds: Thresholds = None,
    ignore_index: Optional[int] = None,
) -> None:
    _multiclass_precision_recall_curve_arg_validation(num_classes, thresholds, ignore_index)
    if not isinstance(min_sensitivity, float) or not (0 <= min_sensitivity <= 1):
        raise ValueError(
            f"Expected argument `min_sensitivity` to be an float in the [0,1] range, but got {min_sensitivity}"
        )


def _multiclass_specificity_at_sensitivity_compute(
    state: Union[Array, Tuple[Array, Array]],
    num_classes: int,
    thresholds: Optional[Array],
    min_sensitivity: float,
) -> Tuple[Array, Array]:
    fpr, sensitivity, thresholds = _multiclass_roc_compute(state, num_classes, thresholds)
    if isinstance(fpr, Array) and fpr.ndim == 2:
        res = [
            _specificity_at_sensitivity(_convert_fpr_to_specificity(fpr[i]), sensitivity[i], thresholds, min_sensitivity)
            for i in range(num_classes)
        ]
    else:
        res = [
            _specificity_at_sensitivity(_convert_fpr_to_specificity(f), s, t, min_sensitivity)
            for f, s, t in zip(fpr, sensitivity, thresholds)
        ]
    return jnp.stack([r[0] for r in res]), jnp.stack([r[1] for r in res])


def multiclass_specificity_at_sensitivity(
    preds: Array,
    target: Array,
    num_classes: int,
    min_sensitivity: float,
    thresholds: Thresholds = None,
    ignore_index: Optional[int] = None,
    validate_args: bool = True,
) -> Tuple[Array, Array]:
    """Per-class highest specificity at fixed sensitivity (reference :201-277)."""
    if validate_args:
        _multiclass_specificity_at_sensitivity_arg_validation(num_classes, min_sensitivity, thresholds, ignore_index)
        _multiclass_precision_recall_curve_tensor_validation(preds, target, num_classes, ignore_index)
    preds, target, thresholds, mask = _multiclass_precision_recall_curve_format(
        preds, target, num_classes, thresholds, ignore_index
    )
    if thresholds is None and ignore_index is not None:
        preds, target = _exact_mode_filter(preds, target, thresholds, ignore_index, mask)
        mask = None
    state = _multiclass_precision_recall_curve_update(preds, target, num_classes, thresholds, mask)
    return _multiclass_specificity_at_sensitivity_compute(state, num_classes, thresholds, min_sensitivity)


def _multilabel_specificity_at_sensitivity_arg_validation(
    num_labels: int,
    min_sensitivity: float,
    thresholds: Thresholds = None,
    ignore_index: Optional[int] = None,
) -> None:
    _multilabel_precision_recall_curve_arg_validation(num_labels, thresholds, ignore_index)
    if not isinstance(min_sensitivity, float) or not (0 <= min_sensitivity <= 1):
        raise ValueError(
            f"Expected argument `min_sensitivity` to be an float in the [0,1] range, but got {min_sensitivity}"
        )


def _multilabel_specificity_at_sensitivity_compute(
    state,
    num_labels: int,
    thresholds: Optional[Array],
    ignore_index: Optional[int],
    min_sensitivity: float,
) -> Tuple[Array, Array]:
    fpr, sensitivity, thresholds = _multilabel_roc_compute(state, num_labels, thresholds, ignore_index)
    if isinstance(fpr, Array) and fpr.ndim == 2:
        res = [
            _specificity_at_sensitivity(_convert_fpr_to_specificity(fpr[i]), sensitivity[i], thresholds, min_sensitivity)
            for i in range(num_labels)
        ]
    else:
        res = [
            _specificity_at_sensitivity(_convert_fpr_to_specificity(f), s, t, min_sensitivity)
            for f, s, t in zip(fpr, sensitivity, thresholds)
        ]
    return jnp.stack([r[0] for r in res]), jnp.stack([r[1] for r in res])


def multilabel_specificity_at_sensitivity(
    preds: Array,
    target: Array,
    num_labels: int,
    min_sensitivity: float,
    thresholds: Thresholds = None,
    ignore_index: Optional[int] = None,
    validate_args: bool = True,
) -> Tuple[Array, Array]:
    """Per-label highest specificity at fixed sensitivity (reference :316-…)."""
    if validate_args:
        _multilabel_specificity_at_sensitivity_arg_validation(num_labels, min_sensitivity, thresholds, ignore_index)
        _multilabel_precision_recall_curve_tensor_validation(preds, target, num_labels, ignore_index)
    preds, target, thresholds, mask = _multilabel_precision_recall_curve_format(
        preds, target, num_labels, thresholds, ignore_index
    )
    state = _multilabel_precision_recall_curve_update(preds, target, num_labels, thresholds, mask)
    return _multilabel_specificity_at_sensitivity_compute(state, num_labels, thresholds, ignore_index, min_sensitivity)
