"""Precision / Recall functionals.

Reference parity: src/torchmetrics/functional/classification/precision_recall.py
(``_precision_recall_reduce`` + 6 entry points + 2 task façades).
"""

from __future__ import annotations

from typing import Optional

import jax.numpy as jnp
from jax import Array

from metrics_tpu.functional.classification._pipeline import binary_pipeline, multiclass_pipeline, multilabel_pipeline
from metrics_tpu.utils.compute import _adjust_weights_safe_divide, _safe_divide


def _precision_recall_reduce(
    stat: str,
    tp: Array,
    fp: Array,
    tn: Array,
    fn: Array,
    average: Optional[str],
    multidim_average: str = "global",
    multilabel: bool = False,
) -> Array:
    different_stat = fp if stat == "precision" else fn  # P = tp/(tp+fp), R = tp/(tp+fn)
    if average == "binary":
        return _safe_divide(tp, tp + different_stat)
    if average == "micro":
        axis = 0 if multidim_average == "global" else 1
        tp = jnp.sum(tp, axis=axis)
        different_stat = jnp.sum(different_stat, axis=axis)
        return _safe_divide(tp, tp + different_stat)
    score = _safe_divide(tp, tp + different_stat)
    return _adjust_weights_safe_divide(score, average, tp, fn)


def binary_precision(preds, target, threshold=0.5, multidim_average="global", ignore_index=None, validate_args=True) -> Array:
    tp, fp, tn, fn = binary_pipeline(preds, target, threshold, multidim_average, ignore_index, validate_args)
    return _precision_recall_reduce("precision", tp, fp, tn, fn, average="binary", multidim_average=multidim_average)


def multiclass_precision(preds, target, num_classes, average="macro", top_k=1, multidim_average="global", ignore_index=None, validate_args=True) -> Array:
    tp, fp, tn, fn = multiclass_pipeline(preds, target, num_classes, average, top_k, multidim_average, ignore_index, validate_args)
    return _precision_recall_reduce("precision", tp, fp, tn, fn, average=average, multidim_average=multidim_average)


def multilabel_precision(preds, target, num_labels, threshold=0.5, average="macro", multidim_average="global", ignore_index=None, validate_args=True) -> Array:
    tp, fp, tn, fn = multilabel_pipeline(preds, target, num_labels, threshold, average, multidim_average, ignore_index, validate_args)
    return _precision_recall_reduce("precision", tp, fp, tn, fn, average=average, multidim_average=multidim_average, multilabel=True)


def binary_recall(preds, target, threshold=0.5, multidim_average="global", ignore_index=None, validate_args=True) -> Array:
    tp, fp, tn, fn = binary_pipeline(preds, target, threshold, multidim_average, ignore_index, validate_args)
    return _precision_recall_reduce("recall", tp, fp, tn, fn, average="binary", multidim_average=multidim_average)


def multiclass_recall(preds, target, num_classes, average="macro", top_k=1, multidim_average="global", ignore_index=None, validate_args=True) -> Array:
    tp, fp, tn, fn = multiclass_pipeline(preds, target, num_classes, average, top_k, multidim_average, ignore_index, validate_args)
    return _precision_recall_reduce("recall", tp, fp, tn, fn, average=average, multidim_average=multidim_average)


def multilabel_recall(preds, target, num_labels, threshold=0.5, average="macro", multidim_average="global", ignore_index=None, validate_args=True) -> Array:
    tp, fp, tn, fn = multilabel_pipeline(preds, target, num_labels, threshold, average, multidim_average, ignore_index, validate_args)
    return _precision_recall_reduce("recall", tp, fp, tn, fn, average=average, multidim_average=multidim_average, multilabel=True)


def precision(
    preds, target, task, threshold=0.5, num_classes=None, num_labels=None, average="micro",
    multidim_average="global", top_k=1, ignore_index=None, validate_args=True,
) -> Array:
    """Task-dispatch façade over binary/multiclass/multilabel precision (reference functional/classification/precision_recall.py).

    Example:
        >>> import jax.numpy as jnp
        >>> from metrics_tpu.functional import precision
        >>> precision(jnp.array([0, 2, 1, 2]), jnp.array([0, 1, 1, 2]), task="multiclass", num_classes=3)
        Array(0.75, dtype=float32)
    """
    task = str(task).lower()
    if task == "binary":
        return binary_precision(preds, target, threshold, multidim_average, ignore_index, validate_args)
    if task == "multiclass":
        return multiclass_precision(preds, target, num_classes, average, top_k, multidim_average, ignore_index, validate_args)
    if task == "multilabel":
        return multilabel_precision(preds, target, num_labels, threshold, average, multidim_average, ignore_index, validate_args)
    raise ValueError(f"Expected argument `task` to either be 'binary', 'multiclass' or 'multilabel' but got {task}")


def recall(
    preds, target, task, threshold=0.5, num_classes=None, num_labels=None, average="micro",
    multidim_average="global", top_k=1, ignore_index=None, validate_args=True,
) -> Array:
    """Task-dispatch façade over binary/multiclass/multilabel recall (reference functional/classification/precision_recall.py).

    Example:
        >>> import jax.numpy as jnp
        >>> from metrics_tpu.functional import recall
        >>> recall(jnp.array([0, 2, 1, 2]), jnp.array([0, 1, 1, 2]), task="multiclass", num_classes=3)
        Array(0.75, dtype=float32)
    """
    task = str(task).lower()
    if task == "binary":
        return binary_recall(preds, target, threshold, multidim_average, ignore_index, validate_args)
    if task == "multiclass":
        return multiclass_recall(preds, target, num_classes, average, top_k, multidim_average, ignore_index, validate_args)
    if task == "multilabel":
        return multilabel_recall(preds, target, num_labels, threshold, average, multidim_average, ignore_index, validate_args)
    raise ValueError(f"Expected argument `task` to either be 'binary', 'multiclass' or 'multilabel' but got {task}")
