"""Cohen's kappa functionals.

Reference parity: src/torchmetrics/functional/classification/cohen_kappa.py
(``_cohen_kappa_reduce`` with optional linear/quadratic weighting).
"""

from __future__ import annotations

from typing import Optional

import jax.numpy as jnp
from jax import Array

from metrics_tpu.functional.classification.confusion_matrix import (
    binary_confusion_matrix,
    multiclass_confusion_matrix,
)


def _cohen_kappa_reduce(confmat: Array, weights: Optional[str] = None) -> Array:
    """Reference cohen_kappa.py ``_cohen_kappa_reduce``."""
    confmat = confmat.astype(jnp.float32)
    n_classes = confmat.shape[0]
    sum0 = jnp.sum(confmat, axis=0, keepdims=True)
    sum1 = jnp.sum(confmat, axis=1, keepdims=True)
    expected = sum1 @ sum0 / jnp.sum(sum0)

    if weights is None:
        w_mat = jnp.ones((n_classes, n_classes), dtype=jnp.float32) - jnp.eye(n_classes, dtype=jnp.float32)
    elif weights in ("linear", "quadratic"):
        w_mat = jnp.arange(n_classes, dtype=jnp.float32)
        w_mat = jnp.abs(w_mat[:, None] - w_mat[None, :])
        if weights == "quadratic":
            w_mat = w_mat**2
    else:
        raise ValueError(f"Received `weights` for which no implementation exists: {weights}")

    k = jnp.sum(w_mat * confmat) / jnp.sum(w_mat * expected)
    return 1 - k


def binary_cohen_kappa(preds, target, threshold=0.5, weights=None, ignore_index=None, validate_args=True) -> Array:
    confmat = binary_confusion_matrix(preds, target, threshold, ignore_index, normalize=None, validate_args=validate_args)
    return _cohen_kappa_reduce(confmat, weights)


def multiclass_cohen_kappa(preds, target, num_classes, weights=None, ignore_index=None, validate_args=True) -> Array:
    confmat = multiclass_confusion_matrix(preds, target, num_classes, ignore_index, normalize=None, validate_args=validate_args)
    return _cohen_kappa_reduce(confmat, weights)


def cohen_kappa(
    preds, target, task, threshold=0.5, num_classes=None, weights=None, ignore_index=None, validate_args=True,
) -> Array:
    """Task-dispatch façade over binary/multiclass Cohen's kappa (reference functional/classification/cohen_kappa.py).

    Example:
        >>> import jax.numpy as jnp
        >>> from metrics_tpu.functional import cohen_kappa
        >>> cohen_kappa(jnp.array([0, 2, 1, 2]), jnp.array([0, 1, 1, 2]), task="multiclass", num_classes=3)
        Array(0.6363636, dtype=float32)
    """
    task = str(task).lower()
    if task == "binary":
        return binary_cohen_kappa(preds, target, threshold, weights, ignore_index, validate_args)
    if task == "multiclass":
        assert isinstance(num_classes, int)
        return multiclass_cohen_kappa(preds, target, num_classes, weights, ignore_index, validate_args)
    raise ValueError(f"Expected argument `task` to either be 'binary' or 'multiclass' but got {task}")
