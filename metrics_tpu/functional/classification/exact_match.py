"""Exact match functionals.

Reference parity: src/torchmetrics/functional/classification/exact_match.py
(multiclass + multilabel variants; a sample scores 1 iff every position is correct).
"""

from __future__ import annotations

from typing import Optional

import jax.numpy as jnp
from jax import Array

from metrics_tpu.functional.classification.stat_scores import (
    _ignore_mask,
    _multiclass_stat_scores_arg_validation,
    _multiclass_stat_scores_format,
    _multiclass_stat_scores_tensor_validation,
    _multilabel_stat_scores_arg_validation,
    _multilabel_stat_scores_format,
    _multilabel_stat_scores_tensor_validation,
)
from metrics_tpu.utils.compute import _safe_divide


def _exact_match_reduce(correct: Array, total: Array, multidim_average: str) -> Array:
    if multidim_average == "global":
        return _safe_divide(jnp.sum(correct), total)
    return correct.astype(jnp.float32)


def multiclass_exact_match(
    preds: Array,
    target: Array,
    num_classes: int,
    multidim_average: str = "global",
    ignore_index: Optional[int] = None,
    validate_args: bool = True,
) -> Array:
    if validate_args:
        _multiclass_stat_scores_arg_validation(num_classes, top_k=1, average=None, multidim_average=multidim_average, ignore_index=ignore_index)
        _multiclass_stat_scores_tensor_validation(preds, target, num_classes, multidim_average, ignore_index)
    preds, target = _multiclass_stat_scores_format(preds, target, top_k=1)
    mask = _ignore_mask(target, ignore_index)
    # ignored positions count as matching (they don't break exactness)
    correct = jnp.all(jnp.where(mask, preds == target, True), axis=1).astype(jnp.int32)
    total = jnp.asarray(correct.shape[0], dtype=jnp.float32)
    return _exact_match_reduce(correct, total, multidim_average)


def multilabel_exact_match(
    preds: Array,
    target: Array,
    num_labels: int,
    threshold: float = 0.5,
    multidim_average: str = "global",
    ignore_index: Optional[int] = None,
    validate_args: bool = True,
) -> Array:
    if validate_args:
        _multilabel_stat_scores_arg_validation(num_labels, threshold, average=None, multidim_average=multidim_average, ignore_index=ignore_index)
        _multilabel_stat_scores_tensor_validation(preds, target, num_labels, multidim_average, ignore_index)
    squeeze_x = jnp.asarray(preds).ndim == 2
    preds, target, mask = _multilabel_stat_scores_format(preds, target, num_labels, threshold, ignore_index)
    correct = jnp.all(jnp.where(mask, preds == target, True), axis=1).astype(jnp.int32)  # (N, X)
    if squeeze_x:
        correct = correct.squeeze(-1)  # 2-d input has no extra dims
    total = jnp.asarray(correct.size, dtype=jnp.float32)
    return _exact_match_reduce(correct, total, multidim_average)


def exact_match(
    preds: Array,
    target: Array,
    task: str,
    num_classes: Optional[int] = None,
    num_labels: Optional[int] = None,
    threshold: float = 0.5,
    multidim_average: str = "global",
    ignore_index: Optional[int] = None,
    validate_args: bool = True,
) -> Array:
    """Task-dispatch façade over multiclass/multilabel exact match (reference functional/classification/exact_match.py).

    Example:
        >>> import jax.numpy as jnp
        >>> from metrics_tpu.functional import exact_match
        >>> exact_match(jnp.array([[0, 2], [1, 1]]), jnp.array([[0, 2], [1, 0]]), task="multiclass", num_classes=3)
        Array(0.5, dtype=float32)
    """
    task = str(task).lower()
    if task == "multiclass":
        assert num_classes is not None
        return multiclass_exact_match(preds, target, num_classes, multidim_average, ignore_index, validate_args)
    if task == "multilabel":
        assert num_labels is not None
        return multilabel_exact_match(preds, target, num_labels, threshold, multidim_average, ignore_index, validate_args)
    raise ValueError(f"Expected argument `task` to either be 'multiclass' or 'multilabel' but got {task}")
