"""Dice functional (legacy-style API with average/mdmc_average).

Reference parity: src/torchmetrics/functional/classification/dice.py
(``_dice_compute`` :24-64, ``dice`` :66-…) and the legacy stat-score machinery
(functional/classification/stat_scores.py ``_stat_scores`` :840, ``_reduce_stat_scores``
:996-1051).

TPU-first notes: the reference's boolean filtering of absent classes
(``numerator[~cond]``) is reformulated as -1 "ignore" sentinels flowing into the masked
reduction — mathematically identical, static shapes under jit.
"""

from __future__ import annotations

from typing import Optional, Tuple

import jax
import jax.numpy as jnp
from jax import Array

from metrics_tpu.utils.checks import _input_format_classification
from metrics_tpu.utils.enums import AverageMethod, DataType, MDMCAverageMethod


def _reduce_stat_scores(
    numerator: Array,
    denominator: Array,
    weights: Optional[Array],
    average: Optional[str],
    mdmc_average: Optional[str],
    zero_division: float = 0.0,
) -> Array:
    """Masked score reduction (reference stat_scores.py:996-1051).

    denominator == 0 → ``zero_division``; denominator < 0 → class ignored (0 weight
    when averaging, NaN when ``average=None``).
    """
    numerator = numerator.astype(jnp.float32)
    denominator = denominator.astype(jnp.float32)
    zero_div_mask = denominator == 0
    ignore_mask = denominator < 0

    weights = jnp.ones_like(denominator) if weights is None else weights.astype(jnp.float32)
    numerator = jnp.where(zero_div_mask, zero_division, numerator)
    denominator = jnp.where(zero_div_mask | ignore_mask, 1.0, denominator)
    weights = jnp.where(ignore_mask, 0.0, weights)

    if average not in (AverageMethod.MICRO, AverageMethod.NONE, None, "micro", "none"):
        weights = weights / jnp.sum(weights, axis=-1, keepdims=True)

    scores = weights * (numerator / denominator)
    scores = jnp.where(jnp.isnan(scores), zero_division, scores)

    if mdmc_average == MDMCAverageMethod.SAMPLEWISE or mdmc_average == "samplewise":
        scores = jnp.mean(scores, axis=0)
        ignore_mask = jnp.sum(ignore_mask, axis=0).astype(bool)

    if average in (AverageMethod.NONE, None, "none"):
        return jnp.where(ignore_mask, jnp.nan, scores)
    return jnp.sum(scores)


def _stat_scores(preds: Array, target: Array, reduce: Optional[str] = "micro") -> Tuple[Array, Array, Array, Array]:
    """tp/fp/tn/fn over 0/1 matrices ``(N, C)`` or ``(N, C, X)`` (reference :840-884)."""
    if reduce == "micro":
        dim = (0, 1) if preds.ndim == 2 else (1, 2)
    elif reduce == "macro":
        dim = (0,) if preds.ndim == 2 else (2,)
    else:  # samples
        dim = (1,)

    true_pred, false_pred = target == preds, target != preds
    pos_pred, neg_pred = preds == 1, preds == 0

    tp = jnp.sum(true_pred * pos_pred, axis=dim)
    fp = jnp.sum(false_pred * pos_pred, axis=dim)
    tn = jnp.sum(true_pred * neg_pred, axis=dim)
    fn = jnp.sum(false_pred * neg_pred, axis=dim)
    return tp.astype(jnp.int32), fp.astype(jnp.int32), tn.astype(jnp.int32), fn.astype(jnp.int32)


def _dice_stat_scores_update(
    preds: Array,
    target: Array,
    reduce: Optional[str] = "micro",
    mdmc_reduce: Optional[str] = None,
    num_classes: Optional[int] = None,
    top_k: Optional[int] = 1,
    threshold: float = 0.5,
    multiclass: Optional[bool] = None,
    ignore_index: Optional[int] = None,
) -> Tuple[Array, Array, Array, Array]:
    """Legacy ``_stat_scores_update`` (reference :887-…): format, reshape per mdmc mode,
    count, and mark the ignored class with -1 sentinels."""
    preds_oh, target_oh, case = _input_format_classification(
        preds, target, threshold=threshold, top_k=top_k, num_classes=num_classes, multiclass=multiclass,
        ignore_index=ignore_index,
    )
    n_cols = preds_oh.shape[1]

    if ignore_index is not None and not 0 <= ignore_index < n_cols and n_cols > 1:
        raise ValueError(f"The `ignore_index` {ignore_index} is not valid for inputs with {n_cols} classes")

    if case == DataType.MULTIDIM_MULTICLASS and mdmc_reduce == "samplewise":
        # recover the (N, C, X) layout: the formatter flattened (N, C, ...) → (N*X, C)
        n = jnp.asarray(target).shape[0]
        preds_oh = preds_oh.reshape(n, -1, n_cols)
        target_oh = target_oh.reshape(n, -1, n_cols)
        preds_oh = jnp.moveaxis(preds_oh, 1, -1)
        target_oh = jnp.moveaxis(target_oh, 1, -1)

    if ignore_index is not None and n_cols > 1:
        if reduce == "micro":
            # drop the class column entirely (no contributions)
            keep = jnp.arange(n_cols) != ignore_index
            preds_oh = preds_oh * keep.reshape((1, -1) + (1,) * (preds_oh.ndim - 2))
            target_oh = target_oh * keep.reshape((1, -1) + (1,) * (target_oh.ndim - 2))

    tp, fp, tn, fn = _stat_scores(preds_oh, target_oh, reduce=reduce)

    if ignore_index is not None and n_cols > 1 and reduce == "macro":
        # -1 sentinel → downstream masked reduction ignores the class
        idx = jnp.arange(tp.shape[-1]) == ignore_index
        tp = jnp.where(idx, -1, tp)
        fp = jnp.where(idx, -1, fp)
        tn = jnp.where(idx, -1, tn)
        fn = jnp.where(idx, -1, fn)
    return tp, fp, tn, fn


def _dice_compute(
    tp: Array,
    fp: Array,
    fn: Array,
    average: Optional[str],
    mdmc_average: Optional[str],
    zero_division: float = 0.0,
) -> Array:
    """Dice = 2·tp / (2·tp + fp + fn) with masked class handling (reference :24-64)."""
    numerator = 2 * tp
    denominator = 2 * tp + fp + fn

    if average in ("macro", "none", None) and mdmc_average != "samplewise":
        # absent classes (no tp/fp/fn) are ignored: -1 sentinel instead of boolean filter
        absent = (tp + fp + fn) == 0
        numerator = jnp.where(absent, -1, numerator)
        denominator = jnp.where(absent, -1, denominator)

    return _reduce_stat_scores(
        numerator=numerator,
        denominator=denominator,
        weights=None if average != "weighted" else (tp + fn),
        average=average,
        mdmc_average=mdmc_average,
        zero_division=zero_division,
    )


def dice(
    preds: Array,
    target: Array,
    zero_division: float = 0.0,
    average: Optional[str] = "micro",
    mdmc_average: Optional[str] = "global",
    threshold: float = 0.5,
    top_k: Optional[int] = None,
    num_classes: Optional[int] = None,
    ignore_index: Optional[int] = None,
) -> Array:
    """Dice score (reference :66-…).

    Example:
        >>> import jax.numpy as jnp
        >>> from metrics_tpu.functional import dice
        >>> dice(jnp.array([0, 2, 1, 2]), jnp.array([0, 1, 1, 2]))
        Array(0.75, dtype=float32)
    """
    allowed_average = ("micro", "macro", "weighted", "samples", "none", None)
    if average not in allowed_average:
        raise ValueError(f"The `average` has to be one of {allowed_average}, got {average}.")
    allowed_mdmc_average = ("global", "samplewise", None)
    if mdmc_average not in allowed_mdmc_average:
        raise ValueError(f"The `mdmc_average` has to be one of {allowed_mdmc_average}, got {mdmc_average}.")
    if average in ("macro", "weighted", "none", None) and (num_classes is None or num_classes < 1):
        raise ValueError(f"When you set `average` as {average}, you have to provide the number of classes.")
    if num_classes is not None and ignore_index is not None and not 0 <= ignore_index < num_classes and num_classes > 1:
        raise ValueError(f"The `ignore_index` {ignore_index} is not valid for inputs with {num_classes} classes")

    reduce = "macro" if average in ("weighted", "none", None) else average
    tp, fp, tn, fn = _dice_stat_scores_update(
        preds, target, reduce=reduce, mdmc_reduce=mdmc_average, num_classes=num_classes,
        top_k=top_k, threshold=threshold, ignore_index=ignore_index,
    )
    return _dice_compute(tp, fp, fn, average, mdmc_average, zero_division)
