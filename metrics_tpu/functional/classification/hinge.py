"""Hinge-loss functionals.

Reference parity: src/torchmetrics/functional/classification/hinge.py
(binary :49-123, multiclass crammer-singer / one-vs-all :150-230).

TPU-first notes: the reference's boolean-mask indexing (``preds[target]``) is
reformulated as ``jnp.where`` selects; ``ignore_index`` becomes a 0/1 sample weight so
shapes stay static under jit.
"""

from __future__ import annotations

from typing import Optional, Tuple

import jax
import jax.numpy as jnp
from jax import Array

from metrics_tpu.functional.classification.stat_scores import _ignore_mask, _sigmoid_if_logits, _softmax_if_logits
from metrics_tpu.utils.checks import _check_same_shape
from metrics_tpu.utils.compute import _safe_divide


def _hinge_loss_compute(measure: Array, total: Array) -> Array:
    return _safe_divide(measure, total)


def _binary_hinge_loss_arg_validation(squared: bool, ignore_index: Optional[int] = None) -> None:
    if not isinstance(squared, bool):
        raise ValueError(f"Expected argument `squared` to be an bool but got {squared}")
    if ignore_index is not None and not isinstance(ignore_index, int):
        raise ValueError(f"Expected argument `ignore_index` to either be `None` or an integer, but got {ignore_index}")


def _binary_hinge_loss_tensor_validation(preds: Array, target: Array, ignore_index: Optional[int] = None) -> None:
    _check_same_shape(preds, target)
    if not jnp.issubdtype(preds.dtype, jnp.floating):
        raise ValueError(
            "Expected argument `preds` to be floating tensor with probabilities/logits"
            f" but got tensor with dtype {preds.dtype}"
        )


def _binary_hinge_loss_update(
    preds: Array, target: Array, squared: bool, mask: Optional[Array] = None
) -> Tuple[Array, Array]:
    """margin = +preds for positives, -preds for negatives; measure = relu(1 - margin)."""
    margin = jnp.where(target.astype(bool), preds, -preds)
    measures = jnp.maximum(1 - margin, 0.0)
    if squared:
        measures = jnp.square(measures)
    w = mask.astype(preds.dtype) if mask is not None else jnp.ones_like(preds)
    return jnp.sum(measures * w), jnp.sum(w)


def binary_hinge_loss(
    preds: Array,
    target: Array,
    squared: bool = False,
    ignore_index: Optional[int] = None,
    validate_args: bool = False,
) -> Array:
    """Mean hinge loss for binary tasks (reference :70-123)."""
    if validate_args:
        _binary_hinge_loss_arg_validation(squared, ignore_index)
        _binary_hinge_loss_tensor_validation(preds, target, ignore_index)
    preds = jnp.asarray(preds).reshape(-1)
    target = jnp.asarray(target).reshape(-1)
    mask = _ignore_mask(target, ignore_index).reshape(-1)
    target = jnp.where(mask, target, 0)
    preds = _sigmoid_if_logits(preds)
    measures, total = _binary_hinge_loss_update(preds, target, squared, mask)
    return _hinge_loss_compute(measures, total)


def _multiclass_hinge_loss_arg_validation(
    num_classes: int,
    squared: bool = False,
    multiclass_mode: str = "crammer-singer",
    ignore_index: Optional[int] = None,
) -> None:
    if not isinstance(num_classes, int) or num_classes < 2:
        raise ValueError(f"Expected argument `num_classes` to be an integer larger than 1, but got {num_classes}")
    _binary_hinge_loss_arg_validation(squared, ignore_index)
    allowed_mm = ("crammer-singer", "one-vs-all")
    if multiclass_mode not in allowed_mm:
        raise ValueError(f"Expected argument `multiclass_mode` to be one of {allowed_mm}, but got {multiclass_mode}.")


def _multiclass_hinge_loss_tensor_validation(
    preds: Array, target: Array, num_classes: int, ignore_index: Optional[int] = None
) -> None:
    if preds.ndim != target.ndim + 1:
        raise ValueError("Expected `preds` to have one more dimension than `target`")
    if preds.shape[1] != num_classes:
        raise ValueError(f"Expected `preds.shape[1]={preds.shape[1]}` to equal `num_classes={num_classes}`")
    if not jnp.issubdtype(preds.dtype, jnp.floating):
        raise ValueError(
            "Expected argument `preds` to be floating tensor with probabilities/logits"
            f" but got tensor with dtype {preds.dtype}"
        )


def _multiclass_hinge_loss_update(
    preds: Array,
    target: Array,
    squared: bool,
    multiclass_mode: str = "crammer-singer",
    mask: Optional[Array] = None,
) -> Tuple[Array, Array]:
    preds = _softmax_if_logits(preds, axis=1)
    num_classes = preds.shape[1]
    onehot = jax.nn.one_hot(target, num_classes, dtype=bool)
    if multiclass_mode == "crammer-singer":
        margin = jnp.sum(jnp.where(onehot, preds, 0.0), axis=1)
        margin = margin - jnp.max(jnp.where(onehot, -jnp.inf, preds), axis=1)
        measures = jnp.maximum(1 - margin, 0.0)
        if squared:
            measures = jnp.square(measures)
        w = mask.astype(preds.dtype) if mask is not None else jnp.ones_like(measures)
        return jnp.sum(measures * w), jnp.sum(w)
    # one-vs-all: per-class hinge, summed over samples → (C,) vector
    margin = jnp.where(onehot, preds, -preds)
    measures = jnp.maximum(1 - margin, 0.0)
    if squared:
        measures = jnp.square(measures)
    w = mask.astype(preds.dtype) if mask is not None else jnp.ones(preds.shape[0], dtype=preds.dtype)
    return jnp.sum(measures * w[:, None], axis=0), jnp.sum(w)


def multiclass_hinge_loss(
    preds: Array,
    target: Array,
    num_classes: int,
    squared: bool = False,
    multiclass_mode: str = "crammer-singer",
    ignore_index: Optional[int] = None,
    validate_args: bool = False,
) -> Array:
    """Mean hinge loss for multiclass tasks (reference :179-246)."""
    if validate_args:
        _multiclass_hinge_loss_arg_validation(num_classes, squared, multiclass_mode, ignore_index)
        _multiclass_hinge_loss_tensor_validation(preds, target, num_classes, ignore_index)
    preds = jnp.moveaxis(jnp.asarray(preds), 1, -1).reshape(-1, num_classes)
    target = jnp.asarray(target).reshape(-1)
    mask = _ignore_mask(target, ignore_index)
    target = jnp.where(mask, target, 0)
    measures, total = _multiclass_hinge_loss_update(preds, target, squared, multiclass_mode, mask)
    return _hinge_loss_compute(measures, total)


def hinge_loss(
    preds: Array,
    target: Array,
    task: str,
    num_classes: Optional[int] = None,
    squared: bool = False,
    multiclass_mode: str = "crammer-singer",
    ignore_index: Optional[int] = None,
    validate_args: bool = True,
) -> Array:
    """Task-dispatch façade (reference :249-…).

    Example:
        >>> import jax.numpy as jnp
        >>> from metrics_tpu.functional import hinge_loss
        >>> preds = jnp.array([[0.7, 0.2, 0.1], [0.2, 0.6, 0.2], [0.1, 0.2, 0.7], [0.3, 0.4, 0.3]])
        >>> target = jnp.array([0, 1, 2, 1])
        >>> hinge_loss(preds, target, task="multiclass", num_classes=3)
        Array(0.625, dtype=float32)
    """
    task = str(task).lower()
    if task == "binary":
        return binary_hinge_loss(preds, target, squared, ignore_index, validate_args)
    if task == "multiclass":
        assert isinstance(num_classes, int)
        return multiclass_hinge_loss(preds, target, num_classes, squared, multiclass_mode, ignore_index, validate_args)
    raise ValueError(f"Expected argument `task` to either be 'binary' or 'multiclass' but got {task}")
