"""Accuracy functionals.

Reference parity: src/torchmetrics/functional/classification/accuracy.py
(``_accuracy_reduce`` + binary/multiclass/multilabel entry points + task façade).
"""

from __future__ import annotations

from typing import Optional

import jax.numpy as jnp
from jax import Array

from metrics_tpu.functional.classification._pipeline import binary_pipeline, multiclass_pipeline, multilabel_pipeline
from metrics_tpu.utils.compute import _adjust_weights_safe_divide, _safe_divide


def _accuracy_reduce(
    tp: Array,
    fp: Array,
    tn: Array,
    fn: Array,
    average: Optional[str],
    multidim_average: str = "global",
    multilabel: bool = False,
) -> Array:
    """Reference accuracy.py ``_accuracy_reduce``."""
    if average == "binary":
        return _safe_divide(tp + tn, tp + tn + fp + fn)
    if average == "micro":
        axis = 0 if multidim_average == "global" else 1
        tp = jnp.sum(tp, axis=axis)
        fn = jnp.sum(fn, axis=axis)
        if multilabel:
            fp = jnp.sum(fp, axis=axis)
            tn = jnp.sum(tn, axis=axis)
            return _safe_divide(tp + tn, tp + tn + fp + fn)
        return _safe_divide(tp, tp + fn)
    score = _safe_divide(tp + tn, tp + tn + fp + fn) if multilabel else _safe_divide(tp, tp + fn)
    return _adjust_weights_safe_divide(score, average, tp, fn)


def binary_accuracy(
    preds: Array,
    target: Array,
    threshold: float = 0.5,
    multidim_average: str = "global",
    ignore_index: Optional[int] = None,
    validate_args: bool = True,
) -> Array:
    tp, fp, tn, fn = binary_pipeline(preds, target, threshold, multidim_average, ignore_index, validate_args)
    return _accuracy_reduce(tp, fp, tn, fn, average="binary", multidim_average=multidim_average)


def multiclass_accuracy(
    preds: Array,
    target: Array,
    num_classes: int,
    average: Optional[str] = "macro",
    top_k: int = 1,
    multidim_average: str = "global",
    ignore_index: Optional[int] = None,
    validate_args: bool = True,
) -> Array:
    tp, fp, tn, fn = multiclass_pipeline(preds, target, num_classes, average, top_k, multidim_average, ignore_index, validate_args)
    return _accuracy_reduce(tp, fp, tn, fn, average=average, multidim_average=multidim_average)


def multilabel_accuracy(
    preds: Array,
    target: Array,
    num_labels: int,
    threshold: float = 0.5,
    average: Optional[str] = "macro",
    multidim_average: str = "global",
    ignore_index: Optional[int] = None,
    validate_args: bool = True,
) -> Array:
    tp, fp, tn, fn = multilabel_pipeline(preds, target, num_labels, threshold, average, multidim_average, ignore_index, validate_args)
    return _accuracy_reduce(tp, fp, tn, fn, average=average, multidim_average=multidim_average, multilabel=True)


def accuracy(
    preds: Array,
    target: Array,
    task: str,
    threshold: float = 0.5,
    num_classes: Optional[int] = None,
    num_labels: Optional[int] = None,
    average: Optional[str] = "micro",
    multidim_average: str = "global",
    top_k: int = 1,
    ignore_index: Optional[int] = None,
    validate_args: bool = True,
) -> Array:
    """Task-dispatch façade (reference accuracy.py bottom).

    Example:
        >>> import jax.numpy as jnp
        >>> from metrics_tpu.functional import accuracy
        >>> accuracy(jnp.array([0, 2, 1, 2]), jnp.array([0, 1, 1, 2]), task="multiclass", num_classes=3)
        Array(0.75, dtype=float32)
    """
    task = str(task).lower()
    if task == "binary":
        return binary_accuracy(preds, target, threshold, multidim_average, ignore_index, validate_args)
    if task == "multiclass":
        assert isinstance(num_classes, int)
        assert isinstance(top_k, int)
        return multiclass_accuracy(
            preds, target, num_classes, average, top_k, multidim_average, ignore_index, validate_args
        )
    if task == "multilabel":
        assert isinstance(num_labels, int)
        return multilabel_accuracy(
            preds, target, num_labels, threshold, average, multidim_average, ignore_index, validate_args
        )
    raise ValueError(f"Expected argument `task` to either be 'binary', 'multiclass' or 'multilabel' but got {task}")
