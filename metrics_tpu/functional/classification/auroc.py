"""AUROC functionals.

Reference parity: src/torchmetrics/functional/classification/auroc.py
(trapezoidal area over the ROC curve; binary ``max_fpr`` with McClish correction;
multiclass macro/weighted/none; multilabel + micro).
"""

from __future__ import annotations

from typing import Optional, Tuple, Union

import jax.numpy as jnp
from jax import Array

from metrics_tpu.functional.classification.precision_recall_curve import (
    Thresholds,
    _exact_mode_filter,
    _exact_target_for_weights,
    _binary_precision_recall_curve_arg_validation,
    _binary_precision_recall_curve_format,
    _binary_precision_recall_curve_tensor_validation,
    _binary_precision_recall_curve_update,
    _multiclass_precision_recall_curve_arg_validation,
    _multiclass_precision_recall_curve_format,
    _multiclass_precision_recall_curve_tensor_validation,
    _multiclass_precision_recall_curve_update,
    _multilabel_precision_recall_curve_arg_validation,
    _multilabel_precision_recall_curve_format,
    _multilabel_precision_recall_curve_tensor_validation,
    _multilabel_precision_recall_curve_update,
)
from metrics_tpu.functional.classification.roc import (
    _binary_roc_compute,
    _multiclass_roc_compute,
    _multilabel_roc_compute,
)
from metrics_tpu.utils.checks import _value_check_possible
from metrics_tpu.utils.compute import _auc_compute_without_check, _safe_divide
from metrics_tpu.utils.prints import rank_zero_warn


def _reduce_auroc(
    fpr: Union[Array, list],
    tpr: Union[Array, list],
    average: Optional[str] = "macro",
    weights: Optional[Array] = None,
) -> Array:
    """Reference auroc.py ``_reduce_auroc``."""
    if isinstance(fpr, Array) and isinstance(tpr, Array):
        res = _auc_compute_without_check(fpr, tpr, 1.0, axis=1)
    else:
        res = jnp.stack([_auc_compute_without_check(x, y, 1.0) for x, y in zip(fpr, tpr)])
    if average is None or average == "none":
        return res
    if _value_check_possible(res) and bool(jnp.isnan(res).any()):
        rank_zero_warn(
            "Average precision score for one or more classes was `nan`. Ignoring these classes in average",
            UserWarning,
        )
    idx = ~jnp.isnan(res)
    if average == "macro":
        return jnp.mean(res[idx]) if _value_check_possible(res) else jnp.nanmean(res)
    if average == "weighted" and weights is not None:
        weights = jnp.where(idx, weights, 0.0)
        weighted = res * _safe_divide(weights, jnp.sum(weights))
        return jnp.sum(weighted[idx]) if _value_check_possible(res) else jnp.nansum(weighted)
    raise ValueError("Received an incompatible combinations of inputs to make reduction.")


def _binary_auroc_compute(
    state: Union[Array, Tuple[Array, Array]],
    thresholds: Optional[Array],
    max_fpr: Optional[float] = None,
    pos_label: int = 1,
) -> Array:
    fpr, tpr, _ = _binary_roc_compute(state, thresholds, pos_label)
    if max_fpr is None or max_fpr == 1:
        return _auc_compute_without_check(fpr, tpr, 1.0)

    # Partial AUC over fpr <= max_fpr (semantics per reference auroc.py:96-110,
    # itself the sklearn convention): the curve is cut at max_fpr — the cut
    # point's tpr is linearly interpolated between its bracketing ROC points —
    # and the truncated area is then rescaled onto [0.5, 1] so chance stays at
    # 0.5 and a perfect ranking at 1 (McClish 1989). The denominator clamp
    # guards the repeated-fpr case where the bracketing points coincide.
    max_area = jnp.asarray(max_fpr, dtype=jnp.float32)
    stop = jnp.searchsorted(fpr, max_area, side="right")
    weight = (max_area - fpr[stop - 1]) / jnp.maximum(fpr[stop] - fpr[stop - 1], 1e-12)
    interp_tpr = tpr[stop - 1] + weight * (tpr[stop] - tpr[stop - 1])
    tpr = jnp.concatenate([tpr[:stop], interp_tpr.reshape(1)])
    fpr = jnp.concatenate([fpr[:stop], max_area.reshape(1)])
    partial_auc = _auc_compute_without_check(fpr, tpr, 1.0)
    min_area = 0.5 * max_area**2  # area under the chance diagonal up to the cut
    return 0.5 * (1 + (partial_auc - min_area) / (max_area - min_area))


def binary_auroc(
    preds: Array,
    target: Array,
    max_fpr: Optional[float] = None,
    thresholds: Thresholds = None,
    ignore_index: Optional[int] = None,
    validate_args: bool = True,
) -> Array:
    if validate_args:
        _binary_precision_recall_curve_arg_validation(thresholds, ignore_index)
        _binary_precision_recall_curve_tensor_validation(preds, target, ignore_index)
        if max_fpr is not None and not (isinstance(max_fpr, float) and 0 < max_fpr <= 1):
            raise ValueError(f"Arguments `max_fpr` should be a float in range (0, 1], but got: {max_fpr}")
    preds, target, thresholds, mask = _binary_precision_recall_curve_format(preds, target, thresholds, ignore_index)
    if thresholds is None and ignore_index is not None:
        preds, target = _exact_mode_filter(preds, target, thresholds, ignore_index, mask)
        mask = None
    state = _binary_precision_recall_curve_update(preds, target, thresholds, mask)
    return _binary_auroc_compute(state, thresholds, max_fpr)


def _multiclass_auroc_compute(
    state: Union[Array, Tuple[Array, Array]],
    num_classes: int,
    average: Optional[str] = "macro",
    thresholds: Optional[Array] = None,
) -> Array:
    fpr, tpr, _ = _multiclass_roc_compute(state, num_classes, thresholds)
    if isinstance(state, tuple):
        weights = jnp.bincount(_exact_target_for_weights(state), length=num_classes).astype(jnp.float32)
    else:
        weights = (state[0, :, 1, 0] + state[0, :, 1, 1]).astype(jnp.float32)
    return _reduce_auroc(fpr, tpr, average, weights=weights)


def multiclass_auroc(
    preds: Array,
    target: Array,
    num_classes: int,
    average: Optional[str] = "macro",
    thresholds: Thresholds = None,
    ignore_index: Optional[int] = None,
    validate_args: bool = True,
) -> Array:
    if validate_args:
        _multiclass_precision_recall_curve_arg_validation(num_classes, thresholds, ignore_index)
        _multiclass_precision_recall_curve_tensor_validation(preds, target, num_classes, ignore_index)
        allowed_average = ("macro", "weighted", "none", None)
        if average not in allowed_average:
            raise ValueError(f"Expected argument `average` to be one of {allowed_average} but got {average}")
    preds, target, thresholds, mask = _multiclass_precision_recall_curve_format(
        preds, target, num_classes, thresholds, ignore_index
    )
    if thresholds is None and ignore_index is not None:
        preds, target = _exact_mode_filter(preds, target, thresholds, ignore_index, mask)
        mask = None
    state = _multiclass_precision_recall_curve_update(preds, target, num_classes, thresholds, mask)
    return _multiclass_auroc_compute(state, num_classes, average, thresholds)


def _multilabel_auroc_compute(
    state,
    num_labels: int,
    average: Optional[str],
    thresholds: Optional[Array],
    ignore_index: Optional[int] = None,
) -> Array:
    if average == "micro":
        if isinstance(state, Array) and thresholds is not None:
            return _binary_auroc_compute(jnp.sum(state, axis=1), thresholds, max_fpr=None)
        preds, target, mask = state
        preds = preds.reshape(-1)
        target = target.reshape(-1)
        m = mask.reshape(-1)
        preds, target = _exact_mode_filter(preds, target, None, 0, m)
        return _binary_auroc_compute((preds, target), thresholds=None, max_fpr=None)

    fpr, tpr, _ = _multilabel_roc_compute(state, num_labels, thresholds, ignore_index)
    if isinstance(state, tuple):
        weights = jnp.sum((jnp.asarray(state[1]) == 1) & jnp.asarray(state[2]), axis=0).astype(jnp.float32)
    else:
        weights = (state[0, :, 1, 0] + state[0, :, 1, 1]).astype(jnp.float32)
    return _reduce_auroc(fpr, tpr, average, weights=weights)


def multilabel_auroc(
    preds: Array,
    target: Array,
    num_labels: int,
    average: Optional[str] = "macro",
    thresholds: Thresholds = None,
    ignore_index: Optional[int] = None,
    validate_args: bool = True,
) -> Array:
    if validate_args:
        _multilabel_precision_recall_curve_arg_validation(num_labels, thresholds, ignore_index)
        _multilabel_precision_recall_curve_tensor_validation(preds, target, num_labels, ignore_index)
        allowed_average = ("micro", "macro", "weighted", "none", None)
        if average not in allowed_average:
            raise ValueError(f"Expected argument `average` to be one of {allowed_average} but got {average}")
    preds, target, thresholds, mask = _multilabel_precision_recall_curve_format(
        preds, target, num_labels, thresholds, ignore_index
    )
    state = _multilabel_precision_recall_curve_update(preds, target, num_labels, thresholds, mask)
    return _multilabel_auroc_compute(state, num_labels, average, thresholds, ignore_index)


def auroc(
    preds: Array,
    target: Array,
    task: str,
    thresholds: Thresholds = None,
    num_classes: Optional[int] = None,
    num_labels: Optional[int] = None,
    average: Optional[str] = "macro",
    max_fpr: Optional[float] = None,
    ignore_index: Optional[int] = None,
    validate_args: bool = True,
):
    """Task-dispatch façade over binary/multiclass/multilabel AUROC (reference functional/classification/auroc.py).

    Example:
        >>> import jax.numpy as jnp
        >>> from metrics_tpu.functional import auroc
        >>> preds = jnp.array([[0.7, 0.2, 0.1], [0.2, 0.6, 0.2], [0.1, 0.2, 0.7], [0.3, 0.4, 0.3]])
        >>> target = jnp.array([0, 1, 2, 1])
        >>> auroc(preds, target, task="multiclass", num_classes=3)
        Array(1., dtype=float32)
    """
    task = str(task).lower()
    if task == "binary":
        return binary_auroc(preds, target, max_fpr, thresholds, ignore_index, validate_args)
    if task == "multiclass":
        assert isinstance(num_classes, int)
        return multiclass_auroc(preds, target, num_classes, average, thresholds, ignore_index, validate_args)
    if task == "multilabel":
        assert isinstance(num_labels, int)
        return multilabel_auroc(preds, target, num_labels, average, thresholds, ignore_index, validate_args)
    raise ValueError(f"Expected argument `task` to either be 'binary', 'multiclass' or 'multilabel' but got {task}")
