"""Average precision functionals.

Reference parity: src/torchmetrics/functional/classification/average_precision.py
(AP = Σ (R_i − R_{i−1}) · P_i over the precision-recall curve).
"""

from __future__ import annotations

from typing import Optional, Tuple, Union

import jax.numpy as jnp
from jax import Array

from metrics_tpu.functional.classification.precision_recall_curve import (
    Thresholds,
    _exact_mode_filter,
    _exact_target_for_weights,
    _binary_precision_recall_curve_arg_validation,
    _binary_precision_recall_curve_compute,
    _binary_precision_recall_curve_format,
    _binary_precision_recall_curve_tensor_validation,
    _binary_precision_recall_curve_update,
    _multiclass_precision_recall_curve_arg_validation,
    _multiclass_precision_recall_curve_compute,
    _multiclass_precision_recall_curve_format,
    _multiclass_precision_recall_curve_tensor_validation,
    _multiclass_precision_recall_curve_update,
    _multilabel_precision_recall_curve_arg_validation,
    _multilabel_precision_recall_curve_compute,
    _multilabel_precision_recall_curve_format,
    _multilabel_precision_recall_curve_tensor_validation,
    _multilabel_precision_recall_curve_update,
)
from metrics_tpu.utils.checks import _value_check_possible
from metrics_tpu.utils.compute import _safe_divide
from metrics_tpu.utils.prints import rank_zero_warn


def _reduce_average_precision(
    precision: Union[Array, list],
    recall: Union[Array, list],
    average: Optional[str] = "macro",
    weights: Optional[Array] = None,
) -> Array:
    """Reference average_precision.py ``_reduce_average_precision``."""
    if isinstance(precision, Array) and isinstance(recall, Array):
        res = -jnp.sum((recall[:, 1:] - recall[:, :-1]) * precision[:, :-1], axis=1)
    else:
        res = jnp.stack([-jnp.sum((r[1:] - r[:-1]) * p[:-1]) for p, r in zip(precision, recall)])
    if average is None or average == "none":
        return res
    if _value_check_possible(res) and bool(jnp.isnan(res).any()):
        rank_zero_warn(
            "Average precision score for one or more classes was `nan`. Ignoring these classes in average",
            UserWarning,
        )
    idx = ~jnp.isnan(res)
    if average == "macro":
        return jnp.mean(res[idx]) if _value_check_possible(res) else jnp.nanmean(res)
    if average == "weighted" and weights is not None:
        weights = jnp.where(idx, weights, 0.0)
        w = _safe_divide(weights, jnp.sum(weights))
        return jnp.sum((res * w)[idx]) if _value_check_possible(res) else jnp.nansum(res * w)
    raise ValueError("Received an incompatible combinations of inputs to make reduction.")


def _binary_average_precision_compute(
    state: Union[Array, Tuple[Array, Array]],
    thresholds: Optional[Array],
    pos_label: int = 1,
) -> Array:
    precision, recall, _ = _binary_precision_recall_curve_compute(state, thresholds, pos_label)
    return -jnp.sum((recall[1:] - recall[:-1]) * precision[:-1])


def binary_average_precision(
    preds: Array,
    target: Array,
    thresholds: Thresholds = None,
    ignore_index: Optional[int] = None,
    validate_args: bool = True,
) -> Array:
    if validate_args:
        _binary_precision_recall_curve_arg_validation(thresholds, ignore_index)
        _binary_precision_recall_curve_tensor_validation(preds, target, ignore_index)
    preds, target, thresholds, mask = _binary_precision_recall_curve_format(preds, target, thresholds, ignore_index)
    if thresholds is None and ignore_index is not None:
        preds, target = _exact_mode_filter(preds, target, thresholds, ignore_index, mask)
        mask = None
    state = _binary_precision_recall_curve_update(preds, target, thresholds, mask)
    return _binary_average_precision_compute(state, thresholds)


def _multiclass_average_precision_compute(
    state: Union[Array, Tuple[Array, Array]],
    num_classes: int,
    average: Optional[str] = "macro",
    thresholds: Optional[Array] = None,
) -> Array:
    precision, recall, _ = _multiclass_precision_recall_curve_compute(state, num_classes, thresholds)
    if isinstance(state, tuple):
        weights = jnp.bincount(_exact_target_for_weights(state), length=num_classes).astype(jnp.float32)
    else:
        weights = (state[0, :, 1, 0] + state[0, :, 1, 1]).astype(jnp.float32)
    return _reduce_average_precision(precision, recall, average, weights=weights)


def multiclass_average_precision(
    preds: Array,
    target: Array,
    num_classes: int,
    average: Optional[str] = "macro",
    thresholds: Thresholds = None,
    ignore_index: Optional[int] = None,
    validate_args: bool = True,
) -> Array:
    if validate_args:
        _multiclass_precision_recall_curve_arg_validation(num_classes, thresholds, ignore_index)
        _multiclass_precision_recall_curve_tensor_validation(preds, target, num_classes, ignore_index)
        allowed_average = ("macro", "weighted", "none", None)
        if average not in allowed_average:
            raise ValueError(f"Expected argument `average` to be one of {allowed_average} but got {average}")
    preds, target, thresholds, mask = _multiclass_precision_recall_curve_format(
        preds, target, num_classes, thresholds, ignore_index
    )
    if thresholds is None and ignore_index is not None:
        preds, target = _exact_mode_filter(preds, target, thresholds, ignore_index, mask)
        mask = None
    state = _multiclass_precision_recall_curve_update(preds, target, num_classes, thresholds, mask)
    return _multiclass_average_precision_compute(state, num_classes, average, thresholds)


def _multilabel_average_precision_compute(
    state,
    num_labels: int,
    average: Optional[str],
    thresholds: Optional[Array],
    ignore_index: Optional[int] = None,
) -> Array:
    if average == "micro":
        if isinstance(state, Array) and thresholds is not None:
            return _binary_average_precision_compute(jnp.sum(state, axis=1), thresholds)
        preds, target, mask = state
        preds, target, m = preds.reshape(-1), target.reshape(-1), mask.reshape(-1)
        preds, target = _exact_mode_filter(preds, target, None, 0, m)
        return _binary_average_precision_compute((preds, target), thresholds=None)

    precision, recall, _ = _multilabel_precision_recall_curve_compute(state, num_labels, thresholds, ignore_index)
    if isinstance(state, tuple):
        weights = jnp.sum((jnp.asarray(state[1]) == 1) & jnp.asarray(state[2]), axis=0).astype(jnp.float32)
    else:
        weights = (state[0, :, 1, 0] + state[0, :, 1, 1]).astype(jnp.float32)
    return _reduce_average_precision(precision, recall, average, weights=weights)


def multilabel_average_precision(
    preds: Array,
    target: Array,
    num_labels: int,
    average: Optional[str] = "macro",
    thresholds: Thresholds = None,
    ignore_index: Optional[int] = None,
    validate_args: bool = True,
) -> Array:
    if validate_args:
        _multilabel_precision_recall_curve_arg_validation(num_labels, thresholds, ignore_index)
        _multilabel_precision_recall_curve_tensor_validation(preds, target, num_labels, ignore_index)
        allowed_average = ("micro", "macro", "weighted", "none", None)
        if average not in allowed_average:
            raise ValueError(f"Expected argument `average` to be one of {allowed_average} but got {average}")
    preds, target, thresholds, mask = _multilabel_precision_recall_curve_format(
        preds, target, num_labels, thresholds, ignore_index
    )
    state = _multilabel_precision_recall_curve_update(preds, target, num_labels, thresholds, mask)
    return _multilabel_average_precision_compute(state, num_labels, average, thresholds, ignore_index)


def average_precision(
    preds: Array,
    target: Array,
    task: str,
    thresholds: Thresholds = None,
    num_classes: Optional[int] = None,
    num_labels: Optional[int] = None,
    average: Optional[str] = "macro",
    ignore_index: Optional[int] = None,
    validate_args: bool = True,
):
    """Task-dispatch façade over binary/multiclass/multilabel average precision (reference functional/classification/average_precision.py).

    Example:
        >>> import jax.numpy as jnp
        >>> from metrics_tpu.functional import average_precision
        >>> preds = jnp.array([[0.7, 0.2, 0.1], [0.2, 0.6, 0.2], [0.1, 0.2, 0.7], [0.3, 0.4, 0.3]])
        >>> target = jnp.array([0, 1, 2, 1])
        >>> average_precision(preds, target, task="multiclass", num_classes=3)
        Array(1., dtype=float32)
    """
    task = str(task).lower()
    if task == "binary":
        return binary_average_precision(preds, target, thresholds, ignore_index, validate_args)
    if task == "multiclass":
        assert isinstance(num_classes, int)
        return multiclass_average_precision(preds, target, num_classes, average, thresholds, ignore_index, validate_args)
    if task == "multilabel":
        assert isinstance(num_labels, int)
        return multilabel_average_precision(preds, target, num_labels, average, thresholds, ignore_index, validate_args)
    raise ValueError(f"Expected argument `task` to either be 'binary', 'multiclass' or 'multilabel' but got {task}")
