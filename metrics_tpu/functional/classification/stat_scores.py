"""Stat-scores (tp/fp/tn/fn) functional pipeline — the foundation of the
classification package.

Reference parity: src/torchmetrics/functional/classification/stat_scores.py — the
5-stage decomposition ``_<task>_stat_scores_{arg_validation, tensor_validation, format,
update, compute}`` (binary :25-138, multiclass :212-440, multilabel :552-693).

TPU-first redesign (SURVEY §7.1):

- ``ignore_index`` is a **0-weight mask**, not boolean filtering (static shapes).
- Per-class counting is one-hot arithmetic (rides the MXU), not index scatter.
- Logit auto-detection ("apply sigmoid if preds outside [0,1]") uses ``lax.cond`` on a
  traced predicate so it stays value-exact *and* jittable.
- Value-dependent validation only runs on concrete arrays (auto ``validate_args=False``
  inside jit).
"""

from __future__ import annotations

import functools
from typing import Optional, Tuple

import jax
import jax.numpy as jnp
from jax import lax, Array

from metrics_tpu.utils.checks import _check_same_shape, _value_check_possible
from metrics_tpu.utils.compute import _safe_divide
from metrics_tpu.utils.data import select_topk

# --------------------------------------------------------------------------- helpers


def _sigmoid_if_logits(preds: Array) -> Array:
    """Apply sigmoid iff any value is outside [0,1] (value-exact, trace-safe)."""
    if _value_check_possible(preds):
        if bool(jnp.any((preds < 0) | (preds > 1))):
            return jax.nn.sigmoid(preds)
        return preds
    return lax.cond(jnp.any((preds < 0) | (preds > 1)), jax.nn.sigmoid, lambda x: x, preds)


def _softmax_if_logits(preds: Array, axis: int = 1) -> Array:
    """Apply softmax iff preds don't already sum to 1 along ``axis``."""
    if _value_check_possible(preds):
        if not bool(jnp.allclose(jnp.sum(preds, axis=axis), 1.0, atol=1e-4)):
            return jax.nn.softmax(preds, axis=axis)
        return preds
    return lax.cond(
        jnp.allclose(jnp.sum(preds, axis=axis), 1.0, atol=1e-4), lambda x: x, lambda x: jax.nn.softmax(x, axis=axis), preds
    )


def _ignore_mask(target: Array, ignore_index: Optional[int]) -> Array:
    """Boolean weight mask that zeroes out ignored positions."""
    if ignore_index is None:
        return jnp.ones_like(target, dtype=jnp.bool_)
    return target != ignore_index


# --------------------------------------------------------------------------- binary


def _binary_stat_scores_arg_validation(
    threshold: float = 0.5,
    multidim_average: str = "global",
    ignore_index: Optional[int] = None,
) -> None:
    """Reference stat_scores.py:25-45."""
    if not (isinstance(threshold, float) and (0 <= threshold <= 1)):
        raise ValueError(f"Expected argument `threshold` to be a float in the [0,1] range, but got {threshold}.")
    allowed_multidim_average = ("global", "samplewise")
    if multidim_average not in allowed_multidim_average:
        raise ValueError(
            f"Expected argument `multidim_average` to be one of {allowed_multidim_average}, but got {multidim_average}"
        )
    if ignore_index is not None and not isinstance(ignore_index, int):
        raise ValueError(f"Expected argument `ignore_index` to either be `None` or an integer, but got {ignore_index}")


def _binary_stat_scores_tensor_validation(
    preds: Array,
    target: Array,
    multidim_average: str = "global",
    ignore_index: Optional[int] = None,
) -> None:
    """Reference stat_scores.py:47-86."""
    _check_same_shape(preds, target)
    if jnp.issubdtype(target.dtype, jnp.floating):
        raise ValueError("Expected argument `target` to be an int or bool tensor, but got a float tensor.")
    if _value_check_possible(target):
        unique_values = set(jnp.unique(target).tolist())
        allowed = {0, 1} if ignore_index is None else {0, 1, ignore_index}
        if not unique_values.issubset(allowed):
            raise RuntimeError(
                f"Detected the following values in `target`: {sorted(unique_values)} but expected only"
                f" the following values {sorted(allowed)}."
            )
    if jnp.issubdtype(preds.dtype, jnp.floating):
        pass  # probs/logits — resolved in format
    elif _value_check_possible(preds):
        unique_values = set(jnp.unique(preds).tolist())
        if not unique_values.issubset({0, 1}):
            raise RuntimeError(
                f"Detected the following values in `preds`: {sorted(unique_values)} but expected only"
                " the following values [0,1] since preds is a label tensor."
            )
    if multidim_average != "global" and preds.ndim < 2:
        raise ValueError("Expected input to be at least 2D when multidim_average is set to `samplewise`")


def _binary_stat_scores_format(
    preds: Array,
    target: Array,
    threshold: float = 0.5,
    ignore_index: Optional[int] = None,
) -> Tuple[Array, Array, Array]:
    """→ flattened ``(N, X)`` 0/1 preds & target + weight mask (reference :88-115).

    Divergence from reference (by design): instead of filtering ``ignore_index``
    positions out, returns a 0/1 ``mask`` with the same shape — static-shape safe.
    """
    preds = jnp.asarray(preds)
    target = jnp.asarray(target)
    if jnp.issubdtype(preds.dtype, jnp.floating):
        preds = _sigmoid_if_logits(preds)
        preds = (preds > threshold).astype(jnp.int32)
    else:
        preds = preds.astype(jnp.int32)
    mask = _ignore_mask(target, ignore_index)
    target = jnp.where(mask, target, 0).astype(jnp.int32)
    preds = jnp.where(mask, preds, 0)

    preds = preds.reshape(preds.shape[0], -1)
    target = target.reshape(target.shape[0], -1)
    mask = mask.reshape(mask.shape[0], -1)
    return preds, target, mask


@functools.partial(jax.jit, static_argnums=(3,))
def _binary_stat_scores_update(
    preds: Array,
    target: Array,
    mask: Array,
    multidim_average: str = "global",
) -> Tuple[Array, Array, Array, Array]:
    """Count tp/fp/tn/fn, masked (reference :117-129). Jitted at definition —
    see ``_multiclass_stat_scores_update``."""
    m = mask.astype(jnp.int32)
    axis = None if multidim_average == "global" else 1
    tp = jnp.sum((preds * target) * m, axis=axis)
    fn = jnp.sum(((1 - preds) * target) * m, axis=axis)
    fp = jnp.sum((preds * (1 - target)) * m, axis=axis)
    tn = jnp.sum(((1 - preds) * (1 - target)) * m, axis=axis)
    return tp, fp, tn, fn


def _binary_stat_scores_compute(
    tp: Array, fp: Array, tn: Array, fn: Array, multidim_average: str = "global"
) -> Array:
    """Stack to [tp, fp, tn, fn, support] (reference :131-138)."""
    return jnp.stack([tp, fp, tn, fn, tp + fn], axis=0 if multidim_average == "global" else 1)


def binary_stat_scores(
    preds: Array,
    target: Array,
    threshold: float = 0.5,
    multidim_average: str = "global",
    ignore_index: Optional[int] = None,
    validate_args: bool = True,
) -> Array:
    """Compute tp/fp/tn/fn for binary tasks (reference stat_scores.py:141-209)."""
    if validate_args:
        _binary_stat_scores_arg_validation(threshold, multidim_average, ignore_index)
        _binary_stat_scores_tensor_validation(preds, target, multidim_average, ignore_index)
    preds, target, mask = _binary_stat_scores_format(preds, target, threshold, ignore_index)
    tp, fp, tn, fn = _binary_stat_scores_update(preds, target, mask, multidim_average)
    return _binary_stat_scores_compute(tp, fp, tn, fn, multidim_average)


# --------------------------------------------------------------------------- multiclass


def _multiclass_stat_scores_arg_validation(
    num_classes: int,
    top_k: int = 1,
    average: Optional[str] = "macro",
    multidim_average: str = "global",
    ignore_index: Optional[int] = None,
) -> None:
    """Reference stat_scores.py:212-245."""
    if not isinstance(num_classes, int) or num_classes < 2:
        raise ValueError(f"Expected argument `num_classes` to be an integer larger than 1, but got {num_classes}")
    if not isinstance(top_k, int) or top_k < 1:
        raise ValueError(f"Expected argument `top_k` to be an integer larger than or equal to 1, but got {top_k}")
    if top_k > num_classes:
        raise ValueError(
            f"Expected argument `top_k` to be smaller or equal to `num_classes` but got {top_k} and {num_classes}"
        )
    allowed_average = ("micro", "macro", "weighted", "none", None)
    if average not in allowed_average:
        raise ValueError(f"Expected argument `average` to be one of {allowed_average}, but got {average}")
    allowed_multidim_average = ("global", "samplewise")
    if multidim_average not in allowed_multidim_average:
        raise ValueError(
            f"Expected argument `multidim_average` to be one of {allowed_multidim_average}, but got {multidim_average}"
        )
    if ignore_index is not None and not isinstance(ignore_index, int):
        raise ValueError(f"Expected argument `ignore_index` to either be `None` or an integer, but got {ignore_index}")


def _multiclass_stat_scores_tensor_validation(
    preds: Array,
    target: Array,
    num_classes: int,
    multidim_average: str = "global",
    ignore_index: Optional[int] = None,
) -> None:
    """Reference stat_scores.py:247-316."""
    if preds.ndim == target.ndim + 1:
        if not jnp.issubdtype(preds.dtype, jnp.floating):
            raise ValueError("If `preds` have one dimension more than `target`, `preds` should be a float tensor.")
        if preds.shape[1] != num_classes:
            raise ValueError("If `preds` have one dimension more than `target`, `preds.shape[1]` should be"
                             " equal to number of classes.")
        if preds.shape[2:] != target.shape[1:]:
            raise ValueError("If `preds` have one dimension more than `target`, the shape of `preds` should be"
                             " (N, C, ...), and the shape of `target` should be (N, ...).")
        if multidim_average != "global" and preds.ndim < 3:
            raise ValueError("If `preds` have one dimension more than `target`, the shape of `preds` should "
                             "at least be of shape (N, C, ...) when multidim_average is set to `samplewise`")
    elif preds.ndim == target.ndim:
        if preds.shape != target.shape:
            raise ValueError("The `preds` and `target` should have the same shape,"
                             f" got `preds` with shape={preds.shape} and `target` with shape={target.shape}.")
        if multidim_average != "global" and preds.ndim < 2:
            raise ValueError("When `preds` and `target` have the same shape, the shape should be (N, ...) with at"
                             " least 2 dimensions when multidim_average is set to `samplewise`")
        if jnp.issubdtype(preds.dtype, jnp.floating):
            raise ValueError("If `preds` and `target` have the same shape, `preds` should be an int tensor.")
    else:
        raise ValueError("Either `preds` and `target` both should have the (same) shape (N, ...), or `target` should be"
                         " (N, ...) and `preds` should be (N, C, ...).")

    if _value_check_possible(target):
        num_unique = int(jnp.max(target, initial=0)) + 1
        check = num_unique > (num_classes if ignore_index is None else num_classes + 1)
        if (ignore_index is None and int(jnp.min(target)) < 0) or check:
            raise RuntimeError(f"Detected more unique values in `target` than `num_classes`. Expected only up to"
                               f" {num_classes} but found up to {num_unique}.")
    if _value_check_possible(preds) and not jnp.issubdtype(preds.dtype, jnp.floating):
        if int(jnp.max(preds, initial=0)) + 1 > num_classes:
            raise RuntimeError("Detected more unique values in `preds` than `num_classes`.")


def _multiclass_stat_scores_format(
    preds: Array,
    target: Array,
    top_k: int = 1,
) -> Tuple[Array, Array]:
    """Flatten extra dims → preds ``(N, C, X)`` probs (or ``(N, X)`` labels), target ``(N, X)``.

    Reference stat_scores.py:318-334.
    """
    preds = jnp.asarray(preds)
    target = jnp.asarray(target)
    if jnp.issubdtype(preds.dtype, jnp.floating):
        if top_k == 1:
            preds = jnp.argmax(preds, axis=1)
            preds = preds.reshape(preds.shape[0], -1)
        else:
            preds = preds.reshape(preds.shape[0], preds.shape[1], -1)
    else:
        preds = preds.reshape(preds.shape[0], -1)
    target = target.reshape(target.shape[0], -1)
    return preds, target


@functools.partial(jax.jit, static_argnums=(2, 3, 4, 5, 6))
def _multiclass_stat_scores_update(
    preds: Array,
    target: Array,
    num_classes: int,
    top_k: int = 1,
    average: Optional[str] = "macro",
    multidim_average: str = "global",
    ignore_index: Optional[int] = None,
) -> Tuple[Array, Array, Array, Array]:
    """Per-class tp/fp/tn/fn via one-hot arithmetic (MXU-friendly).

    Reference stat_scores.py:336-410 computes a confusion matrix by bincount; the
    one-hot formulation here lowers to batched matmul/reduction and needs no scatter.
    Output shapes: global → ``(C,)``; samplewise → ``(N, C)``.

    Jitted at definition (all config args static): the eager module-metric path
    would otherwise dispatch ~10 separate CPU kernels per update — compiling
    fuses them and is what makes the CPU counting path beat the reference's
    single C++ bincount (~6x on the scatter itself at 1M samples). Under an
    outer ``jit`` the wrapper inlines into the surrounding trace.
    """
    mask = _ignore_mask(target, ignore_index)
    target_ = jnp.where(mask, target, 0).astype(jnp.int32)
    m = mask.astype(jnp.float32)

    # Fast path: with label preds, top_k=1 and a global reduce, every count
    # derives from the (C, C) confusion matrix, which routes through the
    # kernel plane's pair count (metrics_tpu/kernels/confmat.py, via
    # _multiclass_confusion_matrix_update): on the host backend one O(N)
    # masked bincount, on accelerators the MXU one-hot matmul (33x over the
    # scatter on the v5e, benchmarks/experiments/onehot_confmat_tpu.py, and
    # one (C,C)-product where the O(N*C) elementwise one-hot form this path
    # previously used on accelerators needs four), and on TPU — where the
    # registry selects it — the Pallas fused streaming kernel that never
    # materializes the (N, C) one-hot operands in HBM (the ROOFLINE.md
    # `stat_scores update` 43.8%-of-HBM row this plane exists for). Excluded:
    # matmul-ineligible sizes on accelerators, where the cm update would fall
    # back to the TPU-slow scatter — the elementwise one-hot arithmetic below
    # is the better floor there.
    # The branch is trace-time and could in principle mismatch the executing
    # device (jit with an explicit non-default device) — that is safe because
    # every path is integer-exact, so path choice affects speed only.
    from metrics_tpu.functional.classification.confusion_matrix import (
        _matmul_lowering_eligible,
        _multiclass_confusion_matrix_update,
    )

    if (
        multidim_average == "global"
        and preds.ndim != 3
        and (jax.default_backend() == "cpu"
             or _matmul_lowering_eligible(preds.size, num_classes))
    ):
        cm = _multiclass_confusion_matrix_update(preds, target, num_classes, ignore_index)
        tp = jnp.diag(cm)
        fn = jnp.sum(cm, axis=1) - tp
        fp = jnp.sum(cm, axis=0) - tp
        tn = jnp.sum(cm) - tp - fn - fp
        return tp.astype(jnp.int32), fp.astype(jnp.int32), tn.astype(jnp.int32), fn.astype(jnp.int32)

    # Out-of-range indices (reachable only with validate_args=False) drop the
    # whole PAIR, exactly like the cm fast path above — otherwise which route
    # runs (and hence the counts) would depend on batch size on accelerators.
    # one_hot already zeroes the out-of-range index itself; the pair-drop needs
    # the mask so e.g. an out-of-range pred doesn't leave its target counted
    # as fn.
    if preds.ndim != 3:
        m = m * ((preds >= 0) & (preds < num_classes)).astype(jnp.float32)
    m = m * ((target_ >= 0) & (target_ < num_classes)).astype(jnp.float32)

    oh_target = jax.nn.one_hot(target_, num_classes, dtype=jnp.float32) * m[..., None]  # (N, X, C)

    if preds.ndim == 3:  # (N, C, X) probs with top_k > 1
        topk_mask = select_topk(preds, top_k, dim=1)  # (N, C, X)
        oh_preds = jnp.moveaxis(topk_mask, 1, -1).astype(jnp.float32) * m[..., None]  # (N, X, C)
    else:
        oh_preds = jax.nn.one_hot(preds.astype(jnp.int32), num_classes, dtype=jnp.float32) * m[..., None]

    sum_axes = (0, 1) if multidim_average == "global" else (1,)
    # The products are exact 0/1 values in f32; summing them in int32 keeps the
    # counts exact past 2^24 (f32 accumulation would silently round there) and
    # matches the bincount fast path bit-for-bit on every backend.
    def _count(prod: Array) -> Array:
        return jnp.sum(prod.astype(jnp.int32), axis=sum_axes)

    tp = _count(oh_preds * oh_target)
    fp = _count(oh_preds * (1.0 - oh_target))
    fn = _count((1.0 - oh_preds) * oh_target)
    # tn must only count non-ignored positions: scale by mask
    tn = _count((1.0 - oh_preds) * (1.0 - oh_target) * m[..., None])
    return tp, fp, tn, fn


def _multiclass_stat_scores_compute(
    tp: Array, fp: Array, tn: Array, fn: Array, average: Optional[str] = "macro", multidim_average: str = "global"
) -> Array:
    """Reference stat_scores.py:412-437."""
    res = jnp.stack([tp, fp, tn, fn, tp + fn], axis=-1)
    if average == "micro":
        return jnp.sum(res, axis=-2)
    if average in ("macro", "weighted"):
        return res  # averaging happens in the derived metric formulas
    return res


def multiclass_stat_scores(
    preds: Array,
    target: Array,
    num_classes: int,
    average: Optional[str] = "macro",
    top_k: int = 1,
    multidim_average: str = "global",
    ignore_index: Optional[int] = None,
    validate_args: bool = True,
) -> Array:
    """Compute tp/fp/tn/fn for multiclass tasks (reference stat_scores.py:440-530)."""
    if validate_args:
        _multiclass_stat_scores_arg_validation(num_classes, top_k, average, multidim_average, ignore_index)
        _multiclass_stat_scores_tensor_validation(preds, target, num_classes, multidim_average, ignore_index)
    preds, target = _multiclass_stat_scores_format(preds, target, top_k)
    tp, fp, tn, fn = _multiclass_stat_scores_update(
        preds, target, num_classes, top_k, average, multidim_average, ignore_index
    )
    return _multiclass_stat_scores_compute(tp, fp, tn, fn, average, multidim_average)


# --------------------------------------------------------------------------- multilabel


def _multilabel_stat_scores_arg_validation(
    num_labels: int,
    threshold: float = 0.5,
    average: Optional[str] = "macro",
    multidim_average: str = "global",
    ignore_index: Optional[int] = None,
) -> None:
    """Reference stat_scores.py:552-581."""
    if not isinstance(num_labels, int) or num_labels < 2:
        raise ValueError(f"Expected argument `num_labels` to be an integer larger than 1, but got {num_labels}")
    if not (isinstance(threshold, float) and (0 <= threshold <= 1)):
        raise ValueError(f"Expected argument `threshold` to be a float, but got {threshold}.")
    allowed_average = ("micro", "macro", "weighted", "none", None)
    if average not in allowed_average:
        raise ValueError(f"Expected argument `average` to be one of {allowed_average}, but got {average}")
    allowed_multidim_average = ("global", "samplewise")
    if multidim_average not in allowed_multidim_average:
        raise ValueError(
            f"Expected argument `multidim_average` to be one of {allowed_multidim_average}, but got {multidim_average}"
        )
    if ignore_index is not None and not isinstance(ignore_index, int):
        raise ValueError(f"Expected argument `ignore_index` to either be `None` or an integer, but got {ignore_index}")


def _multilabel_stat_scores_tensor_validation(
    preds: Array,
    target: Array,
    num_labels: int,
    multidim_average: str = "global",
    ignore_index: Optional[int] = None,
) -> None:
    """Reference stat_scores.py:583-630."""
    _check_same_shape(preds, target)
    if preds.shape[1] != num_labels:
        raise ValueError(
            "Expected both `target.shape[1]` and `preds.shape[1]` to be equal to the number of labels"
        )
    if jnp.issubdtype(target.dtype, jnp.floating):
        raise ValueError("Expected argument `target` to be an int or bool tensor, but got a float tensor.")
    if _value_check_possible(target):
        unique_values = set(jnp.unique(target).tolist())
        allowed = {0, 1} if ignore_index is None else {0, 1, ignore_index}
        if not unique_values.issubset(allowed):
            raise RuntimeError(
                f"Detected the following values in `target`: {sorted(unique_values)} but expected only"
                f" the following values {sorted(allowed)}."
            )
    if multidim_average != "global" and preds.ndim < 3:
        raise ValueError("Expected input to be at least 3D when multidim_average is set to `samplewise`")


def _multilabel_stat_scores_format(
    preds: Array,
    target: Array,
    num_labels: int,
    threshold: float = 0.5,
    ignore_index: Optional[int] = None,
) -> Tuple[Array, Array, Array]:
    """→ ``(N, C, X)`` 0/1 preds & target + mask (reference stat_scores.py:632-654)."""
    preds = jnp.asarray(preds)
    target = jnp.asarray(target)
    if jnp.issubdtype(preds.dtype, jnp.floating):
        preds = _sigmoid_if_logits(preds)
        preds = (preds > threshold).astype(jnp.int32)
    else:
        preds = preds.astype(jnp.int32)
    mask = _ignore_mask(target, ignore_index)
    target = jnp.where(mask, target, 0).astype(jnp.int32)
    preds = jnp.where(mask, preds, 0)
    preds = preds.reshape(preds.shape[0], preds.shape[1], -1)
    target = target.reshape(target.shape[0], target.shape[1], -1)
    mask = mask.reshape(mask.shape[0], mask.shape[1], -1)
    return preds, target, mask


@functools.partial(jax.jit, static_argnums=(3,))
def _multilabel_stat_scores_update(
    preds: Array,
    target: Array,
    mask: Array,
    multidim_average: str = "global",
) -> Tuple[Array, Array, Array, Array]:
    """Reference stat_scores.py:656-666. Output: global → ``(C,)``; samplewise →
    ``(N, C)``. Jitted at definition — see ``_multiclass_stat_scores_update``."""
    m = mask.astype(jnp.int32)
    sum_axes = (0, 2) if multidim_average == "global" else (2,)
    tp = jnp.sum((preds * target) * m, axis=sum_axes)
    fn = jnp.sum(((1 - preds) * target) * m, axis=sum_axes)
    fp = jnp.sum((preds * (1 - target)) * m, axis=sum_axes)
    tn = jnp.sum(((1 - preds) * (1 - target)) * m, axis=sum_axes)
    return tp, fp, tn, fn


def _multilabel_stat_scores_compute(
    tp: Array, fp: Array, tn: Array, fn: Array, average: Optional[str] = "macro", multidim_average: str = "global"
) -> Array:
    """Reference stat_scores.py:668-690."""
    res = jnp.stack([tp, fp, tn, fn, tp + fn], axis=-1)
    if average == "micro":
        return jnp.sum(res, axis=-2)
    return res


def multilabel_stat_scores(
    preds: Array,
    target: Array,
    num_labels: int,
    threshold: float = 0.5,
    average: Optional[str] = "macro",
    multidim_average: str = "global",
    ignore_index: Optional[int] = None,
    validate_args: bool = True,
) -> Array:
    """Compute tp/fp/tn/fn for multilabel tasks (reference stat_scores.py:693-780)."""
    if validate_args:
        _multilabel_stat_scores_arg_validation(num_labels, threshold, average, multidim_average, ignore_index)
        _multilabel_stat_scores_tensor_validation(preds, target, num_labels, multidim_average, ignore_index)
    preds, target, mask = _multilabel_stat_scores_format(preds, target, num_labels, threshold, ignore_index)
    tp, fp, tn, fn = _multilabel_stat_scores_update(preds, target, mask, multidim_average)
    return _multilabel_stat_scores_compute(tp, fp, tn, fn, average, multidim_average)


def stat_scores(
    preds: Array,
    target: Array,
    task: str,
    threshold: float = 0.5,
    num_classes: Optional[int] = None,
    num_labels: Optional[int] = None,
    average: Optional[str] = "micro",
    multidim_average: str = "global",
    top_k: int = 1,
    ignore_index: Optional[int] = None,
    validate_args: bool = True,
) -> Array:
    """Task-dispatch façade (reference stat_scores.py:783-…).

    Example:
        >>> import jax.numpy as jnp
        >>> from metrics_tpu.functional import stat_scores
        >>> stat_scores(jnp.array([0, 2, 1, 2]), jnp.array([0, 1, 1, 2]), task="multiclass", num_classes=3)
        Array([3, 1, 7, 1, 4], dtype=int32)
    """
    task = str(task).lower()
    if task == "binary":
        return binary_stat_scores(preds, target, threshold, multidim_average, ignore_index, validate_args)
    if task == "multiclass":
        assert isinstance(num_classes, int)
        return multiclass_stat_scores(
            preds, target, num_classes, average, top_k, multidim_average, ignore_index, validate_args
        )
    if task == "multilabel":
        assert isinstance(num_labels, int)
        return multilabel_stat_scores(
            preds, target, num_labels, threshold, average, multidim_average, ignore_index, validate_args
        )
    raise ValueError(f"Expected argument `task` to either be 'binary', 'multiclass' or 'multilabel' but got {task}")
