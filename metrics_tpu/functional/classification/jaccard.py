"""Jaccard index (IoU) functionals.

Reference parity: src/torchmetrics/functional/classification/jaccard.py
(``_jaccard_index_reduce`` over a confusion matrix).
"""

from __future__ import annotations

from typing import Optional

import jax.numpy as jnp
from jax import Array

from metrics_tpu.functional.classification.confusion_matrix import (
    binary_confusion_matrix,
    multiclass_confusion_matrix,
    multilabel_confusion_matrix,
)
from metrics_tpu.utils.compute import _safe_divide


def _jaccard_index_reduce(confmat: Array, average: Optional[str], ignore_index: Optional[int] = None) -> Array:
    """Reference jaccard.py ``_jaccard_index_reduce``."""
    allowed_average = ("binary", "micro", "macro", "weighted", "none", None)
    if average not in allowed_average:
        raise ValueError(f"The `average` has to be one of {allowed_average}, got {average}.")
    confmat = confmat.astype(jnp.float32)
    if average == "binary":
        return confmat[1, 1] / (confmat[0, 1] + confmat[1, 0] + confmat[1, 1])

    # NOTE: ignore_index is accepted for signature stability but the v0.12
    # reduce ignores it — ignored samples are already dropped from the
    # confmat, and the ignored CLASS still contributes a 0 to macro (see
    # the weights note below)
    multilabel = confmat.ndim == 3
    if multilabel:
        num = confmat[:, 1, 1]
        denom = confmat[:, 1, 1] + confmat[:, 0, 1] + confmat[:, 1, 0]
    else:
        num = jnp.diag(confmat)
        denom = jnp.sum(confmat, axis=0) + jnp.sum(confmat, axis=1) - jnp.diag(confmat)

    if average == "micro":
        num = jnp.sum(num)
        denom = jnp.sum(denom)

    jaccard = _safe_divide(num, denom)

    if average is None or average == "none" or average == "micro":
        return jaccard
    if average == "weighted":
        weights = confmat[:, 1, 1] + confmat[:, 1, 0] if multilabel else jnp.sum(confmat, axis=1)
    else:
        # plain ones weights, as the reference (jaccard.py:80-81): absent
        # classes — and even an in-range ignored class — contribute their
        # _safe_divide 0 score to the macro mean. Zero-weighting them is the
        # LATER torchmetrics convention; the round-4 fuzz soak caught it
        # leaking in here (0.05-0.07 absolute divergence on absent-class
        # draws vs the executed reference).
        weights = jnp.ones_like(jaccard)
    # plain division like the reference's `(weights*jaccard)/weights.sum()`:
    # an all-ignored stream (zero total weight, weighted average) is NaN, not 0
    return jnp.sum(jaccard * weights / jnp.sum(weights))


def binary_jaccard_index(preds, target, threshold=0.5, ignore_index=None, validate_args=True) -> Array:
    confmat = binary_confusion_matrix(preds, target, threshold, ignore_index, normalize=None, validate_args=validate_args)
    return _jaccard_index_reduce(confmat, average="binary")


def multiclass_jaccard_index(preds, target, num_classes, average="macro", ignore_index=None, validate_args=True) -> Array:
    confmat = multiclass_confusion_matrix(preds, target, num_classes, ignore_index, normalize=None, validate_args=validate_args)
    return _jaccard_index_reduce(confmat, average=average, ignore_index=ignore_index)


def multilabel_jaccard_index(preds, target, num_labels, threshold=0.5, average="macro", ignore_index=None, validate_args=True) -> Array:
    confmat = multilabel_confusion_matrix(preds, target, num_labels, threshold, ignore_index, normalize=None, validate_args=validate_args)
    return _jaccard_index_reduce(confmat, average=average)


def jaccard_index(
    preds, target, task, threshold=0.5, num_classes=None, num_labels=None, average="macro",
    ignore_index=None, validate_args=True,
) -> Array:
    """Task-dispatch façade over binary/multiclass/multilabel Jaccard index (reference functional/classification/jaccard.py).

    Example:
        >>> import jax.numpy as jnp
        >>> from metrics_tpu.functional import jaccard_index
        >>> jaccard_index(jnp.array([0, 2, 1, 2]), jnp.array([0, 1, 1, 2]), task="multiclass", num_classes=3)
        Array(0.6666667, dtype=float32)
    """
    task = str(task).lower()
    if task == "binary":
        return binary_jaccard_index(preds, target, threshold, ignore_index, validate_args)
    if task == "multiclass":
        assert isinstance(num_classes, int)
        return multiclass_jaccard_index(preds, target, num_classes, average, ignore_index, validate_args)
    if task == "multilabel":
        assert isinstance(num_labels, int)
        return multilabel_jaccard_index(preds, target, num_labels, threshold, average, ignore_index, validate_args)
    raise ValueError(f"Expected argument `task` to either be 'binary', 'multiclass' or 'multilabel' but got {task}")
