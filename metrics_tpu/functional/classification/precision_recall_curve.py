"""Precision-recall curve functionals — the two state regimes.

Reference parity: src/torchmetrics/functional/classification/precision_recall_curve.py —
``_binary_clf_curve`` (:27), ``_adjust_threshold_arg`` (:79), format/update/compute for
binary/multiclass/multilabel, incl. the **binned** branch (:184-201) that replaces
O(N)-sample storage with a constant-memory ``(T, 2, 2)`` confusion state.

TPU-first notes: the binned update has two value-identical lowerings chosen per
backend — a ``(T, M) @ (M,)`` comparison-matmul that rides the MXU on TPU, and a
bucketize+histogram form on the host backend that avoids the O(T·M) intermediate
entirely (``_binned_tp_fp_bucketized``; 40-60x vs the reference's comparison
form at 1M samples). Binned mode is the jit/shard_map-native path (static
shapes). Exact mode (``thresholds=None``) keeps ragged value lists and computes
on host via sort+cumsum — same as the reference's design split.
"""

from __future__ import annotations

import functools
from typing import List, Optional, Sequence, Tuple, Union

import jax
import jax.numpy as jnp
from jax import Array

from metrics_tpu.functional.classification.stat_scores import _ignore_mask, _sigmoid_if_logits, _softmax_if_logits
from metrics_tpu.utils.checks import _check_same_shape, _value_check_possible
from metrics_tpu.utils.compute import _safe_divide
from metrics_tpu.utils.data import _bincount, _cumsum

Thresholds = Optional[Union[int, List[float], Array]]


def _binned_tp_fp_bucketized(
    probs: Array, is_pos: Array, valid: Array, col: Array, thresholds: Array, num_cols: int
) -> Tuple[Array, Array]:
    """(T, C) tp/fp counts of ``prob >= threshold`` via bucketize + histogram.

    The comparison-matmul form materialises a (T, E) intermediate — 400 MB at
    1M samples × 100 thresholds — which is the whole cost of the binned update
    on the host backend. This form is O(E·log T): each element's threshold-bin
    ``b = searchsorted(thresholds, p, 'right')`` satisfies ``p >= thr_t ⟺
    b > t``, so one histogram over (bin, column, polarity) keys and an
    inclusive cumsum over bins reproduce the counts EXACTLY (integer counts,
    bit-identical to the comparison form). Flat inputs: ``probs``/``is_pos``/
    ``valid``/``col`` of shape (E,).
    """
    len_t = thresholds.shape[0]
    # searchsorted needs ascending thresholds; the public API accepts any order
    # (the reference compares against user-ordered thresholds), so bucketize in
    # sorted space and un-permute the counts back to the user's order.
    order = jnp.argsort(thresholds)
    b = jnp.searchsorted(thresholds[order], probs, side="right").astype(jnp.int32)  # (E,) in [0, T]
    key = (b * num_cols + col) * 2 + is_pos.astype(jnp.int32)
    overflow = (len_t + 1) * num_cols * 2  # masked-out elements land past the kept range
    key = jnp.where(valid, key, overflow)
    hist = jnp.bincount(key.reshape(-1), length=overflow + 1)[:overflow].reshape(len_t + 1, num_cols, 2)
    cum = jnp.cumsum(hist, axis=0)  # cum[t] = counts with b <= t (sorted space)
    tp_sorted = cum[-1, :, 1][None, :] - cum[:len_t, :, 1]
    fp_sorted = cum[-1, :, 0][None, :] - cum[:len_t, :, 0]
    inv = jnp.argsort(order)
    return tp_sorted[inv], fp_sorted[inv]


def _binary_clf_curve(
    preds: Array,
    target: Array,
    sample_weights: Optional[Array] = None,
    pos_label: int = 1,
    drop_ignore_sentinel: bool = False,
) -> Tuple[Array, Array, Array]:
    """fps/tps/thresholds by descending-score cumsum (reference :27-76).

    Host-side (exact mode): tied prediction scores are collapsed to a single
    threshold point (keeping the last cumsum value per distinct score), matching the
    reference/sklearn ``_binary_clf_curve``. Data-dependent output length — exact mode
    never runs inside jit.

    ``drop_ignore_sentinel`` must be set ONLY by callers whose preds went
    through the *_format helpers (probabilities in [0, 1], where the in-jit
    ``ignore_index`` path sentinel-fills with -1): unformatted scores (logits,
    distances) can legitimately contain -1.0, and silently deleting those rows
    here would corrupt the curve (round-4 advisor finding).
    """
    if sample_weights is not None and not isinstance(sample_weights, Array):
        sample_weights = jnp.asarray(sample_weights, dtype=jnp.float32)
    if not _value_check_possible(preds):
        raise RuntimeError(
            "Exact-mode (thresholds=None) curve COMPUTE cannot run inside jit: the number"
            " of distinct thresholds is data-dependent. Pass `thresholds=...` for the"
            " binned, fully jit-native mode — or keep only compute on the host: the"
            " module API's `update_state`/`sync_state` (including `ignore_index`, which"
            " is sentinel-masked at static shape) can stay fused; run `compute_from`"
            " eagerly."
        )
    if drop_ignore_sentinel:
        # drop sentinel-marked (in-jit ignore_index) rows; host-side boolean
        # indexing is fine here — exact compute never runs under a tracer
        keep = preds != _EXACT_IGNORE_SENTINEL
        if not bool(keep.all()):
            preds = preds[keep]
            target = target[keep]
            if sample_weights is not None:
                sample_weights = sample_weights[keep]
    order = jnp.argsort(preds)[::-1]
    preds = preds[order]
    target = target[order]
    weight = sample_weights[order] if sample_weights is not None else jnp.ones_like(preds, dtype=jnp.float32)

    target = (target == pos_label).astype(jnp.float32)
    tps = _cumsum(target * weight, axis=0)
    fps = _cumsum((1 - target) * weight, axis=0)

    # collapse runs of equal scores: keep the cumulative count at the end of each run
    distinct_idx = jnp.nonzero(jnp.diff(preds))[0]
    threshold_idxs = jnp.concatenate([distinct_idx, jnp.asarray([preds.shape[0] - 1])])
    return fps[threshold_idxs], tps[threshold_idxs], preds[threshold_idxs]


def _adjust_threshold_arg(thresholds: Thresholds = None) -> Optional[Array]:
    """Normalise the thresholds argument (reference :79-90)."""
    if isinstance(thresholds, int):
        thresholds = jnp.linspace(0, 1, thresholds, dtype=jnp.float32)
    if isinstance(thresholds, (list, tuple)):
        thresholds = jnp.asarray(thresholds, dtype=jnp.float32)
    return thresholds




# Exact-mode ignore marker: formatted preds are probabilities in [0, 1]
# (sigmoid/softmax applied in the *_format helpers), so -1 can never collide
# with a real score.
_EXACT_IGNORE_SENTINEL = -1.0


def _exact_mode_filter(preds, target, thresholds, ignore_index, mask):
    """Apply the ignore_index filter for exact mode; sentinel-fill inside jit.

    Eagerly the ignored rows are boolean-filtered out, exactly like the
    reference. Under a tracer that filter is data-dependent, so instead the
    ignored rows are kept at static shape with their scores overwritten by
    ``_EXACT_IGNORE_SENTINEL`` (a 0-weight marker outside the probability
    range); the host-side exact compute (``_binary_clf_curve``) drops sentinel
    rows before sorting, so the fused update runs in-trace and the computed
    curve is identical to the filtered one (SURVEY §7.1: "implement
    ignore_index as a 0-weight mask").

    For 2-D ``preds`` (multiclass one-vs-rest layout) the (N,)-mask ignores
    whole rows.
    """
    if thresholds is None and ignore_index is not None:
        if not _value_check_possible(mask):
            row_mask = mask[:, None] if preds.ndim == 2 and mask.ndim == 1 else mask
            preds = jnp.where(row_mask, preds, _EXACT_IGNORE_SENTINEL)
            # target was already zeroed on ignored rows by the format helper;
            # re-assert it so this function is safe standalone
            return preds, jnp.where(mask, target, 0)
        return preds[mask], target[mask]
    return preds, target


def _exact_target_for_weights(state) -> Array:
    """Host-side: the target rows of an exact-mode tuple state with any in-jit
    sentinel rows removed — for ``average="weighted"`` bincounts, which would
    otherwise count sentinel rows (target zeroed) into class 0."""
    preds, target = jnp.asarray(state[0]), jnp.asarray(state[1])
    col = preds[:, 0] if preds.ndim == 2 else preds
    keep = col != _EXACT_IGNORE_SENTINEL
    if not bool(keep.all()):
        target = target[keep]
    return target


def _binary_precision_recall_curve_arg_validation(
    thresholds: Thresholds = None,
    ignore_index: Optional[int] = None,
) -> None:
    if thresholds is not None and not isinstance(thresholds, (list, int, jax.Array)) and not hasattr(thresholds, "__len__"):
        raise ValueError(
            "Expected argument `thresholds` to either be an integer, list of floats or tensor of floats,"
            f" but got {thresholds}"
        )
    if isinstance(thresholds, int) and thresholds < 2:
        raise ValueError(f"If argument `thresholds` is an integer, expected it to be larger than 1, but got {thresholds}")
    if isinstance(thresholds, list) and not all(isinstance(t, float) and 0 <= t <= 1 for t in thresholds):
        raise ValueError(
            f"If argument `thresholds` is a list, expected all elements to be floats in the [0,1] range, but got {thresholds}"
        )
    if ignore_index is not None and not isinstance(ignore_index, int):
        raise ValueError(f"Expected argument `ignore_index` to either be `None` or an integer, but got {ignore_index}")


def _binary_precision_recall_curve_tensor_validation(
    preds: Array, target: Array, ignore_index: Optional[int] = None
) -> None:
    _check_same_shape(preds, target)
    if not jnp.issubdtype(preds.dtype, jnp.floating):
        raise ValueError("Expected argument `preds` to be an floating tensor, but got tensor with dtype"
                         f" {preds.dtype}")
    if jnp.issubdtype(target.dtype, jnp.floating):
        raise ValueError("Expected argument `target` to be an int or bool tensor, but got tensor with dtype"
                         f" {target.dtype}")
    if _value_check_possible(target):
        unique_values = set(jnp.unique(target).tolist())
        allowed = {0, 1} if ignore_index is None else {0, 1, ignore_index}
        if not unique_values.issubset(allowed):
            raise RuntimeError(
                f"Detected the following values in `target`: {sorted(unique_values)} but expected only"
                f" the following values {sorted(allowed)}."
            )


def _binary_precision_recall_curve_format(
    preds: Array,
    target: Array,
    thresholds: Thresholds = None,
    ignore_index: Optional[int] = None,
) -> Tuple[Array, Array, Optional[Array], Array]:
    """Flatten + sigmoid-if-logits; returns (preds, target, thresholds, weight-mask).

    Divergence from the reference (:150-…): ``ignore_index`` yields a 0/1 weight mask
    instead of filtering, so the binned path stays static-shape.
    """
    preds = jnp.asarray(preds).reshape(-1)
    target = jnp.asarray(target).reshape(-1)
    mask = _ignore_mask(target, ignore_index).reshape(-1)
    target = jnp.where(mask, target, 0)
    preds = _sigmoid_if_logits(preds)
    thresholds = _adjust_threshold_arg(thresholds)
    return preds, target, thresholds, mask


@jax.jit
def _binary_precision_recall_curve_update(
    preds: Array,
    target: Array,
    thresholds: Optional[Array],
    mask: Optional[Array] = None,
) -> Union[Array, Tuple[Array, Array]]:
    """Binned: (T,2,2) state (reference :184-201).

    Value-identical lowerings, chosen per backend (all integer-exact, so the
    trace-time branch affects speed only): on the host backend the bucketized
    histogram (no (T, M) intermediate — ~15x at 1M samples × 100 thresholds);
    on accelerators the kernel plane's ``binned_curve_counts`` entry
    (metrics_tpu/kernels/binned_curve.py) — the Pallas streaming kernel with
    an on-chip (T, 1) accumulator where the registry selects it, the (T, M)
    comparison + two MXU matvecs reference otherwise.
    """
    if thresholds is None:
        return preds, target
    len_t = thresholds.shape[0]
    w = mask.astype(jnp.float32) if mask is not None else jnp.ones_like(preds)
    t = target.astype(jnp.float32) * w
    pos = jnp.sum(t)
    neg = jnp.sum(w) - pos
    if jax.default_backend() == "cpu":
        tp, fp = _binned_tp_fp_bucketized(
            preds, target.astype(bool), w > 0, jnp.zeros(preds.shape, jnp.int32), thresholds, 1
        )
        tp, fp = tp[:, 0].astype(jnp.float32), fp[:, 0].astype(jnp.float32)
    else:
        from metrics_tpu.kernels.binned_curve import binned_curve_counts

        tp, fp = binned_curve_counts(preds, t, w, thresholds)
    fn = pos - tp
    tn = neg - fp
    confmat = jnp.stack([jnp.stack([tn, fp], axis=-1), jnp.stack([fn, tp], axis=-1)], axis=-2)
    return confmat.astype(jnp.int32).reshape(len_t, 2, 2)


def _binary_precision_recall_curve_compute(
    state: Union[Array, Tuple[Array, Array]],
    thresholds: Optional[Array],
    pos_label: int = 1,
) -> Tuple[Array, Array, Array]:
    """Reference :204-248."""
    if isinstance(state, Array) and thresholds is not None:
        tps = state[:, 1, 1]
        fps = state[:, 0, 1]
        fns = state[:, 1, 0]
        precision = _safe_divide(tps, tps + fps)
        recall = _safe_divide(tps, tps + fns)
        precision = jnp.concatenate([precision, jnp.ones(1, dtype=precision.dtype)])
        recall = jnp.concatenate([recall, jnp.zeros(1, dtype=recall.dtype)])
        return precision, recall, thresholds

    preds, target = state
    fps, tps, thresh = _binary_clf_curve(preds, target, pos_label=pos_label, drop_ignore_sentinel=True)
    # plain division, NOT _safe_divide: with zero positives the reference's
    # exact regime yields NaN recall (ref :224-225), which downstream macro
    # reductions then exclude with a warning — a deliberate regime difference
    # from the binned path above (ref binned uses _safe_divide). tps+fps >= 1
    # at every observed threshold, so only recall can produce NaN.
    precision = tps / (tps + fps)
    recall = tps / tps[-1]

    # stop when full recall attained and reverse the outputs so recall is non-increasing
    last_ind = jnp.argmax(tps >= tps[-1])
    sl = slice(0, int(last_ind) + 1)
    precision = jnp.concatenate([precision[sl][::-1], jnp.ones(1, dtype=precision.dtype)])
    recall = jnp.concatenate([recall[sl][::-1], jnp.zeros(1, dtype=recall.dtype)])
    thresh = thresh[sl][::-1]
    return precision, recall, thresh


def binary_precision_recall_curve(
    preds: Array,
    target: Array,
    thresholds: Thresholds = None,
    ignore_index: Optional[int] = None,
    validate_args: bool = True,
) -> Tuple[Array, Array, Array]:
    if validate_args:
        _binary_precision_recall_curve_arg_validation(thresholds, ignore_index)
        _binary_precision_recall_curve_tensor_validation(preds, target, ignore_index)
    preds, target, thresholds, mask = _binary_precision_recall_curve_format(preds, target, thresholds, ignore_index)
    if thresholds is None and ignore_index is not None:
        preds, target = _exact_mode_filter(preds, target, thresholds, ignore_index, mask)
        mask = None
    state = _binary_precision_recall_curve_update(preds, target, thresholds, mask)
    return _binary_precision_recall_curve_compute(state, thresholds)


# --------------------------------------------------------------------------- multiclass


def _multiclass_precision_recall_curve_arg_validation(
    num_classes: int,
    thresholds: Thresholds = None,
    ignore_index: Optional[int] = None,
) -> None:
    if not isinstance(num_classes, int) or num_classes < 2:
        raise ValueError(f"Expected argument `num_classes` to be an integer larger than 1, but got {num_classes}")
    _binary_precision_recall_curve_arg_validation(thresholds, ignore_index)


def _multiclass_precision_recall_curve_tensor_validation(
    preds: Array, target: Array, num_classes: int, ignore_index: Optional[int] = None
) -> None:
    if preds.ndim != target.ndim + 1:
        raise ValueError("Expected `preds` to have one more dimension than `target` but got {} and {}".format(preds.ndim, target.ndim))
    if not jnp.issubdtype(preds.dtype, jnp.floating):
        raise ValueError(f"Expected `preds` to be a float tensor, but got {preds.dtype}")
    if jnp.issubdtype(target.dtype, jnp.floating):
        raise ValueError(f"Expected argument `target` to be an int or bool tensor, but got {target.dtype}")
    if preds.shape[1] != num_classes:
        raise ValueError(f"Expected `preds.shape[1]={preds.shape[1]}` to be equal to the number of classes")
    if preds.shape[0] != target.shape[0] or preds.shape[2:] != target.shape[1:]:
        raise ValueError("Expected the shape of `preds` should be (N, C, ...) and the shape of `target` should be (N, ...).")
    if _value_check_possible(target):
        num_unique = int(jnp.max(target, initial=0)) + 1
        check = num_unique > (num_classes if ignore_index is None else num_classes + 1)
        if check:
            raise RuntimeError("Detected more unique values in `target` than `num_classes`.")


def _multiclass_precision_recall_curve_format(
    preds: Array,
    target: Array,
    num_classes: int,
    thresholds: Thresholds = None,
    ignore_index: Optional[int] = None,
) -> Tuple[Array, Array, Optional[Array], Array]:
    preds = jnp.moveaxis(jnp.asarray(preds), 1, -1).reshape(-1, num_classes)
    target = jnp.asarray(target).reshape(-1)
    mask = _ignore_mask(target, ignore_index)
    target = jnp.where(mask, target, 0)
    preds = _softmax_if_logits(preds, axis=-1)
    thresholds = _adjust_threshold_arg(thresholds)
    return preds, target, thresholds, mask


@functools.partial(jax.jit, static_argnums=(2,))
def _multiclass_precision_recall_curve_update(
    preds: Array,
    target: Array,
    num_classes: int,
    thresholds: Optional[Array],
    mask: Optional[Array] = None,
) -> Union[Array, Tuple[Array, Array]]:
    """Binned: (T, C, 2, 2) one-vs-rest state. Backend-split like the binary
    update (value-identical; bucketized on host, comparison-einsum on TPU)."""
    if thresholds is None:
        return preds, target
    len_t = thresholds.shape[0]
    w = mask.astype(jnp.float32) if mask is not None else jnp.ones_like(target, dtype=jnp.float32)
    oh_target = jax.nn.one_hot(target, num_classes, dtype=jnp.float32) * w[:, None]  # (M, C)
    pos = jnp.sum(oh_target, axis=0)  # (C,)
    total = jnp.sum(w)
    if jax.default_backend() == "cpu":
        m = target.shape[0]
        col = jnp.tile(jnp.arange(num_classes, dtype=jnp.int32), (m, 1))
        is_pos = col == target[:, None].astype(jnp.int32)
        valid = jnp.broadcast_to((w > 0)[:, None], (m, num_classes))
        tp, fp = _binned_tp_fp_bucketized(
            preds.reshape(-1), is_pos.reshape(-1), valid.reshape(-1), col.reshape(-1), thresholds, num_classes
        )
        tp, fp = tp.astype(jnp.float32), fp.astype(jnp.float32)
    else:
        preds_t = (preds[None, :, :] >= thresholds[:, None, None]).astype(jnp.float32) * w[None, :, None]  # (T, M, C)
        tp = jnp.einsum("tmc,mc->tc", preds_t, oh_target)
        fp = jnp.einsum("tmc,mc->tc", preds_t, w[:, None] - oh_target)
    fn = pos[None, :] - tp
    tn = (total - pos)[None, :] - fp
    confmat = jnp.stack([jnp.stack([tn, fp], axis=-1), jnp.stack([fn, tp], axis=-1)], axis=-2)
    return confmat.astype(jnp.int32).reshape(len_t, num_classes, 2, 2)


def _multiclass_precision_recall_curve_compute(
    state: Union[Array, Tuple[Array, Array]],
    num_classes: int,
    thresholds: Optional[Array],
) -> Union[Tuple[Array, Array, Array], Tuple[List[Array], List[Array], List[Array]]]:
    if isinstance(state, Array) and thresholds is not None:
        tps = state[:, :, 1, 1]
        fps = state[:, :, 0, 1]
        fns = state[:, :, 1, 0]
        precision = _safe_divide(tps, tps + fps)
        recall = _safe_divide(tps, tps + fns)
        precision = jnp.concatenate([precision, jnp.ones((1, num_classes), dtype=precision.dtype)], axis=0).T
        recall = jnp.concatenate([recall, jnp.zeros((1, num_classes), dtype=recall.dtype)], axis=0).T
        return precision, recall, thresholds

    preds, target = state
    precision_list, recall_list, thresh_list = [], [], []
    for i in range(num_classes):
        res = _binary_precision_recall_curve_compute((preds[:, i], (target == i).astype(jnp.int32)), thresholds=None, pos_label=1)
        precision_list.append(res[0])
        recall_list.append(res[1])
        thresh_list.append(res[2])
    return precision_list, recall_list, thresh_list


def multiclass_precision_recall_curve(
    preds: Array,
    target: Array,
    num_classes: int,
    thresholds: Thresholds = None,
    ignore_index: Optional[int] = None,
    validate_args: bool = True,
):
    if validate_args:
        _multiclass_precision_recall_curve_arg_validation(num_classes, thresholds, ignore_index)
        _multiclass_precision_recall_curve_tensor_validation(preds, target, num_classes, ignore_index)
    preds, target, thresholds, mask = _multiclass_precision_recall_curve_format(
        preds, target, num_classes, thresholds, ignore_index
    )
    if thresholds is None and ignore_index is not None:
        preds, target = _exact_mode_filter(preds, target, thresholds, ignore_index, mask)
        mask = None
    state = _multiclass_precision_recall_curve_update(preds, target, num_classes, thresholds, mask)
    return _multiclass_precision_recall_curve_compute(state, num_classes, thresholds)


# --------------------------------------------------------------------------- multilabel


def _multilabel_precision_recall_curve_arg_validation(
    num_labels: int,
    thresholds: Thresholds = None,
    ignore_index: Optional[int] = None,
) -> None:
    _multiclass_precision_recall_curve_arg_validation(num_labels, thresholds, ignore_index)


def _multilabel_precision_recall_curve_tensor_validation(
    preds: Array, target: Array, num_labels: int, ignore_index: Optional[int] = None
) -> None:
    _check_same_shape(preds, target)
    if preds.shape[1] != num_labels:
        raise ValueError("Expected `preds.shape[1]` to be equal to the number of labels")
    if not jnp.issubdtype(preds.dtype, jnp.floating):
        raise ValueError(f"Expected `preds` to be a float tensor, but got {preds.dtype}")


def _multilabel_precision_recall_curve_format(
    preds: Array,
    target: Array,
    num_labels: int,
    thresholds: Thresholds = None,
    ignore_index: Optional[int] = None,
) -> Tuple[Array, Array, Optional[Array], Array]:
    preds = jnp.moveaxis(jnp.asarray(preds), 1, -1).reshape(-1, num_labels)
    target = jnp.moveaxis(jnp.asarray(target), 1, -1).reshape(-1, num_labels)
    mask = _ignore_mask(target, ignore_index)
    target = jnp.where(mask, target, 0)
    preds = _sigmoid_if_logits(preds)
    thresholds = _adjust_threshold_arg(thresholds)
    return preds, target, thresholds, mask


@functools.partial(jax.jit, static_argnums=(2,))
def _multilabel_precision_recall_curve_update(
    preds: Array,
    target: Array,
    num_labels: int,
    thresholds: Optional[Array],
    mask: Optional[Array] = None,
) -> Union[Array, Tuple[Array, Array, Array]]:
    if thresholds is None:
        return preds, target, (mask if mask is not None else jnp.ones_like(target, dtype=jnp.bool_))
    len_t = thresholds.shape[0]
    w = mask.astype(jnp.float32) if mask is not None else jnp.ones_like(preds)
    t = target.astype(jnp.float32) * w  # (M, C)
    pos = jnp.sum(t, axis=0)
    total = jnp.sum(w, axis=0)
    if jax.default_backend() == "cpu":  # backend-split like the binary update
        m = preds.shape[0]
        col = jnp.tile(jnp.arange(num_labels, dtype=jnp.int32), (m, 1))
        tp, fp = _binned_tp_fp_bucketized(
            preds.reshape(-1),
            target.astype(bool).reshape(-1),
            (w > 0).reshape(-1),
            col.reshape(-1),
            thresholds,
            num_labels,
        )
        tp, fp = tp.astype(jnp.float32), fp.astype(jnp.float32)
    else:
        preds_t = (preds[None, :, :] >= thresholds[:, None, None]).astype(jnp.float32) * w[None, :, :]  # (T, M, C)
        tp = jnp.einsum("tmc,mc->tc", preds_t, t)
        fp = jnp.einsum("tmc,mc->tc", preds_t, w - t)
    fn = pos[None, :] - tp
    tn = (total - pos)[None, :] - fp
    confmat = jnp.stack([jnp.stack([tn, fp], axis=-1), jnp.stack([fn, tp], axis=-1)], axis=-2)
    return confmat.astype(jnp.int32).reshape(len_t, num_labels, 2, 2)


def _multilabel_precision_recall_curve_compute(
    state,
    num_labels: int,
    thresholds: Optional[Array],
    ignore_index: Optional[int] = None,
):
    if isinstance(state, Array) and thresholds is not None:
        return _multiclass_precision_recall_curve_compute(state, num_labels, thresholds)
    preds, target, mask = state
    precision_list, recall_list, thresh_list = [], [], []
    for i in range(num_labels):
        p, t, m = preds[:, i], target[:, i], mask[:, i]
        if _value_check_possible(m):
            p, t = p[m], t[m]
        res = _binary_precision_recall_curve_compute((p, t), thresholds=None, pos_label=1)
        precision_list.append(res[0])
        recall_list.append(res[1])
        thresh_list.append(res[2])
    return precision_list, recall_list, thresh_list


def multilabel_precision_recall_curve(
    preds: Array,
    target: Array,
    num_labels: int,
    thresholds: Thresholds = None,
    ignore_index: Optional[int] = None,
    validate_args: bool = True,
):
    if validate_args:
        _multilabel_precision_recall_curve_arg_validation(num_labels, thresholds, ignore_index)
        _multilabel_precision_recall_curve_tensor_validation(preds, target, num_labels, ignore_index)
    preds, target, thresholds, mask = _multilabel_precision_recall_curve_format(
        preds, target, num_labels, thresholds, ignore_index
    )
    state = _multilabel_precision_recall_curve_update(preds, target, num_labels, thresholds, mask)
    return _multilabel_precision_recall_curve_compute(state, num_labels, thresholds, ignore_index)


def precision_recall_curve(
    preds: Array,
    target: Array,
    task: str,
    thresholds: Thresholds = None,
    num_classes: Optional[int] = None,
    num_labels: Optional[int] = None,
    ignore_index: Optional[int] = None,
    validate_args: bool = True,
):
    """Task-dispatch façade over binary/multiclass/multilabel precision-recall curves (reference functional/classification/precision_recall_curve.py).

    Example:
        >>> import jax.numpy as jnp
        >>> from metrics_tpu.functional import precision_recall_curve
        >>> preds = jnp.array([0.1, 0.6, 0.8, 0.4])
        >>> target = jnp.array([0, 1, 1, 0])
        >>> precision, recall, thresholds = precision_recall_curve(preds, target, task="binary", thresholds=4)
        >>> precision
        Array([0.5      , 0.6666667, 1.       , 0.       , 1.       ], dtype=float32)
    """
    task = str(task).lower()
    if task == "binary":
        return binary_precision_recall_curve(preds, target, thresholds, ignore_index, validate_args)
    if task == "multiclass":
        assert isinstance(num_classes, int)
        return multiclass_precision_recall_curve(preds, target, num_classes, thresholds, ignore_index, validate_args)
    if task == "multilabel":
        assert isinstance(num_labels, int)
        return multilabel_precision_recall_curve(preds, target, num_labels, thresholds, ignore_index, validate_args)
    raise ValueError(f"Expected argument `task` to either be 'binary', 'multiclass' or 'multilabel' but got {task}")
