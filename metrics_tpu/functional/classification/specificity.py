"""Specificity functionals.

Reference parity: src/torchmetrics/functional/classification/specificity.py.
"""

from __future__ import annotations

from typing import Optional

import jax.numpy as jnp
from jax import Array

from metrics_tpu.functional.classification._pipeline import binary_pipeline, multiclass_pipeline, multilabel_pipeline
from metrics_tpu.utils.compute import _adjust_weights_safe_divide, _safe_divide


def _specificity_reduce(
    tp: Array,
    fp: Array,
    tn: Array,
    fn: Array,
    average: Optional[str],
    multidim_average: str = "global",
    multilabel: bool = False,
) -> Array:
    if average == "binary":
        return _safe_divide(tn, tn + fp)
    if average == "micro":
        axis = 0 if multidim_average == "global" else 1
        tn = jnp.sum(tn, axis=axis)
        fp = jnp.sum(fp, axis=axis)
        return _safe_divide(tn, tn + fp)
    specificity_score = _safe_divide(tn, tn + fp)
    return _adjust_weights_safe_divide(specificity_score, average, tp, fn)


def binary_specificity(preds, target, threshold=0.5, multidim_average="global", ignore_index=None, validate_args=True) -> Array:
    tp, fp, tn, fn = binary_pipeline(preds, target, threshold, multidim_average, ignore_index, validate_args)
    return _specificity_reduce(tp, fp, tn, fn, average="binary", multidim_average=multidim_average)


def multiclass_specificity(preds, target, num_classes, average="macro", top_k=1, multidim_average="global", ignore_index=None, validate_args=True) -> Array:
    tp, fp, tn, fn = multiclass_pipeline(preds, target, num_classes, average, top_k, multidim_average, ignore_index, validate_args)
    return _specificity_reduce(tp, fp, tn, fn, average=average, multidim_average=multidim_average)


def multilabel_specificity(preds, target, num_labels, threshold=0.5, average="macro", multidim_average="global", ignore_index=None, validate_args=True) -> Array:
    tp, fp, tn, fn = multilabel_pipeline(preds, target, num_labels, threshold, average, multidim_average, ignore_index, validate_args)
    return _specificity_reduce(tp, fp, tn, fn, average=average, multidim_average=multidim_average, multilabel=True)


def specificity(
    preds, target, task, threshold=0.5, num_classes=None, num_labels=None, average="micro",
    multidim_average="global", top_k=1, ignore_index=None, validate_args=True,
) -> Array:
    """Task-dispatch façade over binary/multiclass/multilabel specificity (reference functional/classification/specificity.py).

    Example:
        >>> import jax.numpy as jnp
        >>> from metrics_tpu.functional import specificity
        >>> specificity(jnp.array([0, 2, 1, 2]), jnp.array([0, 1, 1, 2]), task="multiclass", num_classes=3)
        Array(0.875, dtype=float32)
    """
    task = str(task).lower()
    if task == "binary":
        return binary_specificity(preds, target, threshold, multidim_average, ignore_index, validate_args)
    if task == "multiclass":
        return multiclass_specificity(preds, target, num_classes, average, top_k, multidim_average, ignore_index, validate_args)
    if task == "multilabel":
        return multilabel_specificity(preds, target, num_labels, threshold, average, multidim_average, ignore_index, validate_args)
    raise ValueError(f"Expected argument `task` to either be 'binary', 'multiclass' or 'multilabel' but got {task}")
