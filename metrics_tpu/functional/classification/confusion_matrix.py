"""Confusion-matrix functionals.

Reference parity: src/torchmetrics/functional/classification/confusion_matrix.py
(binary/multiclass/multilabel + ``_confusion_matrix_reduce`` normalisation).

TPU notes: the multiclass count is the kernel plane's registry-dispatched pair
count (``metrics_tpu/kernels/confmat.py`` — registry contract and dispatch
rules in docs/source/kernels.md). Value-identical lowerings, chosen at trace
time: on accelerators the MXU one-hot matmul — ``one_hot(target).T @
one_hot(preds)`` in bf16 with f32 accumulation (0/1 products are exact in bf16
and the f32 sums are exact for any per-call N < 2**24) — measured 33x faster
than the scatter on a v5e (0.23 ms vs 7.7 ms at 1M samples x 100 classes, 44%
of MXU bf16 peak; see benchmarks/experiments/onehot_confmat_tpu.py); on TPU,
where selected, the Pallas fused streaming kernel that builds the one-hot
tiles on-chip instead of materializing the (N, C) operands in HBM (the
``stat_scores update`` roofline row). On the host backend (and for N >= 2**24
per call) it is ``jnp.bincount(target*C + preds, length=C*C)`` (static-shape
scatter-add; deterministic on XLA — the reference needed a fallback loop for
this, data.py:206-228), where the CPU's serial scatter beats materializing
(N, C) one-hots. ``ignore_index`` routes ignored pairs to an overflow bucket
(scatter) or zeroes the target row (one-hot paths) instead of boolean
filtering.
"""

from __future__ import annotations

import functools
from typing import Optional

import jax
import jax.numpy as jnp
from jax import Array

from metrics_tpu.functional.classification.stat_scores import (
    _binary_stat_scores_format,
    _binary_stat_scores_tensor_validation,
    _ignore_mask,
    _multiclass_stat_scores_format,
    _multiclass_stat_scores_tensor_validation,
    _multilabel_stat_scores_format,
    _multilabel_stat_scores_tensor_validation,
)
from metrics_tpu.utils.compute import _safe_divide


def _confusion_matrix_reduce(confmat: Array, normalize: Optional[str] = None) -> Array:
    """Normalise over true/pred/all (reference confusion_matrix.py:~40)."""
    allowed_normalize = ("true", "pred", "all", "none", None)
    if normalize not in allowed_normalize:
        raise ValueError(f"Argument `normalize` needs to one of the following: {allowed_normalize}")
    if normalize is not None and normalize != "none":
        confmat = confmat.astype(jnp.float32)
        if normalize == "true":
            confmat = _safe_divide(confmat, jnp.sum(confmat, axis=-1, keepdims=True))
        elif normalize == "pred":
            confmat = _safe_divide(confmat, jnp.sum(confmat, axis=-2, keepdims=True))
        elif normalize == "all":
            confmat = _safe_divide(confmat, jnp.sum(confmat, axis=(-2, -1), keepdims=True))
    return confmat


def _binary_confusion_matrix_arg_validation(
    threshold: float = 0.5, ignore_index: Optional[int] = None, normalize: Optional[str] = None
) -> None:
    if not (isinstance(threshold, float) and (0 <= threshold <= 1)):
        raise ValueError(f"Expected argument `threshold` to be a float in the [0,1] range, but got {threshold}.")
    if ignore_index is not None and not isinstance(ignore_index, int):
        raise ValueError(f"Expected argument `ignore_index` to either be `None` or an integer, but got {ignore_index}")
    allowed_normalize = ("true", "pred", "all", "none", None)
    if normalize not in allowed_normalize:
        raise ValueError(f"Expected argument `normalize` to be one of {allowed_normalize}, but got {normalize}")


@jax.jit
def _binary_confusion_matrix_update(preds: Array, target: Array, mask: Array) -> Array:
    """[[tn, fp], [fn, tp]] — 2×2 counts via the masked products. Jitted at
    definition (see ``_multiclass_stat_scores_update`` in stat_scores.py)."""
    m = mask.astype(jnp.int32)
    tp = jnp.sum(preds * target * m)
    fp = jnp.sum(preds * (1 - target) * m)
    fn = jnp.sum((1 - preds) * target * m)
    tn = jnp.sum((1 - preds) * (1 - target) * m)
    return jnp.stack([jnp.stack([tn, fp]), jnp.stack([fn, tp])])


def binary_confusion_matrix(
    preds: Array,
    target: Array,
    threshold: float = 0.5,
    ignore_index: Optional[int] = None,
    normalize: Optional[str] = None,
    validate_args: bool = True,
) -> Array:
    if validate_args:
        _binary_confusion_matrix_arg_validation(threshold, ignore_index, normalize)
        _binary_stat_scores_tensor_validation(preds, target, "global", ignore_index)
    preds, target, mask = _binary_stat_scores_format(preds, target, threshold, ignore_index)
    confmat = _binary_confusion_matrix_update(preds, target, mask)
    return _confusion_matrix_reduce(confmat, normalize)


# Back-compat shims: the pair-count lowerings moved to the kernel plane
# (metrics_tpu/kernels/confmat.py — registry entry #0 is the MXU matmul, with
# the Pallas fused streaming kernel layered above it); nominal/utils.py and the
# tests import these names from here.
from metrics_tpu.kernels.confmat import (  # noqa: E402
    matmul_eligible as _matmul_lowering_eligible,
    pair_count as _pair_count,
    pair_count_matmul as _onehot_count_matmul,
)


def _multiclass_confusion_matrix_matmul(p: Array, t: Array, mask: Array, num_classes: int) -> Array:
    """(C, C) counts, rows = true class, via the plane's one-hot matmul
    (kernels/confmat.py entry #0) — kept for the lowering-parity tests."""
    return _onehot_count_matmul(t, p, num_classes, num_classes, row_mask=mask)


@functools.partial(jax.jit, static_argnums=(2, 3))
def _multiclass_confusion_matrix_update(
    preds: Array, target: Array, num_classes: int, ignore_index: Optional[int] = None
) -> Array:
    """(C, C) counts, rows = true class (reference confusion_matrix.py multiclass
    update). Jitted at definition: fusing key construction + masking + the count
    beats the reference's eager C++ bincount (~2x on CPU, 33x on the v5e via the
    matmul lowering). The count itself is the kernel plane's registry-dispatched
    pair count (metrics_tpu/kernels/confmat.py): Pallas fused streaming kernel
    where selected, MXU one-hot matmul on accelerators, bincount scatter on the
    host backend. Every lowering is integer-exact with identical semantics —
    out-of-range class indices (only reachable with validate_args=False,
    undefined in the reference) are DROPPED by all of them, so the trace-time
    selection affects speed only."""
    mask = _ignore_mask(target, ignore_index)
    t = jnp.where(mask, target, 0).astype(jnp.int32)
    p = preds.astype(jnp.int32)
    return _pair_count(t.reshape(-1), p.reshape(-1), num_classes, num_classes,
                       row_mask=mask.reshape(-1))


def multiclass_confusion_matrix(
    preds: Array,
    target: Array,
    num_classes: int,
    ignore_index: Optional[int] = None,
    normalize: Optional[str] = None,
    validate_args: bool = True,
) -> Array:
    if validate_args:
        _multiclass_stat_scores_tensor_validation(preds, target, num_classes, "global", ignore_index)
    preds, target = _multiclass_stat_scores_format(preds, target, top_k=1)
    confmat = _multiclass_confusion_matrix_update(preds, target, num_classes, ignore_index)
    return _confusion_matrix_reduce(confmat, normalize)


@functools.partial(jax.jit, static_argnums=(3,))
def _multilabel_confusion_matrix_update(preds: Array, target: Array, mask: Array, num_labels: int) -> Array:
    """(C, 2, 2) per-label counts. Jitted at definition (see stat_scores.py)."""
    m = mask.astype(jnp.int32)
    sum_axes = (0, 2)
    tp = jnp.sum(preds * target * m, axis=sum_axes)
    fp = jnp.sum(preds * (1 - target) * m, axis=sum_axes)
    fn = jnp.sum((1 - preds) * target * m, axis=sum_axes)
    tn = jnp.sum((1 - preds) * (1 - target) * m, axis=sum_axes)
    return jnp.stack([tn, fp, fn, tp], axis=-1).reshape(num_labels, 2, 2)


def multilabel_confusion_matrix(
    preds: Array,
    target: Array,
    num_labels: int,
    threshold: float = 0.5,
    ignore_index: Optional[int] = None,
    normalize: Optional[str] = None,
    validate_args: bool = True,
) -> Array:
    if validate_args:
        _multilabel_stat_scores_tensor_validation(preds, target, num_labels, "global", ignore_index)
    preds, target, mask = _multilabel_stat_scores_format(preds, target, num_labels, threshold, ignore_index)
    confmat = _multilabel_confusion_matrix_update(preds, target, mask, num_labels)
    return _confusion_matrix_reduce(confmat, normalize)


def confusion_matrix(
    preds: Array,
    target: Array,
    task: str,
    threshold: float = 0.5,
    num_classes: Optional[int] = None,
    num_labels: Optional[int] = None,
    normalize: Optional[str] = None,
    ignore_index: Optional[int] = None,
    validate_args: bool = True,
) -> Array:
    """Task-dispatch façade over binary/multiclass/multilabel confusion matrices (reference functional/classification/confusion_matrix.py).

    Example:
        >>> import jax.numpy as jnp
        >>> from metrics_tpu.functional import confusion_matrix
        >>> confusion_matrix(jnp.array([0, 2, 1, 2]), jnp.array([0, 1, 1, 2]), task="multiclass", num_classes=3)
        Array([[1, 0, 0],
               [0, 1, 1],
               [0, 0, 1]], dtype=int32)
    """
    task = str(task).lower()
    if task == "binary":
        return binary_confusion_matrix(preds, target, threshold, ignore_index, normalize, validate_args)
    if task == "multiclass":
        assert isinstance(num_classes, int)
        return multiclass_confusion_matrix(preds, target, num_classes, ignore_index, normalize, validate_args)
    if task == "multilabel":
        assert isinstance(num_labels, int)
        return multilabel_confusion_matrix(preds, target, num_labels, threshold, ignore_index, normalize, validate_args)
    raise ValueError(f"Expected argument `task` to either be 'binary', 'multiclass' or 'multilabel' but got {task}")
