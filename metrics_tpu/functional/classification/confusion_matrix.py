"""Confusion-matrix functionals.

Reference parity: src/torchmetrics/functional/classification/confusion_matrix.py
(binary/multiclass/multilabel + ``_confusion_matrix_reduce`` normalisation).

TPU notes: the multiclass count has two value-identical lowerings chosen at
trace time per backend. On accelerators it is an MXU one-hot matmul —
``one_hot(target).T @ one_hot(preds)`` in bf16 with f32 accumulation (0/1
products are exact in bf16 and the f32 sums are exact for any per-call
N < 2**24) — measured 33x faster than the scatter on a v5e (0.23 ms vs 7.7 ms
at 1M samples x 100 classes, 44% of MXU bf16 peak; see
benchmarks/experiments/onehot_confmat_tpu.py). On the host backend (and for
N >= 2**24 per call) it is ``jnp.bincount(target*C + preds, length=C*C)``
(static-shape scatter-add; deterministic on XLA — the reference needed a
fallback loop for this, data.py:206-228), where the CPU's serial scatter beats
materializing (N, C) one-hots. ``ignore_index`` routes ignored pairs to an
overflow bucket (scatter) or zeroes the target row (matmul) instead of boolean
filtering.
"""

from __future__ import annotations

import functools
from typing import Optional

import jax
import jax.numpy as jnp
from jax import Array

from metrics_tpu.functional.classification.stat_scores import (
    _binary_stat_scores_format,
    _binary_stat_scores_tensor_validation,
    _ignore_mask,
    _multiclass_stat_scores_format,
    _multiclass_stat_scores_tensor_validation,
    _multilabel_stat_scores_format,
    _multilabel_stat_scores_tensor_validation,
)
from metrics_tpu.utils.compute import _safe_divide


def _confusion_matrix_reduce(confmat: Array, normalize: Optional[str] = None) -> Array:
    """Normalise over true/pred/all (reference confusion_matrix.py:~40)."""
    allowed_normalize = ("true", "pred", "all", "none", None)
    if normalize not in allowed_normalize:
        raise ValueError(f"Argument `normalize` needs to one of the following: {allowed_normalize}")
    if normalize is not None and normalize != "none":
        confmat = confmat.astype(jnp.float32)
        if normalize == "true":
            confmat = _safe_divide(confmat, jnp.sum(confmat, axis=-1, keepdims=True))
        elif normalize == "pred":
            confmat = _safe_divide(confmat, jnp.sum(confmat, axis=-2, keepdims=True))
        elif normalize == "all":
            confmat = _safe_divide(confmat, jnp.sum(confmat, axis=(-2, -1), keepdims=True))
    return confmat


def _binary_confusion_matrix_arg_validation(
    threshold: float = 0.5, ignore_index: Optional[int] = None, normalize: Optional[str] = None
) -> None:
    if not (isinstance(threshold, float) and (0 <= threshold <= 1)):
        raise ValueError(f"Expected argument `threshold` to be a float in the [0,1] range, but got {threshold}.")
    if ignore_index is not None and not isinstance(ignore_index, int):
        raise ValueError(f"Expected argument `ignore_index` to either be `None` or an integer, but got {ignore_index}")
    allowed_normalize = ("true", "pred", "all", "none", None)
    if normalize not in allowed_normalize:
        raise ValueError(f"Expected argument `normalize` to be one of {allowed_normalize}, but got {normalize}")


@jax.jit
def _binary_confusion_matrix_update(preds: Array, target: Array, mask: Array) -> Array:
    """[[tn, fp], [fn, tp]] — 2×2 counts via the masked products. Jitted at
    definition (see ``_multiclass_stat_scores_update`` in stat_scores.py)."""
    m = mask.astype(jnp.int32)
    tp = jnp.sum(preds * target * m)
    fp = jnp.sum(preds * (1 - target) * m)
    fn = jnp.sum((1 - preds) * target * m)
    tn = jnp.sum((1 - preds) * (1 - target) * m)
    return jnp.stack([jnp.stack([tn, fp]), jnp.stack([fn, tp])])


def binary_confusion_matrix(
    preds: Array,
    target: Array,
    threshold: float = 0.5,
    ignore_index: Optional[int] = None,
    normalize: Optional[str] = None,
    validate_args: bool = True,
) -> Array:
    if validate_args:
        _binary_confusion_matrix_arg_validation(threshold, ignore_index, normalize)
        _binary_stat_scores_tensor_validation(preds, target, "global", ignore_index)
    preds, target, mask = _binary_stat_scores_format(preds, target, threshold, ignore_index)
    confmat = _binary_confusion_matrix_update(preds, target, mask)
    return _confusion_matrix_reduce(confmat, normalize)


def _matmul_lowering_eligible(size: int, num_classes: int) -> bool:
    """Single source of truth for the accelerator matmul-lowering guard (also
    imported by stat_scores.py, which routes through the cm on eligibility).
    2**24: f32-accumulation exactness bound. 2**29: cap the (N, C) bf16
    one-hot operands at ~2 GiB — beyond that the O(N) scatter is the safer
    lowering even though it is slower per element (OOM beats slow)."""
    return size < 2**24 and size * num_classes <= 2**29


def _onehot_count_matmul(row_idx: Array, col_idx: Array, num_rows: int, num_cols: int,
                         row_mask: Optional[Array] = None) -> Array:
    """(num_rows, num_cols) pair counts as a bf16 one-hot MXU matmul — the ONE
    implementation of the lowering (exactness argument in the module
    docstring), shared by the classification confusion matrix and the nominal
    contingency table. Masked samples contribute an all-zero row one-hot;
    out-of-range indices yield all-zero one-hots, i.e. the pair is dropped."""
    oh_r = jax.nn.one_hot(row_idx, num_rows, dtype=jnp.bfloat16)
    if row_mask is not None:
        oh_r = oh_r * row_mask.astype(jnp.bfloat16)[:, None]
    oh_c = jax.nn.one_hot(col_idx, num_cols, dtype=jnp.bfloat16)
    counts = jax.lax.dot_general(oh_r, oh_c, (((0,), (0,)), ((), ())),
                                 preferred_element_type=jnp.float32)
    return counts.astype(jnp.int32)


def _multiclass_confusion_matrix_matmul(p: Array, t: Array, mask: Array, num_classes: int) -> Array:
    """(C, C) counts, rows = true class, via the shared one-hot matmul."""
    return _onehot_count_matmul(t, p, num_classes, num_classes, row_mask=mask)


@functools.partial(jax.jit, static_argnums=(2, 3))
def _multiclass_confusion_matrix_update(
    preds: Array, target: Array, num_classes: int, ignore_index: Optional[int] = None
) -> Array:
    """(C, C) counts, rows = true class (reference confusion_matrix.py multiclass
    update). Jitted at definition: fusing key construction + masking + the count
    beats the reference's eager C++ bincount (~2x on CPU, 33x on the v5e via the
    matmul lowering). The backend branch is trace-time and both lowerings are
    integer-exact with identical semantics — out-of-range class indices (only
    reachable with validate_args=False, undefined in the reference) are DROPPED
    by both, so a device/trace mismatch affects speed only."""
    mask = _ignore_mask(target, ignore_index)
    t = jnp.where(mask, target, 0).astype(jnp.int32)
    p = preds.astype(jnp.int32)
    if jax.default_backend() != "cpu" and _matmul_lowering_eligible(p.size, num_classes):
        return _multiclass_confusion_matrix_matmul(p.reshape(-1), t.reshape(-1),
                                                   mask.reshape(-1), num_classes)
    # ignored and out-of-range pairs go to an overflow bucket (index C*C) that
    # is trimmed after counting (the one-hot path drops them as zero rows)
    in_range = (p >= 0) & (p < num_classes) & (t >= 0) & (t < num_classes)
    unique_mapping = jnp.where((mask & in_range).reshape(-1),
                               (t * num_classes + p).reshape(-1), num_classes * num_classes)
    bins = jnp.bincount(unique_mapping, length=num_classes * num_classes + 1)[: num_classes * num_classes]
    return bins.reshape(num_classes, num_classes)


def multiclass_confusion_matrix(
    preds: Array,
    target: Array,
    num_classes: int,
    ignore_index: Optional[int] = None,
    normalize: Optional[str] = None,
    validate_args: bool = True,
) -> Array:
    if validate_args:
        _multiclass_stat_scores_tensor_validation(preds, target, num_classes, "global", ignore_index)
    preds, target = _multiclass_stat_scores_format(preds, target, top_k=1)
    confmat = _multiclass_confusion_matrix_update(preds, target, num_classes, ignore_index)
    return _confusion_matrix_reduce(confmat, normalize)


@functools.partial(jax.jit, static_argnums=(3,))
def _multilabel_confusion_matrix_update(preds: Array, target: Array, mask: Array, num_labels: int) -> Array:
    """(C, 2, 2) per-label counts. Jitted at definition (see stat_scores.py)."""
    m = mask.astype(jnp.int32)
    sum_axes = (0, 2)
    tp = jnp.sum(preds * target * m, axis=sum_axes)
    fp = jnp.sum(preds * (1 - target) * m, axis=sum_axes)
    fn = jnp.sum((1 - preds) * target * m, axis=sum_axes)
    tn = jnp.sum((1 - preds) * (1 - target) * m, axis=sum_axes)
    return jnp.stack([tn, fp, fn, tp], axis=-1).reshape(num_labels, 2, 2)


def multilabel_confusion_matrix(
    preds: Array,
    target: Array,
    num_labels: int,
    threshold: float = 0.5,
    ignore_index: Optional[int] = None,
    normalize: Optional[str] = None,
    validate_args: bool = True,
) -> Array:
    if validate_args:
        _multilabel_stat_scores_tensor_validation(preds, target, num_labels, "global", ignore_index)
    preds, target, mask = _multilabel_stat_scores_format(preds, target, num_labels, threshold, ignore_index)
    confmat = _multilabel_confusion_matrix_update(preds, target, mask, num_labels)
    return _confusion_matrix_reduce(confmat, normalize)


def confusion_matrix(
    preds: Array,
    target: Array,
    task: str,
    threshold: float = 0.5,
    num_classes: Optional[int] = None,
    num_labels: Optional[int] = None,
    normalize: Optional[str] = None,
    ignore_index: Optional[int] = None,
    validate_args: bool = True,
) -> Array:
    """Task-dispatch façade over binary/multiclass/multilabel confusion matrices (reference functional/classification/confusion_matrix.py).

    Example:
        >>> import jax.numpy as jnp
        >>> from metrics_tpu.functional import confusion_matrix
        >>> confusion_matrix(jnp.array([0, 2, 1, 2]), jnp.array([0, 1, 1, 2]), task="multiclass", num_classes=3)
        Array([[1, 0, 0],
               [0, 1, 1],
               [0, 0, 1]], dtype=int32)
    """
    task = str(task).lower()
    if task == "binary":
        return binary_confusion_matrix(preds, target, threshold, ignore_index, normalize, validate_args)
    if task == "multiclass":
        assert isinstance(num_classes, int)
        return multiclass_confusion_matrix(preds, target, num_classes, ignore_index, normalize, validate_args)
    if task == "multilabel":
        assert isinstance(num_labels, int)
        return multilabel_confusion_matrix(preds, target, num_labels, threshold, ignore_index, normalize, validate_args)
    raise ValueError(f"Expected argument `task` to either be 'binary', 'multiclass' or 'multilabel' but got {task}")
