"""Shared stat-scores pipeline runner used by the derived classification metrics.

The reference repeats the validate→format→update sequence in every metric file
(e.g. functional/classification/precision_recall.py); here it is factored once.
"""

from __future__ import annotations

from typing import Optional, Tuple

from jax import Array

from metrics_tpu.functional.classification.stat_scores import (
    _binary_stat_scores_arg_validation,
    _binary_stat_scores_format,
    _binary_stat_scores_tensor_validation,
    _binary_stat_scores_update,
    _multiclass_stat_scores_arg_validation,
    _multiclass_stat_scores_format,
    _multiclass_stat_scores_tensor_validation,
    _multiclass_stat_scores_update,
    _multilabel_stat_scores_arg_validation,
    _multilabel_stat_scores_format,
    _multilabel_stat_scores_tensor_validation,
    _multilabel_stat_scores_update,
)

StatScores = Tuple[Array, Array, Array, Array]


def binary_pipeline(
    preds: Array,
    target: Array,
    threshold: float = 0.5,
    multidim_average: str = "global",
    ignore_index: Optional[int] = None,
    validate_args: bool = True,
) -> StatScores:
    if validate_args:
        _binary_stat_scores_arg_validation(threshold, multidim_average, ignore_index)
        _binary_stat_scores_tensor_validation(preds, target, multidim_average, ignore_index)
    preds, target, mask = _binary_stat_scores_format(preds, target, threshold, ignore_index)
    return _binary_stat_scores_update(preds, target, mask, multidim_average)


def multiclass_pipeline(
    preds: Array,
    target: Array,
    num_classes: int,
    average: Optional[str] = "macro",
    top_k: int = 1,
    multidim_average: str = "global",
    ignore_index: Optional[int] = None,
    validate_args: bool = True,
) -> StatScores:
    if validate_args:
        _multiclass_stat_scores_arg_validation(num_classes, top_k, average, multidim_average, ignore_index)
        _multiclass_stat_scores_tensor_validation(preds, target, num_classes, multidim_average, ignore_index)
    preds, target = _multiclass_stat_scores_format(preds, target, top_k)
    return _multiclass_stat_scores_update(preds, target, num_classes, top_k, average, multidim_average, ignore_index)


def multilabel_pipeline(
    preds: Array,
    target: Array,
    num_labels: int,
    threshold: float = 0.5,
    average: Optional[str] = "macro",
    multidim_average: str = "global",
    ignore_index: Optional[int] = None,
    validate_args: bool = True,
) -> StatScores:
    if validate_args:
        _multilabel_stat_scores_arg_validation(num_labels, threshold, average, multidim_average, ignore_index)
        _multilabel_stat_scores_tensor_validation(preds, target, num_labels, multidim_average, ignore_index)
    preds, target, mask = _multilabel_stat_scores_format(preds, target, num_labels, threshold, ignore_index)
    return _multilabel_stat_scores_update(preds, target, mask, multidim_average)
