"""Matthews correlation coefficient functionals.

Reference parity: src/torchmetrics/functional/classification/matthews_corrcoef.py
(``_matthews_corrcoef_reduce`` — generalised R_k statistic over the confusion matrix).
"""

from __future__ import annotations

from typing import Optional

import jax.numpy as jnp
from jax import Array

from metrics_tpu.functional.classification.confusion_matrix import (
    binary_confusion_matrix,
    multiclass_confusion_matrix,
    multilabel_confusion_matrix,
)


def _matthews_corrcoef_reduce(confmat: Array) -> Array:
    """Reference matthews_corrcoef.py ``_matthews_corrcoef_reduce``."""
    # convert multilabel into binary by summing the per-label 2x2 matrices
    if confmat.ndim == 3:  # multilabel
        confmat = jnp.sum(confmat, axis=0)

    if confmat.shape == (2, 2):
        tn = confmat[0, 0].astype(jnp.float32)
        fp = confmat[0, 1].astype(jnp.float32)
        fn = confmat[1, 0].astype(jnp.float32)
        tp = confmat[1, 1].astype(jnp.float32)
        numerator = tp * tn - fp * fn
        denom = jnp.sqrt((tp + fp) * (tp + fn) * (tn + fp) * (tn + fn))
        return jnp.where(denom == 0, 0.0, numerator / jnp.where(denom == 0, 1.0, denom))

    confmat = confmat.astype(jnp.float32)
    tk = jnp.sum(confmat, axis=-1)  # number of true occurrences per class
    pk = jnp.sum(confmat, axis=-2)  # number of predicted occurrences per class
    c = jnp.trace(confmat)
    s = jnp.sum(confmat)

    cov_ytyp = c * s - jnp.sum(tk * pk)
    cov_ypyp = s**2 - jnp.sum(pk * pk)
    cov_ytyt = s**2 - jnp.sum(tk * tk)

    denom = cov_ypyp * cov_ytyt
    return jnp.where(denom == 0, 0.0, cov_ytyp / jnp.sqrt(jnp.where(denom == 0, 1.0, denom)))


def binary_matthews_corrcoef(preds, target, threshold=0.5, ignore_index=None, validate_args=True) -> Array:
    confmat = binary_confusion_matrix(preds, target, threshold, ignore_index, normalize=None, validate_args=validate_args)
    return _matthews_corrcoef_reduce(confmat)


def multiclass_matthews_corrcoef(preds, target, num_classes, ignore_index=None, validate_args=True) -> Array:
    confmat = multiclass_confusion_matrix(preds, target, num_classes, ignore_index, normalize=None, validate_args=validate_args)
    return _matthews_corrcoef_reduce(confmat)


def multilabel_matthews_corrcoef(preds, target, num_labels, threshold=0.5, ignore_index=None, validate_args=True) -> Array:
    confmat = multilabel_confusion_matrix(preds, target, num_labels, threshold, ignore_index, normalize=None, validate_args=validate_args)
    return _matthews_corrcoef_reduce(confmat)


def matthews_corrcoef(
    preds, target, task, threshold=0.5, num_classes=None, num_labels=None, ignore_index=None, validate_args=True,
) -> Array:
    """Task-dispatch façade over binary/multiclass/multilabel Matthews correlation (reference functional/classification/matthews_corrcoef.py).

    Example:
        >>> import jax.numpy as jnp
        >>> from metrics_tpu.functional import matthews_corrcoef
        >>> matthews_corrcoef(jnp.array([0, 2, 1, 2]), jnp.array([0, 1, 1, 2]), task="multiclass", num_classes=3)
        Array(0.7, dtype=float32)
    """
    task = str(task).lower()
    if task == "binary":
        return binary_matthews_corrcoef(preds, target, threshold, ignore_index, validate_args)
    if task == "multiclass":
        assert isinstance(num_classes, int)
        return multiclass_matthews_corrcoef(preds, target, num_classes, ignore_index, validate_args)
    if task == "multilabel":
        assert isinstance(num_labels, int)
        return multilabel_matthews_corrcoef(preds, target, num_labels, threshold, ignore_index, validate_args)
    raise ValueError(f"Expected argument `task` to either be 'binary', 'multiclass' or 'multilabel' but got {task}")
