"""Pairwise similarity / distance matrices.

Reference parity: src/torchmetrics/functional/pairwise/{cosine,euclidean,manhattan,
linear}.py + helpers.py (``_check_input``, zero-diagonal, reduction).

TPU notes: all four are (N,D)×(M,D) matmul-shaped — they ride the MXU directly; the
euclidean form uses the ‖x‖²+‖y‖²−2x·y expansion (one matmul) rather than broadcast
subtraction (O(N·M·D) memory).
"""

from __future__ import annotations

from typing import Optional, Tuple

import jax.numpy as jnp
import numpy as np
from jax import Array

from metrics_tpu.utils.compute import _is_eager_cpu, _safe_matmul


def _host_pairwise(kind: str, x: Array, y: Array, zero_diagonal: bool, reduction: Optional[str]) -> Array:
    """Eager-CPU path: the (N,D)x(M,D) GEMM through the host BLAS.

    XLA's CPU gemm measures ~1.5x slower than the multithreaded BLAS numpy
    links (2000x256 cosine: 20 ms jitted vs 13 ms here); under jit or on an
    accelerator the jnp forms below run instead (MXU on TPU).
    """
    _validate_reduction(reduction)  # before the O(N·M·D) GEMM, shared message
    same = y is x  # identity must be checked on the jax arrays — np.asarray
    # returns a distinct view object each call, so `yh is xh` is always False
    xh, yh = np.asarray(x), np.asarray(y)
    if kind == "cosine":
        # plain division (reference cosine.py:36-39): zero rows go NaN; the
        # errstate guard mirrors torch's warning-free 0/0
        with np.errstate(divide="ignore", invalid="ignore"):
            xn = xh / np.linalg.norm(xh, axis=1, keepdims=True)
            yn = xn if same else yh / np.linalg.norm(yh, axis=1, keepdims=True)
        mat = xn @ yn.T
    elif kind == "euclidean":
        # f64 expansion like the reference (euclidean.py:34-40 "upcast to
        # float64 to prevent precision issues"), squared distances cast back
        # to the input dtype before the sqrt — near-duplicate rows would
        # otherwise read ~1e-3 instead of ~1e-8 from f32 cancellation.
        # Deliberate deviation: squared distances that round to a tiny
        # NEGATIVE after the cast-back are clamped to 0 where the reference
        # takes sqrt(negative) -> NaN — an epsilon-level rounding artifact
        # should read as zero distance, not poison downstream reductions
        x64 = xh.astype(np.float64)
        y64 = x64 if same else yh.astype(np.float64)
        x_norm = np.sum(x64 * x64, axis=1, keepdims=True)
        y_norm = x_norm.ravel() if same else np.sum(y64 * y64, axis=1)
        sq = (x_norm + y_norm[None, :] - 2.0 * (x64 @ y64.T)).astype(xh.dtype)
        mat = np.sqrt(np.maximum(sq, 0.0))
    else:  # linear
        mat = xh @ yh.T
    if zero_diagonal:
        np.fill_diagonal(mat, 0.0)
    # reduce in numpy: handing the full matrix to the jnp reducer would copy
    # it into a jax buffer first (16 MB at 2000x2000) just to shrink it
    if reduction == "mean":
        mat = mat.mean(axis=-1)
    elif reduction == "sum":
        mat = mat.sum(axis=-1)
    # zero-copy import: `mat` is function-local and never mutated after this
    # point, so aliasing its buffer is safe — jnp.asarray would copy ~16 MB
    # (measured 5 ms at 2000x2000, a third of the whole GEMM's cost)
    try:
        return jnp.from_dlpack(np.ascontiguousarray(mat))
    except Exception:  # pragma: no cover — dlpack unavailable on some dtypes
        return jnp.asarray(mat)


def _check_input(x: Array, y: Optional[Array] = None, zero_diagonal: Optional[bool] = None) -> Tuple[Array, Array, bool]:
    """Reference pairwise/helpers.py ``_check_input``."""
    if x.ndim != 2:
        raise ValueError(f"Expected argument `x` to be a 2D tensor of shape `[N, d]` but got {x.shape}")
    if y is not None:
        if y.ndim != 2 or y.shape[1] != x.shape[1]:
            raise ValueError(
                "Expected argument `y` to be a 2D tensor of shape `[M, d]` where"
                " `d` should be same as the last dimension of `x`"
            )
        zero_diagonal = False if zero_diagonal is None else zero_diagonal
        return x.astype(jnp.float32), y.astype(jnp.float32), zero_diagonal
    # self-mode: cast ONCE so `y is x` identity survives (the host path keys
    # its reuse of row norms on it)
    x = x.astype(jnp.float32)
    zero_diagonal = True if zero_diagonal is None else zero_diagonal
    return x, x, zero_diagonal


def _validate_reduction(reduction: Optional[str]) -> None:
    if reduction not in ("mean", "sum", "none", None):
        raise ValueError(f"Expected reduction to be one of `['mean', 'sum', None]` but got {reduction}")


def _reduce_distance_matrix(distmat: Array, reduction: Optional[str] = None) -> Array:
    """Reference pairwise/helpers.py ``_reduce_distance_matrix``."""
    _validate_reduction(reduction)
    if reduction == "mean":
        return jnp.mean(distmat, axis=-1)
    if reduction == "sum":
        return jnp.sum(distmat, axis=-1)
    return distmat


def _zero_diag(mat: Array, zero_diagonal: bool) -> Array:
    if zero_diagonal:
        n = min(mat.shape[0], mat.shape[1])
        return mat.at[jnp.arange(n), jnp.arange(n)].set(0.0)
    return mat


def pairwise_cosine_similarity(
    x: Array, y: Optional[Array] = None, reduction: Optional[str] = None, zero_diagonal: Optional[bool] = None
) -> Array:
    """Cosine similarity matrix (reference pairwise/cosine.py).

    Example:
        >>> import jax.numpy as jnp
        >>> from metrics_tpu.functional import pairwise_cosine_similarity
        >>> x = jnp.array([[1.0, 2.0], [3.0, 4.0]])
        >>> y = jnp.array([[0.0, 1.0], [2.0, 2.0]])
        >>> pairwise_cosine_similarity(x, y)
        Array([[0.8944272 , 0.94868326],
               [0.8       , 0.9899495 ]], dtype=float32)
    """
    x, y, zero_diagonal = _check_input(x, y, zero_diagonal)
    if _is_eager_cpu(x) and _is_eager_cpu(y):
        return _host_pairwise("cosine", x, y, zero_diagonal, reduction)
    # plain division, matching the reference (cosine.py:36-39): an all-zero
    # row has 0/0 norm and propagates NaN through its similarities rather
    # than being clamped to 0 — a zero vector has no defined direction
    norm_x = x / jnp.linalg.norm(x, axis=1, keepdims=True)
    norm_y = y / jnp.linalg.norm(y, axis=1, keepdims=True)
    distance = _safe_matmul(norm_x, norm_y.T)
    distance = _zero_diag(distance, zero_diagonal)
    return _reduce_distance_matrix(distance, reduction)


def pairwise_euclidean_distance(
    x: Array, y: Optional[Array] = None, reduction: Optional[str] = None, zero_diagonal: Optional[bool] = None
) -> Array:
    """Euclidean distance matrix via the one-matmul expansion (reference pairwise/euclidean.py).

    With a single input and ``zero_diagonal`` unset, the diagonal is a
    self-distance — exactly 0 mathematically — and is pinned to 0 (sklearn
    semantics), because the one-matmul expansion loses that exactness to f32
    cancellation at large magnitudes. An explicit ``zero_diagonal=False`` is
    honoured (reference behaviour: you get the raw expansion, including its
    diagonal noise), as is passing ``y=x``.

    Precision: the eager host path upcasts the expansion to f64 exactly like
    the reference (euclidean.py:34); the in-jit/accelerator path keeps f32
    (TPU has no f64 units), where near-duplicate rows carry expansion noise
    of order ``sqrt(eps)*scale`` (~1e-3) — the documented deviation.

    Example:
        >>> import jax.numpy as jnp
        >>> from metrics_tpu.functional import pairwise_euclidean_distance
        >>> x = jnp.array([[1.0, 2.0], [3.0, 4.0]])
        >>> y = jnp.array([[0.0, 1.0], [2.0, 2.0]])
        >>> pairwise_euclidean_distance(x, y)
        Array([[1.4142135, 1.       ],
               [4.2426405, 2.236068 ]], dtype=float32)
    """
    x, y, zero_diagonal = _check_input(x, y, zero_diagonal)
    if _is_eager_cpu(x) and _is_eager_cpu(y):
        return _host_pairwise("euclidean", x, y, zero_diagonal, reduction)
    x_norm = jnp.sum(x * x, axis=1, keepdims=True)
    y_norm = jnp.sum(y * y, axis=1)
    distance = x_norm + y_norm[None, :] - 2.0 * _safe_matmul(x, y.T)
    distance = jnp.sqrt(jnp.maximum(distance, 0.0))
    # Self-mode defaults to a pinned diagonal (self-distances are exactly 0
    # mathematically, but the one-matmul expansion loses that to f32
    # cancellation); an explicit ``zero_diagonal=False`` opts out, matching the
    # reference.
    distance = _zero_diag(distance, zero_diagonal)
    return _reduce_distance_matrix(distance, reduction)


def pairwise_manhattan_distance(
    x: Array, y: Optional[Array] = None, reduction: Optional[str] = None, zero_diagonal: Optional[bool] = None
) -> Array:
    """Manhattan (L1) distance matrix (reference pairwise/manhattan.py).

    Example:
        >>> import jax.numpy as jnp
        >>> from metrics_tpu.functional import pairwise_manhattan_distance
        >>> x = jnp.array([[1.0, 2.0], [3.0, 4.0]])
        >>> y = jnp.array([[0.0, 1.0], [2.0, 2.0]])
        >>> pairwise_manhattan_distance(x, y)
        Array([[2., 1.],
               [6., 3.]], dtype=float32)
    """
    x, y, zero_diagonal = _check_input(x, y, zero_diagonal)
    distance = jnp.sum(jnp.abs(x[:, None, :] - y[None, :, :]), axis=-1)
    distance = _zero_diag(distance, zero_diagonal)
    return _reduce_distance_matrix(distance, reduction)


def pairwise_linear_similarity(
    x: Array, y: Optional[Array] = None, reduction: Optional[str] = None, zero_diagonal: Optional[bool] = None
) -> Array:
    """Linear (dot-product) similarity matrix (reference pairwise/linear.py).

    Example:
        >>> import jax.numpy as jnp
        >>> from metrics_tpu.functional import pairwise_linear_similarity
        >>> x = jnp.array([[1.0, 2.0], [3.0, 4.0]])
        >>> y = jnp.array([[0.0, 1.0], [2.0, 2.0]])
        >>> pairwise_linear_similarity(x, y)
        Array([[ 2.,  6.],
               [ 4., 14.]], dtype=float32)
    """
    x, y, zero_diagonal = _check_input(x, y, zero_diagonal)
    if _is_eager_cpu(x) and _is_eager_cpu(y):
        return _host_pairwise("linear", x, y, zero_diagonal, reduction)
    distance = _safe_matmul(x, y.T)
    distance = _zero_diag(distance, zero_diagonal)
    return _reduce_distance_matrix(distance, reduction)
