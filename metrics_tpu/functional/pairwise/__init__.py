"""Pairwise similarity functionals (reference src/torchmetrics/functional/pairwise/ —
functional-only domain, no module classes, SURVEY §2.5)."""

from metrics_tpu.functional.pairwise.similarity import (
    pairwise_cosine_similarity,
    pairwise_euclidean_distance,
    pairwise_linear_similarity,
    pairwise_manhattan_distance,
)

__all__ = [
    "pairwise_cosine_similarity",
    "pairwise_euclidean_distance",
    "pairwise_linear_similarity",
    "pairwise_manhattan_distance",
]
