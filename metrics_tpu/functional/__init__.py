"""Functional metrics layer (SURVEY §2.5 L3, reference src/torchmetrics/functional/)."""
