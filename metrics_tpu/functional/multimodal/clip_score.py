"""CLIPScore (reference src/torchmetrics/functional/multimodal/clip_score.py).

TPU-native: runs a **Flax** CLIP model (``FlaxCLIPModel``); feature extraction and
the cosine-similarity scoring are jnp ops. A user-supplied (model, processor) pair
is accepted so local/random-weight models work without network access.
"""

from __future__ import annotations

from typing import Any, List, Optional, Tuple, Union

import jax.numpy as jnp
import numpy as np
from jax import Array

from metrics_tpu.utils.imports import _TRANSFORMERS_AVAILABLE

_DEFAULT_CLIP_MODEL = "openai/clip-vit-large-patch14"


def _get_model_and_processor(model_name_or_path: str = _DEFAULT_CLIP_MODEL) -> Tuple[Any, Any]:
    """Load a Flax CLIP model + processor (reference clip_score.py:71-86)."""
    if not _TRANSFORMERS_AVAILABLE:
        raise ModuleNotFoundError(
            "`clip_score` metric requires `transformers` package be installed."
            " Either install with `pip install transformers>=4.0` or `pip install torchmetrics[multimodal]`."
        )
    from transformers import CLIPProcessor, FlaxCLIPModel

    model = FlaxCLIPModel.from_pretrained(model_name_or_path)
    processor = CLIPProcessor.from_pretrained(model_name_or_path)
    return model, processor


def _clip_score_update(
    images: Union[Array, List[Array]],
    text: Union[str, List[str]],
    model: Any,
    processor: Any,
) -> Tuple[Array, int]:
    """Per-sample 100·cos(image emb, text emb) (reference clip_score.py:31-68)."""
    if not isinstance(images, list):
        if images.ndim == 3:
            images = [images]
        else:
            images = list(images)
    else:
        images = list(images)

    if not all(i.ndim == 3 for i in images):
        raise ValueError("Expected all images to be 3d but found image that has either more or less")

    if not isinstance(text, list):
        text = [text]

    if len(text) != len(images):
        raise ValueError(
            f"Expected the number of images and text examples to be the same but got {len(images)} and {len(text)}"
        )

    processed_input = processor(text=text, images=[np.asarray(i) for i in images], return_tensors="np", padding=True)

    img_features = model.get_image_features(jnp.asarray(processed_input["pixel_values"]))
    img_features = img_features / jnp.linalg.norm(img_features, axis=-1, keepdims=True)

    txt_features = model.get_text_features(
        jnp.asarray(processed_input["input_ids"]), jnp.asarray(processed_input["attention_mask"])
    )
    txt_features = txt_features / jnp.linalg.norm(txt_features, axis=-1, keepdims=True)

    score = 100 * jnp.sum(img_features * txt_features, axis=-1)
    return score, len(text)


def clip_score(
    images: Union[Array, List[Array]],
    text: Union[str, List[str]],
    model_name_or_path: str = _DEFAULT_CLIP_MODEL,
    model: Optional[Any] = None,
    processor: Optional[Any] = None,
) -> Array:
    """CLIPScore: max(100·cos(E_I, E_C), 0) averaged over samples
    (reference clip_score.py:92-139). Pass ``model``/``processor`` directly to skip
    the pretrained download.
    """
    if (model is None) != (processor is None):
        raise ValueError("Arguments `model` and `processor` must be provided together (or both omitted).")
    if model is None:
        model, processor = _get_model_and_processor(model_name_or_path)
    score, _ = _clip_score_update(images, text, model, processor)
    score = score.mean(0)
    return jnp.maximum(score, jnp.zeros_like(score))
