"""Shared image-kernel helpers: separable gaussian windows + depthwise convs.

Reference parity: src/torchmetrics/functional/image/helper.py (``_gaussian`` :11,
``_gaussian_kernel_2d`` :29, ``_gaussian_kernel_3d`` :62, reflection pads).

TPU-first notes: the sliding windows lower to ``lax.conv_general_dilated`` with
``feature_group_count=C`` (depthwise) — XLA maps these onto the MXU as implicit GEMMs.
Reflection padding is ``jnp.pad(mode="reflect")`` (fused by XLA into the conv input).
"""

from __future__ import annotations

from typing import Sequence

import jax
import jax.numpy as jnp
from jax import Array


def _gaussian(kernel_size: int, sigma: float, dtype=jnp.float32) -> Array:
    """1D normalized gaussian window, shape ``(1, kernel_size)``."""
    dist = jnp.arange((1 - kernel_size) / 2, (1 + kernel_size) / 2, 1.0, dtype=dtype)
    gauss = jnp.exp(-jnp.square(dist / sigma) / 2)
    return (gauss / jnp.sum(gauss)).reshape(1, -1)


def _gaussian_kernel_2d(channel: int, kernel_size: Sequence[int], sigma: Sequence[float], dtype=jnp.float32) -> Array:
    """Depthwise 2D gaussian kernel, shape ``(C, 1, kh, kw)`` (OIHW)."""
    kx = _gaussian(kernel_size[0], sigma[0], dtype)
    ky = _gaussian(kernel_size[1], sigma[1], dtype)
    kernel = kx.T @ ky  # (kh, kw)
    return jnp.broadcast_to(kernel, (channel, 1, kernel_size[0], kernel_size[1]))


def _gaussian_kernel_3d(channel: int, kernel_size: Sequence[int], sigma: Sequence[float], dtype=jnp.float32) -> Array:
    """Depthwise 3D gaussian kernel, shape ``(C, 1, kd, kh, kw)``."""
    kx = _gaussian(kernel_size[0], sigma[0], dtype)
    ky = _gaussian(kernel_size[1], sigma[1], dtype)
    kz = _gaussian(kernel_size[2], sigma[2], dtype)
    kernel_xy = (kx.T @ ky)[:, :, None] * kz.reshape(1, 1, -1)  # (kh, kw, kd) in xy-z order
    return jnp.broadcast_to(kernel_xy, (channel, 1, *kernel_xy.shape))


def _uniform_kernel(channel: int, kernel_size: Sequence[int], dtype=jnp.float32) -> Array:
    size = tuple(kernel_size)
    kernel = jnp.ones(size, dtype=dtype) / float(jnp.prod(jnp.asarray(size)))
    return jnp.broadcast_to(kernel, (channel, 1, *size))


def _depthwise_conv(x: Array, kernel: Array) -> Array:
    """VALID depthwise conv: x ``(N, C, *spatial)``, kernel ``(C, 1, *window)``."""
    ndim_sp = x.ndim - 2
    if ndim_sp == 2:
        dn = ("NCHW", "OIHW", "NCHW")
    elif ndim_sp == 3:
        dn = ("NCDHW", "OIDHW", "NCDHW")
    else:
        raise ValueError(f"Expected 2 or 3 spatial dims, got {ndim_sp}")
    return jax.lax.conv_general_dilated(
        x.astype(kernel.dtype),
        kernel,
        window_strides=(1,) * ndim_sp,
        padding="VALID",
        dimension_numbers=dn,
        feature_group_count=x.shape[1],
    )


def _reflection_pad(x: Array, pads: Sequence[int]) -> Array:
    """Reflection-pad the trailing spatial dims; ``pads`` is per-spatial-dim."""
    cfg = [(0, 0), (0, 0)] + [(p, p) for p in pads]
    return jnp.pad(x, cfg, mode="reflect")


def _avg_pool(x: Array, window: int = 2) -> Array:
    """Non-overlapping mean pool over the trailing spatial dims (torch avg_poolNd)."""
    ndim_sp = x.ndim - 2
    dims = (1, 1) + (window,) * ndim_sp
    summed = jax.lax.reduce_window(x, 0.0, jax.lax.add, dims, dims, "VALID")
    return summed / (window**ndim_sp)
