"""Shared image-kernel helpers: separable gaussian windows as banded matmuls.

Reference parity: src/torchmetrics/functional/image/helper.py (``_gaussian`` :11,
``_gaussian_kernel_2d`` :29, ``_gaussian_kernel_3d`` :62, reflection pads).

TPU-first notes: the separable windows are applied as banded MATMULS (one per
spatial dim) rather than convolutions — GEMMs ride the MXU on TPU and the
multithreaded BLAS on CPU, where ``lax.conv`` lowers poorly. Reflection padding
is ``jnp.pad(mode="reflect")``.
"""

from __future__ import annotations

from typing import Sequence

import jax
import jax.numpy as jnp
from jax import Array


def _gaussian(kernel_size: int, sigma: float, dtype=jnp.float32) -> Array:
    """1D normalized gaussian window, shape ``(1, kernel_size)``."""
    dist = jnp.arange((1 - kernel_size) / 2, (1 + kernel_size) / 2, 1.0, dtype=dtype)
    gauss = jnp.exp(-jnp.square(dist / sigma) / 2)
    return (gauss / jnp.sum(gauss)).reshape(1, -1)


def _reflection_pad(x: Array, pads: Sequence[int]) -> Array:
    """Reflection-pad the trailing spatial dims; ``pads`` is per-spatial-dim."""
    cfg = [(0, 0), (0, 0)] + [(p, p) for p in pads]
    return jnp.pad(x, cfg, mode="reflect")


def _avg_pool(x: Array, window: int = 2) -> Array:
    """Non-overlapping mean pool over the trailing spatial dims (torch avg_poolNd)."""
    ndim_sp = x.ndim - 2
    dims = (1, 1) + (window,) * ndim_sp
    summed = jax.lax.reduce_window(x, 0.0, jax.lax.add, dims, dims, "VALID")
    return summed / (window**ndim_sp)


def _band_matrix(f: Array, in_len: int, dtype) -> Array:
    """(out_len, in_len) banded matrix whose row i holds window ``f`` at offset i —
    a VALID 1-D correlation expressed as a dense matmul."""
    k = f.size
    out_len = in_len - k + 1
    rows = jnp.arange(out_len)[:, None]
    cols = jnp.arange(in_len)[None, :]
    offset = cols - rows  # window position within each row
    band = jnp.where((offset >= 0) & (offset < k), f[jnp.clip(offset, 0, k - 1)], 0)
    return band.astype(dtype)


def _depthwise_conv_separable(x: Array, factors: Sequence[Array]) -> Array:
    """VALID depthwise conv with a separable window: one banded matmul per
    spatial dim.

    The gaussian and uniform SSIM windows are outer products of 1-D windows.
    Each 1-D pass is expressed as ``x @ band.T`` rather than a conv: banded
    matmuls ride the MXU on TPU and the multithreaded GEMM on CPU, where
    ``lax.conv`` lowers poorly (measured 16x faster than the depthwise-conv
    form this replaced on CPU at 256x256/11x11 — 1.7 s -> 108 ms — identical
    results up to FP reassociation; see benchmarks/image_vs_reference.py).
    """
    ndim_sp = x.ndim - 2
    for axis, f in enumerate(factors):
        sp_axis = 2 + axis
        band = _band_matrix(f.astype(x.dtype), x.shape[sp_axis], x.dtype)
        x = jnp.moveaxis(jnp.tensordot(x, band, axes=[[sp_axis], [1]]), -1, sp_axis)
    return x
