"""Peak signal-to-noise ratio functional.

Reference parity: src/torchmetrics/functional/image/psnr.py
(``_psnr_compute`` :23, ``_psnr_update`` :58, ``peak_signal_noise_ratio`` :90).
"""

from __future__ import annotations

from typing import Optional, Tuple, Union

import jax.numpy as jnp
from jax import Array

from metrics_tpu.utils.compute import _host_sq_diff_sum
from metrics_tpu.utils.distributed import reduce
from metrics_tpu.utils.prints import rank_zero_warn


def _psnr_compute(
    sum_squared_error: Array,
    n_obs: Array,
    data_range: Array,
    base: float = 10.0,
    reduction: Optional[str] = "elementwise_mean",
) -> Array:
    psnr_base_e = 2 * jnp.log(data_range) - jnp.log(sum_squared_error / n_obs)
    psnr_vals = psnr_base_e * (10 / jnp.log(base))
    return reduce(psnr_vals, reduction)


def _psnr_update(
    preds: Array,
    target: Array,
    dim: Optional[Union[int, Tuple[int, ...]]] = None,
) -> Tuple[Array, Array]:
    if dim is None:
        host = _host_sq_diff_sum(preds, target)
        if host is not None:
            return host, jnp.asarray(target.size, dtype=jnp.float32)
        sum_squared_error = jnp.sum(jnp.square(preds - target))
        n_obs = jnp.asarray(target.size, dtype=jnp.float32)
        return sum_squared_error, n_obs

    diff = preds - target
    sum_squared_error = jnp.sum(diff * diff, axis=dim)
    dim_list = [dim] if isinstance(dim, int) else list(dim)
    if not dim_list:
        n_obs = jnp.asarray(target.size, dtype=jnp.float32)
    else:
        n = 1
        for d in dim_list:
            n *= target.shape[d]
        n_obs = jnp.broadcast_to(jnp.asarray(n, dtype=jnp.float32), sum_squared_error.shape)
    return sum_squared_error, n_obs


def peak_signal_noise_ratio(
    preds: Array,
    target: Array,
    data_range: Optional[float] = None,
    base: float = 10.0,
    reduction: Optional[str] = "elementwise_mean",
    dim: Optional[Union[int, Tuple[int, ...]]] = None,
) -> Array:
    """PSNR (reference :90-147).

    Example:
        >>> import jax.numpy as jnp
        >>> from metrics_tpu.functional import peak_signal_noise_ratio
        >>> import jax
        >>> key1, key2 = jax.random.split(jax.random.PRNGKey(0))
        >>> preds = jax.random.uniform(key1, (2, 3, 32, 32))
        >>> target = preds * 0.75 + jax.random.uniform(key2, (2, 3, 32, 32)) * 0.25
        >>> peak_signal_noise_ratio(preds, target, data_range=1.0)
        Array(19.837866, dtype=float32)
    """
    if dim is None and reduction != "elementwise_mean":
        rank_zero_warn(f"The `reduction={reduction}` will not have any effect when `dim` is None.")
    preds = jnp.asarray(preds)
    target = jnp.asarray(target)
    if data_range is None:
        if dim is not None:
            raise ValueError("The `data_range` must be given when `dim` is not None.")
        data_range = jnp.max(target) - jnp.min(target)
    else:
        data_range = jnp.asarray(float(data_range))
    sum_squared_error, n_obs = _psnr_update(preds, target, dim=dim)
    return _psnr_compute(sum_squared_error, n_obs, data_range, base=base, reduction=reduction)
