"""Image gradients via 1-step finite differences.

TPU-native port of the reference ``image_gradients``
(src/torchmetrics/functional/image/gradients.py:49): forward differences along H and W
with a zero last row/column, matching the TF convention where the gradient
``I(x+1, y) - I(x, y)`` lands at location ``(x, y)``. Pure jnp slicing + pad — XLA fuses
this into two elementwise subtractions; no gather needed.
"""

from __future__ import annotations

from typing import Tuple

import jax.numpy as jnp


def _image_gradients_validate(img: jnp.ndarray) -> None:
    if not hasattr(img, "ndim"):
        raise TypeError(f"The `img` expects an array type but got {type(img)}")
    if img.ndim != 4:
        raise RuntimeError(f"The `img` expects a 4D tensor but got {img.ndim}D tensor")


def _compute_image_gradients(img: jnp.ndarray) -> Tuple[jnp.ndarray, jnp.ndarray]:
    dy = img[..., 1:, :] - img[..., :-1, :]
    dx = img[..., :, 1:] - img[..., :, :-1]
    dy = jnp.pad(dy, ((0, 0), (0, 0), (0, 1), (0, 0)))
    dx = jnp.pad(dx, ((0, 0), (0, 0), (0, 0), (0, 1)))
    return dy, dx


def image_gradients(img: jnp.ndarray) -> Tuple[jnp.ndarray, jnp.ndarray]:
    """Compute per-pixel image gradients ``(dy, dx)`` of an ``(N, C, H, W)`` image.

    Example:
        >>> import jax.numpy as jnp
        >>> from metrics_tpu.functional.image import image_gradients
        >>> image = jnp.arange(0, 1 * 1 * 5 * 5, dtype=jnp.float32).reshape(1, 1, 5, 5)
        >>> dy, dx = image_gradients(image)
        >>> dy[0, 0, :2, :]
        Array([[5., 5., 5., 5., 5.],
               [5., 5., 5., 5., 5.]], dtype=float32)
    """
    _image_gradients_validate(img)
    return _compute_image_gradients(img)
