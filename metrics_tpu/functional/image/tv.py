"""Total variation functional.

Reference parity: src/torchmetrics/functional/image/tv.py
(``_total_variation_update`` :20, ``_total_variation_compute`` :33, ``total_variation`` :47).
"""

from __future__ import annotations

from typing import Optional, Tuple

import jax.numpy as jnp
from jax import Array


def _total_variation_update(img: Array) -> Tuple[Array, int]:
    if img.ndim != 4:
        raise RuntimeError(f"Expected input `img` to be an 4D tensor, but got {img.shape}")
    diff1 = img[..., 1:, :] - img[..., :-1, :]
    diff2 = img[..., :, 1:] - img[..., :, :-1]
    score = jnp.sum(jnp.abs(diff1), axis=(1, 2, 3)) + jnp.sum(jnp.abs(diff2), axis=(1, 2, 3))
    return score, img.shape[0]


def _total_variation_compute(score: Array, num_elements, reduction: Optional[str]) -> Array:
    if reduction == "mean":
        return jnp.sum(score) / num_elements
    if reduction == "sum":
        return jnp.sum(score)
    if reduction is None or reduction == "none":
        return score
    raise ValueError("Expected argument `reduction` to either be 'sum', 'mean', 'none' or None")


def total_variation(img: Array, reduction: Optional[str] = "sum") -> Array:
    """Anisotropic TV (reference :47-…).

    Example:
        >>> import jax
        >>> from metrics_tpu.functional import total_variation
        >>> img = jax.random.uniform(jax.random.PRNGKey(0), (2, 3, 32, 32))
        >>> total_variation(img)
        Array(3998.7195, dtype=float32)
    """
    score, num_elements = _total_variation_update(jnp.asarray(img))
    return _total_variation_compute(score, num_elements, reduction)
