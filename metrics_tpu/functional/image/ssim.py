"""SSIM and multi-scale SSIM functionals.

Reference parity: src/torchmetrics/functional/image/ssim.py
(``_ssim_update`` :46-179, ``_multiscale_ssim_update`` :310-430).

TPU-first notes: the five sliding-window statistics (μ_p, μ_t, E[p²], E[t²], E[pt]) are
computed in ONE depthwise convolution over a 5·B-stacked batch (the reference's trick,
kept because it maps to a single MXU-bound conv), with reflect padding fused by XLA.
Downsampling between MS-SSIM scales is a reduce_window mean pool.
"""

from __future__ import annotations

from typing import List, Optional, Sequence, Tuple, Union

import jax.numpy as jnp
from jax import Array

from metrics_tpu.functional.image.helper import (
    _avg_pool,
    _depthwise_conv_separable,
    _gaussian,
    _reflection_pad,
)
from metrics_tpu.utils.checks import _check_same_shape
from metrics_tpu.utils.distributed import reduce


def _ssim_check_inputs(preds: Array, target: Array) -> Tuple[Array, Array]:
    preds = jnp.asarray(preds)
    target = jnp.asarray(target)
    if not jnp.issubdtype(preds.dtype, jnp.floating):
        preds = preds.astype(jnp.float32)
    if not jnp.issubdtype(target.dtype, jnp.floating):
        target = target.astype(jnp.float32)
    _check_same_shape(preds, target)
    if preds.ndim not in (4, 5):
        raise ValueError(f"Expected `preds` and `target` to have BxCxHxW or BxCxDxHxW shape. Got {preds.shape}.")
    return preds, target


def _ssim_update(
    preds: Array,
    target: Array,
    gaussian_kernel: bool = True,
    sigma: Union[float, Sequence[float]] = 1.5,
    kernel_size: Union[int, Sequence[int]] = 11,
    data_range: Optional[float] = None,
    k1: float = 0.01,
    k2: float = 0.03,
    return_full_image: bool = False,
    return_contrast_sensitivity: bool = False,
):
    """Per-image SSIM (reference :46-179)."""
    is_3d = preds.ndim == 5
    n_sp = 3 if is_3d else 2

    if not isinstance(sigma, Sequence):
        sigma = n_sp * [sigma]
    if not isinstance(kernel_size, Sequence):
        kernel_size = n_sp * [kernel_size]
    if len(kernel_size) != n_sp or len(sigma) != n_sp:
        raise ValueError(
            f"`kernel_size` has dimension {len(kernel_size)} and `sigma` has dimension {len(sigma)},"
            f" but expected {n_sp} for {'3d' if is_3d else '2d'} inputs"
        )
    if return_full_image and return_contrast_sensitivity:
        raise ValueError("Arguments `return_full_image` and `return_contrast_sensitivity` are mutually exclusive.")
    if any(x % 2 == 0 or x <= 0 for x in kernel_size):
        raise ValueError(f"Expected `kernel_size` to have odd positive number. Got {kernel_size}.")
    if any(y <= 0 for y in sigma):
        raise ValueError(f"Expected `sigma` to have positive number. Got {sigma}.")

    if data_range is None:
        data_range = jnp.maximum(jnp.max(preds) - jnp.min(preds), jnp.max(target) - jnp.min(target))

    c1 = (k1 * data_range) ** 2
    c2 = (k2 * data_range) ** 2
    channel = preds.shape[1]
    dtype = preds.dtype

    if gaussian_kernel:
        size = [int(3.5 * s + 0.5) * 2 + 1 for s in sigma]
        factors = [_gaussian(k, s, dtype).reshape(-1) for k, s in zip(size, sigma)]
    else:
        size = list(kernel_size)
        factors = [jnp.ones(k, dtype=dtype) / k for k in size]

    pads = [(s - 1) // 2 for s in size]
    preds_p = _reflection_pad(preds, pads)
    target_p = _reflection_pad(target, pads)

    # one depthwise conv over the 5·B-stacked batch: μp, μt, E[p²], E[t²], E[pt]
    input_list = jnp.concatenate([preds_p, target_p, preds_p * preds_p, target_p * target_p, preds_p * target_p])
    outputs = _depthwise_conv_separable(input_list, factors)
    b = preds.shape[0]
    mu_pred, mu_target, e_pp, e_tt, e_pt = (outputs[i * b : (i + 1) * b] for i in range(5))

    mu_pred_sq = jnp.square(mu_pred)
    mu_target_sq = jnp.square(mu_target)
    mu_pred_target = mu_pred * mu_target

    sigma_pred_sq = e_pp - mu_pred_sq
    sigma_target_sq = e_tt - mu_target_sq
    sigma_pred_target = e_pt - mu_pred_target

    upper = 2 * sigma_pred_target.astype(dtype) + c2
    lower = (sigma_pred_sq + sigma_target_sq).astype(dtype) + c2

    ssim_idx_full_image = ((2 * mu_pred_target + c1) * upper) / ((mu_pred_sq + mu_target_sq + c1) * lower)

    # interior crop (reference :163-167) — the conv output is already the padded-image
    # valid region, i.e. the full original size; crop the pad-influenced border
    sl = tuple(slice(p, d - p) for p, d in zip(pads, ssim_idx_full_image.shape[2:]))
    ssim_idx = ssim_idx_full_image[(Ellipsis, *sl)]

    if return_contrast_sensitivity:
        contrast_sensitivity = (upper / lower)[(Ellipsis, *sl)]
        return ssim_idx.reshape(b, -1).mean(-1), contrast_sensitivity.reshape(b, -1).mean(-1)
    if return_full_image:
        return ssim_idx.reshape(b, -1).mean(-1), ssim_idx_full_image
    return ssim_idx.reshape(b, -1).mean(-1)


def _ssim_compute(similarities: Array, reduction: Optional[str] = "elementwise_mean") -> Array:
    return reduce(similarities, reduction)


def structural_similarity_index_measure(
    preds: Array,
    target: Array,
    gaussian_kernel: bool = True,
    sigma: Union[float, Sequence[float]] = 1.5,
    kernel_size: Union[int, Sequence[int]] = 11,
    reduction: Optional[str] = "elementwise_mean",
    data_range: Optional[float] = None,
    k1: float = 0.01,
    k2: float = 0.03,
    return_full_image: bool = False,
    return_contrast_sensitivity: bool = False,
):
    """SSIM (reference :202-…).

    Example:
        >>> import jax.numpy as jnp
        >>> from metrics_tpu.functional import structural_similarity_index_measure
        >>> import jax
        >>> key1, key2 = jax.random.split(jax.random.PRNGKey(0))
        >>> preds = jax.random.uniform(key1, (2, 3, 32, 32))
        >>> target = preds * 0.75 + jax.random.uniform(key2, (2, 3, 32, 32)) * 0.25
        >>> structural_similarity_index_measure(preds, target, data_range=1.0)
        Array(0.92449266, dtype=float32)
    """
    preds, target = _ssim_check_inputs(preds, target)
    out = _ssim_update(
        preds, target, gaussian_kernel, sigma, kernel_size, data_range, k1, k2,
        return_full_image, return_contrast_sensitivity,
    )
    if isinstance(out, tuple):
        return _ssim_compute(out[0], reduction), out[1]
    return _ssim_compute(out, reduction)


def _get_normalized_sim_and_cs(
    preds: Array, target: Array, gaussian_kernel, sigma, kernel_size, data_range, k1, k2, normalize=None
) -> Tuple[Array, Array]:
    sim, cs = _ssim_update(
        preds, target, gaussian_kernel, sigma, kernel_size, data_range, k1, k2, return_contrast_sensitivity=True
    )
    if normalize == "relu":
        sim = jnp.maximum(sim, 0.0)
        cs = jnp.maximum(cs, 0.0)
    return sim, cs


def _multiscale_ssim_update(
    preds: Array,
    target: Array,
    gaussian_kernel: bool = True,
    sigma: Union[float, Sequence[float]] = 1.5,
    kernel_size: Union[int, Sequence[int]] = 11,
    data_range: Optional[float] = None,
    k1: float = 0.01,
    k2: float = 0.03,
    betas: Tuple[float, ...] = (0.0448, 0.2856, 0.3001, 0.2363, 0.1333),
    normalize: Optional[str] = None,
) -> Array:
    """MS-SSIM per image (reference :310-430): cs at every scale, sim at the last."""
    is_3d = preds.ndim == 5
    n_sp = 3 if is_3d else 2
    if not isinstance(kernel_size, Sequence):
        kernel_size = n_sp * [kernel_size]
    if not isinstance(sigma, Sequence):
        sigma = n_sp * [sigma]

    if preds.shape[-1] < 2 ** len(betas) or preds.shape[-2] < 2 ** len(betas):
        raise ValueError(
            f"For a given number of `betas` parameters {len(betas)}, the image height and width dimensions must be"
            f" larger than or equal to {2 ** len(betas)}."
        )
    _betas_div = max(1, (len(betas) - 1)) ** 2
    if preds.shape[-2] // _betas_div <= kernel_size[0] - 1:
        raise ValueError(
            f"For a given number of `betas` parameters {len(betas)} and kernel size {kernel_size[0]},"
            f" the image height must be larger than {(kernel_size[0] - 1) * _betas_div}."
        )
    if preds.shape[-1] // _betas_div <= kernel_size[1] - 1:
        raise ValueError(
            f"For a given number of `betas` parameters {len(betas)} and kernel size {kernel_size[1]},"
            f" the image width must be larger than {(kernel_size[1] - 1) * _betas_div}."
        )

    mcs_list: List[Array] = []
    sim = None
    for _ in range(len(betas)):
        sim, cs = _get_normalized_sim_and_cs(
            preds, target, gaussian_kernel, sigma, kernel_size, data_range, k1, k2, normalize
        )
        mcs_list.append(cs)
        preds = _avg_pool(preds, 2)
        target = _avg_pool(target, 2)

    mcs_list[-1] = sim
    mcs_stack = jnp.stack(mcs_list)
    if normalize == "simple":
        mcs_stack = (mcs_stack + 1) / 2
    betas_arr = jnp.asarray(betas, dtype=mcs_stack.dtype).reshape(-1, 1)
    return jnp.prod(mcs_stack**betas_arr, axis=0)


def multiscale_structural_similarity_index_measure(
    preds: Array,
    target: Array,
    gaussian_kernel: bool = True,
    sigma: Union[float, Sequence[float]] = 1.5,
    kernel_size: Union[int, Sequence[int]] = 11,
    reduction: Optional[str] = "elementwise_mean",
    data_range: Optional[float] = None,
    k1: float = 0.01,
    k2: float = 0.03,
    betas: Tuple[float, ...] = (0.0448, 0.2856, 0.3001, 0.2363, 0.1333),
    normalize: Optional[str] = "relu",
) -> Array:
    """MS-SSIM (reference :433-…).

    Example:
        >>> import jax.numpy as jnp
        >>> from metrics_tpu.functional import multiscale_structural_similarity_index_measure
        >>> import jax
        >>> key1, key2 = jax.random.split(jax.random.PRNGKey(0))
        >>> preds = jax.random.uniform(key1, (2, 3, 192, 192))
        >>> target = preds * 0.75 + jax.random.uniform(key2, (2, 3, 192, 192)) * 0.25
        >>> multiscale_structural_similarity_index_measure(preds, target, data_range=1.0)
        Array(0.9372302, dtype=float32)
    """
    if not isinstance(betas, tuple) or not all(isinstance(b, float) for b in betas):
        raise ValueError("Argument `betas` is expected to be of a type tuple of floats.")
    if normalize is not None and normalize not in ("relu", "simple"):
        raise ValueError("Argument `normalize` to be expected either `None` or one of 'relu' or 'simple'")
    preds, target = _ssim_check_inputs(preds, target)
    mcs_per_image = _multiscale_ssim_update(
        preds, target, gaussian_kernel, sigma, kernel_size, data_range, k1, k2, betas, normalize
    )
    return reduce(mcs_per_image, reduction)
