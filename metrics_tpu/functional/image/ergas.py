"""ERGAS functional.

Reference parity: src/torchmetrics/functional/image/ergas.py
(``_ergas_update`` :24, ``_ergas_compute`` :47, the 100·ratio·RMS-of-relative-RMSE form).
"""

from __future__ import annotations

from typing import Optional, Tuple, Union

import jax.numpy as jnp
import numpy as np
from jax import Array

from metrics_tpu.utils.checks import _check_same_shape
from metrics_tpu.utils.compute import _is_eager_cpu
from metrics_tpu.utils.distributed import reduce


def _ergas_update(preds: Array, target: Array) -> Tuple[Array, Array]:
    preds = jnp.asarray(preds)
    target = jnp.asarray(target)
    if preds.dtype != target.dtype:
        target = target.astype(preds.dtype)
    _check_same_shape(preds, target)
    if preds.ndim != 4:
        raise ValueError(f"Expected `preds` and `target` to have BxCxHxW shape. Got preds: {preds.shape}.")
    return preds, target


def _ergas_compute(
    preds: Array,
    target: Array,
    ratio: Union[int, float] = 4,
    reduction: Optional[str] = "elementwise_mean",
) -> Array:
    b, c, h, w = preds.shape
    if preds.dtype == jnp.float32 and _is_eager_cpu(preds) and _is_eager_cpu(target):
        # per-band squared sums as one batched einsum-dot on the host (BLAS);
        # ~1.6x XLA's eager CPU chain at 8x3x256x256. f32-only: the jnp form
        # below keeps wider-dtype accumulation semantics.
        ph = np.asarray(preds).reshape(b, c, h * w)
        th = np.asarray(target).reshape(b, c, h * w)
        d = ph - th
        rmse_per_band = np.sqrt(np.einsum("ncx,ncx->nc", d, d) / (h * w))
        # band means as one BLAS gemv instead of a numpy reduce pass
        mean_target = (th.reshape(b * c, -1) @ np.ones(h * w, np.float32)).reshape(b, c) / (h * w)
        with np.errstate(divide="ignore", invalid="ignore"):
            # zero-mean bands: silently produce inf/nan exactly like the jnp
            # path (numpy would otherwise emit a RuntimeWarning)
            score = 100 * ratio * np.sqrt(np.square(rmse_per_band / mean_target).sum(-1) / c)
        return reduce(jnp.asarray(score), reduction)
    preds = preds.reshape(b, c, h * w)
    target = target.reshape(b, c, h * w)

    diff = preds - target
    sum_squared_error = jnp.sum(diff * diff, axis=2)
    rmse_per_band = jnp.sqrt(sum_squared_error / (h * w))
    mean_target = jnp.mean(target, axis=2)

    ergas_score = 100 * ratio * jnp.sqrt(jnp.sum(jnp.square(rmse_per_band / mean_target), axis=1) / c)
    return reduce(ergas_score, reduction)


def error_relative_global_dimensionless_synthesis(
    preds: Array,
    target: Array,
    ratio: Union[int, float] = 4,
    reduction: Optional[str] = "elementwise_mean",
) -> Array:
    """ERGAS (reference :86-…).

    Example:
        >>> import jax.numpy as jnp
        >>> from metrics_tpu.functional import error_relative_global_dimensionless_synthesis
        >>> import jax
        >>> key1, key2 = jax.random.split(jax.random.PRNGKey(0))
        >>> preds = jax.random.uniform(key1, (2, 3, 32, 32))
        >>> target = preds * 0.75 + jax.random.uniform(key2, (2, 3, 32, 32)) * 0.25
        >>> error_relative_global_dimensionless_synthesis(preds, target, ratio=4)
        Array(81.11109, dtype=float32)
    """
    preds, target = _ergas_update(preds, target)
    return _ergas_compute(preds, target, ratio, reduction)
