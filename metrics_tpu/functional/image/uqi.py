"""Universal Image Quality Index functional.

Reference parity: src/torchmetrics/functional/image/uqi.py
(``_uqi_update`` :26, ``_uqi_compute`` :49, ``universal_image_quality_index`` :126).
Same 5-way stacked depthwise-conv trick as SSIM (UQI = SSIM with c1 = c2 = 0).
"""

from __future__ import annotations

from typing import Optional, Sequence, Tuple

import jax.numpy as jnp
from jax import Array

from metrics_tpu.functional.image.helper import _depthwise_conv_separable, _gaussian, _reflection_pad
from metrics_tpu.utils.checks import _check_same_shape
from metrics_tpu.utils.distributed import reduce


def _uqi_update(preds: Array, target: Array) -> Tuple[Array, Array]:
    preds = jnp.asarray(preds)
    target = jnp.asarray(target)
    if preds.dtype != target.dtype:
        target = target.astype(preds.dtype)
    _check_same_shape(preds, target)
    if preds.ndim != 4:
        raise ValueError(f"Expected `preds` and `target` to have BxCxHxW shape. Got preds: {preds.shape}.")
    return preds, target


def _uqi_compute(
    preds: Array,
    target: Array,
    kernel_size: Sequence[int] = (11, 11),
    sigma: Sequence[float] = (1.5, 1.5),
    reduction: Optional[str] = "elementwise_mean",
    data_range: Optional[float] = None,
) -> Array:
    if len(kernel_size) != 2 or len(sigma) != 2:
        raise ValueError(
            "Expected `kernel_size` and `sigma` to have the length of two."
            f" Got kernel_size: {len(kernel_size)} and sigma: {len(sigma)}."
        )
    if any(x % 2 == 0 or x <= 0 for x in kernel_size):
        raise ValueError(f"Expected `kernel_size` to have odd positive number. Got {kernel_size}.")
    if any(y <= 0 for y in sigma):
        raise ValueError(f"Expected `sigma` to have positive number. Got {sigma}.")

    channel = preds.shape[1]
    dtype = preds.dtype if jnp.issubdtype(preds.dtype, jnp.floating) else jnp.float32
    preds = preds.astype(dtype)
    target = target.astype(dtype)
    factors = [_gaussian(k, s, dtype).reshape(-1) for k, s in zip(kernel_size, sigma)]
    pads = [(k - 1) // 2 for k in kernel_size]

    preds_p = _reflection_pad(preds, pads)
    target_p = _reflection_pad(target, pads)

    input_list = jnp.concatenate([preds_p, target_p, preds_p * preds_p, target_p * target_p, preds_p * target_p])
    outputs = _depthwise_conv_separable(input_list, factors)
    b = preds.shape[0]
    mu_pred, mu_target, e_pp, e_tt, e_pt = (outputs[i * b : (i + 1) * b] for i in range(5))

    mu_pred_sq = jnp.square(mu_pred)
    mu_target_sq = jnp.square(mu_target)
    mu_pred_target = mu_pred * mu_target

    sigma_pred_sq = e_pp - mu_pred_sq
    sigma_target_sq = e_tt - mu_target_sq
    sigma_pred_target = e_pt - mu_pred_target

    upper = 2 * sigma_pred_target
    lower = sigma_pred_sq + sigma_target_sq

    uqi_idx = ((2 * mu_pred_target) * upper) / ((mu_pred_sq + mu_target_sq) * lower)
    sl = tuple(slice(p, d - p) for p, d in zip(pads, uqi_idx.shape[2:]))
    uqi_idx = uqi_idx[(Ellipsis, *sl)]
    return reduce(uqi_idx, reduction)


def universal_image_quality_index(
    preds: Array,
    target: Array,
    kernel_size: Sequence[int] = (11, 11),
    sigma: Sequence[float] = (1.5, 1.5),
    reduction: Optional[str] = "elementwise_mean",
    data_range: Optional[float] = None,
) -> Array:
    """UQI (reference :126-…).

    Example:
        >>> import jax.numpy as jnp
        >>> from metrics_tpu.functional import universal_image_quality_index
        >>> import jax
        >>> key1, key2 = jax.random.split(jax.random.PRNGKey(0))
        >>> preds = jax.random.uniform(key1, (2, 3, 32, 32))
        >>> target = preds * 0.75 + jax.random.uniform(key2, (2, 3, 32, 32)) * 0.25
        >>> universal_image_quality_index(preds, target)
        Array(0.9239566, dtype=float32)
    """
    preds, target = _uqi_update(preds, target)
    return _uqi_compute(preds, target, kernel_size, sigma, reduction, data_range)
