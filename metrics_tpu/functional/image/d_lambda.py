"""Spectral Distortion Index (D_lambda) functional.

Reference parity: src/torchmetrics/functional/image/d_lambda.py
(``_spectral_distortion_index_update`` :26, ``_spectral_distortion_index_compute`` :47).

TPU-first notes: the reference fills the (L, L) cross-band UQI matrices with a Python
double loop of full UQI calls; here all L² band pairs are evaluated in ONE depthwise
conv by stacking every (band_k, band_r) pair along the channel axis.
"""

from __future__ import annotations

from typing import Optional, Tuple

import jax.numpy as jnp
from jax import Array

from metrics_tpu.functional.image.uqi import _uqi_compute
from metrics_tpu.utils.checks import _check_same_shape
from metrics_tpu.utils.distributed import reduce


def _spectral_distortion_index_update(preds: Array, target: Array) -> Tuple[Array, Array]:
    preds = jnp.asarray(preds)
    target = jnp.asarray(target)
    if preds.dtype != target.dtype:
        target = target.astype(preds.dtype)
    _check_same_shape(preds, target)
    if preds.ndim != 4:
        raise ValueError(f"Expected `preds` and `target` to have BxCxHxW shape. Got preds: {preds.shape}.")
    return preds, target


def _pairwise_band_uqi(x: Array) -> Array:
    """(L, L) matrix of UQI between every pair of bands of ``x`` (N, L, H, W)."""
    n, length, h, w = x.shape
    # build (N, L*L, H, W) of (band_k, band_r) pairs → single-channel UQI per pair
    k_idx, r_idx = jnp.meshgrid(jnp.arange(length), jnp.arange(length), indexing="ij")
    a = x[:, k_idx.reshape(-1)]  # (N, L*L, H, W)
    b = x[:, r_idx.reshape(-1)]
    # treat each pair as an independent single-channel image batch
    a = a.reshape(n * length * length, 1, h, w)
    b = b.reshape(n * length * length, 1, h, w)
    # per-pair mean over batch: reshape scores (N*L*L,) → (N, L, L) and mean over N
    scores = _uqi_compute(a, b, reduction="none")
    scores = scores.reshape(n, length, length, *scores.shape[1:])
    return jnp.mean(scores, axis=(0, *range(3, scores.ndim)))


def _spectral_distortion_index_compute(
    preds: Array,
    target: Array,
    p: int = 1,
    reduction: Optional[str] = "elementwise_mean",
) -> Array:
    length = preds.shape[1]
    m1 = _pairwise_band_uqi(target)
    m2 = _pairwise_band_uqi(preds)

    diff = jnp.power(jnp.abs(m1 - m2), p)
    if length == 1:
        output = jnp.power(diff, 1.0 / p)
    else:
        output = jnp.power(1.0 / (length * (length - 1)) * jnp.sum(diff), 1.0 / p)
    return reduce(output, reduction)


def spectral_distortion_index(
    preds: Array,
    target: Array,
    p: int = 1,
    reduction: Optional[str] = "elementwise_mean",
) -> Array:
    """D_lambda (reference :91-…).

    Example:
        >>> import jax.numpy as jnp
        >>> from metrics_tpu.functional import spectral_distortion_index
        >>> import jax
        >>> key1, key2 = jax.random.split(jax.random.PRNGKey(0))
        >>> preds = jax.random.uniform(key1, (2, 3, 32, 32))
        >>> target = preds * 0.75 + jax.random.uniform(key2, (2, 3, 32, 32)) * 0.25
        >>> spectral_distortion_index(preds, target)
        Array(0.00437204, dtype=float32)
    """
    if not isinstance(p, int) or p <= 0:
        raise ValueError(f"Expected `p` to be a positive integer. Got p: {p}.")
    preds, target = _spectral_distortion_index_update(preds, target)
    return _spectral_distortion_index_compute(preds, target, p, reduction)
