"""Spectral Angle Mapper functional.

Reference parity: src/torchmetrics/functional/image/sam.py
(``_sam_update`` :24, ``_sam_compute`` :52, ``spectral_angle_mapper`` :84).
"""

from __future__ import annotations

from typing import Optional, Tuple

import jax.numpy as jnp
from jax import Array

from metrics_tpu.utils.checks import _check_same_shape
from metrics_tpu.utils.distributed import reduce


def _sam_update(preds: Array, target: Array) -> Tuple[Array, Array]:
    preds = jnp.asarray(preds)
    target = jnp.asarray(target)
    if preds.dtype != target.dtype:
        target = target.astype(preds.dtype)
    _check_same_shape(preds, target)
    if preds.ndim != 4:
        raise ValueError(f"Expected `preds` and `target` to have BxCxHxW shape. Got preds: {preds.shape}.")
    if preds.shape[1] <= 1:
        raise ValueError(
            "Expected channel dimension of `preds` and `target` to be larger than 1."
            f" Got preds: {preds.shape[1]}."
        )
    return preds, target


def _sam_compute(preds: Array, target: Array, reduction: Optional[str] = "elementwise_mean") -> Array:
    dot_product = jnp.sum(preds * target, axis=1)
    preds_norm = jnp.linalg.norm(preds, axis=1)
    target_norm = jnp.linalg.norm(target, axis=1)
    sam_score = jnp.arccos(jnp.clip(dot_product / (preds_norm * target_norm), -1, 1))
    return reduce(sam_score, reduction)


def spectral_angle_mapper(preds: Array, target: Array, reduction: Optional[str] = "elementwise_mean") -> Array:
    """Per-pixel spectral angle between channel vectors, reduced (reference :84-…).

    Example:
        >>> import jax.numpy as jnp
        >>> from metrics_tpu.functional import spectral_angle_mapper
        >>> import jax
        >>> key1, key2 = jax.random.split(jax.random.PRNGKey(0))
        >>> preds = jax.random.uniform(key1, (2, 3, 32, 32))
        >>> target = preds * 0.75 + jax.random.uniform(key2, (2, 3, 32, 32)) * 0.25
        >>> spectral_angle_mapper(preds, target)
        Array(0.14725654, dtype=float32)
    """
    preds, target = _sam_update(preds, target)
    return _sam_compute(preds, target, reduction)
