"""Nominal association functionals (reference src/torchmetrics/functional/nominal/)."""

from metrics_tpu.functional.nominal.stats import (
    cramers_v,
    cramers_v_matrix,
    pearsons_contingency_coefficient,
    pearsons_contingency_coefficient_matrix,
    theils_u,
    theils_u_matrix,
    tschuprows_t,
    tschuprows_t_matrix,
)

__all__ = [
    "cramers_v",
    "cramers_v_matrix",
    "pearsons_contingency_coefficient",
    "pearsons_contingency_coefficient_matrix",
    "theils_u",
    "theils_u_matrix",
    "tschuprows_t",
    "tschuprows_t_matrix",
]
