"""Nominal association statistics: Cramér's V, Pearson's contingency, Tschuprow's T,
Theil's U.

Reference parity: src/torchmetrics/functional/nominal/{cramers,pearson,tschuprows,
theils_u}.py — χ²-contingency coefficients over a joint confusion matrix, with the
reference's bias correction and nan handling.
"""

from __future__ import annotations

from typing import Optional

import jax.numpy as jnp
from jax import Array

from metrics_tpu.functional.nominal.utils import (
    _compute_bias_corrected_dims,
    _drop_empty_rows_and_cols,
    _handle_nan_in_data,
    _joint_confusion_matrix,
    _nominal_input_validation,
    _unable_to_compute_warning,
)
from metrics_tpu.utils.checks import _value_check_possible


def _chi2_phi2(confmat: Array):
    """chi-squared statistic and phi2 of a contingency table (shared by all three
    chi2-based coefficients; reference utils.py _compute_chi_squared)."""
    cm = confmat.astype(jnp.float32)
    n = jnp.sum(cm)
    row = jnp.sum(cm, axis=1, keepdims=True)
    col = jnp.sum(cm, axis=0, keepdims=True)
    expected = row @ col / n
    chi2 = jnp.sum(jnp.where(expected > 0, (cm - expected) ** 2 / jnp.where(expected > 0, expected, 1.0), 0.0))
    return chi2, chi2 / n, n


def _num_classes_of(*arrays: Array) -> int:
    return int(max(int(jnp.max(a, initial=0)) for a in arrays)) + 1


def _format_nominal(preds: Array, target: Array, nan_strategy: str, nan_replace_value: Optional[float]):
    preds = jnp.asarray(preds)
    target = jnp.asarray(target)
    if jnp.issubdtype(preds.dtype, jnp.floating) and preds.ndim > 1:
        preds = jnp.argmax(preds, axis=1)
    if jnp.issubdtype(target.dtype, jnp.floating) and target.ndim > 1:
        target = jnp.argmax(target, axis=1)
    preds = preds.astype(jnp.float32)
    target = target.astype(jnp.float32)
    preds, target = _handle_nan_in_data(preds, target, nan_strategy, nan_replace_value)
    return preds.astype(jnp.int32), target.astype(jnp.int32)


def _cramers_v_compute(confmat: Array, bias_correction: bool) -> Array:
    """Reference cramers.py ``_cramers_v_compute``."""
    confmat = _drop_empty_rows_and_cols(confmat)
    _, phi2, n = _chi2_phi2(confmat)
    r, k = confmat.shape
    if bias_correction:
        phi2 = jnp.maximum(0.0, phi2 - (k - 1) * (r - 1) / (n - 1))
        r_c, k_c = _compute_bias_corrected_dims(confmat)
        if _value_check_possible(r_c) and (float(r_c) == 1.0 or float(k_c) == 1.0):
            _unable_to_compute_warning("Cramer's V")
            return jnp.asarray(jnp.nan)
        v = jnp.sqrt(phi2 / jnp.minimum(r_c - 1.0, k_c - 1.0))
    else:
        v = jnp.sqrt(phi2 / min(r - 1, k - 1))
    return jnp.clip(v, 0.0, 1.0)


def cramers_v(
    preds: Array,
    target: Array,
    bias_correction: bool = True,
    nan_strategy: str = "replace",
    nan_replace_value: Optional[float] = 0.0,
) -> Array:
    """Cramér's V (reference functional/nominal/cramers.py).

    Example:
        >>> import jax.numpy as jnp
        >>> from metrics_tpu.functional import cramers_v
        >>> preds = jnp.array([0, 1, 2, 1, 0, 2, 1, 2])
        >>> target = jnp.array([0, 1, 2, 2, 0, 1, 1, 2])
        >>> cramers_v(preds, target)
        Array(0.6146363, dtype=float32)
    """
    _nominal_input_validation(nan_strategy, nan_replace_value)
    preds, target = _format_nominal(preds, target, nan_strategy, nan_replace_value)
    nc = _num_classes_of(preds, target)
    confmat = _joint_confusion_matrix(preds, target, nc, nc)
    return _cramers_v_compute(confmat, bias_correction)


def _pearsons_contingency_coefficient_compute(confmat: Array) -> Array:
    """Reference pearson.py compute."""
    confmat = _drop_empty_rows_and_cols(confmat)
    _, phi2, n = _chi2_phi2(confmat)
    tschuprow = jnp.sqrt(phi2 / (1 + phi2))
    return jnp.clip(tschuprow, 0.0, 1.0)


def pearsons_contingency_coefficient(
    preds: Array,
    target: Array,
    nan_strategy: str = "replace",
    nan_replace_value: Optional[float] = 0.0,
) -> Array:
    """Pearson's contingency coefficient (reference functional/nominal/pearson.py).

    Example:
        >>> import jax.numpy as jnp
        >>> from metrics_tpu.functional import pearsons_contingency_coefficient
        >>> preds = jnp.array([0, 1, 2, 1, 0, 2, 1, 2])
        >>> target = jnp.array([0, 1, 2, 2, 0, 1, 1, 2])
        >>> pearsons_contingency_coefficient(preds, target)
        Array(0.72547626, dtype=float32)
    """
    _nominal_input_validation(nan_strategy, nan_replace_value)
    preds, target = _format_nominal(preds, target, nan_strategy, nan_replace_value)
    nc = _num_classes_of(preds, target)
    confmat = _joint_confusion_matrix(preds, target, nc, nc)
    return _pearsons_contingency_coefficient_compute(confmat)


def _tschuprows_t_compute(confmat: Array, bias_correction: bool) -> Array:
    """Reference tschuprows.py compute."""
    confmat = _drop_empty_rows_and_cols(confmat)
    _, phi2, n = _chi2_phi2(confmat)
    r, k = confmat.shape
    if bias_correction:
        phi2 = jnp.maximum(0.0, phi2 - (k - 1) * (r - 1) / (n - 1))
        r_c, k_c = _compute_bias_corrected_dims(confmat)
        if _value_check_possible(r_c) and (float(r_c) == 1.0 or float(k_c) == 1.0):
            _unable_to_compute_warning("Tschuprow's T")
            return jnp.asarray(jnp.nan)
        t = jnp.sqrt(phi2 / jnp.sqrt((r_c - 1.0) * (k_c - 1.0)))
    else:
        t = jnp.sqrt(phi2 / jnp.sqrt(jnp.asarray(float((r - 1) * (k - 1)))))
    return jnp.clip(t, 0.0, 1.0)


def tschuprows_t(
    preds: Array,
    target: Array,
    bias_correction: bool = True,
    nan_strategy: str = "replace",
    nan_replace_value: Optional[float] = 0.0,
) -> Array:
    """Tschuprow's T (reference functional/nominal/tschuprows.py).

    Example:
        >>> import jax.numpy as jnp
        >>> from metrics_tpu.functional import tschuprows_t
        >>> preds = jnp.array([0, 1, 2, 1, 0, 2, 1, 2])
        >>> target = jnp.array([0, 1, 2, 2, 0, 1, 1, 2])
        >>> tschuprows_t(preds, target)
        Array(0.6146363, dtype=float32)
    """
    _nominal_input_validation(nan_strategy, nan_replace_value)
    preds, target = _format_nominal(preds, target, nan_strategy, nan_replace_value)
    nc = _num_classes_of(preds, target)
    confmat = _joint_confusion_matrix(preds, target, nc, nc)
    return _tschuprows_t_compute(confmat, bias_correction)


def _theils_u_compute(confmat: Array) -> Array:
    """U(X|Y): uncertainty coefficient (reference theils_u.py compute)."""
    confmat = _drop_empty_rows_and_cols(confmat)
    cm = confmat.astype(jnp.float32)
    total = jnp.sum(cm)

    # H(X)
    p_x = jnp.sum(cm, axis=1) / total
    h_x = -jnp.sum(jnp.where(p_x > 0, p_x * jnp.log(jnp.where(p_x > 0, p_x, 1.0)), 0.0))

    # H(X|Y)
    p_y = jnp.sum(cm, axis=0, keepdims=True) / total
    p_xy = cm / total
    h_xy = -jnp.sum(jnp.where(p_xy > 0, p_xy * jnp.log(jnp.where(p_xy > 0, p_xy / p_y, 1.0)), 0.0))

    # zero-entropy X (single observed category): the reference returns 0, not
    # NaN (theils_u.py:99-100) — caught by the round-4 fuzz soak; the where
    # form keeps the branch in-trace
    return jnp.where(h_x == 0.0, jnp.zeros_like(h_x), (h_x - h_xy) / jnp.where(h_x == 0.0, 1.0, h_x))


def theils_u(
    preds: Array,
    target: Array,
    nan_strategy: str = "replace",
    nan_replace_value: Optional[float] = 0.0,
) -> Array:
    """Theil's U (reference functional/nominal/theils_u.py).

    Example:
        >>> import jax.numpy as jnp
        >>> from metrics_tpu.functional import theils_u
        >>> preds = jnp.array([0, 1, 2, 1, 0, 2, 1, 2])
        >>> target = jnp.array([0, 1, 2, 2, 0, 1, 1, 2])
        >>> theils_u(preds, target)
        Array(0.558873, dtype=float32)
    """
    _nominal_input_validation(nan_strategy, nan_replace_value)
    preds, target = _format_nominal(preds, target, nan_strategy, nan_replace_value)
    nc = _num_classes_of(preds, target)
    confmat = _joint_confusion_matrix(preds, target, nc, nc)
    return _theils_u_compute(confmat)


def _matrix(fn, matrix: Array, **kwargs) -> Array:
    """Pairwise column-association matrix (reference *_matrix functions)."""
    num_var = matrix.shape[1]
    out = jnp.ones((num_var, num_var), dtype=jnp.float32)
    for i in range(num_var):
        for j in range(num_var):
            if i == j:
                continue
            val = fn(matrix[:, i], matrix[:, j], **kwargs)
            out = out.at[i, j].set(val)
    return out


def cramers_v_matrix(matrix: Array, bias_correction: bool = True, nan_strategy: str = "replace", nan_replace_value: Optional[float] = 0.0) -> Array:
    """Pairwise column-association matrix of Cramér's V (reference functional/nominal/cramers.py `cramers_v_matrix`).

    Example:
        >>> import jax.numpy as jnp
        >>> from metrics_tpu.functional import cramers_v_matrix
        >>> matrix = jnp.array([[0, 1], [1, 0], [2, 1], [1, 2], [0, 0], [2, 2]])
        >>> cramers_v_matrix(matrix)
        Array([[1., 0.],
               [0., 1.]], dtype=float32)
    """
    out = jnp.ones((matrix.shape[1], matrix.shape[1]), dtype=jnp.float32)
    for i in range(matrix.shape[1]):
        for j in range(i + 1, matrix.shape[1]):
            val = cramers_v(matrix[:, i], matrix[:, j], bias_correction, nan_strategy, nan_replace_value)
            out = out.at[i, j].set(val).at[j, i].set(val)
    return out


def pearsons_contingency_coefficient_matrix(matrix: Array, nan_strategy: str = "replace", nan_replace_value: Optional[float] = 0.0) -> Array:
    """Pairwise column-association matrix of Pearson's contingency coefficient (reference functional/nominal/pearson.py).

    Example:
        >>> import jax.numpy as jnp
        >>> from metrics_tpu.functional import pearsons_contingency_coefficient_matrix
        >>> matrix = jnp.array([[0, 1], [1, 0], [2, 1], [1, 2], [0, 0], [2, 2]])
        >>> pearsons_contingency_coefficient_matrix(matrix)
        Array([[1.        , 0.57735026],
               [0.57735026, 1.        ]], dtype=float32)
    """
    out = jnp.ones((matrix.shape[1], matrix.shape[1]), dtype=jnp.float32)
    for i in range(matrix.shape[1]):
        for j in range(i + 1, matrix.shape[1]):
            val = pearsons_contingency_coefficient(matrix[:, i], matrix[:, j], nan_strategy, nan_replace_value)
            out = out.at[i, j].set(val).at[j, i].set(val)
    return out


def tschuprows_t_matrix(matrix: Array, bias_correction: bool = True, nan_strategy: str = "replace", nan_replace_value: Optional[float] = 0.0) -> Array:
    """Pairwise column-association matrix of Tschuprow's T (reference functional/nominal/tschuprows.py `tschuprows_t_matrix`).

    Example:
        >>> import jax.numpy as jnp
        >>> from metrics_tpu.functional import tschuprows_t_matrix
        >>> matrix = jnp.array([[0, 1], [1, 0], [2, 1], [1, 2], [0, 0], [2, 2]])
        >>> tschuprows_t_matrix(matrix)
        Array([[1., 0.],
               [0., 1.]], dtype=float32)
    """
    out = jnp.ones((matrix.shape[1], matrix.shape[1]), dtype=jnp.float32)
    for i in range(matrix.shape[1]):
        for j in range(i + 1, matrix.shape[1]):
            val = tschuprows_t(matrix[:, i], matrix[:, j], bias_correction, nan_strategy, nan_replace_value)
            out = out.at[i, j].set(val).at[j, i].set(val)
    return out


def theils_u_matrix(matrix: Array, nan_strategy: str = "replace", nan_replace_value: Optional[float] = 0.0) -> Array:
    """Directional column-association matrix of Theil's U (reference functional/nominal/theils_u.py `theils_u_matrix`).

    Example:
        >>> import jax.numpy as jnp
        >>> from metrics_tpu.functional import theils_u_matrix
        >>> matrix = jnp.array([[0, 1], [1, 0], [2, 1], [1, 2], [0, 0], [2, 2]])
        >>> theils_u_matrix(matrix)
        Array([[1.        , 0.36907026],
               [0.36907026, 1.        ]], dtype=float32)
    """
    return _matrix(theils_u, matrix, nan_strategy=nan_strategy, nan_replace_value=nan_replace_value)
