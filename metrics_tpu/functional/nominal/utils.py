"""Shared helpers for nominal association metrics.

Reference parity: src/torchmetrics/functional/nominal/utils.py — χ² statistic, bias
corrections, nan handling, confusion-matrix construction for label pairs.
"""

from __future__ import annotations

from typing import Optional, Tuple

import jax.numpy as jnp
from jax import Array

from metrics_tpu.utils.prints import rank_zero_warn


def _nominal_input_validation(nan_strategy: str, nan_replace_value: Optional[float]) -> None:
    if nan_strategy not in ["replace", "drop"]:
        raise ValueError(
            f"Argument `nan_strategy` is expected to be one of `['replace', 'drop']`, but got {nan_strategy}"
        )
    if nan_strategy == "replace" and not isinstance(nan_replace_value, (int, float)):
        raise ValueError(
            "Argument `nan_replace` is expected to be of a type `int` or `float` when `nan_strategy = 'replace`, "
            f"but got {nan_replace_value}"
        )


def _handle_nan_in_data(
    preds: Array,
    target: Array,
    nan_strategy: str = "replace",
    nan_replace_value: Optional[float] = 0.0,
) -> Tuple[Array, Array]:
    """Replace or drop NaNs (reference utils.py). Host-side (value-dependent for drop)."""
    if nan_strategy == "replace":
        return (
            jnp.where(jnp.isnan(preds), nan_replace_value, preds),
            jnp.where(jnp.isnan(target), nan_replace_value, target),
        )
    keep = ~(jnp.isnan(preds) | jnp.isnan(target))
    return preds[keep], target[keep]


def _compute_bias_corrected_dims(confmat: Array) -> Tuple[Array, Array]:
    """Bias-corrected numbers of rows/cols (reference utils.py)."""
    confmat = confmat.astype(jnp.float32)
    n = jnp.sum(confmat)
    r, k = confmat.shape
    r_corrected = r - (r - 1) ** 2 / (n - 1)
    k_corrected = k - (k - 1) ** 2 / (n - 1)
    return jnp.asarray(r_corrected), jnp.asarray(k_corrected)


def _drop_empty_rows_and_cols(confmat: Array) -> Array:
    """Drop all-zero rows/cols (reference utils.py) — host-side, data-dependent shape."""
    import numpy as np

    cm = np.asarray(confmat)
    cm = cm[cm.sum(1) != 0][:, cm.sum(0) != 0]
    return jnp.asarray(cm)


def _unable_to_compute_warning(metric: str) -> None:
    rank_zero_warn(
        f"Unable to compute {metric} because the data does not allow it. Returning NaN.",
        UserWarning,
    )


def _joint_confusion_matrix(preds: Array, target: Array, num_classes_preds: int, num_classes_target: int) -> Array:
    """(Cx, Cy) contingency counts, rows = preds categories.

    Routed through the kernel plane's pair count
    (``metrics_tpu/kernels/confmat.py``): on accelerators the bf16 one-hot MXU
    matmul (0/1 products exact; f32 accumulation exact under the shared
    ``matmul_eligible`` bound — the scatter measured 33x slower on a v5e), on
    TPU the Pallas fused streaming kernel where selected, on the host backend
    a bincount scatter-add. Out-of-range category values — reachable e.g. via
    raw integer labels containing -1, or a negative ``nan_replace_value`` —
    are DROPPED by every lowering: an out-of-range one-hot row is all-zero,
    and the scatter routes them to a trimmed overflow bucket (``jnp.bincount``
    would otherwise CLIP a negative key to bin 0)."""
    from metrics_tpu.kernels.confmat import pair_count

    p = preds.reshape(-1).astype(jnp.int32)
    t = target.reshape(-1).astype(jnp.int32)
    return pair_count(p, t, num_classes_preds, num_classes_target)
