"""Shared helpers for nominal association metrics.

Reference parity: src/torchmetrics/functional/nominal/utils.py — χ² statistic, bias
corrections, nan handling, confusion-matrix construction for label pairs.
"""

from __future__ import annotations

from typing import Optional, Tuple

import jax.numpy as jnp
from jax import Array

from metrics_tpu.utils.prints import rank_zero_warn


def _nominal_input_validation(nan_strategy: str, nan_replace_value: Optional[float]) -> None:
    if nan_strategy not in ["replace", "drop"]:
        raise ValueError(
            f"Argument `nan_strategy` is expected to be one of `['replace', 'drop']`, but got {nan_strategy}"
        )
    if nan_strategy == "replace" and not isinstance(nan_replace_value, (int, float)):
        raise ValueError(
            "Argument `nan_replace` is expected to be of a type `int` or `float` when `nan_strategy = 'replace`, "
            f"but got {nan_replace_value}"
        )


def _handle_nan_in_data(
    preds: Array,
    target: Array,
    nan_strategy: str = "replace",
    nan_replace_value: Optional[float] = 0.0,
) -> Tuple[Array, Array]:
    """Replace or drop NaNs (reference utils.py). Host-side (value-dependent for drop)."""
    if nan_strategy == "replace":
        return (
            jnp.where(jnp.isnan(preds), nan_replace_value, preds),
            jnp.where(jnp.isnan(target), nan_replace_value, target),
        )
    keep = ~(jnp.isnan(preds) | jnp.isnan(target))
    return preds[keep], target[keep]


def _compute_bias_corrected_dims(confmat: Array) -> Tuple[Array, Array]:
    """Bias-corrected numbers of rows/cols (reference utils.py)."""
    confmat = confmat.astype(jnp.float32)
    n = jnp.sum(confmat)
    r, k = confmat.shape
    r_corrected = r - (r - 1) ** 2 / (n - 1)
    k_corrected = k - (k - 1) ** 2 / (n - 1)
    return jnp.asarray(r_corrected), jnp.asarray(k_corrected)


def _drop_empty_rows_and_cols(confmat: Array) -> Array:
    """Drop all-zero rows/cols (reference utils.py) — host-side, data-dependent shape."""
    import numpy as np

    cm = np.asarray(confmat)
    cm = cm[cm.sum(1) != 0][:, cm.sum(0) != 0]
    return jnp.asarray(cm)


def _unable_to_compute_warning(metric: str) -> None:
    rank_zero_warn(
        f"Unable to compute {metric} because the data does not allow it. Returning NaN.",
        UserWarning,
    )


def _joint_confusion_matrix(preds: Array, target: Array, num_classes_preds: int, num_classes_target: int) -> Array:
    """(Cx, Cy) contingency counts, rows = preds categories.

    Two value-identical lowerings, same design as the classification confusion
    matrix (confusion_matrix.py module docstring): on accelerators a bf16
    one-hot MXU matmul (0/1 products exact; f32 accumulation exact under the
    shared `_matmul_lowering_eligible` bound — the scatter measured 33x slower
    on a v5e), on the host backend a bincount scatter-add. Out-of-range
    category values — reachable e.g. via raw integer labels containing -1, or
    a negative ``nan_replace_value`` — are DROPPED by both: an out-of-range
    one-hot row is all-zero, and the scatter routes them to a trimmed overflow
    bucket (``jnp.bincount`` would otherwise CLIP a negative key to bin 0)."""
    import jax

    from metrics_tpu.functional.classification.confusion_matrix import (
        _matmul_lowering_eligible,
        _onehot_count_matmul,
    )

    p = preds.reshape(-1).astype(jnp.int32)
    t = target.reshape(-1).astype(jnp.int32)
    if jax.default_backend() != "cpu" and _matmul_lowering_eligible(
        p.size, max(num_classes_preds, num_classes_target)
    ):
        return _onehot_count_matmul(p, t, num_classes_preds, num_classes_target)
    size = num_classes_preds * num_classes_target
    in_range = (p >= 0) & (p < num_classes_preds) & (t >= 0) & (t < num_classes_target)
    mapping = jnp.where(in_range, p * num_classes_target + t, size)
    return jnp.bincount(mapping, length=size + 1)[:size].reshape(
        num_classes_preds, num_classes_target
    )
