"""Character error rate (reference src/torchmetrics/functional/text/cer.py)."""

from __future__ import annotations

from typing import List, Tuple, Union

import jax.numpy as jnp
from jax import Array

from metrics_tpu.functional.text.helper import _edit_distances_batched


def _cer_update(preds: Union[str, List[str]], target: Union[str, List[str]]) -> Tuple[Array, Array]:
    """Sum character edit operations and reference char counts (reference cer.py:23-49)."""
    if isinstance(preds, str):
        preds = [preds]
    if isinstance(target, str):
        target = [target]
    pairs = [(list(pred), list(tgt)) for pred, tgt in zip(preds, target)]
    errors = int(_edit_distances_batched(pairs).sum())
    total = sum(len(tgt) for _, tgt in pairs)
    return jnp.asarray(errors, jnp.float32), jnp.asarray(total, jnp.float32)


def _cer_compute(errors: Array, total: Array) -> Array:
    return errors / total


def char_error_rate(preds: Union[str, List[str]], target: Union[str, List[str]]) -> Array:
    """Character error rate of transcriptions vs references (reference cer.py:64-83).

    Example:
        >>> preds = ["this is the prediction", "there is an other sample"]
        >>> target = ["this is the reference", "there is another one"]
        >>> char_error_rate(preds=preds, target=target)  # doctest: +SKIP
        Array(0.3414634, dtype=float32)
    """
    errors, total = _cer_update(preds, target)
    return _cer_compute(errors, total)
