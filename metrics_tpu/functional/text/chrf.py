"""chrF / chrF++ score (reference src/torchmetrics/functional/text/chrf.py).

TPU-first redesign of the state layout: the reference keeps 4+2 *dicts of scalars*
keyed by n-gram order (chrf.py:48-78); here each statistic family is ONE fixed-shape
``(n_char_order,)`` / ``(n_word_order,)`` vector, so the whole metric state is six
psum-able arrays and the compute is vectorized jnp math instead of per-order Python.
"""

from __future__ import annotations

from collections import Counter
from typing import List, Optional, Sequence, Tuple, Union

import jax.numpy as jnp
import numpy as np
from jax import Array

from metrics_tpu.functional.text.helper import _validate_inputs

_EPS_SMOOTHING = 1e-16
# punctuation set used by the official chrF implementation
_PUNCTUATIONS = set("!\"#$%&'()*+,-./:;<=>?@[\\]^_`{|}~")


def _get_characters(sentence: str, whitespace: bool) -> List[str]:
    if whitespace:
        return list(sentence)
    return list(sentence.strip().replace(" ", ""))


def _separate_word_and_punctuation(word: str) -> List[str]:
    """Split leading/trailing punctuation off a word (official chrF behavior)."""
    if len(word) == 1:
        return [word]
    if word[-1] in _PUNCTUATIONS:
        return [word[:-1], word[-1]]
    if word[0] in _PUNCTUATIONS:
        return [word[0], word[1:]]
    return [word]


def _get_words_and_punctuation(sentence: str) -> List[str]:
    return sum((_separate_word_and_punctuation(word) for word in sentence.strip().split()), [])


def _ngram_counts(tokens: List[str], n_gram_order: int) -> List[Counter]:
    """Counter per order 1..n_gram_order."""
    counters = []
    for n in range(1, n_gram_order + 1):
        counters.append(Counter(tuple(tokens[i : i + n]) for i in range(len(tokens) - n + 1)))
    return counters


def _sentence_counts(
    sentence: str, n_char_order: int, n_word_order: int, lowercase: bool, whitespace: bool
) -> Tuple[List[Counter], List[Counter], np.ndarray, np.ndarray]:
    if lowercase:
        sentence = sentence.lower()
    char_counts = _ngram_counts(_get_characters(sentence, whitespace), n_char_order)
    word_counts = _ngram_counts(_get_words_and_punctuation(sentence), n_word_order)
    char_totals = np.array([sum(c.values()) for c in char_counts], dtype=np.float64)
    word_totals = np.array([sum(c.values()) for c in word_counts], dtype=np.float64)
    return char_counts, word_counts, char_totals, word_totals


def _count_matches(hyp_counts: List[Counter], ref_counts: List[Counter]) -> np.ndarray:
    """Per-order clipped match counts (reference chrf.py:193-214)."""
    return np.array(
        [sum((h & r).values()) for h, r in zip(hyp_counts, ref_counts)],
        dtype=np.float64,
    )


def _fscore_from_vectors(
    matching_char: np.ndarray,
    matching_word: np.ndarray,
    hyp_char: np.ndarray,
    hyp_word: np.ndarray,
    ref_char: np.ndarray,
    ref_word: np.ndarray,
    n_order: float,
    beta: float,
) -> float:
    """Vectorized chrF f-score over all orders (reference chrf.py:232-286)."""
    matching = np.concatenate([matching_char, matching_word])
    hyp = np.concatenate([hyp_char, hyp_word])
    ref = np.concatenate([ref_char, ref_word])
    with np.errstate(divide="ignore", invalid="ignore"):
        precision = np.where(hyp > 0, matching / np.maximum(hyp, 1e-30), 0.0)
        recall = np.where(ref > 0, matching / np.maximum(ref, 1e-30), 0.0)
    denominator = np.maximum(beta**2 * precision + recall, _EPS_SMOOTHING)
    f_score = (1 + beta**2) * precision * recall / denominator
    return float(f_score.sum() / n_order)


def _chrf_score_update(
    preds: Union[str, Sequence[str]],
    target: Union[Sequence[str], Sequence[Sequence[str]]],
    n_char_order: int,
    n_word_order: int,
    beta: float,
    lowercase: bool,
    whitespace: bool,
) -> Tuple[np.ndarray, np.ndarray, np.ndarray, np.ndarray, np.ndarray, np.ndarray, List[float]]:
    """Per-batch corpus statistics as six fixed vectors + sentence scores.

    For each hypothesis the best-matching reference (by sentence-level f-score) is
    selected and its statistics accumulated (reference chrf.py:289-481).
    """
    target_corpus, preds_list = _validate_inputs(target, preds)
    n_order = float(n_char_order + n_word_order)

    total_preds_char = np.zeros(n_char_order)
    total_preds_word = np.zeros(n_word_order)
    total_target_char = np.zeros(n_char_order)
    total_target_word = np.zeros(n_word_order)
    total_matching_char = np.zeros(n_char_order)
    total_matching_word = np.zeros(n_word_order)
    sentence_scores: List[float] = []

    for pred, targets in zip(preds_list, target_corpus):
        pred_char_counts, pred_word_counts, pred_char_totals, pred_word_totals = _sentence_counts(
            pred, n_char_order, n_word_order, lowercase, whitespace
        )
        total_preds_char += pred_char_totals
        total_preds_word += pred_word_totals

        best_f_score = 0.0
        best_matching_char = np.zeros(n_char_order)
        best_matching_word = np.zeros(n_word_order)
        best_target_char = np.zeros(n_char_order)
        best_target_word = np.zeros(n_word_order)

        for tgt in targets:
            tgt_char_counts, tgt_word_counts, tgt_char_totals, tgt_word_totals = _sentence_counts(
                tgt, n_char_order, n_word_order, lowercase, whitespace
            )
            matching_char = _count_matches(pred_char_counts, tgt_char_counts)
            matching_word = _count_matches(pred_word_counts, tgt_word_counts)
            f_score = _fscore_from_vectors(
                matching_char, matching_word, pred_char_totals, pred_word_totals,
                tgt_char_totals, tgt_word_totals, n_order, beta,
            )
            if f_score > best_f_score:
                best_f_score = f_score
                best_matching_char, best_matching_word = matching_char, matching_word
                best_target_char, best_target_word = tgt_char_totals, tgt_word_totals

        sentence_scores.append(best_f_score)
        total_target_char += best_target_char
        total_target_word += best_target_word
        total_matching_char += best_matching_char
        total_matching_word += best_matching_word

    return (
        total_preds_char,
        total_preds_word,
        total_target_char,
        total_target_word,
        total_matching_char,
        total_matching_word,
        sentence_scores,
    )


def _chrf_score_compute(
    total_preds_char: Array,
    total_preds_word: Array,
    total_target_char: Array,
    total_target_word: Array,
    total_matching_char: Array,
    total_matching_word: Array,
    n_order: float,
    beta: float,
) -> Array:
    """Corpus-level chrF from accumulated vectors; jittable jnp math."""
    matching = jnp.concatenate([jnp.atleast_1d(total_matching_char), jnp.atleast_1d(total_matching_word)])
    hyp = jnp.concatenate([jnp.atleast_1d(total_preds_char), jnp.atleast_1d(total_preds_word)])
    ref = jnp.concatenate([jnp.atleast_1d(total_target_char), jnp.atleast_1d(total_target_word)])
    precision = jnp.where(hyp > 0, matching / jnp.maximum(hyp, 1e-30), 0.0)
    recall = jnp.where(ref > 0, matching / jnp.maximum(ref, 1e-30), 0.0)
    denominator = jnp.maximum(beta**2 * precision + recall, _EPS_SMOOTHING)
    f_score = (1 + beta**2) * precision * recall / denominator
    return (jnp.sum(f_score) / n_order).astype(jnp.float32)


def chrf_score(
    preds: Union[str, Sequence[str]],
    target: Sequence[Union[str, Sequence[str]]],
    n_char_order: int = 6,
    n_word_order: int = 2,
    beta: float = 2.0,
    lowercase: bool = False,
    whitespace: bool = False,
    return_sentence_level_score: bool = False,
) -> Union[Array, Tuple[Array, Array]]:
    """chrF/chrF++ score of machine-translated text (reference chrf.py:523-635).

    ``n_word_order=0`` gives the original chrF; the 6/2 default is official chrF++.

    Example:
        >>> preds = ['the cat is on the mat']
        >>> target = [['there is a cat on the mat', 'a cat is on the mat']]
        >>> float(chrf_score(preds, target))  # doctest: +ELLIPSIS
        0.8640...
    """
    if not isinstance(n_char_order, int) or n_char_order < 1:
        raise ValueError("Expected argument `n_char_order` to be an integer greater than or equal to 1.")
    if not isinstance(n_word_order, int) or n_word_order < 0:
        raise ValueError("Expected argument `n_word_order` to be an integer greater than or equal to 0.")
    if beta < 0:
        raise ValueError("Expected argument `beta` to be greater than 0.")

    n_order = float(n_char_order + n_word_order)
    (
        total_preds_char,
        total_preds_word,
        total_target_char,
        total_target_word,
        total_matching_char,
        total_matching_word,
        sentence_scores,
    ) = _chrf_score_update(preds, target, n_char_order, n_word_order, beta, lowercase, whitespace)

    score = _chrf_score_compute(
        jnp.asarray(total_preds_char), jnp.asarray(total_preds_word),
        jnp.asarray(total_target_char), jnp.asarray(total_target_word),
        jnp.asarray(total_matching_char), jnp.asarray(total_matching_word),
        n_order, beta,
    )
    if return_sentence_level_score:
        return score, jnp.asarray(sentence_scores, dtype=jnp.float32)
    return score
