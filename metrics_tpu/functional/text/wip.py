"""Word information preserved (reference src/torchmetrics/functional/text/wip.py)."""

from __future__ import annotations

from typing import List, Tuple, Union

import jax.numpy as jnp
from jax import Array

from metrics_tpu.functional.text.helper import _edit_distances_batched


def _wip_update(preds: Union[str, List[str]], target: Union[str, List[str]]) -> Tuple[Array, Array, Array]:
    """Accumulate (edit_distance - max_len) = -hits, ref and pred word totals.

    Reference wip.py:22-55; same negated-hit-count trick as WIL — see _wil_update.
    """
    if isinstance(preds, str):
        preds = [preds]
    if isinstance(target, str):
        target = [target]
    pairs = [(pred.split(), tgt.split()) for pred, tgt in zip(preds, target)]
    errors = int(_edit_distances_batched(pairs).sum())
    target_total = sum(len(tgt) for _, tgt in pairs)
    preds_total = sum(len(pred) for pred, _ in pairs)
    total = sum(max(len(tgt), len(pred)) for pred, tgt in pairs)
    return (
        jnp.asarray(errors - total, jnp.float32),
        jnp.asarray(target_total, jnp.float32),
        jnp.asarray(preds_total, jnp.float32),
    )


def _wip_compute(errors: Array, target_total: Array, preds_total: Array) -> Array:
    return (errors / target_total) * (errors / preds_total)


def word_information_preserved(preds: Union[str, List[str]], target: Union[str, List[str]]) -> Array:
    """Word information preserved of transcriptions vs references (reference wip.py:58-92).

    Example:
        >>> preds = ["this is the prediction", "there is an other sample"]
        >>> target = ["this is the reference", "there is another one"]
        >>> word_information_preserved(preds, target)  # doctest: +SKIP
        Array(0.3472222, dtype=float32)
    """
    errors, target_total, preds_total = _wip_update(preds, target)
    return _wip_compute(errors, target_total, preds_total)
