"""ROUGE score (reference src/torchmetrics/functional/text/rouge.py).

ROUGE-N via clipped n-gram overlap, ROUGE-L via LCS, ROUGE-LSum via union-LCS over
sentence splits — following the official Lin (2004) definitions and the
google-research ``rouge_score`` package behavior. Per-sentence scores are
accumulated as ragged "cat" states (means at compute), matching the reference's
list-state design (text/rouge.py:135).

Provenance note (same policy as ter.py's): ROUGE is a protocol metric — the
helper structure here (normalizer regex, clipped-count n-gram loop, LCS table,
union-LCS for Lsum) deliberately mirrors the reference's decomposition
(reference rouge.py:83-200, itself transcribing the rouge_score package) so
that every step stays auditable against the official scorer; per-function
reference line numbers are cited below. The numerics that differ are redesigns:
the LCS row recurrence runs over numpy int64 rows (no tensor alloc churn) and
sentence splitting falls back to a vendored deterministic splitter (below)
instead of raising when nltk punkt data is absent — the reference refuses to
compute ROUGE-Lsum offline (reference rouge.py:52-77); here punkt is used when
available and the fallback handles the common abbreviation classes punkt
handles (title/latin abbreviations, initials, decimals, ellipses).
"""

from __future__ import annotations

import re
from collections import Counter
from typing import Any, Callable, Dict, List, Optional, Sequence, Tuple, Union

import jax.numpy as jnp
import numpy as np
from jax import Array

from metrics_tpu.utils.imports import _NLTK_AVAILABLE
from metrics_tpu.utils.prints import rank_zero_warn

ALLOWED_ROUGE_KEYS: Dict[str, Union[int, str]] = {
    "rouge1": 1,
    "rouge2": 2,
    "rouge3": 3,
    "rouge4": 4,
    "rouge5": 5,
    "rouge6": 6,
    "rouge7": 7,
    "rouge8": 8,
    "rouge9": 9,
    "rougeL": "L",
    "rougeLsum": "Lsum",
}
ALLOWED_ACCUMULATE_VALUES = ("avg", "best")


# Abbreviations whose trailing period does not end a sentence (lowercased, no
# final dot). Covers the classes the punkt English model resolves: titles,
# latin/citation shorthand, month abbreviations, corporate suffixes. Entries
# that collide with ordinary English words ("no", "sat", "est", …) are left
# out on purpose — a false non-split on "He said no." costs more than a rare
# false split on "no. 5", and a simple splitter cannot use context to decide.
_NON_TERMINAL_ABBREVS = frozenset(
    "mr mrs ms dr prof rev gen sen rep jr sr vs etc al eg ie cf fig figs nos vol vols"
    " pp approx dept inc ltd corp jan feb apr jun jul aug sept oct nov dec".split()
)
_SENT_BOUNDARY = re.compile(r"[.!?]+[\"'”’)\]]*\s+")


def _regex_sentence_split(text: str) -> List[str]:
    """Deterministic sentence splitter (vendored punkt stand-in).

    A candidate boundary is a run of ``.!?`` (plus closing quotes/brackets)
    followed by whitespace. It is REJECTED when the preceding word is a known
    non-terminal abbreviation, a single-letter initial ("J. Smith"), part of a
    dotted acronym ("U.S.A."), or when the period sits inside a number
    ("3.14"); otherwise the text splits after the boundary punctuation.
    """
    text = text.strip()
    if not text:
        return []
    sentences: List[str] = []
    start = 0
    for m in _SENT_BOUNDARY.finditer(text):
        prefix = text[start : m.end()].rstrip()
        word = prefix.rsplit(None, 1)[-1] if prefix else ""
        if word.endswith("."):
            bare = word.rstrip(".").rstrip("\"'”’)]")
            core = bare.lstrip("(\"'“‘[")
            if core.lower() in _NON_TERMINAL_ABBREVS:
                continue  # "Dr. Smith arrived."
            if len(core) == 1 and core.isalpha() and core.isupper() and core != "I":
                # initials: "J. Smith". Lowercase single letters and the pronoun
                # "I" are real sentence ends far more often than initials
                # ("So did I. Then we left."), so they DO split.
                continue
            if "." in core:
                continue  # dotted acronyms: "U.S.A. is large" (punkt keeps these)
            if core.replace(",", "").isdigit() and m.end() < len(text) and text[m.end()].isdigit():
                continue  # number split across whitespace — not a boundary
        sentences.append(text[start : m.end()].strip())
        start = m.end()
    tail = text[start:].strip()
    if tail:
        sentences.append(tail)
    return sentences


def _split_sentence(x: str) -> Sequence[str]:
    """Sentence-split for ROUGE-Lsum (nltk punkt when available; vendored
    deterministic splitter otherwise — the reference raises offline,
    reference rouge.py:52-77)."""
    x = re.sub("<n>", "", x)  # strip the "<n>" newline token Pegasus outputs emit
    if _NLTK_AVAILABLE:
        import nltk

        try:
            return nltk.sent_tokenize(x)
        except LookupError:
            rank_zero_warn(
                "`nltk` punkt data is not available on disk; ROUGE-Lsum is using the vendored"
                " deterministic sentence splitter (handles titles, initials, dotted acronyms and"
                " decimals). Download punkt (`nltk.download('punkt')`) for bit-exact parity with"
                " the official rouge_score package on unusual abbreviation patterns.",
                UserWarning,
            )
    return _regex_sentence_split(x)


def _compute_metrics(hits_or_lcs: int, pred_len: int, target_len: int) -> Dict[str, float]:
    """Precision/recall/F1 from a hit count (reference rouge.py:83-98)."""
    precision = hits_or_lcs / pred_len
    recall = hits_or_lcs / target_len
    if precision == recall == 0.0:
        return {"precision": 0.0, "recall": 0.0, "fmeasure": 0.0}
    fmeasure = 2 * precision * recall / (precision + recall)
    return {"precision": precision, "recall": recall, "fmeasure": fmeasure}


def _lcs_table(pred_tokens: Sequence[str], target_tokens: Sequence[str]) -> np.ndarray:
    """Full LCS DP table, vectorized row recurrence where possible."""
    n, m = len(target_tokens), len(pred_tokens)
    table = np.zeros((n + 1, m + 1), dtype=np.int64)
    pred_arr = np.array(pred_tokens, dtype=object)
    for i in range(1, n + 1):
        match = pred_arr == target_tokens[i - 1]
        row = table[i]
        prev = table[i - 1]
        # LCS row still has a strict left-to-right dependency through the max —
        # keep the scalar inner loop but over numpy int64 (no tensor alloc churn).
        for j in range(1, m + 1):
            row[j] = prev[j - 1] + 1 if match[j - 1] else max(prev[j], row[j - 1])
    return table


def _lcs(pred_tokens: Sequence[str], target_tokens: Sequence[str]) -> int:
    return int(_lcs_table(pred_tokens, target_tokens)[-1, -1])


def _backtracked_lcs(
    lcs_table: np.ndarray, pred_tokens: Sequence[str], target_tokens: Sequence[str]
) -> Sequence[int]:
    """Indices (into target) of one LCS, via table backtracking (rouge.py:122-144)."""
    i = len(pred_tokens)
    j = len(target_tokens)
    backtracked: List[int] = []
    while i > 0 and j > 0:
        if pred_tokens[i - 1] == target_tokens[j - 1]:
            backtracked.insert(0, j - 1)
            i -= 1
            j -= 1
        elif lcs_table[j][i - 1] > lcs_table[j - 1][i]:
            i -= 1
        else:
            j -= 1
    return backtracked


def _union_lcs(pred_tokens_list: Sequence[Sequence[str]], target_tokens: Sequence[str]) -> Sequence[str]:
    """Union-LCS of a target sentence against all prediction sentences (rouge.py:147-169)."""

    def lcs_ind(pred_tokens: Sequence[str]) -> Sequence[int]:
        return _backtracked_lcs(_lcs_table(pred_tokens, target_tokens), pred_tokens, target_tokens)

    indices = sorted(set().union(*(lcs_ind(pred_tokens) for pred_tokens in pred_tokens_list)))
    return [target_tokens[i] for i in indices]


def _normalize_and_tokenize_text(
    text: str,
    stemmer: Optional[Any] = None,
    normalizer: Optional[Callable[[str], str]] = None,
    tokenizer: Optional[Callable[[str], Sequence[str]]] = None,
) -> Sequence[str]:
    """Lowercase-alnum normalization + whitespace split + optional Porter stemming."""
    text = normalizer(text) if callable(normalizer) else re.sub(r"[^a-z0-9]+", " ", text.lower())
    tokens = tokenizer(text) if callable(tokenizer) else re.split(r"\s+", text)
    if stemmer:
        # Only stem words longer than 3 characters (rouge_score behavior).
        tokens = [stemmer.stem(x) if len(x) > 3 else x for x in tokens]
    return [x for x in tokens if (isinstance(x, str) and len(x) > 0)]


def _rouge_n_score(pred: Sequence[str], target: Sequence[str], n_gram: int) -> Dict[str, float]:
    """ROUGE-N from clipped n-gram hits (reference rouge.py:209-231)."""

    def _create_ngrams(tokens: Sequence[str], n: int) -> Counter:
        return Counter(tuple(tokens[i : i + n]) for i in range(len(tokens) - n + 1))

    pred_ngrams, target_ngrams = _create_ngrams(pred, n_gram), _create_ngrams(target, n_gram)
    pred_len, target_len = sum(pred_ngrams.values()), sum(target_ngrams.values())
    if 0 in (pred_len, target_len):
        return {"precision": 0.0, "recall": 0.0, "fmeasure": 0.0}

    hits = sum((pred_ngrams & target_ngrams).values())
    return _compute_metrics(hits, max(pred_len, 1), max(target_len, 1))


def _rouge_l_score(pred: Sequence[str], target: Sequence[str]) -> Dict[str, float]:
    """ROUGE-L from the LCS length (reference rouge.py:234-246)."""
    pred_len, target_len = len(pred), len(target)
    if 0 in (pred_len, target_len):
        return {"precision": 0.0, "recall": 0.0, "fmeasure": 0.0}
    lcs = _lcs(pred, target)
    return _compute_metrics(lcs, pred_len, target_len)


def _rouge_lsum_score(pred: Sequence[Sequence[str]], target: Sequence[Sequence[str]]) -> Dict[str, float]:
    """ROUGE-LSum from union-LCS over sentence splits (reference rouge.py:249-286)."""
    pred_len = sum(map(len, pred))
    target_len = sum(map(len, target))
    if 0 in (pred_len, target_len):
        return {"precision": 0.0, "recall": 0.0, "fmeasure": 0.0}

    def _get_token_counts(sentences: Sequence[Sequence[str]]) -> Counter:
        ngrams: Counter = Counter()
        for sentence in sentences:
            ngrams.update(sentence)
        return ngrams

    pred_tokens_count = _get_token_counts(pred)
    target_tokens_count = _get_token_counts(target)

    hits = 0
    for tgt in target:
        lcs = _union_lcs(pred, tgt)
        for token in lcs:
            if pred_tokens_count[token] > 0 and target_tokens_count[token] > 0:
                hits += 1
                pred_tokens_count[token] -= 1
                target_tokens_count[token] -= 1

    return _compute_metrics(hits, pred_len, target_len)


def _rouge_score_update(
    preds: Sequence[str],
    target: Sequence[Sequence[str]],
    rouge_keys_values: List[Union[int, str]],
    accumulate: str,
    stemmer: Optional[Any] = None,
    normalizer: Optional[Callable[[str], str]] = None,
    tokenizer: Optional[Callable[[str], Sequence[str]]] = None,
) -> Dict[Union[int, str], List[Dict[str, float]]]:
    """Per-sample scores with multi-reference 'best'/'avg' accumulation (rouge.py:289-400)."""
    results: Dict[Union[int, str], List[Dict[str, float]]] = {rouge_key: [] for rouge_key in rouge_keys_values}

    for pred_raw, target_raw in zip(preds, target):
        list_results: List[Dict[Union[int, str], Dict[str, float]]] = []
        pred = _normalize_and_tokenize_text(pred_raw, stemmer, normalizer, tokenizer)
        if "Lsum" in rouge_keys_values:
            pred_lsum = [
                _normalize_and_tokenize_text(pred_sentence, stemmer, normalizer, tokenizer)
                for pred_sentence in _split_sentence(pred_raw)
            ]

        for target_raw_inner in target_raw:
            tgt = _normalize_and_tokenize_text(target_raw_inner, stemmer, normalizer, tokenizer)
            if "Lsum" in rouge_keys_values:
                target_lsum = [
                    _normalize_and_tokenize_text(tgt_sentence, stemmer, normalizer, tokenizer)
                    for tgt_sentence in _split_sentence(target_raw_inner)
                ]

            result_inner: Dict[Union[int, str], Dict[str, float]] = {}
            for rouge_key in rouge_keys_values:
                if isinstance(rouge_key, int):
                    score = _rouge_n_score(pred, tgt, rouge_key)
                elif rouge_key == "L":
                    score = _rouge_l_score(pred, tgt)
                else:  # "Lsum"
                    score = _rouge_lsum_score(pred_lsum, target_lsum)
                result_inner[rouge_key] = score
            list_results.append(result_inner)

        if accumulate == "best":
            key_curr = rouge_keys_values[0]
            all_fmeasure = [v[key_curr]["fmeasure"] for v in list_results]
            highest_idx = int(np.argmax(all_fmeasure))
            for rouge_key in rouge_keys_values:
                results[rouge_key].append(list_results[highest_idx][rouge_key])
        elif accumulate == "avg":
            for rouge_key in rouge_keys_values:
                scores = [r[rouge_key] for r in list_results]
                results[rouge_key].append(
                    {tp: float(np.mean([s[tp] for s in scores])) for tp in ("precision", "recall", "fmeasure")}
                )

    return results


def _rouge_score_compute(sentence_results: Dict[str, List[Array]]) -> Dict[str, Array]:
    """Mean over per-sample scores (reference rouge.py:403-417)."""
    return {rouge_key: jnp.mean(jnp.asarray(scores, jnp.float32)) for rouge_key, scores in sentence_results.items()}


def rouge_score(
    preds: Union[str, Sequence[str]],
    target: Union[str, Sequence[str], Sequence[Sequence[str]]],
    accumulate: str = "best",
    use_stemmer: bool = False,
    normalizer: Optional[Callable[[str], str]] = None,
    tokenizer: Optional[Callable[[str], Sequence[str]]] = None,
    rouge_keys: Union[str, Tuple[str, ...]] = ("rouge1", "rouge2", "rougeL", "rougeLsum"),
) -> Dict[str, Array]:
    """ROUGE score for automatic summarization (reference rouge.py:420-526).

    Example:
        >>> preds = "My name is John"
        >>> target = "Is your name John"
        >>> score = rouge_score(preds, target)
        >>> round(float(score["rouge1_fmeasure"]), 4)
        0.75
    """
    if use_stemmer and not _NLTK_AVAILABLE:
        raise ModuleNotFoundError("Stemmer requires that `nltk` is installed. Use `pip install nltk`.")
    stemmer = None
    if use_stemmer:
        import nltk

        stemmer = nltk.stem.porter.PorterStemmer()

    if not isinstance(rouge_keys, tuple):
        rouge_keys = (rouge_keys,)
    for key in rouge_keys:
        if key not in ALLOWED_ROUGE_KEYS.keys():
            raise ValueError(f"Got unknown rouge key {key}. Expected to be one of {list(ALLOWED_ROUGE_KEYS.keys())}")
    rouge_keys_values = [ALLOWED_ROUGE_KEYS[key] for key in rouge_keys]

    if isinstance(target, list) and all(isinstance(tgt, str) for tgt in target):
        target = [target] if isinstance(preds, str) else [[tgt] for tgt in target]
    if isinstance(preds, str):
        preds = [preds]
    if isinstance(target, str):
        target = [[target]]

    sentence_results = _rouge_score_update(
        preds, target, rouge_keys_values, accumulate=accumulate,
        stemmer=stemmer, normalizer=normalizer, tokenizer=tokenizer,
    )

    output: Dict[str, List[float]] = {}
    for rouge_key, metrics in sentence_results.items():
        for tp in ("fmeasure", "precision", "recall"):
            output[f"rouge{rouge_key}_{tp}"] = [metric[tp] for metric in metrics]

    return _rouge_score_compute(output)
