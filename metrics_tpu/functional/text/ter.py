"""Translation edit rate (reference src/torchmetrics/functional/text/ter.py).

Implements the Tercom algorithm (Snover et al. 2006) as standardized by sacrebleu's
``lib_ter``: a beam-limited Levenshtein DP with an operation trace, plus a greedy
phrase-shift search that accepts shifts while they reduce the edit distance.

TPU-first note: the DP cost rows are vectorized numpy (the within-row insertion
chain is folded with a prefix-min accumulate); only the row loop and the heuristic
shift search stay in Python. State is two psum-able scalars.

Provenance: the host-side shift-search scaffolding (``_find_shifted_pairs``,
``_perform_shift``, ``_trace_to_alignment``, the tokenizer regex tables, and the
shift-ranking tuple order) is a deliberate transcription of the published sacrebleu
``lib_ter`` tercom protocol — the exact rule set is required for bit-parity with the
standard TER definition, so it intentionally mirrors the upstream algorithm rather
than being an independent redesign. The DP kernel itself is original (see above).
"""

from __future__ import annotations

import math
import re
from functools import lru_cache
from typing import Dict, Iterator, List, Optional, Sequence, Tuple, Union

import jax.numpy as jnp
import numpy as np
from jax import Array

from metrics_tpu.functional.text.helper import _validate_inputs

# Tercom-inspired limits
_MAX_SHIFT_SIZE = 10
_MAX_SHIFT_DIST = 50
_BEAM_WIDTH = 25
# below this reference length the tercom DP uses plain-Python rows (numpy
# per-op overhead dominates at narrow beam windows); tests monkeypatch this
_SCALAR_ROW_MAX = 64

# Sacrebleu-inspired limits
_MAX_SHIFT_CANDIDATES = 1000
_INT_INFINITY = int(1e16)

# op codes for the DP trace
_OP_NOTHING, _OP_SUBSTITUTE, _OP_DELETE, _OP_INSERT, _OP_UNDEFINED = 0, 1, 2, 3, 4


class _TercomTokenizer:
    """Tercom normalizer/tokenizer (reference ter.py:57-187)."""

    _ASIAN_PUNCTUATION = r"([、。〈-】〔-〟｡-･・])"
    _FULL_WIDTH_PUNCTUATION = r"([．，？：；！＂（）])"

    def __init__(
        self,
        normalize: bool = False,
        no_punctuation: bool = False,
        lowercase: bool = True,
        asian_support: bool = False,
    ) -> None:
        self.normalize = normalize
        self.no_punctuation = no_punctuation
        self.lowercase = lowercase
        self.asian_support = asian_support

    @lru_cache(maxsize=2**16)
    def __call__(self, sentence: str) -> str:
        if not sentence:
            return ""
        if self.lowercase:
            sentence = sentence.lower()
        if self.normalize:
            sentence = self._normalize_general_and_western(sentence)
            if self.asian_support:
                sentence = self._normalize_asian(sentence)
        if self.no_punctuation:
            sentence = self._remove_punct(sentence)
            if self.asian_support:
                sentence = self._remove_asian_punct(sentence)
        return " ".join(sentence.split())

    @staticmethod
    def _normalize_general_and_western(sentence: str) -> str:
        sentence = f" {sentence} "
        rules = [
            (r"\n-", ""),
            (r"\n", " "),
            (r"&quot;", '"'),
            (r"&amp;", "&"),
            (r"&lt;", "<"),
            (r"&gt;", ">"),
            (r"([{-~[-` -&(-+:-@/])", r" \1 "),
            (r"'s ", r" 's "),
            (r"'s$", r" 's"),
            (r"([^0-9])([\.,])", r"\1 \2 "),
            (r"([\.,])([^0-9])", r" \1 \2"),
            (r"([0-9])(-)", r"\1 \2 "),
        ]
        for pattern, replacement in rules:
            sentence = re.sub(pattern, replacement, sentence)
        return sentence

    @classmethod
    def _normalize_asian(cls, sentence: str) -> str:
        sentence = re.sub(r"([一-鿿㐀-䶿])", r" \1 ", sentence)
        sentence = re.sub(r"([㇀-㇯⺀-⻿])", r" \1 ", sentence)
        sentence = re.sub(r"([㌀-㏿豈-﫿︰-﹏])", r" \1 ", sentence)
        sentence = re.sub(r"([㈀-㼢])", r" \1 ", sentence)
        sentence = re.sub(r"(^|^[぀-ゟ])([぀-ゟ]+)(?=$|^[぀-ゟ])", r"\1 \2 ", sentence)
        sentence = re.sub(r"(^|^[゠-ヿ])([゠-ヿ]+)(?=$|^[゠-ヿ])", r"\1 \2 ", sentence)
        sentence = re.sub(r"(^|^[ㇰ-ㇿ])([ㇰ-ㇿ]+)(?=$|^[ㇰ-ㇿ])", r"\1 \2 ", sentence)
        sentence = re.sub(cls._ASIAN_PUNCTUATION, r" \1 ", sentence)
        sentence = re.sub(cls._FULL_WIDTH_PUNCTUATION, r" \1 ", sentence)
        return sentence

    @staticmethod
    def _remove_punct(sentence: str) -> str:
        return re.sub(r"[\.,\?:;!\"\(\)]", "", sentence)

    @classmethod
    def _remove_asian_punct(cls, sentence: str) -> str:
        sentence = re.sub(cls._ASIAN_PUNCTUATION, r"", sentence)
        return re.sub(cls._FULL_WIDTH_PUNCTUATION, r"", sentence)


def _preprocess_sentence(sentence: str, tokenizer: _TercomTokenizer) -> str:
    return tokenizer(sentence.rstrip())


class _LevenshteinEditDistance:
    """Beam-limited Levenshtein DP against a fixed reference, returning op traces.

    Tie preference on equal cost: substitute/nothing, then delete, then insert
    (tercom convention; the trace is flipped downstream so insert/delete swap).
    Rows are computed with vectorized numpy; the within-row insert chain
    ``dp[j] = min(cand[j], dp[j-1]+1)`` is a prefix-min accumulate.
    """

    def __init__(self, reference_tokens: List[str]) -> None:
        self.reference_tokens = reference_tokens
        self.reference_len = len(reference_tokens)
        self._memo: Dict[Tuple[str, ...], Tuple[int, Tuple[int, ...]]] = {}
        # shared token->int id space so sub-cost rows are vectorized int compares
        self._vocab: Dict[str, int] = {}
        self._ref_ids = self._to_ids(reference_tokens)

    def _to_ids(self, tokens: List[str]) -> np.ndarray:
        vocab = self._vocab
        ids = np.empty(len(tokens), dtype=np.int32)
        for i, tok in enumerate(tokens):
            if tok not in vocab:
                vocab[tok] = len(vocab)
            ids[i] = vocab[tok]
        return ids

    def __call__(self, prediction_tokens: List[str]) -> Tuple[int, Tuple[int, ...]]:
        key = tuple(prediction_tokens)
        hit = self._memo.get(key)
        if hit is not None:
            return hit
        result = self._levenshtein_edit_distance(prediction_tokens)
        if len(self._memo) < 10000:
            self._memo[key] = result
        return result

    def _levenshtein_edit_distance(self, prediction_tokens: List[str]) -> Tuple[int, Tuple[int, ...]]:
        prediction_len = len(prediction_tokens)
        m = self.reference_len
        ref_ids = self._ref_ids
        pred_ids = self._to_ids(prediction_tokens)

        length_ratio = m / prediction_len if prediction_tokens else 1.0
        beam_width = math.ceil(length_ratio / 2 + _BEAM_WIDTH) if length_ratio / 2 > _BEAM_WIDTH else _BEAM_WIDTH

        costs = np.full((prediction_len + 1, m + 1), float(_INT_INFINITY))
        ops = np.full((prediction_len + 1, m + 1), _OP_UNDEFINED, dtype=np.int8)
        costs[0] = np.arange(m + 1, dtype=np.float64)
        ops[0] = _OP_INSERT

        # Typical tercom rows are a narrow beam window (tens of cells); plain
        # Python beats numpy's per-op overhead there. Wide rows take the
        # vectorized prefix-min path below.
        if m < _SCALAR_ROW_MAX:
            return self._scalar_rows(pred_ids, prediction_len, length_ratio, beam_width, costs, ops)

        offsets = np.arange(m + 1, dtype=np.float64)
        for i in range(1, prediction_len + 1):
            pseudo_diag = math.floor(i * length_ratio)
            min_j = max(0, pseudo_diag - beam_width)
            max_j = m + 1 if i == prediction_len else min(m + 1, pseudo_diag + beam_width)
            if min_j >= max_j:
                continue

            prev = costs[i - 1]
            sub_cost = (ref_ids != pred_ids[i - 1]).astype(np.float64)
            # candidates before the insert chain: diagonal (sub/nothing) and above (delete)
            diag = np.concatenate(([float(_INT_INFINITY)], prev[:-1] + sub_cost))
            up = prev + 1.0
            cand = np.minimum(diag, up)
            if min_j == 0:
                cand[0] = prev[0] + 1.0  # j==0: deletion only
            # fold the within-beam insert chain via prefix-min over the window
            w0, w1 = min_j, max_j
            window = cand[w0:w1] - offsets[w0:w1]
            row = np.minimum.accumulate(window) + offsets[w0:w1]
            costs[i, w0:w1] = row

            # op per cell in tercom preference order: sub/nothing > delete > insert
            j_idx = np.arange(w0, w1)
            is_sub = row == diag[w0:w1]
            is_del = row == up[w0:w1]
            row_ops = np.where(is_sub, np.where(sub_cost[j_idx - 1] == 0, _OP_NOTHING, _OP_SUBSTITUTE),
                               np.where(is_del, _OP_DELETE, _OP_INSERT))
            if min_j == 0:
                row_ops[0] = _OP_DELETE
            ops[i, w0:w1] = row_ops

        trace = self._get_trace(prediction_len, ops)
        return int(costs[-1, -1]), trace

    def _scalar_rows(
        self,
        pred_ids: np.ndarray,
        prediction_len: int,
        length_ratio: float,
        beam_width: int,
        costs: np.ndarray,
        ops: np.ndarray,
    ) -> Tuple[int, Tuple[int, ...]]:
        """Plain-Python row loop — same recurrence, window, and tie order as the
        vectorized path; faster when the beam window is a handful of cells."""
        m = self.reference_len
        ref = self._ref_ids.tolist()
        pred = pred_ids.tolist()
        inf = float(_INT_INFINITY)
        prev = list(range(m + 1))
        prev = [float(v) for v in prev]
        for i in range(1, prediction_len + 1):
            pseudo_diag = math.floor(i * length_ratio)
            min_j = max(0, pseudo_diag - beam_width)
            max_j = m + 1 if i == prediction_len else min(m + 1, pseudo_diag + beam_width)
            if min_j >= max_j:
                prev = [inf] * (m + 1)  # mirror the vectorized path: row stays INF
                continue
            cur = [inf] * (m + 1)
            row_ops = ops[i]
            p_tok = pred[i - 1]
            left = inf
            for j in range(min_j, max_j):
                if j == 0:
                    c = prev[0] + 1.0
                    op = _OP_DELETE
                else:
                    diag = prev[j - 1] + (0.0 if ref[j - 1] == p_tok else 1.0)
                    up = prev[j] + 1.0
                    ins = left + 1.0
                    c = diag if diag <= up else up
                    if ins < c:
                        c = ins
                    if c == diag:
                        op = _OP_NOTHING if ref[j - 1] == p_tok else _OP_SUBSTITUTE
                    elif c == up:
                        op = _OP_DELETE
                    else:
                        op = _OP_INSERT
                cur[j] = c
                left = c
                row_ops[j] = op
            costs[i] = cur
            prev = cur
        trace = self._get_trace(prediction_len, ops)
        return int(costs[-1, -1]), trace

    def _get_trace(self, prediction_len: int, ops: np.ndarray) -> Tuple[int, ...]:
        trace: List[int] = []
        i, j = prediction_len, self.reference_len
        while i > 0 or j > 0:
            operation = int(ops[i, j])
            trace.append(operation)
            if operation in (_OP_SUBSTITUTE, _OP_NOTHING):
                i -= 1
                j -= 1
            elif operation == _OP_INSERT:
                j -= 1
            elif operation == _OP_DELETE:
                i -= 1
            else:
                raise ValueError(f"Unknown operation {operation!r}")
        trace.reverse()
        return tuple(trace)


def _flip_trace(trace: Tuple[int, ...]) -> Tuple[int, ...]:
    """Swap insertions and deletions: recipe for rewriting b->a instead of a->b."""
    flip = {_OP_INSERT: _OP_DELETE, _OP_DELETE: _OP_INSERT}
    return tuple(flip.get(op, op) for op in trace)


def _trace_to_alignment(trace: Tuple[int, ...]) -> Tuple[Dict[int, int], List[int], List[int]]:
    """Alignment map + error vectors from an op trace (reference helper.py:383-427)."""
    reference_position = hypothesis_position = -1
    reference_errors: List[int] = []
    hypothesis_errors: List[int] = []
    alignments: Dict[int, int] = {}

    for operation in trace:
        if operation == _OP_NOTHING:
            hypothesis_position += 1
            reference_position += 1
            alignments[reference_position] = hypothesis_position
            reference_errors.append(0)
            hypothesis_errors.append(0)
        elif operation == _OP_SUBSTITUTE:
            hypothesis_position += 1
            reference_position += 1
            alignments[reference_position] = hypothesis_position
            reference_errors.append(1)
            hypothesis_errors.append(1)
        elif operation == _OP_INSERT:
            hypothesis_position += 1
            hypothesis_errors.append(1)
        elif operation == _OP_DELETE:
            reference_position += 1
            alignments[reference_position] = hypothesis_position
            reference_errors.append(1)
        else:
            raise ValueError(f"Unknown operation {operation!r}.")

    return alignments, reference_errors, hypothesis_errors


def _find_shifted_pairs(pred_words: List[str], target_words: List[str]) -> Iterator[Tuple[int, int, int]]:
    """Matching word sub-sequences eligible for shifting (reference ter.py:203-238)."""
    for pred_start in range(len(pred_words)):
        for target_start in range(len(target_words)):
            if abs(target_start - pred_start) > _MAX_SHIFT_DIST:
                continue
            for length in range(1, _MAX_SHIFT_SIZE):
                if pred_words[pred_start + length - 1] != target_words[target_start + length - 1]:
                    break
                yield pred_start, target_start, length
                if len(pred_words) == pred_start + length or len(target_words) == target_start + length:
                    break


def _handle_corner_cases_during_shifting(
    alignments: Dict[int, int],
    pred_errors: List[int],
    target_errors: List[int],
    pred_start: int,
    target_start: int,
    length: int,
) -> bool:
    """True if a candidate shift must be skipped (reference ter.py:241-275)."""
    if sum(pred_errors[pred_start : pred_start + length]) == 0:
        return True
    if sum(target_errors[target_start : target_start + length]) == 0:
        return True
    if pred_start <= alignments[target_start] < pred_start + length:
        return True
    return False


def _perform_shift(words: List[str], start: int, length: int, target: int) -> List[str]:
    """Move ``words[start:start+length]`` to position ``target`` (reference ter.py:278-308)."""
    if target < start:
        return words[:target] + words[start : start + length] + words[target:start] + words[start + length :]
    if target > start + length:
        return words[:start] + words[start + length : target] + words[start : start + length] + words[target:]
    return (
        words[:start] + words[start + length : length + target] + words[start : start + length] + words[length + target :]
    )


def _shift_words(
    pred_words: List[str],
    target_words: List[str],
    cached_edit_distance: _LevenshteinEditDistance,
    checked_candidates: int,
) -> Tuple[int, List[str], int]:
    """One round of the tercom shift search (reference ter.py:311-387)."""
    edit_distance, inverted_trace = cached_edit_distance(pred_words)
    trace = _flip_trace(inverted_trace)
    alignments, target_errors, pred_errors = _trace_to_alignment(trace)

    best: Optional[Tuple[int, int, int, int, List[str]]] = None

    for pred_start, target_start, length in _find_shifted_pairs(pred_words, target_words):
        if _handle_corner_cases_during_shifting(
            alignments, pred_errors, target_errors, pred_start, target_start, length
        ):
            continue

        prev_idx = -1
        for offset in range(-1, length):
            if target_start + offset == -1:
                idx = 0
            elif target_start + offset in alignments:
                idx = alignments[target_start + offset] + 1
            else:
                break  # offset is out of bounds => aims past reference
            if idx == prev_idx:
                continue
            prev_idx = idx

            shifted_words = _perform_shift(pred_words, pred_start, length, idx)

            # Tuple order replicates Tercom's shift ranking.
            candidate = (
                edit_distance - cached_edit_distance(shifted_words)[0],
                length,
                -pred_start,
                -idx,
                shifted_words,
            )
            checked_candidates += 1
            if not best or candidate > best:
                best = candidate

        if checked_candidates >= _MAX_SHIFT_CANDIDATES:
            break

    if not best:
        return 0, pred_words, checked_candidates
    best_score, _, _, _, shifted_words = best
    return best_score, shifted_words, checked_candidates


def _translation_edit_rate(pred_words: List[str], target_words: List[str]) -> float:
    """Number of edits (shifts + Levenshtein ops) to match the sentences (ter.py:390-421)."""
    if len(target_words) == 0:
        return 0.0

    cached_edit_distance = _LevenshteinEditDistance(target_words)
    num_shifts = 0
    checked_candidates = 0
    input_words = pred_words

    while True:
        # do shifts while they reduce the edit distance
        delta, new_input_words, checked_candidates = _shift_words(
            input_words, target_words, cached_edit_distance, checked_candidates
        )
        if checked_candidates >= _MAX_SHIFT_CANDIDATES or delta <= 0:
            break
        num_shifts += 1
        input_words = new_input_words

    edit_distance, _ = cached_edit_distance(input_words)
    return float(num_shifts + edit_distance)


def _compute_sentence_statistics(pred_words: List[str], target_words: List[List[str]]) -> Tuple[float, float]:
    """Best edit count over references + average reference length (ter.py:424-447)."""
    tgt_lengths = 0.0
    best_num_edits = 2e16
    for tgt_words in target_words:
        num_edits = _translation_edit_rate(tgt_words, pred_words)
        tgt_lengths += len(tgt_words)
        if num_edits < best_num_edits:
            best_num_edits = num_edits
    avg_tgt_len = tgt_lengths / len(target_words)
    return best_num_edits, avg_tgt_len


def _compute_ter_score_from_statistics(num_edits, tgt_length):
    if tgt_length > 0 and num_edits > 0:
        return num_edits / tgt_length
    if tgt_length == 0 and num_edits > 0:
        return 1.0
    return 0.0


def _ter_update(
    preds: Union[str, Sequence[str]],
    target: Sequence[Union[str, Sequence[str]]],
    tokenizer: _TercomTokenizer,
) -> Tuple[float, float, List[float]]:
    """Accumulate total edit count / average-ref-length over a batch (ter.py:469-508)."""
    target, preds = _validate_inputs(target, preds)

    total_num_edits = 0.0
    total_tgt_length = 0.0
    sentence_ter: List[float] = []

    for pred, tgt in zip(preds, target):
        tgt_words_ = [_preprocess_sentence(_tgt, tokenizer).split() for _tgt in tgt]
        pred_words_ = _preprocess_sentence(pred, tokenizer).split()
        num_edits, tgt_length = _compute_sentence_statistics(pred_words_, tgt_words_)
        total_num_edits += num_edits
        total_tgt_length += tgt_length
        sentence_ter.append(_compute_ter_score_from_statistics(num_edits, tgt_length))
    return total_num_edits, total_tgt_length, sentence_ter


def _ter_compute(total_num_edits: Array, total_tgt_length: Array) -> Array:
    """Corpus TER from accumulated statistics; jnp-safe for in-trace compute."""
    score = jnp.where(
        total_tgt_length > 0,
        total_num_edits / jnp.maximum(total_tgt_length, 1e-30),
        jnp.where(total_num_edits > 0, 1.0, 0.0),
    )
    return jnp.where(total_num_edits > 0, score, 0.0).astype(jnp.float32)


def translation_edit_rate(
    preds: Union[str, Sequence[str]],
    target: Sequence[Union[str, Sequence[str]]],
    normalize: bool = False,
    no_punctuation: bool = False,
    lowercase: bool = True,
    asian_support: bool = False,
    return_sentence_level_score: bool = False,
) -> Union[Array, Tuple[Array, Array]]:
    """Translation edit rate (reference ter.py:523-587).

    Example:
        >>> preds = ['the cat is on the mat']
        >>> target = [['there is a cat on the mat', 'a cat is on the mat']]
        >>> float(translation_edit_rate(preds, target))  # doctest: +ELLIPSIS
        0.1538...
    """
    if not isinstance(normalize, bool):
        raise ValueError(f"Expected argument `normalize` to be of type boolean but got {normalize}.")
    if not isinstance(no_punctuation, bool):
        raise ValueError(f"Expected argument `no_punctuation` to be of type boolean but got {no_punctuation}.")
    if not isinstance(lowercase, bool):
        raise ValueError(f"Expected argument `lowercase` to be of type boolean but got {lowercase}.")
    if not isinstance(asian_support, bool):
        raise ValueError(f"Expected argument `asian_support` to be of type boolean but got {asian_support}.")

    tokenizer = _TercomTokenizer(normalize, no_punctuation, lowercase, asian_support)
    total_num_edits, total_tgt_length, sentence_ter = _ter_update(preds, target, tokenizer)
    ter_score = _ter_compute(jnp.asarray(total_num_edits), jnp.asarray(total_tgt_length))

    if return_sentence_level_score:
        return ter_score, jnp.asarray(sentence_ter, jnp.float32)
    return ter_score
