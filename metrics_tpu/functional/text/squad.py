"""SQuAD exact-match / F1 (reference src/torchmetrics/functional/text/squad.py).

Implements the official SQuAD v1.1 evaluation protocol: normalized answer strings
(lowercase, strip punctuation/articles/extra whitespace), per-question max over
ground-truth answers, averaged over questions and scaled to percent.
"""

from __future__ import annotations

import re
import string
from collections import Counter
from typing import Any, Callable, Dict, List, Tuple, Union

import jax.numpy as jnp
from jax import Array

from metrics_tpu.utils.prints import rank_zero_warn

PREDS_TYPE = Union[Dict[str, str], List[Dict[str, str]]]
TARGETS_TYPE = Union[Dict[str, Any], List[Dict[str, Any]]]

SQuAD_FORMAT = {
    "answers": {"answer_start": [1], "text": ["This is a test text"]},
    "context": "This is a test context.",
    "id": "1",
    "question": "Is this a test?",
    "title": "train test",
}


def _normalize_text(s: str) -> str:
    """Lowercase, remove punctuation/articles/extra whitespace (squad.py:41-57)."""
    s = s.lower()
    s = "".join(ch for ch in s if ch not in set(string.punctuation))
    s = re.sub(r"\b(a|an|the)\b", " ", s)
    return " ".join(s.split())


def _get_tokens(s: str) -> List[str]:
    return [] if not s else _normalize_text(s).split()


def _compute_f1_score(predicted_answer: str, target_answer: str) -> float:
    """Token-overlap F1 for one answer pair (squad.py:65-79)."""
    target_tokens = _get_tokens(target_answer)
    predicted_tokens = _get_tokens(predicted_answer)
    common = Counter(target_tokens) & Counter(predicted_tokens)
    num_same = sum(common.values())
    if len(target_tokens) == 0 or len(predicted_tokens) == 0:
        # If either is no-answer, F1 is 1 if they agree, 0 otherwise.
        return float(target_tokens == predicted_tokens)
    if num_same == 0:
        return 0.0
    precision = num_same / len(predicted_tokens)
    recall = num_same / len(target_tokens)
    return 2 * precision * recall / (precision + recall)


def _compute_exact_match_score(prediction: str, ground_truth: str) -> float:
    return float(_normalize_text(prediction) == _normalize_text(ground_truth))


def _metric_max_over_ground_truths(metric_fn: Callable[[str, str], float], prediction: str, ground_truths: List[str]) -> float:
    return max(metric_fn(prediction, truth) for truth in ground_truths)


def _squad_input_check(preds: PREDS_TYPE, targets: TARGETS_TYPE) -> Tuple[Dict[str, str], List[Dict[str, Any]]]:
    """Validate and convert inputs to the internal format (squad.py:94-135)."""
    if isinstance(preds, dict):
        preds = [preds]
    if isinstance(targets, dict):
        targets = [targets]

    for pred in preds:
        keys = pred.keys()
        if "prediction_text" not in keys or "id" not in keys:
            raise KeyError(
                "Expected keys in a single prediction are 'prediction_text' and 'id'."
                " Please make sure that 'prediction_text' maps to the answer string and 'id' maps to the key string."
            )

    for target in targets:
        keys = target.keys()
        if "answers" not in keys or "id" not in keys:
            raise KeyError(
                "Expected keys in a single target are 'answers' and 'id'."
                " Please make sure that 'answers' maps to a `SQuAD` format dictionary and 'id' maps to the key"
                f" string.\nSQuAD Format: {SQuAD_FORMAT}"
            )
        if "text" not in target["answers"]:
            raise KeyError(
                "Expected keys in a 'answers' are 'text'."
                f" Please make sure that 'answer' maps to a `SQuAD` format dictionary.\nSQuAD Format: {SQuAD_FORMAT}"
            )

    preds_dict = {prediction["id"]: prediction["prediction_text"] for prediction in preds}
    target_dicts = [
        {"answers": [{"text": txt} for txt in tgt["answers"]["text"]], "id": tgt["id"]} for tgt in targets
    ]
    return preds_dict, [{"paragraphs": [{"qas": target_dicts}]}]


def _squad_update(preds: Dict[str, str], target: List[Dict[str, Any]]) -> Tuple[Array, Array, Array]:
    """Sum EM/F1 over all questions (squad.py:138-181)."""
    f1 = 0.0
    exact_match = 0.0
    total = 0
    for article in target:
        for paragraph in article["paragraphs"]:
            for qa in paragraph["qas"]:
                total += 1
                if qa["id"] not in preds:
                    rank_zero_warn(f"Unanswered question {qa['id']} will receive score 0.")
                    continue
                ground_truths = [x["text"] for x in qa["answers"]]
                pred = preds[qa["id"]]
                exact_match += _metric_max_over_ground_truths(_compute_exact_match_score, pred, ground_truths)
                f1 += _metric_max_over_ground_truths(_compute_f1_score, pred, ground_truths)

    return jnp.asarray(f1, jnp.float32), jnp.asarray(exact_match, jnp.float32), jnp.asarray(total, jnp.int32)


def _squad_compute(f1: Array, exact_match: Array, total: Array) -> Dict[str, Array]:
    return {"exact_match": 100.0 * exact_match / total, "f1": 100.0 * f1 / total}


def squad(preds: PREDS_TYPE, target: TARGETS_TYPE) -> Dict[str, Array]:
    """SQuAD metric (reference squad.py:195-251).

    Example:
        >>> preds = [{"prediction_text": "1976", "id": "56e10a3be3433e1400422b22"}]
        >>> target = [{"answers": {"answer_start": [97], "text": ["1976"]}, "id": "56e10a3be3433e1400422b22"}]
        >>> {k: float(v) for k, v in squad(preds, target).items()}
        {'exact_match': 100.0, 'f1': 100.0}
    """
    preds_dict, target_dict = _squad_input_check(preds, target)
    f1, exact_match, total = _squad_update(preds_dict, target_dict)
    return _squad_compute(f1, exact_match, total)
