"""Shared text helpers: corpus validation and a batched Levenshtein kernel.

Reference parity: src/torchmetrics/functional/text/helper.py (`_validate_inputs` :298,
`_edit_distance` :333). Redesign: the reference runs an O(n·m) pure-Python DP per
pair; here the row recurrence runs in LOCKSTEP ACROSS THE WHOLE CORPUS on padded
(P, max_m) numpy arrays — the Python loop count drops from sum(n_p) to max(n_p).
The within-row insertion dependency ``dp[j] = min(dp[j-1] + 1, cand[j])`` is solved
in closed form as a running prefix-min of ``cand[j] - j`` (all insertion costs are
1), i.e. ``np.minimum.accumulate``. Pairs are grouped into geometric length bands
so outliers never inflate the padding of the rest of the corpus (measured 4.3-8.8x
faster than the reference on WER/CER/MER corpora — benchmarks/text_vs_reference.py).

String tokenization itself stays on host (SURVEY §2.5: state is small tensors; the
algorithms are not worth jitting), but every DP step is a wide vector op.
"""

from __future__ import annotations

from typing import Dict, Hashable, List, Sequence, Tuple, Union

import numpy as np

_BUCKET = 512  # pairs per padded-DP bucket (see _edit_distances_batched)


def _validate_inputs(
    reference_corpus: Union[Sequence[str], Sequence[Sequence[str]]],
    hypothesis_corpus: Union[str, Sequence[str]],
) -> Tuple[Sequence[Sequence[str]], Sequence[str]]:
    """Normalize corpora to (Sequence[Sequence[str]], Sequence[str]) and length-check.

    Reference: functional/text/helper.py:298-330.
    """
    if isinstance(hypothesis_corpus, str):
        hypothesis_corpus = [hypothesis_corpus]

    if all(isinstance(ref, str) for ref in reference_corpus):
        reference_corpus = [reference_corpus] if len(hypothesis_corpus) == 1 else [[ref] for ref in reference_corpus]

    if hypothesis_corpus and all(ref for ref in reference_corpus) and len(reference_corpus) != len(hypothesis_corpus):
        raise ValueError(f"Corpus has different size {len(reference_corpus)} != {len(hypothesis_corpus)}")

    return reference_corpus, hypothesis_corpus


def _banded_chunks(dims: Sequence[Tuple[int, int]]) -> List[List[int]]:
    """Group pair indices into geometric length bands (both axes), chunked at
    ``_BUCKET`` — shared by the Levenshtein and EED lockstep kernels so one
    outlier-size pair never inflates the padded DP of the rest."""
    bands: Dict[Tuple[int, int], List[int]] = {}
    for p, (n, m) in enumerate(dims):
        if m > n:
            n, m = m, n
        bands.setdefault((max(n, 1).bit_length(), max(m, 1).bit_length()), []).append(p)
    chunks: List[List[int]] = []
    for members in bands.values():
        for lo in range(0, len(members), _BUCKET):
            chunks.append(members[lo : lo + _BUCKET])
    return chunks


def _edit_distance(prediction_tokens: Sequence[Hashable], reference_tokens: Sequence[Hashable]) -> int:
    """Levenshtein distance of one pair — thin wrapper over the batched kernel."""
    return int(_edit_distances_batched([(prediction_tokens, reference_tokens)])[0])


def _edit_distances_batched(pairs: Sequence[Tuple[Sequence[Hashable], Sequence[Hashable]]]) -> np.ndarray:
    """Levenshtein distances for a whole corpus of pairs in ONE padded DP.

    The per-pair kernel above still pays ~6 small-numpy calls per DP row, which
    dominates for word-level pairs (tens of tokens). Here the row recurrence
    runs in lockstep across all P pairs on (P, max_m) arrays — the Python loop
    count drops from sum(n_p) to max(n_p) and every step is a wide vector op.
    Each pair is oriented so its longer side is the row axis (Levenshtein is
    symmetric), which minimizes the padded column width. Pads use distinct
    sentinels (-1 vs -2) so padding never matches.
    """
    P = len(pairs)
    if P == 0:
        return np.zeros(0, dtype=np.int64)
    # Length-bucket so outlier-length pairs don't pad the whole corpus to their
    # size (the DP is O(P * max_n * max_m) over the padded shapes). Buckets are
    # geometric length bands (powers of two of the longer side), so within a
    # bucket padding wastes at most ~2x per axis, and an outlier only ever
    # shares a bucket with pairs of its own magnitude. Bands are further split
    # into chunks of _BUCKET pairs to bound the DP arrays.
    chunks = _banded_chunks([(len(a), len(b)) for a, b in pairs])
    if len(chunks) == 1:
        return _edit_distances_batched_same_band(pairs)
    result = np.zeros(P, dtype=np.int64)
    for idx in chunks:
        result[idx] = _edit_distances_batched_same_band([pairs[p] for p in idx])
    return result


def _edit_distances_batched_same_band(pairs: Sequence[Tuple[Sequence[Hashable], Sequence[Hashable]]]) -> np.ndarray:
    """The padded lockstep DP for one length band (see _edit_distances_batched)."""
    P = len(pairs)
    vocab: Dict[Hashable, int] = {}

    def ids(seq: Sequence[Hashable]) -> np.ndarray:
        out = np.empty(len(seq), dtype=np.int64)
        for i, tok in enumerate(seq):
            if tok not in vocab:
                vocab[tok] = len(vocab)
            out[i] = vocab[tok]
        return out

    rows, cols = [], []
    for a, b in pairs:
        a, b = (a, b) if len(a) >= len(b) else (b, a)  # rows = longer side
        rows.append(ids(a))
        cols.append(ids(b))
    n_p = np.asarray([len(r) for r in rows])
    m_p = np.asarray([len(c) for c in cols])
    max_n, max_m = int(n_p.max()), int(m_p.max())

    preds = np.full((P, max_n), -1, dtype=np.int64)
    refs = np.full((P, max_m if max_m else 1), -2, dtype=np.int64)
    for p in range(P):
        preds[p, : n_p[p]] = rows[p]
        refs[p, : m_p[p]] = cols[p]

    result = np.where(n_p == 0, m_p, 0).astype(np.int64)
    offsets = np.arange(refs.shape[1] + 1, dtype=np.int64)
    prev = np.broadcast_to(offsets, (P, offsets.shape[0])).copy()
    col = np.empty((P, 1), dtype=np.int64)
    for i in range(1, max_n + 1):
        sub = prev[:, :-1] + (refs != preds[:, i - 1 : i])
        cand = np.minimum(prev[:, 1:] + 1, sub)
        col[:] = i
        cand = np.concatenate([col, cand], axis=1)
        prev = np.minimum.accumulate(cand - offsets, axis=1) + offsets
        done = n_p == i
        if done.any():
            result[done] = prev[done, m_p[done]]
    return result
