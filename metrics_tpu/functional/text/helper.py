"""Shared text helpers: corpus validation and a vectorized Levenshtein kernel.

Reference parity: src/torchmetrics/functional/text/helper.py (`_validate_inputs` :298,
`_edit_distance` :333). TPU-first redesign: the reference's O(n·m) pure-Python DP loop
is replaced by a wavefront formulation with only ONE Python loop (over the shorter
sequence) and numpy vector work per row — the within-row insertion dependency
``dp[j] = min(dp[j-1] + 1, cand[j])`` is solved in closed form as a running prefix-min
of ``cand[j] - j`` (all insertion costs are 1), i.e. ``np.minimum.accumulate``.

String tokenization itself stays on host (SURVEY §2.5: state is small tensors; the
algorithms are not worth jitting), but every per-row step is vectorized.
"""

from __future__ import annotations

from typing import Dict, Hashable, List, Sequence, Tuple, Union

import numpy as np


def _validate_inputs(
    reference_corpus: Union[Sequence[str], Sequence[Sequence[str]]],
    hypothesis_corpus: Union[str, Sequence[str]],
) -> Tuple[Sequence[Sequence[str]], Sequence[str]]:
    """Normalize corpora to (Sequence[Sequence[str]], Sequence[str]) and length-check.

    Reference: functional/text/helper.py:298-330.
    """
    if isinstance(hypothesis_corpus, str):
        hypothesis_corpus = [hypothesis_corpus]

    if all(isinstance(ref, str) for ref in reference_corpus):
        reference_corpus = [reference_corpus] if len(hypothesis_corpus) == 1 else [[ref] for ref in reference_corpus]

    if hypothesis_corpus and all(ref for ref in reference_corpus) and len(reference_corpus) != len(hypothesis_corpus):
        raise ValueError(f"Corpus has different size {len(reference_corpus)} != {len(hypothesis_corpus)}")

    return reference_corpus, hypothesis_corpus


def _tokens_to_ids(*token_seqs: Sequence[Hashable]) -> List[np.ndarray]:
    """Map arbitrary hashable tokens to a shared int32 id space (host-side)."""
    vocab: Dict[Hashable, int] = {}
    out = []
    for seq in token_seqs:
        ids = np.empty(len(seq), dtype=np.int32)
        for i, tok in enumerate(seq):
            if tok not in vocab:
                vocab[tok] = len(vocab)
            ids[i] = vocab[tok]
        out.append(ids)
    return out


def _edit_distance(prediction_tokens: Sequence[Hashable], reference_tokens: Sequence[Hashable]) -> int:
    """Levenshtein distance via a vectorized row recurrence.

    Same contract as reference helper.py:333-353; unit costs. Row recurrence:
    ``cand[j] = min(prev[j] + 1, prev[j-1] + sub_cost[j])`` is elementwise; the
    remaining within-row term ``dp[j] = min(cand[j], dp[j-1] + 1)`` equals
    ``j + running_min(cand[k] - k, k <= j)`` and is computed with minimum.accumulate.
    """
    pred_ids, ref_ids = _tokens_to_ids(prediction_tokens, reference_tokens)
    n, m = len(pred_ids), len(ref_ids)
    if n == 0:
        return m
    if m == 0:
        return n
    # iterate over the shorter axis to minimize Python-loop iterations
    if n < m:
        pred_ids, ref_ids, n, m = ref_ids, pred_ids, m, n

    prev = np.arange(m + 1, dtype=np.int64)
    offsets = prev  # [0, 1, ..., m] — reused as the prefix-min offset vector
    for i in range(1, n + 1):
        sub = prev[:-1] + (ref_ids != pred_ids[i - 1])
        cand = np.minimum(prev[1:] + 1, sub)
        cand = np.concatenate(([i], cand))
        prev = np.minimum.accumulate(cand - offsets) + offsets
    return int(prev[-1])
