"""Word information lost (reference src/torchmetrics/functional/text/wil.py)."""

from __future__ import annotations

from typing import List, Union

from jax import Array

from metrics_tpu.functional.text.wip import _wip_update as _wil_update  # same statistics (wil.py:23-56)


def _wil_compute(errors: Array, target_total: Array, preds_total: Array) -> Array:
    return 1 - ((errors / target_total) * (errors / preds_total))


def word_information_lost(preds: Union[str, List[str]], target: Union[str, List[str]]) -> Array:
    """Word information lost of transcriptions vs references (reference wil.py:59-93).

    Example:
        >>> preds = ["this is the prediction", "there is an other sample"]
        >>> target = ["this is the reference", "there is another one"]
        >>> word_information_lost(preds, target)  # doctest: +SKIP
        Array(0.6527778, dtype=float32)
    """
    errors, target_total, preds_total = _wil_update(preds, target)
    return _wil_compute(errors, target_total, preds_total)
