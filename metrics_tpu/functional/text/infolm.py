"""InfoLM (reference src/torchmetrics/functional/text/infolm.py, 644 LoC).

Family of untrained masked-LM metrics (Colombo et al., AAAI 2022): each sentence is
summarized as a discrete distribution over the vocabulary — the average of the MLM's
softmax at every masked position — and predictions are scored against references by
an information measure (KL/alpha/beta/AB/Rényi divergences, L1/L2/L∞, Fisher-Rao).

TPU-first redesign of the heavy step: the reference runs ONE model forward per token
position per batch (infolm.py:394-405 — a Python loop of ``seq_len`` forwards); here
all masked variants are materialized as a single ``[batch·seq, seq]`` input (mask on
the diagonal) and run in one chunked forward — XLA sees big static batches, and the
per-position softmax/gather is vectorized jnp. The information measures themselves
are jittable.

The reference sorts inputs by length and mis-applies the sort permutation to the
output (infolm.py:526-528 indexes by ``sorting_indices`` instead of its inverse);
here inputs keep their original order, so scores align with input pairs.
"""

from __future__ import annotations

import math
from collections import Counter, defaultdict
from typing import Any, Dict, Optional, Sequence, Tuple, Union

import jax.numpy as jnp
import numpy as np
from jax import Array

from metrics_tpu.utils.imports import _TRANSFORMERS_AVAILABLE

_ALLOWED_INFORMATION_MEASURE = (
    "kl_divergence",
    "alpha_divergence",
    "beta_divergence",
    "ab_divergence",
    "renyi_divergence",
    "l1_distance",
    "l2_distance",
    "l_infinity_distance",
    "fisher_rao_distance",
)

_DEFAULT_INFOLM_MODEL = "bert-base-uncased"


class _InformationMeasure:
    """Information measures over discrete vocab distributions (infolm.py:82-297).

    All measures are elementwise jnp math over ``[..., vocab]`` distributions and are
    jittable; non-finite values are zeroed as in the reference (infolm.py:148).
    """

    def __init__(self, information_measure: str, alpha: Optional[float] = None, beta: Optional[float] = None) -> None:
        if information_measure not in _ALLOWED_INFORMATION_MEASURE:
            raise ValueError(
                f"Invalid information measure. Expected one of {list(_ALLOWED_INFORMATION_MEASURE)},"
                f" but got {information_measure}."
            )
        self.information_measure = information_measure
        _alpha_measures = ("alpha_divergence", "ab_divergence", "renyi_divergence")
        if information_measure in _alpha_measures and not isinstance(alpha, float):
            raise ValueError(f"Parameter `alpha` is expected to be defined for {information_measure}.")
        if information_measure in ("beta_divergence", "ab_divergence") and not isinstance(beta, float):
            raise ValueError(f"Parameter `beta` is expected to be defined for {information_measure}.")
        if information_measure == "alpha_divergence" and (not isinstance(alpha, float) or alpha in [0, 1]):
            raise ValueError(
                f"Parameter `alpha` is expected to be float differened from 0 and 1 for {information_measure}."
            )
        if information_measure == "beta_divergence" and (not isinstance(beta, float) or beta in [0, -1]):
            raise ValueError(
                f"Parameter `beta` is expected to be float differened from 0 and -1 for {information_measure}."
            )
        if information_measure == "ab_divergence" and (
            any(not isinstance(p, float) for p in [alpha, beta]) or 0 in [alpha, beta, alpha + beta]
        ):
            raise ValueError(
                f"Parameters `alpha`, `beta` and their sum are expected to be differened from 0 for"
                f" {information_measure}."
            )
        if information_measure == "renyi_divergence" and (not isinstance(alpha, float) or alpha == 1):
            raise ValueError(f"Parameter `alpha` is expected to be float differened from 1 for {information_measure}.")

        self.alpha = alpha or 0
        self.beta = beta or 0

    def __call__(self, preds_distribution: Array, target_distribution: Array) -> Array:
        fn = getattr(self, f"_calculate_{self.information_measure}")
        return jnp.nan_to_num(fn(preds_distribution, target_distribution), nan=0.0, posinf=0.0, neginf=0.0)

    @staticmethod
    def _calculate_kl_divergence(preds_distribution: Array, target_distribution: Array) -> Array:
        return jnp.sum(target_distribution * jnp.log(preds_distribution / target_distribution), axis=-1)

    def _calculate_alpha_divergence(self, preds_distribution: Array, target_distribution: Array) -> Array:
        _alpha_denom = self.alpha * (self.alpha - 1)
        return (
            1 - jnp.sum(target_distribution**self.alpha * preds_distribution ** (1 - self.alpha), axis=-1)
        ) / _alpha_denom

    def _calculate_ab_divergence(self, preds_distribution: Array, target_distribution: Array) -> Array:
        a = jnp.log(jnp.sum(target_distribution ** (self.beta + self.alpha), axis=-1)) / (
            self.beta * (self.beta + self.alpha)
        )
        b = jnp.log(jnp.sum(preds_distribution ** (self.beta + self.alpha), axis=-1)) / (
            self.alpha * (self.beta + self.alpha)
        )
        c = jnp.log(jnp.sum(target_distribution**self.alpha * preds_distribution**self.beta, axis=-1)) / (
            self.alpha * self.beta
        )
        return a + b - c

    def _calculate_beta_divergence(self, preds_distribution: Array, target_distribution: Array) -> Array:
        self.alpha = 1.0
        return self._calculate_ab_divergence(preds_distribution, target_distribution)

    def _calculate_renyi_divergence(self, preds_distribution: Array, target_distribution: Array) -> Array:
        return jnp.log(
            jnp.sum(target_distribution**self.alpha * preds_distribution ** (1 - self.alpha), axis=-1)
        ) / (self.alpha - 1)

    @staticmethod
    def _calculate_l1_distance(preds_distribution: Array, target_distribution: Array) -> Array:
        return jnp.sum(jnp.abs(target_distribution - preds_distribution), axis=-1)

    @staticmethod
    def _calculate_l2_distance(preds_distribution: Array, target_distribution: Array) -> Array:
        return jnp.sqrt(jnp.sum((target_distribution - preds_distribution) ** 2, axis=-1))

    @staticmethod
    def _calculate_l_infinity_distance(preds_distribution: Array, target_distribution: Array) -> Array:
        return jnp.max(jnp.abs(target_distribution - preds_distribution), axis=-1)

    @staticmethod
    def _calculate_fisher_rao_distance(preds_distribution: Array, target_distribution: Array) -> Array:
        return 2 * jnp.arccos(jnp.clip(jnp.sqrt(preds_distribution * target_distribution).sum(-1), 0, 1))


def _get_special_tokens_map(tokenizer: Any) -> Dict[str, int]:
    """mask/pad/sep/cls token ids (infolm.py:323-339)."""
    return {
        "mask_token_id": tokenizer.mask_token_id,
        "pad_token_id": tokenizer.pad_token_id,
        "sep_token_id": tokenizer.sep_token_id,
        "cls_token_id": tokenizer.cls_token_id,
    }


def _get_token_mask(input_ids: np.ndarray, pad_token_id: int, sep_token_id: int, cls_token_id: int) -> np.ndarray:
    """1 for content tokens, 0 for special tokens (infolm.py:342-362)."""
    token_mask = (input_ids == pad_token_id) | (input_ids == sep_token_id) | (input_ids == cls_token_id)
    return ~token_mask


def _get_tokens_idf(input_ids: np.ndarray) -> Dict[int, float]:
    """Sentence-frequency IDF over padded rows (helper_embedding_metric.py:230-249)."""
    num_sentences = len(input_ids)
    token_counter: Counter = Counter()
    for ids in input_ids:
        token_counter.update(set(ids.tolist()))
    tokens_idf: Dict[int, float] = defaultdict(lambda: math.log((num_sentences + 1) / 1))
    tokens_idf.update(
        {idx: math.log((num_sentences + 1) / (occurrence + 1)) for idx, occurrence in token_counter.items()}
    )
    return tokens_idf


def _get_data_distribution(
    model: Any,
    input_ids: np.ndarray,
    attention_mask: np.ndarray,
    temperature: float,
    idf: bool,
    special_tokens_map: Dict[str, int],
    batch_size: int,
) -> Array:
    """Per-sentence vocab distribution (infolm.py:365-452), batched mask variants.

    For each sentence, every position is masked in turn and the MLM softmax at that
    position is collected; the sentence distribution is the (idf-weighted) average
    over content positions. All ``seq_len`` variants run in one chunked forward.
    """
    tokens_idf = _get_tokens_idf(input_ids) if idf else None
    out = []
    for start in range(0, len(input_ids), batch_size):
        ids = input_ids[start : start + batch_size]
        mask = attention_mask[start : start + batch_size]
        # trim shared padding for this chunk
        max_len = max(int(mask.sum(1).max()), 1)
        ids, mask = ids[:, :max_len], mask[:, :max_len]
        b, s = ids.shape

        token_mask = _get_token_mask(
            ids,
            special_tokens_map["pad_token_id"],
            special_tokens_map["sep_token_id"],
            special_tokens_map["cls_token_id"],
        )

        # [b, s, s] with the diagonal replaced by the mask token, flattened to [b*s, s]
        variants = np.broadcast_to(ids[:, None, :], (b, s, s)).copy()
        variants[:, np.arange(s), np.arange(s)] = special_tokens_map["mask_token_id"]
        variant_mask = np.broadcast_to(mask[:, None, :], (b, s, s)).reshape(b * s, s)

        logits = model(
            input_ids=jnp.asarray(variants.reshape(b * s, s)), attention_mask=jnp.asarray(variant_mask)
        ).logits
        # softmax at each masked (diagonal) position -> [b, s, vocab]
        logits = logits.reshape(b, s, s, -1)[:, np.arange(s), np.arange(s), :]
        prob_distribution = jnp.asarray(
            jnp.exp(logits / temperature - jnp.max(logits / temperature, axis=-1, keepdims=True))
        )
        prob_distribution = prob_distribution / prob_distribution.sum(-1, keepdims=True)

        if idf:
            ids_idf = np.vectorize(lambda t: tokens_idf[int(t)])(ids).astype(np.float32)
            prob_distribution = prob_distribution * jnp.asarray(ids_idf)[..., None]
            denom = jnp.asarray((token_mask * ids_idf).sum(1))
        else:
            denom = jnp.asarray(token_mask.sum(1).astype(np.float32))

        prob_distribution = prob_distribution * jnp.asarray(token_mask.astype(np.float32))[..., None]
        out.append(prob_distribution.sum(axis=1) / denom[:, None])

    return jnp.concatenate(out, axis=0)


def _infolm_update(
    preds: Union[str, Sequence[str]],
    target: Union[str, Sequence[str]],
    tokenizer: Any,
    max_length: int,
) -> Tuple[np.ndarray, np.ndarray, np.ndarray, np.ndarray]:
    """Tokenize preds/target to fixed-length id/mask arrays (infolm.py:455-485)."""
    if not isinstance(preds, (str, list)):
        preds = list(preds)
    if not isinstance(target, (str, list)):
        target = list(target)

    preds_input = tokenizer(preds, padding="max_length", max_length=max_length, truncation=True, return_tensors="np")
    target_input = tokenizer(target, padding="max_length", max_length=max_length, truncation=True, return_tensors="np")
    return (
        np.asarray(preds_input["input_ids"]),
        np.asarray(preds_input["attention_mask"]),
        np.asarray(target_input["input_ids"]),
        np.asarray(target_input["attention_mask"]),
    )


def _infolm_compute(
    model: Any,
    preds_input: Tuple[np.ndarray, np.ndarray],
    target_input: Tuple[np.ndarray, np.ndarray],
    temperature: float,
    idf: bool,
    information_measure_cls: _InformationMeasure,
    special_tokens_map: Dict[str, int],
    batch_size: int = 64,
) -> Array:
    """Sentence-level InfoLM scores (infolm.py:488-531)."""
    preds_distribution = _get_data_distribution(
        model, preds_input[0], preds_input[1], temperature, idf, special_tokens_map, batch_size
    )
    target_distribution = _get_data_distribution(
        model, target_input[0], target_input[1], temperature, idf, special_tokens_map, batch_size
    )
    # pad vocab axes identically by construction (same model); measure is jittable
    return information_measure_cls(preds_distribution, target_distribution)


def _load_tokenizer_and_model(model_name_or_path: str) -> Tuple[Any, Any]:
    if not _TRANSFORMERS_AVAILABLE:
        raise ModuleNotFoundError(
            "`infolm` metric with default models requires `transformers` package be installed."
        )
    from transformers import AutoTokenizer, FlaxAutoModelForMaskedLM

    tokenizer = AutoTokenizer.from_pretrained(model_name_or_path)
    model = FlaxAutoModelForMaskedLM.from_pretrained(model_name_or_path)
    return tokenizer, model


def infolm(
    preds: Union[str, Sequence[str]],
    target: Union[str, Sequence[str]],
    model_name_or_path: str = _DEFAULT_INFOLM_MODEL,
    temperature: float = 0.25,
    information_measure: str = "kl_divergence",
    idf: bool = True,
    alpha: Optional[float] = None,
    beta: Optional[float] = None,
    device: Optional[Any] = None,
    max_length: Optional[int] = None,
    batch_size: int = 64,
    num_threads: int = 0,
    verbose: bool = True,
    return_sentence_level_score: bool = False,
    model: Optional[Any] = None,
    user_tokenizer: Optional[Any] = None,
) -> Union[Array, Tuple[Array, Array]]:
    """InfoLM score (reference infolm.py:534-642).

    Pass a Flax masked-LM ``model`` + ``user_tokenizer`` directly to skip the
    pretrained download (offline use).

    Example (requires network access for the default model):
        >>> preds = ['he read the book because he was interested in world history']
        >>> target = ['he was interested in world history because he read the book']
        >>> infolm(preds, target, model_name_or_path='google/bert_uncased_L-2_H-128_A-2', idf=False)  # doctest: +SKIP
        Array(-0.1784, dtype=float32)
    """
    if (model is None) != (user_tokenizer is None):
        raise ValueError("Arguments `model` and `user_tokenizer` must be provided together (or both omitted).")
    if model is None:
        tokenizer, model = _load_tokenizer_and_model(model_name_or_path)
    else:
        tokenizer = user_tokenizer
    information_measure_cls = _InformationMeasure(information_measure, alpha, beta)
    max_length = max_length or model.config.max_length
    special_tokens_map = _get_special_tokens_map(tokenizer)

    preds_input_ids, preds_attention_mask, target_input_ids, target_attention_mask = _infolm_update(
        preds, target, tokenizer, max_length
    )
    info_lm_score = _infolm_compute(
        model,
        (preds_input_ids, preds_attention_mask),
        (target_input_ids, target_attention_mask),
        temperature,
        idf,
        information_measure_cls,
        special_tokens_map,
        batch_size,
    )

    if return_sentence_level_score:
        return info_lm_score.mean(), info_lm_score
    return info_lm_score.mean()
