"""SacreBLEU score (reference src/torchmetrics/functional/text/sacre_bleu.py).

Implements the five sacrebleu tokenization schemes ('none', '13a', 'zh', 'intl',
'char') following the published sacrebleu tokenizer specifications
(github.com/mjpost/sacrebleu/tree/master/sacrebleu/tokenizers), then reuses the BLEU
accumulation kernel.
"""

from __future__ import annotations

import re
from functools import partial
from typing import Optional, Sequence, Tuple, Union

from jax import Array
import jax.numpy as jnp

from metrics_tpu.functional.text.bleu import _bleu_score_compute, _bleu_score_update
from metrics_tpu.utils.imports import _REGEX_AVAILABLE

AVAILABLE_TOKENIZERS = ("none", "13a", "zh", "intl", "char")

# CJK / fullwidth unicode block boundaries used by the sacrebleu `zh` tokenizer
_UCODE_RANGES = (
    ("\u3400", "\u4db5"),  # CJK Unified Ideographs Extension A
    ("\u4e00", "\u9fa5"),  # CJK Unified Ideographs
    ("\u9fa6", "\u9fbb"),  # CJK Unified Ideographs, release 4.1
    ("\uf900", "\ufa2d"),  # CJK Compatibility Ideographs
    ("\ufa30", "\ufa6a"),  # CJK Compatibility Ideographs, release 3.2
    ("\ufa70", "\ufad9"),  # CJK Compatibility Ideographs, release 4.1
    ("\U00020000", "\U0002a6d6"),  # CJK Unified Ideographs Extension B
    ("\U0002f800", "\U0002fa1d"),  # CJK Compatibility Supplement
    ("\uff00", "\uffef"),  # full-width ASCII/punctuation, half-width kana, Hangul
    ("\u2e80", "\u2eff"),  # CJK Radicals Supplement
    ("\u3000", "\u303f"),  # CJK punctuation
    ("\u31c0", "\u31ef"),  # CJK strokes
    ("\u2f00", "\u2fdf"),  # Kangxi radicals
    ("\u2ff0", "\u2fff"),  # Chinese character structure
    ("\u3100", "\u312f"),  # phonetic symbols
    ("\u31a0", "\u31bf"),  # phonetic symbols (Taiwanese/Hakka)
    ("\ufe10", "\ufe1f"),
    ("\ufe30", "\ufe4f"),
    ("\u2600", "\u26ff"),
    ("\u2700", "\u27bf"),
    ("\u3200", "\u32ff"),
    ("\u3300", "\u33ff"),
)


class _SacreBLEUTokenizer:
    """Tokenizers matching sacrebleu (reference sacre_bleu.py:80-273)."""

    _REGEX = (
        (re.compile(r"([\{-\~\[-\` -\&\(-\+\:-\@\/])"), r" \1 "),
        (re.compile(r"([^0-9])([\.,])"), r"\1 \2 "),
        (re.compile(r"([\.,])([^0-9])"), r" \1 \2"),
        (re.compile(r"([0-9])(-)"), r"\1 \2 "),
    )

    if _REGEX_AVAILABLE:
        import regex

        _INT_REGEX = (
            (regex.compile(r"(\P{N})(\p{P})"), r"\1 \2 "),
            (regex.compile(r"(\p{P})(\P{N})"), r" \1 \2"),
            (regex.compile(r"(\p{S})"), r" \1 "),
        )

    _TOKENIZE_FN = {
        "none": "_tokenize_base",
        "13a": "_tokenize_13a",
        "zh": "_tokenize_zh",
        "intl": "_tokenize_international",
        "char": "_tokenize_char",
    }

    def __init__(self, tokenize: str, lowercase: bool = False) -> None:
        self.tokenize_fn = getattr(self, self._TOKENIZE_FN[tokenize])
        self.lowercase = lowercase

    def __call__(self, line: str) -> Sequence[str]:
        tokenized_line = self.tokenize_fn(line)
        return self._lower(tokenized_line, self.lowercase).split()

    @classmethod
    def tokenize(cls, line: str, tokenize: str, lowercase: bool = False) -> Sequence[str]:
        tokenize_fn = getattr(cls, cls._TOKENIZE_FN[tokenize])
        tokenized_line = tokenize_fn(line)
        return cls._lower(tokenized_line, lowercase).split()

    @classmethod
    def _tokenize_regex(cls, line: str) -> str:
        for _re, repl in cls._REGEX:
            line = _re.sub(repl, line)
        return " ".join(line.split())

    @staticmethod
    def _is_chinese_char(uchar: str) -> bool:
        return any(start <= uchar <= end for start, end in _UCODE_RANGES)

    @classmethod
    def _tokenize_base(cls, line: str) -> str:
        return line

    @classmethod
    def _tokenize_13a(cls, line: str) -> str:
        """mteval-v13a-equivalent minimal tokenization (WMT standard)."""
        line = line.replace("<skipped>", "")
        line = line.replace("-\n", "")
        line = line.replace("\n", " ")

        if "&" in line:
            line = line.replace("&quot;", '"')
            line = line.replace("&amp;", "&")
            line = line.replace("&lt;", "<")
            line = line.replace("&gt;", ">")

        return cls._tokenize_regex(line)

    @classmethod
    def _tokenize_zh(cls, line: str) -> str:
        """Space-separate CJK chars, then apply the 13a regex pass."""
        line = line.strip()
        line_in_chars = ""
        for char in line:
            if cls._is_chinese_char(char):
                line_in_chars += " " + char + " "
            else:
                line_in_chars += char
        return cls._tokenize_regex(line_in_chars)

    @classmethod
    def _tokenize_international(cls, line: str) -> str:
        """mteval-v14 international tokenization via unicode-category regexes."""
        for _re, repl in cls._INT_REGEX:
            line = _re.sub(repl, line)
        return " ".join(line.split())

    @classmethod
    def _tokenize_char(cls, line: str) -> str:
        return " ".join(char for char in line)

    @staticmethod
    def _lower(line: str, lowercase: bool) -> str:
        return line.lower() if lowercase else line


def sacre_bleu_score(
    preds: Sequence[str],
    target: Sequence[Sequence[str]],
    n_gram: int = 4,
    smooth: bool = False,
    tokenize: str = "13a",
    lowercase: bool = False,
    weights: Optional[Sequence[float]] = None,
) -> Array:
    """SacreBLEU-compatible BLEU score (reference sacre_bleu.py:276-361).

    Example:
        >>> preds = ['the cat is on the mat']
        >>> target = [['there is a cat on the mat', 'a cat is on the mat']]
        >>> float(sacre_bleu_score(preds, target))  # doctest: +ELLIPSIS
        0.7598...
    """
    if tokenize not in AVAILABLE_TOKENIZERS:
        raise ValueError(f"Argument `tokenize` expected to be one of {AVAILABLE_TOKENIZERS} but got {tokenize}.")
    if len(preds) != len(target):
        raise ValueError(f"Corpus has different size {len(preds)} != {len(target)}")
    if tokenize == "intl" and not _REGEX_AVAILABLE:
        raise ModuleNotFoundError("`'intl'` tokenization requires that `regex` is installed.")
    if weights is not None and len(weights) != n_gram:
        raise ValueError(f"List of weights has different weights than `n_gram`: {len(weights)} != {n_gram}")
    if weights is None:
        weights = [1.0 / n_gram] * n_gram

    tokenize_fn = partial(_SacreBLEUTokenizer.tokenize, tokenize=tokenize, lowercase=lowercase)
    numerator, denominator, preds_len, target_len = _bleu_score_update(preds, target, n_gram, tokenize_fn)
    return _bleu_score_compute(
        jnp.asarray(preds_len), jnp.asarray(target_len), jnp.asarray(numerator), jnp.asarray(denominator),
        n_gram, weights, smooth,
    )
