"""Word error rate (reference src/torchmetrics/functional/text/wer.py)."""

from __future__ import annotations

from typing import List, Tuple, Union

import jax.numpy as jnp
from jax import Array

from metrics_tpu.functional.text.helper import _edit_distances_batched


def _wer_update(preds: Union[str, List[str]], target: Union[str, List[str]]) -> Tuple[Array, Array]:
    """Sum edit operations and reference word counts (reference wer.py:23-49)."""
    if isinstance(preds, str):
        preds = [preds]
    if isinstance(target, str):
        target = [target]
    pairs = [(pred.split(), tgt.split()) for pred, tgt in zip(preds, target)]
    errors = int(_edit_distances_batched(pairs).sum())
    total = sum(len(tgt) for _, tgt in pairs)
    return jnp.asarray(errors, jnp.float32), jnp.asarray(total, jnp.float32)


def _wer_compute(errors: Array, total: Array) -> Array:
    return errors / total


def word_error_rate(preds: Union[str, List[str]], target: Union[str, List[str]]) -> Array:
    """Word error rate of transcriptions vs references (reference wer.py:64-83).

    Example:
        >>> preds = ["this is the prediction", "there is an other sample"]
        >>> target = ["this is the reference", "there is another one"]
        >>> word_error_rate(preds=preds, target=target)
        Array(0.5, dtype=float32)
    """
    errors, total = _wer_update(preds, target)
    return _wer_compute(errors, total)
