"""BERTScore (reference src/torchmetrics/functional/text/bert.py, 426 LoC).

TPU-native redesign: embeddings come from a **Flax** HF transformer (or any
user-supplied model via ``user_forward_fn``) and the whole scoring pipeline —
normalization, special-token masking, IDF weighting, the pairwise cosine matching —
is jittable jnp math over statically padded ``[batch, layers, seq, dim]`` arrays.
The reference's DataLoader/TextDataset machinery (bert.py:386-401) collapses into a
padded-batch loop.

Note: the reference sorts each corpus by sentence length independently and returns
scores in that sorted order (helper_embedding_metric.py:84-110 with
``sort_according_length=True``, never unsorted) — a known quirk; here scores are
returned in the ORIGINAL input order, matching the original bert-score package.
"""

from __future__ import annotations

import csv
import math
from collections import Counter, defaultdict
from typing import Any, Callable, Dict, List, Optional, Union

import jax.numpy as jnp
import numpy as np
from jax import Array

from metrics_tpu.utils.imports import _TRANSFORMERS_AVAILABLE
from metrics_tpu.utils.prints import rank_zero_warn

# Default model recommended in the original implementation.
_DEFAULT_MODEL = "roberta-large"


def _process_attention_mask_for_special_tokens(attention_mask: Array) -> Array:
    """Zero the [CLS] and [SEP] positions (helper_embedding_metric.py:34-49)."""
    attention_mask = attention_mask.at[:, 0].set(0)
    sep_token_position = jnp.argmax(jnp.cumsum(attention_mask - 0.1, axis=-1), axis=-1)
    attention_mask = attention_mask.at[jnp.arange(attention_mask.shape[0]), sep_token_position].set(0)
    return attention_mask


def _get_tokens_idf(input_ids: np.ndarray, attention_mask: np.ndarray) -> Dict[int, float]:
    """IDF over the reference corpus: log((N+1)/(df+1)) (helper_embedding_metric.py:230-249)."""
    num_sentences = len(input_ids)
    token_counter: Counter = Counter()
    for ids, mask in zip(input_ids, attention_mask):
        token_counter.update(set(ids[mask.astype(bool)].tolist()))
    tokens_idf: Dict[int, float] = defaultdict(lambda: math.log((num_sentences + 1) / 1))
    tokens_idf.update(
        {idx: math.log((num_sentences + 1) / (occurrence + 1)) for idx, occurrence in token_counter.items()}
    )
    return tokens_idf


def _embed(
    input_ids: np.ndarray,
    attention_mask: np.ndarray,
    model: Any,
    num_layers: Optional[int],
    all_layers: bool,
    idf: bool,
    tokens_idf: Optional[Dict[int, float]],
    batch_size: int,
    user_forward_fn: Optional[Callable],
):
    """Normalized masked embeddings [N, L, S, D] + per-sentence idf scale [N, S]."""
    outs = []
    for start in range(0, len(input_ids), batch_size):
        ids = jnp.asarray(input_ids[start : start + batch_size])
        mask = jnp.asarray(attention_mask[start : start + batch_size])
        if user_forward_fn is not None:
            if all_layers:
                raise ValueError("The option `all_layers=True` can be used only with default `transformers` models.")
            out = jnp.asarray(user_forward_fn(model, {"input_ids": ids, "attention_mask": mask}))
            if out.shape[:2] != ids.shape:
                raise ValueError(
                    "The model output must be a [batch, seq_len, model_dim] tensor aligned with input_ids."
                )
            out = out[:, None]  # layer axis
        else:
            result = model(input_ids=ids, attention_mask=mask, output_hidden_states=True)
            hidden = result.hidden_states
            if all_layers:
                out = jnp.stack(hidden, axis=1)
            else:
                out = jnp.asarray(hidden[num_layers if num_layers is not None else -1])[:, None]
        outs.append(out)
    out = jnp.concatenate(outs, axis=0)

    # normalize and zero special/pad tokens
    out = out / jnp.maximum(jnp.linalg.norm(out, axis=-1, keepdims=True), 1e-12)
    processed_mask = _process_attention_mask_for_special_tokens(jnp.asarray(attention_mask))
    out = jnp.einsum("blsd,bs->blsd", out, processed_mask.astype(out.dtype))

    if idf:
        assert tokens_idf is not None
        idf_np = np.vectorize(lambda t: tokens_idf[int(t)])(input_ids).astype(np.float32)
        input_ids_idf = jnp.asarray(idf_np) * processed_mask
    else:
        input_ids_idf = processed_mask.astype(out.dtype)
    input_ids_idf = input_ids_idf / jnp.sum(input_ids_idf, axis=-1, keepdims=True)

    return out, input_ids_idf


def _get_precision_recall_f1(
    preds_embeddings: Array,
    target_embeddings: Array,
    preds_idf_scale: Array,
    target_idf_scale: Array,
):
    """Greedy cosine matching (reference bert.py:124-157); jittable."""
    cos_sim = jnp.einsum("blpd,blrd->blpr", preds_embeddings, target_embeddings)
    precision = jnp.einsum("bls,bs->bls", jnp.max(cos_sim, axis=3), preds_idf_scale).sum(-1)
    recall = jnp.einsum("bls,bs->bls", jnp.max(cos_sim, axis=2), target_idf_scale).sum(-1)
    f1_score = 2 * precision * recall / (precision + recall)
    f1_score = jnp.nan_to_num(f1_score, nan=0.0)
    # match original bert-score output layout: [layers, batch] squeezed
    return (
        jnp.squeeze(precision.swapaxes(0, 1)),
        jnp.squeeze(recall.swapaxes(0, 1)),
        jnp.squeeze(f1_score.swapaxes(0, 1)),
    )


def _load_baseline(baseline_path: Optional[str] = None) -> Optional[np.ndarray]:
    """Load a local rescale-baseline csv/tsv (bert.py:166-213; no-network variant)."""
    if baseline_path is None:
        rank_zero_warn("Baseline was not successfully loaded. No baseline is going to be used.")
        return None
    with open(baseline_path) as fname:
        rows = [[float(item) for item in row] for idx, row in enumerate(csv.reader(fname)) if idx > 0]
    return np.asarray(rows)[:, 1:]


def _rescale_metrics_with_baseline(
    precision: Array,
    recall: Array,
    f1_score: Array,
    baseline: np.ndarray,
    num_layers: Optional[int] = None,
    all_layers: bool = False,
):
    if num_layers is None and all_layers is False:
        num_layers = -1
    all_metrics = jnp.stack([precision, recall, f1_score], axis=-1)
    baseline = jnp.asarray(baseline)
    baseline_scale = baseline[:, None] if all_layers else baseline[num_layers]
    all_metrics = (all_metrics - baseline_scale) / (1 - baseline_scale)
    return all_metrics[..., 0], all_metrics[..., 1], all_metrics[..., 2]


def _tokenize(text: List[str], tokenizer: Any, max_length: int):
    enc = tokenizer(text, padding="max_length", truncation=True, max_length=max_length, return_tensors="np")
    input_ids = np.asarray(enc["input_ids"])
    attention_mask = np.asarray(enc["attention_mask"])
    # trim shared padding to the longest sequence in the corpus
    max_len = int(attention_mask.sum(1).max())
    return input_ids[:, :max_len], attention_mask[:, :max_len]


def bert_score(
    preds: Union[List[str], Dict[str, Any]],
    target: Union[List[str], Dict[str, Any]],
    model_name_or_path: Optional[str] = None,
    num_layers: Optional[int] = None,
    all_layers: bool = False,
    model: Optional[Any] = None,
    user_tokenizer: Any = None,
    user_forward_fn: Optional[Callable] = None,
    verbose: bool = False,
    idf: bool = False,
    device: Optional[Any] = None,
    max_length: int = 512,
    batch_size: int = 64,
    num_threads: int = 0,
    return_hash: bool = False,
    lang: str = "en",
    rescale_with_baseline: bool = False,
    baseline_path: Optional[str] = None,
    baseline_url: Optional[str] = None,
) -> Dict[str, Union[List[float], str]]:
    """BERTScore precision/recall/F1 per sentence pair (reference bert.py:234-426).

    ``model`` may be any Flax HF transformer (or arbitrary object when paired with
    ``user_forward_fn(model, batch) -> [batch, seq, dim]`` embeddings). Without an
    explicit model, ``model_name_or_path`` is loaded via ``FlaxAutoModel``.
    """
    if len(preds) != len(target):
        raise ValueError("Number of predicted and reference sententes must be the same!")

    if model is None:
        if not _TRANSFORMERS_AVAILABLE:
            raise ModuleNotFoundError(
                "`bert_score` metric with default models requires `transformers` package be installed."
            )
        if model_name_or_path is None:
            rank_zero_warn(
                "The argument `model_name_or_path` was not specified while it is required when default"
                f" `transformers` model are used. It is, therefore, used the default recommended model -"
                f" {_DEFAULT_MODEL}."
            )
        from transformers import AutoTokenizer, FlaxAutoModel

        tokenizer = AutoTokenizer.from_pretrained(model_name_or_path or _DEFAULT_MODEL)
        model = FlaxAutoModel.from_pretrained(model_name_or_path or _DEFAULT_MODEL)
    else:
        tokenizer = user_tokenizer

    try:
        if num_layers and num_layers > model.config.num_hidden_layers:
            raise ValueError(
                f"num_layers={num_layers} is forbidden for {model_name_or_path}."
                f" Please use num_layers <= {model.config.num_hidden_layers}"
            )
    except AttributeError:
        rank_zero_warn("It was not possible to retrieve the parameter `num_layers` from the model specification.")

    _are_empty_lists = all(isinstance(text, list) and len(text) == 0 for text in (preds, target))
    _are_valid_lists = all(
        isinstance(text, list) and len(text) > 0 and isinstance(text[0], str) for text in (preds, target)
    )
    _are_valid_tensors = all(
        isinstance(text, dict) and "input_ids" in text for text in (preds, target)
    )
    if _are_empty_lists:
        rank_zero_warn("Predictions and references are empty.")
        output_dict: Dict[str, Union[List[float], str]] = {"precision": [0.0], "recall": [0.0], "f1": [0.0]}
        if return_hash:
            output_dict.update({"hash": _get_hash(model_name_or_path, num_layers, idf)})
        return output_dict

    baseline = _load_baseline(baseline_path) if rescale_with_baseline else None

    if _are_valid_lists:
        if tokenizer is None:
            raise ValueError(
                "A `user_tokenizer` must be provided together with a user `model` when passing raw sentence"
                " lists (tokenized `input_ids`/`attention_mask` dicts need no tokenizer)."
            )
        target_ids, target_mask = _tokenize(list(target), tokenizer, max_length)
        preds_ids, preds_mask = _tokenize(list(preds), tokenizer, max_length)
    elif _are_valid_tensors:
        target_ids, target_mask = np.asarray(target["input_ids"]), np.asarray(target["attention_mask"])
        preds_ids, preds_mask = np.asarray(preds["input_ids"]), np.asarray(preds["attention_mask"])
    else:
        raise ValueError("Invalid input provided.")

    tokens_idf = _get_tokens_idf(target_ids, target_mask) if idf else None

    target_emb, target_idf_scale = _embed(
        target_ids, target_mask, model, num_layers, all_layers, idf, tokens_idf, batch_size, user_forward_fn
    )
    preds_emb, preds_idf_scale = _embed(
        preds_ids, preds_mask, model, num_layers, all_layers, idf, tokens_idf, batch_size, user_forward_fn
    )

    # pad the sequence axes to a common length so the einsum shapes agree
    seq = max(preds_emb.shape[2], target_emb.shape[2])
    def _pad(e, s):
        pad = [(0, 0)] * e.ndim
        pad[2] = (0, s - e.shape[2])
        return jnp.pad(e, pad)
    def _pad_scale(x, s):
        return jnp.pad(x, [(0, 0), (0, s - x.shape[1])])
    preds_emb, target_emb = _pad(preds_emb, seq), _pad(target_emb, seq)
    preds_idf_scale, target_idf_scale = _pad_scale(preds_idf_scale, seq), _pad_scale(target_idf_scale, seq)

    precision, recall, f1_score = _get_precision_recall_f1(preds_emb, target_emb, preds_idf_scale, target_idf_scale)

    if baseline is not None:
        precision, recall, f1_score = _rescale_metrics_with_baseline(
            precision, recall, f1_score, baseline, num_layers, all_layers
        )

    output_dict = {
        "precision": np.atleast_1d(np.asarray(precision)).tolist(),
        "recall": np.atleast_1d(np.asarray(recall)).tolist(),
        "f1": np.atleast_1d(np.asarray(f1_score)).tolist(),
    }
    if return_hash:
        output_dict.update({"hash": _get_hash(model_name_or_path, num_layers, idf)})
    return output_dict


def _get_hash(model_name_or_path: Optional[str] = None, num_layers: Optional[int] = None, idf: bool = False) -> str:
    return f"{model_name_or_path}_L{num_layers}{'_idf' if idf else '_no-idf'}"
