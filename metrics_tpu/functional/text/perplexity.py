"""Perplexity (reference src/torchmetrics/functional/text/perplexity.py).

Fully jittable kernel: log-softmax + gather + masked sum. ``ignore_index`` is handled
as a 0-weight mask (SURVEY §7.1: masked-weight reformulation instead of boolean
filtering) so shapes stay static under jit. The reference materializes the full
softmax and an O(N²) gather (``probs[:, target].diagonal()``, perplexity.py:95);
here it is a take_along_axis on the log-softmax — O(N) memory and numerically safer.
"""

from __future__ import annotations

from typing import Optional, Tuple

import jax
import jax.numpy as jnp
from jax import Array


def _check_shape_and_type_consistency(preds: Array, target: Array) -> None:
    """Validate [B, S, V] preds vs [B, S] integer target (reference perplexity.py:24-65)."""
    if preds.ndim != 3:
        raise ValueError(
            "Input tensor `preds` is expected to have 3 dimensions, [batch_size, seq_len, vocab_size],"
            f" but got {preds.ndim}."
        )
    if target.ndim != 2:
        raise ValueError(
            f"Input tensor `target` is expected to have 2 dimensions, [batch_size, seq_len], but got {target.ndim}."
        )
    if preds.shape[:2] != target.shape:
        raise ValueError(
            "Input tensors `preds` and `target` are expected to have equaling first two dimensions,"
            f" [batch_size, seq_len], but got {preds.shape[:2]} and {target.shape}."
        )
    if not jnp.issubdtype(preds.dtype, jnp.floating):
        raise TypeError(f"Input tensor `preds` is expected to be of floating point type but got {preds.dtype}.")
    if not jnp.issubdtype(target.dtype, jnp.integer):
        raise TypeError(f"Input tensor `target` is expected to be of integer type but got {target.dtype}.")


def _perplexity_update(preds: Array, target: Array, ignore_index: Optional[int] = None) -> Tuple[Array, Array]:
    """Return (-sum log p(target), token count); jit-safe body after host-side checks."""
    _check_shape_and_type_consistency(preds, target)

    logprobs = jax.nn.log_softmax(preds.reshape(-1, preds.shape[-1]).astype(jnp.float32), axis=-1)
    target = target.reshape(-1)

    if ignore_index is not None:
        mask = target != ignore_index
        target = jnp.where(mask, target, 0)
    else:
        mask = jnp.ones_like(target, dtype=bool)

    token_logprobs = jnp.take_along_axis(logprobs, target[:, None], axis=-1)[:, 0]
    total_log_probs = -jnp.sum(token_logprobs * mask)
    count = jnp.sum(mask).astype(jnp.float32)
    return total_log_probs, count


def _perplexity_compute(total: Array, count: Array) -> Array:
    return jnp.exp(total / count)


def perplexity(preds: Array, target: Array, ignore_index: Optional[int] = None) -> Array:
    """Perplexity of a language model's token probabilities (reference perplexity.py:114-139).

    Args:
        preds: Unnormalized logits for each token, shape ``[batch, seq, vocab]``.
        target: Ground-truth token ids, shape ``[batch, seq]``.
        ignore_index: Target class that does not contribute to the score.

    Example:
        >>> import jax.numpy as jnp
        >>> from metrics_tpu.functional import perplexity
        >>> import jax
        >>> logits = jax.random.normal(jax.random.PRNGKey(0), (2, 4, 6))
        >>> target = jnp.array([[0, 1, 2, 3], [4, 5, 0, 1]])
        >>> perplexity(logits, target)
        Array(4.349334, dtype=float32)
    """
    total, count = _perplexity_update(preds, target, ignore_index)
    return _perplexity_compute(total, count)
