"""Extended edit distance (reference src/torchmetrics/functional/text/eed.py).

Implements the EED measure of Stanchev, Wang & Ney (WMT 2019): a CDER-style
character-level alignment grid with uniform deletion/insertion costs, a long-jump
operation at blank characters, and a coverage penalty for repeated visits.

TPU-first redesign of the inner DP: the reference runs a pure-Python O(|ref|·|hyp|)
double loop (eed.py:114-170). Here the within-row dependency
``next[i] = min(next[i-1] + deletion, cand[i])`` is closed-form as a running
prefix-min of ``cand[i] - i·deletion`` (numpy ``minimum.accumulate``), so only the
O(|ref|) outer loop stays in Python with vector work per row.
"""

from __future__ import annotations

import re
import unicodedata
from typing import List, Optional, Sequence, Tuple, Union

import jax.numpy as jnp
import numpy as np
from jax import Array

from metrics_tpu.functional.text.helper import _validate_inputs


def _eed_function(
    hyp: str,
    ref: str,
    alpha: float = 2.0,
    rho: float = 0.3,
    deletion: float = 0.2,
    insertion: float = 1.0,
) -> float:
    """Sentence-level EED score in [0, 1] (reference eed.py:114-170), vectorized.

    The within-row deletion chain is resolved by iterating the one-step relaxation
    ``next[i] = min(next[i], next[i-1] + deletion)`` to a fixpoint: each sweep is
    vectorized, and because every sweep adds exactly one ``+ deletion`` to values
    computed in the previous sweep, the resulting sums carry the same left-to-right
    FP association as the sequential recurrence — bit-identical results, so the
    argmin-tie-sensitive coverage term matches a sequential implementation exactly.
    Sweep count is bounded by the longest deletion run (short in practice).

    Args:
        hyp: hypothesis string (character-level, spaces included)
        ref: reference string
        alpha: jump penalty
        rho: coverage (revisit) penalty
        deletion: deletion cost
        insertion: insertion/substitution cost
    """
    hyp_arr = np.frombuffer(hyp.encode("utf-32-le"), dtype=np.uint32)
    ref_arr = np.frombuffer(ref.encode("utf-32-le"), dtype=np.uint32)
    n = len(hyp_arr)

    number_of_visits = np.full(n + 1, -1, dtype=np.int64)
    row = np.ones(n + 1)
    row[0] = 0.0  # CDER initialisation: (0,0)=0, rest 1

    for w in range(1, len(ref_arr) + 1):
        # cand[i] = min(substitution/identity from row[i-1], insertion from row[i])
        sub = row[:-1] + (hyp_arr != ref_arr[w - 1])
        cand = np.empty(n + 1)
        cand[0] = row[0] + 1.0
        if n:
            cand[1:] = np.minimum(sub, row[1:] + insertion)
        # fold in the within-row deletion chain: relax to fixpoint (see docstring)
        next_row = cand
        while True:
            relaxed = np.minimum(next_row[1:], next_row[:-1] + deletion)
            if np.array_equal(relaxed, next_row[1:]):
                break
            next_row = np.concatenate((next_row[:1], relaxed))

        min_index = int(np.argmin(next_row))
        number_of_visits[min_index] += 1

        # long jump from the per-row minimum at word boundaries
        if ref[w - 1] == " ":
            next_row = np.minimum(next_row, alpha + next_row[min_index])

        row = next_row

    coverage = rho * float(np.where(number_of_visits >= 0, number_of_visits, 1).sum())
    return min(1.0, (row[-1] + coverage) / (float(len(ref_arr)) + coverage))


def _preprocess_en(sentence: str) -> str:
    """English EED preprocessing: spaced interpunction, squeezed abbreviations."""
    if not isinstance(sentence, str):
        raise ValueError(f"Only strings allowed during preprocessing step, found {type(sentence)} instead")

    sentence = sentence.rstrip()

    for pattern, replacement in ((".", " ."), ("!", " !"), ("?", " ?"), (",", " ,")):
        sentence = sentence.replace(pattern, replacement)

    rules_re = [
        (r"\s+", r" "),  # get rid of extra spaces
        (r"(\d) ([.,]) (\d)", r"\1\2\3"),  # 0 . 1 -> 0.1
        (r"(Dr|Jr|Prof|Rev|Gen|Mr|Mt|Mrs|Ms) .", r"\1."),  # Mr . -> Mr.
    ]
    for pattern, replacement in rules_re:
        sentence = re.sub(pattern, replacement, sentence)

    for pattern, replacement in (("e . g .", "e.g."), ("i . e .", "i.e."), ("U . S .", "U.S.")):
        sentence = sentence.replace(pattern, replacement)

    return " " + sentence + " "


def _preprocess_ja(sentence: str) -> str:
    """Japanese EED preprocessing: NFKC normalization."""
    if not isinstance(sentence, str):
        raise ValueError(f"Only strings allowed during preprocessing step, found {type(sentence)} instead")
    return unicodedata.normalize("NFKC", sentence.rstrip())


def _preprocess_sentences(
    preds: Union[str, Sequence[str]],
    target: Sequence[Union[str, Sequence[str]]],
    language: str,
) -> Tuple[Sequence[str], Sequence[Sequence[str]]]:
    target, preds = _validate_inputs(hypothesis_corpus=preds, reference_corpus=target)

    if language == "en":
        preprocess_function = _preprocess_en
    elif language == "ja":
        preprocess_function = _preprocess_ja
    else:
        raise ValueError(f"Expected argument `language` to either be `en` or `ja` but got {language}")

    preds = [preprocess_function(pred) for pred in preds]
    target = [[preprocess_function(ref) for ref in reference] for reference in target]
    return preds, target


def _compute_sentence_statistics(
    preds_word: str,
    target_words: Sequence[str],
    alpha: float = 2.0,
    rho: float = 0.3,
    deletion: float = 0.2,
    insertion: float = 1.0,
) -> float:
    """Best (lowest) score over all references (reference eed.py:285-313)."""
    return min(_eed_function(preds_word, reference, alpha, rho, deletion, insertion) for reference in target_words)


def _eed_update(
    preds: Union[str, Sequence[str]],
    target: Sequence[Union[str, Sequence[str]]],
    language: str = "en",
    alpha: float = 2.0,
    rho: float = 0.3,
    deletion: float = 0.2,
    insertion: float = 1.0,
) -> List[float]:
    """Sentence-level scores for a batch (reference eed.py:316-354)."""
    preds, target = _preprocess_sentences(preds, target, language)

    # empty inputs contribute nothing
    if 0 in (len(preds), len(target[0])):
        return []

    return [
        _compute_sentence_statistics(hypothesis, target_words, alpha, rho, deletion, insertion)
        for hypothesis, target_words in zip(preds, target)
    ]


def _eed_compute(sentence_level_scores: Sequence[Array]) -> Array:
    if len(sentence_level_scores) == 0:
        return jnp.asarray(0.0, jnp.float32)
    return (jnp.sum(jnp.asarray(sentence_level_scores)) / len(sentence_level_scores)).astype(jnp.float32)


def extended_edit_distance(
    preds: Union[str, Sequence[str]],
    target: Sequence[Union[str, Sequence[str]]],
    language: str = "en",
    return_sentence_level_score: bool = False,
    alpha: float = 2.0,
    rho: float = 0.3,
    deletion: float = 0.2,
    insertion: float = 1.0,
) -> Union[Array, Tuple[Array, Array]]:
    """Extended edit distance score (reference eed.py:357-405).

    Example:
        >>> preds = ["this is the prediction", "here is an other sample"]
        >>> target = ["this is the reference", "here is another one"]
        >>> float(extended_edit_distance(preds=preds, target=target))  # doctest: +ELLIPSIS
        0.3077...
    """
    for param_name, param in zip(["alpha", "rho", "deletion", "insertion"], [alpha, rho, deletion, insertion]):
        if not isinstance(param, float) or param < 0:
            raise ValueError(f"Parameter `{param_name}` is expected to be a non-negative float.")

    sentence_level_scores = _eed_update(preds, target, language, alpha, rho, deletion, insertion)
    average = _eed_compute(sentence_level_scores)

    if return_sentence_level_score:
        return average, jnp.asarray(sentence_level_scores, jnp.float32)
    return average
