"""Extended edit distance (reference src/torchmetrics/functional/text/eed.py).

Implements the EED measure of Stanchev, Wang & Ney (WMT 2019): a CDER-style
character-level alignment grid with uniform deletion/insertion costs, a long-jump
operation at blank characters, and a coverage penalty for repeated visits.

TPU-first redesign of the inner DP: the reference runs a pure-Python O(|ref|·|hyp|)
double loop (eed.py:114-170). Here the within-row dependency
``next[i] = min(next[i-1] + deletion, cand[i])`` is closed-form as a running
prefix-min of ``cand[i] - i·deletion`` (numpy ``minimum.accumulate``), so only the
O(|ref|) outer loop stays in Python with vector work per row.
"""

from __future__ import annotations

import re
import unicodedata
from typing import Dict, List, Optional, Sequence, Tuple, Union

import jax.numpy as jnp
import numpy as np
from jax import Array

from metrics_tpu.functional.text.helper import _banded_chunks, _validate_inputs


def _eed_function(
    hyp: str,
    ref: str,
    alpha: float = 2.0,
    rho: float = 0.3,
    deletion: float = 0.2,
    insertion: float = 1.0,
) -> float:
    """Sentence-level EED score in [0, 1] (reference eed.py:114-170), vectorized.

    The within-row deletion chain is resolved by iterating the one-step relaxation
    ``next[i] = min(next[i], next[i-1] + deletion)`` to a fixpoint: each sweep is
    vectorized, and because every sweep adds exactly one ``+ deletion`` to values
    computed in the previous sweep, the resulting sums carry the same left-to-right
    FP association as the sequential recurrence — bit-identical results, so the
    argmin-tie-sensitive coverage term matches a sequential implementation exactly.
    Sweep count is bounded by the longest deletion run (short in practice).

    Args:
        hyp: hypothesis string (character-level, spaces included)
        ref: reference string
        alpha: jump penalty
        rho: coverage (revisit) penalty
        deletion: deletion cost
        insertion: insertion/substitution cost
    """
    hyp_arr = np.frombuffer(hyp.encode("utf-32-le"), dtype=np.uint32)
    ref_arr = np.frombuffer(ref.encode("utf-32-le"), dtype=np.uint32)
    n = len(hyp_arr)

    number_of_visits = np.full(n + 1, -1, dtype=np.int64)
    row = np.ones(n + 1)
    row[0] = 0.0  # CDER initialisation: (0,0)=0, rest 1

    for w in range(1, len(ref_arr) + 1):
        # cand[i] = min(substitution/identity from row[i-1], insertion from row[i])
        sub = row[:-1] + (hyp_arr != ref_arr[w - 1])
        cand = np.empty(n + 1)
        cand[0] = row[0] + 1.0
        if n:
            cand[1:] = np.minimum(sub, row[1:] + insertion)
        # fold in the within-row deletion chain: relax to fixpoint (see docstring)
        next_row = cand
        while True:
            relaxed = np.minimum(next_row[1:], next_row[:-1] + deletion)
            if np.array_equal(relaxed, next_row[1:]):
                break
            next_row = np.concatenate((next_row[:1], relaxed))

        min_index = int(np.argmin(next_row))
        number_of_visits[min_index] += 1

        # long jump from the per-row minimum at word boundaries
        if ref[w - 1] == " ":
            next_row = np.minimum(next_row, alpha + next_row[min_index])

        row = next_row

    coverage = rho * float(np.where(number_of_visits >= 0, number_of_visits, 1).sum())
    return min(1.0, (row[-1] + coverage) / (float(len(ref_arr)) + coverage))


def _preprocess_en(sentence: str) -> str:
    """English EED preprocessing: spaced interpunction, squeezed abbreviations."""
    if not isinstance(sentence, str):
        raise ValueError(f"Only strings allowed during preprocessing step, found {type(sentence)} instead")

    sentence = sentence.rstrip()

    for pattern, replacement in ((".", " ."), ("!", " !"), ("?", " ?"), (",", " ,")):
        sentence = sentence.replace(pattern, replacement)

    rules_re = [
        (r"\s+", r" "),  # get rid of extra spaces
        (r"(\d) ([.,]) (\d)", r"\1\2\3"),  # 0 . 1 -> 0.1
        (r"(Dr|Jr|Prof|Rev|Gen|Mr|Mt|Mrs|Ms) .", r"\1."),  # Mr . -> Mr.
    ]
    for pattern, replacement in rules_re:
        sentence = re.sub(pattern, replacement, sentence)

    for pattern, replacement in (("e . g .", "e.g."), ("i . e .", "i.e."), ("U . S .", "U.S.")):
        sentence = sentence.replace(pattern, replacement)

    return " " + sentence + " "


def _preprocess_ja(sentence: str) -> str:
    """Japanese EED preprocessing: NFKC normalization."""
    if not isinstance(sentence, str):
        raise ValueError(f"Only strings allowed during preprocessing step, found {type(sentence)} instead")
    return unicodedata.normalize("NFKC", sentence.rstrip())


def _preprocess_sentences(
    preds: Union[str, Sequence[str]],
    target: Sequence[Union[str, Sequence[str]]],
    language: str,
) -> Tuple[Sequence[str], Sequence[Sequence[str]]]:
    target, preds = _validate_inputs(hypothesis_corpus=preds, reference_corpus=target)

    if language == "en":
        preprocess_function = _preprocess_en
    elif language == "ja":
        preprocess_function = _preprocess_ja
    else:
        raise ValueError(f"Expected argument `language` to either be `en` or `ja` but got {language}")

    preds = [preprocess_function(pred) for pred in preds]
    target = [[preprocess_function(ref) for ref in reference] for reference in target]
    return preds, target


def _eed_scores_batched(
    pairs: Sequence[Tuple[str, str]],
    alpha: float = 2.0,
    rho: float = 0.3,
    deletion: float = 0.2,
    insertion: float = 1.0,
) -> np.ndarray:
    """EED scores for many (hyp, ref) pairs in one lockstep DP.

    Exactly the `_eed_function` recurrence run row-by-row across all pairs at
    once on padded (P, max_n+1) arrays. Per-pair FP operation order is
    unchanged (every op is elementwise per pair; the deletion-chain relaxation
    runs until EVERY pair converges, and extra sweeps are no-ops for already
    converged rows), so results are bit-identical to the per-pair kernel —
    asserted by tests/text/test_edit_kernels.py. Hypothesis pads sit at +inf so
    they never win the argmin/jump; rows of exhausted references freeze.
    """
    P = len(pairs)
    if P == 0:
        return np.zeros(0)
    hyps = [np.frombuffer(h.encode("utf-32-le"), dtype=np.uint32) for h, _ in pairs]
    refs = [np.frombuffer(r.encode("utf-32-le"), dtype=np.uint32) for _, r in pairs]
    n_p = np.asarray([len(h) for h in hyps])
    m_p = np.asarray([len(r) for r in refs])
    max_n, max_m = int(n_p.max()), int(m_p.max())

    hyp_pad = np.zeros((P, max_n if max_n else 1), dtype=np.uint32)
    ref_pad = np.zeros((P, max_m if max_m else 1), dtype=np.uint32)
    ref_is_space = np.zeros((P, max_m if max_m else 1), dtype=bool)
    for p in range(P):
        hyp_pad[p, : n_p[p]] = hyps[p]
        ref_pad[p, : m_p[p]] = refs[p]
        ref_is_space[p, : m_p[p]] = refs[p] == ord(" ")

    inf = np.inf
    cols = np.arange(max_n + 1)
    pad_mask = cols[None, :] > n_p[:, None]  # True at padded hypothesis cells
    row = np.ones((P, max_n + 1))
    row[:, 0] = 0.0
    row[pad_mask] = inf
    visits = np.full((P, max_n + 1), -1, dtype=np.int64)

    for w in range(1, max_m + 1):
        active = w <= m_p  # pairs whose reference still has characters
        if not active.any():
            break
        ref_ch = ref_pad[:, w - 1 : w]  # (P, 1)
        sub = row[:, :-1] + (hyp_pad != ref_ch)
        cand = np.empty_like(row)
        cand[:, 0] = row[:, 0] + 1.0
        if max_n:
            cand[:, 1:] = np.minimum(sub, row[:, 1:] + insertion)
        cand[pad_mask] = inf
        next_row = cand
        while True:
            relaxed = np.minimum(next_row[:, 1:], next_row[:, :-1] + deletion)
            relaxed[pad_mask[:, 1:]] = inf
            if np.array_equal(relaxed, next_row[:, 1:]):
                break
            next_row = np.concatenate((next_row[:, :1], relaxed), axis=1)

        min_index = np.argmin(next_row, axis=1)
        visits[active, min_index[active]] += 1

        jump = active & ref_is_space[:, w - 1]
        if jump.any():
            jumped = np.minimum(next_row, alpha + next_row[np.arange(P), min_index][:, None])
            jumped[pad_mask] = inf
            next_row[jump] = jumped[jump]

        row = np.where(active[:, None], next_row, row)

    coverage = rho * np.where(visits >= 0, visits, np.where(pad_mask, 0, 1)).sum(axis=1).astype(np.float64)
    end = row[np.arange(P), n_p]
    return np.minimum(1.0, (end + coverage) / (m_p.astype(np.float64) + coverage))


def _eed_update(
    preds: Union[str, Sequence[str]],
    target: Sequence[Union[str, Sequence[str]]],
    language: str = "en",
    alpha: float = 2.0,
    rho: float = 0.3,
    deletion: float = 0.2,
    insertion: float = 1.0,
) -> List[float]:
    """Sentence-level scores for a batch (reference eed.py:316-354)."""
    preds, target = _preprocess_sentences(preds, target, language)

    # empty inputs contribute nothing
    if 0 in (len(preds), len(target[0])):
        return []

    # flatten (hyp, ref) combinations, batch the DP in geometric length bands,
    # then take the per-hypothesis minimum over its references
    pairs: List[Tuple[str, str]] = []
    owner: List[int] = []
    for h_idx, (hypothesis, target_words) in enumerate(zip(preds, target)):
        if not target_words:
            raise ValueError("Must provide at least one reference sentence per hypothesis")
        for reference in target_words:
            pairs.append((hypothesis, reference))
            owner.append(h_idx)

    scores = np.empty(len(pairs))
    for idx in _banded_chunks([(len(h), len(r)) for h, r in pairs]):
        scores[idx] = _eed_scores_batched([pairs[p] for p in idx], alpha, rho, deletion, insertion)

    out = [float("inf")] * len(preds)
    for p, h_idx in enumerate(owner):
        if scores[p] < out[h_idx]:
            out[h_idx] = scores[p]
    return out


def _eed_compute(sentence_level_scores: Sequence[Array]) -> Array:
    if len(sentence_level_scores) == 0:
        return jnp.asarray(0.0, jnp.float32)
    return (jnp.sum(jnp.asarray(sentence_level_scores)) / len(sentence_level_scores)).astype(jnp.float32)


def extended_edit_distance(
    preds: Union[str, Sequence[str]],
    target: Sequence[Union[str, Sequence[str]]],
    language: str = "en",
    return_sentence_level_score: bool = False,
    alpha: float = 2.0,
    rho: float = 0.3,
    deletion: float = 0.2,
    insertion: float = 1.0,
) -> Union[Array, Tuple[Array, Array]]:
    """Extended edit distance score (reference eed.py:357-405).

    Example:
        >>> preds = ["this is the prediction", "here is an other sample"]
        >>> target = ["this is the reference", "here is another one"]
        >>> float(extended_edit_distance(preds=preds, target=target))  # doctest: +ELLIPSIS
        0.3077...
    """
    for param_name, param in zip(["alpha", "rho", "deletion", "insertion"], [alpha, rho, deletion, insertion]):
        if not isinstance(param, float) or param < 0:
            raise ValueError(f"Parameter `{param_name}` is expected to be a non-negative float.")

    sentence_level_scores = _eed_update(preds, target, language, alpha, rho, deletion, insertion)
    average = _eed_compute(sentence_level_scores)

    if return_sentence_level_score:
        return average, jnp.asarray(sentence_level_scores, jnp.float32)
    return average
