"""Match error rate (reference src/torchmetrics/functional/text/mer.py)."""

from __future__ import annotations

from typing import List, Tuple, Union

import jax.numpy as jnp
from jax import Array

from metrics_tpu.functional.text.helper import _edit_distances_batched


def _mer_update(preds: Union[str, List[str]], target: Union[str, List[str]]) -> Tuple[Array, Array]:
    """Sum edit operations and max(len(ref), len(pred)) (reference mer.py:23-51)."""
    if isinstance(preds, str):
        preds = [preds]
    if isinstance(target, str):
        target = [target]
    pairs = [(pred.split(), tgt.split()) for pred, tgt in zip(preds, target)]
    errors = int(_edit_distances_batched(pairs).sum())
    total = sum(max(len(tgt), len(pred)) for pred, tgt in pairs)
    return jnp.asarray(errors, jnp.float32), jnp.asarray(total, jnp.float32)


def _mer_compute(errors: Array, total: Array) -> Array:
    return errors / total


def match_error_rate(preds: Union[str, List[str]], target: Union[str, List[str]]) -> Array:
    """Match error rate of transcriptions vs references (reference mer.py:66-90).

    Example:
        >>> preds = ["this is the prediction", "there is an other sample"]
        >>> target = ["this is the reference", "there is another one"]
        >>> match_error_rate(preds=preds, target=target)  # doctest: +SKIP
        Array(0.44444445, dtype=float32)
    """
    errors, total = _mer_update(preds, target)
    return _mer_compute(errors, total)
