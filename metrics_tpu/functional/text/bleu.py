"""BLEU score (reference src/torchmetrics/functional/text/bleu.py).

Host-side n-gram counting accumulates into fixed ``(n_gram,)`` arrays — the state is
mesh-syncable with a single psum; the compute formula is jittable jnp vector math.
"""

from __future__ import annotations

from collections import Counter
from typing import Callable, Optional, Sequence, Tuple, Union

import jax.numpy as jnp
import numpy as np
from jax import Array


def _count_ngram(ngram_input_list: Sequence[str], n_gram: int) -> Counter:
    """Count all n-grams of order 1..n_gram (reference bleu.py:26-44)."""
    ngram_counter: Counter = Counter()
    for i in range(1, n_gram + 1):
        for j in range(len(ngram_input_list) - i + 1):
            ngram_counter[tuple(ngram_input_list[j : i + j])] += 1
    return ngram_counter


def _tokenize_fn(sentence: str) -> Sequence[str]:
    return sentence.split()


def _bleu_score_update(
    preds: Sequence[str],
    target: Sequence[Sequence[str]],
    n_gram: int = 4,
    tokenizer: Callable[[str], Sequence[str]] = _tokenize_fn,
) -> Tuple[np.ndarray, np.ndarray, float, float]:
    """Clipped-match numerators/denominators + length stats (reference bleu.py:59-103).

    Returns host numpy ``(numerator, denominator, preds_len, target_len)`` deltas
    that the caller adds into its states.
    """
    target_tokenized = [[tokenizer(line) if line else [] for line in t] for t in target]
    preds_tokenized = [tokenizer(line) if line else [] for line in preds]

    numerator = np.zeros(n_gram)
    denominator = np.zeros(n_gram)
    preds_len = 0.0
    target_len = 0.0

    for pred, targets in zip(preds_tokenized, target_tokenized):
        preds_len += len(pred)
        target_len_list = [len(tgt) for tgt in targets]
        target_len_diff = [abs(len(pred) - x) for x in target_len_list]
        target_len += target_len_list[target_len_diff.index(min(target_len_diff))]
        preds_counter = _count_ngram(pred, n_gram)
        target_counter: Counter = Counter()
        for tgt in targets:
            target_counter |= _count_ngram(tgt, n_gram)

        ngram_counter_clip = preds_counter & target_counter
        for counter_clip in ngram_counter_clip:
            numerator[len(counter_clip) - 1] += ngram_counter_clip[counter_clip]
        for counter in preds_counter:
            denominator[len(counter) - 1] += preds_counter[counter]

    return numerator, denominator, preds_len, target_len


def _bleu_score_compute(
    preds_len: Array,
    target_len: Array,
    numerator: Array,
    denominator: Array,
    n_gram: int,
    weights: Sequence[float],
    smooth: bool,
) -> Array:
    """Geometric-mean precision × brevity penalty (reference bleu.py:106-144); jittable."""
    if smooth:
        precision_scores = (numerator + 1.0) / (denominator + 1.0)
        precision_scores = precision_scores.at[0].set(numerator[0] / denominator[0])
    else:
        precision_scores = numerator / denominator

    log_precision_scores = jnp.asarray(weights) * jnp.log(precision_scores)
    geometric_mean = jnp.exp(jnp.sum(log_precision_scores))
    brevity_penalty = jnp.where(preds_len > target_len, 1.0, jnp.exp(1 - target_len / preds_len))
    bleu = brevity_penalty * geometric_mean
    return jnp.where(jnp.min(numerator) == 0.0, 0.0, bleu).astype(jnp.float32)


def bleu_score(
    preds: Union[str, Sequence[str]],
    target: Sequence[Union[str, Sequence[str]]],
    n_gram: int = 4,
    smooth: bool = False,
    weights: Optional[Sequence[float]] = None,
) -> Array:
    """BLEU score of machine-translated text (reference bleu.py:147-206).

    Example:
        >>> preds = ['the cat is on the mat']
        >>> target = [['there is a cat on the mat', 'a cat is on the mat']]
        >>> float(bleu_score(preds, target))  # doctest: +ELLIPSIS
        0.7598...
    """
    preds_ = [preds] if isinstance(preds, str) else preds
    target_ = [[tgt] if isinstance(tgt, str) else tgt for tgt in target]

    if len(preds_) != len(target_):
        raise ValueError(f"Corpus has different size {len(preds_)} != {len(target_)}")
    if weights is not None and len(weights) != n_gram:
        raise ValueError(f"List of weights has different weights than `n_gram`: {len(weights)} != {n_gram}")
    if weights is None:
        weights = [1.0 / n_gram] * n_gram

    numerator, denominator, preds_len, target_len = _bleu_score_update(preds_, target_, n_gram)
    return _bleu_score_compute(
        jnp.asarray(preds_len), jnp.asarray(target_len), jnp.asarray(numerator), jnp.asarray(denominator),
        n_gram, weights, smooth,
    )
