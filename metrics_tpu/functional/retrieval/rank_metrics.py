"""Per-query retrieval functionals (branch-free, jittable).

Reference parity (formula sources, one file each in the reference):
- retrieval_average_precision — functional/retrieval/average_precision.py
- retrieval_fall_out           — functional/retrieval/fall_out.py
- retrieval_hit_rate           — functional/retrieval/hit_rate.py
- retrieval_normalized_dcg     — functional/retrieval/ndcg.py
- retrieval_precision          — functional/retrieval/precision.py
- retrieval_precision_recall_curve — functional/retrieval/precision_recall_curve.py
- retrieval_r_precision        — functional/retrieval/r_precision.py
- retrieval_recall             — functional/retrieval/recall.py
- retrieval_reciprocal_rank    — functional/retrieval/reciprocal_rank.py

Each operates on the documents of a SINGLE query; grouping over queries lives in
``metrics_tpu.retrieval`` which uses a vectorised segment kernel instead of a host loop.
Empty-positive queries return 0.0 (matching the reference's early-exit), expressed as
``jnp.where`` so the functions stay traceable.
"""

from __future__ import annotations

from typing import Optional, Tuple

import jax.numpy as jnp
from jax import Array

from metrics_tpu.functional.retrieval._utils import (
    _check_retrieval_functional_inputs,
    _target_by_pred_rank,
    _validate_k,
)
from metrics_tpu.utils.compute import _safe_divide


def retrieval_average_precision(preds: Array, target: Array) -> Array:
    """AP over one query: mean of precision@hit over the hit positions.

    Example:
        >>> import jax.numpy as jnp
        >>> from metrics_tpu.functional import retrieval_average_precision
        >>> preds = jnp.array([0.9, 0.2, 0.7, 0.4])
        >>> target = jnp.array([1, 0, 1, 1])
        >>> retrieval_average_precision(preds, target)
        Array(1., dtype=float32)
    """
    preds, target = _check_retrieval_functional_inputs(preds, target)
    t = _target_by_pred_rank(preds, target).astype(jnp.float32)
    cum_hits = jnp.cumsum(t)
    prec_at = cum_hits / jnp.arange(1, t.shape[0] + 1, dtype=jnp.float32)
    total = t.sum()
    return jnp.where(total > 0, (prec_at * t).sum() / jnp.maximum(total, 1.0), 0.0)


def retrieval_precision(preds: Array, target: Array, k: Optional[int] = None, adaptive_k: bool = False) -> Array:
    """Precision@k = (# relevant in top-k) / k; ``adaptive_k`` clamps k to the query size.

    Example:
        >>> import jax.numpy as jnp
        >>> from metrics_tpu.functional import retrieval_precision
        >>> preds = jnp.array([0.9, 0.2, 0.7, 0.4])
        >>> target = jnp.array([1, 0, 1, 1])
        >>> retrieval_precision(preds, target, k=2)
        Array(1., dtype=float32)
    """
    preds, target = _check_retrieval_functional_inputs(preds, target)
    if not isinstance(adaptive_k, bool):
        raise ValueError("`adaptive_k` has to be a boolean")
    _validate_k(k)
    n = preds.shape[0]
    if k is None or (adaptive_k and k > n):
        k = n
    t = _target_by_pred_rank(preds, target).astype(jnp.float32)
    relevant = t[: min(k, n)].sum()
    return jnp.where(target.sum() > 0, relevant / k, 0.0)


def retrieval_recall(preds: Array, target: Array, k: Optional[int] = None) -> Array:
    """Recall@k = (# relevant in top-k) / (# relevant).

    Example:
        >>> import jax.numpy as jnp
        >>> from metrics_tpu.functional import retrieval_recall
        >>> preds = jnp.array([0.9, 0.2, 0.7, 0.4])
        >>> target = jnp.array([1, 0, 1, 1])
        >>> retrieval_recall(preds, target, k=2)
        Array(0.6666667, dtype=float32)
    """
    preds, target = _check_retrieval_functional_inputs(preds, target)
    _validate_k(k)
    n = preds.shape[0]
    k = n if k is None else k
    t = _target_by_pred_rank(preds, target).astype(jnp.float32)
    total = target.sum().astype(jnp.float32)
    relevant = t[: min(k, n)].sum()
    return jnp.where(total > 0, relevant / jnp.maximum(total, 1.0), 0.0)


def retrieval_fall_out(preds: Array, target: Array, k: Optional[int] = None) -> Array:
    """Fall-out@k = (# NON-relevant in top-k) / (# non-relevant).

    Example:
        >>> import jax.numpy as jnp
        >>> from metrics_tpu.functional import retrieval_fall_out
        >>> preds = jnp.array([0.9, 0.2, 0.7, 0.4])
        >>> target = jnp.array([1, 0, 1, 1])
        >>> retrieval_fall_out(preds, target)
        Array(1., dtype=float32)
    """
    preds, target = _check_retrieval_functional_inputs(preds, target)
    _validate_k(k)
    n = preds.shape[0]
    k = n if k is None else k
    neg = 1 - _target_by_pred_rank(preds, target).astype(jnp.float32)
    total_neg = neg.sum()
    retrieved_neg = neg[: min(k, n)].sum()
    return jnp.where(total_neg > 0, retrieved_neg / jnp.maximum(total_neg, 1.0), 0.0)


def retrieval_hit_rate(preds: Array, target: Array, k: Optional[int] = None) -> Array:
    """1.0 if any relevant document is in the top-k, else 0.0.

    Example:
        >>> import jax.numpy as jnp
        >>> from metrics_tpu.functional import retrieval_hit_rate
        >>> preds = jnp.array([0.9, 0.2, 0.7, 0.4])
        >>> target = jnp.array([1, 0, 1, 1])
        >>> retrieval_hit_rate(preds, target)
        Array(1., dtype=float32)
    """
    preds, target = _check_retrieval_functional_inputs(preds, target)
    _validate_k(k)
    n = preds.shape[0]
    k = n if k is None else k
    t = _target_by_pred_rank(preds, target).astype(jnp.float32)
    return (t[: min(k, n)].sum() > 0).astype(jnp.float32)


def retrieval_r_precision(preds: Array, target: Array) -> Array:
    """Precision at k = (# relevant); branch-free via a rank<R mask.

    Example:
        >>> import jax.numpy as jnp
        >>> from metrics_tpu.functional import retrieval_r_precision
        >>> preds = jnp.array([0.9, 0.2, 0.7, 0.4])
        >>> target = jnp.array([1, 0, 1, 1])
        >>> retrieval_r_precision(preds, target)
        Array(1., dtype=float32)
    """
    preds, target = _check_retrieval_functional_inputs(preds, target)
    t = _target_by_pred_rank(preds, target).astype(jnp.float32)
    total = target.sum().astype(jnp.float32)
    ranks = jnp.arange(t.shape[0], dtype=jnp.float32)
    relevant = (t * (ranks < total)).sum()
    return jnp.where(total > 0, relevant / jnp.maximum(total, 1.0), 0.0)


def retrieval_reciprocal_rank(preds: Array, target: Array) -> Array:
    """1 / rank of the first relevant document (argmax finds the first True).

    Example:
        >>> import jax.numpy as jnp
        >>> from metrics_tpu.functional import retrieval_reciprocal_rank
        >>> preds = jnp.array([0.9, 0.2, 0.7, 0.4])
        >>> target = jnp.array([1, 0, 1, 1])
        >>> retrieval_reciprocal_rank(preds, target)
        Array(1., dtype=float32)
    """
    preds, target = _check_retrieval_functional_inputs(preds, target)
    t = _target_by_pred_rank(preds, target).astype(jnp.float32)
    first = jnp.argmax(t)  # first occurrence of the max (1.0) — the top-ranked hit
    return jnp.where(target.sum() > 0, 1.0 / (first.astype(jnp.float32) + 1.0), 0.0)


def _dcg(target: Array) -> Array:
    denom = jnp.log2(jnp.arange(target.shape[-1], dtype=jnp.float32) + 2.0)
    return (target / denom).sum(axis=-1)


def retrieval_normalized_dcg(preds: Array, target: Array, k: Optional[int] = None) -> Array:
    """nDCG@k with raw-gain DCG (gain = target value, like the reference).

    Example:
        >>> import jax.numpy as jnp
        >>> from metrics_tpu.functional import retrieval_normalized_dcg
        >>> preds = jnp.array([0.9, 0.2, 0.7, 0.4])
        >>> target = jnp.array([1, 0, 1, 1])
        >>> retrieval_normalized_dcg(preds, target)
        Array(1., dtype=float32)
    """
    preds, target = _check_retrieval_functional_inputs(preds, target, allow_non_binary_target=True)
    _validate_k(k)
    n = preds.shape[0]
    k = n if k is None else k
    target = target.astype(jnp.float32)
    sorted_target = _target_by_pred_rank(preds, target)[: min(k, n)]
    ideal_target = jnp.sort(target)[::-1][: min(k, n)]
    ideal_dcg = _dcg(ideal_target)
    target_dcg = _dcg(sorted_target)
    return jnp.where(ideal_dcg > 0, _safe_divide(target_dcg, ideal_dcg), 0.0)


def retrieval_precision_recall_curve(
    preds: Array,
    target: Array,
    max_k: Optional[int] = None,
    adaptive_k: bool = False,
) -> Tuple[Array, Array, Array]:
    """(precision@k, recall@k, k) for k in 1..max_k over one query.

    Example:
        >>> import jax.numpy as jnp
        >>> from metrics_tpu.functional import retrieval_precision_recall_curve
        >>> preds = jnp.array([0.9, 0.2, 0.7, 0.4])
        >>> target = jnp.array([1, 0, 1, 1])
        >>> precision, recall, top_k = retrieval_precision_recall_curve(preds, target, max_k=2)
        >>> recall
        Array([0.33333334, 0.6666667 ], dtype=float32)
    """
    preds, target = _check_retrieval_functional_inputs(preds, target)
    if not isinstance(adaptive_k, bool):
        raise ValueError("`adaptive_k` has to be a boolean")
    if max_k is not None and not (isinstance(max_k, int) and max_k > 0):
        raise ValueError("`max_k` has to be a positive integer or None")
    n = preds.shape[0]
    max_k = n if max_k is None else max_k

    if adaptive_k and max_k > n:
        topk = jnp.concatenate(
            [jnp.arange(1, n + 1, dtype=jnp.float32), jnp.full((max_k - n,), float(n), dtype=jnp.float32)]
        )
    else:
        topk = jnp.arange(1, max_k + 1, dtype=jnp.float32)

    t = _target_by_pred_rank(preds, target).astype(jnp.float32)[: min(max_k, n)]
    t = jnp.pad(t, (0, max(0, max_k - t.shape[0])))
    cum_rel = jnp.cumsum(t)
    total = target.sum().astype(jnp.float32)
    has_pos = total > 0
    recall = jnp.where(has_pos, cum_rel / jnp.maximum(total, 1.0), jnp.zeros(max_k))
    precision = jnp.where(has_pos, cum_rel / topk, jnp.zeros(max_k))
    return precision, recall, topk.astype(jnp.int32)
