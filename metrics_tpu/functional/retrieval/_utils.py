"""Shared helpers for per-query retrieval functionals.

Reference parity: src/torchmetrics/functional/retrieval/* (each function operates on the
documents of a single query). TPU-native notes: every function here is branch-free on
data (``jnp.where`` instead of ``if target.sum()``), so they are jittable with static
shapes; ``k`` is a static Python int.
"""

from __future__ import annotations

from typing import Optional, Tuple

import jax.numpy as jnp
from jax import Array

from metrics_tpu.utils.checks import _value_check_possible


def _check_retrieval_functional_inputs(
    preds: Array,
    target: Array,
    allow_non_binary_target: bool = False,
) -> Tuple[Array, Array]:
    """Validate and flatten one query's (preds, target) pair.

    Reference: utilities/checks.py ``_check_retrieval_functional_inputs``.
    """
    preds = jnp.asarray(preds)
    target = jnp.asarray(target)
    if preds.shape != target.shape or preds.size == 0:
        raise ValueError("`preds` and `target` must be non-empty and of the same shape")
    if not jnp.issubdtype(preds.dtype, jnp.floating):
        raise ValueError("`preds` must be a tensor of floats")
    if not jnp.issubdtype(target.dtype, jnp.integer) and not jnp.issubdtype(target.dtype, jnp.bool_):
        raise ValueError("`target` must be a tensor of booleans or integers")
    if not allow_non_binary_target and _value_check_possible(target) and bool(jnp.any((target > 1) | (target < 0))):
        raise ValueError("`target` must contain `binary` values")
    return preds.reshape(-1).astype(jnp.float32), target.reshape(-1)


def _validate_k(k: Optional[int]) -> None:
    if k is not None and not (isinstance(k, int) and k > 0):
        raise ValueError("`k` has to be a positive integer or None")


def _target_by_pred_rank(preds: Array, target: Array) -> Array:
    """Target values reordered by descending prediction score."""
    return target[jnp.argsort(-preds)]
