"""One-shot functional twins of the sketch metrics (:mod:`metrics_tpu.sketch`).

Each function runs the same pure kernels the module metrics accumulate with,
over a single batch — handy for ad-hoc analytics and for oracling the
streaming path in tests (module metric fed the same stream must answer
bit-identically).
"""

from __future__ import annotations

from typing import Sequence, Tuple, Union

import jax.numpy as jnp
from jax import Array

from metrics_tpu.sketch import kernels

__all__ = ["approx_count_distinct", "approx_quantiles", "approx_heavy_hitters"]


def approx_quantiles(
    value: Union[float, Array],
    quantiles: Sequence[float] = (0.5, 0.9, 0.99),
    *,
    alpha: float = 0.01,
    n_buckets: int = 2048,
    min_trackable: float = 1e-8,
) -> Array:
    """DDSketch quantile estimates of one batch (relative error ≤ ``alpha``)."""
    gamma, log_gamma, offset = kernels.ddsketch_params(alpha, min_trackable)
    pos = jnp.zeros(int(n_buckets), jnp.int32)
    neg = jnp.zeros(int(n_buckets), jnp.int32)
    zero = jnp.zeros((), jnp.int32)
    vmin = jnp.asarray(jnp.inf, jnp.float32)
    vmax = jnp.asarray(-jnp.inf, jnp.float32)
    pos, neg, zero, vmin, vmax = kernels.ddsketch_update(
        pos, neg, zero, vmin, vmax, value, log_gamma=log_gamma, offset=offset
    )
    return kernels.ddsketch_quantiles(
        pos, neg, zero, vmin, vmax, tuple(quantiles), gamma=gamma, offset=offset
    )


def approx_count_distinct(value: Union[float, Array], *, p: int = 12) -> Array:
    """HyperLogLog distinct-count estimate of one batch (std err ≈ 1.04/√2^p)."""
    if not 4 <= int(p) <= 16:
        raise ValueError(f"`p` must be in [4, 16], got {p}")
    registers = kernels.hll_update(jnp.zeros(1 << int(p), jnp.int32), value, p=int(p))
    return kernels.hll_estimate(registers)


def approx_heavy_hitters(
    value: Union[int, Array], *, k: int = 32, depth: int = 4, width: int = 2048
) -> Tuple[Array, Array]:
    """Top-``k`` heavy hitters of one batch of non-negative int ids.

    Returns ``(keys, counts)`` sorted by count-min estimate descending; unused
    candidate slots are ``-1``/``0``.
    """
    counts = jnp.zeros((int(depth), int(width)), jnp.int32)
    ledger = jnp.stack(
        [jnp.full((int(k),), -1, jnp.int32), jnp.zeros((int(k),), jnp.int32)], axis=1
    )
    counts, ledger = kernels.cms_update(counts, ledger, value)
    return kernels.hh_rank(counts, ledger)
