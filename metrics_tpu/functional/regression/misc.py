"""Remaining regression functionals: CosineSimilarity, KLDivergence, TweedieDeviance,
Kendall, Spearman.

Reference parity: src/torchmetrics/functional/regression/{cosine_similarity,kl_divergence,
tweedie_deviance,kendall,spearman}.py. Rank correlations (Kendall/Spearman) operate on
the full concatenated sample (cat states) — sort-based but static-shape at compute.
"""

from __future__ import annotations

from typing import Optional, Tuple

import jax
import jax.numpy as jnp
import numpy as np
from jax import Array

from metrics_tpu.utils.checks import _check_same_shape
from metrics_tpu.utils.compute import _is_eager_cpu, _safe_xlogy


# --------------------------------------------------------------------------- cosine similarity


def _cosine_similarity_update(preds: Array, target: Array) -> Tuple[Array, Array]:
    _check_same_shape(preds, target)
    if preds.ndim != 2:
        raise ValueError(f"Expected input to cosine similarity to be 2D tensors of shape `[N,D]`, got {preds.ndim}D")
    return preds.astype(jnp.float32), target.astype(jnp.float32)


def _cosine_similarity_compute(preds: Array, target: Array, reduction: Optional[str] = "sum") -> Array:
    dot_product = jnp.sum(preds * target, axis=-1)
    preds_norm = jnp.linalg.norm(preds, axis=-1)
    target_norm = jnp.linalg.norm(target, axis=-1)
    similarity = dot_product / (preds_norm * target_norm)
    reduction_mapping = {
        "sum": jnp.sum,
        "mean": jnp.mean,
        "none": lambda x: x,
        None: lambda x: x,
    }
    return reduction_mapping[reduction](similarity)


def cosine_similarity(preds: Array, target: Array, reduction: Optional[str] = "sum") -> Array:
    """Cosine similarity (reference functional/regression/cosine_similarity.py).

    Example:
        >>> import jax.numpy as jnp
        >>> from metrics_tpu.functional import cosine_similarity
        >>> preds = jnp.array([[1.0, 2.0], [3.0, 4.0]])
        >>> target = jnp.array([[1.0, 1.0], [3.0, 5.0]])
        >>> cosine_similarity(preds, target, reduction="mean")
        Array(0.97168756, dtype=float32)
    """
    preds, target = _cosine_similarity_update(preds, target)
    return _cosine_similarity_compute(preds, target, reduction)


# --------------------------------------------------------------------------- kl divergence


def _kld_update(p: Array, q: Array, log_prob: bool) -> Tuple[Array, int]:
    """Reference kl_divergence.py update."""
    _check_same_shape(p, q)
    if p.ndim != 2 or q.ndim != 2:
        raise ValueError(f"Expected both p and q distribution to be 2D but got {p.ndim} and {q.ndim} respectively")
    total = p.shape[0]
    if log_prob:
        measures = jnp.sum(jnp.exp(p) * (p - q), axis=-1)
    else:
        p = p / jnp.sum(p, axis=-1, keepdims=True)
        q = q / jnp.sum(q, axis=-1, keepdims=True)
        # no epsilon clamp on q (reference kl_divergence.py:43-45): a tiny q
        # bin under p mass must contribute its full p*log(p/q) — a clamp at
        # ~1e-6 silently halved KL on peaked q distributions (caught by the
        # fuzz-parity tier); q == 0 with p > 0 correctly yields inf
        measures = jnp.sum(_safe_xlogy(p, p / q), axis=-1)
    return measures, total


def _kld_compute(measures: Array, total: Array, reduction: Optional[str] = "mean") -> Array:
    if reduction == "sum":
        return jnp.sum(measures)
    if reduction == "mean":
        return jnp.sum(measures) / total
    if reduction is None or reduction == "none":
        return measures
    return measures / total


def kl_divergence(p: Array, q: Array, log_prob: bool = False, reduction: Optional[str] = "mean") -> Array:
    """KL divergence (reference functional/regression/kl_divergence.py).

    Example:
        >>> import jax.numpy as jnp
        >>> from metrics_tpu.functional import kl_divergence
        >>> p = jnp.array([[0.4, 0.6], [0.5, 0.5]])
        >>> q = jnp.array([[0.3, 0.7], [0.5, 0.5]])
        >>> kl_divergence(p, q)
        Array(0.01129122, dtype=float32)
    """
    measures, total = _kld_update(p, q, log_prob)
    return _kld_compute(measures, total, reduction)


# --------------------------------------------------------------------------- tweedie deviance


def _tweedie_deviance_score_update(preds: Array, target: Array, power: float = 0.0) -> Tuple[Array, Array]:
    """Reference tweedie_deviance.py update — four analytic regimes by ``power``."""
    _check_same_shape(preds, target)
    preds = preds.astype(jnp.float32)
    target = target.astype(jnp.float32)

    if power == 0:
        deviance_score = jnp.power(target - preds, 2)
    elif power == 1:
        deviance_score = 2 * (_safe_xlogy(target, target / preds) + preds - target)
    elif power == 2:
        deviance_score = 2 * (jnp.log(preds / target) + (target / preds) - 1)
    else:  # power < 0 or 1 < power < 2 or power > 2 — general Tweedie formula
        target_term = jnp.maximum(target, 0.0) if power < 0 else target
        deviance_score = 2 * (
            jnp.power(target_term, 2 - power) / ((1 - power) * (2 - power))
            - target * jnp.power(preds, 1 - power) / (1 - power)
            + jnp.power(preds, 2 - power) / (2 - power)
        )
    sum_deviance_score = jnp.sum(deviance_score)
    num_observations = jnp.asarray(target.size, dtype=jnp.float32)
    return sum_deviance_score, num_observations


def _tweedie_deviance_score_compute(sum_deviance_score: Array, num_observations: Array) -> Array:
    return sum_deviance_score / num_observations


def tweedie_deviance_score(preds: Array, target: Array, power: float = 0.0) -> Array:
    """Tweedie deviance (reference functional/regression/tweedie_deviance.py).

    Example:
        >>> import jax.numpy as jnp
        >>> from metrics_tpu.functional import tweedie_deviance_score
        >>> preds = jnp.array([0.5, 1.2, 2.0, 4.0])
        >>> target = jnp.array([0.6, 1.0, 2.5, 3.5])
        >>> tweedie_deviance_score(preds, target)
        Array(0.1375, dtype=float32)
    """
    if 0 < power < 1:
        raise ValueError(f"Deviance Score is not defined for power={power}.")
    s, n = _tweedie_deviance_score_update(preds, target, power)
    return _tweedie_deviance_score_compute(s, n)


# --------------------------------------------------------------------------- rank helpers


def _rank_data_host(x: "np.ndarray") -> "np.ndarray":
    """numpy average-tie ranking: one argsort + run-length tie averaging.

    Avoids per-element binary searches entirely — run starts come from the
    sorted array's change points, each run's average rank is computed once,
    and an inverse-permutation scatter places them. ~3x faster than XLA's
    CPU sort path at 1M elements (np.argsort is multiway/cache-friendly
    where XLA's CPU sort is not).
    """
    n = x.shape[0]
    order = np.argsort(x, kind="stable")
    sx = x[order]
    new = np.empty(n, bool)
    new[0] = True
    np.not_equal(sx[1:], sx[:-1], out=new[1:])
    run_id = np.cumsum(new) - 1
    first = np.flatnonzero(new)
    counts = np.diff(np.append(first, n))
    avg = (2 * first + counts - 1) / 2.0 + 1.0  # mean of positions, 1-based
    out = np.empty(n, np.float32)
    out[order] = avg[run_id]
    return out


def _rank_data(x: Array) -> Array:
    """Average-tie ranking (1-based), as scipy.stats.rankdata (reference spearman.py)."""
    if x.shape[0] > 0 and _is_eager_cpu(x):
        # eager host path: numpy's sort is ~4x faster than XLA's CPU sort; the
        # jnp path below stays for jit traces, accelerators, and empty inputs
        return jnp.asarray(_rank_data_host(np.asarray(x)))
    sorted_x = jnp.sort(x)
    # average ranks over ties: for each element, rank = mean of positions with equal value
    # first/last position of each value via searchsorted on the sorted array
    first = jnp.searchsorted(sorted_x, x, side="left")
    last = jnp.searchsorted(sorted_x, x, side="right") - 1
    return (first + last).astype(jnp.float32) / 2.0 + 1.0


def _spearman_corrcoef_compute(preds: Array, target: Array, eps: float = 1.17e-06) -> Array:
    """Rank → Pearson (reference spearman.py compute)."""
    if preds.ndim == 1:
        preds = _rank_data(preds)
        target = _rank_data(target)
    else:
        preds = jnp.stack([_rank_data(preds[:, i]) for i in range(preds.shape[1])], axis=-1)
        target = jnp.stack([_rank_data(target[:, i]) for i in range(target.shape[1])], axis=-1)

    preds_diff = preds - jnp.mean(preds, axis=0)
    target_diff = target - jnp.mean(target, axis=0)

    cov = jnp.mean(preds_diff * target_diff, axis=0)
    preds_std = jnp.sqrt(jnp.mean(preds_diff * preds_diff, axis=0))
    target_std = jnp.sqrt(jnp.mean(target_diff * target_diff, axis=0))

    corrcoef = cov / (preds_std * target_std + eps)
    return jnp.clip(corrcoef, -1.0, 1.0)


def spearman_corrcoef(preds: Array, target: Array) -> Array:
    """Spearman rank correlation (reference functional/regression/spearman.py).

    Example:
        >>> import jax.numpy as jnp
        >>> from metrics_tpu.functional import spearman_corrcoef
        >>> preds = jnp.array([2.5, 0.0, 2.0, 8.0])
        >>> target = jnp.array([3.0, -0.5, 2.0, 7.0])
        >>> spearman_corrcoef(preds, target)
        Array(0.99999905, dtype=float32)
    """
    _check_same_shape(preds, target)
    if not jnp.issubdtype(preds.dtype, jnp.floating) or not jnp.issubdtype(target.dtype, jnp.floating):
        raise TypeError("Expected `preds` and `target` both to be floating point tensors")
    return _spearman_corrcoef_compute(preds.astype(jnp.float32), target.astype(jnp.float32))


def _kendall_tau_compute(preds: Array, target: Array, variant: str = "b") -> Array:
    """Kendall's tau via O(N²) pairwise sign comparison (reference kendall.py uses an
    O(N log N) merge-sort count; the pairwise form is a dense (N,N) elementwise grid —
    XLA-friendly and exact, acceptable for metric-sized N)."""
    px = preds[:, None] - preds[None, :]
    py = target[:, None] - target[None, :]
    sign_prod = jnp.sign(px) * jnp.sign(py)
    iu = jnp.triu_indices(preds.shape[0], k=1)
    s = sign_prod[iu]
    concordant = jnp.sum(s > 0)
    discordant = jnp.sum(s < 0)
    n = preds.shape[0]
    n0 = n * (n - 1) / 2.0
    tx = jnp.sum(jnp.sign(px)[iu] == 0)  # ties in x
    ty = jnp.sum(jnp.sign(py)[iu] == 0)
    txy = jnp.sum((jnp.sign(px)[iu] == 0) & (jnp.sign(py)[iu] == 0))
    if variant == "a":
        # reference convention (kendall.py:184-185): ties drop out of the
        # denominator — (C − D) / (C + D), not the textbook (C − D) / C(n,2)
        return (concordant - discordant) / (concordant + discordant)
    if variant == "b":
        return (concordant - discordant) / jnp.sqrt((n0 - tx) * (n0 - ty))
    # variant "c": needs the number of distinct values per variable
    mx = jnp.unique(preds, size=n, fill_value=jnp.inf)
    my = jnp.unique(target, size=n, fill_value=jnp.inf)
    m = jnp.minimum(jnp.sum(jnp.isfinite(mx)), jnp.sum(jnp.isfinite(my))).astype(jnp.float32)
    return 2 * (concordant - discordant) / (n**2 * (m - 1) / m)


def _kendall_p_value(tau: Array, n: int, alternative: str) -> Array:
    """Asymptotic normal-approximation p-value for tau (reference kendall.py
    ``_calculate_p_value``): z = 3·tau·sqrt(n(n−1)) / sqrt(2(2n+5))."""
    from jax.scipy.stats import norm

    z = 3 * tau * jnp.sqrt(n * (n - 1.0)) / jnp.sqrt(2.0 * (2 * n + 5.0))
    if alternative == "two-sided":
        return 2 * norm.sf(jnp.abs(z))
    if alternative == "greater":
        return norm.sf(z)
    if alternative == "less":
        return norm.cdf(z)
    raise ValueError(f"Argument `alternative` is expected to be one of `['two-sided', 'less', 'greater']`, but got {alternative!r}")


def kendall_rank_corrcoef(
    preds: Array,
    target: Array,
    variant: str = "b",
    t_test: bool = False,
    alternative: Optional[str] = "two-sided",
) -> Array:
    """Kendall rank correlation; with ``t_test=True`` returns ``(tau, p_value)``
    (reference functional/regression/kendall.py:343-416).

    Example:
        >>> import jax.numpy as jnp
        >>> from metrics_tpu.functional import kendall_rank_corrcoef
        >>> preds = jnp.array([2.5, 0.0, 2.0, 8.0])
        >>> target = jnp.array([3.0, -0.5, 2.0, 7.0])
        >>> kendall_rank_corrcoef(preds, target)
        Array(1., dtype=float32, weak_type=True)
    """
    _check_same_shape(preds, target)
    if variant not in ("a", "b", "c"):
        raise ValueError(f"Argument `variant` is expected to be one of `['a', 'b', 'c']`, but got {variant!r}")
    if not isinstance(t_test, bool):
        raise ValueError(f"Argument `t_test` is expected to be of a type `bool`, but got {t_test!r}")
    if t_test and alternative not in ("two-sided", "less", "greater"):
        raise ValueError(
            f"Argument `alternative` is expected to be one of `['two-sided', 'less', 'greater']`, but got {alternative!r}"
        )
    if preds.ndim == 1:
        tau = _kendall_tau_compute(preds.astype(jnp.float32), target.astype(jnp.float32), variant)
        n = preds.shape[0]
    else:
        tau = jnp.stack(
            [_kendall_tau_compute(preds[:, i].astype(jnp.float32), target[:, i].astype(jnp.float32), variant) for i in range(preds.shape[1])]
        )
        n = preds.shape[0]
    if t_test:
        return tau, _kendall_p_value(tau, n, alternative)
    return tau
