"""Error-sum regression functionals: MAE, MSE, MAPE, SMAPE, WMAPE, MSLE, LogCosh.

Reference parity: src/torchmetrics/functional/regression/{mae,mse,mape,symmetric_mape,
wmape,log_mse,log_cosh}.py — each decomposed into ``_*_update`` (sum-of-errors +
count) and ``_*_compute`` (safe divide), the canonical two-sum streaming pattern.
"""

from __future__ import annotations

from typing import Tuple

import jax
import jax.numpy as jnp
from jax import Array

import numpy as np

from metrics_tpu.utils.checks import _check_same_shape
from metrics_tpu.utils.compute import _host_sq_diff_sum, _safe_divide

# Error-sum kernels are jitted at definition: each eager update would otherwise
# dispatch 2-4 separate O(N) passes (sub, abs/square, sum); compiling fuses
# them into one memory sweep, which is what beats the reference's eager torch
# chain (same rationale as classification stat_scores). Under an outer jit the
# wrapper inlines into the surrounding trace.


@jax.jit
def _mae_kernel(preds: Array, target: Array) -> Array:
    preds = preds if jnp.issubdtype(preds.dtype, jnp.floating) else preds.astype(jnp.float32)
    target = target if jnp.issubdtype(target.dtype, jnp.floating) else target.astype(jnp.float32)
    return jnp.sum(jnp.abs(preds - target))


def _mean_absolute_error_update(preds: Array, target: Array) -> Tuple[Array, int]:
    _check_same_shape(preds, target)
    return _mae_kernel(preds, target), target.size


def _mean_absolute_error_compute(sum_abs_error: Array, num_obs: Array) -> Array:
    return sum_abs_error / num_obs


def mean_absolute_error(preds: Array, target: Array) -> Array:
    """MAE (reference functional/regression/mae.py).

    Example:
        >>> import jax.numpy as jnp
        >>> from metrics_tpu.functional import mean_absolute_error
        >>> preds = jnp.array([2.5, 0.0, 2.0, 8.0])
        >>> target = jnp.array([3.0, -0.5, 2.0, 7.0])
        >>> mean_absolute_error(preds, target)
        Array(0.5, dtype=float32)
    """
    sum_abs_error, num_obs = _mean_absolute_error_update(preds, target)
    return _mean_absolute_error_compute(sum_abs_error, num_obs)


@jax.jit
def _mse_kernel(preds: Array, target: Array) -> Array:
    diff = preds - target
    return jnp.sum(diff * diff, axis=0)


def _mean_squared_error_update(preds: Array, target: Array, num_outputs: int) -> Tuple[Array, int]:
    _check_same_shape(preds, target)
    if num_outputs == 1:
        preds = preds.reshape(-1)
        target = target.reshape(-1)
    # half-precision inputs accumulate in f32 (f16 overflows at 65504; bf16
    # loses whole counts past 256) — the repo-wide dtype policy
    if jnp.issubdtype(preds.dtype, jnp.floating) and jnp.finfo(preds.dtype).bits < 32:
        preds = preds.astype(jnp.float32)
    if jnp.issubdtype(target.dtype, jnp.floating) and jnp.finfo(target.dtype).bits < 32:
        target = target.astype(jnp.float32)
    if preds.ndim == 1:
        host = _host_sq_diff_sum(preds, target)
        if host is not None:
            return host, target.shape[0]
    return _mse_kernel(preds, target), target.shape[0]


def _mean_squared_error_compute(sum_squared_error: Array, num_obs: Array, squared: bool = True) -> Array:
    res = sum_squared_error / num_obs
    return res if squared else jnp.sqrt(res)


def mean_squared_error(preds: Array, target: Array, squared: bool = True, num_outputs: int = 1) -> Array:
    """MSE / RMSE (reference functional/regression/mse.py).

    Example:
        >>> import jax.numpy as jnp
        >>> from metrics_tpu.functional import mean_squared_error
        >>> preds = jnp.array([2.5, 0.0, 2.0, 8.0])
        >>> target = jnp.array([3.0, -0.5, 2.0, 7.0])
        >>> mean_squared_error(preds, target)
        Array(0.375, dtype=float32)
    """
    sum_squared_error, num_obs = _mean_squared_error_update(preds, target, num_outputs)
    return _mean_squared_error_compute(sum_squared_error, num_obs, squared)


@jax.jit
def _mape_kernel(preds: Array, target: Array, epsilon: Array) -> Array:
    return jnp.sum(jnp.abs(preds - target) / jnp.clip(jnp.abs(target), min=epsilon))


def _mean_absolute_percentage_error_update(preds: Array, target: Array, epsilon: float = 1.17e-06) -> Tuple[Array, int]:
    _check_same_shape(preds, target)
    return _mape_kernel(preds, target, epsilon), target.size


def _mean_absolute_percentage_error_compute(sum_abs_per_error: Array, num_obs: Array) -> Array:
    return sum_abs_per_error / num_obs


def mean_absolute_percentage_error(preds: Array, target: Array) -> Array:
    """MAPE (reference functional/regression/mape.py).

    Example:
        >>> import jax.numpy as jnp
        >>> from metrics_tpu.functional import mean_absolute_percentage_error
        >>> preds = jnp.array([0.5, 1.2, 2.0, 4.0])
        >>> target = jnp.array([0.6, 1.0, 2.5, 3.5])
        >>> mean_absolute_percentage_error(preds, target)
        Array(0.17738096, dtype=float32)
    """
    s, n = _mean_absolute_percentage_error_update(preds, target)
    return _mean_absolute_percentage_error_compute(s, n)


@jax.jit
def _smape_kernel(preds: Array, target: Array, epsilon: Array) -> Array:
    abs_per_error = jnp.abs(preds - target) / jnp.clip(jnp.abs(target) + jnp.abs(preds), min=epsilon)
    return 2 * jnp.sum(abs_per_error)


def _symmetric_mean_absolute_percentage_error_update(
    preds: Array, target: Array, epsilon: float = 1.17e-06
) -> Tuple[Array, int]:
    _check_same_shape(preds, target)
    return _smape_kernel(preds, target, epsilon), target.size


def symmetric_mean_absolute_percentage_error(preds: Array, target: Array) -> Array:
    """SMAPE (reference functional/regression/symmetric_mape.py).

    Example:
        >>> import jax.numpy as jnp
        >>> from metrics_tpu.functional import symmetric_mean_absolute_percentage_error
        >>> preds = jnp.array([2.5, 0.0, 2.0, 8.0])
        >>> target = jnp.array([3.0, -0.5, 2.0, 7.0])
        >>> symmetric_mean_absolute_percentage_error(preds, target)
        Array(0.5787879, dtype=float32)
    """
    s, n = _symmetric_mean_absolute_percentage_error_update(preds, target)
    return s / n


@jax.jit
def _wmape_kernel(preds: Array, target: Array) -> Tuple[Array, Array]:
    return jnp.sum(jnp.abs((preds - target).reshape(-1))), jnp.sum(jnp.abs(target.reshape(-1)))


def _weighted_mean_absolute_percentage_error_update(preds: Array, target: Array) -> Tuple[Array, Array]:
    _check_same_shape(preds, target)
    return _wmape_kernel(preds, target)


def _weighted_mean_absolute_percentage_error_compute(sum_abs_error: Array, sum_scale: Array, epsilon: float = 1.17e-06) -> Array:
    return sum_abs_error / jnp.clip(sum_scale, min=epsilon)


def weighted_mean_absolute_percentage_error(preds: Array, target: Array) -> Array:
    """WMAPE (reference functional/regression/wmape.py).

    Example:
        >>> import jax.numpy as jnp
        >>> from metrics_tpu.functional import weighted_mean_absolute_percentage_error
        >>> preds = jnp.array([2.5, 0.0, 2.0, 8.0])
        >>> target = jnp.array([3.0, -0.5, 2.0, 7.0])
        >>> weighted_mean_absolute_percentage_error(preds, target)
        Array(0.16, dtype=float32)
    """
    s, scale = _weighted_mean_absolute_percentage_error_update(preds, target)
    return _weighted_mean_absolute_percentage_error_compute(s, scale)


@jax.jit
def _msle_kernel(preds: Array, target: Array) -> Array:
    return jnp.sum((jnp.log1p(preds) - jnp.log1p(target)) ** 2)


def _mean_squared_log_error_update(preds: Array, target: Array) -> Tuple[Array, int]:
    _check_same_shape(preds, target)
    return _msle_kernel(preds, target), target.size


def mean_squared_log_error(preds: Array, target: Array) -> Array:
    """MSLE (reference functional/regression/log_mse.py).

    Example:
        >>> import jax.numpy as jnp
        >>> from metrics_tpu.functional import mean_squared_log_error
        >>> preds = jnp.array([0.5, 1.2, 2.0, 4.0])
        >>> target = jnp.array([0.6, 1.0, 2.5, 3.5])
        >>> mean_squared_log_error(preds, target)
        Array(0.01202814, dtype=float32)
    """
    s, n = _mean_squared_log_error_update(preds, target)
    return s / n


def _unsqueeze_tensors(preds: Array, target: Array) -> Tuple[Array, Array]:
    if preds.ndim == 1:
        return preds[:, None], target[:, None]
    return preds, target


@jax.jit
def _log_cosh_kernel(preds: Array, target: Array) -> Array:
    diff = preds - target
    # numerically-stable log(cosh(x)) = x + softplus(-2x) - log(2)
    return jnp.sum(diff + jax_softplus(-2.0 * diff) - jnp.log(2.0), axis=0)


def _log_cosh_error_update(preds: Array, target: Array, num_outputs: int) -> Tuple[Array, int]:
    _check_same_shape(preds, target)
    preds, target = _unsqueeze_tensors(preds, target)
    return _log_cosh_kernel(preds, target), preds.shape[0]


def jax_softplus(x: Array) -> Array:
    return jnp.logaddexp(x, 0.0)


def _log_cosh_error_compute(sum_log_cosh_error: Array, num_obs: Array) -> Array:
    return jnp.squeeze(sum_log_cosh_error / num_obs)


def log_cosh_error(preds: Array, target: Array) -> Array:
    """LogCosh error (reference functional/regression/log_cosh.py).

    Example:
        >>> import jax.numpy as jnp
        >>> from metrics_tpu.functional import log_cosh_error
        >>> preds = jnp.array([2.5, 0.0, 2.0, 8.0])
        >>> target = jnp.array([3.0, -0.5, 2.0, 7.0])
        >>> log_cosh_error(preds, target)
        Array(0.16850246, dtype=float32)
    """
    s, n = _log_cosh_error_update(preds, target, num_outputs=1)
    return _log_cosh_error_compute(s, n)
