"""Regression functional metrics (reference src/torchmetrics/functional/regression/)."""

from metrics_tpu.functional.regression.basic import (
    log_cosh_error,
    mean_absolute_error,
    mean_absolute_percentage_error,
    mean_squared_error,
    mean_squared_log_error,
    symmetric_mean_absolute_percentage_error,
    weighted_mean_absolute_percentage_error,
)
from metrics_tpu.functional.regression.misc import (
    cosine_similarity,
    kendall_rank_corrcoef,
    kl_divergence,
    spearman_corrcoef,
    tweedie_deviance_score,
)
from metrics_tpu.functional.regression.moments import (
    concordance_corrcoef,
    explained_variance,
    pearson_corrcoef,
    r2_score,
)

__all__ = [
    "concordance_corrcoef",
    "cosine_similarity",
    "explained_variance",
    "kendall_rank_corrcoef",
    "kl_divergence",
    "log_cosh_error",
    "mean_absolute_error",
    "mean_absolute_percentage_error",
    "mean_squared_error",
    "mean_squared_log_error",
    "pearson_corrcoef",
    "r2_score",
    "spearman_corrcoef",
    "symmetric_mean_absolute_percentage_error",
    "tweedie_deviance_score",
    "weighted_mean_absolute_percentage_error",
]
