"""Moment-based regression functionals: Pearson, Concordance, ExplainedVariance, R².

Reference parity: src/torchmetrics/functional/regression/{pearson,concordance,
explained_variance,r2}.py — all stream second moments (Welford-style for Pearson),
making the states fixed-shape and psum-mergeable.
"""

from __future__ import annotations

import os
import threading
from typing import Tuple, Union

import jax
import jax.numpy as jnp
from jax import Array

import numpy as np

from metrics_tpu.utils.checks import _check_same_shape, _value_check_possible
from metrics_tpu.utils.compute import _is_eager_cpu
from metrics_tpu.utils.prints import rank_zero_warn

# small bounded cache: plain sums on the host path run as BLAS dots against a
# ones vector (multithreaded) instead of numpy's single-threaded reduce; a few
# entries serve streams that alternate batch sizes (e.g. a trailing partial
# batch) without reallocating the ones vector every update
_ONES_CACHE: dict = {}
_ONES_CACHE_MAX = 8


# With >1 core, BLAS's threaded dot-against-ones beats numpy's single-threaded
# pairwise sum despite reading 2x the bytes (ones vector included); on a single
# core the extra 4 MB read makes it strictly slower, so plain np.sum wins.
# sched_getaffinity sees cgroup/taskset limits that os.cpu_count ignores.
# Captured ONCE at import: if process affinity changes later (worker-pool
# pinning, cgroup update) the heuristic goes stale — perf-only, never wrong.
try:
    _SUM_VIA_DOT = len(os.sched_getaffinity(0)) > 1
except AttributeError:  # platforms without sched_getaffinity
    _SUM_VIA_DOT = (os.cpu_count() or 1) > 1


def _host_sum(x: "np.ndarray") -> "np.ndarray":
    if not _SUM_VIA_DOT:
        return np.sum(x)
    n = x.shape[0]
    ones = _ONES_CACHE.get(n)
    if ones is None:
        if len(_ONES_CACHE) >= _ONES_CACHE_MAX:
            _ONES_CACHE.pop(next(iter(_ONES_CACHE)))  # FIFO eviction
        ones = np.ones(n, np.float32)
        _ONES_CACHE[n] = ones
    return np.dot(x, ones)


_SCRATCH = threading.local()


def _host_diff_sums(
    t: "np.ndarray", p: "np.ndarray", want_sum: bool = True
) -> Tuple["np.ndarray", "np.ndarray"]:
    """``(sum(t - p), sum((t - p)**2))`` via a reusable per-thread scratch buffer.

    A fresh 4 MB temporary per 1M-sample update is page-fault-bound (~0.5 ms —
    half the whole r2 kernel); writing the diff into a kept buffer pays only the
    memory bandwidth after the first call at a given size. The scratch view is
    reduced HERE and never escapes, so no caller can hold a view that the next
    call silently invalidates. ``want_sum=False`` skips the plain-sum pass for
    callers that only need the squared sum (r2), returning ``(None, dot)``.
    """
    n = t.shape[0]
    buf = getattr(_SCRATCH, "buf", None)
    if buf is None or buf.shape[0] < n:
        buf = np.empty(n, np.float32)
        _SCRATCH.buf = buf
    d = buf[:n]
    np.subtract(t, p, out=d)
    return (_host_sum(d) if want_sum else None), np.dot(d, d)


# --------------------------------------------------------------------------- pearson


def _pearson_corrcoef_update(
    preds: Array,
    target: Array,
    mean_x: Array,
    mean_y: Array,
    var_x: Array,
    var_y: Array,
    corr_xy: Array,
    n_prior: Array,
    num_outputs: int,
) -> Tuple[Array, Array, Array, Array, Array, Array]:
    """Parallel Welford update of means/vars/cov (reference pearson.py:22-69)."""
    _check_same_shape(preds, target)
    if num_outputs == 1:
        preds = preds.reshape(-1)
        target = target.reshape(-1)
    return _pearson_kernel(preds, target, mean_x, mean_y, var_x, var_y, corr_xy, n_prior)


@jax.jit
def _pearson_kernel(
    preds: Array,
    target: Array,
    mean_x: Array,
    mean_y: Array,
    var_x: Array,
    var_y: Array,
    corr_xy: Array,
    n_prior: Array,
) -> Tuple[Array, Array, Array, Array, Array, Array]:
    # jitted at definition: fuses the five O(N) passes (two sums + three
    # centered products) into one memory sweep; inlines under an outer jit
    preds = preds.astype(jnp.float32)
    target = target.astype(jnp.float32)

    n_obs = jnp.asarray(preds.shape[0], dtype=jnp.float32)
    mx_new = (n_prior * mean_x + jnp.sum(preds, axis=0)) / (n_prior + n_obs)
    my_new = (n_prior * mean_y + jnp.sum(target, axis=0)) / (n_prior + n_obs)
    n_total = n_prior + n_obs

    var_x = var_x + jnp.sum((preds - mx_new) * (preds - mean_x), axis=0)
    var_y = var_y + jnp.sum((target - my_new) * (target - mean_y), axis=0)
    corr_xy = corr_xy + jnp.sum((preds - mx_new) * (target - mean_y), axis=0)

    return mx_new, my_new, var_x, var_y, corr_xy, n_total


def _pearson_corrcoef_compute(var_x: Array, var_y: Array, corr_xy: Array, nb: Array) -> Array:
    """Reference pearson.py ``_pearson_corrcoef_compute``.

    Plain division, as the reference (pearson.py:77-81): a zero-variance input
    (constant preds or target) gives 0/0 → NaN, which ``clip`` preserves.
    An earlier epsilon-clamp here silently returned 0.0 on constant inputs —
    caught by the round-4 fuzz soak against the executed reference.
    """
    var_x = var_x / (nb - 1)
    var_y = var_y / (nb - 1)
    corr_xy = corr_xy / (nb - 1)
    corrcoef = corr_xy / jnp.sqrt(var_x * var_y)
    return jnp.clip(corrcoef, -1.0, 1.0)


def pearson_corrcoef(preds: Array, target: Array) -> Array:
    """Pearson correlation coefficient (reference functional/regression/pearson.py).

    Example:
        >>> import jax.numpy as jnp
        >>> from metrics_tpu.functional import pearson_corrcoef
        >>> preds = jnp.array([2.5, 0.0, 2.0, 8.0])
        >>> target = jnp.array([3.0, -0.5, 2.0, 7.0])
        >>> pearson_corrcoef(preds, target)
        Array(0.98486954, dtype=float32)
    """
    d = preds.shape[1] if preds.ndim == 2 else 1
    shape = (d,) if d > 1 else ()
    zeros = jnp.zeros(shape, dtype=jnp.float32)
    _, _, var_x, var_y, corr_xy, nb = _pearson_corrcoef_update(
        preds, target, zeros, zeros, zeros, zeros, zeros, jnp.zeros((), jnp.float32), num_outputs=d
    )
    return _pearson_corrcoef_compute(var_x, var_y, corr_xy, nb)


# --------------------------------------------------------------------------- concordance


def _concordance_corrcoef_compute(
    mean_x: Array, mean_y: Array, var_x: Array, var_y: Array, corr_xy: Array, nb: Array
) -> Array:
    """CCC via the (clamped) pearson factor, exactly as the reference
    (concordance.py:20-30): ``2·ρ·σx·σy / (σx² + σy² + (μx − μy)²)`` with the
    n−1-normalised variances from ``_pearson_corrcoef_compute``. The earlier
    algebraically-simplified ``2·cov/(...)`` form normalised by n instead of
    n−1, which diverges by O(Δμ²/n) whenever the means differ (≈1e-4 at
    n≈200 — caught by the round-4 fuzz soak), and bypassed the reference's
    ρ-clamp and its NaN on zero-variance inputs.
    """
    pearson = _pearson_corrcoef_compute(var_x, var_y, corr_xy, nb)
    var_x = var_x / (nb - 1)
    var_y = var_y / (nb - 1)
    return 2.0 * pearson * jnp.sqrt(var_x) * jnp.sqrt(var_y) / (var_x + var_y + (mean_x - mean_y) ** 2)


def concordance_corrcoef(preds: Array, target: Array) -> Array:
    """Concordance correlation coefficient (reference functional/regression/concordance.py).

    Example:
        >>> import jax.numpy as jnp
        >>> from metrics_tpu.functional import concordance_corrcoef
        >>> preds = jnp.array([2.5, 0.0, 2.0, 8.0])
        >>> target = jnp.array([3.0, -0.5, 2.0, 7.0])
        >>> concordance_corrcoef(preds, target)
        Array(0.9777347, dtype=float32)
    """
    d = preds.shape[1] if preds.ndim == 2 else 1
    shape = (d,) if d > 1 else ()
    zeros = jnp.zeros(shape, dtype=jnp.float32)
    mean_x, mean_y, var_x, var_y, corr_xy, nb = _pearson_corrcoef_update(
        preds, target, zeros, zeros, zeros, zeros, zeros, jnp.zeros((), jnp.float32), num_outputs=d
    )
    return _concordance_corrcoef_compute(mean_x, mean_y, var_x, var_y, corr_xy, nb)


# --------------------------------------------------------------------------- explained variance


@jax.jit
def _explained_variance_kernel(preds: Array, target: Array) -> Tuple[Array, Array, Array, Array]:
    preds = preds.astype(jnp.float32)
    target = target.astype(jnp.float32)
    diff = target - preds
    return (
        jnp.sum(diff, axis=0),
        jnp.sum(diff * diff, axis=0),
        jnp.sum(target, axis=0),
        jnp.sum(target * target, axis=0),
    )


def _explained_variance_update(preds: Array, target: Array) -> Tuple[int, Array, Array, Array, Array]:
    """Streaming sums (reference explained_variance.py:~30)."""
    _check_same_shape(preds, target)
    if preds.ndim == 1 and _is_eager_cpu(preds):
        # squared sums as BLAS dots — ~2x XLA's CPU reduction; results stay as
        # numpy scalars (no device put — _accumulate and the compute jit both
        # take them natively)
        t = np.asarray(target, np.float32)
        sum_d, dot_dd = _host_diff_sums(t, np.asarray(preds, np.float32))
        return (
            preds.shape[0],
            sum_d,
            dot_dd,
            _host_sum(t),
            np.dot(t, t),
        )
    sum_error, sum_squared_error, sum_target, sum_squared_target = _explained_variance_kernel(preds, target)
    return preds.shape[0], sum_error, sum_squared_error, sum_target, sum_squared_target


def _explained_variance_compute(
    num_obs: Array,
    sum_error: Array,
    sum_squared_error: Array,
    sum_target: Array,
    sum_squared_target: Array,
    multioutput: str = "uniform_average",
) -> Array:
    """Reference explained_variance.py compute."""
    diff_avg = sum_error / num_obs
    numerator = sum_squared_error / num_obs - diff_avg * diff_avg

    target_avg = sum_target / num_obs
    denominator = sum_squared_target / num_obs - target_avg * target_avg

    nonzero_numerator = numerator != 0
    nonzero_denominator = denominator != 0
    valid_score = nonzero_numerator & nonzero_denominator
    output_scores = jnp.ones_like(diff_avg)
    output_scores = jnp.where(
        valid_score, 1.0 - numerator / jnp.where(valid_score, denominator, 1.0), output_scores
    )
    output_scores = jnp.where(nonzero_numerator & ~nonzero_denominator, jnp.zeros_like(output_scores), output_scores)

    if multioutput == "raw_values":
        return output_scores
    if multioutput == "uniform_average":
        return jnp.mean(output_scores)
    if multioutput == "variance_weighted":
        denom_sum = jnp.sum(denominator)
        return jnp.sum(denominator / denom_sum * output_scores)
    raise ValueError(
        "Argument `multioutput` must be either `raw_values`, `uniform_average` or `variance_weighted`"
    )


def explained_variance(preds: Array, target: Array, multioutput: str = "uniform_average") -> Array:
    """Explained variance (reference functional/regression/explained_variance.py).

    Example:
        >>> import jax.numpy as jnp
        >>> from metrics_tpu.functional import explained_variance
        >>> preds = jnp.array([2.5, 0.0, 2.0, 8.0])
        >>> target = jnp.array([3.0, -0.5, 2.0, 7.0])
        >>> explained_variance(preds, target)
        Array(0.95717347, dtype=float32)
    """
    n, se, sse, st, sst = _explained_variance_update(preds, target)
    return _explained_variance_compute(n, se, sse, st, sst, multioutput)


# --------------------------------------------------------------------------- r2


@jax.jit
def _r2_kernel(preds: Array, target: Array) -> Tuple[Array, Array, Array]:
    preds = preds.astype(jnp.float32)
    target = target.astype(jnp.float32)
    sum_obs = jnp.sum(target, axis=0)
    sum_squared_obs = jnp.sum(target * target, axis=0)
    residual = jnp.sum((target - preds) ** 2, axis=0)
    return sum_squared_obs, sum_obs, residual


def _r2_score_update(preds: Array, target: Array) -> Tuple[Array, Array, Array, int]:
    """Streaming sums (reference r2.py:~25)."""
    _check_same_shape(preds, target)
    if preds.ndim == 1 and _is_eager_cpu(preds):
        # squared sums as BLAS dots — ~2x XLA's CPU reduction; results stay as
        # numpy scalars (no device put — _accumulate and the compute jit both
        # take them natively)
        t = np.asarray(target, np.float32)
        _, dot_dd = _host_diff_sums(t, np.asarray(preds, np.float32), want_sum=False)
        return (
            np.dot(t, t),
            _host_sum(t),
            dot_dd,
            target.shape[0],
        )
    sum_squared_obs, sum_obs, residual = _r2_kernel(preds, target)
    return sum_squared_obs, sum_obs, residual, target.shape[0]


def _r2_score_compute(
    sum_squared_obs: Array,
    sum_obs: Array,
    residual: Array,
    num_obs: Array,
    adjusted: int = 0,
    multioutput: str = "uniform_average",
) -> Array:
    """Reference r2.py compute (incl. adjusted-R² variant)."""
    if _value_check_possible(num_obs) and num_obs < 2:
        # the reference raises inside compute (r2.py:78-80); keep the guard
        # here so the MODULE path hits it too, not only the functional wrapper
        raise ValueError("Needs at least two samples to calculate r2 score.")
    mean_obs = sum_obs / num_obs
    tss = sum_squared_obs - sum_obs * mean_obs
    # plain division, as the reference (r2.py:83-84): constant targets give
    # tss == 0 → -inf (or NaN when the residual is also 0), NOT a masked 0 —
    # that masking convention belongs to explained_variance only (sklearn
    # semantics there; caught by the round-4 fuzz soak)
    raw_scores = 1 - (residual / tss)

    if multioutput == "raw_values":
        r2 = raw_scores
    elif multioutput == "uniform_average":
        r2 = jnp.mean(raw_scores)
    elif multioutput == "variance_weighted":
        tss_sum = jnp.sum(tss)
        r2 = jnp.sum(tss / tss_sum * raw_scores)
    else:
        raise ValueError(
            "Argument `multioutput` must be either `raw_values`, `uniform_average` or `variance_weighted`"
        )

    if adjusted < 0 or not isinstance(adjusted, int):
        raise ValueError("`adjusted` parameter should be an integer larger or equal to 0.")
    if adjusted != 0:
        # reference r2.py:101-112: degenerate adjustments warn and FALL BACK to
        # the standard score instead of dividing by zero / flipping sign
        if _value_check_possible(num_obs):
            if adjusted > num_obs - 1:
                rank_zero_warn(
                    "More independent regressions than data points in adjusted r2 score. "
                    "Falls back to standard r2 score.",
                    UserWarning,
                )
            elif adjusted == num_obs - 1:
                rank_zero_warn(
                    "Division by zero in adjusted r2 score. Falls back to standard r2 score.", UserWarning
                )
            else:
                return 1 - (1 - r2) * (num_obs - 1) / (num_obs - adjusted - 1)
            return r2
        # traced num_obs: same fallback, selected in-graph
        adjusted_r2 = 1 - (1 - r2) * (num_obs - 1) / (num_obs - adjusted - 1)
        return jnp.where(num_obs - adjusted - 1 > 0, adjusted_r2, r2)
    return r2


def r2_score(preds: Array, target: Array, adjusted: int = 0, multioutput: str = "uniform_average") -> Array:
    """R² score (reference functional/regression/r2.py).

    Example:
        >>> import jax.numpy as jnp
        >>> from metrics_tpu.functional import r2_score
        >>> preds = jnp.array([2.5, 0.0, 2.0, 8.0])
        >>> target = jnp.array([3.0, -0.5, 2.0, 7.0])
        >>> r2_score(preds, target)
        Array(0.94860816, dtype=float32)
    """
    sum_squared_obs, sum_obs, residual, num_obs = _r2_score_update(preds, target)
    if num_obs < 2:
        raise ValueError("Needs at least two samples to calculate r2 score.")
    return _r2_score_compute(sum_squared_obs, sum_obs, residual, num_obs, adjusted, multioutput)
