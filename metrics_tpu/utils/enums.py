"""String enums used across the package.

Reference parity: src/torchmetrics/utilities/enums.py (EnumStr base :18, DataType :48,
AverageMethod :61, MDMCAverageMethod :79). Behaviour preserved: case-insensitive
``from_str`` lookup with '-'/'_' normalisation.
"""

from __future__ import annotations

from enum import Enum
from typing import Optional


class EnumStr(str, Enum):
    """Base class: case-insensitive string enum."""

    @classmethod
    def from_str(cls, value: str) -> Optional["EnumStr"]:
        try:
            return cls[value.replace("-", "_").upper()]
        except KeyError:
            return None

    def __eq__(self, other: object) -> bool:
        if isinstance(other, str):
            return self.value.lower() == other.lower()
        return super().__eq__(other)

    def __hash__(self) -> int:
        return hash(self.value.lower())


class DataType(EnumStr):
    """Classification input type."""

    BINARY = "binary"
    MULTILABEL = "multi-label"
    MULTICLASS = "multi-class"
    MULTIDIM_MULTICLASS = "multi-dim multi-class"


class AverageMethod(EnumStr):
    MICRO = "micro"
    MACRO = "macro"
    WEIGHTED = "weighted"
    NONE = None  # type: ignore[assignment]
    SAMPLES = "samples"


class MDMCAverageMethod(EnumStr):
    GLOBAL = "global"
    SAMPLEWISE = "samplewise"


class ClassificationTask(EnumStr):
    """Task kind used by the task-dispatch façades."""

    BINARY = "binary"
    MULTICLASS = "multiclass"
    MULTILABEL = "multilabel"

    @classmethod
    def from_str_or_raise(cls, value: str) -> "ClassificationTask":
        task = cls.from_str(value)
        if task is None:
            raise ValueError(
                f"Invalid Classification: expected one of ['binary', 'multiclass', 'multilabel'] but got {value}"
            )
        return task  # type: ignore[return-value]


class ClassificationTaskNoMultilabel(EnumStr):
    """Tasks for metrics without a multilabel variant (e.g. calibration, hinge)."""

    BINARY = "binary"
    MULTICLASS = "multiclass"

    @classmethod
    def from_str_or_raise(cls, value: str) -> "ClassificationTaskNoMultilabel":
        task = cls.from_str(value)
        if task is None:
            raise ValueError(
                f"Invalid Classification: expected one of ['binary', 'multiclass'] but got {value}"
            )
        return task  # type: ignore[return-value]
