"""Numerically-safe compute helpers.

Reference parity: src/torchmetrics/utilities/compute.py (``_safe_matmul`` :22,
``_safe_xlogy`` :32, ``_safe_divide`` :47, trapezoidal ``auc`` :84,103).

TPU notes: matmuls route to the MXU; on TPU bf16 inputs are upcast to f32 for
accumulation rather than the reference's fp16→fp32 dance. All helpers are jittable
(no data-dependent Python control flow).
"""

from __future__ import annotations

from typing import Optional

import jax
import jax.numpy as jnp
from jax import Array


def _is_eager_cpu(x: Array) -> bool:
    """True when ``x`` is a concrete array on the host CPU backend.

    Gates numpy fast paths (multithreaded BLAS dots, cache-friendly sorts)
    that beat XLA's single-threaded CPU lowerings; under a trace or on an
    accelerator the jnp form is used instead. ``np.asarray`` of a concrete
    CPU-backend jax array is zero-copy, so the gate itself is free.
    """
    return jax.default_backend() == "cpu" and not isinstance(x, jax.core.Tracer)


def _host_sq_diff_sum(preds: Array, target: Array):
    """``sum((target-preds)**2)`` as one multithreaded host BLAS dot, or None.

    Engages only for concrete f32 arrays on the eager CPU backend (the jnp
    fallbacks preserve wider/integer dtypes, so those must not downcast);
    callers fall back to their jnp form on None. ~2x XLA's single-threaded
    CPU reduction at 1M elements.
    """
    import numpy as np

    if (
        preds.dtype == jnp.float32
        and target.dtype == jnp.float32
        and _is_eager_cpu(preds)
        and _is_eager_cpu(target)
    ):
        d = (np.asarray(target) - np.asarray(preds)).ravel()
        return jnp.asarray(np.dot(d, d))
    return None


def _safe_matmul(x: Array, y: Array) -> Array:
    """Matmul that upcasts half-precision inputs so accumulation happens in f32."""
    if x.dtype in (jnp.float16, jnp.bfloat16) or y.dtype in (jnp.float16, jnp.bfloat16):
        return (x.astype(jnp.float32) @ y.astype(jnp.float32)).astype(x.dtype)
    return x @ y


def _safe_xlogy(x: Array, y: Array) -> Array:
    """``x * log(y)`` that is 0 when ``x == 0`` (even if y==0 → log = -inf)."""
    res = x * jnp.log(y)
    return jnp.where(x == 0.0, jnp.zeros((), dtype=res.dtype), res)


def _safe_divide(num: Array, denom: Array, zero_division: float = 0.0) -> Array:
    """Element-wise division that returns ``zero_division`` where ``denom == 0``.

    Mirrors reference semantics (denominator replaced before dividing so no NaN/Inf is
    ever produced — important under jit where NaNs propagate silently).
    """
    num = num if jnp.issubdtype(jnp.asarray(num).dtype, jnp.floating) else jnp.asarray(num, dtype=jnp.float32)
    denom = denom if jnp.issubdtype(jnp.asarray(denom).dtype, jnp.floating) else jnp.asarray(denom, dtype=jnp.float32)
    zero = jnp.asarray(denom) == 0
    res = num / jnp.where(zero, jnp.ones((), dtype=jnp.asarray(denom).dtype), denom)
    return jnp.where(zero, jnp.asarray(zero_division, dtype=res.dtype), res)


def _adjust_weights_safe_divide(score: Array, average: Optional[str], tp: Array, fn: Array) -> Array:
    """Weighted / macro / none averaging of per-class scores (reference: compute.py)."""
    if average is None or average == "none":
        return score
    if average == "weighted":
        weights = tp + fn
    else:
        # plain ones, matching the reference exactly (accuracy.py:76,
        # f_beta.py:58, precision_recall.py:58, specificity.py:55,
        # hamming.py:78): classes absent from preds AND target contribute a
        # 0/0 -> 0 score to the macro mean rather than being excluded (the
        # exclusion convention only appears in later torchmetrics versions)
        weights = jnp.ones_like(score)
    weights = weights.astype(jnp.float32)
    return jnp.sum(_safe_divide(weights, jnp.sum(weights, axis=-1, keepdims=True)) * score, axis=-1)


def _auc_compute_without_check(x: Array, y: Array, direction: float, axis: int = -1) -> Array:
    """Trapezoidal area under the curve; ``direction`` flips sign for descending x."""
    dx = jnp.diff(x, axis=axis)
    y_avg = (jnp.take(y, jnp.arange(1, y.shape[axis]), axis=axis) + jnp.take(y, jnp.arange(0, y.shape[axis] - 1), axis=axis)) / 2.0
    return jnp.sum(dx * y_avg, axis=axis) * direction


def _auc_compute(x: Array, y: Array, reorder: bool = False) -> Array:
    if reorder:
        order = jnp.argsort(x)
        x = x[order]
        y = y[order]
    # Direction is data-dependent; resolve it with jnp.where so the fn stays jittable.
    dx = jnp.diff(x)
    direction = jnp.where(jnp.all(dx <= 0), -1.0, 1.0)
    return _auc_compute_without_check(x, y, direction)


def auc(x: Array, y: Array, reorder: bool = False) -> Array:
    """Area under the curve via the trapezoidal rule (reference compute.py:84)."""
    x = jnp.asarray(x)
    y = jnp.asarray(y)
    if x.ndim != 1 or y.ndim != 1:
        raise ValueError(f"Expected 1-d x and y, got {x.ndim}-d and {y.ndim}-d")
    if x.shape[0] != y.shape[0]:
        raise ValueError("x and y must have the same length")
    return _auc_compute(x, y, reorder=reorder)
