"""Offline weight IO: flax variable pytrees ↔ flat ``.npz`` files.

One shared protocol for every bundled network (InceptionV3 for FID/KID/IS,
the LPIPS backbones): keys are ``/``-joined pytree paths, values are the raw
arrays. Keeping a single implementation prevents the two ends of the format
from drifting apart.
"""

from __future__ import annotations

from typing import Dict

import jax
import jax.numpy as jnp
import numpy as np


def save_params(params: Dict, path: str) -> None:
    """Write a flax param/batch-stats pytree as a flat npz (keys = '/'-joined paths)."""
    flat = jax.tree_util.tree_flatten_with_path(params)[0]
    arrays = {jax.tree_util.keystr(kp, simple=True, separator="/"): np.asarray(v) for kp, v in flat}
    np.savez(path, **arrays)


def load_params(path: str) -> Dict:
    """Inverse of :func:`save_params`."""
    loaded = np.load(path)
    tree: Dict = {}
    for key in loaded.files:
        node = tree
        parts = key.split("/")
        for part in parts[:-1]:
            node = node.setdefault(part, {})
        node[parts[-1]] = jnp.asarray(loaded[key])
    return tree
