"""Data manipulation utilities.

Reference parity: src/torchmetrics/utilities/data.py — ``dim_zero_{cat,sum,mean,max,min}``
(:24-50), ``_flatten``/``_flatten_dict``, ``to_onehot``, ``select_topk``, ``to_categorical``,
``apply_to_collection`` (:148-195), ``_squeeze_if_scalar``, ``_bincount`` (:206-228, with its
XLA/deterministic fallback — natively fine here: ``jnp.bincount(length=n)`` is static-shape),
``_flexible_bincount``, ``allclose``.

TPU notes: ``_bincount`` additionally offers a one-hot matmul path that maps the histogram
onto the MXU — useful when counting into few buckets from large inputs.
"""

from __future__ import annotations

from collections.abc import Mapping, Sequence
from typing import Any, Callable, Dict, List, Optional, Tuple, Union

import jax
import jax.numpy as jnp
import numpy as np
from jax import Array

METRIC_EPS = 1e-6


def dim_zero_cat(x: Union[Array, List[Array]]) -> Array:
    """Concatenate a (list of) array(s) along dim 0."""
    if isinstance(x, (jax.Array, np.ndarray)):
        return jnp.asarray(x)
    if not x:  # empty list
        raise ValueError("No samples to concatenate")
    x = [jnp.atleast_1d(jnp.asarray(y)) for y in x]
    return jnp.concatenate(x, axis=0)


def dim_zero_sum(x: Array) -> Array:
    return jnp.sum(x, axis=0)


def dim_zero_mean(x: Array) -> Array:
    return jnp.mean(x, axis=0)


def dim_zero_max(x: Array) -> Array:
    return jnp.max(x, axis=0)


def dim_zero_min(x: Array) -> Array:
    return jnp.min(x, axis=0)


def _flatten(x: Sequence) -> list:
    """Flatten list of lists into a single list."""
    return [item for sublist in x for item in sublist]


def _flatten_dict(x: Dict) -> Tuple[Dict, bool]:
    """Flatten dict-of-dicts one level; returns (flat, was_flattened)."""
    new_dict = {}
    duplicates = False
    for key, value in x.items():
        if isinstance(value, dict):
            for k, v in value.items():
                if k in new_dict:
                    duplicates = True
                new_dict[k] = v
        else:
            if key in new_dict:
                duplicates = True
            new_dict[key] = value
    return new_dict, duplicates


def to_onehot(label_tensor: Array, num_classes: int) -> Array:
    """Convert dense label tensor ``(N, ...)`` → one-hot ``(N, C, ...)``.

    Reference: data.py ``to_onehot``. Static-shape friendly: `num_classes` must be a
    Python int (XLA constraint, same as the reference's explicit arg).
    """
    onehot = jax.nn.one_hot(label_tensor, num_classes, dtype=jnp.int64 if label_tensor.dtype == jnp.int64 else jnp.int32)
    # one_hot appends the class dim last; reference puts it at dim 1.
    return jnp.moveaxis(onehot, -1, 1)


def select_topk(prob_tensor: Array, topk: int = 1, dim: int = 1) -> Array:
    """Binary mask of the top-k entries along ``dim`` (reference data.py select_topk)."""
    if topk == 1:  # cheap argmax path
        idx = jnp.argmax(prob_tensor, axis=dim, keepdims=True)
        mask = jnp.zeros_like(prob_tensor, dtype=jnp.int32)
        return jnp.put_along_axis(mask, idx, 1, axis=dim, inplace=False)
    _, idx = jax.lax.top_k(jnp.moveaxis(prob_tensor, dim, -1), topk)
    mask = jnp.zeros(jnp.moveaxis(prob_tensor, dim, -1).shape, dtype=jnp.int32)
    mask = jnp.put_along_axis(mask, idx, 1, axis=-1, inplace=False)
    return jnp.moveaxis(mask, -1, dim)


def to_categorical(x: Array, argmax_dim: int = 1) -> Array:
    """Probabilities/logits → dense labels via argmax (reference data.py to_categorical)."""
    return jnp.argmax(x, axis=argmax_dim)


def apply_to_collection(
    data: Any,
    dtype: Union[type, tuple],
    function: Callable,
    *args: Any,
    wrong_dtype: Optional[Union[type, tuple]] = None,
    **kwargs: Any,
) -> Any:
    """Recursively apply ``function`` to all elements of type ``dtype``.

    Reference: data.py:148-195. Supports Mapping, NamedTuple, Sequence.
    """
    elem_type = type(data)
    if isinstance(data, dtype) and (wrong_dtype is None or not isinstance(data, wrong_dtype)):
        return function(data, *args, **kwargs)
    if isinstance(data, Mapping):
        return elem_type({k: apply_to_collection(v, dtype, function, *args, wrong_dtype=wrong_dtype, **kwargs) for k, v in data.items()})
    if isinstance(data, tuple) and hasattr(data, "_fields"):  # namedtuple
        return elem_type(*(apply_to_collection(d, dtype, function, *args, wrong_dtype=wrong_dtype, **kwargs) for d in data))
    if isinstance(data, Sequence) and not isinstance(data, str):
        return elem_type([apply_to_collection(d, dtype, function, *args, wrong_dtype=wrong_dtype, **kwargs) for d in data])
    return data


def _squeeze_scalar_element_tensor(x: Array) -> Array:
    return x.squeeze() if x.size == 1 else x


def _squeeze_if_scalar(data: Any) -> Any:
    return apply_to_collection(data, jax.Array, _squeeze_scalar_element_tensor)


def _bincount(x: Array, minlength: int) -> Array:
    """Count occurrences of each value in ``x`` (ints in [0, minlength)).

    Reference: data.py:206-228 — there, a fallback loop exists because
    ``torch.bincount`` is non-deterministic on CUDA and unsupported on XLA.
    Here ``jnp.bincount(length=n)`` is static-shape, deterministic and natively
    lowered by XLA (scatter-add), so no fallback is needed.
    """
    return jnp.bincount(x.reshape(-1), length=minlength)


def _bincount_matmul(x: Array, minlength: int) -> Array:
    """One-hot × ones matmul histogram — rides the MXU for large x, few buckets."""
    oh = jax.nn.one_hot(x.reshape(-1), minlength, dtype=jnp.float32)
    return jnp.sum(oh, axis=0).astype(jnp.int32)


def _flexible_bincount(x: Array) -> Array:
    """Bincount over the *unique* values of ``x`` (reference _flexible_bincount).

    Data-dependent output shape → host-side only (used by retrieval compute, which is
    host-orchestrated over list states, like the reference).
    """
    x = x - jnp.min(x)
    unique_x = jnp.unique(x)
    counts = _bincount(x, minlength=int(jnp.max(x)) + 1)
    return counts[unique_x]


def allclose(t1: Array, t2: Array, atol: float = 1e-8, rtol: float = 1e-5) -> bool:
    """dtype-robust allclose (reference data.py allclose)."""
    t1 = jnp.asarray(t1)
    t2 = jnp.asarray(t2)
    if t1.dtype != t2.dtype:
        t2 = t2.astype(t1.dtype)
    return bool(jnp.allclose(t1, t2, atol=atol, rtol=rtol))


def _cumsum(x: Array, axis: int = 0) -> Array:
    """Deterministic cumsum (reference works around CUDA nondeterminism; XLA is fine)."""
    return jnp.cumsum(x, axis=axis)
