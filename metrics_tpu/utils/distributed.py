"""Reductions + cross-process gather.

Reference parity: src/torchmetrics/utilities/distributed.py — ``reduce`` (:22),
``class_reduce`` (:44), ``gather_all_tensors`` (:93-148, incl. the pad-to-max protocol
for uneven shapes at :136-148).

TPU-native redesign (SURVEY §2.3): the reference's one collective (all_gather over
torch.distributed, reduce afterwards in Python) becomes, in order of preference:

1. *No collective at all* — in single-controller JAX, an update running on a globally
   sharded ``jax.Array`` already produces the global state (XLA inserts the psum).
2. ``jax.lax.psum/pmax/pmin/all_gather`` over a named mesh axis, when metric update/
   compute run *inside* ``shard_map`` (see :mod:`metrics_tpu.parallel.sync`).
3. Host-level gather across processes for multi-controller jobs — implemented here with
   the same pad-to-max + trim protocol as the reference for ragged states.
"""

from __future__ import annotations

from typing import Any, List, Optional

import jax
import jax.numpy as jnp
import numpy as np
from jax import Array

from metrics_tpu.utils.compute import _safe_divide


def reduce(x: Array, reduction: Optional[str]) -> Array:
    """Reduce a tensor: 'elementwise_mean' | 'sum' | 'none'/None (reference :22)."""
    if reduction == "elementwise_mean":
        return jnp.mean(x)
    if reduction == "sum":
        return jnp.sum(x)
    if reduction is None or reduction == "none":
        return x
    raise ValueError("Reduction parameter unknown.")


def class_reduce(num: Array, denom: Array, weights: Array, class_reduction: str = "none") -> Array:
    """Per-class fraction reduction: micro/macro/weighted/none with NaN→0 guard.

    Reference: distributed.py:44-90.
    """
    valid_reduction = ("micro", "macro", "weighted", "none", None)
    fraction = _safe_divide(jnp.sum(num), jnp.sum(denom)) if class_reduction == "micro" else _safe_divide(num, denom)

    if class_reduction == "micro":
        return fraction
    if class_reduction == "macro":
        return jnp.mean(fraction)
    if class_reduction == "weighted":
        return jnp.sum(fraction * _safe_divide(weights, jnp.sum(weights)))
    if class_reduction == "none" or class_reduction is None:
        return fraction
    raise ValueError(f"Reduction parameter {class_reduction} unknown. Choose between one of these: {valid_reduction}")


def distributed_available() -> bool:
    """Multi-controller JAX job? (reference: torch.distributed.is_initialized)."""
    try:
        return jax.process_count() > 1
    except Exception:
        return False


def gather_all_tensors(result: Array, group: Optional[Any] = None, *, transport: Optional[Any] = None) -> List[Array]:
    """Gather a tensor from every process into a list (reference :93-148).

    Cross-process host-level gather for multi-controller JAX, routed through the
    comm plane's transport layer (:func:`metrics_tpu.comm.transport.gather_ragged`):
    shapes gather first, then one allgather for equal shapes or the reference's
    pad-to-max + trim protocol for ragged first dims (exact-size broadcast when
    the transport supports it and padding would dominate). Mixed-rank shards
    raise — same constraint as the reference protocol. On a single process this
    is a cheap identity wrap. ``transport`` is injectable for tests and custom
    fabrics; the default is the process-wide comm transport.
    """
    from metrics_tpu.comm import plane as _plane
    from metrics_tpu.comm.transport import gather_ragged

    if transport is None:
        if not distributed_available():
            return [jnp.asarray(result)]
        transport = _plane.get_config().transport or _plane.default_transport()
    rows = gather_ragged(transport, np.asarray(result), rank=getattr(transport, "rank", None))
    return [jnp.asarray(r) for r in rows]


def default_dist_sync_fn(result: Array, group: Optional[Any] = None) -> List[Array]:
    """The default ``dist_sync_fn`` used by :class:`metrics_tpu.Metric`."""
    return gather_all_tensors(result, group)
