"""Reductions + cross-process gather.

Reference parity: src/torchmetrics/utilities/distributed.py — ``reduce`` (:22),
``class_reduce`` (:44), ``gather_all_tensors`` (:93-148, incl. the pad-to-max protocol
for uneven shapes at :136-148).

TPU-native redesign (SURVEY §2.3): the reference's one collective (all_gather over
torch.distributed, reduce afterwards in Python) becomes, in order of preference:

1. *No collective at all* — in single-controller JAX, an update running on a globally
   sharded ``jax.Array`` already produces the global state (XLA inserts the psum).
2. ``jax.lax.psum/pmax/pmin/all_gather`` over a named mesh axis, when metric update/
   compute run *inside* ``shard_map`` (see :mod:`metrics_tpu.parallel.sync`).
3. Host-level gather across processes for multi-controller jobs — implemented here with
   the same pad-to-max + trim protocol as the reference for ragged states.
"""

from __future__ import annotations

from typing import Any, Callable, List, Optional

import jax
import jax.numpy as jnp
import numpy as np
from jax import Array

from metrics_tpu.utils.compute import _safe_divide


def reduce(x: Array, reduction: Optional[str]) -> Array:
    """Reduce a tensor: 'elementwise_mean' | 'sum' | 'none'/None (reference :22)."""
    if reduction == "elementwise_mean":
        return jnp.mean(x)
    if reduction == "sum":
        return jnp.sum(x)
    if reduction is None or reduction == "none":
        return x
    raise ValueError("Reduction parameter unknown.")


def class_reduce(num: Array, denom: Array, weights: Array, class_reduction: str = "none") -> Array:
    """Per-class fraction reduction: micro/macro/weighted/none with NaN→0 guard.

    Reference: distributed.py:44-90.
    """
    valid_reduction = ("micro", "macro", "weighted", "none", None)
    fraction = _safe_divide(jnp.sum(num), jnp.sum(denom)) if class_reduction == "micro" else _safe_divide(num, denom)

    if class_reduction == "micro":
        return fraction
    if class_reduction == "macro":
        return jnp.mean(fraction)
    if class_reduction == "weighted":
        return jnp.sum(fraction * _safe_divide(weights, jnp.sum(weights)))
    if class_reduction == "none" or class_reduction is None:
        return fraction
    raise ValueError(f"Reduction parameter {class_reduction} unknown. Choose between one of these: {valid_reduction}")


def distributed_available() -> bool:
    """Multi-controller JAX job? (reference: torch.distributed.is_initialized)."""
    try:
        return jax.process_count() > 1
    except Exception:
        return False


def gather_all_tensors(result: Array, group: Optional[Any] = None) -> List[Array]:
    """Gather a tensor from every process into a list (reference :93-148).

    Cross-process host-level gather for multi-controller JAX. Handles uneven first-dim
    shapes with the reference's pad-to-max + trim protocol. On a single process this is
    a cheap identity wrap.
    """
    if not distributed_available():
        return [jnp.asarray(result)]

    from jax.experimental import multihost_utils

    result = jnp.asarray(result)
    world = jax.process_count()
    # gather shapes first (same protocol as reference :126-142)
    local_shape = np.asarray(result.shape, dtype=np.int64) if result.ndim else np.zeros((0,), np.int64)
    all_shapes = multihost_utils.process_allgather(local_shape)  # (world, ndim)
    all_shapes = [tuple(int(d) for d in s) for s in np.asarray(all_shapes)]
    if all(s == all_shapes[0] for s in all_shapes):
        gathered = multihost_utils.process_allgather(result)  # (world, ...)
        return [jnp.asarray(gathered[i]) for i in range(world)]
    # uneven: pad to max along every dim, gather, trim
    max_shape = tuple(max(s[d] for s in all_shapes) for d in range(len(all_shapes[0])))
    pad = [(0, m - s) for m, s in zip(max_shape, result.shape)]
    padded = jnp.pad(result, pad)
    gathered = multihost_utils.process_allgather(padded)
    out = []
    for i in range(world):
        slices = tuple(slice(0, d) for d in all_shapes[i])
        out.append(jnp.asarray(gathered[i])[slices])
    return out


def default_dist_sync_fn(result: Array, group: Optional[Any] = None) -> List[Array]:
    """The default ``dist_sync_fn`` used by :class:`metrics_tpu.Metric`."""
    return gather_all_tensors(result, group)
