"""Rank-zero-only printing/warning helpers.

Reference parity: src/torchmetrics/utilities/prints.py:22-49 (``rank_zero_only`` keyed on
the ``LOCAL_RANK`` env var). TPU-native version keys on ``jax.process_index()`` — the
multi-controller JAX equivalent of a distributed rank — falling back to the env var when
JAX is not yet initialised.
"""

from __future__ import annotations

import logging
import os
import warnings
from functools import partial, wraps
from typing import Any, Callable

log = logging.getLogger("metrics_tpu")


def _rank() -> int:
    try:
        import jax

        return jax.process_index()
    except Exception:
        return int(os.environ.get("LOCAL_RANK", 0))


def rank_zero_only(fn: Callable) -> Callable:
    """Run ``fn`` only on process 0 of a multi-process JAX job."""

    @wraps(fn)
    def wrapped_fn(*args: Any, **kwargs: Any) -> Any:
        if _rank() == 0:
            return fn(*args, **kwargs)
        return None

    return wrapped_fn


@rank_zero_only
def _warn(message: str, *args: Any, **kwargs: Any) -> None:
    warnings.warn(message, *args, **kwargs)


@rank_zero_only
def _info(message: str, **kwargs: Any) -> None:
    log.info(message, **kwargs)


@rank_zero_only
def _debug(message: str, **kwargs: Any) -> None:
    log.debug(message, **kwargs)


rank_zero_warn = _warn
rank_zero_info = _info
rank_zero_debug = _debug


def rank_zero_warn_once(message: str) -> None:
    _seen = _warn_once_registry
    if message not in _seen:
        _seen.add(message)
        rank_zero_warn(message)


_warn_once_registry: set = set()
