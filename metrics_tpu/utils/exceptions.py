"""Exception types.

Reference parity: src/torchmetrics/utilities/exceptions.py (TorchMetricsUserError).
"""


class MetricsTPUUserError(Exception):
    """Error raised for misuse of the metrics API."""


# Alias with a generic name used across the package.
UserError = MetricsTPUUserError
