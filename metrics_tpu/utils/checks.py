"""Input validation helpers.

Reference parity: src/torchmetrics/utilities/checks.py (751 LoC). The reference's checks
freely branch on tensor *values* (e.g. "preds must be in [0,1]"). Under XLA that is only
possible on concrete (non-traced) arrays, so every value-dependent check here goes through
:func:`_value_check_possible` and silently no-ops when the input is a tracer — the exact
analogue of the reference's ``validate_args=False`` escape hatch, applied automatically
inside jit. Shape/dtype checks are trace-safe and always run.
"""

from __future__ import annotations

from typing import Any, Optional, Sequence, Tuple

import jax
import jax.numpy as jnp
from jax import Array

from metrics_tpu.utils.data import _flatten, select_topk, to_onehot
from metrics_tpu.utils.enums import DataType


def is_tracer(x: Any) -> bool:
    return isinstance(x, jax.core.Tracer)


def _value_check_possible(*arrays: Any) -> bool:
    """True if all inputs are concrete (value-dependent validation may run)."""
    return not any(is_tracer(a) for a in arrays)


def _check_same_shape(preds: Array, target: Array) -> None:
    """Raise if shapes differ (trace-safe; shapes are static under XLA)."""
    if preds.shape != target.shape:
        raise RuntimeError(
            f"Predictions and targets are expected to have the same shape, but got {preds.shape} and {target.shape}."
        )


def _basic_input_validation(preds: Array, target: Array, threshold: float, ignore_index: Optional[int]) -> None:
    """Basic cross-metric validation (reference checks.py:26-60)."""
    if _value_check_possible(target) and jnp.issubdtype(target.dtype, jnp.floating):
        raise ValueError("The `target` has to be an integer tensor.")

    if _value_check_possible(target):
        unique_values = jnp.unique(target)
        if ignore_index is None:
            check = jnp.any((unique_values != 0) & (unique_values != 1) & (unique_values < 0))
        else:
            check = jnp.any((unique_values != 0) & (unique_values != 1) & (unique_values != ignore_index) & (unique_values < 0))
        if bool(check):
            raise ValueError("The `target` has to be a non-negative tensor.")

    preds_float = jnp.issubdtype(preds.dtype, jnp.floating)
    if _value_check_possible(preds) and not preds_float and bool(jnp.any(preds < 0)):
        raise ValueError("If `preds` are integers, they have to be non-negative.")

    if not 0 < threshold < 1:
        raise ValueError(f"The `threshold` should be a float in the (0,1) interval, got {threshold}")


def _check_shape_and_type_consistency(preds: Array, target: Array) -> Tuple[DataType, int]:
    """Classify the input pair as BINARY / MULTICLASS / MULTILABEL / MULTIDIM_MULTICLASS.

    Reference: checks.py:63-120. Shape-only logic → fully trace-safe.
    """
    preds_float = jnp.issubdtype(preds.dtype, jnp.floating)

    if preds.ndim == target.ndim:
        if preds.shape != target.shape:
            raise ValueError("The `preds` and `target` should have the same shape, got different shapes.")
        if preds_float and _value_check_possible(target) and int(jnp.max(target, initial=0)) > 1:
            raise ValueError("If `preds` and `target` are of shape (N, ...) and `preds` are floats, `target` should be binary.")
        if preds.ndim == 1:
            case = DataType.BINARY if preds_float else DataType.MULTICLASS
        else:
            case = DataType.MULTILABEL if preds_float else DataType.MULTIDIM_MULTICLASS
        implied_classes = preds.shape[1] if preds.ndim > 1 else 1
    elif preds.ndim == target.ndim + 1:
        if not preds_float:
            raise ValueError("If `preds` have one dimension more than `target`, `preds` should be a float tensor.")
        if preds.shape[2:] != target.shape[1:]:
            raise ValueError("If `preds` have one dimension more than `target`, the shape must be (N, C, ...).")
        implied_classes = preds.shape[1]
        case = DataType.MULTICLASS if preds.ndim == 2 else DataType.MULTIDIM_MULTICLASS
    else:
        raise ValueError("Either `preds` and `target` both should have the (same) shape (N, ...), or `target` (N, ...) and `preds` (N, C, ...).")
    return case, implied_classes


def _check_classification_inputs(
    preds: Array,
    target: Array,
    threshold: float,
    num_classes: Optional[int],
    multiclass: Optional[bool],
    top_k: Optional[int],
    ignore_index: Optional[int] = None,
) -> DataType:
    """Full legacy-input validation (reference checks.py:123-…, abbreviated to the
    shape/type machine; value checks only run on concrete arrays)."""
    _basic_input_validation(preds, target, threshold, ignore_index)
    case, implied_classes = _check_shape_and_type_consistency(preds, target)
    if num_classes is not None and case != DataType.BINARY and num_classes != implied_classes and preds.ndim != target.ndim:
        raise ValueError(f"num_classes={num_classes} does not match implied classes {implied_classes}")
    if top_k is not None and case not in (DataType.MULTICLASS, DataType.MULTIDIM_MULTICLASS) and not (
        case == DataType.MULTILABEL and top_k == 1
    ):
        if top_k != 1:
            raise ValueError("You can only use `top_k` with multiclass inputs.")
    return case


def _input_squeeze(preds: Array, target: Array) -> Tuple[Array, Array]:
    """Remove excess (size-1 trailing batch) dimensions (reference checks.py)."""
    if preds.shape[0] == 1:
        preds = preds.reshape(1, -1) if preds.ndim > 1 and preds.shape[1] > 1 else preds.reshape(1, -1)
        target = target.reshape(1, -1)
    else:
        preds, target = preds.squeeze(), target.squeeze()
    return preds, target


def _input_format_classification(
    preds: Array,
    target: Array,
    threshold: float = 0.5,
    top_k: Optional[int] = None,
    num_classes: Optional[int] = None,
    multiclass: Optional[bool] = None,
    ignore_index: Optional[int] = None,
) -> Tuple[Array, Array, DataType]:
    """Legacy formatter: any valid input pair → ``(N, C)``/``(N, C, X)`` binary tensors.

    Reference: checks.py ``_input_format_classification``. Used by the legacy-style
    metrics (e.g. Dice). Returns int arrays of 0/1 plus the detected mode.
    """
    preds = jnp.asarray(preds)
    target = jnp.asarray(target)
    if preds.ndim == 0:
        preds = preds.reshape(1)
    if target.ndim == 0:
        target = target.reshape(1)
    case = _check_classification_inputs(
        preds, target, threshold=threshold, num_classes=num_classes, multiclass=multiclass, top_k=top_k,
        ignore_index=ignore_index,
    )
    preds_float = jnp.issubdtype(preds.dtype, jnp.floating)
    top_k = top_k if top_k else 1

    if case in (DataType.BINARY, DataType.MULTILABEL) and not top_k > 1:
        if preds_float:
            # logits → probs
            if _value_check_possible(preds) and bool(jnp.any((preds < 0) | (preds > 1))):
                preds = jax.nn.sigmoid(preds)
            preds = (preds >= threshold).astype(jnp.int32)
        else:
            preds = preds.astype(jnp.int32)
        preds = preds.reshape(preds.shape[0], -1)
        target = target.reshape(target.shape[0], -1).astype(jnp.int32)
        if multiclass:
            target = to_onehot(target.reshape(-1), 2).reshape(target.shape[0] * target.shape[1], 2) if case == DataType.BINARY else target
    elif case == DataType.MULTICLASS or (case == DataType.MULTIDIM_MULTICLASS) or top_k > 1:
        nc = num_classes
        if nc is None:
            if preds.ndim == target.ndim + 1:
                nc = preds.shape[1]
            else:
                if not _value_check_possible(preds, target):
                    raise ValueError("num_classes must be given explicitly inside jit")
                nc = int(max(int(jnp.max(preds, initial=0)), int(jnp.max(target, initial=0)))) + 1
        if preds.ndim == target.ndim + 1:  # probs/logits
            axes = (0, 1) + tuple(range(2, preds.ndim))
            preds = select_topk(preds, top_k, dim=1)
        else:
            preds = to_onehot(preds.astype(jnp.int32), nc)
        target = to_onehot(target.astype(jnp.int32), nc)
        preds = preds.reshape(preds.shape[0], preds.shape[1], -1).reshape(preds.shape[0], -1) if preds.ndim > 2 and case != DataType.MULTIDIM_MULTICLASS else preds
        # flatten extra dims into (N, C, X) → (N*X, C)
        if preds.ndim > 2:
            preds = jnp.moveaxis(preds, 1, -1).reshape(-1, nc)
            target = jnp.moveaxis(target, 1, -1).reshape(-1, nc)
        preds = preds.reshape(-1, nc).astype(jnp.int32)
        target = target.reshape(-1, nc).astype(jnp.int32)
    else:
        raise ValueError(f"Unsupported input case {case}")
    return preds, target, case


def _check_retrieval_shape(indexes: Array, preds: Array, target: Array) -> None:
    if indexes.shape != preds.shape or target.shape != preds.shape:
        raise IndexError("`indexes`, `preds` and `target` must be of the same shape")


def _check_retrieval_inputs(
    indexes: Array,
    preds: Array,
    target: Array,
    allow_non_binary_target: bool = False,
    ignore_index: Optional[int] = None,
) -> Tuple[Array, Array, Array]:
    """Check and format retrieval inputs (reference checks.py _check_retrieval_inputs)."""
    if indexes.shape == () or preds.shape == () or target.shape == ():
        raise ValueError("`indexes`, `preds` and `target` must be non-empty and non-scalar tensors")
    _check_retrieval_shape(indexes, preds, target)
    if not jnp.issubdtype(indexes.dtype, jnp.integer):
        raise ValueError("`indexes` must be a tensor of long integers")
    if not jnp.issubdtype(preds.dtype, jnp.floating):
        raise ValueError("`preds` must be a tensor of floats")
    if not jnp.issubdtype(target.dtype, jnp.integer) and not jnp.issubdtype(target.dtype, jnp.bool_):
        raise ValueError("`target` must be a tensor of booleans or integers")
    if ignore_index is not None and _value_check_possible(target):
        valid = target != ignore_index
        indexes, preds, target = indexes[valid], preds[valid], target[valid]
    if not allow_non_binary_target and _value_check_possible(target) and bool(jnp.any((jnp.asarray(target) > 1) | (jnp.asarray(target) < 0))):
        raise ValueError("`target` must contain `binary` values")
    # int32 query ids: jax defaults to 32-bit ints (x64 disabled) and an int64
    # request would just warn and truncate to int32 anyway.
    return indexes.reshape(-1).astype(jnp.int32), preds.reshape(-1).astype(jnp.float32), target.reshape(-1)


def _allclose_recursive(res1: Any, res2: Any, atol: float = 1e-8) -> bool:
    """Recursive allclose over nested list/tuple/dict of arrays (reference checks.py)."""
    if isinstance(res1, (list, tuple)):
        return all(_allclose_recursive(r1, r2, atol) for r1, r2 in zip(res1, res2))
    if isinstance(res1, dict):
        return all(_allclose_recursive(res1[k], res2[k], atol) for k in res1)
    return bool(jnp.allclose(jnp.asarray(res1), jnp.asarray(res2), atol=atol))


def check_forward_full_state_property(
    metric_class,
    init_args: Optional[dict] = None,
    input_args: Optional[dict] = None,
    num_update_to_compare: Sequence[int] = (10, 100, 1000),
    reps: int = 5,
) -> None:
    """Time full-state vs reduced-state ``forward`` (reference checks.py:626-714)."""
    import time

    init_args = init_args or {}
    input_args = input_args or {}

    class FullState(metric_class):
        full_state_update = True

    class PartState(metric_class):
        full_state_update = False

    fullstate = FullState(**init_args)
    partstate = PartState(**init_args)

    equal = True
    for _ in range(max(num_update_to_compare)):
        out1 = fullstate(**input_args)
        out2 = partstate(**input_args)
        equal = equal and _allclose_recursive(out1, out2)
    res1 = fullstate.compute()
    res2 = partstate.compute()
    equal = equal and _allclose_recursive(res1, res2)
    mean_full, mean_part = [], []
    for metric in (FullState, PartState):
        out = mean_full if metric is FullState else mean_part
        for num in num_update_to_compare:
            m = metric(**init_args)
            start = time.perf_counter()
            for _ in range(reps):
                for _ in range(num):
                    m(**input_args)
                m.reset()
            out.append((time.perf_counter() - start) / reps)
    faster = sum(mean_part) < sum(mean_full)
    print(f"Output equal: {equal}; partial-state faster: {faster}")
    if equal and faster:
        print(f"Recommended: set `full_state_update=False` on {metric_class.__name__}")
