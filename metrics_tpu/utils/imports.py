"""Optional-dependency availability flags.

Reference parity: src/torchmetrics/utilities/imports.py:20-45. Anything not baked into
the image is gated here and the dependent metric raises a clear error at construction.
"""

from __future__ import annotations

import importlib.util


def _package_available(name: str) -> bool:
    try:
        return importlib.util.find_spec(name) is not None
    except (ImportError, ModuleNotFoundError, ValueError):
        return False


_TRANSFORMERS_AVAILABLE = _package_available("transformers")
_SCIPY_AVAILABLE = _package_available("scipy")
_NLTK_AVAILABLE = _package_available("nltk")
_JIWER_AVAILABLE = _package_available("jiwer")
_ROUGE_SCORE_AVAILABLE = _package_available("rouge_score")
_BERTSCORE_AVAILABLE = _TRANSFORMERS_AVAILABLE
_SACREBLEU_AVAILABLE = _package_available("sacrebleu")
_REGEX_AVAILABLE = _package_available("regex")
_PESQ_AVAILABLE = _package_available("pesq")
_PYSTOI_AVAILABLE = _package_available("pystoi")
_LPIPS_AVAILABLE = _package_available("lpips")
_MATPLOTLIB_AVAILABLE = _package_available("matplotlib")
# The reference additionally gates on pycocotools/torchvision/torch-fidelity/
# fast_bss_eval/tqdm (ref imports.py:36-44); those paths are fully native here
# (detection mAP incl. segm, SDR, inception features, no progress-bar dep), so
# no flags exist for them.
_SKLEARN_AVAILABLE = _package_available("sklearn")
_FLAX_AVAILABLE = _package_available("flax")
_TORCH_AVAILABLE = _package_available("torch")
