"""Matplotlib-optional plot helpers backing ``Metric.plot()``.

Reference parity: src/torchmetrics/utilities/plot.py:43 (``plot_single_or_multi_val``),
:156 (``plot_confusion_matrix``). Values here are jax/numpy arrays (or lists of them
for time series); everything is converted with ``np.asarray`` on entry, so plotting
never touches the device.
"""

from __future__ import annotations

from math import ceil, floor, sqrt
from typing import Any, List, Optional, Sequence, Tuple, Union

import numpy as np

from metrics_tpu.utils.imports import _MATPLOTLIB_AVAILABLE

_PLOT_OUT_TYPE = Tuple[object, object]


def _error_on_missing_matplotlib() -> None:
    if not _MATPLOTLIB_AVAILABLE:
        raise ModuleNotFoundError(
            "Plot function expects `matplotlib` to be installed. Please install with `pip install matplotlib`"
        )


def plot_single_or_multi_val(
    val: Union[Any, Sequence[Any]],
    ax: Optional[Any] = None,
    higher_is_better: Optional[bool] = None,
    lower_bound: Optional[float] = None,
    upper_bound: Optional[float] = None,
    legend_name: Optional[str] = None,
    name: Optional[str] = None,
) -> _PLOT_OUT_TYPE:
    """Plot one metric value, a per-class value vector, or a time series of either.

    A single array is rendered as point markers (scalar: one dot; vector: one dot per
    class/label); a list/tuple of arrays is a time series with steps on the x-axis.
    Bounds are drawn as dashed lines with an "Optimal value" marker on the better one.

    Returns ``(fig, ax)``; raises ``ModuleNotFoundError`` without matplotlib.
    """
    _error_on_missing_matplotlib()
    import matplotlib.pyplot as plt

    fig, ax = plt.subplots() if ax is None else (None, ax)
    ax.get_xaxis().set_visible(False)

    if not isinstance(val, (list, tuple)):
        arr = np.atleast_1d(np.asarray(val))
        if arr.size == 1:
            ax.plot(arr, marker="o", markersize=10)
        else:
            for i, v in enumerate(arr):
                label = f"{legend_name} {i}" if legend_name else f"{i}"
                ax.plot(i, v, marker="o", markersize=10, linestyle="None", label=label)
    else:
        series = np.stack([np.asarray(v) for v in val], 0)  # [steps] or [steps, classes]
        multi_series = series.ndim != 1
        series = series.T if multi_series else series[None, :]
        for i, v in enumerate(series):
            label = (f"{legend_name} {i}" if legend_name else f"{i}") if multi_series else ""
            ax.plot(v, marker="o", markersize=10, linestyle="-", label=label)
        ax.get_xaxis().set_visible(True)
        ax.set_xlabel("Step")
        ax.set_xticks(np.arange(series.shape[1]))

    handles, labels = ax.get_legend_handles_labels()
    if handles and labels:
        ax.legend(handles, labels, loc="upper center", bbox_to_anchor=(0.5, 1.15), ncol=3, fancybox=True, shadow=True)

    ylim = ax.get_ylim()
    if lower_bound is not None and upper_bound is not None:
        factor = 0.1 * (upper_bound - lower_bound)
    else:
        factor = 0.1 * (ylim[1] - ylim[0])
    ax.set_ylim(
        bottom=lower_bound - factor if lower_bound is not None else ylim[0] - factor,
        top=upper_bound + factor if upper_bound is not None else ylim[1] + factor,
    )

    ax.grid(True)
    ax.set_ylabel(name if name is not None else None)

    xlim = ax.get_xlim()
    factor = 0.1 * (xlim[1] - xlim[0])
    bounds = [b for b in (lower_bound, upper_bound) if b is not None]
    if bounds:
        ax.hlines(bounds, xlim[0], xlim[1], linestyles="dashed", colors="k")
    if higher_is_better is not None:
        if lower_bound is not None and not higher_is_better:
            ax.set_xlim(xlim[0] - factor, xlim[1])
            ax.text(xlim[0], lower_bound, s="Optimal \n value", horizontalalignment="center", verticalalignment="center")
        if upper_bound is not None and higher_is_better:
            ax.set_xlim(xlim[0] - factor, xlim[1])
            ax.text(xlim[0], upper_bound, s="Optimal \n value", horizontalalignment="center", verticalalignment="center")
    return fig, ax


def _get_col_row_split(n: int) -> Tuple[int, int]:
    """Near-square rows x cols split for n panels."""
    nsq = sqrt(n)
    if int(nsq) ** 2 == n:
        return int(nsq), int(nsq)
    if floor(nsq) * ceil(nsq) >= n:
        return floor(nsq), ceil(nsq)
    return ceil(nsq), ceil(nsq)


def trim_axs(axs: Any, nb: int) -> Any:
    """Keep the first ``nb`` axes of a subplot grid, removing the rest from the figure."""
    if not isinstance(axs, np.ndarray):
        return axs
    flat = list(axs.flat)
    for ax in flat[nb:]:
        ax.remove()
    return np.asarray(flat[:nb])


def plot_confusion_matrix(
    confmat: Any,
    add_text: bool = True,
    labels: Optional[List[str]] = None,
) -> _PLOT_OUT_TYPE:
    """Render an ``[N, N]`` confusion matrix (or ``[L, 2, 2]`` multilabel stack)."""
    _error_on_missing_matplotlib()
    import matplotlib.pyplot as plt

    confmat = np.asarray(confmat)
    if confmat.ndim == 3:  # multilabel
        nb, n_classes = confmat.shape[0], 2
        rows, cols = _get_col_row_split(nb)
    else:
        nb, n_classes, rows, cols = 1, confmat.shape[0], 1, 1

    if labels is not None and confmat.ndim != 3 and len(labels) != n_classes:
        raise ValueError(
            "Expected number of elements in arg `labels` to match number of labels in confmat but "
            f"got {len(labels)} and {n_classes}"
        )
    labels = labels if labels is not None else np.arange(n_classes).tolist()

    fig, axs = plt.subplots(nrows=rows, ncols=cols)
    axs = trim_axs(axs, nb)
    for i in range(nb):
        ax = axs[i] if isinstance(axs, np.ndarray) else axs
        if confmat.ndim == 3:
            ax.set_title(f"Label {i}", fontsize=15)
        ax.imshow(confmat[i] if confmat.ndim == 3 else confmat)
        ax.set_xlabel("True class", fontsize=15)
        ax.set_ylabel("Predicted class", fontsize=15)
        ax.set_xticks(list(range(n_classes)))
        ax.set_yticks(list(range(n_classes)))
        ax.set_xticklabels(labels, rotation=45, fontsize=10)
        ax.set_yticklabels(labels, rotation=25, fontsize=10)
        if add_text:
            for ii in range(n_classes):
                for jj in range(n_classes):
                    v = confmat[i, ii, jj] if confmat.ndim == 3 else confmat[ii, jj]
                    ax.text(jj, ii, str(v.item()), ha="center", va="center", fontsize=15)
    return fig, axs
