"""Tier plane configuration: the knobs of the residency hierarchy."""

from __future__ import annotations

import time
from dataclasses import dataclass, field
from typing import Callable, Optional

from metrics_tpu.utils.exceptions import MetricsTPUUserError


@dataclass(frozen=True)
class TierConfig:
    """Residency policy for a :class:`~metrics_tpu.engine.StreamingEngine`.

    ``hot_capacity`` bounds the number of tenants resident in the stacked
    device slab; the eviction pass (dispatcher thread, between micro-batches)
    demotes the coldest tenants down to this bound, so HBM scales with the
    hot-set size rather than the registered-tenant count. ``warm_capacity``
    bounds the host-RAM mirror — overflow spills to ``spill_directory`` in the
    ``MTCKPT1`` container format (``None`` disables the cold tier and lets the
    warm mirror grow unbounded). Idleness is a per-tenant last-active stamp:
    each dispatched request re-stamps its tenant, and seconds since the stamp
    (saturating at ``idle_demote_s``) is the coldness ordering — so a
    saturated reading certifies at least ``idle_demote_s`` seconds of
    silence. Quarantined tenants evict first; pinned tenants never.
    """

    hot_capacity: int = 1024
    warm_capacity: Optional[int] = None
    spill_directory: Optional[str] = None
    idle_demote_s: float = 30.0
    check_interval_s: float = 0.05
    durable: bool = True
    clock: Callable[[], float] = field(default=time.perf_counter, repr=False)

    def __post_init__(self) -> None:
        if self.hot_capacity < 1:
            raise MetricsTPUUserError(
                f"tier.hot_capacity must be >= 1, got {self.hot_capacity}"
            )
        if self.warm_capacity is not None and self.warm_capacity < 0:
            raise MetricsTPUUserError(
                f"tier.warm_capacity must be >= 0, got {self.warm_capacity}"
            )
        if self.warm_capacity is not None and self.spill_directory is None:
            raise MetricsTPUUserError(
                "tier.warm_capacity needs tier.spill_directory — a bounded warm "
                "mirror has to overflow somewhere"
            )
        if self.idle_demote_s <= 0:
            raise MetricsTPUUserError(
                f"tier.idle_demote_s must be > 0, got {self.idle_demote_s}"
            )
        if self.check_interval_s < 0:
            raise MetricsTPUUserError(
                f"tier.check_interval_s must be >= 0, got {self.check_interval_s}"
            )
