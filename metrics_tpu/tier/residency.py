"""Residency manager: which tenants live where, and who gets demoted next.

Mechanics and policy for the three-tier slab. A non-resident tenant is one
*entry* — ``{"state": host tree | None, "ring": [row | None, ...], "rot": N}``
— captured from the stacked slab at demotion time. ``rot`` is the engine's
rotation counter when the entry was captured: window ring segments age out by
rotation, so readmission (and host-side peeks) place each captured row at its
*absolute* segment index rather than positionally, which is what makes a
demote→readmit round trip bit-identical to a never-demoted twin even when
rotations happened in between.

The manager itself holds no locks: every mutating call happens on the engine's
dispatcher thread or under the engine's dispatch lock (the same discipline the
slab itself uses). Idleness is a per-tenant last-active stamp: ``touch``
records the clock, seconds since the stamp (saturating at ``idle_demote_s``)
is the coldness ordering, and a tenant with no stamp counts as fully idle.
``touch`` runs once per dispatched request on the hot path, which is why it is
a bare dict write rather than anything with a lock in it (the tier <5%
overhead gate in benchmarks/engine_throughput.py).
"""

from __future__ import annotations

from typing import Any, Dict, Hashable, List, Optional, Set, Tuple

import jax
import jax.numpy as jnp

from metrics_tpu.engine.stream import KeyedState
from metrics_tpu.tier.coldstore import ColdStore
from metrics_tpu.tier.config import TierConfig

HOT = "hot"
WARM = "warm"
COLD = "cold"


# --------------------------------------------------------------------- mechanics


def capture_entry(keyed: Any, key: Hashable) -> Dict[str, Any]:
    """One tenant's full state as a host entry (live + ring rows + rotation stamp).

    Does not mutate the slab — the caller evicts separately so the capture /
    journal / evict order stays explicit at the call site.
    """
    state = keyed.state_of(key)
    ring_rows: List[Any] = []
    if isinstance(keyed, KeyedState):
        slot = keyed._slots[key]
        if keyed._ring is not None:
            for cap, snap in keyed._ring:
                if slot >= cap:
                    ring_rows.append(None)
                else:
                    ring_rows.append(jax.tree.map(lambda x: x[slot], snap))
    else:
        if keyed._ring is not None:
            for seg in keyed._ring:
                ring_rows.append(seg.get(key))
    entry = jax.device_get({"state": state, "ring": ring_rows})
    entry["rot"] = int(keyed.rotations)
    return entry


def _scatter_ring_row(keyed: KeyedState, slot: int, pos: int, row: Any) -> None:
    ring = keyed._ring
    cap, snap = ring[pos]
    if slot >= cap:
        # the segment snapshot predates this slot: grow it so the readmitted
        # contribution has a row to land in
        leaves, treedef = jax.tree_util.tree_flatten(snap)
        grown = [
            jnp.concatenate(
                [leaf, jnp.broadcast_to(init, (keyed.capacity - cap,) + init.shape)],
                axis=0,
            )
            for leaf, init in zip(leaves, keyed._init_leaves)
        ]
        snap = jax.tree_util.tree_unflatten(treedef, grown)
        cap = keyed.capacity
    snap = jax.tree.map(lambda s, r: s.at[slot].set(jnp.asarray(r)), snap, row)
    ring[pos] = (cap, snap)


def restore_entry(keyed: Any, key: Hashable, entry: Dict[str, Any]) -> None:
    """Readmit a captured entry into an already-allocated slot.

    Each captured ring row lands at its absolute segment index (rows whose
    segment aged out of the window are dropped); the captured live state lands
    in the slab if no rotation happened since capture, otherwise in the ring
    segment the live segment became — exactly where a never-demoted twin's
    contribution would sit.
    """
    rot = int(entry.get("rot", keyed.rotations))
    shift = keyed.rotations - rot
    rows = list(entry.get("ring") or [])
    state = entry.get("state")
    if isinstance(keyed, KeyedState):
        keyed.ensure_capacity()
        slot = keyed._slots[key]
        ring = keyed._ring
        cur_len = len(ring) if ring is not None else 0
        base = keyed.rotations - cur_len  # absolute index of ring[0]
        for j, row in enumerate(rows):
            if row is None:
                continue
            pos = (rot - len(rows) + j) - base
            if 0 <= pos < cur_len:
                _scatter_ring_row(keyed, slot, pos, row)
        if state is not None:
            if shift == 0:
                keyed.set_state(key, jax.tree.map(jnp.asarray, state))
            else:
                pos = rot - base
                if 0 <= pos < cur_len:
                    _scatter_ring_row(keyed, slot, pos, state)
    else:
        ring = keyed._ring
        cur_len = len(ring) if ring is not None else 0
        base = keyed.rotations - cur_len
        for j, row in enumerate(rows):
            if row is None:
                continue
            pos = (rot - len(rows) + j) - base
            if 0 <= pos < cur_len:
                ring[pos][key] = row
        if state is not None and shift == 0:
            keyed.set_state(key, state)
        else:
            keyed.slot_for(key)  # ensure an init live state exists
            if state is not None and shift > 0:
                pos = rot - base
                if 0 <= pos < cur_len:
                    ring[pos][key] = state


def peek_state(metric: Any, keyed: Any, entry: Dict[str, Any], *, window: bool) -> Any:
    """Host-side read of a non-resident entry — no readmission, no slab writes.

    Returns what ``state_of`` (``window=False``) or ``merged_state``
    (``window=True``) would return had the tenant been readmitted first.
    """
    rot = int(entry.get("rot", keyed.rotations))
    shift = keyed.rotations - rot
    state = entry.get("state")
    live = state if (state is not None and shift == 0) else None
    ring = getattr(keyed, "_ring", None)
    if not window or not ring:
        return live if live is not None else metric.init_state()
    base = keyed.rotations - len(ring)
    rows = list(entry.get("ring") or [])
    contributions: List[Tuple[int, Any]] = []
    for j, row in enumerate(rows):
        if row is None:
            continue
        abs_idx = rot - len(rows) + j
        if abs_idx >= base:
            contributions.append((abs_idx, row))
    if state is not None and shift > 0 and rot >= base:
        contributions.append((rot, state))
    contributions.sort(key=lambda t: t[0])
    merged = None
    for _, row in contributions:
        merged = row if merged is None else metric.merge_states(merged, row)
    if live is not None:
        merged = live if merged is None else metric.merge_states(merged, live)
    return merged if merged is not None else metric.init_state()


# ------------------------------------------------------------------------ policy


class TierManager:
    """Warm mirror + cold manifest + eviction policy for one engine."""

    def __init__(self, cfg: TierConfig, metric: Any) -> None:
        self.cfg = cfg
        self.metric = metric
        self.warm: Dict[Hashable, Dict[str, Any]] = {}
        self.cold: Dict[Hashable, Optional[str]] = {}  # key -> spill file, None = init
        self.pinned: Set[Hashable] = set()
        self.store: Optional[ColdStore] = (
            ColdStore(cfg.spill_directory, durable=cfg.durable)
            if cfg.spill_directory
            else None
        )
        self._heat: Dict[Hashable, float] = {}  # key -> last-active clock stamp
        self._next_check = 0.0

    # -------------------------------------------------------------- residency map

    def has(self, key: Hashable) -> bool:
        return key in self.warm or key in self.cold

    def tier_of(self, key: Hashable) -> Optional[str]:
        if key in self.warm:
            return WARM
        if key in self.cold:
            return COLD
        return None

    def keys(self) -> Tuple[Hashable, ...]:
        return tuple(self.warm) + tuple(self.cold)

    def register_cold(self, key: Hashable) -> bool:
        """Register a tenant with no state yet: a cold, init-valued resident.

        Costs one dict entry — this is what lets a million registered tenants
        coexist with a bounded slab.
        """
        if key in self.warm or key in self.cold:
            return False
        self.cold[key] = None
        return True

    def discard(self, key: Hashable) -> None:
        """Drop any non-resident record for ``key`` (it went hot, or was evicted)."""
        self.warm.pop(key, None)
        name = self.cold.pop(key, None)
        if name and self.store is not None:
            self.store.delete(name)

    def pop_entry(self, key: Hashable) -> Tuple[Optional[Dict[str, Any]], Optional[str]]:
        """Remove and return (entry, source_tier) for a non-resident tenant.

        A cold tenant's blob is read back through the ``MTCKPT1`` restore path;
        its spill file is NOT deleted here — the caller deletes only after the
        promotion is journaled, so recovery can never dangle on a pointer whose
        promote record hasn't landed.
        """
        entry = self.warm.pop(key, None)
        if entry is not None:
            return entry, WARM
        if key in self.cold:
            name = self.cold.pop(key)
            if name is None:
                return None, COLD
            assert self.store is not None
            entry = self.store.load(name)
            entry["_spill_file"] = name
            return entry, COLD
        return None, None

    def peek_entry(self, key: Hashable) -> Optional[Dict[str, Any]]:
        """Read a non-resident tenant's entry without changing its residency."""
        entry = self.warm.get(key)
        if entry is not None:
            return entry
        if key in self.cold:
            name = self.cold[key]
            if name is None:
                return None
            assert self.store is not None
            return self.store.load(name)
        return None

    # ------------------------------------------------------------------- idleness

    def touch(self, key: Hashable) -> None:
        """Record activity: stamp the tenant's last-active instant."""
        self._heat[key] = self.cfg.clock()

    def idleness(self, key: Hashable) -> float:
        """Seconds since last touch, saturating at ``idle_demote_s``."""
        stamp = self._heat.get(key)
        if stamp is None:
            return self.cfg.idle_demote_s
        idle = self.cfg.clock() - stamp
        cap = self.cfg.idle_demote_s
        return cap if idle > cap else (idle if idle > 0 else 0.0)

    def forget_heat(self, key: Hashable) -> None:
        self._heat.pop(key, None)

    # --------------------------------------------------------------------- policy

    def due(self, hot_count: int) -> bool:
        """Cheap gate for the between-batches pass: over cap, or cadence elapsed."""
        if hot_count > self.cfg.hot_capacity:
            return True
        now = self.cfg.clock()
        if now >= self._next_check:
            self._next_check = now + self.cfg.check_interval_s
            return True
        return False

    def victims(
        self, hot_keys: Any, need: int, quarantined: Set[Hashable]
    ) -> List[Hashable]:
        """Pick ``need`` demotion victims: quarantined first, then coldest."""
        if need <= 0:
            return []
        scored = []
        for i, key in enumerate(hot_keys):
            if key in self.pinned:
                continue
            scored.append((key in quarantined, self.idleness(key), -i, key))
        scored.sort(key=lambda t: (t[0], t[1], t[2]), reverse=True)
        return [t[3] for t in scored[:need]]

    def spill_victims(self) -> List[Hashable]:
        """Warm tenants to push to disk (oldest demotions first)."""
        if self.cfg.warm_capacity is None or self.store is None:
            return []
        excess = len(self.warm) - self.cfg.warm_capacity
        if excess <= 0:
            return []
        return list(self.warm)[:excess]

    # --------------------------------------------------------------- reset / views

    def reset(self) -> List[str]:
        """Zero every non-resident tenant (engine ``reset()``): all become
        cold-with-init. Returns the orphaned spill file names for the caller
        to delete (after the reset is journaled)."""
        orphans = [name for name in self.cold.values() if name]
        for key in list(self.warm):
            self.cold[key] = None
        self.warm.clear()
        for key in list(self.cold):
            self.cold[key] = None
        self._heat.clear()
        return orphans

    def snapshot_view(self) -> Dict[str, Any]:
        """The snapshot section for a partially-resident engine: the warm
        mirror rides in the snapshot by value, cold tenants by manifest
        pointer (the spill files are already durable containers)."""
        return {
            "warm": [[key, entry] for key, entry in self.warm.items()],
            "cold": [[key, name] for key, name in self.cold.items()],
            "pinned": list(self.pinned),
            "spill_directory": self.store.directory if self.store else None,
        }

    def restore_view(self, view: Dict[str, Any]) -> None:
        """Inherit a residency map (recovery, follower bootstrap, promotion)."""
        self.warm = {key: entry for key, entry in view.get("warm") or []}
        self.cold = {key: name for key, name in view.get("cold") or []}
        self.pinned = set(view.get("pinned") or [])
        self._heat.clear()
        spill_dir = view.get("spill_directory")
        if self.store is None and spill_dir:
            self.store = ColdStore(spill_dir, durable=self.cfg.durable)
