"""Tier plane — million-tenant residency-aware state tiering.

Turns the engine's stacked :class:`~metrics_tpu.engine.stream.KeyedState` into
a three-tier slab:

- **hot** — tenants stay in the stacked device arrays exactly as before; the
  fused dispatch path is untouched and tiering costs nothing while the working
  set fits ``TierConfig.hot_capacity``.
- **warm** — demoted tenants live as per-tenant host-RAM entries (numpy rows
  captured from the slab); readmission is one ``device_put``-backed slot
  install, well under a dispatch interval.
- **cold** — warm overflow spills to disk in the ``MTCKPT1`` container format
  and readmits through the same bit-identical restore path checkpoints use.

Demoted slots return to the slab's free-list (gated on a journaled retire
record so WAL replay can't alias rows), so HBM is bounded by the hot-set size
rather than the registered-tenant count. Eviction is guard-driven — idleness
is a token-bucket coldness clock, quarantined tenants evict first, pinned
tenants never — and runs on the dispatcher thread between micro-batches.
``submit()`` to a non-resident tenant promotes it transparently before the
micro-batch that needs the row. See ``docs/source/tiering.md``.
"""

from metrics_tpu.tier.coldstore import ColdStore
from metrics_tpu.tier.config import TierConfig
from metrics_tpu.tier.residency import (
    COLD,
    HOT,
    WARM,
    TierManager,
    capture_entry,
    peek_state,
    restore_entry,
)

__all__ = [
    "COLD",
    "ColdStore",
    "HOT",
    "TierConfig",
    "TierManager",
    "WARM",
    "capture_entry",
    "peek_state",
    "restore_entry",
]
