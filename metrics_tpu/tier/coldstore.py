"""Cold tier: per-tenant ``MTCKPT1`` spill files under one directory.

Each spilled tenant is one self-validating container blob (the PR 4 snapshot
format — CRC-guarded manifest + lossless codecs, so the round trip is
bit-identical), written with the ckpt store's atomic temp+fsync+rename. File
names are content-free (a digest of the key plus a uniquifier): the residency
manifest, not the directory listing, is the source of truth for which file
belongs to which tenant — a crashed spill leaves at worst an orphaned file,
never a torn or aliased one.
"""

from __future__ import annotations

import hashlib
import os
from typing import Any, Dict, Hashable, Optional, Tuple

from metrics_tpu.ckpt import format as ckpt_format
from metrics_tpu.ckpt.store import atomic_write


class ColdStore:
    """Spill-file manager for one engine's cold tier."""

    def __init__(self, directory: str, *, durable: bool = True) -> None:
        self.directory = os.path.abspath(directory)
        self.durable = durable
        self._seq = 0
        os.makedirs(self.directory, exist_ok=True)

    def path(self, name: str) -> str:
        return os.path.join(self.directory, name)

    @staticmethod
    def _digest(key: Hashable) -> str:
        return hashlib.sha1(repr(key).encode("utf-8")).hexdigest()[:16]

    def spill(self, key: Hashable, entry: Dict[str, Any]) -> Tuple[str, bytes]:
        """Serialize ``entry`` and write it atomically; returns (name, blob)."""
        blob = ckpt_format.dumps(entry, meta={"kind": "tier-cold"})
        digest = self._digest(key)
        while True:
            name = f"cold-{digest}-{self._seq:08x}.mtckpt"
            self._seq += 1
            if not os.path.exists(self.path(name)):
                break
        atomic_write(self.path(name), blob, durable=self.durable)
        return name, blob

    def read_bytes(self, name: str) -> bytes:
        with open(self.path(name), "rb") as f:
            return f.read()

    def load(self, name: str) -> Dict[str, Any]:
        return ckpt_format.loads(self.read_bytes(name)).tree

    def delete(self, name: Optional[str]) -> None:
        if not name:
            return
        try:
            os.unlink(self.path(name))
        except OSError:
            pass
