"""Durable snapshot store + request journal: crash-safe files on a local dir.

**SnapshotStore** — generational snapshots with atomic commit. A commit writes
to a dot-prefixed temp file in the same directory, ``fsync``\\ s it, then
``os.replace``\\ s onto the final name and fsyncs the directory: a reader (or a
restart) either sees the complete previous generation or the complete new one,
never a torn file under the real name. A torn *temp* file left by a crash is
invisible to the generation scan and swept on the next construction.
Retention keeps the newest ``retain`` generations per rank; recovery
(:meth:`latest_valid`) walks generations newest-first and skips anything whose
checksums (or caller-supplied validation) fail — a bit-flipped or truncated
snapshot costs one generation of staleness, never a corrupt restore.

Multihost: each rank owns its own file per generation
(``gen-<g>.rank<r>-of<w>.ckpt``) — persisting never needs a gather, and one
rank's corruption never blocks another's recovery.

**RequestJournal** — a WAL-style append log for the engine's
accepted-after-last-snapshot requests. Records are length+CRC framed; replay
stops at the first torn frame (the crash tail), so a record is either replayed
whole or not at all. Segments rotate at snapshot commits and segments fully
covered by a snapshot are deleted.
"""

from __future__ import annotations

import os
import struct
import threading
import zlib
from typing import Any, Callable, Dict, Iterator, List, Optional, Tuple

from metrics_tpu.ckpt import format as ckpt_format
from metrics_tpu.ckpt.format import CorruptSnapshotError, Snapshot
from metrics_tpu.obs import instrument as _obs
from metrics_tpu.utils.prints import rank_zero_warn

__all__ = ["JournalTailCursor", "RequestJournal", "SnapshotStore", "atomic_write"]

_TMP_PREFIX = ".tmp."


def _fsync_dir(path: str) -> None:
    """Make a rename durable: fsync the containing directory (POSIX). Best
    effort — platforms without dir-fd fsync (or exotic filesystems) skip."""
    try:
        fd = os.open(path, os.O_RDONLY)
    except OSError:
        return
    try:
        os.fsync(fd)
    except OSError:
        pass
    finally:
        os.close(fd)


def atomic_write(path: str, data: bytes, *, durable: bool = True) -> None:
    """Write ``data`` to ``path`` atomically: temp file + fsync + rename.

    ``durable=False`` skips the fsyncs (tests, throwaway dirs) but keeps the
    atomic rename — readers still never observe a torn file.
    """
    d, name = os.path.split(os.path.abspath(path))
    tmp = os.path.join(d, f"{_TMP_PREFIX}{name}.{os.getpid()}")
    with open(tmp, "wb") as f:
        f.write(data)
        f.flush()
        if durable:
            os.fsync(f.fileno())
    os.replace(tmp, path)
    if durable:
        _fsync_dir(d)


class SnapshotStore:
    """Generational snapshot files under one directory, atomic per commit."""

    def __init__(
        self,
        root: str,
        *,
        retain: int = 3,
        rank: int = 0,
        world: int = 1,
        durable: bool = True,
    ) -> None:
        if retain < 1:
            raise ValueError(f"retain must be >= 1, got {retain}")
        if not (0 <= rank < world):
            raise ValueError(f"rank must be in [0, world), got rank={rank} world={world}")
        self.root = os.path.abspath(root)
        self.retain = int(retain)
        self.rank = int(rank)
        self.world = int(world)
        self.durable = durable
        # generations skipped by the last latest_valid scan: (generation, reason)
        self.last_skipped: List[Tuple[int, str]] = []
        os.makedirs(self.root, exist_ok=True)
        self._sweep_tmp()

    # ------------------------------------------------------------------ layout

    def _suffix(self) -> str:
        return f".rank{self.rank:05d}-of{self.world:05d}.ckpt"

    def path(self, generation: int) -> str:
        return os.path.join(self.root, f"gen-{generation:012d}{self._suffix()}")

    def generations(self) -> List[int]:
        """This rank's committed generations, ascending."""
        out = []
        suffix = self._suffix()
        try:
            names = os.listdir(self.root)
        except OSError:
            return []
        for name in names:
            if name.startswith("gen-") and name.endswith(suffix):
                try:
                    out.append(int(name[len("gen-") : len("gen-") + 12]))
                except ValueError:
                    continue
        return sorted(out)

    def _sweep_tmp(self) -> None:
        # A crash mid-commit leaves only an invisible temp file. Sweep every
        # temp matching THIS store's rank suffix regardless of pid — the dead
        # writer's pid is gone, and each rank has a single owner, so any
        # same-rank temp here is an orphan (other ranks' temps are left alone).
        marker = f"{_TMP_PREFIX}gen-"
        suffix = self._suffix() + "."
        for name in os.listdir(self.root):
            if name.startswith(marker) and suffix in name:
                try:
                    os.remove(os.path.join(self.root, name))
                except OSError:
                    pass

    # ------------------------------------------------------------------ writes

    def next_generation(self) -> int:
        gens = self.generations()
        return (gens[-1] + 1) if gens else 0

    def commit(self, data: bytes, *, generation: Optional[int] = None) -> int:
        """Atomically persist one snapshot blob; returns its generation."""
        gen = self.next_generation() if generation is None else int(generation)
        atomic_write(self.path(gen), data, durable=self.durable)
        self.gc()
        return gen

    def gc(self) -> List[int]:
        """Delete this rank's oldest generations beyond ``retain``; returns them."""
        gens = self.generations()
        dropped = gens[: -self.retain] if len(gens) > self.retain else []
        for g in dropped:
            try:
                os.remove(self.path(g))
            except OSError:
                pass
        return dropped

    def delete(self, generation: int) -> None:
        try:
            os.remove(self.path(generation))
        except FileNotFoundError:
            pass

    # ------------------------------------------------------------------ reads

    def read(self, generation: int) -> bytes:
        with open(self.path(generation), "rb") as f:
            return f.read()

    def read_meta(self, generation: int) -> Dict[str, Any]:
        """One generation's manifest ``meta`` — header + manifest bytes only,
        no payload decode (CRC-checked; corrupt manifests raise)."""
        import struct

        from metrics_tpu.ckpt.format import MAGIC

        with open(self.path(generation), "rb") as f:
            head = f.read(len(MAGIC) + 12)
            if len(head) < len(MAGIC) + 12:
                raise CorruptSnapshotError("truncated header")
            (mlen,) = struct.unpack_from("<Q", head, len(MAGIC))
            data = head + f.read(mlen)
        return ckpt_format.read_manifest(data).get("meta", {})

    def latest_valid(
        self, *, validate: Optional[Callable[[Snapshot], None]] = None
    ) -> Optional[Tuple[int, Snapshot]]:
        """Newest generation that decodes, checksums, and validates clean.

        Walks newest-first; a corrupt/torn/unreadable generation (or one the
        caller's ``validate`` rejects) is recorded in :attr:`last_skipped` and
        the scan falls back to the previous one. ``None`` when nothing valid
        exists.
        """
        self.last_skipped = []
        found = None
        for gen in reversed(self.generations()):
            try:
                snap = ckpt_format.loads(self.read(gen))
                if validate is not None:
                    validate(snap)
                found = (gen, snap)
                break
            except (CorruptSnapshotError, OSError, ValueError, KeyError, TypeError) as exc:
                self.last_skipped.append((gen, f"{type(exc).__name__}: {exc}"))
        if self.last_skipped:
            # a silently skipped generation is silent corruption to an operator:
            # each skip costs one generation of recovery staleness, and a full
            # sweep of skips means NOTHING was recoverable — say so, loudly
            # (warn always; counter master-gated like every obs series)
            for gen, reason in self.last_skipped:
                _obs.record_ckpt_skipped(reason.split(":", 1)[0])
            rank_zero_warn(
                f"SnapshotStore({self.root!r}): skipped {len(self.last_skipped)} corrupt/invalid "
                f"generation(s) during recovery scan: "
                + "; ".join(f"gen {g}: {r}" for g, r in self.last_skipped[:3])
                + ("; ..." if len(self.last_skipped) > 3 else "")
                + (" — recovered from an older generation" if found is not None
                   else " — NO valid generation remained"),
                RuntimeWarning,
            )
        return found


# ---------------------------------------------------------------------- journal

_FRAME = struct.Struct("<II")  # payload nbytes, payload crc32


class RequestJournal:
    """Append-only, CRC-framed request log with segment rotation.

    Each record gets a monotone sequence number, persistent across reopen
    (segments are named by their first seq; a record's seq is first_seq +
    index). Appends go through an internal lock; :meth:`append_many` batches
    one ``write`` for a drained engine batch. ``sync`` policy per append is
    the caller's call — :meth:`flush` exposes flush-only and fsync levels.

    ``synced_seq`` is the highest seq known fsynced to stable storage —
    advanced wherever a real fsync lands (durable ``flush(fsync=True)``,
    rotation, close) and initialised to ``last_seq`` on reopen (whatever the
    scan found on disk has, by definition, survived). The engine's
    ``wal_fsync="commit"`` durability contract is exactly "a reopen never
    resumes numbering below ``synced_seq``".
    """

    def __init__(self, root: str, *, name: str = "wal", rank: int = 0, durable: bool = True) -> None:
        self.root = os.path.abspath(root)
        self.name = name
        self.rank = int(rank)
        self.durable = durable
        self.torn_records = 0  # frames dropped at a torn tail during scan/replay
        self._lock = threading.Lock()
        self._file: Optional[Any] = None
        os.makedirs(self.root, exist_ok=True)
        self.last_seq = -1
        segs = self._segments()
        if segs:
            # resume numbering after everything already on disk; a torn tail
            # (crash mid-append) is truncated away so records appended after
            # the reopen stay replayable behind an unbroken seq chain
            first, path = segs[-1]
            records, clean_len, torn = self._scan_segment(path)
            if torn:
                with open(path, "r+b") as f:
                    f.truncate(clean_len)
            self.last_seq = first + records - 1
        self.synced_seq = self.last_seq

    # ------------------------------------------------------------------ layout

    def _seg_path(self, first_seq: int) -> str:
        return os.path.join(self.root, f"{self.name}-{first_seq:012d}.rank{self.rank:05d}.log")

    def _segments(self) -> List[Tuple[int, str]]:
        """(first_seq, path) ascending."""
        out = []
        marker = f".rank{self.rank:05d}.log"
        prefix = f"{self.name}-"
        try:
            names = os.listdir(self.root)
        except OSError:
            return []
        for n in names:
            if n.startswith(prefix) and n.endswith(marker):
                try:
                    out.append((int(n[len(prefix) : len(prefix) + 12]), os.path.join(self.root, n)))
                except ValueError:
                    continue
        return sorted(out)

    # ------------------------------------------------------------------ writes

    def _ensure_file(self) -> Any:
        if self._file is None:
            self._file = open(self._seg_path(self.last_seq + 1), "ab")
        return self._file

    @staticmethod
    def _frame(payload: bytes) -> bytes:
        return _FRAME.pack(len(payload), zlib.crc32(payload) & 0xFFFFFFFF) + payload

    def append(self, payload: bytes) -> int:
        """Append one record; returns its sequence number."""
        return self.append_many([payload])[-1]

    def append_many(self, payloads: List[bytes]) -> List[int]:
        """Append a batch under one lock/one write; returns the seqs in order."""
        if not payloads:
            return []
        frames = b"".join(self._frame(p) for p in payloads)
        with self._lock:
            f = self._ensure_file()
            f.write(frames)
            seqs = list(range(self.last_seq + 1, self.last_seq + 1 + len(payloads)))
            self.last_seq = seqs[-1]
        return seqs

    def flush(self, *, fsync: bool = False) -> None:
        with self._lock:
            if self._file is not None:
                self._file.flush()
                if fsync and self.durable:
                    os.fsync(self._file.fileno())
                    self.synced_seq = self.last_seq

    def rotate(self, covered_seq: int) -> None:
        """Start a fresh segment; drop segments fully covered by ``covered_seq``
        (i.e. whose every record a snapshot at that seq already includes)."""
        with self._lock:
            if self._file is not None:
                self._file.flush()
                if self.durable:
                    os.fsync(self._file.fileno())
                    self.synced_seq = self.last_seq
                self._file.close()
                self._file = None
            segs = self._segments()
            for i, (first, path) in enumerate(segs):
                next_first = segs[i + 1][0] if i + 1 < len(segs) else self.last_seq + 1
                if next_first - 1 <= covered_seq:
                    try:
                        os.remove(path)
                    except OSError:
                        pass

    def close(self) -> None:
        with self._lock:
            if self._file is not None:
                self._file.flush()
                if self.durable:
                    os.fsync(self._file.fileno())
                    self.synced_seq = self.last_seq
                self._file.close()
                self._file = None

    # ------------------------------------------------------------------ reads

    @staticmethod
    def _scan_segment(path: str) -> Tuple[int, int, bool]:
        """(intact record count, clean byte length, torn?) for one segment."""
        try:
            with open(path, "rb") as f:
                data = f.read()
        except OSError:
            return 0, 0, False
        off = records = 0
        while off + _FRAME.size <= len(data):
            n, crc = _FRAME.unpack_from(data, off)
            payload = data[off + _FRAME.size : off + _FRAME.size + n]
            if len(payload) != n or (zlib.crc32(payload) & 0xFFFFFFFF) != crc:
                return records, off, True
            records += 1
            off += _FRAME.size + n
        return records, off, off != len(data)

    def _read_segment(self, path: str) -> Iterator[bytes]:
        """Yield whole records; stop at the first torn/corrupt frame (crash tail)."""
        try:
            with open(path, "rb") as f:
                data = f.read()
        except OSError:
            return
        off = 0
        while off + _FRAME.size <= len(data):
            n, crc = _FRAME.unpack_from(data, off)
            payload = data[off + _FRAME.size : off + _FRAME.size + n]
            if len(payload) != n or (zlib.crc32(payload) & 0xFFFFFFFF) != crc:
                self.torn_records += 1
                return
            yield payload
            off += _FRAME.size + n
        if off != len(data):
            self.torn_records += 1

    def read_from(self, after_seq: int = -1) -> Iterator[Tuple[int, bytes]]:
        """Cross-segment tail-follow read: ``(seq, record)`` for every intact
        record with seq > ``after_seq``, in order — safe under a live writer and
        concurrent :meth:`rotate`.

        Unlike :meth:`replay` (the exclusive-reopen recovery path), this NEVER
        truncates: a follower/shipper tailing the primary's journal must not
        destroy the primary's in-flight tail. An incomplete final frame (the
        writer mid-append) simply ends the iteration — call again with the last
        yielded seq to continue once the append lands. Segments wholly covered
        by ``after_seq`` are skipped without reading; a segment deleted by a
        concurrent ``rotate(covered_seq)`` ends the iteration (its records were
        snapshot-covered — the caller sees the seq discontinuity on the next
        call and falls back to a snapshot). Yielded seqs are strictly ascending
        and contiguous within one call.

        One frame-parse implementation serves both tail-follow APIs: this is a
        thin one-pass loop over :class:`JournalTailCursor`, with the
        within-one-call contiguity contract enforced here (the stateful cursor
        instead surfaces a rotation gap as a seq jump across polls).
        """
        cursor = self.tail_cursor(after_seq)
        last: Optional[int] = None
        batch = 1024  # stream in bounded slices — read_from must stay lazy
        while True:
            records = cursor.read(max_records=batch)
            for seq, payload in records:
                if last is not None and seq != last + 1:
                    return  # discontinuity (tear/rotation mid-walk): stop here
                yield seq, payload
                last = seq
            if len(records) < batch:
                return  # reached the tail: one pass, like the segment walk

    def tail_cursor(self, after_seq: int = -1) -> "JournalTailCursor":
        """A stateful incremental reader with :meth:`read_from`'s semantics —
        for pollers (the repl shipper) that tail the journal every few ms and
        must not re-read/re-CRC the whole active segment per poll."""
        return JournalTailCursor(self, after_seq)

    def replay(self, after_seq: int = -1) -> Iterator[Tuple[int, bytes]]:
        """Yield ``(seq, record)`` for every intact record with seq > ``after_seq``.

        A torn frame ends its segment, and everything after the tear is
        unordered relative to it — replay stops there: exactly the records
        whose append completed before the crash, in order.
        """
        self.flush()
        expected = None
        for first, path in self._segments():
            if expected is not None and first != expected:
                return  # seq gap (e.g. manually removed segment): stop
            before = self.torn_records
            seq = first
            for payload in self._read_segment(path):
                if seq > after_seq:
                    yield seq, payload
                seq += 1
            if self.torn_records != before:
                return  # torn tail: nothing after it is trustworthy
            expected = seq


class JournalTailCursor:
    """Stateful tail-follow over a live :class:`RequestJournal`.

    Same contract as :meth:`RequestJournal.read_from` (never truncates; an
    incomplete tail frame ends a poll; a rotation-induced gap surfaces as a
    seq jump), but the position — (segment, byte offset, next seq) — persists
    between polls, so each :meth:`read` costs only the NEW tail bytes. Polling
    ``read_from`` instead re-reads and re-CRCs the entire active segment every
    time: O(segment) per poll, quadratic over a segment's lifetime — exactly
    what a 20ms-tick shipper must not do.
    """

    def __init__(self, journal: RequestJournal, after_seq: int = -1) -> None:
        self._journal = journal
        self.seq = int(after_seq)  # last seq handed out
        self._path: Optional[str] = None
        self._first = 0  # first seq of the current segment
        self._next = 0  # seq of the next frame at _offset
        self._offset = 0  # byte offset of the next frame in the current segment

    def _locate(self) -> bool:
        """Point at the first segment not wholly covered by ``self.seq``."""
        segs = self._journal._segments()
        for i, (first, path) in enumerate(segs):
            nxt = segs[i + 1][0] if i + 1 < len(segs) else None
            if nxt is not None and nxt - 1 <= self.seq:
                continue
            self._path, self._first, self._next, self._offset = path, first, first, 0
            return True
        return False

    def read(self, max_records: Optional[int] = None) -> List[Tuple[int, bytes]]:
        """Every intact record appended since the last poll (bounded by
        ``max_records``), as ``(seq, payload)`` in order."""
        with self._journal._lock:
            if self._journal._file is not None:
                self._journal._file.flush()
        out: List[Tuple[int, bytes]] = []
        relocated = False
        while True:
            if self._path is None and not self._locate():
                return out
            try:
                with open(self._path, "rb") as f:
                    f.seek(self._offset)
                    data = f.read()
            except OSError:
                # segment rotated away under us: its records were snapshot-
                # covered — re-locate (once per poll, bounding the loop under
                # a racing rotator); the caller sees the resulting seq jump.
                # Records already buffered are flushed FIRST: one read() never
                # spans a discontinuity, so a caller checking contiguity at
                # records[0] (the shipper) cannot ship across a hidden gap.
                self._path = None
                if out or relocated:
                    return out
                relocated = True
                continue
            off = 0
            while off + _FRAME.size <= len(data):
                n, crc = _FRAME.unpack_from(data, off)
                payload = data[off + _FRAME.size : off + _FRAME.size + n]
                if len(payload) != n or (zlib.crc32(payload) & 0xFFFFFFFF) != crc:
                    break  # incomplete (live append) or torn: stop at it
                if self._next > self.seq:
                    out.append((self._next, payload))
                    self.seq = self._next
                self._next += 1
                off += _FRAME.size + n
                if max_records is not None and len(out) >= max_records:
                    self._offset += off
                    return out
            self._offset += off
            nxt = None
            for first, path in self._journal._segments():
                if first > self._first and (nxt is None or first < nxt[0]):
                    nxt = (first, path)
            if len(data) - off > 0:
                if nxt is None:
                    # leftover bytes in the NEWEST segment: a live writer's
                    # in-flight frame — stop exactly at the unparsed bytes and
                    # wait for the append to land
                    return out
                # mid-history tear: a newer segment exists, so this one is
                # immutable (rotation closed its file before the next segment
                # was created) and the bytes can never complete — waiting here
                # would wedge the cursor forever, silently stalling a shipper
                # rewound below the tear with no gap signal. Hop to the next
                # segment; the seq jump surfaces at the caller's records[0]
                # contiguity check (buffered records flush FIRST so one read
                # never spans the discontinuity).
                if out:
                    return out
                self._path, self._first, self._next, self._offset = nxt[1], nxt[0], nxt[0], 0
                continue
            if nxt is None:
                return out  # newest segment: wait for appends
            if out and nxt[0] != self._next:
                # rotation GC'd the segments in between: flush what we have so
                # the seq jump lands at the START of the next read, where the
                # caller's records[0] continuity check can see it
                return out
            self._path, self._first, self._next, self._offset = nxt[1], nxt[0], nxt[0], 0
