"""Durable snapshot store + request journal: crash-safe files on a local dir.

**SnapshotStore** — generational snapshots with atomic commit. A commit writes
to a dot-prefixed temp file in the same directory, ``fsync``\\ s it, then
``os.replace``\\ s onto the final name and fsyncs the directory: a reader (or a
restart) either sees the complete previous generation or the complete new one,
never a torn file under the real name. A torn *temp* file left by a crash is
invisible to the generation scan and swept on the next construction.
Retention keeps the newest ``retain`` generations per rank; recovery
(:meth:`latest_valid`) walks generations newest-first and skips anything whose
checksums (or caller-supplied validation) fail — a bit-flipped or truncated
snapshot costs one generation of staleness, never a corrupt restore.

Multihost: each rank owns its own file per generation
(``gen-<g>.rank<r>-of<w>.ckpt``) — persisting never needs a gather, and one
rank's corruption never blocks another's recovery.

**RequestJournal** — a WAL-style append log for the engine's
accepted-after-last-snapshot requests. Records are length+CRC framed; replay
stops at the first torn frame (the crash tail), so a record is either replayed
whole or not at all. Segments rotate at snapshot commits and segments fully
covered by a snapshot are deleted.
"""

from __future__ import annotations

import os
import struct
import threading
import zlib
from typing import Any, Callable, Dict, Iterator, List, Optional, Tuple

from metrics_tpu.ckpt import format as ckpt_format
from metrics_tpu.ckpt.format import CorruptSnapshotError, Snapshot

__all__ = ["RequestJournal", "SnapshotStore", "atomic_write"]

_TMP_PREFIX = ".tmp."


def _fsync_dir(path: str) -> None:
    """Make a rename durable: fsync the containing directory (POSIX). Best
    effort — platforms without dir-fd fsync (or exotic filesystems) skip."""
    try:
        fd = os.open(path, os.O_RDONLY)
    except OSError:
        return
    try:
        os.fsync(fd)
    except OSError:
        pass
    finally:
        os.close(fd)


def atomic_write(path: str, data: bytes, *, durable: bool = True) -> None:
    """Write ``data`` to ``path`` atomically: temp file + fsync + rename.

    ``durable=False`` skips the fsyncs (tests, throwaway dirs) but keeps the
    atomic rename — readers still never observe a torn file.
    """
    d, name = os.path.split(os.path.abspath(path))
    tmp = os.path.join(d, f"{_TMP_PREFIX}{name}.{os.getpid()}")
    with open(tmp, "wb") as f:
        f.write(data)
        f.flush()
        if durable:
            os.fsync(f.fileno())
    os.replace(tmp, path)
    if durable:
        _fsync_dir(d)


class SnapshotStore:
    """Generational snapshot files under one directory, atomic per commit."""

    def __init__(
        self,
        root: str,
        *,
        retain: int = 3,
        rank: int = 0,
        world: int = 1,
        durable: bool = True,
    ) -> None:
        if retain < 1:
            raise ValueError(f"retain must be >= 1, got {retain}")
        if not (0 <= rank < world):
            raise ValueError(f"rank must be in [0, world), got rank={rank} world={world}")
        self.root = os.path.abspath(root)
        self.retain = int(retain)
        self.rank = int(rank)
        self.world = int(world)
        self.durable = durable
        # generations skipped by the last latest_valid scan: (generation, reason)
        self.last_skipped: List[Tuple[int, str]] = []
        os.makedirs(self.root, exist_ok=True)
        self._sweep_tmp()

    # ------------------------------------------------------------------ layout

    def _suffix(self) -> str:
        return f".rank{self.rank:05d}-of{self.world:05d}.ckpt"

    def path(self, generation: int) -> str:
        return os.path.join(self.root, f"gen-{generation:012d}{self._suffix()}")

    def generations(self) -> List[int]:
        """This rank's committed generations, ascending."""
        out = []
        suffix = self._suffix()
        try:
            names = os.listdir(self.root)
        except OSError:
            return []
        for name in names:
            if name.startswith("gen-") and name.endswith(suffix):
                try:
                    out.append(int(name[len("gen-") : len("gen-") + 12]))
                except ValueError:
                    continue
        return sorted(out)

    def _sweep_tmp(self) -> None:
        # A crash mid-commit leaves only an invisible temp file. Sweep every
        # temp matching THIS store's rank suffix regardless of pid — the dead
        # writer's pid is gone, and each rank has a single owner, so any
        # same-rank temp here is an orphan (other ranks' temps are left alone).
        marker = f"{_TMP_PREFIX}gen-"
        suffix = self._suffix() + "."
        for name in os.listdir(self.root):
            if name.startswith(marker) and suffix in name:
                try:
                    os.remove(os.path.join(self.root, name))
                except OSError:
                    pass

    # ------------------------------------------------------------------ writes

    def next_generation(self) -> int:
        gens = self.generations()
        return (gens[-1] + 1) if gens else 0

    def commit(self, data: bytes, *, generation: Optional[int] = None) -> int:
        """Atomically persist one snapshot blob; returns its generation."""
        gen = self.next_generation() if generation is None else int(generation)
        atomic_write(self.path(gen), data, durable=self.durable)
        self.gc()
        return gen

    def gc(self) -> List[int]:
        """Delete this rank's oldest generations beyond ``retain``; returns them."""
        gens = self.generations()
        dropped = gens[: -self.retain] if len(gens) > self.retain else []
        for g in dropped:
            try:
                os.remove(self.path(g))
            except OSError:
                pass
        return dropped

    def delete(self, generation: int) -> None:
        try:
            os.remove(self.path(generation))
        except FileNotFoundError:
            pass

    # ------------------------------------------------------------------ reads

    def read(self, generation: int) -> bytes:
        with open(self.path(generation), "rb") as f:
            return f.read()

    def read_meta(self, generation: int) -> Dict[str, Any]:
        """One generation's manifest ``meta`` — header + manifest bytes only,
        no payload decode (CRC-checked; corrupt manifests raise)."""
        import struct

        from metrics_tpu.ckpt.format import MAGIC

        with open(self.path(generation), "rb") as f:
            head = f.read(len(MAGIC) + 12)
            if len(head) < len(MAGIC) + 12:
                raise CorruptSnapshotError("truncated header")
            (mlen,) = struct.unpack_from("<Q", head, len(MAGIC))
            data = head + f.read(mlen)
        return ckpt_format.read_manifest(data).get("meta", {})

    def latest_valid(
        self, *, validate: Optional[Callable[[Snapshot], None]] = None
    ) -> Optional[Tuple[int, Snapshot]]:
        """Newest generation that decodes, checksums, and validates clean.

        Walks newest-first; a corrupt/torn/unreadable generation (or one the
        caller's ``validate`` rejects) is recorded in :attr:`last_skipped` and
        the scan falls back to the previous one. ``None`` when nothing valid
        exists.
        """
        self.last_skipped = []
        for gen in reversed(self.generations()):
            try:
                snap = ckpt_format.loads(self.read(gen))
                if validate is not None:
                    validate(snap)
                return gen, snap
            except (CorruptSnapshotError, OSError, ValueError, KeyError, TypeError) as exc:
                self.last_skipped.append((gen, f"{type(exc).__name__}: {exc}"))
        return None


# ---------------------------------------------------------------------- journal

_FRAME = struct.Struct("<II")  # payload nbytes, payload crc32


class RequestJournal:
    """Append-only, CRC-framed request log with segment rotation.

    Each record gets a monotone sequence number, persistent across reopen
    (segments are named by their first seq; a record's seq is first_seq +
    index). Appends go through an internal lock; :meth:`append_many` batches
    one ``write`` for a drained engine batch. ``sync`` policy per append is
    the caller's call — :meth:`flush` exposes flush-only and fsync levels.
    """

    def __init__(self, root: str, *, name: str = "wal", rank: int = 0, durable: bool = True) -> None:
        self.root = os.path.abspath(root)
        self.name = name
        self.rank = int(rank)
        self.durable = durable
        self.torn_records = 0  # frames dropped at a torn tail during scan/replay
        self._lock = threading.Lock()
        self._file: Optional[Any] = None
        os.makedirs(self.root, exist_ok=True)
        self.last_seq = -1
        segs = self._segments()
        if segs:
            # resume numbering after everything already on disk; a torn tail
            # (crash mid-append) is truncated away so records appended after
            # the reopen stay replayable behind an unbroken seq chain
            first, path = segs[-1]
            records, clean_len, torn = self._scan_segment(path)
            if torn:
                with open(path, "r+b") as f:
                    f.truncate(clean_len)
            self.last_seq = first + records - 1

    # ------------------------------------------------------------------ layout

    def _seg_path(self, first_seq: int) -> str:
        return os.path.join(self.root, f"{self.name}-{first_seq:012d}.rank{self.rank:05d}.log")

    def _segments(self) -> List[Tuple[int, str]]:
        """(first_seq, path) ascending."""
        out = []
        marker = f".rank{self.rank:05d}.log"
        prefix = f"{self.name}-"
        try:
            names = os.listdir(self.root)
        except OSError:
            return []
        for n in names:
            if n.startswith(prefix) and n.endswith(marker):
                try:
                    out.append((int(n[len(prefix) : len(prefix) + 12]), os.path.join(self.root, n)))
                except ValueError:
                    continue
        return sorted(out)

    # ------------------------------------------------------------------ writes

    def _ensure_file(self) -> Any:
        if self._file is None:
            self._file = open(self._seg_path(self.last_seq + 1), "ab")
        return self._file

    @staticmethod
    def _frame(payload: bytes) -> bytes:
        return _FRAME.pack(len(payload), zlib.crc32(payload) & 0xFFFFFFFF) + payload

    def append(self, payload: bytes) -> int:
        """Append one record; returns its sequence number."""
        return self.append_many([payload])[-1]

    def append_many(self, payloads: List[bytes]) -> List[int]:
        """Append a batch under one lock/one write; returns the seqs in order."""
        if not payloads:
            return []
        frames = b"".join(self._frame(p) for p in payloads)
        with self._lock:
            f = self._ensure_file()
            f.write(frames)
            seqs = list(range(self.last_seq + 1, self.last_seq + 1 + len(payloads)))
            self.last_seq = seqs[-1]
        return seqs

    def flush(self, *, fsync: bool = False) -> None:
        with self._lock:
            if self._file is not None:
                self._file.flush()
                if fsync and self.durable:
                    os.fsync(self._file.fileno())

    def rotate(self, covered_seq: int) -> None:
        """Start a fresh segment; drop segments fully covered by ``covered_seq``
        (i.e. whose every record a snapshot at that seq already includes)."""
        with self._lock:
            if self._file is not None:
                self._file.flush()
                if self.durable:
                    os.fsync(self._file.fileno())
                self._file.close()
                self._file = None
            segs = self._segments()
            for i, (first, path) in enumerate(segs):
                next_first = segs[i + 1][0] if i + 1 < len(segs) else self.last_seq + 1
                if next_first - 1 <= covered_seq:
                    try:
                        os.remove(path)
                    except OSError:
                        pass

    def close(self) -> None:
        with self._lock:
            if self._file is not None:
                self._file.flush()
                if self.durable:
                    os.fsync(self._file.fileno())
                self._file.close()
                self._file = None

    # ------------------------------------------------------------------ reads

    @staticmethod
    def _scan_segment(path: str) -> Tuple[int, int, bool]:
        """(intact record count, clean byte length, torn?) for one segment."""
        try:
            with open(path, "rb") as f:
                data = f.read()
        except OSError:
            return 0, 0, False
        off = records = 0
        while off + _FRAME.size <= len(data):
            n, crc = _FRAME.unpack_from(data, off)
            payload = data[off + _FRAME.size : off + _FRAME.size + n]
            if len(payload) != n or (zlib.crc32(payload) & 0xFFFFFFFF) != crc:
                return records, off, True
            records += 1
            off += _FRAME.size + n
        return records, off, off != len(data)

    def _read_segment(self, path: str) -> Iterator[bytes]:
        """Yield whole records; stop at the first torn/corrupt frame (crash tail)."""
        try:
            with open(path, "rb") as f:
                data = f.read()
        except OSError:
            return
        off = 0
        while off + _FRAME.size <= len(data):
            n, crc = _FRAME.unpack_from(data, off)
            payload = data[off + _FRAME.size : off + _FRAME.size + n]
            if len(payload) != n or (zlib.crc32(payload) & 0xFFFFFFFF) != crc:
                self.torn_records += 1
                return
            yield payload
            off += _FRAME.size + n
        if off != len(data):
            self.torn_records += 1

    def replay(self, after_seq: int = -1) -> Iterator[Tuple[int, bytes]]:
        """Yield ``(seq, record)`` for every intact record with seq > ``after_seq``.

        A torn frame ends its segment, and everything after the tear is
        unordered relative to it — replay stops there: exactly the records
        whose append completed before the crash, in order.
        """
        self.flush()
        expected = None
        for first, path in self._segments():
            if expected is not None and first != expected:
                return  # seq gap (e.g. manually removed segment): stop
            before = self.torn_records
            seq = first
            for payload in self._read_segment(path):
                if seq > after_seq:
                    yield seq, payload
                seq += 1
            if self.torn_records != before:
                return  # torn tail: nothing after it is trustworthy
            expected = seq
