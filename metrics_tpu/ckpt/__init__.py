"""metrics_tpu.ckpt — durable state plane: crash-safe checkpoint, recovery, replay.

The persistence story for everything stateful in the library::

    from metrics_tpu import ckpt

    metric.persistent(True)          # optional: ckpt.save captures full state anyway
    metric.save("acc.ckpt")          # atomic, checksummed, lossless by default
    fresh = BinaryAccuracy()
    fresh.restore("acc.ckpt")        # strict schema validation; bit-identical compute()

    store = ckpt.SnapshotStore("/var/ckpt", retain=3)        # generational + GC
    writer = ckpt.AsyncCheckpointer(store, interval_s=30.0)  # background, bounded staleness
    writer.maybe_checkpoint(lambda: (state_tree, {"step": 7}))
    gen, snap = store.latest_valid()                         # skips corrupt generations

Layout: :mod:`~metrics_tpu.ckpt.format` (versioned manifest + per-leaf CRC32 +
comm-codec compression), :mod:`~metrics_tpu.ckpt.store` (atomic tmp+fsync+rename
commits, retention, per-rank sharded layout, WAL request journal),
:mod:`~metrics_tpu.ckpt.writer` (async background checkpointer),
:mod:`~metrics_tpu.ckpt.restore` (strict validation + migration hooks +
``save``/``restore``), :mod:`~metrics_tpu.ckpt.faults` (torn-write/bit-flip/
partial-manifest/disk-full injection for durability tests).

The engine integration (periodic per-tenant snapshots, WAL replay, restart
recovery) lives in :mod:`metrics_tpu.engine.runtime` behind
``StreamingEngine(checkpoint=CheckpointConfig(...))``. Guarantees and format
spec: ``docs/source/persistence.md``.
"""

from __future__ import annotations

from metrics_tpu.ckpt.format import (
    FORMAT_VERSION,
    CorruptSnapshotError,
    Snapshot,
    dumps,
    loads,
    read_manifest,
)
from metrics_tpu.ckpt.restore import (
    CKPT_SCHEMA_VERSION,
    CkptSchemaError,
    clear_migrations,
    migrate,
    register_migration,
    restore,
    save,
)
from metrics_tpu.ckpt.store import RequestJournal, SnapshotStore, atomic_write
from metrics_tpu.ckpt.writer import AsyncCheckpointer

__all__ = [
    "CKPT_SCHEMA_VERSION",
    "FORMAT_VERSION",
    "AsyncCheckpointer",
    "CkptSchemaError",
    "CorruptSnapshotError",
    "RequestJournal",
    "Snapshot",
    "SnapshotStore",
    "atomic_write",
    "clear_migrations",
    "dumps",
    "loads",
    "migrate",
    "read_manifest",
    "register_migration",
    "restore",
    "save",
]
