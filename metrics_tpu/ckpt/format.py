"""Snapshot wire format: versioned manifest + checksummed, codec'd host leaves.

One snapshot is one byte blob::

    magic "MTCKPT1\\n" | manifest_len (u64 LE) | manifest_crc32 (u32 LE)
    | manifest (UTF-8 JSON) | payload bytes (concatenated)

The manifest carries ``format_version`` (this container layout),
``schema_version`` (the *payload* schema — bumped by producers, bridged by
:mod:`metrics_tpu.ckpt.restore`'s migration registry), free-form ``meta``, a
JSON skeleton of the state pytree, and one entry per binary leaf recording the
original dtype/shape, the codec that produced the wire payloads, and a CRC32
per payload. Every integrity failure — bad magic, truncation, a manifest or
payload CRC mismatch, an undecodable manifest — raises
:class:`CorruptSnapshotError`, which is the signal the store's generation scan
keys on (a torn or bit-flipped snapshot is *skipped*, never half-restored).

Leaves ride the comm codec layer (:mod:`metrics_tpu.comm.codec`): the default
:class:`~metrics_tpu.comm.codec.CodecPolicy` keeps every leaf lossless
(bit-identical round trip, the acceptance bar); an opted-in lossy policy
quantizes exactly the leaves the comm plane would (dtype- and
reduction-aware — counts and ``_update_count`` stay exact, same bounds as
documented in ``docs/source/comm.md``).

Tree handling is structural, not pickled: dicts (string keys), lists, tuples,
``None``, JSON scalars and array-likes round-trip natively; anything else
(tenant-key maps with non-string keys, detection's host RLE tuples) falls back
to a checksummed pickle *object leaf* — still integrity-checked, just opaque.
"""

from __future__ import annotations

import json
import pickle
import struct
import zlib
from dataclasses import dataclass, field
from typing import Any, Dict, List, Optional, Tuple

import numpy as np

from metrics_tpu.comm.codec import CodecPolicy, EncodedLeaf, get_codec

MAGIC = b"MTCKPT1\n"
FORMAT_VERSION = 1
_HEADER = struct.Struct("<QI")  # manifest nbytes, manifest crc32

__all__ = [
    "FORMAT_VERSION",
    "MAGIC",
    "CorruptSnapshotError",
    "Snapshot",
    "dumps",
    "loads",
    "read_manifest",
]


class CorruptSnapshotError(Exception):
    """The blob is not a valid snapshot: bad magic, truncated, or a CRC failed."""


@dataclass
class Snapshot:
    """A decoded snapshot: the reconstructed tree plus its manifest identity."""

    tree: Any
    meta: Dict[str, Any]
    schema_version: int
    format_version: int
    manifest: Dict[str, Any] = field(repr=False, default_factory=dict)


def _crc(data: bytes) -> int:
    return zlib.crc32(data) & 0xFFFFFFFF


def _dtype_name(dtype: Any) -> str:
    return np.dtype(dtype).name


def _dtype_from_name(name: str) -> np.dtype:
    try:
        return np.dtype(name)
    except TypeError:
        # extension dtypes (bfloat16 et al.) register under ml_dtypes, which
        # jax always ships with
        import ml_dtypes

        return np.dtype(getattr(ml_dtypes, name))


def _is_array(x: Any) -> bool:
    # duck-typed so jax.Array, np.ndarray and np.generic all qualify without
    # importing jax here (the format must stay loadable host-side)
    return hasattr(x, "dtype") and hasattr(x, "shape") and hasattr(x, "__array__")


class _Writer:
    """Accumulates payload bytes + leaf records while walking the tree."""

    def __init__(self, policy: CodecPolicy, reductions: Dict[str, Any]) -> None:
        self.policy = policy
        self.reductions = reductions
        self.leaves: List[Dict[str, Any]] = []
        self.chunks: List[bytes] = []
        self.offset = 0

    def _add_payload(self, data: bytes) -> Dict[str, Any]:
        rec = {"off": self.offset, "n": len(data), "crc": _crc(data)}
        self.chunks.append(data)
        self.offset += len(data)
        return rec

    def add_array(self, x: Any, name: str) -> int:
        arr = np.asarray(x)
        codec_name = self.policy.choose(
            name, self.reductions.get(name), arr.dtype, int(arr.nbytes)
        )
        enc = get_codec(codec_name).encode(arr)
        payloads = []
        for p in enc.payloads:
            p = np.ascontiguousarray(p)
            rec = self._add_payload(p.tobytes())
            rec["dtype"] = _dtype_name(p.dtype)
            rec["shape"] = list(p.shape)
            payloads.append(rec)
        self.leaves.append(
            {
                "kind": "array",
                "dtype": _dtype_name(arr.dtype),
                "shape": list(arr.shape),
                "codec": enc.codec,
                "payloads": payloads,
            }
        )
        return len(self.leaves) - 1

    def add_object(self, x: Any) -> int:
        rec = self._add_payload(pickle.dumps(x, protocol=pickle.HIGHEST_PROTOCOL))
        self.leaves.append({"kind": "object", "payloads": [rec]})
        return len(self.leaves) - 1


def _encode_node(x: Any, name: str, w: _Writer) -> Any:
    """Tree node -> JSON skeleton; binary/opaque leaves go through the writer.

    ``name`` is the nearest enclosing dict key — the identity the codec policy
    keys its exactness rules on (``_update_count`` and friends).
    """
    if x is None:
        return {"t": "n"}
    # arrays before scalars: np.float64 subclasses float (and np.generic
    # scalars carry a dtype worth preserving exactly)
    if _is_array(x):
        return {"t": "a", "i": w.add_array(x, name)}
    if isinstance(x, bool):  # before int: bool is an int subclass
        return {"t": "p", "v": x}
    if isinstance(x, (int, float, str)):
        return {"t": "p", "v": x}
    if isinstance(x, dict):
        if all(isinstance(k, str) for k in x):
            return {"t": "d", "k": list(x.keys()), "v": [_encode_node(v, k, w) for k, v in x.items()]}
        return {"t": "o", "i": w.add_object(x)}  # non-string keys: opaque
    if isinstance(x, (list, tuple)):
        return {
            "t": "l" if isinstance(x, list) else "t",
            "v": [_encode_node(v, name, w) for v in x],
        }
    return {"t": "o", "i": w.add_object(x)}


def _decode_node(node: Dict[str, Any], leaves: List[Any]) -> Any:
    t = node["t"]
    if t == "n":
        return None
    if t == "p":
        return node["v"]
    if t == "a" or t == "o":
        return leaves[node["i"]]
    if t == "d":
        return dict(zip(node["k"], (_decode_node(v, leaves) for v in node["v"])))
    if t == "l":
        return [_decode_node(v, leaves) for v in node["v"]]
    if t == "t":
        return tuple(_decode_node(v, leaves) for v in node["v"])
    raise CorruptSnapshotError(f"unknown skeleton node type {t!r}")


def dumps(
    tree: Any,
    *,
    policy: Optional[CodecPolicy] = None,
    reductions: Optional[Dict[str, Any]] = None,
    schema_version: int = 1,
    meta: Optional[Dict[str, Any]] = None,
) -> bytes:
    """Serialize a state pytree into one self-validating snapshot blob.

    ``policy`` defaults to the all-lossless :class:`CodecPolicy` — the round
    trip is then bit-identical. ``reductions`` maps state *names* (the nearest
    dict key of a leaf) to their ``dist_reduce_fx`` so a lossy policy can keep
    reducible/count states exact, exactly as the comm plane does.
    """
    w = _Writer(policy if policy is not None else CodecPolicy(), reductions or {})
    skeleton = _encode_node(tree, "", w)
    manifest = {
        "format_version": FORMAT_VERSION,
        "schema_version": int(schema_version),
        "meta": meta or {},
        "skeleton": skeleton,
        "leaves": w.leaves,
        "payload_nbytes": w.offset,
    }
    mbytes = json.dumps(manifest, separators=(",", ":")).encode("utf-8")
    return b"".join([MAGIC, _HEADER.pack(len(mbytes), _crc(mbytes)), mbytes, *w.chunks])


def _split(data: bytes) -> Tuple[Dict[str, Any], bytes]:
    if len(data) < len(MAGIC) + _HEADER.size:
        raise CorruptSnapshotError(f"truncated header ({len(data)} bytes)")
    if data[: len(MAGIC)] != MAGIC:
        raise CorruptSnapshotError("bad magic — not a metrics_tpu snapshot")
    mlen, mcrc = _HEADER.unpack_from(data, len(MAGIC))
    start = len(MAGIC) + _HEADER.size
    mbytes = data[start : start + mlen]
    if len(mbytes) != mlen:
        raise CorruptSnapshotError("truncated manifest")
    if _crc(mbytes) != mcrc:
        raise CorruptSnapshotError("manifest CRC mismatch")
    try:
        manifest = json.loads(mbytes.decode("utf-8"))
    except (UnicodeDecodeError, json.JSONDecodeError) as exc:
        raise CorruptSnapshotError(f"undecodable manifest: {exc}") from exc
    if manifest.get("format_version") != FORMAT_VERSION:
        raise CorruptSnapshotError(
            f"unsupported format_version {manifest.get('format_version')!r} (expected {FORMAT_VERSION})"
        )
    return manifest, data[start + mlen :]


def read_manifest(data: bytes) -> Dict[str, Any]:
    """Validate the header/manifest CRC and return the manifest — no payload work."""
    manifest, _ = _split(data)
    return manifest


def _decode_leaf(entry: Dict[str, Any], payload: bytes) -> Any:
    raw: List[bytes] = []
    for rec in entry["payloads"]:
        chunk = payload[rec["off"] : rec["off"] + rec["n"]]
        if len(chunk) != rec["n"]:
            raise CorruptSnapshotError("truncated payload (torn write)")
        if _crc(chunk) != rec["crc"]:
            raise CorruptSnapshotError("payload CRC mismatch (corrupt leaf)")
        raw.append(chunk)
    if entry["kind"] == "object":
        try:
            return pickle.loads(raw[0])
        except Exception as exc:  # noqa: BLE001 — CRC passed but unpicklable: still corrupt
            raise CorruptSnapshotError(f"undecodable object leaf: {exc}") from exc
    arrays = tuple(
        np.frombuffer(chunk, dtype=_dtype_from_name(rec["dtype"])).reshape(rec["shape"])
        for rec, chunk in zip(entry["payloads"], raw)
    )
    enc = EncodedLeaf(
        entry["codec"], arrays, tuple(entry["shape"]), _dtype_from_name(entry["dtype"])
    )
    return get_codec(entry["codec"]).decode(enc)


def verify(data: bytes) -> Dict[str, Any]:
    """Integrity-check one snapshot blob — manifest CRC + every payload chunk
    length/CRC — WITHOUT decoding any leaf (no numpy reconstruction, no codec
    decode, no unpickling). Returns the validated manifest.

    The repl shipper's pre-flight: it ships the raw bytes, so it needs the
    corruption-skip guarantee and ``meta["seq"]``, not the decoded tree —
    :func:`loads` would rebuild the whole state every checkpoint interval
    just to throw it away. Raises :class:`CorruptSnapshotError` exactly when
    :func:`loads` would for integrity failures (a CRC-clean but undecodable
    leaf — a writer bug, not corruption — is only caught by a full decode).
    """
    manifest, payload = _split(data)
    if len(payload) < int(manifest.get("payload_nbytes", 0)):
        raise CorruptSnapshotError(
            f"truncated payload region: {len(payload)} < {manifest['payload_nbytes']} bytes"
        )
    for entry in manifest["leaves"]:
        for rec in entry["payloads"]:
            chunk = payload[rec["off"] : rec["off"] + rec["n"]]
            if len(chunk) != rec["n"]:
                raise CorruptSnapshotError("truncated payload (torn write)")
            if _crc(chunk) != rec["crc"]:
                raise CorruptSnapshotError("payload CRC mismatch (corrupt leaf)")
    return manifest


def loads(data: bytes) -> Snapshot:
    """Decode + integrity-check one snapshot blob back into a host-numpy tree."""
    manifest, payload = _split(data)
    if len(payload) < int(manifest.get("payload_nbytes", 0)):
        raise CorruptSnapshotError(
            f"truncated payload region: {len(payload)} < {manifest['payload_nbytes']} bytes"
        )
    leaves = [_decode_leaf(entry, payload) for entry in manifest["leaves"]]
    tree = _decode_node(manifest["skeleton"], leaves)
    return Snapshot(
        tree=tree,
        meta=manifest.get("meta", {}),
        schema_version=int(manifest.get("schema_version", 1)),
        format_version=int(manifest["format_version"]),
        manifest=manifest,
    )
