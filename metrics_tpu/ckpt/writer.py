"""Background async checkpointer: bounded-staleness snapshots off the hot path.

The owner (the engine dispatcher between micro-batches, or any host loop)
calls :meth:`AsyncCheckpointer.maybe_checkpoint` with a *snapshot function* —
a callable producing a consistent host-side ``(tree, meta)`` view of the state
it wants persisted. The checkpointer decides whether a snapshot is due
(``interval_s`` elapsed) and whether the background writer can take it (one
in-flight write at a time); if so it runs the snapshot function *on the
caller's thread* (that is what makes the view consistent — the owner picks the
quiescent point) and hands the host tree to the writer thread, which
serializes (:mod:`metrics_tpu.ckpt.format`), commits
(:class:`~metrics_tpu.ckpt.store.SnapshotStore`), and records obs series
(bytes, latency, generation, failures) under the configured ``site``.

Staleness is bounded by ``interval_s`` + one serialize/commit, and an overdue
snapshot whose predecessor is still writing is *skipped*, not queued — the
store never falls progressively behind a fast producer. A failed write is
counted and remembered (:attr:`last_error`), never raised into the owner's
loop: checkpointing degrades, serving does not.
"""

from __future__ import annotations

import queue
import threading
import time
from typing import Any, Callable, Dict, Optional, Tuple

from metrics_tpu.ckpt import format as ckpt_format
from metrics_tpu.ckpt.store import SnapshotStore
from metrics_tpu.comm.codec import CodecPolicy
from metrics_tpu.obs import instrument as _obs

__all__ = ["AsyncCheckpointer"]

SnapshotFn = Callable[[], Tuple[Any, Optional[Dict[str, Any]]]]
CommitHook = Callable[[int, Any, Optional[Dict[str, Any]]], None]


class AsyncCheckpointer:
    """One background writer thread over a :class:`SnapshotStore`."""

    def __init__(
        self,
        store: SnapshotStore,
        *,
        interval_s: float = 30.0,
        site: str = "ckpt",
        policy: Optional[CodecPolicy] = None,
        reductions: Optional[Dict[str, Any]] = None,
        schema_version: int = 1,
        on_commit: Optional[CommitHook] = None,
        on_error: Optional[Callable[[BaseException], None]] = None,
    ) -> None:
        self.store = store
        self.interval_s = float(interval_s)
        self.site = site
        self.policy = policy
        self.reductions = reductions
        self.schema_version = int(schema_version)
        self.on_commit = on_commit
        self.on_error = on_error

        self.writes = 0
        self.skipped = 0  # due snapshots dropped because the writer was busy
        self.failures = 0
        # failure streak since the last successful commit — what a circuit
        # breaker (metrics_tpu.guard) or an operator dashboard keys off
        self.consecutive_failures = 0
        self.last_generation: Optional[int] = None
        self.last_error: Optional[BaseException] = None

        self._last_attempt = time.monotonic()
        self._queue: "queue.Queue[Optional[Tuple[Any, Optional[Dict[str, Any]]]]]" = queue.Queue(
            maxsize=1
        )
        self._idle = threading.Event()
        self._idle.set()
        # serializes claiming the idle slot: maybe_checkpoint (any producer
        # thread) and checkpoint_sync (caller thread) must never both decide
        # the writer is free and commit concurrently
        self._claim_lock = threading.Lock()
        self._closed = False
        self._thread = threading.Thread(
            target=self._run, name=f"metrics-tpu-ckpt-{site}", daemon=True
        )
        self._thread.start()

    # ------------------------------------------------------------------ producer side

    def due(self) -> bool:
        return time.monotonic() - self._last_attempt >= self.interval_s

    def maybe_checkpoint(self, snapshot_fn: SnapshotFn, *, force: bool = False) -> bool:
        """Take + enqueue a snapshot if one is due and the writer is free.

        Returns True when a snapshot was handed to the writer. Never raises
        from the write path; never blocks beyond the snapshot function itself.
        """
        if self._closed:
            return False
        if not force and not self.due():
            return False
        while True:
            with self._claim_lock:
                if self._idle.is_set():
                    self._idle.clear()  # claimed
                    break
            if not force:
                # busy: SKIP, and do NOT reset the timer — the next call
                # retries as soon as the writer frees up, keeping worst-case
                # staleness at interval_s + one write, not 2x interval_s
                self.skipped += 1
                return False
            # a forced snapshot waits for the in-flight write instead of
            # silently racing it for the claim
            self._idle.wait()
        self._last_attempt = time.monotonic()
        try:
            tree, meta = snapshot_fn()
        except BaseException:
            self._idle.set()  # never strand the claim on a snapshot failure
            raise
        self._queue.put((tree, meta))
        return True

    def checkpoint_sync(self, snapshot_fn: SnapshotFn) -> Optional[int]:
        """Snapshot + write on the calling thread (quiesce points, close paths).

        Claims the writer's idle slot first, so a concurrent background write
        can never commit alongside it (two commits racing ``next_generation``
        could pick the same number). Returns the committed generation, or
        ``None`` on failure (recorded, not raised — same contract as the
        async path).
        """
        while True:
            self._idle.wait()
            with self._claim_lock:
                if self._idle.is_set():
                    self._idle.clear()
                    break
        try:
            self._last_attempt = time.monotonic()
            tree, meta = snapshot_fn()
            return self._write(tree, meta)
        finally:
            self._idle.set()

    def quiesce(self, timeout: Optional[float] = None) -> bool:
        """Block until no write is in flight. True if idle was reached."""
        return self._idle.wait(timeout)

    def close(self) -> None:
        if self._closed:
            return
        self._closed = True
        self._queue.put(None)
        self._thread.join(timeout=30.0)

    # ------------------------------------------------------------------ writer side

    def _run(self) -> None:
        while True:
            item = self._queue.get()
            if item is None:
                self._idle.set()
                return
            tree, meta = item
            try:
                self._write(tree, meta)
            finally:
                self._idle.set()

    def _write(self, tree: Any, meta: Optional[Dict[str, Any]]) -> Optional[int]:
        t0 = time.perf_counter()
        try:
            with _obs.ckpt_span("ckpt.write", site=self.site):
                data = ckpt_format.dumps(
                    tree,
                    policy=self.policy,
                    reductions=self.reductions,
                    schema_version=self.schema_version,
                    meta=meta,
                )
                gen = self.store.commit(data)
        except BaseException as exc:  # noqa: BLE001 — a failed write must not kill the owner
            self.failures += 1
            self.consecutive_failures += 1
            self.last_error = exc
            _obs.record_ckpt_failure(self.site, "write")
            if self.on_error is not None:
                try:
                    self.on_error(exc)
                except Exception:  # noqa: BLE001 — best-effort callback
                    pass
            return None
        self.writes += 1
        self.consecutive_failures = 0
        self.last_generation = gen
        _obs.record_ckpt_io(self.site, "write", len(data), time.perf_counter() - t0, generation=gen)
        if self.on_commit is not None:
            try:
                self.on_commit(gen, tree, meta)
            except Exception as exc:  # noqa: BLE001 — best-effort callback
                self.last_error = exc
        return gen
