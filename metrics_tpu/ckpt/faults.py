"""Fault-injection doubles for durability testing: torn writes, bit flips,
partial manifests, disk-full.

Used by ``tests/ckpt/`` and the ``ckpt`` surface of ``tools/fuzz_soak.py`` to
prove the recovery invariant: no matter where a write is interrupted or what a
single corruption hits, :meth:`SnapshotStore.latest_valid` recovers the newest
*intact* generation and never a corrupt one.

The corruptors operate on committed snapshot files in place — exactly the
artifacts a real crash or silent media error would leave:

- :func:`tear` — truncate the file at a byte offset (a write that died
  mid-flight, after the rename was replayed from the journal of a simpler
  non-atomic writer, or a partially synced page);
- :func:`flip_bit` — invert one bit (silent media/DMA corruption);
- :func:`strip_payloads` — keep the header + manifest, drop payload bytes (a
  "partial manifest" file: metadata intact, data gone);
- :class:`DiskFull` — patches the store's atomic writer so the data write
  raises ``ENOSPC`` after ``allow`` successful commits, verifying a failed
  commit never leaves a visible torn generation behind.
"""

from __future__ import annotations

import errno
import os
import struct
from typing import Optional

from metrics_tpu.ckpt import store as _store
from metrics_tpu.ckpt.format import MAGIC

__all__ = ["DiskFull", "flip_bit", "strip_payloads", "tear"]


def tear(path: str, keep_bytes: Optional[int] = None, frac: float = 0.5) -> int:
    """Truncate ``path`` to ``keep_bytes`` (default: ``frac`` of its size).

    Returns the resulting size. ``keep_bytes=0`` leaves an empty file — the
    most extreme torn write.
    """
    size = os.path.getsize(path)
    keep = int(size * frac) if keep_bytes is None else int(keep_bytes)
    keep = max(0, min(keep, size))
    with open(path, "r+b") as f:
        f.truncate(keep)
    return keep


def flip_bit(path: str, offset: Optional[int] = None, bit: int = 0) -> int:
    """Invert one bit of ``path`` (default: middle byte). Returns the offset."""
    size = os.path.getsize(path)
    if size == 0:
        return 0
    off = (size // 2) if offset is None else int(offset) % size
    with open(path, "r+b") as f:
        f.seek(off)
        byte = f.read(1)
        f.seek(off)
        f.write(bytes([byte[0] ^ (1 << (bit % 8))]))
    return off


def strip_payloads(path: str) -> int:
    """Truncate ``path`` right after its manifest: header + metadata survive,
    every payload byte is gone. Returns the resulting size."""
    with open(path, "rb") as f:
        head = f.read(len(MAGIC) + 12)
    if len(head) < len(MAGIC) + 12 or head[: len(MAGIC)] != MAGIC:
        raise ValueError(f"{path} is not a snapshot file")
    (mlen,) = struct.unpack_from("<Q", head, len(MAGIC))
    keep = len(MAGIC) + 12 + mlen
    with open(path, "r+b") as f:
        f.truncate(keep)
    return keep


class DiskFull:
    """Context manager: the store's atomic write raises ``ENOSPC`` after
    ``allow`` successful commits. The refused write must leave no visible
    generation (the temp file never reaches its final name)."""

    def __init__(self, allow: int = 0) -> None:
        self.allow = int(allow)
        self.refused = 0
        self._orig = None

    def __enter__(self) -> "DiskFull":
        self._orig = _store.atomic_write

        def failing(path: str, data: bytes, *, durable: bool = True) -> None:
            if self.allow > 0:
                self.allow -= 1
                return self._orig(path, data, durable=durable)
            self.refused += 1
            raise OSError(errno.ENOSPC, "No space left on device (injected)")

        _store.atomic_write = failing
        return self

    def __exit__(self, *exc_info: object) -> None:
        _store.atomic_write = self._orig
