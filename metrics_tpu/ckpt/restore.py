"""Restore path: strict schema validation, migrations, and ``save``/``restore``.

``save(obj, path)`` captures a metric's (or collection's) FULL registered
state — persistence flags are forced on for the duration, so the capture rides
the exact ``state_dict`` machinery the library already trusts (wrapper extras,
nested child metrics, compute-group leader refresh all included) without
permanently flipping anyone's flags. Update counts are carried alongside so a
restored metric keeps its running-mean and warning semantics.

``restore(obj, path)`` is strict by construction, three layers deep:

1. **integrity** — the blob's magic/CRCs (a corrupt file raises
   :class:`~metrics_tpu.ckpt.format.CorruptSnapshotError`, it is never
   partially applied);
2. **schema** — the snapshot's ``schema_version`` is bridged to the current
   one through the migration-hook registry (:func:`register_migration`); a
   version gap with no registered bridge refuses loudly;
3. **structure** — every fixed array state is checked against the live
   instance's registered spec (unknown state names, missing states, dtype and
   shape mismatches each raise :class:`CkptSchemaError` *before* any attribute
   is touched), then the payload rides the existing strict
   ``load_state_dict`` (missing persistent keys and unconsumed stray keys
   raise there, as everywhere else in the library).

After a collection restore the compute-group aliasing is re-established:
group members are re-pointed at their leader's freshly restored arrays, and
every member's compute cache is dropped — a restore must never leave a member
serving pre-restore state.
"""

from __future__ import annotations

import time
from contextlib import contextmanager
from typing import Any, Callable, Dict, Iterator, Optional, Tuple

import jax.numpy as jnp
import numpy as np

from metrics_tpu.ckpt import format as ckpt_format
from metrics_tpu.ckpt.format import Snapshot
from metrics_tpu.ckpt.store import atomic_write
from metrics_tpu.comm.codec import CodecPolicy
from metrics_tpu.obs import instrument as _obs

__all__ = [
    "CKPT_SCHEMA_VERSION",
    "CkptSchemaError",
    "clear_migrations",
    "migrate",
    "register_migration",
    "restore",
    "save",
]

# The CURRENT payload schema for metric/collection snapshots. Bump when the
# save-tree layout changes, and register a migration bridging the old version.
CKPT_SCHEMA_VERSION = 1


class CkptSchemaError(Exception):
    """The snapshot does not fit the live instance (or its schema version)."""


# ---------------------------------------------------------------------- migrations

_MIGRATIONS: Dict[int, Callable[[Any, Dict[str, Any]], Any]] = {}


def register_migration(from_version: int, fn: Callable[[Any, Dict[str, Any]], Any]) -> None:
    """Register ``fn(tree, meta) -> tree`` bridging ``from_version`` → ``from_version + 1``.

    Chained automatically: restoring a v1 snapshot at schema v3 runs the 1→2
    then the 2→3 hook. Registering a version twice raises — two subsystems
    disagreeing about a bridge is a bug, not a merge.
    """
    v = int(from_version)
    if v in _MIGRATIONS:
        raise ValueError(f"migration from schema version {v} already registered")
    _MIGRATIONS[v] = fn


def clear_migrations() -> None:
    """Drop all registered hooks (test isolation)."""
    _MIGRATIONS.clear()


def migrate(snapshot: Snapshot, target_version: int) -> Any:
    """Bridge ``snapshot.tree`` up to ``target_version`` through the registry."""
    tree, version = snapshot.tree, snapshot.schema_version
    if version > target_version:
        raise CkptSchemaError(
            f"snapshot schema v{version} is NEWER than this library's v{target_version} — "
            "refusing to guess at a downgrade"
        )
    while version < target_version:
        fn = _MIGRATIONS.get(version)
        if fn is None:
            raise CkptSchemaError(
                f"snapshot schema v{version} has no registered migration to v{version + 1} "
                f"(target v{target_version}); register one with ckpt.register_migration"
            )
        tree = fn(tree, snapshot.meta)
        version += 1
    return tree


# ---------------------------------------------------------------------- walking

def _is_collection(obj: Any) -> bool:
    from metrics_tpu.collections import MetricCollection

    return isinstance(obj, MetricCollection)


def _walk_metrics(obj: Any, prefix: str = "") -> Iterator[Tuple[str, Any]]:
    """(state_dict prefix, metric) for obj + every nested child, depth-first —
    the same recursion ``state_dict``/``load_state_dict`` route through."""
    from metrics_tpu.metric import Metric

    if _is_collection(obj):
        for name, m in obj._modules.items():
            yield from _walk_metrics(m, f"{prefix}{name}.")
        return
    if isinstance(obj, Metric):
        yield prefix, obj
        for name, child in obj._child_metrics():
            yield from _walk_metrics(child, f"{prefix}{name}.")
        return
    # duck-typed trackers (MetricTracker is neither Metric nor collection):
    # walk the tracked history under the prefixes its own state_dict uses
    tracked = getattr(obj, "_metrics", None)
    if isinstance(tracked, (list, tuple)):
        for i, m in enumerate(tracked):
            yield from _walk_metrics(m, f"{prefix}_metrics.{i}.")


@contextmanager
def _all_persistent(obj: Any) -> Iterator[None]:
    """Force every state persistent for the block, restoring flags after —
    ``save``/``restore`` capture full state through the parity ``state_dict``
    machinery without changing what the user's own checkpoints contain."""
    saved = [(m, dict(m._persistent)) for _, m in _walk_metrics(obj)]
    for m, _ in saved:
        for key in m._persistent:
            m._persistent[key] = True
    try:
        yield
    finally:
        for m, flags in saved:
            m._persistent.update(flags)


# ---------------------------------------------------------------------- save

def _build_tree(obj: Any) -> Tuple[Dict[str, Any], Dict[str, Any]]:
    """(snapshot tree, name→reduction map for the codec policy)."""
    with _all_persistent(obj):
        sd = obj.state_dict()
    reductions: Dict[str, Any] = {}
    counts: Dict[str, int] = {}
    for prefix, m in _walk_metrics(obj):
        counts[prefix] = int(m._update_count)
        for name, red in m._reductions.items():
            if isinstance(red, str):
                reductions.setdefault(name, red)
    tree = {
        "kind": "collection" if _is_collection(obj) else "metric",
        "class": type(obj).__name__,
        "state_dict": sd,
        "update_counts": counts,
    }
    return tree, reductions


def save(
    obj: Any,
    path: str,
    *,
    policy: Optional[CodecPolicy] = None,
    meta: Optional[Dict[str, Any]] = None,
    durable: bool = True,
) -> None:
    """Write one atomic, checksummed snapshot of ``obj``'s full state to ``path``.

    ``policy=None`` (the default) is lossless — ``restore`` then reproduces
    ``compute()`` bit-identically. A lossy :class:`CodecPolicy` is opt-in and
    applies the comm plane's dtype/reduction exactness rules (counts stay
    exact; error bounds as documented for the codecs in ``docs/source/comm.md``).
    """
    t0 = time.perf_counter()
    with _obs.ckpt_span("ckpt.save", site="metric", cls=type(obj).__name__):
        tree, reductions = _build_tree(obj)
        from metrics_tpu import __version__

        full_meta = {"library_version": __version__, **(meta or {})}
        data = ckpt_format.dumps(
            tree,
            policy=policy,
            reductions=reductions,
            schema_version=CKPT_SCHEMA_VERSION,
            meta=full_meta,
        )
        atomic_write(path, data, durable=durable)
    _obs.record_ckpt_io("metric", "write", len(data), time.perf_counter() - t0)


# ---------------------------------------------------------------------- validate + apply

def _validate_tree(obj: Any, tree: Any, *, strict_shapes: bool = True) -> None:
    """Structural checks of the snapshot against the live instance — all
    failures raise BEFORE any attribute is touched.

    Key-set enforcement (missing persistent keys, unconsumed strays) is NOT
    duplicated here: that rides the existing strict ``load_state_dict``
    machinery, which also owns dynamic-structure rebuilds (MetricTracker's
    per-increment history). What load can't check is *parameters*: a key that
    exists on both sides but with the wrong dtype or shape would silently
    poison the next update, so those are compared against the live instance's
    own serialized view here.
    """
    if not isinstance(tree, dict) or "state_dict" not in tree:
        raise CkptSchemaError("snapshot tree is not a metric checkpoint (no state_dict)")
    expected_kind = "collection" if _is_collection(obj) else "metric"
    if tree.get("kind") != expected_kind:
        raise CkptSchemaError(
            f"snapshot holds a {tree.get('kind')!r}, live instance is a {expected_kind} "
            f"({type(obj).__name__})"
        )
    sd = tree["state_dict"]
    if not isinstance(sd, dict):
        raise CkptSchemaError("snapshot state_dict is not a mapping")
    with _all_persistent(obj):
        live = obj.state_dict()
    problems = []
    for key, expected in live.items():
        if key not in sd:
            continue  # strict load_state_dict raises on genuinely missing keys
        val = sd[key]
        if isinstance(expected, (list, tuple)):
            if not isinstance(val, (list, tuple)):
                problems.append(
                    f"state {key!r}: expected a list ('cat') state, got {type(val).__name__}"
                )
            continue
        if not (hasattr(expected, "dtype") and hasattr(expected, "shape")):
            continue  # host-object payloads: opaque to structural checks
        if not (hasattr(val, "dtype") and hasattr(val, "shape")):
            problems.append(f"state {key!r}: expected an array, got {type(val).__name__}")
            continue
        if np.dtype(val.dtype) != np.dtype(expected.dtype):
            problems.append(
                f"state {key!r}: dtype {np.dtype(val.dtype).name} != live {np.dtype(expected.dtype).name}"
            )
        if strict_shapes and tuple(val.shape) != tuple(expected.shape):
            problems.append(
                f"state {key!r}: shape {tuple(val.shape)} != live {tuple(expected.shape)}"
            )
    if problems:
        shown = "; ".join(problems[:6]) + (" ..." if len(problems) > 6 else "")
        raise CkptSchemaError(f"snapshot does not fit {type(obj).__name__}: {shown}")


def _apply_tree(obj: Any, tree: Dict[str, Any]) -> None:
    sd = dict(tree["state_dict"])
    # numpy leaves go in verbatim: load_state_dict owns the jnp conversion for
    # array states and keeps list entries host-native (detection semantics)
    with _all_persistent(obj):
        obj.load_state_dict(sd, strict=True)
    counts = tree.get("update_counts", {})
    for prefix, m in _walk_metrics(obj):
        if prefix in counts:
            m._update_count = int(counts[prefix])
        # a restore invalidates everything derived from pre-restore state
        m._update_called = m._update_count > 0
        m._computed = None
        m._cache = None
        m._is_synced = False
        m._batch_state = None
    if _is_collection(obj):
        # Re-establish compute-group aliasing: members must point at their
        # leader's freshly restored arrays, not at whatever they held before
        # (the regression this guards: a member serving stale pre-restore
        # state from its own _computed cache or un-aliased arrays).
        if obj._groups_checked:
            obj._compute_groups_create_state_ref(copy=False)
            obj._state_is_copy = False


def restore(
    obj: Any,
    path: str,
    *,
    strict_shapes: bool = True,
) -> Snapshot:
    """Load ``path`` into the live ``obj``; returns the decoded :class:`Snapshot`.

    Integrity failures raise :class:`CorruptSnapshotError`; schema/structure
    mismatches raise :class:`CkptSchemaError`. Either way the live instance is
    untouched on failure.
    """
    t0 = time.perf_counter()
    with _obs.ckpt_span("ckpt.restore", site="metric", cls=type(obj).__name__):
        with open(path, "rb") as f:
            data = f.read()
        snap = ckpt_format.loads(data)
        tree = migrate(snap, CKPT_SCHEMA_VERSION)
        _validate_tree(obj, tree, strict_shapes=strict_shapes)
        # load_state_dict raises mid-walk on a missing key; roll the instance
        # back so a failed restore never leaves half-applied state behind
        saved = [(m, dict(m.__dict__)) for _, m in _walk_metrics(obj)]
        tracked = getattr(obj, "_metrics", None)
        saved_tracked = list(tracked) if isinstance(tracked, list) else None
        try:
            _apply_tree(obj, tree)
        except BaseException:
            for m, d in saved:
                m.__dict__.clear()
                m.__dict__.update(d)
            if saved_tracked is not None:
                obj._metrics[:] = saved_tracked
            raise
    _obs.record_ckpt_io(
        "metric", "restore", len(data), time.perf_counter() - t0, generation=None
    )
    return snap


def as_device_state(sd: Dict[str, Any]) -> Dict[str, Any]:
    """Convenience: numpy state_dict leaves → jax arrays (lists stay lists)."""
    out: Dict[str, Any] = {}
    for k, v in sd.items():
        if isinstance(v, (list, tuple)):
            out[k] = [jnp.asarray(x) if hasattr(x, "dtype") else x for x in v]
        elif hasattr(v, "dtype"):
            out[k] = jnp.asarray(v)
        else:
            out[k] = v
    return out
