"""BERTScore module metric (reference src/torchmetrics/text/bert.py)."""

from __future__ import annotations

from typing import Any, Callable, Dict, List, Optional, Union

import jax.numpy as jnp
import numpy as np
from jax import Array

from metrics_tpu.functional.text.bert import _DEFAULT_MODEL, bert_score
from metrics_tpu.metric import Metric
from metrics_tpu.utils.imports import _TRANSFORMERS_AVAILABLE
from metrics_tpu.utils.prints import rank_zero_warn


def _preprocess(text: List[str], tokenizer: Any, max_length: int):
    enc = tokenizer(text, padding="max_length", truncation=True, max_length=max_length, return_tensors="np")
    return np.asarray(enc["input_ids"]), np.asarray(enc["attention_mask"])


class BERTScore(Metric):
    """Streaming BERTScore (reference text/bert.py:42-225).

    Example (requires the `transformers` flax models; not executed offline):
        >>> from metrics_tpu.text import BERTScore
        >>> metric = BERTScore(model_name_or_path="roberta-large")  # doctest: +SKIP
        >>> metric.update(["the cat sat"], ["a cat sat"])  # doctest: +SKIP
        >>> {k: round(float(v), 3) for k, v in metric.compute().items()}  # doctest: +SKIP
        {'precision': 0.99..., 'recall': 0.99..., 'f1': 0.99...}

    Tokenized sentences accumulate as ragged "cat" states; the heavy embedding
    model runs once at ``compute`` (reference design — BASELINE "large embedding
    states" scenario accumulates tokens, not embeddings).
    """

    is_differentiable = False
    higher_is_better = True
    full_state_update = False
    _host_compute = True

    def __init__(
        self,
        model_name_or_path: Optional[str] = None,
        num_layers: Optional[int] = None,
        all_layers: bool = False,
        model: Optional[Any] = None,
        user_tokenizer: Optional[Any] = None,
        user_forward_fn: Optional[Callable] = None,
        verbose: bool = False,
        idf: bool = False,
        device: Optional[Any] = None,
        max_length: int = 512,
        batch_size: int = 64,
        num_threads: int = 0,
        return_hash: bool = False,
        lang: str = "en",
        rescale_with_baseline: bool = False,
        baseline_path: Optional[str] = None,
        baseline_url: Optional[str] = None,
        **kwargs: Any,
    ) -> None:
        super().__init__(**kwargs)
        self.model_name_or_path = model_name_or_path or _DEFAULT_MODEL
        self.num_layers = num_layers
        self.all_layers = all_layers
        self.model = model
        self.user_forward_fn = user_forward_fn
        self.verbose = verbose
        self.idf = idf
        self.embedding_device = device
        self.max_length = max_length
        self.batch_size = batch_size
        self.num_threads = num_threads
        self.return_hash = return_hash
        self.lang = lang
        self.rescale_with_baseline = rescale_with_baseline
        self.baseline_path = baseline_path
        self.baseline_url = baseline_url

        if user_tokenizer:
            self.tokenizer = user_tokenizer
            self.user_tokenizer = True
        else:
            if not _TRANSFORMERS_AVAILABLE:
                raise ModuleNotFoundError(
                    "`BERTScore` metric with default tokenizers requires `transformers` package be installed."
                )
            if model_name_or_path is None:
                rank_zero_warn(
                    "The argument `model_name_or_path` was not specified while it is required when the default"
                    f" `transformers` model is used. It will use the default recommended model - {_DEFAULT_MODEL}."
                )
            from transformers import AutoTokenizer

            self.tokenizer = AutoTokenizer.from_pretrained(self.model_name_or_path)
            self.user_tokenizer = False

        self.add_state("preds_input_ids", [], dist_reduce_fx="cat")
        self.add_state("preds_attention_mask", [], dist_reduce_fx="cat")
        self.add_state("target_input_ids", [], dist_reduce_fx="cat")
        self.add_state("target_attention_mask", [], dist_reduce_fx="cat")

    def update(self, preds: List[str], target: List[str]) -> None:
        if len(preds) != len(target):
            raise ValueError("Number of predicted and reference sententes must be the same!")
        preds_ids, preds_mask = _preprocess(list(preds), self.tokenizer, self.max_length)
        target_ids, target_mask = _preprocess(list(target), self.tokenizer, self.max_length)
        self.preds_input_ids.append(jnp.asarray(preds_ids))
        self.preds_attention_mask.append(jnp.asarray(preds_mask))
        self.target_input_ids.append(jnp.asarray(target_ids))
        self.target_attention_mask.append(jnp.asarray(target_mask))

    @staticmethod
    def _cat_and_trim(ids_list, mask_list) -> Dict[str, np.ndarray]:
        """Concatenate accumulated batches and trim shared padding to the longest
        sequence — avoids running the model/matching at full max_length."""
        ids = np.concatenate([np.asarray(x) for x in ids_list])
        mask = np.concatenate([np.asarray(x) for x in mask_list])
        max_len = max(int(mask.sum(1).max()), 1)
        return {"input_ids": ids[:, :max_len], "attention_mask": mask[:, :max_len]}

    def compute(self) -> Dict[str, Union[List[float], str]]:
        return bert_score(
            preds=self._cat_and_trim(self.preds_input_ids, self.preds_attention_mask),
            target=self._cat_and_trim(self.target_input_ids, self.target_attention_mask),
            model_name_or_path=self.model_name_or_path,
            num_layers=self.num_layers,
            all_layers=self.all_layers,
            model=self.model,
            user_tokenizer=self.tokenizer if self.user_tokenizer else None,
            user_forward_fn=self.user_forward_fn,
            verbose=self.verbose,
            idf=self.idf,
            device=self.embedding_device,
            max_length=self.max_length,
            batch_size=self.batch_size,
            num_threads=self.num_threads,
            return_hash=self.return_hash,
            lang=self.lang,
            rescale_with_baseline=self.rescale_with_baseline,
            baseline_path=self.baseline_path,
            baseline_url=self.baseline_url,
        )
