"""WordInfoPreserved module metric (reference src/torchmetrics/text/wip.py)."""

from __future__ import annotations

from typing import Any, List, Union

import jax.numpy as jnp
from jax import Array

from metrics_tpu.functional.text.wip import _wip_compute, _wip_update
from metrics_tpu.metric import Metric, zero_state


class WordInfoPreserved(Metric):
    """Word information preserved over a streaming corpus (reference text/wip.py:23-93).

    Example:
        >>> from metrics_tpu import WordInfoPreserved
        >>> metric = WordInfoPreserved()
        >>> metric.update(["this is the prediction"], ["this is the reference"])
        >>> metric.compute()
        Array(0.5625, dtype=float32)
    """

    is_differentiable = False
    higher_is_better = True
    full_state_update = False

    def __init__(self, **kwargs: Any) -> None:
        super().__init__(**kwargs)
        self.add_state("errors", zero_state((), jnp.float32), dist_reduce_fx="sum")
        self.add_state("target_total", zero_state((), jnp.float32), dist_reduce_fx="sum")
        self.add_state("preds_total", zero_state((), jnp.float32), dist_reduce_fx="sum")

    def update(self, preds: Union[str, List[str]], target: Union[str, List[str]]) -> None:
        errors, target_total, preds_total = _wip_update(preds, target)
        self.errors = self.errors + errors
        self.target_total = self.target_total + target_total
        self.preds_total = self.preds_total + preds_total

    def compute(self) -> Array:
        return _wip_compute(self.errors, self.target_total, self.preds_total)
