"""TranslationEditRate module metric (reference src/torchmetrics/text/ter.py)."""

from __future__ import annotations

from typing import Any, Sequence, Tuple, Union

import jax.numpy as jnp
from jax import Array

from metrics_tpu.functional.text.ter import _TercomTokenizer, _ter_compute, _ter_update
from metrics_tpu.metric import Metric, zero_state


class TranslationEditRate(Metric):
    """TER over a streaming corpus (reference text/ter.py:24-122).

    Example:
        >>> from metrics_tpu import TranslationEditRate
        >>> metric = TranslationEditRate()
        >>> metric.update(["the cat"], [["the cat"]])
        >>> metric.compute()
        Array(0., dtype=float32)
    """

    is_differentiable = False
    higher_is_better = False
    full_state_update = False

    def __init__(
        self,
        normalize: bool = False,
        no_punctuation: bool = False,
        lowercase: bool = True,
        asian_support: bool = False,
        return_sentence_level_score: bool = False,
        **kwargs: Any,
    ) -> None:
        super().__init__(**kwargs)
        if not isinstance(normalize, bool):
            raise ValueError(f"Expected argument `normalize` to be of type boolean but got {normalize}.")
        if not isinstance(no_punctuation, bool):
            raise ValueError(f"Expected argument `no_punctuation` to be of type boolean but got {no_punctuation}.")
        if not isinstance(lowercase, bool):
            raise ValueError(f"Expected argument `lowercase` to be of type boolean but got {lowercase}.")
        if not isinstance(asian_support, bool):
            raise ValueError(f"Expected argument `asian_support` to be of type boolean but got {asian_support}.")
        self.tokenizer = _TercomTokenizer(normalize, no_punctuation, lowercase, asian_support)
        self.return_sentence_level_score = return_sentence_level_score

        self.add_state("total_num_edits", zero_state((), jnp.float32), dist_reduce_fx="sum")
        self.add_state("total_tgt_len", zero_state((), jnp.float32), dist_reduce_fx="sum")
        if self.return_sentence_level_score:
            self.add_state("sentence_ter", [], dist_reduce_fx="cat")

    def update(self, preds: Union[str, Sequence[str]], target: Sequence[Union[str, Sequence[str]]]) -> None:
        num_edits, tgt_length, sentence_ter = _ter_update(preds, target, self.tokenizer)
        self.total_num_edits = self.total_num_edits + num_edits
        self.total_tgt_len = self.total_tgt_len + tgt_length
        if self.return_sentence_level_score and sentence_ter:
            self.sentence_ter.append(jnp.asarray(sentence_ter, jnp.float32))

    def compute(self) -> Union[Array, Tuple[Array, Array]]:
        score = _ter_compute(self.total_num_edits, self.total_tgt_len)
        if self.return_sentence_level_score:
            return score, jnp.concatenate([jnp.atleast_1d(s) for s in self.sentence_ter])
        return score
