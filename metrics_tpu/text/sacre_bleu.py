"""SacreBLEU module metric (reference src/torchmetrics/text/sacre_bleu.py)."""

from __future__ import annotations

from typing import Any, Optional, Sequence

from metrics_tpu.functional.text.sacre_bleu import AVAILABLE_TOKENIZERS, _SacreBLEUTokenizer
from metrics_tpu.text.bleu import BLEUScore
from metrics_tpu.utils.imports import _REGEX_AVAILABLE


class SacreBLEUScore(BLEUScore):
    """BLEU with sacrebleu tokenization (reference text/sacre_bleu.py:29-112).

    Example:
        >>> from metrics_tpu import SacreBLEUScore
        >>> metric = SacreBLEUScore()
        >>> metric.update(["the cat is on the mat"], [["the cat is on the mat"]])
        >>> metric.compute()
        Array(1., dtype=float32)
    """

    def __init__(
        self,
        n_gram: int = 4,
        smooth: bool = False,
        tokenize: str = "13a",
        lowercase: bool = False,
        weights: Optional[Sequence[float]] = None,
        **kwargs: Any,
    ) -> None:
        super().__init__(n_gram=n_gram, smooth=smooth, weights=weights, **kwargs)
        if tokenize not in AVAILABLE_TOKENIZERS:
            raise ValueError(f"Argument `tokenize` expected to be one of {AVAILABLE_TOKENIZERS} but got {tokenize}.")
        if tokenize == "intl" and not _REGEX_AVAILABLE:
            raise ModuleNotFoundError("`'intl'` tokenization requires that `regex` is installed.")
        self.tokenizer = _SacreBLEUTokenizer(tokenize, lowercase)

    @property
    def _tokenizer(self):
        return self.tokenizer
