"""WordErrorRate module metric (reference src/torchmetrics/text/wer.py)."""

from __future__ import annotations

from typing import Any, List, Union

import jax.numpy as jnp
from jax import Array

from metrics_tpu.functional.text.wer import _wer_compute, _wer_update
from metrics_tpu.metric import Metric, zero_state


class WordErrorRate(Metric):
    """Word error rate over a streaming corpus (reference text/wer.py:23-92).

    Example:
        >>> from metrics_tpu import WordErrorRate
        >>> metric = WordErrorRate()
        >>> metric.update(["this is the prediction"], ["this is the reference"])
        >>> metric.compute()
        Array(0.25, dtype=float32)
    """

    is_differentiable = False
    higher_is_better = False
    full_state_update = False

    def __init__(self, **kwargs: Any) -> None:
        super().__init__(**kwargs)
        self.add_state("errors", zero_state((), jnp.float32), dist_reduce_fx="sum")
        self.add_state("total", zero_state((), jnp.float32), dist_reduce_fx="sum")

    def update(self, preds: Union[str, List[str]], target: Union[str, List[str]]) -> None:
        errors, total = _wer_update(preds, target)
        self.errors = self.errors + errors
        self.total = self.total + total

    def compute(self) -> Array:
        return _wer_compute(self.errors, self.total)
