"""SQuAD module metric (reference src/torchmetrics/text/squad.py)."""

from __future__ import annotations

from typing import Any, Dict

import jax.numpy as jnp
from jax import Array

from metrics_tpu.functional.text.squad import (
    PREDS_TYPE,
    TARGETS_TYPE,
    _squad_compute,
    _squad_input_check,
    _squad_update,
)
from metrics_tpu.metric import Metric, zero_state


class SQuAD(Metric):
    """SQuAD EM/F1 over a streaming corpus (reference text/squad.py:29-115).

    Example:
        >>> from metrics_tpu import SQuAD
        >>> metric = SQuAD()
        >>> metric.update([{"prediction_text": "the cat", "id": "1"}],
        ...               [{"answers": {"text": ["the cat"], "answer_start": [0]}, "id": "1"}])
        >>> {k: float(v) for k, v in metric.compute().items()}
        {'exact_match': 100.0, 'f1': 100.0}
    """

    is_differentiable = False
    higher_is_better = True
    full_state_update = False

    def __init__(self, **kwargs: Any) -> None:
        super().__init__(**kwargs)
        self.add_state("f1_score", zero_state((), jnp.float32), dist_reduce_fx="sum")
        self.add_state("exact_match", zero_state((), jnp.float32), dist_reduce_fx="sum")
        self.add_state("total", zero_state((), jnp.int32), dist_reduce_fx="sum")

    def update(self, preds: PREDS_TYPE, target: TARGETS_TYPE) -> None:
        preds_dict, target_dict = _squad_input_check(preds, target)
        f1, exact_match, total = _squad_update(preds_dict, target_dict)
        self.f1_score = self.f1_score + f1
        self.exact_match = self.exact_match + exact_match
        self.total = self.total + total

    def compute(self) -> Dict[str, Array]:
        return _squad_compute(self.f1_score, self.exact_match, self.total)
