"""WordInfoLost module metric (reference src/torchmetrics/text/wil.py)."""

from __future__ import annotations

from typing import Any, List, Union

import jax.numpy as jnp
from jax import Array

from metrics_tpu.functional.text.wil import _wil_compute, _wil_update
from metrics_tpu.metric import Metric, zero_state


class WordInfoLost(Metric):
    """Word information lost over a streaming corpus (reference text/wil.py:23-93).

    Example:
        >>> from metrics_tpu import WordInfoLost
        >>> metric = WordInfoLost()
        >>> metric.update(["this is the prediction"], ["this is the reference"])
        >>> metric.compute()
        Array(0.4375, dtype=float32)
    """

    is_differentiable = False
    higher_is_better = False
    full_state_update = False

    def __init__(self, **kwargs: Any) -> None:
        super().__init__(**kwargs)
        self.add_state("errors", zero_state((), jnp.float32), dist_reduce_fx="sum")
        self.add_state("target_total", zero_state((), jnp.float32), dist_reduce_fx="sum")
        self.add_state("preds_total", zero_state((), jnp.float32), dist_reduce_fx="sum")

    def update(self, preds: Union[str, List[str]], target: Union[str, List[str]]) -> None:
        errors, target_total, preds_total = _wil_update(preds, target)
        self.errors = self.errors + errors
        self.target_total = self.target_total + target_total
        self.preds_total = self.preds_total + preds_total

    def compute(self) -> Array:
        return _wil_compute(self.errors, self.target_total, self.preds_total)
