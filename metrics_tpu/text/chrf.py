"""CHRFScore module metric (reference src/torchmetrics/text/chrf.py).

State redesign (SURVEY §7.1): the reference registers 4+2 scalar states per n-gram
order (text/chrf.py:119-130); here each statistic family is a single fixed-shape
vector state, psum-able in one collective.
"""

from __future__ import annotations

from typing import Any, Optional, Sequence, Tuple, Union

import jax.numpy as jnp
from jax import Array

from metrics_tpu.functional.text.chrf import _chrf_score_compute, _chrf_score_update
from metrics_tpu.metric import Metric, zero_state


class CHRFScore(Metric):
    """chrF/chrF++ score over a streaming corpus (reference text/chrf.py:46-186).

    Example:
        >>> from metrics_tpu import CHRFScore
        >>> metric = CHRFScore()
        >>> metric.update(["the cat"], [["the cat"]])
        >>> metric.compute()
        Array(1., dtype=float32)
    """

    is_differentiable = False
    higher_is_better = True
    full_state_update = True

    def __init__(
        self,
        n_char_order: int = 6,
        n_word_order: int = 2,
        beta: float = 2.0,
        lowercase: bool = False,
        whitespace: bool = False,
        return_sentence_level_score: bool = False,
        **kwargs: Any,
    ) -> None:
        super().__init__(**kwargs)
        if not isinstance(n_char_order, int) or n_char_order < 1:
            raise ValueError("Expected argument `n_char_order` to be an integer greater than or equal to 1.")
        if not isinstance(n_word_order, int) or n_word_order < 0:
            raise ValueError("Expected argument `n_word_order` to be an integer greater than or equal to 0.")
        if beta < 0:
            raise ValueError("Expected argument `beta` to be greater than 0.")
        self.n_char_order = n_char_order
        self.n_word_order = n_word_order
        self.beta = beta
        self.lowercase = lowercase
        self.whitespace = whitespace
        self.return_sentence_level_score = return_sentence_level_score
        self.n_order = float(n_char_order + n_word_order)

        self.add_state("total_preds_char_n_grams", zero_state(n_char_order), dist_reduce_fx="sum")
        self.add_state("total_preds_word_n_grams", zero_state(n_word_order), dist_reduce_fx="sum")
        self.add_state("total_target_char_n_grams", zero_state(n_char_order), dist_reduce_fx="sum")
        self.add_state("total_target_word_n_grams", zero_state(n_word_order), dist_reduce_fx="sum")
        self.add_state("total_matching_char_n_grams", zero_state(n_char_order), dist_reduce_fx="sum")
        self.add_state("total_matching_word_n_grams", zero_state(n_word_order), dist_reduce_fx="sum")
        if self.return_sentence_level_score:
            self.add_state("sentence_chrf_score", [], dist_reduce_fx="cat")

    def update(self, preds: Union[str, Sequence[str]], target: Union[Sequence[str], Sequence[Sequence[str]]]) -> None:
        (
            preds_char,
            preds_word,
            target_char,
            target_word,
            matching_char,
            matching_word,
            sentence_scores,
        ) = _chrf_score_update(
            preds, target, self.n_char_order, self.n_word_order, self.beta, self.lowercase, self.whitespace
        )
        self.total_preds_char_n_grams = self.total_preds_char_n_grams + preds_char
        self.total_preds_word_n_grams = self.total_preds_word_n_grams + preds_word
        self.total_target_char_n_grams = self.total_target_char_n_grams + target_char
        self.total_target_word_n_grams = self.total_target_word_n_grams + target_word
        self.total_matching_char_n_grams = self.total_matching_char_n_grams + matching_char
        self.total_matching_word_n_grams = self.total_matching_word_n_grams + matching_word
        if self.return_sentence_level_score:
            self.sentence_chrf_score.append(jnp.asarray(sentence_scores, jnp.float32))

    def compute(self) -> Union[Array, Tuple[Array, Array]]:
        score = _chrf_score_compute(
            self.total_preds_char_n_grams,
            self.total_preds_word_n_grams,
            self.total_target_char_n_grams,
            self.total_target_word_n_grams,
            self.total_matching_char_n_grams,
            self.total_matching_word_n_grams,
            self.n_order,
            self.beta,
        )
        if self.return_sentence_level_score:
            return score, jnp.concatenate([jnp.atleast_1d(s) for s in self.sentence_chrf_score])
        return score
