"""CharErrorRate module metric (reference src/torchmetrics/text/cer.py)."""

from __future__ import annotations

from typing import Any, List, Union

import jax.numpy as jnp
from jax import Array

from metrics_tpu.functional.text.cer import _cer_compute, _cer_update
from metrics_tpu.metric import Metric, zero_state


class CharErrorRate(Metric):
    """Character error rate over a streaming corpus (reference text/cer.py:24-95).

    Example:
        >>> from metrics_tpu import CharErrorRate
        >>> metric = CharErrorRate()
        >>> metric.update(["abcd"], ["abce"])
        >>> metric.compute()
        Array(0.25, dtype=float32)
    """

    is_differentiable = False
    higher_is_better = False
    full_state_update = False

    def __init__(self, **kwargs: Any) -> None:
        super().__init__(**kwargs)
        self.add_state("errors", zero_state((), jnp.float32), dist_reduce_fx="sum")
        self.add_state("total", zero_state((), jnp.float32), dist_reduce_fx="sum")

    def update(self, preds: Union[str, List[str]], target: Union[str, List[str]]) -> None:
        errors, total = _cer_update(preds, target)
        self.errors = self.errors + errors
        self.total = self.total + total

    def compute(self) -> Array:
        return _cer_compute(self.errors, self.total)
