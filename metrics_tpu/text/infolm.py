"""InfoLM module metric (reference src/torchmetrics/text/infolm.py:37).

Stateful shell over the functional InfoLM (``functional/text/infolm.py``): tokenized
sentences accumulate as ragged "cat" states (mirroring the reference's four
``dist_reduce_fx="cat"`` states, infolm.py:148-151) and the masked-LM runs once at
``compute``. TPU extension over the reference: a Flax masked-LM ``model`` +
``user_tokenizer`` can be injected directly (like BERTScore) for offline use.
"""

from __future__ import annotations

from typing import Any, List, Optional, Sequence, Tuple, Union

import jax.numpy as jnp
import numpy as np
from jax import Array

from metrics_tpu.functional.text.infolm import (
    _ALLOWED_INFORMATION_MEASURE,
    _DEFAULT_INFOLM_MODEL,
    _get_special_tokens_map,
    _infolm_compute,
    _infolm_update,
    _InformationMeasure,
    _load_tokenizer_and_model,
)
from metrics_tpu.metric import Metric

__all__ = ["InfoLM"]


class InfoLM(Metric):
    """Information-measure distance between predicted and reference sentence
    distributions under an untrained masked language model (Colombo et al., AAAI 2022).

    Args mirror the reference class (text/infolm.py:107-128); ``model`` /
    ``user_tokenizer`` additionally allow injecting a Flax MLM + tokenizer pair so no
    pretrained download is needed.

    Example (requires the `transformers` flax models; not executed offline):
        >>> from metrics_tpu.text import InfoLM
        >>> metric = InfoLM(model_name_or_path="google/bert_uncased_L-2_H-128_A-2")  # doctest: +SKIP
        >>> metric.update(["he read the book"], ["he reads the book"])  # doctest: +SKIP
        >>> metric.compute()  # doctest: +SKIP
        Array(-0.1..., dtype=float32)
    """

    is_differentiable = False
    higher_is_better = True
    full_state_update = False
    _host_compute = True  # string tokenization + chunked model forwards on host

    preds_input_ids: List[Array]
    preds_attention_mask: List[Array]
    target_input_ids: List[Array]
    target_attention_mask: List[Array]

    def __init__(
        self,
        model_name_or_path: str = _DEFAULT_INFOLM_MODEL,
        temperature: float = 0.25,
        information_measure: str = "kl_divergence",
        idf: bool = True,
        alpha: Optional[float] = None,
        beta: Optional[float] = None,
        device: Optional[Any] = None,
        max_length: Optional[int] = None,
        batch_size: int = 64,
        num_threads: int = 0,
        verbose: bool = True,
        return_sentence_level_score: bool = False,
        model: Optional[Any] = None,
        user_tokenizer: Optional[Any] = None,
        **kwargs: Any,
    ) -> None:
        super().__init__(**kwargs)
        if (model is None) != (user_tokenizer is None):
            raise ValueError("Arguments `model` and `user_tokenizer` must be provided together (or both omitted).")
        if temperature <= 0:
            raise ValueError(f"Argument `temperature` expected to be a positive float, got {temperature}")
        self.model_name_or_path = model_name_or_path
        self.temperature = temperature
        self.information_measure = information_measure
        self.idf = idf
        self.alpha = alpha
        self.beta = beta
        self.batch_size = batch_size
        self.num_threads = num_threads
        self.verbose = verbose
        self.return_sentence_level_score = return_sentence_level_score

        if model is None:
            self.tokenizer, self.model = _load_tokenizer_and_model(model_name_or_path)
        else:
            self.tokenizer, self.model = user_tokenizer, model
        self.information_measure_cls = _InformationMeasure(information_measure, alpha, beta)
        self.max_length = max_length or self.model.config.max_length
        self.special_tokens_map = _get_special_tokens_map(self.tokenizer)

        self.add_state("preds_input_ids", [], dist_reduce_fx="cat")
        self.add_state("preds_attention_mask", [], dist_reduce_fx="cat")
        self.add_state("target_input_ids", [], dist_reduce_fx="cat")
        self.add_state("target_attention_mask", [], dist_reduce_fx="cat")

    def update(self, preds: Union[str, Sequence[str]], target: Union[str, Sequence[str]]) -> None:
        """Tokenize and accumulate preds/target id+mask batches (reference :153-162)."""
        preds_input_ids, preds_attention_mask, target_input_ids, target_attention_mask = _infolm_update(
            preds, target, self.tokenizer, self.max_length
        )
        self.preds_input_ids.append(jnp.asarray(preds_input_ids))
        self.preds_attention_mask.append(jnp.asarray(preds_attention_mask))
        self.target_input_ids.append(jnp.asarray(target_input_ids))
        self.target_attention_mask.append(jnp.asarray(target_attention_mask))

    @staticmethod
    def _cat(chunks: List[Array]) -> np.ndarray:
        return np.concatenate([np.asarray(c) for c in chunks], axis=0)

    def compute(self) -> Union[Array, Tuple[Array, Array]]:
        """Run the masked-LM over all accumulated sentences and score (reference :164-197)."""
        scores = _infolm_compute(
            self.model,
            (self._cat(self.preds_input_ids), self._cat(self.preds_attention_mask)),
            (self._cat(self.target_input_ids), self._cat(self.target_attention_mask)),
            self.temperature,
            self.idf,
            self.information_measure_cls,
            self.special_tokens_map,
            self.batch_size,
        )
        if self.return_sentence_level_score:
            return scores.mean(), scores
        return scores.mean()
