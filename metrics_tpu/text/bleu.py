"""BLEU module metric (reference src/torchmetrics/text/bleu.py)."""

from __future__ import annotations

from typing import Any, Optional, Sequence

import jax.numpy as jnp
from jax import Array

from metrics_tpu.functional.text.bleu import _bleu_score_compute, _bleu_score_update, _tokenize_fn
from metrics_tpu.metric import Metric, zero_state


class BLEUScore(Metric):
    """BLEU score over a streaming corpus (reference text/bleu.py:28-107).

    States are four psum-able arrays: per-order clipped-match numerators and
    denominators plus corpus length counters.

    Example:
        >>> from metrics_tpu import BLEUScore
        >>> metric = BLEUScore()
        >>> metric.update(["the cat is on the mat"], [["the cat is on the mat"]])
        >>> metric.compute()
        Array(1., dtype=float32)
    """

    is_differentiable = False
    higher_is_better = True
    full_state_update = True

    def __init__(
        self,
        n_gram: int = 4,
        smooth: bool = False,
        weights: Optional[Sequence[float]] = None,
        **kwargs: Any,
    ) -> None:
        super().__init__(**kwargs)
        self.n_gram = n_gram
        self.smooth = smooth
        if weights is not None and len(weights) != n_gram:
            raise ValueError(f"List of weights has different weights than `n_gram`: {len(weights)} != {n_gram}")
        self.weights = weights if weights is not None else [1.0 / n_gram] * n_gram

        self.add_state("preds_len", zero_state((), jnp.float32), dist_reduce_fx="sum")
        self.add_state("target_len", zero_state((), jnp.float32), dist_reduce_fx="sum")
        self.add_state("numerator", zero_state(self.n_gram), dist_reduce_fx="sum")
        self.add_state("denominator", zero_state(self.n_gram), dist_reduce_fx="sum")

    def update(self, preds: Sequence[str], target: Sequence[Sequence[str]]) -> None:
        preds_ = [preds] if isinstance(preds, str) else preds
        target_ = [[tgt] if isinstance(tgt, str) else tgt for tgt in target]
        if len(preds_) != len(target_):
            raise ValueError(f"Corpus has different size {len(preds_)} != {len(target_)}")
        numerator, denominator, preds_len, target_len = _bleu_score_update(
            preds_, target_, self.n_gram, self._tokenizer
        )
        self.preds_len = self.preds_len + preds_len
        self.target_len = self.target_len + target_len
        self.numerator = self.numerator + numerator
        self.denominator = self.denominator + denominator

    def compute(self) -> Array:
        return _bleu_score_compute(
            self.preds_len, self.target_len, self.numerator, self.denominator, self.n_gram, self.weights, self.smooth
        )

    @property
    def _tokenizer(self):
        return _tokenize_fn
