"""ROUGEScore module metric (reference src/torchmetrics/text/rouge.py)."""

from __future__ import annotations

from typing import Any, Callable, Dict, Optional, Sequence, Tuple, Union

import jax.numpy as jnp
from jax import Array

from metrics_tpu.functional.text.rouge import (
    ALLOWED_ACCUMULATE_VALUES,
    ALLOWED_ROUGE_KEYS,
    _rouge_score_compute,
    _rouge_score_update,
)
from metrics_tpu.metric import Metric
from metrics_tpu.utils.imports import _NLTK_AVAILABLE


class ROUGEScore(Metric):
    """ROUGE-N/L/LSum over a streaming corpus; per-sample scores as ragged "cat"
    states (reference text/rouge.py:31-175).

    Example:
        >>> from metrics_tpu.text import ROUGEScore
        >>> metric = ROUGEScore()
        >>> scores = metric(["the cat is on the mat"], ["the cat is on the mat"])
        >>> float(scores["rouge1_fmeasure"])
        1.0
    """

    is_differentiable = False
    higher_is_better = True
    full_state_update = True

    def __init__(
        self,
        use_stemmer: bool = False,
        normalizer: Optional[Callable[[str], str]] = None,
        tokenizer: Optional[Callable[[str], Sequence[str]]] = None,
        accumulate: str = "best",
        rouge_keys: Union[str, Tuple[str, ...]] = ("rouge1", "rouge2", "rougeL", "rougeLsum"),
        **kwargs: Any,
    ) -> None:
        super().__init__(**kwargs)
        if use_stemmer and not _NLTK_AVAILABLE:
            raise ModuleNotFoundError("Stemmer requires that `nltk` is installed. Use `pip install nltk`.")
        if accumulate not in ALLOWED_ACCUMULATE_VALUES:
            raise ValueError(
                f"Got unknown accumulate value {accumulate}. Expected to be one of {ALLOWED_ACCUMULATE_VALUES}"
            )
        if not isinstance(rouge_keys, tuple):
            rouge_keys = (rouge_keys,)
        for key in rouge_keys:
            if key not in ALLOWED_ROUGE_KEYS.keys():
                raise ValueError(f"Got unknown rouge key {key}. Expected to be one of {list(ALLOWED_ROUGE_KEYS.keys())}")

        self.rouge_keys = rouge_keys
        self.rouge_keys_values = [ALLOWED_ROUGE_KEYS[key] for key in rouge_keys]
        self.use_stemmer = use_stemmer
        self.normalizer = normalizer
        self.tokenizer = tokenizer
        self.accumulate = accumulate
        self._stemmer = None
        if use_stemmer:
            import nltk

            self._stemmer = nltk.stem.porter.PorterStemmer()

        for rouge_key in self.rouge_keys:
            for score in ["fmeasure", "precision", "recall"]:
                self.add_state(f"{rouge_key}_{score}", [], dist_reduce_fx=None)

    def update(
        self,
        preds: Union[str, Sequence[str]],
        target: Union[str, Sequence[str], Sequence[Sequence[str]]],
    ) -> None:
        if isinstance(target, list) and all(isinstance(tgt, str) for tgt in target):
            target = [target] if isinstance(preds, str) else [[tgt] for tgt in target]
        if isinstance(preds, str):
            preds = [preds]
        if isinstance(target, str):
            target = [[target]]

        output = _rouge_score_update(
            preds,
            target,
            self.rouge_keys_values,
            accumulate=self.accumulate,
            stemmer=self._stemmer,
            normalizer=self.normalizer,
            tokenizer=self.tokenizer,
        )
        for rouge_key, metrics in output.items():
            for metric in metrics:
                for tp, value in metric.items():
                    getattr(self, f"rouge{rouge_key}_{tp}").append(jnp.asarray(value, jnp.float32))

    def compute(self) -> Dict[str, Array]:
        update_output = {}
        for rouge_key in self.rouge_keys_values:
            for tp in ["fmeasure", "precision", "recall"]:
                update_output[f"rouge{rouge_key}_{tp}"] = getattr(self, f"rouge{rouge_key}_{tp}")
        return _rouge_score_compute(update_output)

    def __getstate__(self) -> Dict[str, Any]:
        # PorterStemmer is re-created on load
        state = super().__getstate__()
        state["_stemmer"] = None
        return state

    def __setstate__(self, state: Dict[str, Any]) -> None:
        super().__setstate__(state)
        if self.use_stemmer:
            import nltk

            self._stemmer = nltk.stem.porter.PorterStemmer()
