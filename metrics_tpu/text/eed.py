"""ExtendedEditDistance module metric (reference src/torchmetrics/text/eed.py)."""

from __future__ import annotations

from typing import Any, Sequence, Tuple, Union

import jax.numpy as jnp
from jax import Array

from metrics_tpu.functional.text.eed import _eed_compute, _eed_update
from metrics_tpu.metric import Metric


class ExtendedEditDistance(Metric):
    """EED over a streaming corpus; sentence scores kept as a ragged "cat" state
    (reference text/eed.py:24-123).

    Example:
        >>> from metrics_tpu import ExtendedEditDistance
        >>> metric = ExtendedEditDistance()
        >>> metric.update(["the cat"], ["the cat"])
        >>> round(float(metric.compute()), 4)
        0.0323
    """

    is_differentiable = False
    higher_is_better = False
    full_state_update = False

    def __init__(
        self,
        language: str = "en",
        return_sentence_level_score: bool = False,
        alpha: float = 2.0,
        rho: float = 0.3,
        deletion: float = 0.2,
        insertion: float = 1.0,
        **kwargs: Any,
    ) -> None:
        super().__init__(**kwargs)
        if language not in ("en", "ja"):
            raise ValueError(f"Expected argument `language` to either be `en` or `ja` but got {language}")
        self.language = language
        self.return_sentence_level_score = return_sentence_level_score
        for param_name, param in zip(["alpha", "rho", "deletion", "insertion"], [alpha, rho, deletion, insertion]):
            if not isinstance(param, float) or param < 0:
                raise ValueError(f"Parameter `{param_name}` is expected to be a non-negative float.")
        self.alpha = alpha
        self.rho = rho
        self.deletion = deletion
        self.insertion = insertion

        self.add_state("sentence_eed", [], dist_reduce_fx="cat")

    def update(
        self,
        preds: Union[str, Sequence[str]],
        target: Sequence[Union[str, Sequence[str]]],
    ) -> None:
        scores = _eed_update(preds, target, self.language, self.alpha, self.rho, self.deletion, self.insertion)
        if scores:
            self.sentence_eed.append(jnp.asarray(scores, jnp.float32))

    def compute(self) -> Union[Array, Tuple[Array, Array]]:
        if self.sentence_eed:
            all_scores = jnp.concatenate([jnp.atleast_1d(s) for s in self.sentence_eed])
            average = _eed_compute(all_scores)
        else:
            all_scores = jnp.zeros((0,), jnp.float32)
            average = jnp.asarray(0.0, jnp.float32)
        if self.return_sentence_level_score:
            return average, all_scores
        return average
