"""Perplexity module metric (reference src/torchmetrics/text/perplexity.py)."""

from __future__ import annotations

from typing import Any, Optional

import jax.numpy as jnp
from jax import Array

from metrics_tpu.functional.text.perplexity import _perplexity_compute, _perplexity_update
from metrics_tpu.metric import Metric, zero_state


class Perplexity(Metric):
    """Perplexity of language-model token probabilities (reference text/perplexity.py:23-78).

    Fully jittable update/compute — usable inside a pjit'ed eval step via the
    functional ``update_state``/``compute_from`` API.

    Example:
        >>> import jax.numpy as jnp
        >>> from metrics_tpu import Perplexity
        >>> probs = jnp.array([[[0.6, 0.2, 0.2], [0.2, 0.7, 0.1]]])
        >>> target = jnp.array([[0, 1]])
        >>> metric = Perplexity()
        >>> metric.update(probs, target)
        >>> round(float(metric.compute()), 4)
        2.2461
    """

    is_differentiable = True
    higher_is_better = False
    full_state_update = False

    def __init__(self, ignore_index: Optional[int] = None, **kwargs: Any) -> None:
        super().__init__(**kwargs)
        if ignore_index is not None and not isinstance(ignore_index, int):
            raise ValueError(f"Argument `ignore_index` expected to either be `None` or an `int` but got {ignore_index}")
        self.ignore_index = ignore_index
        self.add_state("total_log_probs", zero_state((), jnp.float32), dist_reduce_fx="sum")
        self.add_state("count", zero_state((), jnp.float32), dist_reduce_fx="sum")

    def update(self, preds: Array, target: Array) -> None:
        total_log_probs, count = _perplexity_update(preds, target, self.ignore_index)
        self.total_log_probs = self.total_log_probs + total_log_probs
        self.count = self.count + count

    def compute(self) -> Array:
        return _perplexity_compute(self.total_log_probs, self.count)
